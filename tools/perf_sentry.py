"""Bench-regression sentry: change-point verdicts that survive noisy hosts.

The committed bench history (``BENCH_r0*.json``) plus fresh runs form a
series per metric.  A naive "new mean < old mean" check on this series
is worthless here: the history contains runs where the accelerator
tunnel was dead (``tpu-backend-unavailable``, value 0) and fresh runs
land on a single-core container whose noise floor dwarfs small real
regressions.  The sentry therefore applies three disciplines:

1. **Degenerate-sample quarantine** — history entries with a nonzero
   rc, a parse error, an ``error`` field, or a non-positive value are
   classified unusable.  Too few usable baselines produces the verdict
   ``no-baseline``, never ``regression``.

2. **Paired-sorted deltas** — baseline and candidate series are sorted
   and paired elementwise; the per-pair relative slowdown is computed
   and the *median* taken.  A reshuffle of the same measurements gives
   identical sorted series, hence exactly zero deltas and a quiet
   verdict (this is the zero-false-positive property ``selftest``
   checks); a uniform injected slowdown survives the pairing intact.

3. **Robust noise floor + host-health stamping** — the flag threshold
   is ``max(--rel-threshold, baseline p10–p90 spread / median)``, and
   every verdict is stamped with tools/host_health.py's probe.  A
   slowdown measured on an unhealthy host is reported as
   ``degraded-host`` (rc 0), not ``regression`` (rc 1): re-run when
   the machine recovers instead of blaming the commit.

4. **The cost arm (ISSUE 20)** — wall-clock is only one witness.  XLA's
   static cost census (docs/cost_model.json, tools/cost_observatory.py)
   is a pure function of the compiled program: a cost delta between two
   manifests has a ZERO noise floor, so the cost arm's ``regression`` is
   never downgraded by a sick host — an injected algorithmic regression
   is flagged even where the timing arm must say ``degraded-host``, and
   a pure timing wobble with zero cost delta stays quiet.  ``selftest``
   proves that exact split.

Usage:
  python tools/perf_sentry.py check --history 'BENCH_r0*.json' --new run.json
  python tools/perf_sentry.py cost --baseline old_cost_model.json
  python tools/perf_sentry.py selftest
"""

from __future__ import annotations

import argparse
import glob
import json
import math
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import host_health  # noqa: E402

from scheduler_plugins_tpu.obs import costmodel  # noqa: E402

MIN_BASELINE = 3
DEFAULT_REL_THRESHOLD = 0.10

# Which direction is "worse" per metric family.  Throughput-style
# metrics regress downward, latency-style metrics regress upward.
_LOWER_IS_BETTER_SUFFIXES = ("_ms", "_ns", "_s", "_seconds", "_latency")


def lower_is_better(metric: str) -> bool:
    return metric.endswith(_LOWER_IS_BETTER_SUFFIXES)


# ---------------------------------------------------------------------------
# History ingestion
# ---------------------------------------------------------------------------

def _sample_from_line(line: dict, source: str) -> dict:
    """Normalise one bench JSON line into a sample dict."""
    metric = line.get("metric", "unknown")
    value = line.get("value")
    err = line.get("error")
    usable = (
        err in (None, "")
        and isinstance(value, (int, float))
        and math.isfinite(float(value))
        and float(value) > 0
    )
    return {
        "source": source,
        "metric": metric,
        "value": float(value) if isinstance(value, (int, float)) else None,
        "error": err,
        "usable": usable,
    }


def extract_samples(obj, source: str) -> list[dict]:
    """Accept either a committed wrapper {n, cmd, rc, tail, parsed},
    a raw bench line {metric, value, ...}, or a list of either."""
    if isinstance(obj, list):
        out: list[dict] = []
        for item in obj:
            out.extend(extract_samples(item, source))
        return out
    if not isinstance(obj, dict):
        return []
    if "parsed" in obj or "rc" in obj:  # committed wrapper
        parsed = obj.get("parsed")
        if obj.get("rc", 1) != 0 or parsed is None:
            return [{
                "source": source, "metric": "unknown", "value": None,
                "error": "run-failed", "usable": False,
            }]
        return extract_samples(parsed, source)
    if "metric" in obj:
        return [_sample_from_line(obj, source)]
    # bench.py multi-line runs: {"lines": [...]} or dict of named lines
    if "lines" in obj and isinstance(obj["lines"], list):
        return extract_samples(obj["lines"], source)
    return []


def load_files(paths: list[str]) -> list[dict]:
    samples: list[dict] = []
    for path in paths:
        try:
            with open(path) as fh:
                text = fh.read()
        except OSError as exc:
            samples.append({"source": path, "metric": "unknown", "value": None,
                            "error": f"unreadable: {exc}", "usable": False})
            continue
        # A file may hold one pretty-printed object or one JSON line per row.
        try:
            samples.extend(extract_samples(json.loads(text), path))
            continue
        except ValueError:
            pass
        for ln in text.splitlines():
            ln = ln.strip()
            if not ln:
                continue
            try:
                samples.extend(extract_samples(json.loads(ln), path))
            except ValueError:
                continue
    return samples


# ---------------------------------------------------------------------------
# Verdicts
# ---------------------------------------------------------------------------

def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(math.floor(pos))
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] * (1.0 - frac) + sorted_vals[hi] * frac


def _median(vals: list[float]) -> float:
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return float("nan")
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def verdict(baseline: list[float], candidate: list[float], *,
            metric: str = "unknown",
            rel_threshold: float = DEFAULT_REL_THRESHOLD,
            health: dict | None = None) -> dict:
    """Paired-sorted change-point verdict for one metric series."""
    out: dict = {
        "metric": metric,
        "baseline_n": len(baseline),
        "candidate_n": len(candidate),
        "rel_threshold": rel_threshold,
    }
    if len(baseline) < MIN_BASELINE or not candidate:
        out["verdict"] = "no-baseline"
        out["reason"] = (
            f"need >= {MIN_BASELINE} usable baseline samples and >= 1 "
            f"candidate sample (have {len(baseline)}/{len(candidate)})")
        return out

    base = sorted(baseline)
    cand = sorted(candidate)
    med = _median(base)
    p10, p90 = _percentile(base, 0.10), _percentile(base, 0.90)
    spread_rel = (p90 - p10) / med if med > 0 else float("inf")
    floor = max(rel_threshold, spread_rel)
    out["baseline_median"] = med
    out["baseline_spread_rel"] = round(spread_rel, 6)
    out["noise_floor"] = round(floor, 6)

    # Pair k-th smallest with k-th smallest; with unequal lengths pair the
    # shorter series against evenly spaced quantiles of the longer one so
    # neither tail dominates.
    n = min(len(base), len(cand))
    if len(base) == len(cand):
        pairs = list(zip(base, cand))
    elif len(cand) < len(base):
        pairs = [(_percentile(base, (i + 0.5) / n), cand[i]) for i in range(n)]
    else:
        pairs = [(base[i], _percentile(cand, (i + 0.5) / n)) for i in range(n)]

    worse = lower_is_better(metric)
    deltas = []
    for b, c in pairs:
        if b <= 0:
            continue
        slow = (c - b) / b if worse else (b - c) / b
        deltas.append(slow)
    if not deltas:
        out["verdict"] = "no-baseline"
        out["reason"] = "no positive baseline pairs"
        return out

    med_slow = _median(deltas)
    out["median_slowdown"] = round(med_slow, 6)
    out["pair_deltas"] = [round(d, 6) for d in deltas]

    if med_slow > floor:
        if health is not None and not health.get("healthy", True):
            out["verdict"] = "degraded-host"
            out["reason"] = ("slowdown exceeds noise floor but host probe is "
                            f"unhealthy ({health.get('reasons')}); re-run on a "
                            "healthy host before blaming the change")
        else:
            out["verdict"] = "regression"
            out["reason"] = (f"median paired slowdown {med_slow:.1%} exceeds "
                            f"noise floor {floor:.1%}")
    elif med_slow < -floor:
        out["verdict"] = "improved"
        out["reason"] = f"median paired speedup {-med_slow:.1%}"
    else:
        out["verdict"] = "ok"
        out["reason"] = (f"median paired slowdown {med_slow:.1%} within "
                        f"noise floor {floor:.1%}")
    if health is not None:
        out["host"] = health
    return out


def check_series(history_samples: list[dict], new_samples: list[dict], *,
                 rel_threshold: float, health: dict | None) -> dict:
    """Group samples by metric and produce one verdict per metric."""
    metrics: dict[str, tuple[list[float], list[float]]] = {}
    for s in history_samples:
        if s["usable"]:
            metrics.setdefault(s["metric"], ([], []))[0].append(s["value"])
    for s in new_samples:
        if s["usable"]:
            metrics.setdefault(s["metric"], ([], []))[1].append(s["value"])
    verdicts = {
        m: verdict(base, cand, metric=m, rel_threshold=rel_threshold,
                   health=health)
        for m, (base, cand) in sorted(metrics.items())
    }
    if not verdicts:
        verdicts["unknown"] = {
            "metric": "unknown", "verdict": "no-baseline",
            "reason": "no usable samples in history or candidate runs",
            "baseline_n": 0, "candidate_n": 0,
        }
    order = ("no-baseline", "improved", "ok", "degraded-host", "regression")
    worst = max((v["verdict"] for v in verdicts.values()), key=order.index)
    unusable = [s for s in history_samples + new_samples if not s["usable"]]
    return {
        "sentry": "perf_sentry",
        "overall": worst,
        "verdicts": verdicts,
        "unusable_samples": len(unusable),
        "unusable_detail": [
            {"source": s["source"], "error": s["error"]} for s in unusable[:10]
        ],
    }


# ---------------------------------------------------------------------------
# The cost arm: deterministic verdicts from static cost manifests
# ---------------------------------------------------------------------------

#: combined-verdict severity order — cost "regression" outranks the
#: timing arm's "degraded-host": a sick host can invalidate a timing
#: but it cannot change a compiled program's static cost.
VERDICT_ORDER = ("no-baseline", "improved", "ok", "degraded-host",
                 "regression")


def cost_verdict(base_row: dict | None, cand_row: dict | None, *,
                 program: str = "unknown",
                 health: dict | None = None) -> dict:
    """Deterministic verdict for one program's static cost shape.

    Compares the budgeted cost axes (flops, bytes accessed, peak bytes)
    of two docs/cost_model.json rows.  The noise floor is EXACTLY zero:
    any increase on any budgeted axis is a regression, any decrease an
    improvement, digest-identical rows are quiet.  ``health`` is
    accepted for interface symmetry with `verdict()` but deliberately
    NEVER downgrades — that asymmetry is the whole point of the arm."""
    out: dict = {"program": program, "arm": "cost", "noise_floor": 0.0}
    if not base_row or not cand_row:
        out["verdict"] = "no-baseline"
        out["reason"] = "missing cost row (run tools/cost_observatory.py)"
        return out
    if base_row.get("cost_digest") == cand_row.get("cost_digest"):
        out["verdict"] = "ok"
        out["reason"] = "identical cost digest (zero cost delta)"
        out["max_rel_delta"] = 0.0
        return out
    deltas = {}
    for f in costmodel.BUDGET_FIELDS:
        b, c = base_row.get(f), cand_row.get(f)
        if b is None or c is None:
            continue
        deltas[f] = round((c - b) / b, 6) if b else (1.0 if c else 0.0)
    if not deltas:
        # static-only rows: the digest covers TPU StableHLO + collective
        # census — a digest move with no CPU cost axes is still a shape
        # change that must be reviewed, but has no magnitude to rank.
        out["verdict"] = "regression"
        out["reason"] = ("static-only cost shape changed (TPU digest or "
                         "collective census drift)")
        return out
    worst_field = max(deltas, key=lambda f: deltas[f])
    worst = deltas[worst_field]
    out["deltas"] = deltas
    out["max_rel_delta"] = worst
    if worst > 0:
        out["verdict"] = "regression"
        out["reason"] = (f"{worst_field} grew {worst:+.1%}; static cost "
                         "deltas have no noise floor — a sick host cannot "
                         "explain this away")
    elif any(d < 0 for d in deltas.values()):
        out["verdict"] = "improved"
        out["reason"] = f"cost shrank (worst axis {worst_field} {worst:+.1%})"
    else:
        out["verdict"] = "ok"
        out["reason"] = "cost digest moved but budgeted axes are unchanged"
    return out


def cost_check(base_manifest: dict | None,
               cand_manifest: dict | None) -> dict:
    """Per-program cost verdicts between two cost manifests."""
    base_p = (base_manifest or {}).get("programs", {})
    cand_p = (cand_manifest or {}).get("programs", {})
    verdicts = {
        name: cost_verdict(base_p.get(name), cand_p.get(name), program=name)
        for name in sorted(set(base_p) | set(cand_p))
    }
    if not verdicts:
        verdicts["unknown"] = {
            "program": "unknown", "arm": "cost", "verdict": "no-baseline",
            "reason": "no cost manifests to compare",
        }
    worst = max((v["verdict"] for v in verdicts.values()),
                key=VERDICT_ORDER.index)
    return {
        "sentry": "perf_sentry_cost_arm",
        "overall": worst,
        "jax_baseline": (base_manifest or {}).get("jax"),
        "jax_candidate": (cand_manifest or {}).get("jax"),
        "comparable_jax": (base_manifest or {}).get("jax")
        == (cand_manifest or {}).get("jax"),
        "verdicts": verdicts,
    }


def combine_arms(timing: str, cost: str) -> str:
    """Two-arm combined verdict: worst of both by VERDICT_ORDER.  A cost
    ``regression`` therefore overrides a timing ``degraded-host`` —
    exactly the split the selftest proves — while a cost ``ok`` never
    upgrades a timing regression (a runtime-only regression, e.g. a bad
    donation pattern, is invisible to static cost)."""
    return max((timing, cost), key=VERDICT_ORDER.index)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def cmd_check(args) -> int:
    hist_paths: list[str] = []
    for pat in args.history:
        hist_paths.extend(sorted(glob.glob(pat)))
    new_paths: list[str] = []
    for pat in args.new:
        new_paths.extend(sorted(glob.glob(pat)))
    health = None if args.no_probe else host_health.probe(args.probe_timeout)
    report = check_series(
        load_files(hist_paths), load_files(new_paths),
        rel_threshold=args.rel_threshold, health=health)
    report["history_files"] = hist_paths
    report["new_files"] = new_paths
    if args.cost_baseline:
        cost = cost_check(
            costmodel.load_manifest(args.cost_baseline),
            costmodel.load_manifest(args.cost_candidate))
        report["cost_arm"] = cost
        report["timing_overall"] = report["overall"]
        report["overall"] = combine_arms(report["overall"], cost["overall"])
    print(json.dumps(report, sort_keys=True))
    return 1 if report["overall"] == "regression" else 0


def cmd_cost(args) -> int:
    """Standalone cost-arm verdict between two cost manifests."""
    report = cost_check(
        costmodel.load_manifest(args.baseline),
        costmodel.load_manifest(args.candidate))
    print(json.dumps(report, sort_keys=True))
    return 1 if report["overall"] == "regression" else 0


def _timed_series(n: int, work: int, reps: int = 5) -> list[float]:
    """Really-measured wall times of a fixed deterministic workload.

    Each sample is the min over ``reps`` back-to-back runs: the minimum
    is the classic robust timer — scheduler preemptions and co-tenant
    noise only ever add time, so min-of-k recovers the workload's true
    cost and keeps the series' p10-p90 spread below the injected shifts
    the selftest must detect even on a loaded single-core container.
    """
    out = []
    for _ in range(n):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            acc = 0
            for i in range(work):
                acc += i * i
            best = min(best, time.perf_counter() - t0)
        out.append(best * 1e3)
    assert acc >= 0
    return out


def cmd_selftest(args) -> int:
    """Prove the three sentry properties on real timings:
    reshuffle => quiet, injected 20% slowdown => flagged,
    degenerate committed history => no-baseline."""
    health_ok = {"healthy": True, "reasons": []}

    # Measure a real series, re-measuring with more reps if this host is
    # too noisy for the nominal 25% injection to clear its own floor.
    inject_factor = 1.25
    for reps in (5, 11, 21):
        base = _timed_series(n=15, work=20_000, reps=reps)
        probe_v = verdict(base, base, metric="selftest_ms", health=health_ok)
        if probe_v["noise_floor"] < (inject_factor - 1.0) * 0.8:
            break
    scaled = False
    if probe_v["noise_floor"] >= (inject_factor - 1.0) * 0.8:
        # Host never settled: a 25% shift genuinely drowns in this
        # machine's noise and a correct sentry must stay quiet on it.
        # Test the same property at a detectable magnitude instead.
        inject_factor = 1.0 + 2.0 * probe_v["noise_floor"]
        scaled = True

    # 1. Reshuffle: same measurements, different order -> exactly quiet.
    shuffled = list(base)
    random.Random(1234).shuffle(shuffled)
    v_shuffle = verdict(base, shuffled, metric="selftest_ms",
                        health=health_ok)
    quiet = v_shuffle["verdict"] == "ok" and v_shuffle["median_slowdown"] == 0.0

    # 2. Inject a uniform slowdown (nominally 20% throughput loss, i.e.
    #    x1.25 latency) -> flagged even against this host's measured
    #    noise, because pairing keeps the shift intact on every pair.
    injected = [t * inject_factor for t in base]
    v_inject = verdict(base, injected, metric="selftest_ms",
                       health=health_ok)
    flagged = v_inject["verdict"] == "regression"

    # 2b. Same injection on an unhealthy host downgrades, never blames.
    v_degraded = verdict(base, injected, metric="selftest_ms",
                         health={"healthy": False, "reasons": ["load_high"]})
    downgraded = v_degraded["verdict"] == "degraded-host"

    # 3. Committed degenerate history (tunnel-down runs, value 0) must
    #    yield no-baseline, not a regression.
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    hist = load_files(sorted(glob.glob(os.path.join(repo, "BENCH_r0*.json"))))
    usable = [s for s in hist if s["usable"]]
    v_hist = check_series(hist, [_sample_from_line(
        {"metric": "pods_scheduled_per_sec", "value": 100.0}, "selftest")],
        rel_threshold=DEFAULT_REL_THRESHOLD, health=health_ok)
    no_baseline = (not usable) == (v_hist["overall"] == "no-baseline")

    # 4. The two-arm split (ISSUE 20).  Same simulated sick host as 2b,
    #    but the candidate carries an injected ALGORITHMIC regression: a
    #    doubled flops/bytes cost shape (the accidental O(N*P) gather).
    #    The timing arm must downgrade (it cannot trust this host); the
    #    cost arm must still say regression (static cost has a zero
    #    noise floor); the combined verdict must side with the cost arm.
    base_cost = {"flops": 1_000_000, "bytes_accessed": 2_000_000,
                 "peak_bytes": 500_000}
    base_cost["cost_digest"] = costmodel.cost_digest(base_cost)
    bad_cost = {"flops": base_cost["flops"] * 2,
                "bytes_accessed": base_cost["bytes_accessed"] * 2,
                "peak_bytes": base_cost["peak_bytes"]}
    bad_cost["cost_digest"] = costmodel.cost_digest(bad_cost)
    sick = {"healthy": False, "reasons": ["load_high"]}
    v_cost_sick = cost_verdict(base_cost, bad_cost, program="selftest",
                               health=sick)
    split = (
        v_degraded["verdict"] == "degraded-host"        # timing arm yields
        and v_cost_sick["verdict"] == "regression"       # cost arm does not
        and combine_arms(v_degraded["verdict"],
                         v_cost_sick["verdict"]) == "regression"
    )

    # 4b. Pure timing wobble with ZERO cost delta stays quiet on the
    #     cost arm: identical digests short-circuit to ok.
    v_cost_same = cost_verdict(base_cost, dict(base_cost),
                               program="selftest", health=sick)
    cost_quiet = (v_cost_same["verdict"] == "ok"
                  and v_cost_same["max_rel_delta"] == 0.0
                  and combine_arms("ok", v_cost_same["verdict"]) == "ok")

    ok = quiet and flagged and downgraded and no_baseline and split \
        and cost_quiet
    print(json.dumps({
        "sentry": "perf_sentry_selftest",
        "ok": ok,
        "reshuffle_quiet": quiet,
        "injection_flagged": flagged,
        "unhealthy_host_downgraded": downgraded,
        "degenerate_history_no_baseline": no_baseline,
        "cost_arm_overrides_degraded_host": split,
        "cost_arm_zero_delta_quiet": cost_quiet,
        "usable_history_samples": len(usable),
        "injected_factor": round(inject_factor, 6),
        "injection_scaled_to_host_noise": scaled,
        "injected_median_slowdown": v_inject.get("median_slowdown"),
        "noise_floor": v_inject.get("noise_floor"),
        "cost_arm_max_rel_delta": v_cost_sick.get("max_rel_delta"),
    }, sort_keys=True))
    return 0 if ok else 1


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)

    chk = sub.add_parser("check", help="verdict new runs against history")
    chk.add_argument("--history", action="append", default=None,
                     help="glob of committed history files "
                          "(default BENCH_r0*.json); repeatable")
    chk.add_argument("--new", action="append", required=True,
                     help="glob of fresh bench JSON files; repeatable")
    chk.add_argument("--rel-threshold", type=float,
                     default=DEFAULT_REL_THRESHOLD)
    chk.add_argument("--no-probe", action="store_true",
                     help="skip the host-health probe stamp")
    chk.add_argument("--probe-timeout", type=float,
                     default=host_health.DEFAULT_TIMEOUT_S)
    chk.add_argument("--cost-baseline",
                     help="baseline docs/cost_model.json to run the "
                          "deterministic cost arm against (combined "
                          "verdict: cost regression overrides "
                          "degraded-host)")
    chk.add_argument("--cost-candidate", default=None,
                     help="candidate cost manifest (default: the "
                          "committed docs/cost_model.json)")
    chk.set_defaults(fn=cmd_check)

    cst = sub.add_parser("cost", help="deterministic cost-arm verdict "
                                      "between two cost manifests")
    cst.add_argument("--baseline", required=True,
                     help="baseline cost_model.json (e.g. from the "
                          "merge-base commit)")
    cst.add_argument("--candidate", default=None,
                     help="candidate manifest (default: committed "
                          "docs/cost_model.json)")
    cst.set_defaults(fn=cmd_cost)

    st = sub.add_parser("selftest", help="prove sentry properties on "
                                         "real timings; rc 1 on failure")
    st.set_defaults(fn=cmd_selftest)

    args = ap.parse_args(argv)
    if getattr(args, "history", "sentinel") is None:
        args.history = ["BENCH_r0*.json"]
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
