#!/usr/bin/env python
"""AOT TPU compile-readiness gate: StableHLO lowering + landmine scan.

The north star is on-chip throughput, but the axon tunnel can be dead for
whole rounds (CLAUDE.md) — so "runs on TPU" needs evidence that does not
require hardware. This tool cross-lowers every hot program to TPU StableHLO
via `jax.export` on the CPU backend (no TPU needed: lowering is the
platform-specific trace, compilation is not run) and then scans the emitted
module text for the landmine patterns CLAUDE.md documents:

- `dot`/`dot_general` on i64 operands (int64 matmul is unsupported on TPU);
- `reduce_window` over i64 (the vmem-hungry lowering 2-D int64 `jnp.cumsum`
  takes on TPU — can hang compiles);
- convolutions fed by i64 operands.

Programs covered (the full bench surface + the sharded solves + the graft
entry): bench configs 0-6 — including the north-star chunk loop — both
sharded solves in `parallel/solver.py`, and `__graft_entry__.entry()`.

A digest manifest (`docs/tpu_lowering.json`: program -> StableHLO SHA-256 +
op histogram, loc-metadata stripped) is committed so program regressions
show up as diffs. Hash equality is only enforced when the running jax
version matches the manifest's (StableHLO text is jax-version-dependent);
on a different jax the gate still enforces the program set, lowering
success, and zero landmines.

Usage:
    python tools/tpu_lower.py              # lower all, scan, write manifest
    python tools/tpu_lower.py --check     # read-only verify against manifest
    python tools/tpu_lower.py --programs entry bench_cfg0_tpu_smoke
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import re
import sys
from collections import Counter
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
MANIFEST = REPO / "docs" / "tpu_lowering.json"

if str(REPO) not in sys.path:  # `python tools/tpu_lower.py` from anywhere
    sys.path.insert(0, str(REPO))

#: TPU platform string passed to jax.export.
TARGET_PLATFORM = "tpu"


def bootstrap(n_devices: int = 8) -> None:
    """Force an n-device virtual CPU platform BEFORE the first backend touch
    (the environment pins `jax_platforms=axon` via config, which beats env
    vars and blocks forever when the tunnel is down). Delegates to
    `__graft_entry__._force_cpu_platform`, which also UPGRADES a
    pre-existing smaller `--xla_force_host_platform_device_count` in
    XLA_FLAGS — a stale 4-device export must not starve the 8-way sharded
    programs. Idempotent; must run before any jnp array is created.

    Also clears SPT_SANITIZE: program construction branches on it
    (checkify-instrumented solver builds), and the certification tools —
    this one and tools/jaxpr_audit.py, which shares this bootstrap — must
    always trace/lower the SHIPPED programs, never instrumented variants
    (a stray `export SPT_SANITIZE=1` would otherwise silently regenerate
    the committed manifests from the wrong programs)."""
    import __graft_entry__

    os.environ.pop("SPT_SANITIZE", None)
    __graft_entry__._force_cpu_platform(n_devices)
    # Pallas kernel bodies serialize into the tpu_custom_call payload as
    # MLIR *bytecode*, whose per-op locations the textual loc-stripper in
    # `canonical_text` cannot reach. With full tracebacks (the default)
    # those locations include THIS tool's call-stack frames, so any line
    # shift in this file silently drifted the three pallas program
    # digests. Single-frame locations pin the payload to the innermost
    # user frame (the kernel source itself) — digests track the kernels,
    # not the certification tool.
    import jax

    jax.config.update("jax_include_full_tracebacks_in_locations", False)


# ---------------------------------------------------------------------------
# StableHLO landmine scanner (pure text analysis — no jax required)
# ---------------------------------------------------------------------------

_OP_RE = re.compile(r'"?stablehlo\.([a-z_0-9]+)"?')
#: element-type i64 inside a tensor type: `tensor<8x8xi64>` / `tensor<i64>`
#: (`ui64` deliberately not matched: the landmines are signed-i64 ops).
_I64_ELT_RE = re.compile(r"(?:x|<)i64>")
#: ops where i64 operands are TPU landmines
_MATMUL_OPS = ("dot_general", "dot", "convolution")


def op_histogram(text: str) -> dict[str, int]:
    """{stablehlo op name: count} over the module text."""
    return dict(Counter(m.group(1) for m in _OP_RE.finditer(text)))


def _operand_signature(
    text: str, start: int, region_op: bool = False, window: int = 6000
) -> str:
    """The `(operand types)` of the op starting at `start`.

    Plain one-line ops (dot/dot_general/convolution) carry
    ` : (types) -> ...` or ` : type` on their OWN line — that form must be
    read first, or a nearby region op's closing signature gets
    mis-attributed. Region ops (reduce_window) close with
    `}) : (types) -> ...` a few lines down. Returns "" when not found."""
    chunk = text[start : start + window]
    if region_op:
        m = re.search(r"\}\)?\s*:\s*\(([^)]*)\)", chunk)
        return m.group(1) if m else ""
    line = chunk.split("\n", 1)[0]
    m = re.search(r":\s*\(([^)]*)\)", line)
    if m is None:
        m = re.search(r":\s*(tensor<[^>]*>)", line)
    return m.group(1) if m else ""


def scan_landmines(text: str) -> list[dict]:
    """CLAUDE.md TPU landmines in a StableHLO module: i64 `dot`/
    `dot_general`/`convolution` operands, and `reduce_window` over i64
    (what 2-D int64 cumsum lowers to on TPU). Returns finding dicts with
    the op name and its operand signature."""
    findings = []
    for m in _OP_RE.finditer(text):
        op = m.group(1)
        if op in _MATMUL_OPS:
            sig = _operand_signature(text, m.start())
            if _I64_ELT_RE.search(sig):
                findings.append(
                    {"op": op, "signature": sig.strip(), "offset": m.start()}
                )
        elif op == "reduce_window":
            sig = _operand_signature(text, m.start(), region_op=True)
            if _I64_ELT_RE.search(sig) and _max_tensor_rank(sig) >= 2:
                # 1-D i64 reduce_window is the standard TPU cumsum lowering
                # and benign; the CLAUDE.md landmine is the MULTI-DIM form
                # (2-D int64 cumsum), whose windows go vmem-pathological
                findings.append(
                    {"op": op, "signature": sig.strip(), "offset": m.start()}
                )
    return findings


def _max_tensor_rank(signature: str) -> int:
    """Highest tensor rank among `tensor<...>` types in a signature."""
    rank = 0
    for m in re.finditer(r"tensor<([^>]*)>", signature):
        dims = m.group(1).split("x")
        rank = max(rank, len(dims) - 1)  # last element is the dtype
    return rank


def canonical_text(text: str) -> str:
    """Module text with loc metadata stripped, so the digest tracks the
    PROGRAM (ops + types + structure) — not source line numbers, and not
    the process-global #locN counter (which shifts with whatever else was
    traced earlier in the process and made naive digests order-dependent).

    `loc(...)` attributes nest parens (`loc("f"(#loc3))`), so a balanced
    scanner removes them; any remaining bare #locN tokens and #locN
    definition lines are dropped too."""
    out = []
    i, n = 0, len(text)
    while i < n:
        j = text.find("loc(", i)
        # only strip the attribute form: start-of-token boundary
        while j > 0 and j < n and (text[j - 1].isalnum() or text[j - 1] == "_"):
            j = text.find("loc(", j + 1)
        if j == -1:
            out.append(text[i:])
            break
        out.append(text[i:j].rstrip(" "))
        depth, k = 0, j + 3
        while k < n:
            if text[k] == "(":
                depth += 1
            elif text[k] == ")":
                depth -= 1
                if depth == 0:
                    break
            k += 1
        i = k + 1
    text = "".join(out)
    text = re.sub(r"#loc\d*", "", text)
    return "\n".join(
        line.rstrip()
        for line in text.splitlines()
        if line.strip() not in ("", "=")
    )


def stablehlo_digest(text: str) -> str:
    return hashlib.sha256(canonical_text(text).encode()).hexdigest()


# ---------------------------------------------------------------------------
# Program registry: name -> builder returning (jitted_fn, args, mesh|None)
# ---------------------------------------------------------------------------


def _batch_solve_program(shape):
    """Configs 0/1: `bench.flagship_solve_stats` on `bench.alloc_problem` —
    the exact construction + jitted fn bench ships (wave-occupancy stats
    included: the timed program is the certified program)."""
    import jax

    import bench

    _, snap, _, weights = bench.alloc_problem(**shape)
    return jax.jit(bench.flagship_solve_stats), (snap, weights), None


def build_entry():
    import jax

    import __graft_entry__

    fn, args = __graft_entry__.entry()
    return jax.jit(fn), args, None


def build_cfg0_tpu_smoke():
    import bench

    return _batch_solve_program(bench.SMOKE_SHAPE)


def build_cfg1_flagship():
    import bench

    return _batch_solve_program(bench.FLAGSHIP_SHAPE)


def _sequential_program(config):
    """Configs 2-5: the bit-faithful sequential solve on
    `bench.config_problem`'s scenario/roster table (the one copy of those
    shapes), traced with the TPU-path scan unroll (runtime._scan_unroll
    returns 8 on TPU device kinds — mirror that here so the digest covers
    the program the chip would run, not the CPU test trace)."""
    import bench
    from scheduler_plugins_tpu.framework import Profile, Scheduler

    cluster, plugins, _ = bench.config_problem(config)
    scheduler = Scheduler(Profile(plugins=plugins))
    pending = scheduler.sort_pending(cluster.pending_pods(), cluster)
    snap, meta = cluster.snapshot(pending, now_ms=0)
    scheduler.prepare(meta, cluster)
    state0 = scheduler.initial_state(snap)
    auxes = tuple(p.aux() for p in scheduler.profile.plugins)
    fn = scheduler._make_solve(unroll=8)  # already jitted
    return fn, (snap, state0, auxes), None


def build_cfg2_trimaran_sequential():
    return _sequential_program(2)


def build_cfg3_numa_sequential():
    return _sequential_program(3)


def build_cfg4_gang_quota_sequential():
    return _sequential_program(4)


def build_cfg5_network_sequential():
    return _sequential_program(5)


def build_cfg6_north_star_chunk():
    """The north-star chunk loop body — `bench.north_star_chunk_solver()`
    (the DONATED jit: donation changes the exported calling convention, so
    the certified program must carry it), at the real node-count/chunk
    shapes from `bench.NORTH_STAR_SHAPE`, with the chunk-invariant tensors
    as arguments exactly as bench jits it (one pod chunk of cluster build
    suffices: every chunk shares this one compiled program)."""
    import bench
    from scheduler_plugins_tpu.ops.fit import free_capacity

    shape = bench.NORTH_STAR_SHAPE
    chunk = shape["chunk"]
    _, snap, meta, weights, raw, _ = bench.north_star_problem(
        shape["n_nodes"], chunk, chunk
    )
    free = free_capacity(snap.nodes.alloc, snap.nodes.requested)
    args = (
        raw,
        snap.nodes.mask,
        snap.pods.req[:chunk],
        snap.pods.mask[:chunk],
        free,
    )
    return bench.north_star_chunk_solver(), args, None


def _mesh8():
    from scheduler_plugins_tpu.parallel.mesh import make_mesh

    return make_mesh(8)


def build_sharded_batch_solve():
    """`parallel.solver.sharded_batch_solve`'s jitted program on an 8-way
    ("pods", "nodes") mesh — the gang+quota allocatable flagship with the
    snapshot sharded per `snapshot_shardings` (the dryrun_multichip layout;
    XLA inserts the cross-shard collectives)."""
    import jax

    import __graft_entry__
    from scheduler_plugins_tpu.parallel.mesh import shard_snapshot
    from scheduler_plugins_tpu.parallel.solver import batch_solve

    import jax.numpy as jnp

    from scheduler_plugins_tpu.api.resources import CPU, MEMORY

    mesh = _mesh8()
    pods_dim, nodes_dim = mesh.devices.shape
    scheduler, snap, meta = __graft_entry__._build_problem(
        n_nodes=16, n_pods=32, pad_nodes=16, pad_pods=32
    )
    assert 16 % nodes_dim == 0 and 32 % pods_dim == 0
    snap = shard_snapshot(snap, mesh)
    weights = jnp.asarray(
        meta.index.encode({CPU: 1 << 20, MEMORY: 1}), jnp.int64
    )
    fn = jax.jit(lambda s, w: batch_solve(s, w, 8))
    return fn, (snap, weights), mesh


def build_sharded_profile_batch_solve():
    """`parallel.solver.sharded_profile_batch_solve`'s jitted program: the
    mixed plugin roster (allocatable + NUMA + network + topology-spread
    validators) under the same 8-way mesh — the full-roster multi-chip
    path, not just the flagship."""
    from scheduler_plugins_tpu.framework import Profile, Scheduler
    from scheduler_plugins_tpu.models import mixed_scenario
    from scheduler_plugins_tpu.parallel.mesh import shard_snapshot
    from scheduler_plugins_tpu.parallel.solver import profile_batch_fn
    from scheduler_plugins_tpu.plugins import (
        NetworkOverhead,
        NodeResourcesAllocatable,
        NodeResourceTopologyMatch,
        PodTopologySpread,
    )

    mesh = _mesh8()
    cluster = mixed_scenario(n_nodes=16, n_pods=32)
    sched = Scheduler(
        Profile(
            plugins=[
                NodeResourcesAllocatable(),
                NodeResourceTopologyMatch(),
                NetworkOverhead(),
                PodTopologySpread(),
            ]
        )
    )
    for p in sched.profile.plugins:
        p.configure_cluster(cluster)
    pending = sched.sort_pending(cluster.pending_pods(), cluster)
    snap, meta = cluster.snapshot(pending, now_ms=0, pad_nodes=16, pad_pods=32)
    sched.prepare(meta, cluster)
    snap = shard_snapshot(snap, mesh)
    fn, args = profile_batch_fn(sched, snap, max_waves=8)
    return fn, args, mesh


def build_serving_delta_apply():
    """`serving.deltas.delta_apply_program` — the donated O(changed)
    scatter-apply the resident-state serving engine folds each cycle's
    delta batch with (`serving.engine.ServeEngine._apply_batch`), at the
    reduced resident shape `serving.engine.lower_program_args` builds.
    The donated resident carry changes the exported calling convention,
    so the certified program must carry it (like cfg6's chunk solver)."""
    from scheduler_plugins_tpu.serving.engine import lower_program_args

    fn, args = lower_program_args()
    return fn, args, None


def build_serving_node_compact():
    """`serving.deltas.node_compact_program` — the donated row-shift
    gather the streaming serve engine replaces node-delete rebases with
    (`StreamingServeEngine._compact_row`), at the reduced resident shape
    `serving.engine.compact_lower_args` builds. Same donated-carry
    calling convention as serving_delta_apply."""
    from scheduler_plugins_tpu.serving.engine import compact_lower_args

    fn, args = compact_lower_args()
    return fn, args, None


def _sharded_wave_chunk_program(use_pallas: bool):
    """Shared staging for the two sharded-wave-chunk manifest entries —
    ONE copy of the reduced shard-smoke problem, mesh and
    `rank_order_inputs` pre-permutation (exactly as bench stages it), so
    the lax and pallas entries can never drift onto different shapes. The
    resident rank-ordered free carry is DONATED (the exported calling
    convention must carry it, like cfg6's chunk program)."""
    import bench
    from scheduler_plugins_tpu.parallel.mesh import make_node_mesh
    from scheduler_plugins_tpu.parallel.solver import (
        rank_order_inputs,
        sharded_wave_chunk_solver,
    )

    shape = bench.SHARD_SMOKE_SHAPE
    problem = bench.mega_problem(
        shape["n_nodes"], shape["n_pods"], shape["chunk"]
    )
    mesh = make_node_mesh(shape["devices"])
    node_ids, rank_free = rank_order_inputs(
        problem["raw"], problem["free0"], problem["node_mask"],
        shape["devices"],
    )
    chunk = shape["chunk"]
    fn = sharded_wave_chunk_solver(
        mesh, shape["n_nodes"], rescue_window=256,
        use_pallas=use_pallas, pallas_interpret=False,
    )
    args = (
        node_ids, problem["req"][:chunk], problem["mask"][:chunk], rank_free
    )
    return fn, args, mesh


def build_sharded_wave_chunk():
    """The sharded wave chunk program (`parallel.solver.
    sharded_wave_chunk_solver` — the shard_map ring-election waterfill the
    mega config 8 ships) on an 8-way ("nodes",) mesh at the reduced
    shard-smoke shapes. The lowering proves the per-wave ring/psum
    elections — never a full node-axis gather — lower to TPU collectives.
    use_pallas pinned False: this entry certifies the LAX collectives
    build — an ambient SPT_PALLAS=1 in the manifest-refresh shell must
    never silently swap which formulation carries this program's digest."""
    return _sharded_wave_chunk_program(use_pallas=False)


def build_sharded_wave_chunk_pallas():
    """The sharded wave chunk program with the PALLAS election path
    (`use_pallas=True, pallas_interpret=False` — the COMPILED kernels, not
    the CPU twins): same shapes/mesh as `sharded_wave_chunk` (shared
    staging), but every per-wave collective is a `parallel.kernels` ring
    program. Lowering this proves the whole solve — kernels under
    shard_map under the wave while_loops, Mosaic bodies included — exports
    to TPU StableHLO (`tpu_custom_call` with the serialized kernel
    payloads), which is the ISSUE 13 readiness evidence
    `make tpu-first-cycle` checks."""
    return _sharded_wave_chunk_program(use_pallas=True)


def _node_mesh8():
    from scheduler_plugins_tpu.parallel.mesh import make_node_mesh

    return make_node_mesh(8)


def build_pallas_ring_offsets():
    """`parallel.kernels.ring_offsets_f64` standalone (compiled body, 8-way
    node mesh): the double-buffered `make_async_remote_copy` exclusive-
    scan ring at the lite wave's cumulative-free payload shape. The
    kernel-body op census (dma_start/dma_wait, semaphore ops) lives in
    docs/jaxpr_audit.json; this entry certifies the Mosaic body serializes
    into TPU StableHLO."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from scheduler_plugins_tpu.api.resources import CANONICAL
    from scheduler_plugins_tpu.parallel import kernels as pk
    from scheduler_plugins_tpu.parallel.mesh import NODES_AXIS

    mesh = _node_mesh8()
    S, R = 8, len(CANONICAL)

    def per_shard(x):
        return pk.ring_offsets_f64(
            x.reshape(R), NODES_AXIS, S, interpret=False
        )

    fn = jax.jit(shard_map(
        per_shard, mesh=mesh, in_specs=P(NODES_AXIS),
        out_specs=(P(NODES_AXIS), P(NODES_AXIS)), check_rep=False,
    ))
    x = jnp.arange(S * R, dtype=jnp.float64) * (1 << 30)
    return fn, (x,), mesh


def build_pallas_fused_election():
    """`parallel.kernels.fused_election` standalone (compiled body, 8-way
    node mesh) at the rescue-window election shape: min-rank keys plus the
    winner node-id/free-row payload in one ring program — the kernel that
    retires the packed admission-verdict psum."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from scheduler_plugins_tpu.api.resources import CANONICAL
    from scheduler_plugins_tpu.parallel import kernels as pk
    from scheduler_plugins_tpu.parallel.mesh import NODES_AXIS

    mesh = _node_mesh8()
    S, R, W = 8, len(CANONICAL), 256
    HP = 1 + pk.N_LIMBS * R

    def per_shard(keys, payload):
        return pk.fused_election(
            keys.reshape(W), payload.reshape(HP, W), NODES_AXIS, S,
            interpret=False,
        )

    fn = jax.jit(shard_map(
        per_shard, mesh=mesh, in_specs=(P(NODES_AXIS), P(NODES_AXIS)),
        out_specs=(P(), P(None, None)), check_rep=False,
    ))
    keys = jnp.zeros(S * W, jnp.int32)
    payload = jnp.zeros(S * HP * W, jnp.int32)
    return fn, (keys, payload), mesh


def _gang_problem():
    """Reduced rank-gang problem shared by the two gang programs: the
    config-10 scenario generators at smoke shape, lowered through the
    SAME `gangs.phase.build_rank_gang_problem` the shipped phase uses."""
    from scheduler_plugins_tpu.gangs.phase import build_rank_gang_problem
    from scheduler_plugins_tpu.models import rank_gang_scenario

    cluster = rank_gang_scenario(
        n_nodes=16, n_regions=2, zones_per_region=2, n_mpi=2, mpi_ranks=4,
        n_dl=1, dl_min=2, dl_desired=3, dl_max=4,
    )
    pending = sorted(cluster.pending_pods(), key=lambda p: p.creation_ms)
    prob = build_rank_gang_problem(cluster, pending, now=0)
    assert prob is not None
    return prob


def build_rank_gang_solve():
    """`gangs.topology.gang_solve_body` — the topology-block waterfill
    gang solve (scan over gangs, carried free/eq_used/rank_nodes). The
    `SolverState.rank_nodes` carry is initialized from the resident
    assignment (`RankGangState.prev_assigned` — its CARRY_COUNTERPARTS
    snapshot twin), so the jaxpr audit's JA001 can prove the solve
    threads placements through the carry."""
    import jax
    import jax.numpy as jnp

    from scheduler_plugins_tpu.framework.plugin import SolverState
    from scheduler_plugins_tpu.gangs.topology import gang_solve_fn

    prob = _gang_problem()
    gangs = jax.tree.map(jnp.asarray, prob["gangs"])
    state0 = SolverState(
        free=jnp.asarray(prob["free0"]),
        eq_used=jnp.asarray(prob["eq_used0"]),
        rank_nodes=jnp.asarray(prob["gangs"].prev_assigned),
    )
    return gang_solve_fn(), (gangs, state0, jnp.asarray(prob["node_mask"])), None


def build_elastic_shrink():
    """`gangs.elastic.shrink_select` — the elastic shrink-selection
    program (highest-cost ranks released first) over the resident
    rank-assignment carry."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from scheduler_plugins_tpu.gangs.elastic import shrink_select

    prob = _gang_problem()
    gangs = prob["gangs"]
    G, M = gangs.rank_mask.shape
    # a resident assignment: every masked slot on some node (the shrink
    # program runs on LIVE gangs)
    rank_nodes = np.where(
        gangs.rank_mask, np.arange(M)[None, :] % prob["free0"].shape[0], -1
    ).astype(np.int32)
    args = (
        jnp.asarray(rank_nodes),
        jnp.asarray(gangs.rank_mask),
        jnp.asarray(gangs.node_block),
        jnp.asarray(gangs.block_cost),
        jnp.asarray(np.ones(G, np.int32)),
    )
    return jax.jit(shrink_select), args, None


def build_serving_side_apply():
    """`serving.deltas.side_apply_program` — the donated O(changed)
    scatter-apply maintaining the resident gang/quota side tables
    (`serving.engine.ServeEngine._apply_side`; ISSUE 12), at the reduced
    shape `serving.engine.side_lower_args` builds. Same donated-carry
    calling convention as serving_delta_apply."""
    from scheduler_plugins_tpu.serving.engine import side_lower_args

    fn, args = side_lower_args()
    return fn, args, None


def build_wave_gang_solve():
    """`gangs.waves.wave_solve_body` — one wave of the wave-batched gang
    solve: the sequential scan's own per-gang body
    (`gangs.topology.place_gang_one`) vmapped over a lane of independent
    gang ids against the wave-start state (the host validator owns the
    between-wave carries). Lowered at the reduced `_gang_problem` shape
    with an 8-lane wave."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from scheduler_plugins_tpu.gangs.waves import wave_solve_fn

    prob = _gang_problem()
    gangs = jax.tree.map(jnp.asarray, prob["gangs"])
    G = prob["gangs"].rank_mask.shape[0]
    ids = jnp.asarray((np.arange(8) % G).astype(np.int32))
    args = (
        gangs, jnp.asarray(prob["free0"]), jnp.asarray(prob["eq_used0"]),
        jnp.asarray(prob["node_mask"]), ids,
    )
    return wave_solve_fn(), args, None


def build_packing_solve():
    """`parallel.solver.packing_solve_fn` — the jitted packing-mode
    flagship program (ISSUE 14: targeted waterfill wave placement +
    `ops.packing.packing_refine` consolidation rounds + the shared
    finalize tail) at the reduced pack-smoke shape. The iteration
    budget, fragmentation-price weight and temperature schedule are the
    traced `pack_aux` argument, so ONE program serves every budget the
    bench frontier sweeps — the property the lowering certifies for
    TPU (the refinement's `lax.while_loop` bound is a traced scalar)."""
    import bench
    from scheduler_plugins_tpu.ops.packing import pack_aux_vector
    from scheduler_plugins_tpu.parallel.solver import packing_solve_fn

    shape = bench.PACK_SMOKE_SHAPE
    _, snap, _, weights = bench.packing_problem(
        shape["n_nodes"], shape["demand_frac"], shape["empty_frac"]
    )
    fn = packing_solve_fn(collect_stats=True)
    return fn, (snap, weights, pack_aux_vector(32, 4.0, 0.0, 0.5)), None


def build_sweep_solve():
    """The vmapped counterfactual weight sweep (`parallel.solver
    .sweep_solve_fn` — the tuning observatory's hot program): the
    bit-faithful sequential solve body vmapped over an 8-lane candidate
    weight bucket on the reduced tune-smoke trimaran roster
    (tools/tune.py SMOKE corpus roster at a smaller shape; candidate
    weights are traced per-lane arguments, so ONE program serves every
    candidate — the property the lowering certifies for TPU)."""
    import jax.numpy as jnp

    from scheduler_plugins_tpu import plugins as P
    from scheduler_plugins_tpu.framework import Profile, Scheduler
    from scheduler_plugins_tpu.models import trimaran_scenario
    from scheduler_plugins_tpu.parallel.solver import sweep_solve_fn
    from scheduler_plugins_tpu.tuning import sweep

    cluster = trimaran_scenario(n_nodes=64, n_pods=32, seed=0)
    scheduler = Scheduler(Profile(plugins=[
        P.TargetLoadPacking(), P.LoadVariationRiskBalancing(),
    ]))
    pending = scheduler.sort_pending(cluster.pending_pods(), cluster)
    snap, meta = cluster.snapshot(pending, now_ms=0)
    scheduler.prepare(meta, cluster)
    W = sweep.pad_candidates(sweep.candidate_weights([1, 1], 8))
    auxes = tuple(p.aux() for p in scheduler.profile.plugins)
    fn = sweep_solve_fn(scheduler)
    args = (snap, scheduler.initial_state(snap), auxes, jnp.asarray(W))
    return fn, args, None


def _lane_problem():
    """Reduced zoned multi-tenant roster for the K-lane programs: 16
    nodes, 96 pods over 8 tenant namespaces (12 per segment), the
    allocatable profile — the smallest shape that exercises the lane
    gather + scan and the segment-grain screen axes."""
    from scheduler_plugins_tpu.api.objects import Container, Node, Pod
    from scheduler_plugins_tpu.api.resources import CPU, MEMORY, PODS
    from scheduler_plugins_tpu.framework import Profile, Scheduler
    from scheduler_plugins_tpu.plugins import NodeResourcesAllocatable
    from scheduler_plugins_tpu.state.cluster import Cluster

    gib = 1 << 30
    cluster = Cluster()
    for i in range(16):
        cluster.add_node(Node(
            name=f"n{i:02d}",
            allocatable={CPU: 64_000, MEMORY: 256 * gib, PODS: 256},
        ))
    for s in range(96):
        cluster.add_pod(Pod(
            name=f"p{s:03d}", namespace=f"t{s % 8}", creation_ms=s,
            containers=[Container(requests={CPU: 500, MEMORY: gib})],
        ))
    scheduler = Scheduler(Profile(plugins=[NodeResourcesAllocatable()]))
    pending = scheduler.sort_pending(cluster.pending_pods(), cluster)
    snap, meta = cluster.snapshot(pending, now_ms=0)
    scheduler.prepare(meta, cluster)
    return cluster, scheduler, pending, snap


def build_lane_solve():
    """`parallel.lanes.lane_solve_fn` — the K-lane speculative solve
    (ISSUE 17): vmap over the lane axis of a scan of THE parity step
    body (`_solve_step`, one copy shared with `Scheduler.solve`), each
    lane's pod rows gathered ONCE outside the scan so the step body runs
    zero batched gathers (the CPU per-row-loop / TPU vmem-hostile
    dynamic-slice gotcha). Lowered at K=4 lanes over the reduced zoned
    roster — the program shape `LaneSolver._dispatch` compiles per
    (K, bucket); the conflict repair reuses it at (1, L')."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from scheduler_plugins_tpu.parallel.lanes import (
        _bucket,
        lane_solve_fn,
        partition_segments,
    )

    cluster, scheduler, pending, snap = _lane_problem()
    k = 4
    lanes, _, _, _, _ = partition_segments(pending, cluster, k)
    bucket = _bucket(max(len(lane) for lane in lanes))
    idx2d = np.zeros((k, bucket), np.int32)
    live2d = np.zeros((k, bucket), bool)
    for j, lane in enumerate(lanes):
        idx2d[j, : len(lane)] = lane
        live2d[j, : len(lane)] = True
    state0 = scheduler.initial_state(snap)
    auxes = tuple(p.aux() for p in scheduler.profile.plugins)
    fn = jax.jit(lane_solve_fn(scheduler))
    args = (snap, state0, auxes, jnp.asarray(idx2d), jnp.asarray(live2d))
    return fn, args, None


def build_lane_screen():
    """`parallel.lanes.lane_screen_fn` — the conflict fence's stage-1
    compiled monotone screen (ISSUE 17): per-lane speculative node
    deficits + the segment-grain sufficient certificates (commit-safety
    and the two fit arms over host-accumulated per-segment demand
    extremes) in ONE dispatch over flat narrow arguments (the snapshot
    pytree flattening cost is the reason for the calling convention).
    Lowered at K=4 on the reduced zoned roster, quota/gang screens off
    (their branches extend the same program; the decision tables in
    tests/test_lanes.py pin the semantics)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from scheduler_plugins_tpu.parallel.lanes import (
        _bucket,
        lane_screen_fn,
        partition_segments,
    )

    cluster, scheduler, pending, snap = _lane_problem()
    k = 4
    _, seg_of_pod, lane_of_seg, seg_keys, _ = partition_segments(
        pending, cluster, k
    )
    P = snap.num_pods
    R = snap.pods.req.shape[1]
    S_b = _bucket(max(1, len(seg_keys)))
    state0 = scheduler.initial_state(snap)
    # shape-true placeholder outputs: the screen's inputs are the lane
    # outputs; values are irrelevant to the lowering, dtypes/shapes not
    assignment = np.full(P, -1, np.int32)
    lane_full = np.zeros(P, np.int32)
    lane_full[: len(pending)] = lane_of_seg[seg_of_pod]
    seg_lanes = np.zeros(S_b, np.int32)
    seg_lanes[: lane_of_seg.shape[0]] = lane_of_seg
    seg_mx = np.full((S_b, R), -np.inf, np.float64)
    seg_mn = np.full((S_b, R), np.inf, np.float64)
    core = (
        snap.pods.req, snap.pods.mask, snap.pods.gated, state0.free,
        snap.nodes.mask, jnp.asarray(assignment), jnp.asarray(lane_full),
        jnp.asarray(seg_mx), jnp.asarray(seg_mn), jnp.asarray(seg_lanes),
    )
    fn = jax.jit(lane_screen_fn(k, False, False))
    return fn, (core, (), ()), None


PROGRAMS = {
    "entry": build_entry,
    "lane_solve": build_lane_solve,
    "lane_screen": build_lane_screen,
    "serving_delta_apply": build_serving_delta_apply,
    "serving_node_compact": build_serving_node_compact,
    "sharded_wave_chunk": build_sharded_wave_chunk,
    "sharded_wave_chunk_pallas": build_sharded_wave_chunk_pallas,
    "pallas_ring_offsets": build_pallas_ring_offsets,
    "pallas_fused_election": build_pallas_fused_election,
    "sweep_solve": build_sweep_solve,
    "packing_solve": build_packing_solve,
    "rank_gang_solve": build_rank_gang_solve,
    "wave_gang_solve": build_wave_gang_solve,
    "elastic_shrink": build_elastic_shrink,
    "serving_side_apply": build_serving_side_apply,
    "bench_cfg0_tpu_smoke": build_cfg0_tpu_smoke,
    "bench_cfg1_flagship": build_cfg1_flagship,
    "bench_cfg2_trimaran_sequential": build_cfg2_trimaran_sequential,
    "bench_cfg3_numa_sequential": build_cfg3_numa_sequential,
    "bench_cfg4_gang_quota_sequential": build_cfg4_gang_quota_sequential,
    "bench_cfg5_network_sequential": build_cfg5_network_sequential,
    "bench_cfg6_north_star_chunk": build_cfg6_north_star_chunk,
    "sharded_batch_solve": build_sharded_batch_solve,
    "sharded_profile_batch_solve": build_sharded_profile_batch_solve,
}


def lower_program(name: str) -> str:
    """Build + AOT-lower one registered program to TPU StableHLO text."""
    import jax
    import jax.export

    from scheduler_plugins_tpu.parallel.mesh import ambient_mesh

    fn, args, mesh = PROGRAMS[name]()
    if mesh is not None:
        with ambient_mesh(mesh):
            exported = jax.export.export(fn, platforms=(TARGET_PLATFORM,))(
                *args
            )
    else:
        exported = jax.export.export(fn, platforms=(TARGET_PLATFORM,))(*args)
    return exported.mlir_module()


def analyze(name: str) -> dict:
    text = lower_program(name)
    findings = scan_landmines(text)
    hist = op_histogram(text)
    return {
        "sha256": stablehlo_digest(text),
        "stablehlo_bytes": len(canonical_text(text)),
        "ops": {k: hist[k] for k in sorted(hist)},
        "landmines": findings,
    }


def run(names, check: bool) -> int:
    import jax

    prior = {}
    if MANIFEST.exists():
        prior = json.loads(MANIFEST.read_text())
    results, failures = {}, []
    for name in names:
        print(f"[tpu-lower] {name} ...", flush=True)
        try:
            results[name] = analyze(name)
        except Exception as exc:  # lowering failure IS the gate tripping
            failures.append(f"{name}: lowering failed: {exc!r}")
            continue
        mines = results[name]["landmines"]
        if mines:
            for f in mines:
                failures.append(
                    f"{name}: TPU landmine {f['op']} on ({f['signature']})"
                )
        print(
            f"[tpu-lower] {name}: "
            f"{results[name]['stablehlo_bytes']} bytes, "
            f"{sum(results[name]['ops'].values())} ops, "
            f"{len(mines)} landmines",
            flush=True,
        )

    manifest = {
        "jax": jax.__version__,
        "platform": TARGET_PLATFORM,
        "programs": {
            n: {
                "sha256": r["sha256"],
                "stablehlo_bytes": r["stablehlo_bytes"],
                "landmines": len(r["landmines"]),
                "ops": r["ops"],
            }
            for n, r in sorted(results.items())
        },
    }

    if check and not prior:
        # the gate must fail CLOSED: a missing/deleted manifest means there
        # is nothing to verify drift against
        failures.append(
            "docs/tpu_lowering.json missing: run `python tools/tpu_lower.py` "
            "and commit it"
        )
    if check and prior:
        prior_programs = prior.get("programs", {})
        # any checked program absent from the manifest is a coverage gap —
        # also for --programs subsets (a new program must not check green
        # before its digest is committed)
        missing = [n for n in names if n in PROGRAMS and n not in prior_programs]
        if missing:
            failures.append(
                f"manifest missing programs {missing}: run "
                "`python tools/tpu_lower.py` and commit docs/tpu_lowering.json"
            )
        if prior.get("jax") == jax.__version__:
            for n, r in results.items():
                want = prior_programs.get(n, {}).get("sha256")
                if want and want != r["sha256"]:
                    failures.append(
                        f"{n}: StableHLO digest drift "
                        f"(manifest {want[:12]}.., now {r['sha256'][:12]}..) "
                        "— intended? re-run `python tools/tpu_lower.py` and "
                        "commit the manifest diff"
                    )
        else:
            print(
                f"[tpu-lower] note: manifest was written under jax "
                f"{prior.get('jax')}, running {jax.__version__}; digest "
                "equality not enforced (lowering text is version-dependent), "
                "landmine/coverage gates still apply"
            )

    if not check and set(names) == set(PROGRAMS) and not failures:
        MANIFEST.write_text(json.dumps(manifest, indent=1, sort_keys=True) + "\n")
        print(f"[tpu-lower] wrote {MANIFEST.relative_to(REPO)}")
    elif not check:
        # a failed or partial run must never clobber the last-good manifest
        reason = "failures" if failures else "partial program set"
        print(f"[tpu-lower] {reason}: manifest NOT rewritten")

    for f in failures:
        print(f"[tpu-lower] FAIL: {f}", file=sys.stderr)
    if not failures:
        print(
            f"[tpu-lower] OK: {len(results)}/{len(names)} programs lower to "
            f"TPU StableHLO with zero landmines"
        )
    return 1 if failures else 0


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument(
        "--check",
        action="store_true",
        help="read-only: verify against the committed manifest "
        "(digest equality enforced only under the manifest's jax version)",
    )
    parser.add_argument(
        "--programs",
        nargs="+",
        choices=sorted(PROGRAMS),
        default=sorted(PROGRAMS),
        help="subset of programs (default: all)",
    )
    args = parser.parse_args(argv)
    bootstrap()
    return run(args.programs, check=args.check)


if __name__ == "__main__":
    sys.exit(main())
