#!/usr/bin/env python
"""AST lint enforcing the CLAUDE.md invariants that only bite at
compile/runtime today (pure stdlib — no jax import, no tracing):

- **GL001 aux-closure-capture** — plugin config arrays must flow through the
  `aux()` channel (read back as `self._aux` after `bind_aux`), never read
  directly inside jitted tensor methods: jit caches the traced program by
  shape, so a closure-captured array is constant-folded and silently goes
  stale when config or name<->code layouts change between cycles.
- **GL002 i64-2d-cumsum** — no `jnp.cumsum` on int64 arrays with an `axis=`
  argument (the 2-D form): it lowers to vmem-hungry reduce-windows on TPU
  and can hang compiles. Use 1-D scans over sorted segments, float64
  (exact < 2^53), or an explicit int32 dtype.
- **GL003 i64-matmul** — no `@` / `jnp.dot` / `jnp.matmul` /
  `lax.dot_general` on int64 operands: int64 `dot_general` is unsupported
  on TPU.
- **GL004 block-until-ready-timing** — no `block_until_ready()` in a
  function that also reads a wall clock: it can return early through the
  axon tunnel; force completion with a host transfer (`np.asarray(x)`).
- **GL005 resource-slot-literal** — resource-axis positions must come from
  `api.resources.CANONICAL` / `meta.index.position(...)`, never hardcoded
  slot integers: the C++ bridge (`bridge/snapshot_store.cc`) hardcodes the
  same slots, so silent drift is silent data corruption.
- **GL006 donated-buffer-reuse** — a buffer passed in a DONATED position of
  a jitted call (`jax.jit(..., donate_argnums=...)` or
  `parallel.pipeline.donated_chunk_solver`) is dead after the call: XLA may
  have reused its memory for the outputs, and reading it raises (or, through
  a tunneled backend, can return garbage). Rebind the name from the call's
  results (`a, free = solve(..., free)`) before any further read. The check
  is lexical and conservative: only Name operands at literal donated
  positions are tracked, reassignment revives, and loop back-edges are not
  followed.
- **GL007 library-config-update** — no `jax.config.update(...)` outside the
  sanctioned owner files (`config-update-owners` in the pyproject config):
  platform/precision config is owned by the entrypoints and the test
  bootstrap (`tests/conftest.py`); a library-level update fights their
  platform pinning and its effect depends on import order.
- **GL008 jit-walltime** — no wall-clock reads (`time.perf_counter`,
  `time.perf_counter_ns`, `time.time`, `time.monotonic`, ...) inside
  jit-traced functions: trace-time Python runs ONCE per compile, so the
  "timestamp" is a baked constant that measures nothing — and through the
  tunneled backend even host-side `block_until_ready` timing lies (GL004).
  Device work is timed by bracketing HOST-SYNC transfers
  (`np.asarray(result)`); see `utils/observability.py` Tracer. Functions
  count as jit-traced when decorated with / passed to `jax.jit`,
  `parallel.pipeline.donated_chunk_solver`, `utils.sanitize.checkified`,
  or when they are Plugin tensor methods (which run under the fused
  solve's trace).

- **GL009 node-axis-all-gather** — no `lax.all_gather` /
  `all_gather_invariant` over the NODE shard axis (`"nodes"` /
  `parallel.mesh.NODES_AXIS`): the sharded wave solver's per-wave
  elections reduce per-shard CHAMPIONS (ring `ppermute` scans, psum/pmin
  slot-scatter reductions — `ops.assign.block_exclusive_offsets`); an
  all_gather of the node axis reassembles the full (N, ...) tensor on
  every shard, silently degrading the O(shards)-collective election back
  to a full gather. The shard-smoke gate's jaxpr collective census is the
  compiled-level twin.

- **GL011 pallas-kernel-purity** — inside a `pallas_call` kernel body: no
  host callbacks (`io_callback` / `pure_callback` / `debug_callback`), no
  wall-clock reads (`time.*`), and no Python `if`/`while` branching on the
  kernel's ref/traced parameters. A Pallas body is staged ONCE by Mosaic:
  host calls cannot cross the kernel boundary at all, a clock read is a
  baked constant (GL008's rule, one level deeper), and a Python branch on
  a ref value either fails to trace or silently bakes one path. Branch on
  STATIC closure config (shard counts, interpret flags) instead and mask
  traced conditions with `jnp.where`/`pl.when`. Detection is lexical and
  conservative: a function counts as a kernel body when its name is the
  first argument of a `pallas_call(...)` call (directly or through
  `functools.partial`); helpers it delegates to are trusted, like GL006's
  helper blindness.

- **GL010 swallowed-exception** — no broad exception handler (bare
  ``except:``, ``Exception``, ``BaseException``) whose body is only
  ``pass``/``...``: around solve/ingest sites that is how a backend
  fault, a poisoned delta batch, or a checkpoint failure vanishes
  silently. Fault paths must record + re-route (retry/failover/park/
  re-base — `resilience.watchdog` is the pattern); sanctioned
  best-effort paths (GC finalizers, shutdown cleanup, optional-dep
  probes) carry an inline ignore with their reason.

- **GL013 unaudited-f64-quantity-cast** — no new `.astype(jnp.float64)`
  (or array construction with `dtype=float64`) of a provably-int64
  quantity tensor outside the audited exactness owners
  (`exact-cast-owners` in the pyproject config). int64 quantities are
  exact in float64 only below 2^53; the owner modules' casts are walked
  and PROVEN by `tools/kernel_audit.py` KA003 (interval lattice over the
  declared `api.bounds` families, assumptions recorded in
  docs/kernel_audit.json), but a cast in un-traced new code silently
  assumes the invariant with no audit trail. Route new casts through the
  blessed helpers (`utils.intmath.exact_f64` — the sanctioned asserted-
  bound cast — or `parallel.kernels.join_limbs`), or add the module to
  the owner list, which is a reviewed declaration that its programs are
  in the kernel auditor's trace scope.

- **GL012 anonymous-thread** — every `threading.Thread(...)` must pass
  explicit `name=` and `daemon=`. The concurrency auditor
  (`tools/race_audit.py`) and the daemon's `/healthz` thread census key
  thread ENTRY POINTS by thread name — an anonymous thread is
  unauditable (it shows up as `Thread-7` in the live census and as an
  `anon@file:line` entry in the manifest, so topology drift cannot be
  attributed). Implicit `daemon` is a shutdown hazard: a forgotten
  non-daemon thread blocks interpreter exit.

Dtype inference is deliberately conservative: a rule fires only when an
operand PROVABLY carries int64 (explicit `.astype(jnp.int64)`, an int64
array constructor, a local name assigned from one, or a known int64
snapshot field like `.req`/`.alloc`). Unknown dtypes never fire.

Suppress a finding with a trailing `# graft-lint: ignore[GLxxx]` comment.

Config (`pyproject.toml [tool.graft-lint]`, parsed with a tiny stdlib
TOML subset — flat string / string-list keys only):
- `exclude`: repo-relative path prefixes skipped when EXPANDING directory
  arguments (the known-bad fixture corpora); a file named explicitly on
  the command line is always linted.
- `config-update-owners`: repo-relative path prefixes where GL007 is
  sanctioned.

Usage: python tools/graft_lint.py [paths...]   (default: the source tree
plus tests/ and tools/)
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

#: default lint scope: the package, the driver entry files, and the test +
#: tool trees (known-bad fixture corpora are excluded via the pyproject
#: config, not path hacks)
DEFAULT_PATHS = (
    "scheduler_plugins_tpu", "bench.py", "__graft_entry__.py", "tests",
    "tools",
)


def load_config() -> dict:
    """`[tool.graft-lint]` from pyproject.toml. Deliberately tiny TOML
    subset (the repo stays stdlib-only on py3.10, no tomllib): flat
    `key = "str"` / `key = ["str", ...]` entries inside the one section,
    values parsed as Python literals (valid for TOML strings/string
    lists)."""
    import ast as _ast

    cfg = {"exclude": [], "config-update-owners": [], "exact-cast-owners": []}
    path = REPO / "pyproject.toml"
    if not path.exists():
        return cfg
    def strip_comment(s: str) -> str:
        """Drop a trailing `# ...` TOML comment, respecting quoted strings
        (a `#` inside quotes is content, not a comment)."""
        quote = None
        for i, ch in enumerate(s):
            if quote:
                if ch == quote:
                    quote = None
            elif ch in "\"'":
                quote = ch
            elif ch == "#":
                return s[:i].rstrip()
        return s

    section, key, buf = None, None, None
    for raw in path.read_text().splitlines():
        line = strip_comment(raw.strip())
        if buf is not None:
            if not line:
                continue  # blank/comment-only lines inside a list
            buf += " " + line
            if line.endswith("]"):
                try:
                    cfg[key] = list(_ast.literal_eval(buf))
                except (ValueError, SyntaxError):
                    # a malformed list must fail LOUDLY: silently dropping
                    # `exclude` would sweep the known-bad fixture corpora
                    # into make lint with findings that look real
                    raise SystemExit(
                        f"graft-lint: unparseable [tool.graft-lint] value "
                        f"for {key!r} in pyproject.toml: {buf!r}"
                    )
                buf = None
            continue
        if line.startswith("["):
            section = line.strip("[]").strip()
            continue
        if section != "tool.graft-lint" or not line or line.startswith("#"):
            continue
        if "=" in line:
            key, _, val = line.partition("=")
            key, val = key.strip(), val.strip()
            if val.startswith("[") and not val.endswith("]"):
                buf = val
                continue
            try:
                parsed = _ast.literal_eval(val)
            except (ValueError, SyntaxError):
                continue
            cfg[key] = (
                list(parsed) if isinstance(parsed, (list, tuple)) else parsed
            )
    return cfg


def _rel_to_repo(path: Path):
    """Repo-relative POSIX path of `path`, or None when outside the repo
    (tmp-dir test files: never excluded, never GL007-sanctioned)."""
    try:
        return Path(path).resolve().relative_to(REPO).as_posix()
    except ValueError:
        return None

INT64, INT32, FLOAT, BOOL, UNKNOWN = "int64", "int32", "float", "bool", None

#: jitted tensor methods of the Plugin trait (framework/plugin.py) — code in
#: these runs under trace, so host-built jnp arrays read here are closure
#: captures. aux()/bind_aux and prepare_solve()/bind_presolve are the
#: sanctioned channels.
TENSOR_METHODS = frozenset({
    "admit", "filter", "score", "normalize", "commit", "static_node_scores",
    "filter_batch", "score_batch", "filter_rows", "batch_rows", "wave_guard",
    "wave_guard_demand", "wave_capacity", "validate_at", "commit_batch",
    "prepare_solve",
})
#: host-side methods where building jnp arrays is fine (they run BEFORE the
#: trace; arrays built here must then travel via aux()).
HOST_BUILD_METHODS = frozenset({
    "__init__", "prepare", "prepare_cluster", "configure_cluster",
})
#: attribute reads sanctioned inside tensor methods
SANCTIONED_ATTRS = frozenset({"_aux", "_presolve"})

#: jnp array constructors
ARRAY_CTORS = frozenset({
    "asarray", "array", "zeros", "ones", "full", "arange", "stack",
    "concatenate", "eye", "linspace",
})

#: snapshot fields that are int64 by construction (state/snapshot.py lowers
#: quantities as int64 in reference units)
INT64_ATTRS = frozenset({"req", "alloc", "requested", "nom_req"})

#: names that denote a (R,)-shaped resource vector — a literal-int subscript
#: on these is a hardcoded resource slot
RESOURCE_VECTOR_NAMES = re.compile(r"^(weights|w_res|resource_weights)$")
#: names/attrs denoting (..., R)-shaped resource tensors — a literal int in
#: the LAST position of a multi-axis subscript is a hardcoded resource slot
RESOURCE_TENSOR_NAMES = re.compile(
    r"^(req|reqs|quota_req|alloc|allocatable|free|free0|requested|capacity"
    r"|demand|dem|usage|used|eq_used|q_min|q_max)$"
)
RESOURCE_TENSOR_ATTRS = frozenset({"req", "alloc", "requested", "nom_req"})

MAX_CANONICAL_SLOT = 3  # cpu, memory, ephemeral-storage, pods


class Finding:
    def __init__(self, path, node, rule, message):
        self.path = path
        self.line = getattr(node, "lineno", 0)
        self.col = getattr(node, "col_offset", 0)
        self.rule = rule
        self.message = message

    def __str__(self):
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


# ---------------------------------------------------------------------------
# dtype inference
# ---------------------------------------------------------------------------


def _dtype_from_dtype_expr(node):
    """jnp.int64 / np.float64 / "int64" -> lattice tag."""
    name = None
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value
    if name is None:
        return UNKNOWN
    if name in ("int64", "uint64"):
        return INT64
    if name in ("int32", "int16", "int8", "uint32", "uint16", "uint8"):
        return INT32
    if name.startswith("float") or name.startswith("bfloat"):
        return FLOAT
    if name.startswith("bool"):
        return BOOL
    return UNKNOWN


def _call_dtype(node, env):
    func = node.func
    if isinstance(func, ast.Attribute):
        if func.attr == "astype" and node.args:
            return _dtype_from_dtype_expr(node.args[0])
        if func.attr in ARRAY_CTORS:
            for kw in node.keywords:
                if kw.arg == "dtype":
                    return _dtype_from_dtype_expr(kw.value)
            # positional dtype: asarray/array(x, D), full(shape, v, D),
            # zeros/ones/arange(shape, D)
            pos = {"asarray": 1, "array": 1, "zeros": 1, "ones": 1,
                   "full": 2, "arange": None, "eye": None}.get(func.attr, None)
            if pos is not None and len(node.args) > pos:
                return _dtype_from_dtype_expr(node.args[pos])
            if func.attr in ("asarray", "array") and len(node.args) >= 1:
                return infer_dtype(node.args[0], env)
            return UNKNOWN
        if func.attr in ("cumsum", "cumprod", "where", "sum", "prod",
                         "maximum", "minimum", "clip"):
            for kw in node.keywords:
                if kw.arg == "dtype":
                    return _dtype_from_dtype_expr(kw.value)
            if func.attr == "where" and len(node.args) == 3:
                return _combine(
                    infer_dtype(node.args[1], env),
                    infer_dtype(node.args[2], env),
                )
            if node.args:
                return infer_dtype(node.args[0], env)
        if func.attr in ("transpose", "reshape", "ravel", "squeeze", "copy"):
            return infer_dtype(func.value, env)
    return UNKNOWN


def _combine(a, b):
    if a == b:
        return a
    if FLOAT in (a, b):
        # int64 + float -> float; but unknown + float stays unknown-float?
        # conservative: float wins only when both sides are known
        return FLOAT if UNKNOWN not in (a, b) else UNKNOWN
    if UNKNOWN in (a, b):
        return UNKNOWN
    if INT64 in (a, b):
        return INT64
    return UNKNOWN


def infer_dtype(node, env):
    """Conservative dtype lattice walk; UNKNOWN when not provable."""
    if isinstance(node, ast.Call):
        return _call_dtype(node, env)
    if isinstance(node, ast.Name):
        return env.get(node.id, UNKNOWN)
    if isinstance(node, ast.Attribute):
        if node.attr == "T":
            return infer_dtype(node.value, env)
        if node.attr in INT64_ATTRS:
            return INT64
        return UNKNOWN
    if isinstance(node, ast.Subscript):
        return infer_dtype(node.value, env)
    if isinstance(node, ast.UnaryOp):
        return infer_dtype(node.operand, env)
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.MatMult):
            return UNKNOWN
        return _combine(
            infer_dtype(node.left, env), infer_dtype(node.right, env)
        )
    if isinstance(node, ast.Constant):
        if isinstance(node.value, bool):
            return BOOL
        if isinstance(node.value, float):
            return FLOAT
        return UNKNOWN  # python ints adopt the other operand's dtype
    return UNKNOWN


def build_env(fn_node):
    """name -> dtype for single-dtype local assignments in one function."""
    seen: dict[str, set] = {}
    for node in _walk_scope(fn_node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name):
                seen.setdefault(t.id, set()).add(
                    infer_dtype(node.value, {})
                )
    env = {}
    for name, dts in seen.items():
        dts.discard(UNKNOWN)
        if len(dts) == 1:
            env[name] = next(iter(dts))
    # second pass so names defined from other names resolve one level deep
    for node in _walk_scope(fn_node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id not in env:
                dt = infer_dtype(node.value, env)
                if dt is not UNKNOWN:
                    env[t.id] = dt
    return env


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _is_jnp_call(node, names):
    """Call like jnp.X / np.X / lax.X / jax.lax.X with X in names."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    return isinstance(f, ast.Attribute) and f.attr in names


def _functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            yield node


def _walk_scope(fn):
    """Walk one function's nodes WITHOUT descending into nested
    function/lambda scopes: each nested scope is visited by its own
    `_functions` pass with its own env, so an enclosing `a = x.astype(
    jnp.int64)` cannot taint a nested function's shadowing parameter `a`
    (and findings inside nested scopes aren't reported twice)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def check_matmul(path, tree, findings):
    """GL003: int64 @ / dot / matmul / dot_general."""
    for fn in _functions(tree):
        env = build_env(fn)
        for node in _walk_scope(fn):
            operands = None
            if isinstance(node, ast.BinOp) and isinstance(node.op, ast.MatMult):
                operands = (node.left, node.right)
            elif _is_jnp_call(node, {"dot", "matmul", "dot_general", "vdot",
                                     "tensordot", "einsum"}):
                operands = tuple(node.args[:3])
            if operands is None:
                continue
            for op in operands:
                if infer_dtype(op, env) == INT64:
                    findings.append(Finding(
                        path, node, "GL003",
                        "int64 matmul/dot_general: unsupported on TPU — "
                        "cast to float64 (exact < 2^53) or float32",
                    ))
                    break


def check_cumsum(path, tree, findings):
    """GL002: jnp.cumsum on int64 with axis= (the 2-D form)."""
    for fn in _functions(tree):
        env = build_env(fn)
        for node in _walk_scope(fn):
            if not _is_jnp_call(node, {"cumsum"}):
                continue
            kw = {k.arg: k.value for k in node.keywords}
            # cumsum(a, axis, dtype): axis/dtype may be positional
            axis = kw.get("axis") or (node.args[1] if len(node.args) > 1 else None)
            dtype = kw.get("dtype") or (node.args[2] if len(node.args) > 2 else None)
            if isinstance(axis, ast.Constant) and axis.value is None:
                axis = None  # explicit axis=None flattens: the 1-D form
            if axis is None:
                continue  # 1-D cumsum: fine on TPU
            if dtype is not None:
                if _dtype_from_dtype_expr(dtype) != INT64:
                    continue
                dt = INT64
            else:
                dt = infer_dtype(node.args[0], env) if node.args else UNKNOWN
            if dt == INT64:
                findings.append(Finding(
                    path, node, "GL002",
                    "multi-axis int64 cumsum: lowers to vmem-hungry "
                    "reduce_window on TPU — use 1-D sorted-segment scans, "
                    "float64, or int32",
                ))


def check_block_until_ready(path, tree, findings):
    """GL004: block_until_ready in a wall-clock-reading function."""
    for fn in _functions(tree):
        if isinstance(fn, ast.Lambda):
            continue
        reads_clock = False
        for node in _walk_scope(fn):
            if isinstance(node, ast.Attribute) and node.attr in (
                "perf_counter", "monotonic", "time", "perf_counter_ns"
            ):
                base = node.value
                if isinstance(base, ast.Name) and base.id == "time":
                    reads_clock = True
        if not reads_clock:
            continue
        for node in _walk_scope(fn):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ) and node.func.attr == "block_until_ready":
                findings.append(Finding(
                    path, node, "GL004",
                    "block_until_ready() in a timing function: it can "
                    "return early through the axon tunnel — force "
                    "completion with a host transfer (np.asarray)",
                ))


def _plugin_classes(trees):
    """Transitive Plugin subclasses across all parsed files."""
    bases = {}
    for _, tree in trees:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                names = []
                for b in node.bases:
                    if isinstance(b, ast.Name):
                        names.append(b.id)
                    elif isinstance(b, ast.Attribute):
                        names.append(b.attr)
                bases[node.name] = names
    plugins = {"Plugin"}
    changed = True
    while changed:
        changed = False
        for cls, bs in bases.items():
            if cls not in plugins and any(b in plugins for b in bs):
                plugins.add(cls)
                changed = True
    return plugins


def check_aux_capture(path, tree, plugin_classes, findings):
    """GL001: tensor methods reading host-built jnp array attributes."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef) or node.name not in plugin_classes:
            continue
        captured = set()
        for meth in node.body:
            if not isinstance(meth, ast.FunctionDef):
                continue
            if meth.name not in HOST_BUILD_METHODS:
                continue
            for sub in ast.walk(meth):
                if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                    t = sub.targets[0]
                    if (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        # RHS builds or contains a jnp array?
                        for c in ast.walk(sub.value):
                            if _is_jnp_call(c, ARRAY_CTORS) and isinstance(
                                c.func.value, ast.Name
                            ) and c.func.value.id in ("jnp", "jax"):
                                captured.add(t.attr)
                                break
        if not captured:
            continue
        for meth in node.body:
            if not isinstance(meth, ast.FunctionDef):
                continue
            if meth.name not in TENSOR_METHODS:
                continue
            # `self.X is None` presence checks are trace-time CONFIG
            # branches, not value captures: flipping presence changes the
            # aux() pytree structure, which retraces — sanctioned idiom
            presence_checks = set()
            for sub in ast.walk(meth):
                if isinstance(sub, ast.Compare) and all(
                    isinstance(op, (ast.Is, ast.IsNot)) for op in sub.ops
                ):
                    for side in (sub.left, *sub.comparators):
                        presence_checks.add(id(side))
            for sub in ast.walk(meth):
                if (
                    isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"
                    and sub.attr in captured
                    and sub.attr not in SANCTIONED_ATTRS
                    and id(sub) not in presence_checks
                    and not isinstance(getattr(sub, "ctx", None), ast.Store)
                ):
                    findings.append(Finding(
                        path, sub, "GL001",
                        f"jitted {meth.name}() reads host-built array "
                        f"self.{sub.attr}: a jit closure capture is "
                        "constant-folded per shape and goes stale — route "
                        "it through aux()/bind_aux (read self._aux)",
                    ))


def check_resource_slots(path, tree, findings):
    """GL005: hardcoded resource-axis slot integers."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Subscript):
            continue
        base = node.value
        # unwrap .at[...] indexing
        if isinstance(base, ast.Attribute) and base.attr == "at":
            base = base.value
        idx = node.slice
        is_vector = (
            isinstance(base, ast.Name)
            and RESOURCE_VECTOR_NAMES.match(base.id) is not None
        )
        resourceish = is_vector or (
            isinstance(base, ast.Name)
            and RESOURCE_TENSOR_NAMES.match(base.id) is not None
        ) or (
            isinstance(base, ast.Attribute)
            and base.attr in RESOURCE_TENSOR_ATTRS
        )
        if not resourceish:
            continue
        slot = None
        if is_vector and isinstance(idx, ast.Constant) and isinstance(
            idx.value, int
        ) and not isinstance(idx.value, bool):
            slot = idx.value
        elif isinstance(idx, ast.Tuple) and idx.elts:
            last = idx.elts[-1]
            leading_sliced = any(
                isinstance(e, ast.Slice)
                or (isinstance(e, ast.Constant) and e.value is Ellipsis)
                for e in idx.elts[:-1]
            )
            if leading_sliced and isinstance(last, ast.Constant) and isinstance(
                last.value, int
            ) and not isinstance(last.value, bool):
                slot = last.value
        if slot is not None and 0 <= slot <= MAX_CANONICAL_SLOT:
            findings.append(Finding(
                path, node, "GL005",
                f"hardcoded resource slot [{slot}]: the axis order is "
                "owned by api.resources.CANONICAL (and mirrored by the "
                "C++ bridge) — use CANONICAL.index(...) / "
                "meta.index.position(...)",
            ))


def check_config_update(path, tree, findings):
    """GL007: `jax.config.update(...)` (or `config.update` from
    `from jax import config`) outside the sanctioned owner files. The
    bare-name form only fires when the module actually binds `config`
    FROM jax — a local dict named `config` being .update()d is not a
    finding."""
    jax_config_imported = any(
        isinstance(node, ast.ImportFrom)
        and node.module == "jax"
        and any((alias.asname or alias.name) == "config"
                and alias.name == "config" for alias in node.names)
        for node in ast.walk(tree)
    )
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and f.attr == "update"):
            continue
        base = f.value
        is_jax_config = (
            isinstance(base, ast.Attribute)
            and base.attr == "config"
            and isinstance(base.value, ast.Name)
            and base.value.id == "jax"
        ) or (
            isinstance(base, ast.Name)
            and base.id == "config"
            and jax_config_imported
        )
        if not is_jax_config:
            continue
        findings.append(Finding(
            path, node, "GL007",
            "jax.config.update in library code: platform/precision config "
            "is owned by the entrypoints and tests/conftest.py "
            "(config-update-owners in pyproject [tool.graft-lint]) — a "
            "library-level update fights their platform pinning",
        ))


#: wall-clock reads that are meaningless (trace-time constants) inside a
#: jit-traced function
WALL_CLOCK_ATTRS = frozenset({
    "perf_counter", "perf_counter_ns", "time", "time_ns", "monotonic",
    "monotonic_ns", "process_time", "process_time_ns",
})

#: callables whose function argument gets jit-traced
JIT_WRAPPERS = frozenset({"jit", "donated_chunk_solver", "checkified"})


def check_thread_names(path, tree, findings):
    """GL012: `threading.Thread(...)` without explicit `name=` and
    `daemon=`. The bare-name `Thread(...)` form fires only when the
    module binds `Thread` from threading — another class that happens
    to be called Thread is not a finding."""
    thread_imported = any(
        isinstance(node, ast.ImportFrom)
        and node.module == "threading"
        and any((alias.asname or alias.name) == "Thread"
                and alias.name == "Thread" for alias in node.names)
        for node in ast.walk(tree)
    )
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        is_thread = (
            isinstance(f, ast.Attribute)
            and f.attr == "Thread"
            and isinstance(f.value, ast.Name)
            and f.value.id == "threading"
        ) or (
            isinstance(f, ast.Name) and f.id == "Thread" and thread_imported
        )
        if not is_thread:
            continue
        kwargs = {k.arg for k in node.keywords if k.arg}
        missing = [k for k in ("name", "daemon") if k not in kwargs]
        if not missing:
            continue
        findings.append(Finding(
            path, node, "GL012",
            f"threading.Thread without explicit {' and '.join(missing)}: "
            "the concurrency auditor (tools/race_audit.py) and the "
            "/healthz thread census key entry points by thread name — "
            "anonymous threads are unauditable, and implicit daemon is a "
            "shutdown hazard",
        ))


def _callee_name(func):
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _jitted_function_nodes(tree):
    """Function/lambda nodes in `tree` that get jit-traced: decorated with
    jit (bare, `jax.jit`, or `partial(jax.jit, ...)`), or passed (by name
    or inline lambda) as the first argument of `jax.jit` /
    `donated_chunk_solver` / `checkified`. Name references resolve to
    every same-named def in the file — conservative in the right
    direction for a lint that flags wall clocks."""
    defs_by_name: dict[str, list] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, []).append(node)
    jitted = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = _callee_name(target)
                if name == "jit":
                    jitted.append(node)
                elif name == "partial" and isinstance(dec, ast.Call) and any(
                    _callee_name(a) == "jit"
                    for a in dec.args
                    if isinstance(a, (ast.Name, ast.Attribute))
                ):
                    jitted.append(node)
        elif isinstance(node, ast.Call):
            if _callee_name(node.func) not in JIT_WRAPPERS or not node.args:
                continue
            fn_arg = node.args[0]
            if isinstance(fn_arg, ast.Lambda):
                jitted.append(fn_arg)
            elif isinstance(fn_arg, ast.Name):
                jitted.extend(defs_by_name.get(fn_arg.id, ()))
    return jitted


def check_jit_walltime(path, tree, plugin_classes, findings):
    """GL008: wall-clock reads inside jit-traced functions (including
    Plugin tensor methods and functions nested inside a traced scope)."""
    traced = list(_jitted_function_nodes(tree))
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name in plugin_classes:
            traced.extend(
                meth for meth in node.body
                if isinstance(meth, ast.FunctionDef)
                and meth.name in TENSOR_METHODS
            )
    seen = set()
    for fn in traced:
        # descend into NESTED defs too: code defined inside a traced
        # function traces with it
        for sub in ast.walk(fn):
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in WALL_CLOCK_ATTRS
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id == "time"):
                continue
            key = (sub.lineno, sub.col_offset)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(
                path, sub, "GL008",
                f"time.{sub.func.attr}() inside a jit-traced function: "
                "trace-time Python runs once per compile, so this is a "
                "baked constant, not a measurement — time device work by "
                "bracketing host-sync transfers (np.asarray) outside the "
                "jit (GL004's rule; see utils/observability.py)",
            ))


def _donate_positions(node):
    """Literal int positions from a donate_argnums/carry_argnum value."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = {
            e.value
            for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, int)
        }
        return vals or None
    return None


def _donating_jits(tree):
    """name -> donated arg positions, from `x = jax.jit(f, donate_argnums=
    ...)` and `x = donated_chunk_solver(f, carry_argnum=k)` assignments
    (module- or function-level). Only literal positions are tracked."""
    out = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        if not isinstance(t, ast.Name) or not isinstance(node.value, ast.Call):
            continue
        call = node.value
        fname = (
            call.func.attr if isinstance(call.func, ast.Attribute)
            else getattr(call.func, "id", None)
        )
        pos = None
        if fname == "jit":
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    pos = _donate_positions(kw.value)
        elif fname == "donated_chunk_solver":
            for kw in call.keywords:
                if kw.arg == "carry_argnum":
                    pos = _donate_positions(kw.value)
            if pos is None and len(call.args) > 1:
                pos = _donate_positions(call.args[1])
        if pos:
            out[t.id] = pos
    return out


def _sweep_unit(unit, extra_stores, donating, poisoned, report):
    """One statement unit: check loads against the poisoned set FIRST
    (passing an already-donated buffer anywhere is a read), then the
    unit's donating calls poison their donated Name operands, then the
    unit's assignment targets revive — so the chunk-carry idiom
    `a, free = solve(..., free)` is clean."""
    loads, stores, calls = [], list(extra_stores or ()), []
    for node in ast.walk(unit):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                loads.append(node)
            elif isinstance(node.ctx, ast.Store):
                stores.append(node.id)
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Name
        ) and node.func.id in donating:
            calls.append(node)
    for name_node in loads:
        if name_node.id in poisoned:
            report(name_node, poisoned[name_node.id])
    for call in calls:
        for k in donating[call.func.id]:
            if k < len(call.args) and isinstance(call.args[k], ast.Name):
                poisoned[call.args[k].id] = call.func.id
    for name in stores:
        poisoned.pop(name, None)


def _sweep_body(body, donating, poisoned, report):
    """Sweep a statement list in source order, mutating `poisoned`.
    Loop bodies are swept TWICE — the second pass carries the poison from
    the end of the first, so a carry donated in iteration k and read (not
    rebound) at the top of iteration k+1 is caught. If/try branches sweep
    on copies and union their surviving poison (either branch may have
    run); nested function/class definitions are their own scope."""
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            targets = [
                n.id for n in ast.walk(stmt.target)
                if isinstance(n, ast.Name)
            ]
            _sweep_unit(stmt.iter, targets, donating, poisoned, report)
            for _ in range(2):  # second pass: loop back-edge
                # the loop TARGET rebinds at the top of every iteration —
                # revive it before each pass, or a donated per-iteration
                # input (`for x in xs: step(a, x)`) false-positives on the
                # back-edge sweep
                for name in targets:
                    poisoned.pop(name, None)
                _sweep_body(stmt.body, donating, poisoned, report)
            _sweep_body(stmt.orelse, donating, poisoned, report)
        elif isinstance(stmt, ast.While):
            _sweep_unit(stmt.test, [], donating, poisoned, report)
            for _ in range(2):
                _sweep_body(stmt.body, donating, poisoned, report)
            _sweep_body(stmt.orelse, donating, poisoned, report)
        elif isinstance(stmt, ast.If):
            _sweep_unit(stmt.test, [], donating, poisoned, report)
            then_p, else_p = dict(poisoned), dict(poisoned)
            _sweep_body(stmt.body, donating, then_p, report)
            _sweep_body(stmt.orelse, donating, else_p, report)
            poisoned.clear()
            poisoned.update(then_p)
            poisoned.update(else_p)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                names = []
                if item.optional_vars is not None:
                    names = [
                        n.id for n in ast.walk(item.optional_vars)
                        if isinstance(n, ast.Name)
                    ]
                _sweep_unit(item.context_expr, names, donating, poisoned,
                            report)
            _sweep_body(stmt.body, donating, poisoned, report)
        elif isinstance(stmt, ast.Try):
            _sweep_body(stmt.body, donating, poisoned, report)
            for handler in stmt.handlers:
                _sweep_body(handler.body, donating, poisoned, report)
            _sweep_body(stmt.orelse, donating, poisoned, report)
            _sweep_body(stmt.finalbody, donating, poisoned, report)
        else:
            _sweep_unit(stmt, None, donating, poisoned, report)


def check_donated_reuse(path, tree, findings):
    """GL006: a Name read after being passed in a donated position of a
    jitted call, without an intervening rebind — including across loop
    iterations (the chunk-loop bug class: `for ...: a = solve(raw, free)`
    without rebinding `free`). Findings are deduplicated per site so the
    loop double-sweep reports each read once."""
    donating = _donating_jits(tree)
    if not donating:
        return
    for fn in _functions(tree):
        if isinstance(fn, ast.Lambda):
            continue
        seen = set()

        def report(name_node, callee):
            key = (name_node.lineno, name_node.col_offset, name_node.id)
            if key in seen:
                return
            seen.add(key)
            findings.append(Finding(
                path, name_node, "GL006",
                f"read of {name_node.id!r} after it was donated to "
                f"{callee!r}(): the donated buffer may have been reused "
                "for outputs — rebind it from the call's results first",
            ))

        _sweep_body(fn.body, donating, {}, report)


#: the node shard axis name (mirrors parallel.mesh.NODES_AXIS — the lint is
#: stdlib-only and cannot import jax-adjacent modules)
_NODE_AXIS_LITERAL = "nodes"
_NODE_AXIS_NAMES = frozenset({"NODES_AXIS"})


def _is_node_axis_expr(node) -> bool:
    """Does this AST expression denote the node shard axis? Literal
    "nodes", the NODES_AXIS constant (bare or attribute), or a tuple/list
    containing one of those (multi-axis gathers over the node axis are
    just as much a full-axis gather)."""
    if isinstance(node, ast.Constant):
        return node.value == _NODE_AXIS_LITERAL
    if isinstance(node, ast.Name):
        return node.id in _NODE_AXIS_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr in _NODE_AXIS_NAMES
    if isinstance(node, (ast.Tuple, ast.List)):
        return any(_is_node_axis_expr(e) for e in node.elts)
    return False


def check_node_axis_all_gather(path, tree, findings):
    """GL009: `all_gather`/`all_gather_invariant` over the node shard
    axis. The axis is read from the second positional argument or the
    `axis_name` keyword; gathers over other axes (pod-axis prefix
    exchanges, side-table sweeps) are not findings."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _callee_name(node.func)
        if name not in ("all_gather", "all_gather_invariant"):
            continue
        axis = node.args[1] if len(node.args) >= 2 else None
        for kw in node.keywords:
            if kw.arg == "axis_name":
                axis = kw.value
        if axis is None or not _is_node_axis_expr(axis):
            continue
        findings.append(Finding(
            path, node, "GL009",
            f"{name} over the node shard axis reassembles the full node "
            "tensor on every shard — the ring election degrades back to a "
            "full gather. Reduce per-shard champions instead "
            "(ops.assign.block_exclusive_offsets / ring_exclusive_scan, "
            "lax.pmin/psum key reductions)",
        ))


#: host-callback callables that can never appear inside a Pallas kernel
#: body (the kernel is staged by Mosaic; there is no host to call back to)
_HOST_CALLBACK_NAMES = frozenset({
    "io_callback", "pure_callback", "debug_callback", "callback",
})


def _pallas_kernel_fns(tree):
    """(FunctionDef, n_bound, kw_bound) triples for defs whose NAME is
    passed as the first argument of a `pallas_call(...)` call — directly
    or through `functools.partial(name, ...)`. `n_bound`/`kw_bound` are
    the leading positional count and keyword names `partial` statically
    binds (minimum / intersection across references when a name is used
    more than once): those parameters hold compile-time Python config,
    not traced refs, so GL011's branch check must not fire on them. Name
    resolution is module-wide and conservative: every def sharing a
    referenced name is treated as a kernel body (nested `def kernel(...)`
    closures are the repo idiom, `parallel/kernels.py`)."""
    refs: dict = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and _callee_name(node.func) == "pallas_call"
                and node.args):
            continue
        first = node.args[0]
        n_bound, kw_bound = 0, frozenset()
        if isinstance(first, ast.Call) and _callee_name(
            first.func
        ) == "partial" and first.args:
            n_bound = len(first.args) - 1
            kw_bound = frozenset(
                kw.arg for kw in first.keywords if kw.arg
            )
            first = first.args[0]
        if isinstance(first, ast.Name):
            prev = refs.get(first.id)
            refs[first.id] = (
                (n_bound, kw_bound) if prev is None
                else (min(prev[0], n_bound), prev[1] & kw_bound)
            )
    if not refs:
        return []
    return [
        (fn,) + refs[fn.name] for fn in ast.walk(tree)
        if isinstance(fn, ast.FunctionDef) and fn.name in refs
    ]


def check_pallas_kernel_purity(path, tree, findings):
    """GL011: host callbacks, wall-clock reads, and Python branching on
    traced ref parameters inside `pallas_call` kernel bodies."""
    for fn, n_bound, kw_bound in _pallas_kernel_fns(tree):
        positional = [
            a.arg for a in fn.args.posonlyargs + fn.args.args
        ]
        # partial-bound leading positionals / keywords are static Python
        # config (the sanctioned "branch on static closure config" shape)
        params = set(positional[n_bound:]) - kw_bound
        params.update(
            a.arg for a in fn.args.kwonlyargs if a.arg not in kw_bound
        )
        if fn.args.vararg:
            params.add(fn.args.vararg.arg)

        def reads_param(expr):
            return any(
                isinstance(n, ast.Name) and n.id in params
                for n in ast.walk(expr)
            )

        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call):
                name = _callee_name(sub.func)
                if name in _HOST_CALLBACK_NAMES:
                    findings.append(Finding(
                        path, sub, "GL011",
                        f"host callback {name}() inside a pallas_call "
                        "kernel body: the kernel is staged by Mosaic — "
                        "there is no host to call back to; move the "
                        "callback outside the kernel",
                    ))
                elif (isinstance(sub.func, ast.Attribute)
                        and sub.func.attr in WALL_CLOCK_ATTRS
                        and isinstance(sub.func.value, ast.Name)
                        and sub.func.value.id == "time"):
                    findings.append(Finding(
                        path, sub, "GL011",
                        f"time.{sub.func.attr}() inside a pallas_call "
                        "kernel body: the body is staged once, so this is "
                        "a baked constant (GL008 one level deeper) — time "
                        "kernels by bracketing host-sync transfers "
                        "outside the program",
                    ))
            elif isinstance(sub, (ast.If, ast.While)) and reads_param(
                sub.test
            ):
                findings.append(Finding(
                    path, sub, "GL011",
                    "Python branching on a kernel ref/traced parameter "
                    "inside a pallas_call body: the branch is resolved at "
                    "staging time (wrong or untraceable) — branch on "
                    "static closure config, or mask with jnp.where / "
                    "pl.when",
                ))


def _is_float64_expr(node) -> bool:
    """jnp.float64 / np.float64 / "float64" — float64 SPECIFICALLY (the
    exactness contract is about the 2^53 mantissa line; float32 casts of
    int64 are a different, visibly lossy decision)."""
    name = None
    if isinstance(node, ast.Attribute):
        name = node.attr
    elif isinstance(node, ast.Name):
        name = node.id
    elif isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value
    return name == "float64"


def check_exact_f64_cast(path, tree, findings):
    """GL013: int64 -> float64 casts of quantity tensors outside the
    audited exactness owners. Fires on `X.astype(jnp.float64)` and on
    array constructors with an explicit float64 dtype whose operand is
    provably int64 (the same conservative dtype lattice as GL002/GL003:
    unknown dtypes never fire)."""
    scopes = [tree]
    scopes.extend(_functions(tree))
    for fn in scopes:
        env = build_env(fn)
        for node in _walk_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            operand = None
            if isinstance(f, ast.Attribute) and f.attr == "astype" \
                    and node.args and _is_float64_expr(node.args[0]):
                operand = f.value
            elif isinstance(f, ast.Attribute) and f.attr in ARRAY_CTORS:
                dtype = None
                for kw in node.keywords:
                    if kw.arg == "dtype":
                        dtype = kw.value
                if dtype is not None and _is_float64_expr(dtype) \
                        and node.args:
                    operand = node.args[0]
            if operand is None:
                continue
            if infer_dtype(operand, env) != INT64:
                continue
            findings.append(Finding(
                path, node, "GL013",
                "float64 cast of an int64 quantity outside the audited "
                "exactness owners: exact only below 2^53, and this call "
                "site is outside tools/kernel_audit.py's proven trace "
                "scope — use utils.intmath.exact_f64 (asserted-bound "
                "cast) / parallel.kernels.join_limbs, or add the module "
                "to exact-cast-owners in pyproject [tool.graft-lint] to "
                "bring it under the audit",
            ))


def check_swallowed_exception(path, tree, findings):
    """GL010: a broad exception handler (bare ``except:``, ``except
    Exception``, ``except BaseException``) whose body is only
    ``pass``/``...``. Around solve/ingest sites this is how a backend
    fault, a poisoned delta batch, or a checkpoint failure disappears
    without a trace — fault paths must RECORD (log/metric) and RE-ROUTE
    (retry, failover, park, re-base; `resilience.watchdog` is the
    pattern), never swallow. Narrow handlers for specific exceptions are
    fine; genuinely-sanctioned best-effort paths (GC finalizers,
    shutdown cleanup, optional-dependency probes) carry an inline
    ``# graft-lint: ignore[GL010]`` with their reason."""

    def is_broad(t) -> bool:
        if t is None:
            return True  # bare except
        if isinstance(t, ast.Name):
            return t.id in ("Exception", "BaseException")
        if isinstance(t, ast.Tuple):
            return any(is_broad(e) for e in t.elts)
        return False

    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if not is_broad(node.type):
            continue
        body_swallows = all(
            isinstance(stmt, ast.Pass)
            or (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant))
            for stmt in node.body
        )
        if not body_swallows:
            continue
        findings.append(Finding(
            path, node, "GL010",
            "broad exception handler swallows the fault (body is only "
            "pass) — record + re-route instead: log/count it and retry, "
            "fail over, park, or re-base (resilience.watchdog is the "
            "pattern); a sanctioned best-effort path needs an inline "
            "ignore with its reason",
        ))


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

_IGNORE_RE = re.compile(r"#\s*graft-lint:\s*ignore(?:\[([A-Z0-9, ]+)\])?")


def _suppressed(finding, source_lines):
    if 0 < finding.line <= len(source_lines):
        m = _IGNORE_RE.search(source_lines[finding.line - 1])
        if m:
            rules = m.group(1)
            return rules is None or finding.rule in re.split(r"[,\s]+", rules)
    return False


def lint_file(
    path: Path,
    config_owner: bool = False,
    exact_cast_owner: bool = False,
) -> tuple[list, object, str]:
    """(findings, ast tree, source) for one file — the tree/source feed the
    cross-file plugin-hierarchy pass and suppression filter in lint_paths.
    `config_owner` marks a sanctioned GL007 owner file (platform/precision
    config allowed); `exact_cast_owner` marks a GL013 exactness-owner file
    (its int64 -> float64 casts are walked by the kernel auditor's jaxpr
    lattice, so the source-level rule stands down). Direct callers default
    to NOT owned."""
    source = path.read_text()
    tree = ast.parse(source, filename=str(path))
    findings: list[Finding] = []
    rel = path
    check_matmul(rel, tree, findings)
    check_cumsum(rel, tree, findings)
    check_block_until_ready(rel, tree, findings)
    check_resource_slots(rel, tree, findings)
    check_donated_reuse(rel, tree, findings)
    check_node_axis_all_gather(rel, tree, findings)
    check_swallowed_exception(rel, tree, findings)
    check_pallas_kernel_purity(rel, tree, findings)
    check_thread_names(rel, tree, findings)
    if not config_owner:
        check_config_update(rel, tree, findings)
    if not exact_cast_owner:
        check_exact_f64_cast(rel, tree, findings)
    return findings, tree, source


def lint_paths(paths) -> list[Finding]:
    cfg = load_config()
    exclude = tuple(cfg.get("exclude", ()))
    owners = tuple(cfg.get("config-update-owners", ()))
    cast_owners = tuple(cfg.get("exact-cast-owners", ()))

    def excluded(f):
        rel = _rel_to_repo(f)
        return rel is not None and any(rel.startswith(e) for e in exclude)

    def owned(f):
        rel = _rel_to_repo(f)
        return rel is not None and any(rel.startswith(o) for o in owners)

    def cast_owned(f):
        rel = _rel_to_repo(f)
        return rel is not None and any(rel.startswith(o) for o in cast_owners)

    files = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            # config exclusions apply when EXPANDING directories only —
            # a file named explicitly is always linted (the fixture tests
            # point the linter straight at the known-bad corpus)
            files.extend(f for f in sorted(p.rglob("*.py")) if not excluded(f))
        else:
            files.append(p)
    all_findings, trees, sources = [], [], {}
    for f in files:
        findings, tree, source = lint_file(
            f, config_owner=owned(f), exact_cast_owner=cast_owned(f))
        all_findings.extend(findings)
        trees.append((f, tree))
        sources[f] = source.splitlines()
    plugin_classes = _plugin_classes(trees)
    for f, tree in trees:
        extra: list[Finding] = []
        check_aux_capture(f, tree, plugin_classes, extra)
        check_jit_walltime(f, tree, plugin_classes, extra)
        all_findings.extend(extra)
    return [
        fi for fi in all_findings
        if not _suppressed(fi, sources.get(fi.path, []))
    ]


def main(argv=None):
    args = argv if argv is not None else sys.argv[1:]
    paths = args or [str(REPO / p) for p in DEFAULT_PATHS]
    findings = lint_paths(paths)
    for f in sorted(findings, key=lambda f: (str(f.path), f.line)):
        print(f)
    if findings:
        print(f"graft-lint: {len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("graft-lint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
