"""Standalone demo control plane for the compose/packaging recipe.

A stdlib HTTP server speaking just enough kube-apiserver: LIST endpoints
for nodes and pending pods (synthetic demo workload), WATCH endpoints that
hold the stream open with periodic BOOKMARKs, and the pod `binding`
subresource POST — which it logs and records, flipping the pod to bound so
a relist converges. The deploy/docker-compose.yaml demo points the
scheduler daemon at this process; `docker compose logs demo-apiserver`
then shows every binding the scheduler made.

Usage: python tools/demo_apiserver.py [--port 8001] [--nodes 8] [--pods 24]
"""

from __future__ import annotations

import argparse
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def make_state(n_nodes: int, n_pods: int):
    nodes = [{
        "kind": "Node", "apiVersion": "v1",
        "metadata": {"name": f"demo-node-{i}", "uid": f"node-{i}",
                     "resourceVersion": str(10 + i),
                     "labels": {"topology.kubernetes.io/zone": f"z{i % 2}"}},
        "status": {"allocatable": {"cpu": "8", "memory": "32Gi",
                                   "pods": "110"}},
    } for i in range(n_nodes)]
    pods = [{
        "kind": "Pod", "apiVersion": "v1",
        "metadata": {"name": f"demo-pod-{j}", "namespace": "default",
                     "uid": f"pod-{j}",
                     "resourceVersion": str(100 + j)},
        "spec": {"containers": [{"name": "c", "resources": {"requests": {
            "cpu": f"{250 + 50 * (j % 4)}m", "memory": "512Mi"}}}]},
        "status": {"phase": "Pending"},
    } for j in range(n_pods)]
    return nodes, pods


class DemoApiServer:
    def __init__(self, host: str, port: int, n_nodes: int, n_pods: int):
        self.lock = threading.Lock()
        self.nodes, self.pods = make_state(n_nodes, n_pods)
        self.bindings: dict[str, str] = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.0"

            def log_message(self, *args):
                pass

            def _json(self, payload, code=200):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                from urllib.parse import parse_qs, urlparse

                parsed = urlparse(self.path)
                watching = parse_qs(parsed.query).get(
                    "watch", ["0"])[0] in ("1", "true")
                if watching:
                    return self._watch()
                with outer.lock:
                    if parsed.path == "/api/v1/nodes":
                        items = list(outer.nodes)
                        kind = "NodeList"
                    elif parsed.path == "/api/v1/pods":
                        items = [p for p in outer.pods
                                 if p["metadata"]["uid"]
                                 not in outer.bindings]
                        kind = "PodList"
                    else:
                        return self._json({"kind": "Status", "code": 404},
                                          code=404)
                self._json({"kind": kind, "apiVersion": "v1",
                            "metadata": {"resourceVersion": "1000"},
                            "items": items})

            def _watch(self):
                # hold the stream open with periodic bookmarks; the
                # reflector resumes from them after any disconnect
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                try:
                    for k in range(3600):
                        time.sleep(10)
                        self.wfile.write((json.dumps({
                            "type": "BOOKMARK",
                            "object": {"kind": "Pod", "metadata": {
                                "resourceVersion": str(2000 + k)}},
                        }) + "\n").encode())
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if self.path.endswith("/binding"):
                    name = self.path.rsplit("/pods/", 1)[1].split("/")[0]
                    node = body.get("target", {}).get("name", "?")
                    with outer.lock:
                        for p in outer.pods:
                            if p["metadata"]["name"] == name:
                                outer.bindings[p["metadata"]["uid"]] = node
                    print(f"BOUND {name} -> {node}", flush=True)
                    self.send_response(201)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(404)
                self.end_headers()

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.address = self._httpd.server_address

    def serve_forever(self):
        print(f"demo apiserver on http://{self.address[0]}:{self.address[1]} "
              f"({len(self.nodes)} nodes, {len(self.pods)} pending pods)",
              flush=True)
        self._httpd.serve_forever()

    def start_background(self):
        t = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="demo-apiserver",
        )
        t.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8001)
    ap.add_argument("--nodes", type=int, default=8)
    ap.add_argument("--pods", type=int, default=24)
    args = ap.parse_args(argv)
    DemoApiServer(args.host, args.port, args.nodes, args.pods).serve_forever()


if __name__ == "__main__":
    main()
