"""Ledger smoke gate: overhead bound, exact decomposition, engine identity.

One JSON line, rc 1 on failure.  Three properties of the pod-lifecycle
ledger (scheduler_plugins_tpu/obs/ledger.py) are checked end-to-end:

1. **Overhead** — ledger-on vs ledger-off cycles are timed as
   interleaved pairs (the tools/replay.py smoke discipline: drift hits
   both arms of a pair equally, so the statistic is the MEDIAN OF
   PAIRED deltas, and the bound is max(2%, the off series' own p10-p90
   spread) — overhead below the run's jitter is not attributable to
   the ledger).

2. **Decomposition** — for every pod the ledger retires, the recorded
   stage times must sum exactly to the pod's end-to-end latency
   (telescoping integer-ns accounting makes this an identity, and this
   gate keeps it one).

3. **Engine identity** — the same churn scenario driven through serial
   ``run_cycle`` and through ``PipelinedCycle`` must produce
   event-SEQUENCE-identical ledgers: same (cycle, lane, seq, uid,
   kind, detail) tuples in the same order.  Stamps may differ; order
   and attribution may not.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE_SHAPE = dict(n_gangs=4, gang_size=8, n_nodes=64)
SMOKE_RUNS = 17
BOUND_PCT = 2.0


def _overhead() -> dict:
    import bench
    from scheduler_plugins_tpu.framework import Profile, Scheduler, run_cycle
    from scheduler_plugins_tpu.obs import ledger as podledger

    _, plugins, _ = bench.config_problem(4, shape=SMOKE_SHAPE)
    scheduler = Scheduler(Profile(plugins=plugins))

    def one_cycle():
        cluster, _p, _ = bench.config_problem(4, shape=SMOKE_SHAPE)
        start = time.perf_counter()
        run_cycle(scheduler, cluster, now=1000)
        return time.perf_counter() - start

    one_cycle()  # compile warmup: later cycles hit the jit cache
    # ledger-path warmup: first enabled cycle pays one-time lazy costs
    prev = podledger.use(podledger.Ledger().start())
    one_cycle()
    podledger.use(prev)

    off, on, pair_pct = [], [], []
    decomposition_errors = 0
    retired = 0
    for _ in range(SMOKE_RUNS):
        t_off = one_cycle()
        off.append(t_off)
        led = podledger.Ledger()
        prev = podledger.use(led.start())
        try:
            t_on = one_cycle()
        finally:
            podledger.use(prev)
        on.append(t_on)
        pair_pct.append(100.0 * (t_on - t_off) / t_off)
        decomposition_errors += len(led.decomposition_errors())
        retired += led.pods_bound

    median_off = sorted(off)[len(off) // 2]
    median_on = sorted(on)[len(on) // 2]
    overhead_pct = sorted(pair_pct)[len(pair_pct) // 2]
    off_sorted = sorted(off)
    spread_pct = 100.0 * (
        off_sorted[int(0.9 * (len(off) - 1))]
        - off_sorted[int(0.1 * (len(off) - 1))]
    ) / median_off
    bound = max(float(os.environ.get("SPT_LEDGER_BOUND_PCT", BOUND_PCT)),
                spread_pct)
    return {
        "off_cycle_ms": round(median_off * 1000, 2),
        "on_cycle_ms": round(median_on * 1000, 2),
        "overhead_pct": round(overhead_pct, 2),
        "bound_pct": round(bound, 2),
        "noise_floor_pct": round(spread_pct, 2),
        "pods_bound": retired,
        "decomposition_errors": decomposition_errors,
        "overhead_ok": overhead_pct <= bound,
        "decomposition_ok": decomposition_errors == 0 and retired > 0,
    }


def _churn_scenario(drive) -> "Ledger":
    """Run the shared churn scenario under a fresh ledger via ``drive``,
    a callable (cluster, scheduler, now, add_pods) -> None per cycle."""
    from scheduler_plugins_tpu.api.objects import Container, Node, Pod
    from scheduler_plugins_tpu.api.resources import CPU, MEMORY, PODS
    from scheduler_plugins_tpu.framework import Profile, Scheduler
    from scheduler_plugins_tpu.obs import ledger as podledger
    from scheduler_plugins_tpu.plugins import NodeResourcesAllocatable
    from scheduler_plugins_tpu.state.cluster import Cluster

    gib = 1 << 30
    c = Cluster()
    for i in range(4):
        c.add_node(Node(name=f"n{i}",
                        allocatable={CPU: 16_000, MEMORY: 64 * gib,
                                     PODS: 110}))

    def pod(name, cpu=500, created=0):
        return Pod(name=name, creation_ms=created,
                   containers=[Container(requests={CPU: cpu, MEMORY: gib})])

    sched = Scheduler(Profile(plugins=[NodeResourcesAllocatable()]))
    led = podledger.Ledger()
    prev = podledger.use(led.start())
    try:
        waves = [
            [pod(f"a{i}", created=10 + i) for i in range(3)],
            [pod("big", cpu=50_000, created=20)],  # never fits: blamed
            [pod(f"b{i}", created=30 + i) for i in range(2)],
            [],
        ]
        now = 1000
        for wave in waves:
            drive(c, sched, now, wave)
            now += 1000
    finally:
        podledger.use(prev)
    return led


def _identity() -> dict:
    from scheduler_plugins_tpu.framework import PipelinedCycle, run_cycle

    def serial_drive(c, sched, now, wave):
        for p in wave:
            c.add_pod(p)
        run_cycle(sched, c, now=now)

    pipes: dict = {}

    def pipe_drive(c, sched, now, wave):
        pipe = pipes.setdefault(id(c), PipelinedCycle(sched, c))
        for p in wave:
            c.add_pod(p)
        pipe.tick(now=now)
        pipe.flush()

    serial_led = _churn_scenario(serial_drive)
    pipe_led = _churn_scenario(pipe_drive)
    for pipe in pipes.values():
        pipe.close()

    s_seq, p_seq = serial_led.sequence(), pipe_led.sequence()
    first_diff = None
    for i, (a, b) in enumerate(zip(s_seq, p_seq)):
        if a != b:
            first_diff = {"index": i, "serial": a, "pipelined": b}
            break
    return {
        "serial_events": len(s_seq),
        "pipelined_events": len(p_seq),
        "sequence_identical": s_seq == p_seq,
        "first_divergence": first_diff,
        "serial_decomposition_errors": len(serial_led.decomposition_errors()),
        "pipelined_decomposition_errors": len(pipe_led.decomposition_errors()),
    }


def main() -> int:
    import bench

    bench.apply_platform_override()
    overhead = _overhead()
    ident = _identity()
    ok = (
        overhead["overhead_ok"]
        and overhead["decomposition_ok"]
        and ident["sequence_identical"]
        and ident["serial_decomposition_errors"] == 0
        and ident["pipelined_decomposition_errors"] == 0
    )
    print(json.dumps({
        "metric": "ledger_smoke",
        **overhead,
        **ident,
        "ok": ok,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
