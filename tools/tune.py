#!/usr/bin/env python
"""Counterfactual weight tuner over flight-recorder corpora, and the
`make tune-smoke` gate.

`tune BUNDLE` replays every recorded cycle of a bundle under K candidate
plugin-weight vectors in ONE vmapped batched solve per cycle
(`tuning.sweep`: candidate weights are traced per-lane arguments, so the
whole sweep compiles exactly once — asserted via the PR 5 compile-watch
counters), scores each candidate on the placement-quality objective
vector (`tuning.quality`: fragmentation, utilization imbalance, gang
wait, unplaced fraction, plus score drift vs the recorded sequential
anchor on the baseline profile's own cycle-initial objective), replays
every candidate's placements through the independent numpy
hard-constraint oracles (`tuning.gates`: fit, queue-order quota, gang
quorum — the PR 2/7 differential oracles), and emits a tuned profile
JSON through the `api.config.profile_spec` inverse — ONLY when the
winning candidate strictly improves at least one objective with ZERO
hard-constraint violations across every tuned replay. The tuner is never
a black box: `--explain UID` renders the before/after per-plugin score
table (`Scheduler.explain_rows` via `flightrec.explain_solver`) for any
recorded pod, so every weight change is inspectable decision by
decision.

Ranking: per candidate, each objective's delta vs the in-band baseline
(lane 0 = the recorded profile's own weights) is sense-adjusted
(`tuning.quality.SENSE`) and taken in the objective's own dimensionless
units (every ranked objective is a fraction/relative quantity); the rank
score is the sum. A candidate that regresses any objective by more than
`--tolerance` points (default 0.01) is disqualified — a tune must not
buy one objective by silently selling another.

`smoke` is the CI gate (`make tune-smoke`): record a reduced trimaran
corpus through the REAL `run_cycle` hooks, sweep >= 64 candidates, and
require one compile for the sweep program, an emitted profile, and a
clean constraint audit.

One JSON line per action on stdout; rc 1 on any failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # `python tools/tune.py` from anywhere
    sys.path.insert(0, str(REPO))

#: reduced trimaran corpus for the smoke gate: two scoring plugins with a
#: real packing-vs-balance trade-off (synthetic per-node metrics), small
#: enough for a 2-core runner, 3 cycles with distinct seeds
SMOKE_SHAPE = dict(n_nodes=96, n_pods=128, cycles=3)
SMOKE_CANDIDATES = 64


def _prepare_for_cycle(scheduler, lc, meta) -> None:
    """Re-prepare the shared scheduler for ONE recorded cycle and re-bake
    that cycle's recorded host_state — must run immediately before every
    solve/score of that cycle (cycles of one corpus can carry different
    layouts or cluster-derived specializations; solving cycle i under
    cycle j's prepared state would replay a program the recorded cycle
    never ran). Equal static_keys across cycles keep one compiled sweep
    program; a cycle whose specialization genuinely differs retraces,
    which is correct."""
    from scheduler_plugins_tpu.utils import flightrec

    scheduler.prepare(meta, None)
    for plugin, rec in zip(scheduler.profile.plugins, lc.manifest["plugins"]):
        hs = rec.get("host_state")
        if hs is not None:
            plugin.restore_host_state(
                flightrec.unpack_pytree(hs, lc._blobs_for(hs))
            )


def _load_corpus(bundle_dir: str):
    """[(LoadedCycle, scheduler, snap, meta, auxes, anchor, wait, mode)]
    for every complete recorded cycle, with ONE rebuilt scheduler shared
    across the corpus (its jit caches amortize across cycles; callers
    `_prepare_for_cycle` before touching any one cycle)."""
    import numpy as np

    from scheduler_plugins_tpu.utils import flightrec

    cycles = flightrec.load_bundle(bundle_dir)
    if not cycles:
        raise SystemExit(f"no cycles in bundle {bundle_dir!r}")
    scheduler = None
    corpus = []
    for lc in cycles:
        if not lc.manifest.get("complete"):
            continue
        if scheduler is None:
            scheduler, _faithful = lc.scheduler()
        snap = lc.snapshot()
        meta = lc.meta()
        auxes = lc.auxes()
        anchor = lc.output("assignment")
        wait = lc.output("wait")
        if anchor is None:
            continue
        mode = (lc.manifest.get("outputs") or {}).get("mode")
        corpus.append((
            lc, scheduler, snap, meta, auxes,
            np.asarray(anchor), np.asarray(wait), mode,
        ))
    if not corpus:
        raise SystemExit("bundle has no complete cycles with outputs")
    return corpus


def _promotion_corpus(corpus):
    """Wrap `_load_corpus` tuples as `tuning.promotion.CorpusCycle`s —
    the gate/rank/disqualify body itself lives in `tuning.promotion`,
    shared verbatim with the online shadow lane (`tuning.shadow`)."""
    from scheduler_plugins_tpu.tuning.promotion import CorpusCycle

    return [
        CorpusCycle(
            scheduler=scheduler, snap=snap, meta=meta, auxes=auxes,
            anchor=anchor, wait=wait, mode=mode,
            prepare=(lambda sched, lc=lc, meta=meta:
                     _prepare_for_cycle(sched, lc, meta)),
        )
        for lc, scheduler, snap, meta, auxes, anchor, wait, mode in corpus
    ]


def _tuned_spec(corpus, W, k):
    """Tuned profile JSON via the `profile_spec` inverse: the recorded
    profile config with candidate k's weights applied."""
    from scheduler_plugins_tpu.api.config import load_profile, profile_spec

    manifest = corpus[0][0].manifest
    profile = load_profile(manifest["profile_config"])
    profile.name = manifest.get("profile", profile.name)
    for plugin, w in zip(profile.plugins, W[k]):
        plugin.weight = int(w)
    return profile_spec(profile)


def _explain_pair(corpus, W, k, uid, top=5):
    """(baseline table, tuned table) for one recorded pod — the
    before/after score breakdown that makes the tuner's choice
    inspectable (`flightrec.explain_solver` on a scheduler rebuilt with
    each weight vector)."""
    from scheduler_plugins_tpu.utils import flightrec

    for lc, _s, snap, meta, auxes, anchor, _w, _mode in corpus:
        if uid not in meta.pod_names:
            continue

        def table(weights, assignment):
            scheduler, _m, _f = flightrec.rebuild_scheduler(
                lc.manifest,
                lambda spec: flightrec.unpack_pytree(
                    spec, lc._blobs_for(spec)
                ),
            )
            for plugin, w in zip(scheduler.profile.plugins, weights):
                plugin.weight = int(w)
            return flightrec.explain_solver(
                scheduler, snap, meta, uid, top_k=top,
                assignment=assignment, auxes=auxes,
                cycle=lc.manifest["cycle"],
            )

        return table(W[0], anchor), table(W[k], None)
    raise SystemExit(f"uid {uid!r} not found in any recorded cycle")


def cmd_tune(args) -> int:
    from scheduler_plugins_tpu.tuning import promotion, sweep
    from scheduler_plugins_tpu.utils import observability as obs

    corpus = _load_corpus(args.bundle)
    scheduler = corpus[0][1]
    base = [int(p.weight) for p in scheduler.profile.plugins]
    W = sweep.candidate_weights(base, args.candidates, seed=args.seed)
    # scoped registry view: count only the compiles THIS sweep causes,
    # not whatever the corpus replay above already accumulated
    scope = obs.metrics.scoped()
    # the gate/rank/disqualify body shared with the online shadow lane
    # (tuning.promotion — ONE copy of the acceptance rules)
    verdict = promotion.evaluate_candidates(
        _promotion_corpus(corpus), W, args.tolerance
    )
    sweep_compiles = scope.get(obs.JIT_CACHE_MISS, program="sweep_solve")
    best = verdict.best

    out = {
        "metric": "tune",
        "bundle": args.bundle,
        "cycles": len(corpus),
        "candidates": int(W.shape[0]),
        "sweep_compiles": int(sweep_compiles),
        "plugins": [p.name for p in scheduler.profile.plugins],
        "baseline_weights": base,
        "baseline_objectives": {
            name: round(float(v[0]), 6)
            for name, v in verdict.objectives.items()
        },
        "tuned_weights": [int(w) for w in W[best]],
        "tuned_objectives": {
            name: round(float(v[best]), 6)
            for name, v in verdict.objectives.items()
        },
        "improvement_pct": {
            name: round(100.0 * float(imp[best]), 3)
            for name, imp in verdict.improvements.items()
        },
        "improved_objectives": verdict.improved,
        "hard_violations": int(verdict.violations[best]),
        "anchor_mismatches": int(verdict.anchor_mismatches),
        "candidates_disqualified": verdict.disqualified,
        "accepted": verdict.accepted,
    }
    if verdict.accepted and args.out:
        spec = _tuned_spec(corpus, W, best)
        obs.atomic_write(
            args.out, json.dumps(spec, indent=2, sort_keys=True) + "\n"
        )
        out["profile"] = args.out
    if args.explain:
        before, after = _explain_pair(corpus, W, best, args.explain,
                                      top=args.top)
        out["explain"] = {"uid": args.explain, "before": before,
                          "after": after}
    print(json.dumps(out))
    return 0 if verdict.accepted else 1


# ---------------------------------------------------------------------------
# quality over a bundle (shared with tools/replay.py quality)
# ---------------------------------------------------------------------------


def bundle_quality(bundle_dir: str) -> dict:
    """Per-cycle quality of a bundle's RECORDED placements (the jitted
    tensor core), diffed against the recorded per-cycle stamp when one
    exists, plus the corpus-level gang admission latency."""
    import numpy as np

    from scheduler_plugins_tpu.tuning import quality
    from scheduler_plugins_tpu.utils import flightrec

    cycles = flightrec.load_bundle(bundle_dir)
    rows = []
    latency_feed = []
    for lc in cycles:
        assignment = lc.output("assignment")
        if assignment is None:
            continue
        snap = lc.snapshot()
        wait = lc.output("wait")
        admitted = lc.output("admitted")
        wait = (
            np.zeros(len(np.asarray(assignment)), bool)
            if wait is None else np.asarray(wait)
        )
        q = quality.cycle_quality(snap, np.asarray(assignment), admitted,
                                  wait)
        recorded = (lc.manifest.get("report") or {}).get("quality")
        row = {
            "cycle": lc.manifest["cycle"],
            "quality": {k: round(v, 6) for k, v in q.items()},
        }
        if recorded is not None:
            row["recorded_quality"] = recorded
            row["matches_recorded"] = all(
                abs(q[k] - recorded[k]) < 1e-9 for k in q if k in recorded
            )
        rows.append(row)
        gang = np.asarray(snap.pods.gang) if snap.gangs is not None else None
        if gang is not None:
            latency_feed.append(
                (lc.manifest["meta"]["gang_names"], gang,
                 np.asarray(assignment), wait)
            )
    out = {"bundle": bundle_dir, "cycles": rows}
    if latency_feed:
        lat = quality.gang_admission_latency(latency_feed)
        out["gang_latency_cycles"] = (
            round(float(np.mean(list(lat.values()))), 3) if lat else None
        )
    return out


# ---------------------------------------------------------------------------
# the CI gate
# ---------------------------------------------------------------------------


def _record_smoke_corpus(out_dir: str) -> None:
    """Record the reduced trimaran corpus through the REAL `run_cycle`
    hooks: one shared Scheduler (warm jit cache), a fresh seeded cluster
    per cycle (clusters are single-use — run_cycle binds their pods),
    distinct seeds so the corpus is not one cycle three times."""
    from scheduler_plugins_tpu import plugins as P
    from scheduler_plugins_tpu.framework import Profile, Scheduler, run_cycle
    from scheduler_plugins_tpu.models import trimaran_scenario
    from scheduler_plugins_tpu.utils import flightrec

    scheduler = Scheduler(Profile(plugins=[
        P.TargetLoadPacking(), P.LoadVariationRiskBalancing(),
    ]))

    def one_cycle(seed):
        cluster = trimaran_scenario(
            n_nodes=SMOKE_SHAPE["n_nodes"], n_pods=SMOKE_SHAPE["n_pods"],
            seed=seed,
        )
        return run_cycle(scheduler, cluster, now=1000 + seed)

    one_cycle(0)  # compile warmup, recorder off
    flightrec.recorder.start(capacity=SMOKE_SHAPE["cycles"] + 1)
    for seed in range(SMOKE_SHAPE["cycles"]):
        flightrec.recorder.seed = seed
        one_cycle(seed)
    flightrec.recorder.save(out_dir)
    flightrec.recorder.stop()


def cmd_smoke(args) -> int:
    import bench

    bench.apply_platform_override()
    out_dir = args.out or os.path.join(
        tempfile.mkdtemp(prefix="tune_smoke_"), "bundle"
    )
    _record_smoke_corpus(out_dir)
    profile_path = os.path.join(out_dir, "tuned_profile.json")
    ns = argparse.Namespace(
        bundle=out_dir, candidates=SMOKE_CANDIDATES, seed=0,
        tolerance=0.05, out=profile_path, explain=None, top=5,
    )
    # capture cmd_tune's JSON line so the smoke emits ONE line
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = cmd_tune(ns)
    tune = json.loads(buf.getvalue())

    # re-verify the EMITTED profile independently: load it back through
    # api.config, re-solve every recorded cycle with the tuned weights
    # via the replay path, and re-run the hard-constraint oracles
    emitted_ok = False
    emitted_violations = None
    if tune.get("profile"):
        import numpy as np

        from scheduler_plugins_tpu.api.config import load_profile
        from scheduler_plugins_tpu.framework import Scheduler
        from scheduler_plugins_tpu.tuning import gates

        with open(tune["profile"]) as f:
            spec = json.load(f)
        tuned_sched = Scheduler(load_profile(spec))
        corpus = _load_corpus(out_dir)
        emitted_violations = 0
        for lc, _s, snap, meta, auxes, _anchor, _w, _mode in corpus:
            _prepare_for_cycle(tuned_sched, lc, meta)
            result = tuned_sched.solve(snap, auxes=auxes, mode="sequential")
            emitted_violations += gates.hard_violations(
                snap, np.asarray(result.assignment), np.asarray(result.wait)
            )["total"]
        emitted_ok = emitted_violations == 0

    ok = (
        tune.get("accepted") is True
        and tune.get("sweep_compiles", 99) <= 1
        and tune.get("candidates", 0) >= SMOKE_CANDIDATES
        and tune.get("hard_violations", 1) == 0
        and emitted_ok
    )
    print(json.dumps({
        "metric": "tune_smoke",
        "bundle": out_dir,
        "sweep_compiles": tune.get("sweep_compiles"),
        "candidates": tune.get("candidates"),
        "improved_objectives": tune.get("improved_objectives"),
        "improvement_pct": tune.get("improvement_pct"),
        "tuned_weights": tune.get("tuned_weights"),
        "baseline_weights": tune.get("baseline_weights"),
        "emitted_profile": tune.get("profile"),
        "emitted_profile_violations": emitted_violations,
        "accepted": tune.get("accepted"),
        "ok": bool(ok),
    }))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tools/tune.py", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_tune = sub.add_parser(
        "tune", help="sweep a bundle corpus, rank candidates, emit a "
        "gated tuned profile"
    )
    p_tune.add_argument("bundle")
    p_tune.add_argument("--candidates", type=int, default=64)
    p_tune.add_argument("--seed", type=int, default=0)
    p_tune.add_argument("--tolerance", type=float, default=0.01,
                        help="max fractional regression allowed on any "
                             "objective (default 1%%)")
    p_tune.add_argument("--out", default=None,
                        help="tuned profile JSON path (emitted only when "
                             "the gates accept)")
    p_tune.add_argument("--explain", default=None, metavar="UID",
                        help="render the before/after per-plugin score "
                             "table for this recorded pod")
    p_tune.add_argument("--top", type=int, default=5)
    p_smoke = sub.add_parser("smoke", help="the make tune-smoke CI gate")
    p_smoke.add_argument("--out", default=None,
                         help="corpus dir (default: temp dir)")
    args = ap.parse_args(argv)
    return {"tune": cmd_tune, "smoke": cmd_smoke}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
