"""Compiled-cost observatory: the static FLOP/byte/memory census (ISSUE 20).

Walks the SAME 24-program registry that tools/tpu_lower.py, jaxpr_audit
and kernel_audit share (`tpu_lower.PROGRAMS` — one registry, four
auditors), compiles each program on the deterministic CPU backend, and
records XLA's own `cost_analysis()` / `memory_analysis()` numbers joined
with the three static censuses the repo already commits:

- the TPU StableHLO op histogram + digest (docs/tpu_lowering.json),
- the collective census from `parallel/solver.collective_census` for the
  mesh programs (per-wave psum/ppermute/dma counts),
- the Pallas VMEM envelopes from docs/kernel_audit.json,

then projects a TPU roofline bound per program (peaks owned by
`parallel/vmem.py`, next to the VMEM budget): compute-vs-memory-bound
verdict and step-time floor, valid even while the axon tunnel is dead.

The three Mosaic-kernel programs cannot CPU-compile (`Only interpret
mode is supported on CPU backend`) and get STATIC-ONLY rows: null CPU
cost, digest based on the TPU StableHLO sha + collective census — still
counted toward 24/24 coverage, still drift-gated.

Manifest discipline (the tpu_lower pattern):

- `python tools/cost_observatory.py` re-measures everything and refreshes
  docs/cost_model.json — ONLY on a fully-clean full-registry run.
  Budgets are review-gated: carried forward from the committed manifest
  (a refresh can't silently launder a breach); `--rebudget` re-derives
  them at BUDGET_HEADROOM over fresh measurements.
- `--check` (make cost-audit-check) is read-only and fail-closed:
  missing manifest, coverage gap, budget breach, or cost-digest drift
  (enforced only under the manifest's pinned jax version — codegen
  differs across versions; CI pins jax to the manifest's pin) all exit
  non-zero.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))

import tpu_lower  # noqa: E402  (shared registry + CPU bootstrap)

from scheduler_plugins_tpu.obs import costmodel  # noqa: E402

MANIFEST = costmodel.MANIFEST_PATH
TPU_LOWERING = REPO / "docs" / "tpu_lowering.json"
KERNEL_AUDIT = REPO / "docs" / "kernel_audit.json"

#: Mosaic-kernel programs: pallas_call lowers only in interpret mode on
#: the CPU backend, so there is no CPU compile to cost — their rows are
#: static-only (TPU digest + census + VMEM envelope), by design.
STATIC_ONLY = {
    "sharded_wave_chunk_pallas": "mosaic-kernel-not-cpu-compilable",
    "pallas_ring_offsets": "mosaic-kernel-not-cpu-compilable",
    "pallas_fused_election": "mosaic-kernel-not-cpu-compilable",
}


def _load(path: Path) -> dict:
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return {}


def measure(name: str, tpu_manifest: dict, kernel_manifest: dict) -> dict:
    """One program's full cost row (compile + joins + roofline)."""
    from scheduler_plugins_tpu.parallel.mesh import ambient_mesh
    from scheduler_plugins_tpu.parallel.solver import collective_census

    fn, args, mesh = tpu_lower.PROGRAMS[name]()
    row: dict = {f: None for f in costmodel.COST_FIELDS}

    if name in STATIC_ONLY:
        row["static_only"] = STATIC_ONLY[name]
    else:
        row["static_only"] = None
        row.update(costmodel.compiled_cost(fn, args, mesh))

    # collective census: the mesh programs' per-wave collective counts,
    # plus the Mosaic programs (their pallas_call/dma_start equations are
    # the ring transfers the roofline can't see)
    if mesh is not None or name in STATIC_ONLY:
        if mesh is not None:
            with ambient_mesh(mesh):
                census = collective_census(fn, *args)
        else:
            census = collective_census(fn, *args)
        row["collectives"] = {k: int(v) for k, v in sorted(census.items())}
    else:
        row["collectives"] = {}

    # TPU StableHLO join (committed, separately gated by tpu-lower-check)
    tpu_row = tpu_manifest.get("programs", {}).get(name)
    if tpu_row:
        row["tpu"] = {
            "sha256": tpu_row["sha256"],
            "stablehlo_bytes": int(tpu_row["stablehlo_bytes"]),
            "ops_total": int(sum(tpu_row.get("ops", {}).values())),
        }
    else:
        row["tpu"] = None

    # Pallas VMEM envelope join (committed, gated by kernel-audit-check)
    kernels = (
        kernel_manifest.get("programs", {}).get(name, {}).get("kernels", [])
    )
    row["kernels"] = [
        {
            "name": k["name"],
            "vmem_bytes": int(k["vmem_bytes"]),
            "budget_bytes": int(k["budget_bytes"]),
            "payload_copies": int(k["payload_copies"]),
        }
        for k in kernels
    ]

    if row["flops"] is not None:
        row["roofline"] = costmodel.roofline(
            row["flops"], row["bytes_accessed"]
        )
    else:
        row["roofline"] = None

    row["cost_digest"] = costmodel.cost_digest(row)
    return row


def _hardware_block() -> dict:
    from scheduler_plugins_tpu.parallel import vmem

    t = vmem.VMEM_TARGET
    return {
        "target": t,
        "peak_flops_per_s": vmem.PEAK_FLOPS_PER_S[t],
        "hbm_bytes_per_s": vmem.HBM_BYTES_PER_S[t],
        "vmem_budget_bytes": vmem.VMEM_BUDGET_BYTES[t],
    }


def run(names: list[str], check: bool, rebudget: bool = False) -> int:
    import jax

    prior = _load(MANIFEST)
    tpu_manifest = _load(TPU_LOWERING)
    kernel_manifest = _load(KERNEL_AUDIT)
    full_set = list(names) == list(tpu_lower.PROGRAMS)

    if check:
        if not prior:
            print(f"[cost-audit] FAIL: missing manifest {MANIFEST}")
            return 1
        missing = sorted(set(tpu_lower.PROGRAMS) - set(prior.get("programs", {})))
        if missing:
            print(f"[cost-audit] FAIL: manifest missing programs: {missing}")
            return 1

    same_jax = prior.get("jax") == jax.__version__
    if check and not same_jax:
        print(
            f"[cost-audit] jax {jax.__version__} != manifest pin "
            f"{prior.get('jax')}: digest drift not comparable, budgets "
            "still enforced"
        )

    results, failures = {}, []
    for name in names:
        print(f"[cost-audit] {name} ...", flush=True)
        try:
            row = measure(name, tpu_manifest, kernel_manifest)
        except Exception as exc:  # a cost-compile failure IS the gate
            failures.append(f"{name}: cost measurement failed: {exc!r}")
            continue

        prior_row = prior.get("programs", {}).get(name, {})
        if rebudget or not prior_row.get("budgets"):
            budgets = costmodel.default_budgets(row)
        else:
            budgets = prior_row["budgets"]
        row["budgets"] = budgets

        for v in costmodel.budget_violations(row, budgets):
            failures.append(f"{name}: budget violation: {v}")

        if check and same_jax:
            committed = prior_row.get("cost_digest")
            if committed != row["cost_digest"]:
                failures.append(
                    f"{name}: cost drift: measured digest "
                    f"{row['cost_digest'][:12]} != committed "
                    f"{str(committed)[:12]} (refresh via `make cost-audit` "
                    "and review the delta)"
                )

        results[name] = row
        rl = row["roofline"]
        desc = (
            f"{rl['bound']}-bound, floor {rl['step_floor_us']:.1f}us"
            if rl
            else f"static-only ({row['static_only']})"
        )
        print(
            f"[cost-audit] {name}: flops={row['flops']} "
            f"bytes={row['bytes_accessed']} peak={row['peak_bytes']} "
            f"[{desc}]"
        )

    for f in failures:
        print(f"[cost-audit] FAIL: {f}")

    if check:
        print(
            f"[cost-audit] check: {len(results)}/{len(names)} measured, "
            f"{len(failures)} failures"
        )
        return 1 if failures else 0

    if failures:
        print("[cost-audit] NOT writing manifest (failures above)")
        return 1
    if not full_set:
        print(
            "[cost-audit] partial run (--programs): NOT writing manifest; "
            "refresh requires the full registry"
        )
        return 0
    manifest = {
        "jax": jax.__version__,
        "platform": "cpu",
        "hardware": _hardware_block(),
        "programs": {k: results[k] for k in sorted(results)},
    }
    MANIFEST.write_text(json.dumps(manifest, indent=1, sort_keys=True) + "\n")
    n_static = sum(1 for r in results.values() if r["static_only"])
    print(
        f"[cost-audit] wrote {MANIFEST.relative_to(REPO)}: "
        f"{len(results)} programs ({n_static} static-only), "
        f"manifest digest {costmodel.manifest_digest(manifest)[:12]}"
    )
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--check", action="store_true",
        help="read-only fail-closed gate: re-measure and compare against "
             "the committed manifest (budgets always; digests under the "
             "pinned jax version)")
    ap.add_argument(
        "--programs",
        help="comma-separated subset (refresh still requires a full run "
             "to write the manifest)")
    ap.add_argument(
        "--rebudget", action="store_true",
        help="re-derive review-gated budgets at the standard headroom "
             "over fresh measurements (default: carry committed budgets "
             "forward)")
    args = ap.parse_args(argv)

    tpu_lower.bootstrap()
    if args.programs:
        names = [n.strip() for n in args.programs.split(",") if n.strip()]
        unknown = [n for n in names if n not in tpu_lower.PROGRAMS]
        if unknown:
            ap.error(f"unknown programs: {unknown}")
    else:
        names = list(tpu_lower.PROGRAMS)
    return run(names, check=args.check, rebudget=args.rebudget)


if __name__ == "__main__":
    sys.exit(main())
