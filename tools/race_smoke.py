#!/usr/bin/env python
"""Seeded deterministic race harness (`make race-smoke`) — the dynamic
gate paired with `tools/race_audit.py --check`.

Replays a reduced pipelined-cycle + shadow-tuner + watchdog composite —
the three concurrency surfaces the static auditor models: the async
bind flusher, the shadow sweep worker lane (with its deadlined
counterfactual probes), and a deliberately-hung `call_with_deadline`
worker exercising the abandonment contract — under N seeded
interleavings with `utils/racecheck.py` installed (`SPT_RACE=1`:
lock/event proxies + a seeded cooperative yield injector).

Asserts, across ALL interleavings:
- zero lockset violations (non-owner release, double acquire),
- zero lock-order inversions observed at runtime,
- per-cycle placements BIT-IDENTICAL across every interleaving: the
  tuner runs `observe_only=True`, so the shadow lane's full worker/lock
  traffic runs but may never change live weights — scheduling output
  must not depend on thread timing.

One JSON line on stdout; rc 1 on any failure.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # `python tools/race_smoke.py` from anywhere
    sys.path.insert(0, str(REPO))

os.environ["SPT_RACE"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_SEEDS = int(os.environ.get("SPT_RACE_SEEDS", "8"))
N_CYCLES = 6
HANG_CYCLE = 2          # cycle index that launches the hung worker
HANG_S = 0.6            # how long the abandoned worker keeps running
HANG_DEADLINE_S = 0.05  # watchdog gives up long before that


def _build_cluster(Cluster, Node, Pod, Container, CPU, MEMORY, PODS):
    gib = 1 << 30

    def mknode(name, cpu=16_000):
        return Node(
            name=name, allocatable={CPU: cpu, MEMORY: 64 * gib, PODS: 110}
        )

    def mkpod(name, cpu=500, created=0):
        return Pod(
            name=name, creation_ms=created,
            containers=[Container(requests={CPU: cpu, MEMORY: gib})],
        )

    cluster = Cluster()
    for i in range(3):
        cluster.add_node(mknode(f"n{i}"))
    return cluster, mkpod


def run_seed(seed: int) -> dict:
    import jax

    jax.config.update("jax_platforms", "cpu")

    from scheduler_plugins_tpu.api.objects import Container, Node, Pod
    from scheduler_plugins_tpu.api.resources import CPU, MEMORY, PODS
    from scheduler_plugins_tpu.framework import (
        PipelinedCycle,
        Profile,
        Scheduler,
    )
    from scheduler_plugins_tpu.plugins import NodeResourcesAllocatable
    from scheduler_plugins_tpu.resilience.watchdog import (
        BackendUnavailable,
        call_with_deadline,
    )
    from scheduler_plugins_tpu.state.cluster import Cluster
    from scheduler_plugins_tpu.utils import flightrec
    from scheduler_plugins_tpu.tuning.shadow import ShadowTuner
    from scheduler_plugins_tpu.utils import racecheck

    if not racecheck.install(seed):
        raise RuntimeError("racecheck.install refused (SPT_RACE unset?)")
    try:
        scheduler = Scheduler(Profile(plugins=[NodeResourcesAllocatable()]))
        cluster, mkpod = _build_cluster(
            Cluster, Node, Pod, Container, CPU, MEMORY, PODS
        )
        flightrec.recorder.start(capacity=4)
        # observe_only: the whole shadow lane (worker thread, deadlined
        # probes, promotion machinery) runs, but active weights can
        # never change — the standing proof that placements must be
        # interleaving-independent
        tuner = ShadowTuner(
            scheduler, candidates=8, corpus_cycles=2, sweep_every=2,
            confirm_sweeps=1, observe_only=True, sync=False, seed=0,
        )
        pipe = PipelinedCycle(scheduler, cluster)
        reports_by_cycle = []
        hang_abandoned = False
        now = 1_000
        for i in range(N_CYCLES):
            cluster.add_pod(mkpod(f"p{i}", created=i))
            tuner.begin_cycle(now_ms=now)
            report = pipe.tick(now)
            tuner.observe_report(report)
            reports_by_cycle.append(report)
            if i == HANG_CYCLE:
                try:
                    call_with_deadline(
                        lambda: time.sleep(HANG_S), HANG_DEADLINE_S,
                        label="race-smoke.hang",
                    )
                except BackendUnavailable:
                    hang_abandoned = True
            now += 1_000
        pipe.flush()
        tuner.quiesce(timeout_s=30.0)
        pipe.close()
        # decision fields are only stable behind the conflict fence
        # (PipelinedCycle.tick docstring) — snapshot them post-flush
        placements = [dict(r.bound) for r in reports_by_cycle]
        flightrec.recorder.stop()
        # let the abandoned hang worker drain before uninstalling the
        # proxies — its Event writes must stay instrumented to the end
        time.sleep(HANG_S + 0.1)
        rep = racecheck.report()
        rep["placements"] = placements
        rep["hang_abandoned"] = hang_abandoned
        return rep
    finally:
        racecheck.uninstall()


def main() -> int:
    start = time.perf_counter()
    failures = []
    reports = []
    for seed in range(N_SEEDS):
        try:
            reports.append(run_seed(seed))
        except Exception as exc:
            failures.append(f"seed {seed}: {type(exc).__name__}: {exc}")
            break
    total_violations = sum(len(r["violations"]) for r in reports)
    for i, r in enumerate(reports):
        for v in r["violations"]:
            failures.append(f"seed {i}: {v['kind']}: {v['detail']}")
        if not r["hang_abandoned"]:
            failures.append(
                f"seed {i}: the hung worker was not abandoned — the "
                "watchdog deadline never fired"
            )
        if r["locks_created"] < 2:
            failures.append(
                f"seed {i}: only {r['locks_created']} checked locks "
                "created — the proxies are not actually installed"
            )
    identical = bool(reports) and all(
        r["placements"] == reports[0]["placements"] for r in reports
    )
    if reports and not identical:
        failures.append(
            "placements differ across interleavings (observe_only shadow "
            "lane leaked into live scheduling, or the cycle is "
            "timing-dependent)"
        )
    bound_total = (
        sum(len(b) for b in reports[0]["placements"]) if reports else 0
    )
    result = {
        "race_smoke": {
            "seeds": len(reports),
            "cycles": N_CYCLES,
            "violations": total_violations,
            "order_edges": max(
                (r["order_edges"] for r in reports), default=0
            ),
            "locks_created": max(
                (r["locks_created"] for r in reports), default=0
            ),
            "yields": sum(r["yields"] for r in reports),
            "placements_identical": identical,
            "pods_bound": bound_total,
            "elapsed_s": round(time.perf_counter() - start, 3),
            "failures": failures,
        }
    }
    print(json.dumps(result))
    for f in failures:
        print(f"[race-smoke] FAIL: {f}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
