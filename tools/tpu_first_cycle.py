#!/usr/bin/env python
"""One-command TPU re-entry gate (`make tpu-first-cycle`, ISSUE 13).

The axon tunnel has been dead since round 5 (CLAUDE.md): every bench
number in-tree is CPU-backend, and the compile-readiness manifests are
the standing TPU evidence. This tool makes the first healthy tunnel day a
ONE-COMMAND event: it runs the whole readiness chain and emits a single
structured JSON verdict, degrading gracefully at the probe step while the
tunnel is down.

Steps (each an isolated subprocess, so backend/platform pinning never
leaks between them):

1. **probe** — `bench.backend_probe()`: the CLAUDE.md 8x8-matmul
   host-transfer round-trip against the REAL backend, with the structured
   timeout/import-error/device-error classification.
2. **lower** — `tools/tpu_lower.py --check` on the three Pallas programs
   (`pallas_ring_offsets`, `pallas_fused_election`,
   `sharded_wave_chunk_pallas`): the compiled kernel bodies must still
   serialize to TPU StableHLO and match the committed manifest digests.
3. **interpret parity** — `bench.py --pallas-smoke` on the CPU host mesh:
   the interpret twins must stay bit-identical to the lax collectives
   build (placements + resident carry + clean capacity audit, zero
   framework collectives left in the wave bodies).
4. **on-chip** (only when the probe is healthy AND the default backend is
   a real TPU) — one config-8 chunk at the reduced SHARD_SMOKE shape with
   the COMPILED kernels (`--onchip-child` mode): both the pallas and lax
   arms run on-chip, placements must match bit-exactly, and the measured
   pods/s (host-transfer fenced, never `block_until_ready` — CLAUDE.md)
   is the first real on-chip election number.

Exit code: 1 only when a CODE gate fails (lowering, parity, or an
ATTEMPTED on-chip run); a dead tunnel is an environment verdict, reported
in the JSON with rc 0 so the gate can run on a schedule until the window
opens.

Usage:
    python tools/tpu_first_cycle.py [--out FILE]
    python tools/tpu_first_cycle.py --onchip-child   # internal step 4
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

PALLAS_PROGRAMS = (
    "pallas_ring_offsets",
    "pallas_fused_election",
    "sharded_wave_chunk_pallas",
)


def _tail(text: str, n: int = 3) -> list[str]:
    return [ln[:300] for ln in text.strip().splitlines()[-n:]]


def step_probe() -> dict:
    """Real-backend tunnel probe (bench's subprocess probe — a dead axon
    tunnel cannot hang this process). JAX_PLATFORMS is dropped from the
    child env so the probe sees the environment's real backend pin, not a
    CI cpu override."""
    import bench

    env_platforms = os.environ.pop("JAX_PLATFORMS", None)
    try:
        verdict = bench.backend_probe()
    finally:
        if env_platforms is not None:
            os.environ["JAX_PLATFORMS"] = env_platforms
    return {"kind": "healthy"} if verdict is None else verdict


def step_lower() -> dict:
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "tpu_lower.py"), "--check",
         "--programs", *PALLAS_PROGRAMS],
        capture_output=True, text=True, timeout=1800,
        cwd=str(REPO),
    )
    return {
        "ok": proc.returncode == 0,
        "programs": list(PALLAS_PROGRAMS),
        "detail": _tail(proc.stderr if proc.returncode else proc.stdout),
    }


def step_interpret_parity() -> dict:
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench.py"), "--pallas-smoke"],
        capture_output=True, text=True, timeout=1800, env=env,
        cwd=str(REPO),
    )
    out: dict = {"ok": proc.returncode == 0}
    try:
        line = json.loads(proc.stdout.strip().splitlines()[-1])
        out.update({
            k: line.get(k)
            for k in ("placements_match", "carry_match",
                      "capacity_violations",
                      "framework_collectives_left", "pods_per_sec")
        })
    except Exception:
        out["detail"] = _tail(proc.stderr or proc.stdout)
    return out


def step_on_chip() -> dict:
    timeout = float(os.environ.get("SPT_ONCHIP_TIMEOUT_S", 900))
    env = {**os.environ, "SPT_PALLAS": "1", "SPT_PALLAS_INTERPRET": "0"}
    env.pop("JAX_PLATFORMS", None)  # the real backend, not a cpu pin
    try:
        proc = subprocess.run(
            [sys.executable, __file__, "--onchip-child"],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=str(REPO),
        )
    except subprocess.TimeoutExpired:
        return {"ok": False, "error": f"on-chip child hung > {timeout}s "
                                      "(tunnel died mid-run?)"}
    if proc.returncode != 0:
        return {"ok": False, "error": "on-chip child failed",
                "detail": _tail(proc.stderr)}
    try:
        return {"ok": True,
                **json.loads(proc.stdout.strip().splitlines()[-1])}
    except Exception:
        return {"ok": False, "error": "unparseable on-chip child output",
                "detail": _tail(proc.stdout)}


def onchip_child() -> int:
    """Step 4 body (own process, real backend): one reduced config-8
    chunk through the sharded wave solver with the COMPILED Pallas
    kernels, and the lax-collectives build on the same tensors —
    placements must match bit-exactly on-chip, and the timed number is
    fenced by host transfers (`np.asarray`), never `block_until_ready`
    (CLAUDE.md: it can return early through the axon tunnel)."""
    import numpy as np

    import bench
    import jax
    import jax.numpy as jnp

    backend = jax.default_backend()
    if backend != "tpu":
        # a healthy probe on a non-TPU host (no axon platform pin — dev
        # laptop, CI) is an ENVIRONMENT verdict, not a code-gate failure:
        # report it as a skip so the parent keeps rc 0 per the contract
        print(json.dumps({"skipped": "default-backend-not-tpu",
                          "backend": backend}))
        return 0
    shape = dict(bench.SHARD_SMOKE_SHAPE)
    shape["devices"] = min(shape["devices"], jax.device_count())

    from scheduler_plugins_tpu.parallel.mesh import make_node_mesh
    from scheduler_plugins_tpu.parallel.solver import (
        rank_order_inputs,
        sharded_wave_chunk_solver,
    )

    problem = bench.mega_problem(
        shape["n_nodes"], shape["n_pods"], shape["chunk"]
    )
    mesh = make_node_mesh(shape["devices"])
    node_ids, rank_free0 = rank_order_inputs(
        problem["raw"], problem["free0"], problem["node_mask"],
        shape["devices"],
    )
    carry_host = np.asarray(rank_free0)
    chunk = shape["chunk"]
    req, mask = problem["req"][:chunk], problem["mask"][:chunk]

    def timed_arm(use_pallas):
        solver = sharded_wave_chunk_solver(
            mesh, shape["n_nodes"], rescue_window=256,
            use_pallas=use_pallas, pallas_interpret=False,
        )
        out, _ = solver(node_ids, req, mask, jnp.asarray(carry_host))
        np.asarray(out[0])  # compile + fence
        start = time.perf_counter()
        out, _ = solver(node_ids, req, mask, jnp.asarray(carry_host))
        a = np.asarray(out[0])  # host transfer IS the completion fence
        return a, time.perf_counter() - start

    a_pk, t_pk = timed_arm(True)
    a_lax, t_lax = timed_arm(False)
    match = bool((a_pk == a_lax).all())
    print(json.dumps({
        "device_kind": jax.devices()[0].device_kind,
        "devices": shape["devices"],
        "chunk_pods": chunk,
        "placed": int((a_pk >= 0).sum()),
        "placements_match_on_chip": match,
        "pallas_chunk_s": round(t_pk, 4),
        "lax_chunk_s": round(t_lax, 4),
        "pallas_pods_per_sec": round(chunk / t_pk, 1),
        "vs_lax_collectives_on_chip": round(t_lax / t_pk, 2),
    }))
    return 0 if match else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--out", default=None, metavar="FILE",
                        help="also write the readiness JSON to FILE")
    parser.add_argument("--onchip-child", action="store_true",
                        help="internal: run step 4 in this process")
    args = parser.parse_args(argv)
    if args.onchip_child:
        return onchip_child()

    report: dict = {"gate": "tpu-first-cycle",
                    "ts": int(time.time())}
    print("[tpu-first-cycle] probing the real backend ...", file=sys.stderr)
    report["probe"] = step_probe()
    tunnel_alive = report["probe"]["kind"] == "healthy"
    print(f"[tpu-first-cycle] probe: {report['probe']['kind']}",
          file=sys.stderr)

    print("[tpu-first-cycle] checking kernel lowering vs the committed "
          "manifest ...", file=sys.stderr)
    report["lowering"] = step_lower()
    print("[tpu-first-cycle] running interpret-mode parity "
          "(bench --pallas-smoke) ...", file=sys.stderr)
    report["interpret_parity"] = step_interpret_parity()

    if tunnel_alive:
        print("[tpu-first-cycle] tunnel HEALTHY: running the on-chip "
              "config-8 chunk ...", file=sys.stderr)
        report["on_chip"] = step_on_chip()
    else:
        report["on_chip"] = {
            "skipped": "tpu-backend-unavailable",
            "detail": report["probe"],
        }

    code_ok = (
        report["lowering"]["ok"] and report["interpret_parity"]["ok"]
        and report["on_chip"].get("ok", True)  # skipped counts as not-failed
    )
    report["ready"] = bool(
        code_ok and tunnel_alive and report["on_chip"].get("ok", False)
        and "skipped" not in report["on_chip"]
    )
    report["verdict"] = (
        "on-chip number captured" if report["ready"]
        else ("code gates green; waiting on the tunnel" if code_ok
              else "code gate FAILED")
    )
    out = json.dumps(report)
    print(out)
    if args.out:
        Path(args.out).write_text(out + "\n")
    return 0 if code_ok else 1


if __name__ == "__main__":
    sys.exit(main())
