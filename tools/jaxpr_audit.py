#!/usr/bin/env python
"""Jaxpr-level invariant auditor: carry provenance, donation discipline,
i64 dataflow and effect ordering on the COMPILED programs.

`tools/graft_lint.py` enforces the CLAUDE.md invariants at the source-AST
level; this tool proves them on the traced programs themselves, where
helper indirection, vmap/scan batching and cross-function dataflow are
fully resolved. It traces the same program registry `tools/tpu_lower.py`
AOT-lowers (bench cfgs 0-6 including the north-star chunk, both sharded
solves, `entry()`) to closed jaxprs and walks them with a provenance
lattice: every input leaf is tagged with its pytree path (snapshot family,
SolverState carry, aux channel), and tags propagate forward through every
equation — including pjit/scan/while/cond sub-jaxprs, with a fixpoint over
loop carries.

Rules:

- **JA001 stale-snapshot read** — a program output depends on a static
  snapshot tensor whose SolverState carry counterpart
  (`state.snapshot.CARRY_COUNTERPARTS` /
  `state.scheduling.TRACK_CARRY_COUNTERPARTS`) is also a program input but
  is DEAD in the jaxpr (eliminated by DCE): the solve consumed the static
  base where the live carry exists, i.e. a plugin bypassed the carry.
  Cycle-initial snapshot reads are sanctioned by design (scores are
  documented cycle-initial) — the rule fires only on a dead carry.
- **JA002 post-donation read** — a var passed in a DONATED position of an
  inner jitted call (`donated_invars` on the pjit equation) is consumed by
  any LATER equation, or returned, in the enclosing jaxpr. The
  compiled-level complement of graft-lint GL006: catches reuse routed
  through helpers or unrolled loop iterations that the lexical AST sweep
  cannot see.
- **JA003 i64 landmine through indirection** — an i64 `dot_general`/
  `conv_general_dilated`, a rank>=2 i64 cumulative-scan primitive, or a
  rank>=2 i64 `reduce_window` anywhere in the traced program, however it
  was reached (vmap batching, scan bodies, helper chains invisible to the
  source AST). Pre-lowering twin of the StableHLO landmine scan, with
  operand provenance attached as evidence.
- **JA004 nondeterminism** — unordered-effect callbacks inside solve
  programs: `io_callback(ordered=False)` and debug-print callbacks. Solve
  programs must be replayable; unordered host effects are not.

`pallas_call` equations (the ISSUE 13 ring kernels) are first-class:
input taints flow onto the kernel body's input refs (output/scratch refs
enter untainted), the body jaxpr is walked under every JA rule like any
other sub-jaxpr, and a per-program KERNEL-BODY OP CENSUS (dma_start/
dma_wait/semaphore ops and the body arithmetic) is recorded in the
manifest — the jaxpr-level twin of the StableHLO manifest, whose
`tpu_custom_call` payload is opaque to the text scan.

A manifest (`docs/jaxpr_audit.json`: per-program rule verdicts +
provenance-tagged equation counts) is committed so program drift shows up
as a diff; `--check` is the read-only fail-closed CI gate (missing manifest
fails, rule violations always fail, count equality is enforced only under
the manifest's jax version — jaxprs are version-dependent).

Usage:
    python tools/jaxpr_audit.py             # audit all, write manifest
    python tools/jaxpr_audit.py --check     # read-only verify vs manifest
    python tools/jaxpr_audit.py --programs entry bench_cfg3_numa_sequential
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
MANIFEST = REPO / "docs" / "jaxpr_audit.json"

if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.tpu_lower import PROGRAMS, bootstrap  # noqa: E402  (registry reuse)

RULES = ("JA001", "JA002", "JA003", "JA004")

#: call-like primitives whose sub-jaxpr invars align 1:1 with the equation
#: operands (param name -> where the jaxpr lives)
_CALL_PRIMS = {
    "pjit": "jaxpr",
    "closed_call": "call_jaxpr",
    "core_call": "call_jaxpr",
    "remat": "jaxpr",
    "checkpoint": "jaxpr",
    "custom_jvp_call": "call_jaxpr",
    "custom_vjp_call": "call_jaxpr",
    "custom_vmap_call": "call_jaxpr",
    # shard_map's body jaxpr takes the PER-SHARD blocks of the same
    # operands, 1:1 with the equation invars — provenance flows through
    # unchanged (the sharded wave solver program)
    "shard_map": "jaxpr",
}

#: cumulative-scan primitives whose rank>=2 i64 form lowers to the
#: vmem-pathological multi-dim reduce_window on TPU (CLAUDE.md)
_CUM_PRIMS = frozenset({"cumsum", "cumprod", "cummax", "cummin"})


# ---------------------------------------------------------------------------
# input labeling (pytree-path provenance)
# ---------------------------------------------------------------------------


#: per-program role names for positional (non-dataclass) arguments; programs
#: absent here get type-derived roles (ClusterSnapshot -> "snap",
#: SolverState -> "state", tuple -> "aux", else "argN")
ROLE_OVERRIDES = {
    # north_star_solve_chunk(raw, node_mask, req_chunk, mask_chunk, free0):
    # the free carry is the SolverState.free thread of the chunk pipeline
    "bench_cfg6_north_star_chunk": (
        "score_raw", "snap.nodes.mask", "snap.pods.req", "snap.pods.mask",
        "state.free",
    ),
    # apply_node_deltas(nodes, <7 packed upsert cols>, <6 usage cols>):
    # the NodeState argument is the donated RESIDENT carry (the serving
    # engine's cycle-to-cycle thread), not a static snapshot — label it
    # state.* so JA001's stale-snapshot rule doesn't treat the resident
    # columns as a bypassed snapshot read
    "serving_delta_apply": (
        "state.nodes",
        "up.idx", "up.valid", "up.alloc", "up.capacity", "up.mask",
        "up.region", "up.zone",
        "d.idx", "d.requested", "d.nonzero", "d.limits", "d.pod_count",
        "d.terminating",
    ),
    # compact_node_rows(nodes, gather_idx, valid): the NodeState arg is
    # the donated RESIDENT carry being row-compacted in place (the
    # serving engine's cycle-to-cycle thread), same labeling rationale
    # as serving_delta_apply
    "serving_node_compact": ("state.nodes", "gather_idx", "valid"),
    # sharded_wave_chunk(node_ids, req_chunk, mask_chunk, rank_free): the
    # rank-ordered free block is the donated RESIDENT carry threading
    # chunk to chunk on device (the sharded analog of cfg6's state.free)
    "sharded_wave_chunk": (
        "node_ids", "snap.pods.req", "snap.pods.mask", "state.free",
    ),
    # same program with the SPT_PALLAS election path: identical calling
    # convention, the collectives are pallas_call ring kernels
    "sharded_wave_chunk_pallas": (
        "node_ids", "snap.pods.req", "snap.pods.mask", "state.free",
    ),
    # packing_solve(snap, weights, pack_aux): the flagship packing-mode
    # program — `weights` is the static allocatable score config and
    # `pack_aux` the traced packing-knob vector (iterations/price/
    # temperature/decay), both aux-channel inputs, not snapshot state
    "packing_solve": ("snap", "aux.weights", "aux.packing"),
    # sweep(snap, state0, auxes, W): the (K, L) candidate weight matrix
    # IS an aux-channel input — per-lane weight scalars bound through
    # Plugin.bind_weight, the traced twin of the profile's static weight
    # (labeling it aux keeps JA001's snapshot-bypass lattice honest about
    # where candidate config enters the program)
    "sweep_solve": ("snap", "state", "aux", "aux.weights"),
    # gang_solve_body(gangs, state0, node_mask): the RankGangState arg is
    # the gang phase's snapshot family — labeling it snap.ranks makes its
    # `prev_assigned` leaf the CARRY_COUNTERPARTS twin of the
    # SolverState.rank_nodes carry, so JA001 proves the solve never
    # bypasses the rank-assignment carry (the state arg keeps its
    # type-derived "state" role)
    "rank_gang_solve": ("snap.ranks", "state", "snap.nodes.mask"),
    # wave_solve_body(gangs, free, eq_used, node_mask, ids): ONE wave of
    # the wave-batched gang solve — the per-gang body vmapped over a
    # lane of gang ids against the wave-start state. There is no
    # SolverState arg BY DESIGN: the free/eq/rank carries live host-side
    # between waves (the validator commits accepted lanes exactly), so
    # the wave-start state is labeled state.* (it IS the live carry, not
    # a static snapshot) and the gang tensors snap.ranks
    "wave_gang_solve": (
        "snap.ranks", "state.free", "state.eq_used", "snap.nodes.mask",
        "wave.ids",
    ),
    # apply_side_deltas(tables, <4 gang cols>, <3 ns cols>): the
    # SideTables argument is the donated RESIDENT gang/quota aggregate
    # carry (the serving engine's cycle-to-cycle thread), same labeling
    # rationale as serving_delta_apply
    "serving_side_apply": (
        "state.side",
        "sd.g_idx", "sd.g_assigned", "sd.g_gated", "sd.g_slack",
        "sd.q_idx", "sd.q_used", "sd.q_count",
    ),
    # shrink_select(rank_nodes, live, node_block, block_cost, n_release):
    # rank_nodes is the RESIDENT rank-assignment carry (the elastic delta
    # program mutates resident state, not a snapshot); the release count
    # is elastic config
    "elastic_shrink": (
        "state.rank_nodes", "snap.ranks.rank_mask", "snap.ranks.node_block",
        "snap.ranks.block_cost", "elastic.release",
    ),
}


def default_roles(args):
    """Role name per top-level argument, derived from the repo's calling
    conventions: snapshots and solver states are recognized by type, a
    tuple argument is the aux channel, everything else is positional."""
    from scheduler_plugins_tpu.framework.plugin import SolverState
    from scheduler_plugins_tpu.state.snapshot import ClusterSnapshot

    roles = []
    for i, a in enumerate(args):
        if isinstance(a, ClusterSnapshot):
            roles.append("snap")
        elif isinstance(a, SolverState):
            roles.append("state")
        elif isinstance(a, tuple):
            roles.append("aux")
        else:
            roles.append(f"arg{i}")
    return tuple(roles)


def label_leaves(args, roles=None):
    """One provenance label per flattened leaf of `args`, in jax flatten
    order (so labels align with the closed jaxpr's invars): role of the
    top-level argument + the leaf's pytree key path within it."""
    from jax import tree_util as jtu

    roles = tuple(roles) if roles is not None else default_roles(args)
    labels = []
    for path, _leaf in jtu.tree_flatten_with_path(tuple(args))[0]:
        idx = path[0].idx
        labels.append(f"{roles[idx]}{jtu.keystr(path[1:])}")
    return labels


def classify(labels) -> str:
    """Lattice point name for a taint set: which provenance families feed a
    value. Stable strings — they key the committed manifest's op counts."""
    kinds = set()
    for label in labels:
        if label.startswith("snap."):
            kinds.add("snapshot")
        elif label.startswith("state."):
            kinds.add("carry")
        elif label.startswith("aux"):
            kinds.add("aux")
        else:
            kinds.add("other")
    if not kinds:
        return "const"
    return "+".join(sorted(kinds))


# ---------------------------------------------------------------------------
# taint propagation + per-equation rule checks
# ---------------------------------------------------------------------------

_EMPTY = frozenset()


def _is_i64(v) -> bool:
    aval = getattr(v, "aval", None)
    return aval is not None and str(getattr(aval, "dtype", "")) == "int64"


def _rank(v) -> int:
    aval = getattr(v, "aval", None)
    return len(getattr(aval, "shape", ()))


class Auditor:
    """Forward taint walk over a closed jaxpr with recursive sub-jaxpr
    handling. Collects JA002/JA003/JA004 findings and the provenance-tagged
    equation census during the walk; JA001 is decided afterwards from the
    output taints plus a DCE liveness pass."""

    def __init__(self):
        self.violations: list[dict] = []
        self.op_counts: Counter = Counter()
        #: primitive census over pallas_call KERNEL BODIES only (the
        #: manifest's jaxpr-level evidence for the opaque Mosaic payloads)
        self.pallas_ops: Counter = Counter()
        self.eqn_count = 0
        self._scanned: set[int] = set()  # eqn ids already rule-checked
        self._seen_sites: set = set()    # violation dedup across revisits

    # -- violation plumbing -------------------------------------------------

    def _add(self, rule, detail, **extra):
        key = (rule, detail)
        if key in self._seen_sites:
            return
        self._seen_sites.add(key)
        self.violations.append({"rule": rule, "detail": detail, **extra})

    # -- the walk -----------------------------------------------------------

    def propagate(self, jaxpr, in_taints):
        """Per-output taint sets for one `core.Jaxpr` given per-invar taint
        sets. Mutates the census/violation state; revisits (loop fixpoints)
        re-propagate taints but never double-count equations."""
        from jax import core

        env: dict = {}

        def read(v):
            if isinstance(v, core.Literal):
                return _EMPTY
            return env.get(v, _EMPTY)

        def write(v, t):
            env[v] = env.get(v, _EMPTY) | t

        for var, taint in zip(jaxpr.invars, in_taints):
            write(var, taint)
        donated: dict = {}  # var -> donating call name
        for eqn in jaxpr.eqns:
            first_visit = id(eqn) not in self._scanned
            ts = [read(v) for v in eqn.invars]
            # JA002: consuming (or re-donating) an already-donated var
            for v in eqn.invars:
                if not isinstance(v, core.Literal) and v in donated:
                    self._add(
                        "JA002",
                        f"var donated to {donated[v]!r} consumed later by "
                        f"{eqn.primitive.name}",
                        primitive=eqn.primitive.name,
                    )
            out_ts = self._eqn(eqn, ts)
            if first_visit:
                self._scanned.add(id(eqn))
                self.eqn_count += 1
                self.op_counts[
                    f"{classify(frozenset().union(*out_ts) if out_ts else _EMPTY)}"
                ] += 1
                self._check_primitive(eqn, ts)
            di = eqn.params.get("donated_invars")
            if di and eqn.primitive.name in _CALL_PRIMS:
                name = eqn.params.get("name", eqn.primitive.name)
                for flag, v in zip(di, eqn.invars):
                    if flag and not isinstance(v, core.Literal):
                        donated[v] = name
            for v, t in zip(eqn.outvars, out_ts):
                if type(v).__name__ != "DropVar":
                    write(v, t)
        for v in jaxpr.outvars:
            if not isinstance(v, core.Literal) and v in donated:
                self._add(
                    "JA002",
                    f"var donated to {donated[v]!r} returned from the "
                    "enclosing jaxpr",
                )
        return [read(v) for v in jaxpr.outvars]

    def _eqn(self, eqn, ts):
        """Output taints for one equation, recursing into sub-jaxprs."""
        name = eqn.primitive.name
        params = eqn.params
        if name in _CALL_PRIMS and _CALL_PRIMS[name] in params:
            sub = params[_CALL_PRIMS[name]]
            sub_jaxpr = getattr(sub, "jaxpr", sub)
            if len(sub_jaxpr.invars) == len(ts):
                return self.propagate(sub_jaxpr, ts)
            return self._fallback(eqn, ts)
        if name == "scan":
            return self._scan(eqn, ts)
        if name == "while":
            return self._while(eqn, ts)
        if name == "cond":
            return self._cond(eqn, ts)
        if name == "pallas_call":
            return self._pallas(eqn, ts)
        # generic primitive (or unknown higher-order op): every output
        # carries the union of input taints; unknown sub-jaxprs are still
        # rule-scanned with that coarse union
        return self._fallback(eqn, ts)

    def _fallback(self, eqn, ts):
        union = frozenset().union(*ts) if ts else _EMPTY
        from jax import core

        for sub in core.jaxprs_in_params(eqn.params):
            sub_jaxpr = getattr(sub, "jaxpr", sub)
            self.propagate(sub_jaxpr, [union] * len(sub_jaxpr.invars))
        return [union for _ in eqn.outvars]

    def _scan(self, eqn, ts):
        params = eqn.params
        sub = params["jaxpr"].jaxpr
        n_consts = params["num_consts"]
        n_carry = params["num_carry"]
        consts, carry, xs = (
            ts[:n_consts], ts[n_consts:n_consts + n_carry], ts[n_consts + n_carry:]
        )
        carry = list(carry)
        for _ in range(32):  # fixpoint over the loop back-edge
            outs = self.propagate(sub, consts + carry + xs)
            new_carry = [c | o for c, o in zip(carry, outs[:n_carry])]
            if new_carry == carry:
                break
            carry = new_carry
        outs = self.propagate(sub, consts + carry + xs)
        return outs[:n_carry] + outs[n_carry:]

    def _while(self, eqn, ts):
        params = eqn.params
        cond_sub = params["cond_jaxpr"].jaxpr
        body_sub = params["body_jaxpr"].jaxpr
        cn, bn = params["cond_nconsts"], params["body_nconsts"]
        cond_consts, body_consts, carry = ts[:cn], ts[cn:cn + bn], list(ts[cn + bn:])
        pred = _EMPTY
        for _ in range(32):
            pred = self.propagate(cond_sub, cond_consts + carry)[0]
            outs = self.propagate(body_sub, body_consts + carry)
            new_carry = [c | o for c, o in zip(carry, outs)]
            if new_carry == carry:
                break
            carry = new_carry
        # trip count is control-dependence: outputs inherit the predicate
        return [c | pred for c in carry]

    def _pallas(self, eqn, ts):
        """pallas_call: the body jaxpr's invars are [input refs..., output
        refs..., scratch refs...] — input taints map 1:1 onto the leading
        refs (provenance "through the grid"), outputs/scratch enter
        untainted. The body is rule-walked like any sub-jaxpr, its
        primitive names censused into `pallas_ops`, and the equation's
        outputs carry the union of input taints (the kernel writes its
        output refs from the inputs; finer ref-dataflow is deliberately
        coarse-but-sound, like `_fallback`)."""
        from jax import core

        sub = eqn.params.get("jaxpr")
        if sub is None:
            return self._fallback(eqn, ts)
        sub_jaxpr = getattr(sub, "jaxpr", sub)
        if id(eqn) not in self._scanned:

            def census(j):
                for e in j.eqns:
                    self.pallas_ops[e.primitive.name] += 1
                    for s in core.jaxprs_in_params(e.params):
                        census(getattr(s, "jaxpr", s))

            census(sub_jaxpr)
        taints = list(ts) + [_EMPTY] * (len(sub_jaxpr.invars) - len(ts))
        self.propagate(sub_jaxpr, taints[: len(sub_jaxpr.invars)])
        union = frozenset().union(*ts) if ts else _EMPTY
        return [union for _ in eqn.outvars]

    def _cond(self, eqn, ts):
        pred, oper = ts[0], ts[1:]
        outs = None
        for branch in eqn.params["branches"]:
            b_outs = self.propagate(branch.jaxpr, oper)
            outs = b_outs if outs is None else [
                a | b for a, b in zip(outs, b_outs)
            ]
        return [o | pred for o in (outs or [])]

    # -- per-primitive rules (JA003 / JA004) --------------------------------

    def _check_primitive(self, eqn, ts):
        name = eqn.primitive.name
        if name in ("dot_general", "conv_general_dilated"):
            if any(_is_i64(v) for v in eqn.invars[:2]):
                self._add(
                    "JA003",
                    f"i64 {name} "
                    f"(provenance: {sorted(frozenset().union(*ts) or {'const'})})",
                    primitive=name,
                )
        elif name in _CUM_PRIMS:
            v = eqn.invars[0]
            if _is_i64(v) and _rank(v) >= 2:
                self._add(
                    "JA003",
                    f"rank-{_rank(v)} i64 {name}: lowers to multi-dim "
                    f"reduce_window on TPU "
                    f"(provenance: {sorted(frozenset().union(*ts) or {'const'})})",
                    primitive=name,
                )
        elif name.startswith("reduce_window"):
            v = eqn.invars[0]
            if _is_i64(v) and _rank(v) >= 2:
                self._add(
                    "JA003",
                    f"rank-{_rank(v)} i64 {name}",
                    primitive=name,
                )
        elif name == "io_callback":
            if not eqn.params.get("ordered", False):
                self._add(
                    "JA004",
                    "io_callback(ordered=False) inside a solve program",
                    primitive=name,
                )
        elif name in ("debug_callback", "debug_print"):
            self._add(
                "JA004",
                f"{name} (debug print) inside a solve program",
                primitive=name,
            )


# ---------------------------------------------------------------------------
# liveness (dead-carry detection for JA001)
# ---------------------------------------------------------------------------


def used_inputs(closed_jaxpr) -> list[bool]:
    """Per-invar liveness: does the input contribute to any output? Uses
    jax's own DCE (handles pjit/scan/while/cond sub-jaxpr recursion
    precisely); falls back to a coarse any-equation-reads-it sweep if the
    private API moves."""
    jaxpr = closed_jaxpr.jaxpr
    try:
        from jax._src.interpreters import partial_eval as pe

        _, used = pe.dce_jaxpr(jaxpr, [True] * len(jaxpr.outvars))
        return list(used)
    except Exception as exc:
        # the degradation must be VISIBLE: the coarse sweep cannot see a
        # carry that is read but discarded, so JA001 is weaker here
        print(
            f"[jaxpr-audit] note: DCE liveness unavailable ({exc!r}); "
            "falling back to coarse any-read liveness — JA001 may miss "
            "dead-after-read carries",
            file=sys.stderr,
        )
        from jax import core

        read: set = set()

        def sweep(j):
            for eqn in j.eqns:
                for v in eqn.invars:
                    if not isinstance(v, core.Literal):
                        read.add(v)
                for sub in core.jaxprs_in_params(eqn.params):
                    sweep(getattr(sub, "jaxpr", sub))
            for v in j.outvars:
                if not isinstance(v, core.Literal):
                    read.add(v)

        sweep(jaxpr)
        return [v in read for v in jaxpr.invars]


def carry_pairs():
    """(snapshot label, carry label) counterpart pairs, as input labels."""
    from scheduler_plugins_tpu.state.scheduling import TRACK_CARRY_COUNTERPARTS
    from scheduler_plugins_tpu.state.snapshot import CARRY_COUNTERPARTS

    pairs = []
    for suffix, field in {**CARRY_COUNTERPARTS,
                          **TRACK_CARRY_COUNTERPARTS}.items():
        pairs.append((f"snap{suffix}", f"state.{field}"))
    return pairs


# ---------------------------------------------------------------------------
# program audit
# ---------------------------------------------------------------------------


def audit_fn(fn, args, roles=None, mesh=None) -> dict:
    """Trace `fn(*args)` to a closed jaxpr and run every JA rule. `roles`
    optionally names the top-level arguments (see `label_leaves`); `mesh`
    wraps the trace in the ambient mesh (sharded programs)."""
    import jax

    from scheduler_plugins_tpu.parallel.mesh import ambient_mesh

    if mesh is not None:
        with ambient_mesh(mesh):
            closed = jax.make_jaxpr(fn)(*args)
    else:
        closed = jax.make_jaxpr(fn)(*args)
    labels = label_leaves(args, roles)
    if len(labels) != len(closed.jaxpr.invars):
        raise RuntimeError(
            f"label/invar mismatch: {len(labels)} leaves vs "
            f"{len(closed.jaxpr.invars)} invars (kwargs or non-leaf "
            "arguments are not supported by the auditor)"
        )
    auditor = Auditor()
    out_taints = auditor.propagate(
        closed.jaxpr, [frozenset([label]) for label in labels]
    )
    out_union = frozenset().union(*out_taints) if out_taints else _EMPTY

    live = used_inputs(closed)
    live_labels = {lab for lab, u in zip(labels, live) if u}
    label_set = set(labels)
    for snap_label, carry_label in carry_pairs():
        if snap_label not in label_set or carry_label not in label_set:
            continue  # the pair must exist in THIS program's inputs
        if snap_label in out_union and carry_label not in live_labels:
            auditor._add(
                "JA001",
                f"outputs depend on static {snap_label!r} while its carry "
                f"counterpart {carry_label!r} is dead in the jaxpr — the "
                "solve bypassed the SolverState carry",
                snapshot=snap_label,
                carry=carry_label,
            )

    rule_counts = {r: 0 for r in RULES}
    for v in auditor.violations:
        rule_counts[v["rule"]] += 1
    return {
        "rules": rule_counts,
        "violations": auditor.violations,
        "eqns": auditor.eqn_count,
        "provenance_ops": {
            k: auditor.op_counts[k] for k in sorted(auditor.op_counts)
        },
        # kernel-body primitive census over pallas_call equations ({} for
        # programs without kernels): the committed jaxpr-level evidence
        # for what lives inside the opaque tpu_custom_call payloads
        "pallas_kernels": {
            k: auditor.pallas_ops[k] for k in sorted(auditor.pallas_ops)
        },
        "output_provenance": classify(out_union),
    }


def audit_program(name: str) -> dict:
    fn, args, mesh = PROGRAMS[name]()
    return audit_fn(fn, args, roles=ROLE_OVERRIDES.get(name), mesh=mesh)


# ---------------------------------------------------------------------------
# driver (mirrors tools/tpu_lower.py: fail-closed --check, committed digest)
# ---------------------------------------------------------------------------


def run(names, check: bool) -> int:
    import jax

    prior = {}
    if MANIFEST.exists():
        prior = json.loads(MANIFEST.read_text())
    results, failures = {}, []
    for name in names:
        print(f"[jaxpr-audit] {name} ...", flush=True)
        try:
            results[name] = audit_program(name)
        except Exception as exc:  # a program that cannot trace IS a failure
            failures.append(f"{name}: trace failed: {exc!r}")
            continue
        res = results[name]
        for v in res["violations"]:
            failures.append(f"{name}: {v['rule']} {v['detail']}")
        print(
            f"[jaxpr-audit] {name}: {res['eqns']} eqns, "
            f"{sum(res['rules'].values())} violations, "
            f"output provenance {res['output_provenance']}",
            flush=True,
        )

    manifest = {
        "jax": jax.__version__,
        "programs": {
            n: {
                "rules": r["rules"],
                "eqns": r["eqns"],
                "provenance_ops": r["provenance_ops"],
                "pallas_kernels": r["pallas_kernels"],
                "output_provenance": r["output_provenance"],
            }
            for n, r in sorted(results.items())
        },
    }

    if check and not prior:
        failures.append(
            "docs/jaxpr_audit.json missing: run `python tools/jaxpr_audit.py`"
            " and commit it"
        )
    if check and prior:
        prior_programs = prior.get("programs", {})
        missing = [n for n in names if n in PROGRAMS and n not in prior_programs]
        if missing:
            failures.append(
                f"manifest missing programs {missing}: run "
                "`python tools/jaxpr_audit.py` and commit docs/jaxpr_audit.json"
            )
        for n, p in prior_programs.items():
            dirty = {r: c for r, c in p.get("rules", {}).items() if c}
            if dirty:
                failures.append(f"manifest records violations for {n}: {dirty}")
        if prior.get("jax") == jax.__version__:
            for n, r in results.items():
                want = prior_programs.get(n, {})
                if want and (
                    want.get("eqns") != r["eqns"]
                    or want.get("provenance_ops") != r["provenance_ops"]
                    or want.get("pallas_kernels", {})
                    != r["pallas_kernels"]
                ):
                    failures.append(
                        f"{n}: jaxpr census drift vs manifest — intended? "
                        "re-run `python tools/jaxpr_audit.py` and commit the "
                        "manifest diff"
                    )
        else:
            print(
                f"[jaxpr-audit] note: manifest written under jax "
                f"{prior.get('jax')}, running {jax.__version__}; census "
                "equality not enforced, rule/coverage gates still apply"
            )

    if not check and set(names) == set(PROGRAMS) and not failures:
        MANIFEST.write_text(json.dumps(manifest, indent=1, sort_keys=True) + "\n")
        print(f"[jaxpr-audit] wrote {MANIFEST.relative_to(REPO)}")
    elif not check:
        reason = "failures" if failures else "partial program set"
        print(f"[jaxpr-audit] {reason}: manifest NOT rewritten")

    for f in failures:
        print(f"[jaxpr-audit] FAIL: {f}", file=sys.stderr)
    if not failures:
        print(
            f"[jaxpr-audit] OK: {len(results)}/{len(names)} programs audit "
            "clean (JA001-JA004)"
        )
    return 1 if failures else 0


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument(
        "--check",
        action="store_true",
        help="read-only: verify against the committed manifest (census "
        "equality enforced only under the manifest's jax version)",
    )
    parser.add_argument(
        "--programs",
        nargs="+",
        choices=sorted(PROGRAMS),
        default=sorted(PROGRAMS),
        help="subset of programs (default: all)",
    )
    args = parser.parse_args(argv)
    bootstrap()
    return run(args.programs, check=args.check)


if __name__ == "__main__":
    sys.exit(main())
