#!/usr/bin/env python
"""Whole-program static concurrency auditor: thread-topology discovery,
RacerD-style must-hold lockset analysis, deadlock-order lint.

`tools/graft_lint.py` enforces the CLAUDE.md invariants file-by-file at
the source-AST level and `tools/jaxpr_audit.py` proves them on the traced
programs; NEITHER sees the host-side thread topology that orchestrates
them — the pipelined cycle's async bind flusher, the shadow-tuner worker
lane, watchdog abandoned-on-timeout workers, the daemon's HTTP/signal/
elector/agent threads, and the bridge feed/collector threads, all sharing
mutable scheduler state behind ad-hoc `threading.Lock`s. This tool closes
that gap (pure stdlib — no jax import, like graft_lint, so its CI job
installs nothing):

1. **Thread-entry discovery** — every `threading.Thread(target=...)`
   (keyed by its `name=`, which GL012 makes mandatory),
   `ThreadPoolExecutor(thread_name_prefix=...)` + `.submit(...)` lane,
   worker-queue `.submit(...)` lane (a thread whose target is a method of
   the worker class), threading-server handler class (the serve_forever
   thread dispatches into `do_*`/`handle`), `signal.signal(...)` handler,
   and the declared main-thread entries (`main()` functions and
   `MAIN_METHODS`). `resilience.call_with_deadline(fn, ...)` payloads are
   attached to the `wd-*` worker entry the wrapper spawns.
2. **Reachability with locksets** — from each entry point the call graph
   is walked (self/typed-attribute/alias/import resolution, conservative:
   unresolvable calls are skipped) computing per-entry reachable
   attribute/global read-write sets; every access site carries the set of
   locks lexically held (`with lock:` scoping, linear
   `acquire()`/`release()`), joined with the locks held at the call
   sites on the path. The MUST-HOLD lockset of (entry, var) is the
   intersection over all reachable access sites.

Rules:

- **CA001 unlocked shared state** — a var written on one entry point and
  read (or written) on another where the two entries' must-hold locksets
  share no common lock. Sync primitives (Lock/Event/Queue attrs) and
  `__init__`-time publication (happens-before thread start) are exempt.
- **CA002 lock-order inversion** — the cross-entry lock-acquisition
  graph (edge A->B when B is acquired while A is held) contains a cycle:
  a potential deadlock.
- **CA003 unserialized tracing/memo** — a jit-trace or memo-insertion
  site (`rebuild_scheduler`, `jax.jit`, `make_jaxpr`, `checkified`,
  `donated_chunk_solver`, writes to `*cache*`/`*memo*` attrs) reachable
  from two or more entry points with no common serializing lock — the
  `flightrec._EXPLAIN_LOCK` lesson, generalized: concurrent tracing
  corrupts the jit cache.
- **CA004 signal-handler lock reach** — a signal handler's reachable set
  acquires a lock that another entry point also acquires: the handler
  can fire while that thread holds the lock, and deadlock. Handlers must
  only set Events / flip flags.
- **CA005 abandoned-worker writes** — a watchdog-abandonable worker
  (entry name matching `wd-*` / `solve-watchdog`) whose reachable set
  writes ANY attribute/global: the PR 9 abandonment contract says a
  deadlined worker may write only its own locals and its result
  box/Event, because it keeps running as an orphan after the deadline.

Sanctioning an audited-safe site: a trailing
`# race-audit: safe[CAxxx] — reason` comment. On an access/acquire line
it exempts that site; on a `def` line it exempts the whole body; on a
CALL line it exempts everything reached through that call on this path
(the fence-ordered bind flusher idiom: the caller vouches for the
subtree). Sanction counts are recorded in the manifest so review sees
the audited surface.

Verdicts + the entry-point table land in the committed fail-closed
manifest `docs/race_audit.json` (the tpu_lowering/jaxpr_audit pattern):
`--check` fails on a missing manifest, any recorded or current
violation, and entry-table/census drift. The daemon's `/healthz`
`threads` block diffs the live thread census against the manifest's
entry table at runtime; `utils/racecheck.py` + `make race-smoke` are the
dynamic counterpart (seeded interleavings over lock/event proxies).

Usage:
    python tools/race_audit.py             # audit the package, write manifest
    python tools/race_audit.py --check     # read-only verify vs manifest
    python tools/race_audit.py --paths f.py ...   # audit specific files
"""

from __future__ import annotations

import argparse
import ast
import fnmatch
import json
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
MANIFEST = REPO / "docs" / "race_audit.json"

RULES = ("CA001", "CA002", "CA003", "CA004", "CA005")
TOOL_VERSION = 1

#: the default audit surface (the package; tools/tests are host-side
#: single-threaded drivers)
DEFAULT_ROOTS = ("scheduler_plugins_tpu",)

#: methods that run on the MAIN thread by contract (the daemon loop);
#: module-level functions literally named `main` join automatically
MAIN_METHODS = (
    "scheduler_plugins_tpu.__main__:Daemon.run",
    "scheduler_plugins_tpu.__main__:Daemon.tick",
)

#: callables whose invocation traces/compiles or inserts into a jit cache
#: (CA003's serialization surface)
TRACE_CALLEES = frozenset({
    "rebuild_scheduler", "jit", "make_jaxpr", "checkified",
    "donated_chunk_solver", "eval_shape", "lower",
})

#: entry-name patterns bound by the watchdog abandonment contract (CA005)
ABANDONABLE_PATTERNS = ("wd-*", "solve-watchdog")

#: constructor names that create sync primitives — attributes holding one
#: are synchronization, not shared data (their own thread safety is the
#: stdlib's contract)
_SYNC_CTORS = frozenset({
    "Lock", "RLock", "Event", "Condition", "Semaphore",
    "BoundedSemaphore", "Barrier", "SimpleQueue", "Queue", "LifoQueue",
    "PriorityQueue", "local",
})
_LOCK_CTORS = frozenset({"Lock", "RLock"})

#: method names that mutate their receiver (a call `self.x.append(...)`
#: is a WRITE to self.x). Deliberately excludes Event.set/Queue.get and
#: the observability counters' inc/set_gauge (internally locked).
_MUTATORS = frozenset({
    "append", "extend", "insert", "remove", "pop", "popleft", "clear",
    "update", "setdefault", "add", "discard", "appendleft", "sort",
})

_SAFE_RE = re.compile(r"#\s*race-audit:\s*safe(?:\[([A-Z0-9, ]+)\])?")

_INIT_METHODS = frozenset({"__init__", "__post_init__", "__new__"})


# ---------------------------------------------------------------------------
# symbol model
# ---------------------------------------------------------------------------


class Fn:
    """One function/method/nested-def: resolved accesses, lock
    acquisitions and calls, each stamped with the lexically-held lockset
    and any sanction at its line."""

    def __init__(self, key, module, cls, name, node, path,
                 is_method=False):
        self.key = key          # "module:Class.meth" / "module:fn"
        self.module = module
        self.cls = cls          # owning class key or None
        self.name = name
        self.node = node
        self.path = path
        self.is_method = is_method
        self.is_init = name in _INIT_METHODS
        #: (var, kind, locks, line, sanctions)  kind in {"read","write"}
        self.accesses: list = []
        #: (lock_id, line, sanctions)
        self.acquires: list = []
        #: (target_fn_keys, locks, line, callee_name, sanctions)
        self.calls: list = []
        self.sanctions_def: frozenset = frozenset()


class Cls:
    def __init__(self, key, module, name, node):
        self.key = key
        self.module = module
        self.name = name
        self.node = node
        self.bases: list[str] = []       # raw base names
        self.methods: dict[str, Fn] = {}
        self.attr_types: dict[str, str] = {}   # attr -> class key
        self.sync_attrs: set[str] = set()
        self.lock_attrs: set[str] = set()


class Model:
    def __init__(self):
        self.files: dict[Path, ast.Module] = {}
        self.sources: dict[Path, list[str]] = {}
        self.modules: dict[Path, str] = {}
        self.classes: dict[str, Cls] = {}        # key -> Cls
        self.class_by_name: dict[str, list[str]] = {}
        self.funcs: dict[str, Fn] = {}           # key -> Fn
        self.module_funcs: dict[str, dict[str, str]] = {}  # mod -> name -> key
        self.module_globals: dict[str, set[str]] = {}
        self.lock_globals: dict[str, set[str]] = {}
        self.imports: dict[str, dict[str, tuple]] = {}  # mod -> local -> spec
        self.param_types: dict[tuple, str] = {}  # (fn_key, param) -> class key
        # entry-point raw material
        self.threads: list = []      # (name_pat, targets, named, line, path)
        self.pools: dict[tuple, str] = {}        # (cls_key, attr) -> prefix
        self.pool_submits: dict[tuple, list] = {}
        self.worker_submits: dict[str, list] = {}  # worker cls key -> fn keys
        self.servers: dict[tuple, str] = {}      # (cls_key, attr) -> handler
        self.signals: list = []      # (signame, fn_keys, line, path)
        self.deadline_targets: list = []         # fn keys

    def mro(self, cls_key):
        """cls_key plus transitively-resolved bases (parsed classes only)."""
        out, stack = [], [cls_key]
        while stack:
            k = stack.pop(0)
            if k in out or k not in self.classes:
                continue
            out.append(k)
            c = self.classes[k]
            for b in c.bases:
                for cand in self.class_by_name.get(b, ()):
                    stack.append(cand)
        return out

    def attr_owner(self, cls_key, attr):
        """Class key in the MRO that declares `attr`, else cls_key."""
        for k in self.mro(cls_key):
            c = self.classes[k]
            if (attr in c.attr_types or attr in c.sync_attrs
                    or attr in c.lock_attrs):
                return k
        return cls_key

    def find_method(self, cls_key, name):
        for k in self.mro(cls_key):
            fn = self.classes[k].methods.get(name)
            if fn is not None:
                return fn
        return None


def _ctor_name(call):
    return _callee_name(call.func) if isinstance(call, ast.Call) else None


def _builder_ctor(model: Model, val):
    """`SomeClass(...).start()` where start's returns are all `self`
    (the builder idiom) types the target as SomeClass."""
    if not (isinstance(val, ast.Call)
            and isinstance(val.func, ast.Attribute)
            and isinstance(val.func.value, ast.Call)):
        return None
    inner = _ctor_name(val.func.value)
    if not inner or not model.class_by_name.get(inner):
        return None
    meth = model.find_method(model.class_by_name[inner][0], val.func.attr)
    if meth is None:
        return None
    rets = [s for s in ast.walk(meth.node) if isinstance(s, ast.Return)]
    if rets and all(
        isinstance(r.value, ast.Name) and r.value.id == "self" for r in rets
    ):
        return inner
    return None


def _collect_attr_census(model: Model):
    """Sync/lock/typed attribute census — runs AFTER every file's symbol
    pass so `self.x = SomeClass(...)` resolves classes from other files."""
    for c in model.classes.values():
        for meth in ast.walk(c.node):
            if not isinstance(meth, ast.Assign) or len(meth.targets) != 1:
                continue
            t = meth.targets[0]
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            cn = _ctor_name(meth.value)
            if not (cn in _SYNC_CTORS or (cn and model.class_by_name.get(cn))):
                cn = _builder_ctor(model, meth.value)
            if cn in _SYNC_CTORS:
                c.sync_attrs.add(t.attr)
                if cn in _LOCK_CTORS:
                    c.lock_attrs.add(t.attr)
            elif cn and model.class_by_name.get(cn):
                c.attr_types[t.attr] = model.class_by_name[cn][0]


def _module_name(path: Path) -> str:
    try:
        rel = path.resolve().relative_to(REPO)
        return ".".join(rel.with_suffix("").parts)
    except ValueError:
        return path.stem


def _sanctions_at(source_lines, line) -> frozenset:
    if 0 < line <= len(source_lines):
        m = _SAFE_RE.search(source_lines[line - 1])
        if m:
            rules = m.group(1)
            if rules is None:
                return frozenset(RULES)
            return frozenset(r for r in re.split(r"[,\s]+", rules) if r)
    return frozenset()


def _name_pattern(node) -> str | None:
    """Thread `name=` value as a match pattern: constants verbatim,
    f-string interpolations collapsed to `*`."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            else:
                parts.append("*")
        return "".join(parts)
    return None


def _callee_name(func):
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


# ---------------------------------------------------------------------------
# pass A: symbols (classes, functions, imports, globals, sync attrs)
# ---------------------------------------------------------------------------


def _collect_symbols(model: Model, path: Path, tree: ast.Module):
    mod = model.modules[path]
    model.module_funcs.setdefault(mod, {})
    model.module_globals.setdefault(mod, set())
    model.lock_globals.setdefault(mod, set())
    model.imports.setdefault(mod, {})

    ctor_name = _ctor_name

    def reg_class(node, prefix):
        key = f"{mod}:{prefix}{node.name}"
        c = Cls(key, mod, node.name, node)
        for b in node.bases:
            n = _callee_name(b) if isinstance(b, ast.Call) else (
                b.attr if isinstance(b, ast.Attribute)
                else getattr(b, "id", None)
            )
            if n:
                c.bases.append(n)
        model.classes[key] = c
        model.class_by_name.setdefault(node.name, []).append(key)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fkey = f"{key}.{item.name}"
                fn = Fn(fkey, mod, key, item.name, item, path,
                        is_method=True)
                model.funcs[fkey] = fn
                c.methods[item.name] = fn
                walk_fn(item, key, prefix=f"{prefix}{node.name}.")
            elif isinstance(item, ast.ClassDef):
                reg_class(item, prefix=f"{prefix}{node.name}.")

    def walk_fn(fn_node, cls_key, prefix):
        """Register nested defs/classes inside a function body."""
        for item in ast.walk(fn_node):
            if item is fn_node:
                continue
            if isinstance(item, ast.ClassDef):
                # nested handler classes (feed/health servers)
                if not any(
                    item.name == c.name and c.module == mod
                    for c in model.classes.values()
                ):
                    reg_class(item, prefix=f"{prefix}{fn_node.name}.")
            elif isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fkey = f"{mod}:{prefix}{fn_node.name}.{item.name}"
                if fkey not in model.funcs:
                    fn = Fn(fkey, mod, cls_key, item.name, item, path)
                    model.funcs[fkey] = fn
                    # nested defs also resolvable by bare name
                    model.module_funcs[mod].setdefault(item.name, fkey)

    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            reg_class(node, prefix="")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            key = f"{mod}:{node.name}"
            fn = Fn(key, mod, None, node.name, node, path)
            model.funcs[key] = fn
            model.module_funcs[mod][node.name] = key
            walk_fn(node, None, prefix="")
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    model.module_globals[mod].add(t.id)
                    if ctor_name(node.value) in _LOCK_CTORS:
                        model.lock_globals[mod].add(t.id)

    # imports anywhere in the file (function-local `import threading` is
    # common in hot-path modules) — first binding of a name wins
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                model.imports[mod].setdefault(
                    a.asname or a.name.split(".")[0], ("module", a.name)
                )
        elif isinstance(node, ast.ImportFrom):
            src = node.module or ""
            if node.level:
                parts = mod.split(".")
                base = parts[: max(0, len(parts) - node.level)]
                src = ".".join(base + ([src] if src else []))
            if not src:
                continue
            for a in node.names:
                model.imports[mod].setdefault(
                    a.asname or a.name, ("from", src, a.name)
                )


# ---------------------------------------------------------------------------
# pass B: resolution walk (two rounds to saturate attr/param types)
# ---------------------------------------------------------------------------


class _Resolver:
    """Walk one function body with an environment mapping names to
    resolutions and a lexical lockset, emitting resolved records."""

    def __init__(self, model, fn: Fn, env: dict, emit: bool):
        self.m = model
        self.fn = fn
        self.env = dict(env)
        self.emit = emit
        self.src = model.sources[fn.path]
        self.held: list[str] = []

    # -- expression resolution ---------------------------------------------

    def resolve(self, node):
        """-> ("instance", cls_key) | ("module", mod) | ("class", key) |
        ("fn", key) | ("lock", id) | None."""
        if isinstance(node, ast.Name):
            r = self.env.get(node.id)
            if r is not None:
                return r
            mod = self.fn.module
            if node.id in self.m.lock_globals.get(mod, ()):
                return ("lock", f"{mod}:{node.id}")
            imp = self.m.imports.get(mod, {}).get(node.id)
            if imp is not None:
                if imp[0] == "module":
                    return ("module", imp[1])
                src_mod, name = imp[1], imp[2]
                for k in self.m.class_by_name.get(name, ()):
                    if self.m.classes[k].module == src_mod:
                        return ("class", k)
                fk = self.m.module_funcs.get(src_mod, {}).get(name)
                if fk:
                    return ("fn", fk)
                if name in self.m.lock_globals.get(src_mod, ()):
                    return ("lock", f"{src_mod}:{name}")
                return ("module", src_mod)
            for k in self.m.class_by_name.get(node.id, ()):
                if self.m.classes[k].module == mod:
                    return ("class", k)
            fk = self.m.module_funcs.get(mod, {}).get(node.id)
            if fk:
                return ("fn", fk)
            return None
        if isinstance(node, ast.Attribute):
            base = self.resolve(node.value)
            if base is None:
                return None
            if base[0] == "instance":
                owner = self.m.attr_owner(base[1], node.attr)
                c = self.m.classes.get(owner)
                if c is not None:
                    if node.attr in c.lock_attrs:
                        return ("lock", f"{owner.split(':')[-1]}.{node.attr}")
                    ty = c.attr_types.get(node.attr)
                    if ty:
                        return ("instance", ty)
                meth = self.m.find_method(base[1], node.attr)
                if meth is not None:
                    return ("fn", meth.key)
                return None
            if base[0] == "module":
                mod = base[1]
                if node.attr in self.m.lock_globals.get(mod, ()):
                    return ("lock", f"{mod}:{node.attr}")
                for k in self.m.class_by_name.get(node.attr, ()):
                    if self.m.classes[k].module == mod:
                        return ("class", k)
                fk = self.m.module_funcs.get(mod, {}).get(node.attr)
                if fk:
                    return ("fn", fk)
                return None
            if base[0] == "class":
                meth = self.m.find_method(base[1], node.attr)
                if meth is not None:
                    return ("fn", meth.key)
            return None
        if isinstance(node, ast.Call):
            # with self.feed.locked(): -> the lock the method returns
            tgt = self.resolve(node.func)
            if tgt and tgt[0] == "fn":
                body = self.m.funcs[tgt[1]].node.body
                rets = [s for s in body if isinstance(s, ast.Return)]
                if len(rets) == 1 and rets[0].value is not None:
                    inner = _Resolver(
                        self.m, self.m.funcs[tgt[1]],
                        self._callee_env(self.m.funcs[tgt[1]]), emit=False,
                    )
                    r = inner.resolve(rets[0].value)
                    if r and r[0] in ("lock", "instance"):
                        return r
            if tgt and tgt[0] == "class":
                return ("instance", tgt[1])
            return None
        return None

    def _callee_env(self, fn: Fn):
        env = {}
        if fn.cls is not None:
            if fn.is_method and fn.node.args.args:
                env[fn.node.args.args[0].arg] = ("instance", fn.cls)
            elif not fn.is_method:
                # a def nested inside a method: `self` is a closure ref
                env["self"] = ("instance", fn.cls)
        for a in fn.node.args.args:
            ty = self.m.param_types.get((fn.key, a.arg))
            if ty:
                env[a.arg] = ("instance", ty)
        return env

    def resolve_fn_arg(self, node):
        """A callable expression (thread target / submit arg) -> fn keys."""
        if isinstance(node, ast.Lambda):
            if isinstance(node.body, ast.Call):
                return self.resolve_fn_arg(node.body.func)
            return []
        if isinstance(node, ast.Call):
            name = _callee_name(node.func)
            if name == "partial" and node.args:
                return self.resolve_fn_arg(node.args[0])
            return []
        r = self.resolve(node)
        if r and r[0] == "fn":
            return [r[1]]
        if r and r[0] == "class":  # callable class: its __call__ / __init__
            meth = self.m.find_method(r[1], "__call__")
            return [meth.key] if meth else []
        return []

    # -- variable identity --------------------------------------------------

    def var_of(self, node):
        """Attribute/Name node -> shared-variable id, or None (locals,
        sync primitives, unresolvable bases)."""
        if isinstance(node, ast.Attribute):
            base = self.resolve(node.value)
            if base is None:
                return None
            if base[0] == "instance":
                owner = self.m.attr_owner(base[1], node.attr)
                c = self.m.classes.get(owner)
                if c is not None and (node.attr in c.sync_attrs):
                    return None
                if self.m.find_method(base[1], node.attr) is not None:
                    return None
                return f"{owner.split(':')[-1]}.{node.attr}"
            if base[0] == "module":
                mod = base[1]
                if node.attr in self.m.lock_globals.get(mod, ()):
                    return None
                if self.m.module_funcs.get(mod, {}).get(node.attr):
                    return None
                if self.m.class_by_name.get(node.attr):
                    return None
                return f"{mod}:{node.attr}"
            return None
        if isinstance(node, ast.Name):
            mod = self.fn.module
            if node.id in self.env or node.id in self.m.imports.get(mod, {}):
                return None
            if node.id in self.m.module_globals.get(mod, ()):
                if node.id in self.m.lock_globals.get(mod, ()):
                    return None
                if node.id in self.m.module_funcs.get(mod, {}):
                    return None
                if self.m.class_by_name.get(node.id):
                    return None
                return f"{mod}:{node.id}"
        return None

    # -- emission -----------------------------------------------------------

    def _san(self, line):
        return _sanctions_at(self.src, line) | self.fn.sanctions_def

    def access(self, node, kind):
        if not self.emit:
            return
        var = self.var_of(node)
        if var is None:
            return
        self.fn.accesses.append((
            var, kind, frozenset(self.held), node.lineno, self._san(node.lineno)
        ))

    def acquire(self, lock_id, line):
        if self.emit:
            self.fn.acquires.append((
                lock_id, frozenset(self.held), line, self._san(line)
            ))

    def call(self, targets, line, callee_name):
        if self.emit and (targets or callee_name in TRACE_CALLEES):
            self.fn.calls.append((
                tuple(targets), frozenset(self.held), line, callee_name,
                self._san(line),
            ))

    # -- the walk -----------------------------------------------------------

    def walk(self):
        fn = self.fn
        self.env.update(self._callee_env(fn))
        fn.sanctions_def = _sanctions_at(self.src, fn.node.lineno)
        # `global` declarations make bare-Name stores global writes
        self.globals_decl = {
            n for s in ast.walk(fn.node) if isinstance(s, ast.Global)
            for n in s.names
        }
        self.walk_body(fn.node.body)

    def walk_body(self, stmts):
        for stmt in stmts:
            self.walk_stmt(stmt)

    def walk_stmt(self, stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate scopes: walked as their own Fn/Cls
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in stmt.items:
                self.visit_expr(item.context_expr)
                r = self.resolve(item.context_expr)
                if r and r[0] == "lock":
                    self.acquire(r[1], item.context_expr.lineno)
                    self.held.append(r[1])
                    pushed += 1
                if item.optional_vars is not None and r is not None:
                    for n in ast.walk(item.optional_vars):
                        if isinstance(n, ast.Name):
                            self.env[n.id] = r
            self.walk_body(stmt.body)
            for _ in range(pushed):
                self.held.pop()
            return
        if isinstance(stmt, ast.Assign):
            self.visit_expr(stmt.value)
            rhs = self.resolve(stmt.value)
            for t in stmt.targets:
                self.visit_target(t, rhs)
            self._special_assign(stmt, rhs)
            return
        if isinstance(stmt, ast.AugAssign):
            self.visit_expr(stmt.value)
            self.visit_expr(stmt.target, aug=True)
            self.visit_target(stmt.target, None)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.visit_expr(stmt.value)
                if stmt.target is not None:
                    self.visit_target(stmt.target, self.resolve(stmt.value))
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self.visit_expr(stmt.test)
            self.walk_body(stmt.body)
            self.walk_body(stmt.orelse)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.visit_expr(stmt.iter)
            self.walk_body(stmt.body)
            self.walk_body(stmt.orelse)
            return
        if isinstance(stmt, ast.Try):
            self.walk_body(stmt.body)
            for h in stmt.handlers:
                self.walk_body(h.body)
            self.walk_body(stmt.orelse)
            self.walk_body(stmt.finalbody)
            return
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            self.visit_expr(stmt.value)
            return
        if isinstance(stmt, ast.Expr):
            # linear acquire()/release() tracking
            v = stmt.value
            if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute):
                r = self.resolve(v.func.value)
                if r and r[0] == "lock":
                    if v.func.attr == "acquire":
                        self.acquire(r[1], v.lineno)
                        self.held.append(r[1])
                        return
                    if v.func.attr == "release":
                        if r[1] in self.held:
                            self.held.remove(r[1])
                        return
            self.visit_expr(v)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self.visit_expr(child)
            elif isinstance(child, ast.stmt):
                self.walk_stmt(child)

    def visit_target(self, t, rhs):
        if isinstance(t, ast.Name):
            if rhs is not None:
                self.env[t.id] = rhs
            elif t.id in self.env:
                del self.env[t.id]
            if t.id in getattr(self, "globals_decl", ()):
                self.access(t, "write")
        elif isinstance(t, ast.Attribute):
            self.access(t, "write")
            self.visit_expr(t.value)
        elif isinstance(t, ast.Subscript):
            # X[...] = v  mutates X
            if isinstance(t.value, (ast.Attribute, ast.Name)):
                self.access(t.value, "write")
            self.visit_expr(t.value)
            self.visit_expr(t.slice)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self.visit_target(e, None)

    def visit_expr(self, node, aug=False):
        if node is None:
            return
        if isinstance(node, ast.Call):
            self.visit_call(node)
            return
        if isinstance(node, ast.Attribute):
            self.access(node, "write" if aug else "read")
            self.visit_expr(node.value)
            return
        if isinstance(node, ast.Name):
            self.access(node, "write" if aug else "read")
            return
        if isinstance(node, ast.Lambda):
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self.visit_expr(child)

    # -- calls: resolution + thread-topology records ------------------------

    def visit_call(self, node):
        name = _callee_name(node.func)
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        if name == "Thread" and self._is_threading(node.func, "Thread"):
            self._record_thread(node, kw)
        elif name == "ThreadPoolExecutor":
            pass  # handled at the assignment (needs the target attr)
        elif name == "signal" and isinstance(node.func, ast.Attribute):
            self._record_signal(node)
        elif name == "submit" and isinstance(node.func, ast.Attribute):
            self._record_submit(node)
        elif name == "call_with_deadline" and node.args:
            tks = self.resolve_fn_arg(node.args[0])
            if self.emit and tks:
                self.m.deadline_targets.extend(tks)

        # mutating method call on a shared var is a write to it
        if (isinstance(node.func, ast.Attribute) and name in _MUTATORS
                and isinstance(node.func.value, (ast.Attribute, ast.Name))):
            self.access(node.func.value, "write")

        # resolve the callee for the call graph; record trace callees
        targets = []
        r = self.resolve(node.func)
        if r and r[0] == "fn":
            targets = [r[1]]
        elif r and r[0] == "class":
            init = self.m.find_method(r[1], "__init__")
            if init is not None:
                targets = [init.key]
            self._infer_param_types(r[1], node)
        self.call(targets, node.lineno, name)

        self.visit_expr(node.func.value if isinstance(
            node.func, ast.Attribute) else None)
        for a in node.args:
            self.visit_expr(a)
        for k in node.keywords:
            self.visit_expr(k.value)

    def _is_threading(self, func, which):
        if isinstance(func, ast.Name):
            imp = self.m.imports.get(self.fn.module, {}).get(func.id)
            return imp == ("from", "threading", which)
        if isinstance(func, ast.Attribute) and isinstance(
            func.value, ast.Name
        ):
            imp = self.m.imports.get(self.fn.module, {}).get(func.value.id)
            return imp is not None and imp[:2] == ("module", "threading")
        return False

    def _record_thread(self, node, kw):
        if not self.emit:
            return
        targets = self.resolve_fn_arg(kw["target"]) if "target" in kw else []
        # target self.<attr>.serve_forever: a threading server — the
        # entry's real bodies are the handler class's do_*/handle methods
        if not targets and "target" in kw and isinstance(
            kw["target"], ast.Attribute
        ) and kw["target"].attr == "serve_forever":
            targets = self._server_handlers(kw["target"].value)
        pat = _name_pattern(kw.get("name"))
        named = "name" in kw
        if pat is None:
            rel = _rel(self.fn.path)
            pat = f"anon@{rel}:{node.lineno}"
        self.m.threads.append((pat, targets, named, node.lineno, self.fn.path))

    def _server_handlers(self, server_expr):
        """self._httpd.serve_forever -> handler-class methods, via the
        `self._httpd = SomeServer(addr, Handler)` assignment."""
        if not (isinstance(server_expr, ast.Attribute)
                and self.resolve(server_expr.value)):
            return []
        base = self.resolve(server_expr.value)
        if base is None or base[0] != "instance":
            return []
        key = (base[1], server_expr.attr)
        hcls = self.m.servers.get(key)
        if hcls is None:
            return []
        c = self.m.classes.get(hcls)
        if c is None:
            return []
        keys = [fn.key for n, fn in c.methods.items()
                if n.startswith("do_") or n in ("handle", "_apply")]
        return keys or [fn.key for fn in c.methods.values()]

    def _record_signal(self, node):
        f = node.func
        if not (isinstance(f.value, ast.Name)
                and self.m.imports.get(self.fn.module, {}).get(f.value.id,
                                                               ())[:2]
                == ("module", "signal")):
            return
        if len(node.args) < 2 or not self.emit:
            return
        sig = node.args[0]
        signame = sig.attr if isinstance(sig, ast.Attribute) else "SIG"
        targets = self.resolve_fn_arg(node.args[1])
        self.m.signals.append(
            (signame, targets, node.lineno, self.fn.path)
        )

    def _record_submit(self, node):
        if not self.emit or not node.args:
            return
        base = node.func.value
        tks = self.resolve_fn_arg(node.args[0])
        if not tks:
            return
        if isinstance(base, ast.Attribute):
            b = self.resolve(base.value)
            if b and b[0] == "instance":
                owner = self.m.attr_owner(b[1], base.attr)
                key = (owner, base.attr)
                if key in self.m.pools:
                    self.m.pool_submits.setdefault(key, []).extend(tks)
                    return
        r = self.resolve(base)
        if r and r[0] == "instance":
            self.m.worker_submits.setdefault(r[1], []).extend(tks)

    def _special_assign(self, stmt, rhs):
        """Executor / server constructions need the assignment target."""
        if not self.emit:
            return
        val = stmt.value
        if isinstance(val, ast.IfExp):  # x = Pool(...) if flag else None
            val = val.body if isinstance(val.body, ast.Call) else val.orelse
        if not isinstance(val, ast.Call):
            return
        call = val
        cname = _callee_name(call.func)
        t = stmt.targets[0] if len(stmt.targets) == 1 else None
        attr_key = None
        if isinstance(t, ast.Attribute):
            b = self.resolve(t.value)
            if b and b[0] == "instance":
                attr_key = (self.m.attr_owner(b[1], t.attr), t.attr)
        if cname == "ThreadPoolExecutor" and attr_key:
            kw = {k.arg: k.value for k in call.keywords if k.arg}
            pat = _name_pattern(kw.get("thread_name_prefix"))
            self.m.pools[attr_key] = (
                f"{pat}*" if pat else
                f"pool@{_rel(self.fn.path)}:{call.lineno}"
            )
        elif cname and "server" in cname.lower() and attr_key:
            for a in call.args:
                r = self.resolve(a)
                if r and r[0] == "class":
                    self.m.servers[attr_key] = r[1]
                    break

    def _infer_param_types(self, cls_key, call):
        """HealthServer(self, ...) from a Daemon method: the constructor
        param gets the caller's instance type."""
        init = self.m.find_method(cls_key, "__init__")
        if init is None:
            return
        params = [a.arg for a in init.node.args.args][1:]
        for i, a in enumerate(call.args):
            r = self.resolve(a)
            if r and r[0] == "instance" and i < len(params):
                self.m.param_types[(init.key, params[i])] = r[1]


# ---------------------------------------------------------------------------
# model build
# ---------------------------------------------------------------------------


def _rel(path: Path) -> str:
    try:
        return Path(path).resolve().relative_to(REPO).as_posix()
    except ValueError:
        return Path(path).name


def build_model(paths) -> Model:
    model = Model()
    files = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        else:
            files.append(p)
    for f in files:
        source = f.read_text()
        tree = ast.parse(source, filename=str(f))
        model.files[f] = tree
        model.sources[f] = source.splitlines()
        model.modules[f] = _module_name(f)
    for f, tree in model.files.items():
        _collect_symbols(model, f, tree)
    _collect_attr_census(model)
    # two resolution rounds: round 1 saturates attr/param types (and is
    # thrown away), round 2 emits the final records
    for rnd in (0, 1):
        for fn in model.funcs.values():
            fn.accesses, fn.acquires, fn.calls = [], [], []
        model.threads, model.signals = [], []
        model.pools, model.pool_submits = {}, {}
        model.worker_submits, model.servers = {}, {}
        model.deadline_targets = []
        for fn in model.funcs.values():
            _Resolver(model, fn, {}, emit=True).walk()
    return model


# ---------------------------------------------------------------------------
# entry-point assembly
# ---------------------------------------------------------------------------


def discover_entries(model: Model) -> dict:
    """entry name -> {"targets": [fn keys], "kind": ..., "sites": [...]}."""
    entries: dict[str, dict] = {}

    def add(name, targets, kind, site=None):
        e = entries.setdefault(
            name, {"targets": [], "kind": kind, "sites": []}
        )
        for t in targets:
            if t not in e["targets"]:
                e["targets"].append(t)
        if site and site not in e["sites"]:
            e["sites"].append(site)

    for pat, targets, _named, line, path in model.threads:
        kind = "server" if any(
            model.funcs[t].cls and "Handler" in (model.funcs[t].cls or "")
            for t in targets
        ) else "thread"
        add(pat, targets, kind, f"{_rel(path)}:{line}")
    for key, prefix in model.pools.items():
        add(prefix, model.pool_submits.get(key, []), "pool")
    for cls_key, tks in model.worker_submits.items():
        # a worker class whose loop thread is an entry: submitted fns run
        # on that entry
        for pat, targets, _n, _l, _p in model.threads:
            if any(model.funcs[t].cls == cls_key for t in targets):
                add(pat, tks, "thread")
    for signame, targets, line, path in model.signals:
        add(f"signal:{signame}", targets, "signal", f"{_rel(path)}:{line}")
    if model.deadline_targets:
        for name in entries:
            if fnmatch.fnmatch(name, "wd-*"):
                add(name, model.deadline_targets, "thread")
                break
        else:
            if any(fnmatch.fnmatch(name, p) for name in entries
                   for p in ABANDONABLE_PATTERNS):
                pass
    mains = [
        k for mod, fns in model.module_funcs.items()
        for n, k in fns.items() if n == "main"
    ] + [m for m in MAIN_METHODS if m in model.funcs]
    if mains:
        add("main", mains, "main")
    return entries


# ---------------------------------------------------------------------------
# reachability + rules
# ---------------------------------------------------------------------------


class _EntryWalk:
    """Per-entry reachable access/acquire/trace-site sets with must-hold
    locksets (intersection over sites) and lock-order edges."""

    def __init__(self, model: Model, entry: str, targets):
        self.m = model
        self.entry = entry
        #: var -> kind -> [must-hold lockset (inter), sites, suppressed]
        self.vars: dict[str, dict] = {}
        self.acquired: dict[str, list] = {}   # lock -> sites (CA004)
        self.edges: set[tuple] = set()        # (held, acquired)
        self.edge_sites: dict[tuple, str] = {}
        self.trace: dict[str, dict] = {}      # site -> {"locks":, "name":}
        self.sanction_count = 0
        self.reached: set[str] = set()
        self._visited: set[tuple] = set()
        for t in targets:
            self._walk(t, frozenset(), frozenset())

    def _walk(self, fn_key, held, suppressed, depth=0):
        if depth > 64 or fn_key not in self.m.funcs:
            return
        state = (fn_key, held, suppressed)
        if state in self._visited or len(self._visited) > 200_000:
            return
        self._visited.add(state)
        self.reached.add(fn_key)
        fn = self.m.funcs[fn_key]
        sup_def = suppressed | fn.sanctions_def
        if fn.sanctions_def:
            self.sanction_count += 1
        for var, kind, locks, line, san in fn.accesses:
            if fn.is_init:
                continue  # construction happens-before thread start
            eff = held | locks
            sup = sup_def | san
            if san:
                self.sanction_count += 1
            rec = self.vars.setdefault(var, {})
            slot = rec.setdefault(
                kind, {"locks": None, "sites": [], "suppressed": set(RULES)}
            )
            slot["locks"] = eff if slot["locks"] is None else (
                slot["locks"] & eff
            )
            if len(slot["sites"]) < 4:
                slot["sites"].append(f"{_rel(fn.path)}:{line}")
            slot["suppressed"] &= sup
            # CA003: memo/cache attr writes are trace sites, keyed by
            # the memo var (every insertion site of one memo must share
            # a serializing lock)
            attr = var.rsplit(".", 1)[-1].rsplit(":", 1)[-1]
            if kind == "write" and ("cache" in attr or "memo" in attr):
                self._trace_site(var, f"{_rel(fn.path)}:{line}", eff, sup)
        for lock, locks, line, san in fn.acquires:
            eff = held | locks
            sup = sup_def | san
            if san:
                self.sanction_count += 1
            if "CA004" not in sup:
                self.acquired.setdefault(lock, []).append(
                    f"{_rel(fn.path)}:{line}"
                )
            if "CA002" not in sup:
                for h in eff:
                    if h != lock:
                        self.edges.add((h, lock))
                        self.edge_sites.setdefault(
                            (h, lock), f"{_rel(fn.path)}:{line}"
                        )
        for targets, locks, line, callee, san in fn.calls:
            eff = held | locks
            sup = sup_def | san
            if san:
                self.sanction_count += 1
            if callee in TRACE_CALLEES:
                # keyed by the traced program's NAME, not the call site:
                # the jit/trace cache is per-program, so two lock-free
                # call sites of one program race just as hard as one
                self._trace_site(callee, f"{_rel(fn.path)}:{line}",
                                 eff, sup)
            for t in targets:
                self._walk(t, eff, sup, depth + 1)

    def _trace_site(self, name, site, locks, suppressed):
        rec = self.trace.setdefault(
            name, {"site": site, "locks": None, "suppressed": set(RULES)}
        )
        rec["locks"] = locks if rec["locks"] is None else (
            rec["locks"] & locks
        )
        rec["suppressed"] &= suppressed


def analyze(model: Model, entries: dict) -> dict:
    walks = {
        name: _EntryWalk(model, name, spec["targets"])
        for name, spec in entries.items()
    }
    violations: list[dict] = []

    def add(rule, detail, **extra):
        violations.append({"rule": rule, "detail": detail, **extra})

    # -- CA001: unlocked cross-entry shared state ---------------------------
    all_vars = sorted({v for w in walks.values() for v in w.vars})
    for var in all_vars:
        flagged = False
        for e1, w1 in walks.items():
            wrec = w1.vars.get(var, {}).get("write")
            if wrec is None or "CA001" in wrec["suppressed"]:
                continue
            for e2, w2 in walks.items():
                if e2 == e1 or flagged:
                    continue
                for kind in ("read", "write"):
                    rec = w2.vars.get(var, {}).get(kind)
                    if rec is None or "CA001" in rec["suppressed"]:
                        continue
                    if (wrec["locks"] or frozenset()) & (
                        rec["locks"] or frozenset()
                    ):
                        continue
                    add(
                        "CA001",
                        f"{var!r} written on entry {e1!r} "
                        f"({wrec['sites'][0]}) and {kind} on entry {e2!r} "
                        f"({rec['sites'][0]}) with no common lock "
                        f"(must-hold {sorted(wrec['locks'] or ())} vs "
                        f"{sorted(rec['locks'] or ())})",
                        var=var, entries=sorted((e1, e2)),
                    )
                    flagged = True
                    break
            if flagged:
                break

    # -- CA002: lock-order inversion ----------------------------------------
    edges: dict[str, set] = {}
    sites: dict[tuple, str] = {}
    for w in walks.values():
        for a, b in w.edges:
            edges.setdefault(a, set()).add(b)
            sites.setdefault((a, b), w.edge_sites.get((a, b), "?"))
    seen_cycles = set()

    def dfs(start, node, path):
        for nxt in edges.get(node, ()):
            if nxt == start and len(path) >= 2:
                cyc = tuple(sorted(path))
                if cyc not in seen_cycles:
                    seen_cycles.add(cyc)
                    order = " -> ".join(path + [start])
                    where = ", ".join(
                        sites.get((path[i], path[(i + 1) % len(path)]), "?")
                        for i in range(len(path))
                    )
                    add(
                        "CA002",
                        f"lock-order cycle {order} (sites: {where}) — "
                        "potential deadlock",
                        locks=sorted(cyc),
                    )
            elif nxt not in path and len(path) < 6:
                dfs(start, nxt, path + [nxt])

    for a in sorted(edges):
        dfs(a, a, [a])

    # -- CA003: unserialized trace/memo programs ----------------------------
    trace_progs: dict[str, dict] = {}
    for e, w in walks.items():
        for name, rec in w.trace.items():
            if "CA003" in rec["suppressed"]:
                continue
            t = trace_progs.setdefault(
                name, {"site": rec["site"], "by": {}}
            )
            t["by"][e] = rec["locks"] or frozenset()
    for name, rec in sorted(trace_progs.items()):
        if len(rec["by"]) < 2:
            continue
        common = None
        for locks in rec["by"].values():
            common = locks if common is None else (common & locks)
        if common:
            continue
        add(
            "CA003",
            f"trace/memo program {name!r} (e.g. {rec['site']}) reachable "
            f"from entries {sorted(rec['by'])} with no common serializing "
            "lock (the _EXPLAIN_LOCK rule): concurrent tracing corrupts "
            "the jit cache",
            site=rec["site"], name=name, entries=sorted(rec["by"]),
        )

    # -- CA004: signal handlers reaching locks ------------------------------
    for e, w in walks.items():
        if not e.startswith("signal:"):
            continue
        other_locks = {
            lock for e2, w2 in walks.items() if e2 != e
            for lock in w2.acquired
        }
        for lock, lsites in sorted(w.acquired.items()):
            if lock in other_locks:
                add(
                    "CA004",
                    f"signal handler entry {e!r} acquires lock {lock!r} "
                    f"({lsites[0]}) also taken by other entries: the "
                    "handler can fire while the lock is held and "
                    "deadlock — handlers must only set Events",
                    lock=lock, entry=e,
                )

    # -- CA005: abandoned-worker writes -------------------------------------
    for e, w in walks.items():
        if not any(fnmatch.fnmatch(e, p) for p in ABANDONABLE_PATTERNS):
            continue
        for var in sorted(w.vars):
            rec = w.vars[var].get("write")
            if rec is None or "CA005" in rec["suppressed"]:
                continue
            add(
                "CA005",
                f"abandonable worker entry {e!r} writes {var!r} "
                f"({rec['sites'][0]}): after the deadline the orphaned "
                "worker keeps running — it may write only its own locals "
                "and its result box/Event (the PR 9 abandonment contract)",
                var=var, entry=e,
            )

    rule_counts = {r: 0 for r in RULES}
    for v in violations:
        rule_counts[v["rule"]] += 1
    lock_edges = sorted(f"{a} -> {b}" for a in edges for b in edges[a])
    return {
        "rules": rule_counts,
        "violations": violations,
        "lock_order_edges": lock_edges,
        "census": {
            "functions": len(model.funcs),
            "classes": len(model.classes),
            "entries": len(entries),
            "shared_vars": len(all_vars),
            "locks": len({
                lock for w in walks.values() for lock in w.acquired
            }),
            "sanctioned_sites": sum(
                w.sanction_count for w in walks.values()
            ),
        },
    }


def audit_paths(paths) -> dict:
    model = build_model(paths)
    entries = discover_entries(model)
    res = analyze(model, entries)
    res["entries"] = {
        name: {"kind": spec["kind"], "targets": sorted(spec["targets"])}
        for name, spec in sorted(entries.items())
    }
    return res


# ---------------------------------------------------------------------------
# driver (mirrors jaxpr_audit: fail-closed --check, committed manifest)
# ---------------------------------------------------------------------------


def run(paths=None, check: bool = False) -> int:
    paths = paths or [str(REPO / r) for r in DEFAULT_ROOTS]
    default_set = paths == [str(REPO / r) for r in DEFAULT_ROOTS]
    prior = {}
    if MANIFEST.exists():
        prior = json.loads(MANIFEST.read_text())
    res = audit_paths(paths)
    failures = [
        f"{v['rule']} {v['detail']}" for v in res["violations"]
    ]
    print(
        f"[race-audit] {res['census']['functions']} functions, "
        f"{res['census']['entries']} thread entry points, "
        f"{res['census']['shared_vars']} shared vars, "
        f"{sum(res['rules'].values())} violations",
        flush=True,
    )
    for name, spec in res["entries"].items():
        print(f"[race-audit]   entry {name!r} ({spec['kind']}): "
              f"{len(spec['targets'])} target(s)")

    manifest = {
        "tool": TOOL_VERSION,
        "rules": res["rules"],
        "entries": res["entries"],
        "lock_order_edges": res["lock_order_edges"],
        "census": res["census"],
    }

    if check and not prior:
        failures.append(
            "docs/race_audit.json missing: run `make race-audit` and "
            "commit it"
        )
    if check and prior:
        dirty = {r: c for r, c in prior.get("rules", {}).items() if c}
        if dirty:
            failures.append(f"manifest records violations: {dirty}")
        if prior.get("entries") != manifest["entries"]:
            missing = sorted(
                set(manifest["entries"]) - set(prior.get("entries", {}))
            )
            extra = sorted(
                set(prior.get("entries", {})) - set(manifest["entries"])
            )
            failures.append(
                "thread-entry table drift vs manifest "
                f"(new: {missing}, gone: {extra}) — intended? re-run "
                "`make race-audit` and commit docs/race_audit.json"
            )
        elif prior.get("tool") == TOOL_VERSION and (
            prior.get("census") != manifest["census"]
            or prior.get("lock_order_edges") != manifest["lock_order_edges"]
        ):
            failures.append(
                "concurrency census drift vs manifest — intended? re-run "
                "`make race-audit` and commit docs/race_audit.json"
            )

    if not check and default_set and not failures:
        MANIFEST.write_text(
            json.dumps(manifest, indent=1, sort_keys=True) + "\n"
        )
        print(f"[race-audit] wrote {MANIFEST.relative_to(REPO)}")
    elif not check:
        reason = "failures" if failures else "non-default path set"
        print(f"[race-audit] {reason}: manifest NOT rewritten")

    for f in failures:
        print(f"[race-audit] FAIL: {f}", file=sys.stderr)
    if not failures:
        print(
            f"[race-audit] OK: {res['census']['entries']} entry points "
            "audit clean (CA001-CA005)"
        )
    return 1 if failures else 0


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument(
        "--check", action="store_true",
        help="read-only: verify against the committed manifest",
    )
    parser.add_argument(
        "--paths", nargs="+", default=None,
        help="files/dirs to audit (default: the package)",
    )
    args = parser.parse_args(argv)
    return run(args.paths, check=args.check)


if __name__ == "__main__":
    sys.exit(main())
