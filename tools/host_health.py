"""Host-health probe: is this machine fit to produce trustworthy timings?

Benchmark numbers taken on a sick host (hung accelerator tunnel, load
spike from a co-tenant, thermal throttle) look exactly like code
regressions.  This probe produces one JSON line capturing the two
signals we have learned to distrust first (see CLAUDE.md "TPU
gotchas"):

  * a small timed matmul forced through a host transfer
    (``np.asarray`` — ``block_until_ready`` can return early through
    the axon tunnel), run in a daemon thread under a hard timeout so
    a dead tunnel reports ``probe_timeout`` instead of hanging the
    caller; and
  * 1-minute loadavg normalised by CPU count.

``make verify`` prints this line before the suite so every archived
log is self-describing, and tools/perf_sentry.py uses the same
``probe()`` to downgrade "regression" verdicts to "degraded-host"
when the host itself cannot be trusted.  rc is always 0 — a sick
host is a finding, not a failure of the probe.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

# Matmul wall-time above this (ms) marks the host degraded: on a healthy
# CPU backend an 8x8 float32 matmul plus transfer is far under 1s even
# with cold jit; multi-second times mean a wedged tunnel or a host under
# severe load.  Kept deliberately loose — the probe must never flag a
# merely busy-but-fine machine.
MATMUL_DEGRADED_MS = 2000.0
# 1-minute loadavg per core above this marks the host loaded.
LOAD_DEGRADED_PER_CPU = 4.0
DEFAULT_TIMEOUT_S = 30.0


def _timed_matmul(out: dict) -> None:
    import numpy as np
    import jax.numpy as jnp

    t0 = time.monotonic()
    # Host transfer, not block_until_ready: see CLAUDE.md TPU gotchas.
    res = np.asarray(jnp.ones((8, 8)) @ jnp.ones((8, 8)))
    out["matmul_ms"] = (time.monotonic() - t0) * 1000.0
    out["matmul_ok"] = bool(abs(float(res[0][0]) - 8.0) < 1e-6)


def probe(timeout_s: float = DEFAULT_TIMEOUT_S) -> dict:
    """Return a host-health dict; never raises, never hangs past timeout_s."""
    out: dict = {
        "probe": "host_health",
        "matmul_ms": None,
        "matmul_ok": False,
        "timeout_s": timeout_s,
    }
    th = threading.Thread(
        target=_timed_matmul, args=(out,), daemon=True,
        name="host-health-probe",
    )
    t0 = time.monotonic()
    th.start()
    th.join(timeout_s)
    if th.is_alive():
        out["error"] = "probe_timeout"
        out["matmul_ms"] = (time.monotonic() - t0) * 1000.0
    try:
        la1, la5, la15 = os.getloadavg()
    except OSError:  # pragma: no cover - platform without getloadavg
        la1 = la5 = la15 = -1.0
    ncpu = os.cpu_count() or 1
    out["loadavg_1m"] = round(la1, 3)
    out["loadavg_5m"] = round(la5, 3)
    out["cpu_count"] = ncpu
    out["load_per_cpu"] = round(la1 / ncpu, 4) if la1 >= 0 else None

    reasons = []
    if not out["matmul_ok"]:
        reasons.append(out.get("error", "matmul_failed"))
    elif out["matmul_ms"] is not None and out["matmul_ms"] > MATMUL_DEGRADED_MS:
        reasons.append("matmul_slow")
    if out["load_per_cpu"] is not None and out["load_per_cpu"] > LOAD_DEGRADED_PER_CPU:
        reasons.append("load_high")
    out["healthy"] = not reasons
    out["reasons"] = reasons
    if out["matmul_ms"] is not None:
        out["matmul_ms"] = round(out["matmul_ms"], 3)
    return out


def cost_arm_summary() -> dict | None:
    """The deterministic companion to a sick-host verdict (ISSUE 20):
    a one-block summary of the committed static-cost manifest
    (docs/cost_model.json).  Wall-clock numbers from this machine may be
    garbage, but the cost manifest digest is a pure function of the
    committed tree — so a degraded host still has a trustworthy perf
    statement ("the cost shape is X") and an algorithmic regression
    cannot hide behind (or be faked by) host sickness.  None when no
    manifest is committed; never raises."""
    try:
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        if repo not in sys.path:
            sys.path.insert(0, repo)
        from scheduler_plugins_tpu.obs import costmodel

        manifest = costmodel.load_manifest()
        if not manifest:
            return None
        programs = manifest.get("programs", {})
        return {
            "arm": "cost",
            "manifest_digest": costmodel.manifest_digest(manifest),
            "programs": len(programs),
            "static_only": sum(
                1 for r in programs.values() if r.get("static_only")
            ),
            "jax": manifest.get("jax"),
            "note": ("static cost is backend-independent: verdict a "
                     "suspect change with `perf_sentry.py cost` even "
                     "while this host is degraded"),
        }
    except Exception:
        return None


def main(argv: list[str] | None = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--timeout", type=float, default=DEFAULT_TIMEOUT_S,
        help="seconds to wait for the timed matmul before declaring the "
             "accelerator tunnel dead (default %(default)s)")
    ap.add_argument(
        "--cost-arm", action="store_true",
        help="attach the deterministic cost-arm summary "
             "(docs/cost_model.json digest) so a degraded-host line "
             "still carries a trustworthy perf statement")
    args = ap.parse_args(argv)
    out = probe(args.timeout)
    if args.cost_arm:
        out["cost_arm"] = cost_arm_summary()
    print(json.dumps(out, sort_keys=True))
    return 0


if __name__ == "__main__":
    sys.exit(main())
