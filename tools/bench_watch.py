"""Tunnel-resilient bench capture loop (VERDICT r2 item 1).

The axon TPU tunnel dies for hours at a time (CLAUDE.md "TPU gotchas"), and
both prior rounds ended with the driver's one-shot `python bench.py` hitting a
dead window (BENCH_r01/r02). This watcher turns capture into a continuous
background process: probe the backend cheaply, and whenever a healthy window
appears, run the BASELINE.md configs and append each JSON result — stamped
with a wall-clock time — to `BENCH_CAPTURES.jsonl` at the repo root.

`bench.py` then uses the newest matching capture as a clearly-labeled
fallback (`"stale_capture": true`, `"captured_unix": ...`) when the tunnel is
dead at the moment the driver runs it, so the round artifact carries a real
measured number either way. Every bench line is stamped with the JAX
backend/device-kind; replay filters out non-TPU (CPU fallback) captures.

Usage:  python tools/bench_watch.py [--interval 900] [--once] [--max-hours 11]
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAPTURES = os.path.join(REPO, "BENCH_CAPTURES.jsonl")


def log(msg):
    """Every probe/sweep line carries a wall-clock timestamp so a dead round
    is provable from the log alone (VERDICT r3: 8 untimestamped probes across
    a whole round is not a serious attempt)."""
    stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    print(f"{stamp} {msg}", flush=True)

#: (config, mode, per-run subprocess timeout seconds). Config 1 ignores mode.
#: Config 0 (tiny-shape smoke) runs FIRST: even a short healthy window then
#: yields *a* verified on-chip artifact (VERDICT r4 item 1a).
RUNS = [
    (0, "sequential", 420),
    (1, "sequential", 900),
    (2, "sequential", 900),
    (3, "sequential", 900),
    (4, "sequential", 900),
    (5, "sequential", 900),
    (2, "batch", 900),
    (3, "batch", 900),
    (4, "batch", 900),
    (5, "batch", 900),
    (6, "sequential", 1800),  # north-star 10k x 100k
]


def probe(timeout=75):
    sys.path.insert(0, REPO)
    import bench

    return bench.backend_probe(timeout=timeout)


def log_cost_arm():
    """Print the deterministic cost-arm statement beside a sick-probe
    verdict (ISSUE 20): a dead tunnel invalidates every timing this loop
    would have captured, but the committed static-cost digest is still a
    comparable trajectory point — and an algorithmic regression cannot
    hide behind the sick box (check it with `perf_sentry.py cost`)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import host_health

    arm = host_health.cost_arm_summary()
    if arm is None:
        log("[watch] cost arm: no committed cost manifest "
            "(run `make cost-audit`)")
    else:
        log(f"[watch] cost arm: manifest {arm['manifest_digest'][:12]} "
            f"({arm['programs']} programs, jax {arm['jax']}) — static "
            "trajectory point valid despite sick host")


def run_one(config, mode, timeout, trace_dir=None):
    cmd = [sys.executable, os.path.join(REPO, "bench.py"), "--config", str(config)]
    if config in (2, 3, 4, 5):
        cmd += ["--mode", mode]
    if trace_dir:
        cmd += ["--trace", trace_dir]
    try:
        proc = subprocess.run(
            cmd, timeout=timeout, capture_output=True, text=True, cwd=REPO
        )
    except subprocess.TimeoutExpired:
        return {"error": f"bench-timeout ({timeout}s)"}
    line = (proc.stdout or "").strip().splitlines()
    for ln in reversed(line):
        try:
            return json.loads(ln)
        except ValueError:
            continue
    tail = (proc.stderr or "").strip().splitlines()
    return {"error": "bench-failed: " + (tail[-1][:200] if tail else f"rc={proc.returncode}")}


def append(entry):
    with open(CAPTURES, "a") as f:
        f.write(json.dumps(entry) + "\n")


def cycle():
    """One full capture sweep; returns count of real (non-error) captures."""
    good = 0
    for config, mode, timeout in RUNS:
        diagnosis = probe()
        if diagnosis is not None:
            log(f"[watch] probe sick before config {config}: {diagnosis}")
            log_cost_arm()
            return good
        # on the first SUCCESSFUL flagship run, also dump a jax profiler
        # trace (op-level data for the next tuning round — VERDICT r4 item
        # 1b); a failed attempt removes its partial dir so the next cycle
        # retries instead of being suppressed forever
        trace_dir = os.path.join(REPO, ".profile_trace")
        want_trace = config == 1 and not os.path.exists(trace_dir)
        result = run_one(config, mode, timeout,
                         trace_dir=trace_dir if want_trace else None)
        if want_trace and ("error" in result or not result.get("value", 0)):
            import shutil

            shutil.rmtree(trace_dir, ignore_errors=True)
        entry = {"ts": time.time(), "config": config, "mode": mode, **result}
        append(entry)
        ok = "error" not in result and result.get("value", 0) > 0
        good += ok
        log(f"[watch] config {config}/{mode}: "
            f"{result.get('value', result.get('error'))}")
    return good


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=int, default=240,
                    help="seconds between probe attempts when sick / sweeps when healthy")
    ap.add_argument("--once", action="store_true")
    ap.add_argument("--max-hours", type=float, default=11.0)
    args = ap.parse_args()
    deadline = time.time() + args.max_hours * 3600
    sweeps = 0
    while time.time() < deadline:
        diagnosis = probe()
        if diagnosis is None:
            log("[watch] tunnel HEALTHY — starting capture sweep")
            n = cycle()
            sweeps += 1
            log(f"[watch] sweep {sweeps} done ({n} good captures)")
            if args.once:
                return
        else:
            log(f"[watch] tunnel sick: {diagnosis}")
            log_cost_arm()
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
