#!/usr/bin/env python
"""Flight-recorder replay + explain CLI, and the `make replay-smoke` gate.

Subcommands over bundles written by `utils.flightrec` (the daemon's
`--record/--record-dir`, `bench.py --record dir/`, or `FlightRecorder
.save`):

- `info BUNDLE` — list recorded cycles (digest, mode, batch size, placed).
- `replay BUNDLE [--cycle K]` — re-run recorded cycles offline through the
  bit-identical sequential parity path (`Scheduler.solve`) with the
  RECORDED aux arrays bound, and diff placements. A sequential-mode record
  that fails to replay bit-identically is an error (rc 1); wave-mode
  records (batch/streamed) report their diff as evidence (soft
  tie-breaking may differ) without failing.
- `explain BUNDLE --uid UID [--cycle K] [--top N] [--batched]` — the
  per-plugin score table for one recorded pod (the upstream `--v=10`
  score dump): per-plugin weighted normalized columns, built-in fit
  margin, winner gap.
- `quality BUNDLE` — placement-quality objectives (`tuning.quality`:
  fragmentation, utilization imbalance, gang wait, unplaced fraction;
  corpus-level gang admission latency when gangs are recorded) for every
  recorded cycle's placements, diffed against the per-cycle stamp
  `run_cycle` recorded when one exists.
- `smoke` — the CI gate (`make replay-smoke`): record a reduced bench
  cycle through the REAL `run_cycle` hooks, save/load the bundle, replay
  it (diff must be empty), validate the explain JSON against
  `EXPLAIN_SCHEMA`, check the explain columns sum to the solver's total,
  and bound recorder-enabled overhead the same way tools/trace_smoke.py
  bounds tracer overhead: interleaved off/on medians,
  ≤ max(SPT_RECORD_BOUND_PCT [default 2%], the off series' p10-p90
  spread).

One JSON line per action on stdout; rc 1 on any failure.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # `python tools/replay.py` from anywhere
    sys.path.insert(0, str(REPO))

#: reduced gang+quota roster shape for the smoke gate: big enough that a
#: cycle is not pure dispatch overhead, small enough for a 2-core runner
SMOKE_SHAPE = dict(n_gangs=4, gang_size=8, n_nodes=64)
#: interleaved off/on pairs. 17 (was 7): the overhead statistic is the
#: median of PAIRED deltas, and on a noisy 2-core host a 7-pair median
#: flaked at ~13% both ways (PR 7 notes it failed identically on
#: pre-PR HEAD) — more pairs + pairing makes the gate measure the
#: recorder, not the host's scheduler jitter
SMOKE_RUNS = 17


# ---------------------------------------------------------------------------
# explain JSON schema (stdlib check — no jsonschema dependency)
# ---------------------------------------------------------------------------

#: field -> allowed types (None in the tuple = nullable)
EXPLAIN_SCHEMA = {
    "uid": (str,),
    "cycle": (int, None),
    "pod_index": (int,),
    "profile": (str,),
    "path": (str,),
    "admitted": (bool,),
    "placed": (bool, None),
    "assigned": (str, None),
    "failed_plugin": (str, None),
    "winner": (str, None),
    "winner_total": (int, None),
    "runner_up_gap": (int, None),
    "weights": (dict,),
    "candidates": (list,),
}

CANDIDATE_SCHEMA = {
    "node": (str,),
    "total": (int,),
    "gap_to_winner": (int, None),
    "feasible": (bool,),
    "fit_margin": (int, None),
    "scores": (dict,),
}


def _check_fields(obj: dict, schema: dict, where: str) -> list[str]:
    errors = []
    for field, types in schema.items():
        if field not in obj:
            errors.append(f"{where}: missing field {field!r}")
            continue
        value = obj[field]
        if value is None:
            if None not in types:
                errors.append(f"{where}.{field}: unexpected null")
            continue
        concrete = tuple(t for t in types if t is not None)
        # bool is an int subclass: reject bools where ints are expected
        if isinstance(value, bool) and bool not in concrete:
            errors.append(f"{where}.{field}: bool where {concrete} expected")
        elif not isinstance(value, concrete):
            errors.append(
                f"{where}.{field}: {type(value).__name__} not in "
                f"{[t.__name__ for t in concrete]}"
            )
    return errors


def validate_explain(obj) -> list[str]:
    """Structural errors in one explain JSON object (empty list = valid).
    Shared by the smoke gate and tests/test_explain.py."""
    if not isinstance(obj, dict):
        return ["explain payload is not an object"]
    errors = _check_fields(obj, EXPLAIN_SCHEMA, "explain")
    for name, weight in (obj.get("weights") or {}).items():
        if not isinstance(name, str) or isinstance(weight, bool) or not (
            isinstance(weight, int)
        ):
            errors.append(f"explain.weights[{name!r}]: not str -> int")
    candidates = obj.get("candidates")
    if isinstance(candidates, list):
        if not candidates:
            errors.append("explain.candidates: empty")
        for i, cand in enumerate(candidates):
            if not isinstance(cand, dict):
                errors.append(f"candidates[{i}]: not an object")
                continue
            errors += _check_fields(cand, CANDIDATE_SCHEMA, f"candidates[{i}]")
            scores = cand.get("scores")
            if isinstance(scores, dict):
                if set(scores) != set(obj.get("weights") or {}):
                    errors.append(
                        f"candidates[{i}].scores: plugin set != weights set"
                    )
                # the tentpole invariant: columns sum to the total
                if all(
                    isinstance(v, int) and not isinstance(v, bool)
                    for v in scores.values()
                ) and isinstance(cand.get("total"), int):
                    if sum(scores.values()) != cand["total"]:
                        errors.append(
                            f"candidates[{i}]: score columns sum "
                            f"{sum(scores.values())} != total {cand['total']}"
                        )
    return errors


# ---------------------------------------------------------------------------
# subcommands
# ---------------------------------------------------------------------------


def _cost_stamp_drift(bundle: str) -> dict | None:
    """Compare the bundle's recorded static-cost provenance (`cost.json`,
    written by flightrec.save) against the CURRENT docs/cost_model.json:
    a digest mismatch means the bundle was recorded under a program with
    a different cost shape — replay numbers then compare an old
    algorithm against new expectations. None when the bundle predates
    the stamp (old bundles stay loadable)."""
    import os

    from scheduler_plugins_tpu.obs import costmodel

    path = os.path.join(bundle, "cost.json")
    try:
        with open(path) as f:
            recorded = json.load(f)
    except (OSError, ValueError):
        return None
    current = costmodel.load_manifest()
    if not current:
        return {"recorded_digest": recorded.get("manifest_digest"),
                "current_digest": None, "drifted": None,
                "warning": "no committed cost manifest to compare against"}
    cur_digest = costmodel.manifest_digest(current)
    drifted = cur_digest != recorded.get("manifest_digest")
    out = {
        "recorded_digest": recorded.get("manifest_digest"),
        "current_digest": cur_digest,
        "drifted": drifted,
    }
    if drifted:
        cur_p = {n: r.get("cost_digest")
                 for n, r in current.get("programs", {}).items()}
        rec_p = recorded.get("programs", {})
        out["changed_programs"] = sorted(
            n for n in set(cur_p) | set(rec_p) if cur_p.get(n) != rec_p.get(n)
        )
        out["warning"] = (
            "bundle was recorded under a program with a different cost "
            "shape — replay compares an old algorithm against the "
            "current tree"
        )
    return out


def cmd_info(args) -> int:
    from scheduler_plugins_tpu.utils import flightrec

    cycles = flightrec.load_bundle(args.bundle)
    out = []
    for lc in cycles:
        m = lc.manifest
        outputs = m.get("outputs") or {}
        out.append({
            "cycle": m["cycle"],
            "digest": m.get("digest"),
            "digest_ok": lc.digest_ok(),
            "profile": m.get("profile"),
            "mode": outputs.get("mode"),
            "pods": len(m.get("meta", {}).get("pod_names", [])),
            "nodes": len(m.get("meta", {}).get("node_names", [])),
            "seed": m.get("seed"),
            "complete": m.get("complete"),
        })
    print(json.dumps({"bundle": args.bundle, "cycles": out,
                      "cost_shape": _cost_stamp_drift(args.bundle)}))
    return 0


def cmd_replay(args) -> int:
    from scheduler_plugins_tpu.utils import flightrec

    cycles = flightrec.load_bundle(args.bundle)
    if args.cycle is not None:
        cycles = [c for c in cycles if c.manifest["cycle"] == args.cycle]
        if not cycles:
            print(json.dumps({"error": f"cycle {args.cycle} not in bundle"}))
            return 1
    failed = False
    results = []
    for lc in cycles:
        out = flightrec.replay_cycle(lc)
        public = {k: v for k, v in out.items() if not k.startswith("_")}
        # bit-identical replay is the CONTRACT for sequential records; a
        # wave-mode record's diff is evidence of soft tie-break drift
        must_match = out["mode"] == "sequential"
        ok = (
            out["digest_ok"]
            and (out["placements_match"] or not must_match)
        )
        public["ok"] = ok
        failed |= not ok
        results.append(public)
    print(json.dumps({"bundle": args.bundle, "replays": results,
                      "ok": not failed}))
    return 1 if failed else 0


def cmd_explain(args) -> int:
    from scheduler_plugins_tpu.utils import flightrec

    cycles = flightrec.load_bundle(args.bundle)
    chosen = None
    for lc in reversed(cycles):
        if args.cycle is not None and lc.manifest["cycle"] != args.cycle:
            continue
        if args.uid in lc.manifest.get("meta", {}).get("pod_names", []):
            chosen = lc
            break
    if chosen is None:
        print(json.dumps({
            "error": f"uid {args.uid!r} not found in bundle"
            + (f" cycle {args.cycle}" if args.cycle is not None else "")
        }))
        return 1
    table = flightrec.explain_record(
        chosen, args.uid, top_k=args.top, batched=args.batched
    )
    errors = validate_explain(table)
    table["schema_errors"] = errors
    print(json.dumps(table))
    return 1 if errors else 0


def cmd_timeline(args) -> int:
    """Reconstruct one pod's cross-cycle lifecycle story from a bundle's
    pod-ledger segment (`ledger.json`, written by FlightRecorder.save
    when the obs.ledger was live): events with (cycle, lane, seq)
    coordinates, the per-stage latency decomposition and the observing
    cycles' meta. Without --uid, prints the bundle's SLI summary and the
    recorded uids instead."""
    import os

    path = os.path.join(args.bundle, "ledger.json")
    if not os.path.exists(path):
        print(json.dumps({
            "error": "bundle has no ledger.json (the pod-lifecycle "
                     "ledger was disabled when the bundle was saved)"
        }))
        return 1
    with open(path) as f:
        export = json.load(f)
    records = export.get("retired", []) + export.get("live", [])
    if not args.uid:
        print(json.dumps({
            "bundle": args.bundle,
            "sli": export.get("sli"),
            "pods": [
                {"uid": r["uid"], "outcome": r["outcome"],
                 "e2e_ms": r["e2e_ms"], "attempts": r["attempts"]}
                for r in records
            ],
        }))
        return 0
    rec = next((r for r in records if r["uid"] == args.uid), None)
    if rec is None:
        print(json.dumps(
            {"error": f"uid {args.uid!r} not in the bundle's ledger"}
        ))
        return 1
    cycles = {m["cycle"]: m for m in export.get("cycles", [])}
    rec = dict(rec)
    rec["cycles"] = [
        cycles[c] for c in sorted({e["cycle"] for e in rec["events"]})
        if c in cycles
    ]
    # the decomposition invariant, re-checked on the persisted copy (ms
    # floats survive the ns->ms conversion exactly for any realistic
    # lifetime: both sides are the same sums scaled by 1e-6)
    if rec["e2e_ms"] is not None:
        rec["stages_sum_ms"] = sum(rec["stages_ms"].values())
        rec["decomposition_exact"] = (
            abs(rec["stages_sum_ms"] - rec["e2e_ms"]) < 1e-6
        )
    print(json.dumps(rec))
    return 0


def cmd_quality(args) -> int:
    """Quality objectives over a bundle's recorded placements (the jitted
    `tuning.quality` tensor core; `tools/tune.py` owns the shared
    implementation so the tuner and this view cannot diverge)."""
    from tools.tune import bundle_quality

    out = bundle_quality(args.bundle)
    mismatched = [
        row["cycle"] for row in out["cycles"]
        if row.get("matches_recorded") is False
    ]
    out["ok"] = not mismatched
    print(json.dumps(out))
    return 1 if mismatched else 0


# ---------------------------------------------------------------------------
# the CI gate
# ---------------------------------------------------------------------------


def _smoke_cluster():
    """A fresh seed-0 cluster per cycle (run_cycle binds its pending pods,
    so a cluster is single-use here); the Scheduler is built ONCE and
    shared across cycles so every measured cycle hits the jit cache — the
    overhead bound must compare recorder capture against a warm solve,
    not against trace+compile noise that would swamp any regression."""
    import bench

    cluster, plugins, _ = bench.config_problem(4, shape=SMOKE_SHAPE)
    return cluster, plugins


def cmd_smoke(args) -> int:
    import numpy as np

    import bench
    from scheduler_plugins_tpu.framework import run_cycle
    from scheduler_plugins_tpu.utils import flightrec

    bench.apply_platform_override()
    bound_pct = float(os.environ.get("SPT_RECORD_BOUND_PCT", 2.0))
    out_dir = args.out or os.path.join(
        tempfile.mkdtemp(prefix="replay_smoke_"), "bundle"
    )

    from scheduler_plugins_tpu.framework import Profile, Scheduler

    _, plugins = _smoke_cluster()
    scheduler = Scheduler(Profile(plugins=plugins))

    def one_cycle():
        cluster, _plugins = _smoke_cluster()
        start = time.perf_counter()
        report = run_cycle(scheduler, cluster, now=1000)
        return time.perf_counter() - start, report

    one_cycle()  # compile warmup (recorder off; later cycles hit the cache)
    # recorder-path warmup: the FIRST capture pays lazy imports (struct
    # registry, digest machinery) that are one-time process cost, not
    # per-cycle recorder overhead — keep them out of the measured pairs
    flightrec.recorder.start(capacity=2)
    flightrec.recorder.seed = 0  # config_problem scenarios are seed-0
    one_cycle()

    # interleaved off/on pairs: drift hits both arms of a pair equally,
    # so the overhead statistic is the MEDIAN OF PAIRED deltas — robust
    # to the 2-core host's scheduler jitter in a way two independent
    # medians are not (the pre-fix gate flaked at ~13% both directions)
    off, on, pair_pct = [], [], []
    report = None
    for _ in range(SMOKE_RUNS):
        flightrec.recorder.stop()
        t_off, _r = one_cycle()
        off.append(t_off)
        flightrec.recorder.start(capacity=2)
        flightrec.recorder.seed = 0
        t_on, report = one_cycle()
        on.append(t_on)
        pair_pct.append(100.0 * (t_on - t_off) / t_off)
    median_off = sorted(off)[len(off) // 2]
    median_on = sorted(on)[len(on) // 2]
    overhead_pct = sorted(pair_pct)[len(pair_pct) // 2]
    # noise floor: the off series' own p10-p90 spread — overhead below
    # the run's jitter is not attributable to the recorder
    off_sorted = sorted(off)
    spread_pct = 100.0 * (
        off_sorted[int(0.9 * (len(off) - 1))]
        - off_sorted[int(0.1 * (len(off) - 1))]
    ) / median_off
    bound = max(bound_pct, spread_pct)

    # save the LAST recorded cycle and round-trip it
    save = flightrec.recorder.save(out_dir)
    flightrec.recorder.stop()
    cycles = flightrec.load_bundle(out_dir)
    replay = flightrec.replay_cycle(cycles[-1])
    replay_ok = (
        replay["digest_ok"]
        and replay["placements_match"]
        and replay["aux_match"]
        and replay["mode"] == "sequential"
    )

    # explain a failed pod when the cycle had one, else the first pod;
    # schema validation includes the columns-sum-to-total invariant
    pod_names = cycles[-1].manifest["meta"]["pod_names"]
    uid = (report.failed[0] if report and report.failed else pod_names[0])
    table = flightrec.explain_record(cycles[-1], uid)
    schema_errors = validate_explain(table)

    ok = (
        replay_ok
        and not schema_errors
        and overhead_pct <= bound
        and bool(report.bound)
    )
    print(json.dumps({
        "metric": "replay_smoke",
        "off_cycle_ms": round(median_off * 1000, 2),
        "on_cycle_ms": round(median_on * 1000, 2),
        "overhead_pct": round(overhead_pct, 2),
        "bound_pct": round(bound, 2),
        "noise_floor_pct": round(spread_pct, 2),
        "bundle": save,
        "replay": {k: v for k, v in replay.items()
                   if not k.startswith("_")},
        "replay_ok": replay_ok,
        "explain_uid": uid,
        "explain_schema_errors": schema_errors[:5],
        "ok": ok,
    }))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tools/replay.py", description=__doc__)
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_info = sub.add_parser("info", help="list a bundle's recorded cycles")
    p_info.add_argument("bundle")
    p_replay = sub.add_parser(
        "replay", help="re-run recorded cycles through Scheduler.solve "
        "and diff placements"
    )
    p_replay.add_argument("bundle")
    p_replay.add_argument("--cycle", type=int, default=None)
    p_explain = sub.add_parser(
        "explain", help="per-plugin score table for one recorded pod"
    )
    p_explain.add_argument("bundle")
    p_explain.add_argument("--uid", required=True)
    p_explain.add_argument("--cycle", type=int, default=None)
    p_explain.add_argument("--top", type=int, default=5)
    p_explain.add_argument("--batched", action="store_true",
                           help="derive columns through the batched "
                                "solver's class-collapsed row hooks")
    p_quality = sub.add_parser(
        "quality", help="placement-quality objectives for every recorded "
        "cycle (tuning.quality)"
    )
    p_quality.add_argument("bundle")
    p_timeline = sub.add_parser(
        "timeline", help="one pod's cross-cycle lifecycle story from the "
        "bundle's pod-ledger segment (ledger.json)"
    )
    p_timeline.add_argument("bundle")
    p_timeline.add_argument("--uid", default=None,
                            help="pod uid (omit to list recorded pods + "
                                 "the bundle's SLI summary)")
    p_smoke = sub.add_parser("smoke", help="the make replay-smoke CI gate")
    p_smoke.add_argument("--out", default=None,
                         help="bundle output dir (default: temp dir)")
    args = ap.parse_args(argv)
    return {
        "info": cmd_info,
        "replay": cmd_replay,
        "explain": cmd_explain,
        "quality": cmd_quality,
        "timeline": cmd_timeline,
        "smoke": cmd_smoke,
    }[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
