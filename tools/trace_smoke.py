#!/usr/bin/env python
"""Trace smoke gate (`make trace-smoke`): the cycle tracer must (a) emit a
Perfetto-loadable trace covering the framework extension-point spans AND
the chunk pipeline's H2D/solve/D2H rows, and (b) cost ≤ the overhead bound
when enabled.

Two measured series on a REDUCED north-star shape (the same
`bench.north_star_chunk_solver` program, smaller tensors), interleaved
tracing-off / tracing-on so drift hits both equally; medians compared.
The bound is `max(SPT_TRACE_BOUND_PCT [default 2%], the tracing-off
series' own p10-p90 spread)` — the 2% target is the acceptance criterion
at north-star scale, and the spread floor keeps a sub-100ms CI-runner run
from failing on scheduler jitter the tracer didn't cause. Overhead here is
strictly conservative vs the north star: the reduced shape does LESS
device work per span, so the tracer's per-span cost is a LARGER fraction
of the wall clock than it is at 10k x 102k.

Trace validation (`validate_trace`, reused by tests/test_observability.py):
JSON with a `traceEvents` list, phases only X/B/E/M (Perfetto's
chrome-trace subset), numeric non-negative ts/dur, B/E stack-paired per
tid, and per-tid X spans either disjoint or properly nested — plus the
pipeline rows and at least one framework extension-point span present.

One JSON line on stdout; rc 1 on any failure.
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # `python tools/trace_smoke.py` from anywhere
    sys.path.insert(0, str(REPO))

#: reduced north-star shape: big enough that a run is not pure dispatch
#: overhead, small enough for a 2-core CI runner
SMOKE_SHAPE = dict(n_nodes=256, n_pods=4096, chunk=512)
RUNS = 9


# ---------------------------------------------------------------------------
# trace validation (shared with tests)
# ---------------------------------------------------------------------------


def validate_trace(trace) -> list[str]:
    """Structural errors in a Chrome-trace-event / Perfetto JSON dict
    (empty list = valid)."""
    errors: list[str] = []
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    open_stacks: dict = {}
    spans_per_tid: dict = {}
    for i, e in enumerate(events):
        ph = e.get("ph")
        if ph not in ("X", "B", "E", "M"):
            errors.append(f"event {i}: phase {ph!r} not in X/B/E/M")
            continue
        if "name" not in e or "pid" not in e or "tid" not in e:
            errors.append(f"event {i}: missing name/pid/tid")
            continue
        if ph == "M":
            continue
        ts = e.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"event {i}: bad ts {ts!r}")
            continue
        key = (e["pid"], e["tid"])
        if ph == "B":
            open_stacks.setdefault(key, []).append(e["name"])
        elif ph == "E":
            stack = open_stacks.get(key)
            if not stack:
                errors.append(f"event {i}: E without matching B on {key}")
            else:
                stack.pop()
        else:  # X
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"event {i}: bad dur {dur!r}")
                continue
            spans_per_tid.setdefault(key, []).append((ts, ts + dur, e["name"]))
    for key, stack in open_stacks.items():
        if stack:
            errors.append(f"unclosed B events on {key}: {stack}")
    # per-tid spans must be timeline-renderable: sorted by start they are
    # pairwise either disjoint or properly nested (no partial overlap)
    for key, spans in spans_per_tid.items():
        spans.sort()
        active: list[tuple] = []
        for start, end, name in spans:
            while active and active[-1][1] <= start:
                active.pop()
            if active and end > active[-1][1]:
                errors.append(
                    f"tid {key}: span {name!r} [{start},{end}] partially "
                    f"overlaps {active[-1][2]!r} [{active[-1][0]},"
                    f"{active[-1][1]}]"
                )
            active.append((start, end, name))
    return errors


#: rows the concurrent cycle pipeline emits per tick
#: (framework.pipeline_cycle: ingest/dispatch+fence/overlap-finalize on
#: the main thread, bind/post-bind on the flusher row)
PIPELINED_CYCLE_ROWS = (
    "Cycle/ingest", "Cycle/solve", "Cycle/finalize", "Cycle/bind",
)


def required_rows(trace, extra=()) -> list[str]:
    """Rows the tentpole promises: pipeline H2D/solve/D2H per buffer and a
    framework extension-point row, plus any caller-required `extra` rows
    (the gate adds `PIPELINED_CYCLE_ROWS`). Returns the MISSING rows."""
    names = {
        e["args"]["name"]
        for e in trace.get("traceEvents", ())
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    missing = [
        row
        for row in (
            "pipeline/h2d/buf0", "pipeline/h2d/buf1",
            "pipeline/solve/buf0", "pipeline/solve/buf1",
            "pipeline/d2h/buf0", "pipeline/d2h/buf1",
            "framework",
            *extra,
        )
        if row not in names
    ]
    return missing


# ---------------------------------------------------------------------------
# the gate
# ---------------------------------------------------------------------------


def _pipeline_run(solve_chunk, raw, node_mask, chunk_inputs, snap):
    """One pipeline pass over the reduced shape; returns (elapsed_s,
    timeline). The free carry is rebuilt per run (it is DONATED)."""
    from scheduler_plugins_tpu.ops.fit import free_capacity
    from scheduler_plugins_tpu.parallel.pipeline import run_chunk_pipeline

    free = free_capacity(snap.nodes.alloc, snap.nodes.requested)
    start = time.perf_counter()
    results, free, _, timeline = run_chunk_pipeline(
        solve_chunk, (raw, node_mask), chunk_inputs, free
    )
    # pipeline results are already host numpy (device_get)
    return time.perf_counter() - start, timeline, results


def main(out_path=None, bound_pct=None):
    import numpy as np

    import bench
    from scheduler_plugins_tpu.utils import observability as obs

    bench.apply_platform_override()
    if bound_pct is None:
        bound_pct = float(os.environ.get("SPT_TRACE_BOUND_PCT", 2.0))
    out_path = out_path or os.environ.get(
        "SPT_TRACE_OUT", "/tmp/trace_smoke.json"
    )

    shape = SMOKE_SHAPE
    _, snap, meta, weights, raw, padded = bench.north_star_problem(
        shape["n_nodes"], shape["n_pods"], shape["chunk"]
    )
    node_mask = snap.nodes.mask
    solve_chunk = bench.north_star_chunk_solver()
    req_np = np.asarray(snap.pods.req)
    mask_np = np.asarray(snap.pods.mask)
    chunk = shape["chunk"]
    chunk_inputs = [
        (req_np[lo:lo + chunk], mask_np[lo:lo + chunk])
        for lo in range(0, padded, chunk)
    ]

    obs.tracer.stop()
    _pipeline_run(solve_chunk, raw, node_mask, chunk_inputs, snap)  # compile

    off, on = [], []
    final_trace = None
    for _ in range(RUNS):
        obs.tracer.stop()
        t, _, _ = _pipeline_run(solve_chunk, raw, node_mask, chunk_inputs,
                                snap)
        off.append(t)
        obs.tracer.start(clear=True)
        t, _, _ = _pipeline_run(solve_chunk, raw, node_mask, chunk_inputs,
                                snap)
        on.append(t)
        final_trace = None  # events live in the tracer until exported

    # one traced scheduling cycle on a tiny cluster adds the framework
    # extension-point rows to the exported trace (tracer still running)
    from scheduler_plugins_tpu.api.objects import Container, Node, Pod
    from scheduler_plugins_tpu.api.resources import CPU, MEMORY, PODS
    from scheduler_plugins_tpu.framework import Profile, Scheduler, run_cycle
    from scheduler_plugins_tpu.plugins import NodeResourcesAllocatable
    from scheduler_plugins_tpu.state.cluster import Cluster

    gib = 1 << 30
    cluster = Cluster()
    for i in range(8):
        cluster.add_node(Node(
            name=f"n{i}",
            allocatable={CPU: 16000, MEMORY: 64 * gib, PODS: 110},
        ))
    for p in range(32):
        cluster.add_pod(Pod(
            name=f"p{p}", creation_ms=p,
            containers=[Container(requests={CPU: 500, MEMORY: gib})],
        ))
    cluster.add_pod(Pod(
        name="too-big", creation_ms=99,
        containers=[Container(requests={CPU: 10 ** 9})],
    ))
    report = run_cycle(
        Scheduler(Profile(plugins=[NodeResourcesAllocatable()])), cluster,
        now=0,
    )
    # two pipelined ticks on a fresh serve-mode cluster add the
    # concurrent-cycle rows (Cycle/{ingest,solve,finalize,bind}) to the
    # exported trace — the overlap stages the tentpole promises are
    # observable, and their spans must stay Perfetto-valid alongside the
    # serial spans (the bind row is emitted from the flusher thread)
    from scheduler_plugins_tpu.framework import PipelinedCycle
    from scheduler_plugins_tpu.serving import StreamingServeEngine

    pcluster = Cluster()
    for i in range(8):
        pcluster.add_node(Node(
            name=f"pn{i}",
            allocatable={CPU: 16000, MEMORY: 64 * gib, PODS: 110},
        ))
    for p in range(8):
        pcluster.add_pod(Pod(
            name=f"pp{p}", creation_ms=p,
            containers=[Container(requests={CPU: 500, MEMORY: gib})],
        ))
    engine = StreamingServeEngine().attach(pcluster)
    pipe = PipelinedCycle(
        Scheduler(Profile(plugins=[NodeResourcesAllocatable()])),
        pcluster, serve=engine,
    )
    pipe.tick(now=1000)
    pcluster.add_pod(Pod(
        name="pp9", creation_ms=20,
        containers=[Container(requests={CPU: 500, MEMORY: gib})],
    ))
    pipe.tick(now=2000)
    pipe.close()
    obs.tracer.stop()
    obs.tracer.write(out_path)
    with open(out_path) as f:
        final_trace = json.load(f)

    median_off = sorted(off)[len(off) // 2]
    median_on = sorted(on)[len(on) // 2]
    overhead_pct = 100.0 * (median_on - median_off) / median_off
    # noise floor: the tracing-off series' own p10-p90 spread — overhead
    # below the run-to-run jitter is not attributable to the tracer
    off_sorted = sorted(off)
    spread_pct = 100.0 * (
        off_sorted[int(0.9 * (len(off) - 1))]
        - off_sorted[int(0.1 * (len(off) - 1))]
    ) / median_off
    bound = max(bound_pct, spread_pct)

    errors = validate_trace(final_trace)
    missing = required_rows(final_trace, extra=PIPELINED_CYCLE_ROWS)
    attribution_ok = (
        bool(report.failed_by)
        and set(report.failed_by.values()) == {"NodeResourcesFit"}
    )
    ok = (
        not errors
        and not missing
        and overhead_pct <= bound
        and attribution_ok
    )
    print(json.dumps({
        "metric": "trace_smoke",
        "off_pods_per_sec": round(shape["n_pods"] / median_off, 1),
        "on_pods_per_sec": round(shape["n_pods"] / median_on, 1),
        "overhead_pct": round(overhead_pct, 2),
        "bound_pct": round(bound, 2),
        "noise_floor_pct": round(spread_pct, 2),
        "trace_events": len(final_trace.get("traceEvents", ())),
        "trace_errors": errors[:5],
        "missing_rows": missing,
        "attribution_ok": attribution_ok,
        "trace_path": out_path,
        "ok": ok,
    }))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
