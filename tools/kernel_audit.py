#!/usr/bin/env python
"""Kernel-resource & numeric-exactness auditor: static VMEM envelopes,
DMA discipline, and the 2^53 exactness lattice (ISSUE 18).

Third static-analysis prong. `tools/graft_lint.py` enforces the CLAUDE.md
invariants on the source AST; `tools/jaxpr_audit.py` proves carry/
donation/i64/effect invariants on the traced programs; this tool audits
the ON-CHIP and NUMERIC surface of the same program registry
(`tools/tpu_lower.PROGRAMS`): what the Pallas kernels resident-allocate,
whether their DMA protocol is balanced on every control path, and whether
the float64/int32 arithmetic the solver calls "exact" actually stays
inside the representable range.

Rules:

- **KA001 VMEM envelope** — every `pallas_call` body's worst-case VMEM
  footprint, computed statically from its block-mapped ref shapes x
  dtypes x double-buffer copies (grid-pipelined operands count twice) +
  VMEM scratch, must fit the per-target budget table
  (`parallel.vmem.VMEM_BUDGET_BYTES`); semaphores live in semaphore
  memory and are counted separately. The per-kernel envelopes are
  committed to docs/kernel_audit.json, and the solver's
  `PALLAS_MAX_ELECTION_ELEMS` gate must equal the envelope-derived
  threshold (`parallel.vmem.derive_max_election_elems`) with the traced
  worst-case payload-copy count no worse than the family table the
  derivation uses — the gate is machine-checked, not hand-picked.
- **KA002 DMA discipline** — inside every kernel body: each
  `make_async_remote_copy` start must have a matching wait on ALL
  control paths (cond branches must leave the same in-flight set, loop
  bodies must be balanced), no wait before the corresponding start, and
  no (semaphore, slot) pair re-armed while its copy is still in flight.
- **KA003 exactness lattice** — declared static bounds on the input
  families (`api.bounds.LABEL_BOUNDS`, int64 reference units) propagate
  through casts, sums, cumsums, dot_generals, scatters and scan/while
  carries as a max-abs interval lattice with provenance taint. Flagged,
  with the provenance chain: any float64 accumulation of exact integer
  quantity operands whose result cannot be proven < 2^53, any int64 ->
  float64 cast of a quantity not provably < 2^53 (outside the blessed
  helpers `api.bounds.EXACT_FN_BOUNDS`), and any int32 demotion of a
  quantity not provably < 2^31. Where the naive interval overflows on a
  QUANTITY aggregation, the declared cluster-total invariant
  (`QUANTITY_SUM_MAX`) is substituted and the assumption is RECORDED in
  the manifest — every scattered "exact < 2^53" comment becomes either
  an arithmetic fact or a named, committed assumption.

A manifest (`docs/kernel_audit.json`: per-program rule verdicts, per-
kernel envelopes, DMA censuses, recorded assumptions, the derived
election threshold) is committed so drift shows up as a diff; `--check`
is the read-only fail-closed CI gate (missing manifest fails, rule
violations always fail, census equality enforced only under the
manifest's jax version). The manifest is never rewritten while
`SPT_PALLAS_MAX_ELECTION_ELEMS` overrides the derived gate.

Usage:
    python tools/kernel_audit.py             # audit all, write manifest
    python tools/kernel_audit.py --check     # read-only verify vs manifest
    python tools/kernel_audit.py --programs entry pallas_ring_offsets
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from collections import Counter
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
MANIFEST = REPO / "docs" / "kernel_audit.json"

if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.jaxpr_audit import (  # noqa: E402  (registry + labeling reuse)
    _CALL_PRIMS,
    ROLE_OVERRIDES,
    label_leaves,
)
from tools.tpu_lower import PROGRAMS, bootstrap  # noqa: E402

RULES = ("KA001", "KA002", "KA003")

#: the pallas kernel programs' positional args are election payloads —
#: declared-quantity roles the generic type-derived labeling can't see
KA_ROLE_OVERRIDES = {
    **ROLE_OVERRIDES,
    "pallas_ring_offsets": ("elect.payload",),
    "pallas_fused_election": ("elect.keys", "elect.payload"),
    # flagship_solve_stats(snap, weights): the int64 allocatable-weight
    # vector is aux-channel plugin config, declared <= 2^20 in
    # api.bounds (the reference's resource_allocation.go weight range)
    "bench_cfg0_tpu_smoke": ("snap", "aux.weights"),
    "bench_cfg1_flagship": ("snap", "aux.weights"),
}

#: f64 ops that CLAIM integer exactness when fed exact integer operands
#: (an f64 div/exp/etc. is score math — approximate by design, no claim)
_ACCUM_PRIMS = frozenset(
    {"add", "sub", "mul", "dot_general", "reduce_sum", "cumsum"}
)

#: aggregation primitives eligible for the declared cluster-total cap
_EMPTY = frozenset()


def _aval(v):
    return getattr(v, "aval", None)


def _dtype_str(v) -> str:
    aval = _aval(v)
    return str(getattr(aval, "dtype", ""))


def _shape(v):
    aval = _aval(v)
    return tuple(getattr(aval, "shape", ()))


def _is_sem_ref(v) -> bool:
    s = str(_aval(v))
    return "semaphore" in s or "dma_sem" in s


class Val:
    """One lattice point: provenance taint, max-abs bound (None =
    unknown), exactness (the value is an integer held exactly in its
    dtype), and quantity kind ("elem" = declared per-element resource
    quantity, "sum" = aggregation of quantities under the declared
    cluster-total invariant, "plain" = no quantity semantics)."""

    __slots__ = ("taint", "bound", "exact", "kind")

    def __init__(self, taint=_EMPTY, bound=None, exact=False, kind="plain"):
        self.taint = taint
        self.bound = bound
        self.exact = exact
        self.kind = kind

    def key(self):
        return (self.taint, self.bound, self.exact, self.kind)

    def quantity(self) -> bool:
        return self.kind in ("elem", "sum")


def _neutral(v: Val) -> bool:
    """A side proven |x| <= 1 (the literal arm of `where(mask, q, 0)`,
    `maximum(q, 0)`, a reset-to-1 segment sentinel) is kind-NEUTRAL in a
    join: masking or seeding a quantity stream with 0/±1 constants does
    not change what the aggregation invariant bounds (QUANTITY_SUM_MAX
    has cluster-scale headroom over per-lane ±1 sentinels)."""
    return v.bound is not None and v.bound <= 1


def _kind_join(a: Val, b: Val) -> str:
    """Kind of a two-way join/merge, with 0/±1 sides kind-neutral."""
    if _neutral(b):
        return a.kind
    if _neutral(a):
        return b.kind
    if a.kind == b.kind:
        return a.kind
    return "sum" if a.quantity() and b.quantity() else "plain"


def _join(a: Val, b: Val) -> Val:
    """Control-flow join: union taint, weakest bound/exactness/kind."""
    bound = None if (a.bound is None or b.bound is None) else max(a.bound, b.bound)
    return Val(a.taint | b.taint, bound, a.exact and b.exact,
               _kind_join(a, b))


def _badd(a, b):
    return None if (a is None or b is None) else a + b


def _bmul(a, b):
    return None if (a is None or b is None) else a * b


def _bmax(*bs):
    if any(b is None for b in bs):
        return None
    return max(bs) if bs else None


class KernelAuditor:
    """Forward interval/taint walk over a closed jaxpr with recursive
    sub-jaxpr handling (KA003), plus per-`pallas_call` VMEM envelope
    accounting (KA001) and DMA-protocol simulation (KA002)."""

    def __init__(self, axis_sizes=None):
        from scheduler_plugins_tpu.api import bounds as B

        self.B = B
        self.axis_sizes = dict(axis_sizes or {})
        self.violations: list[dict] = []
        self.assumptions: set[str] = set()
        self.kernels: list[dict] = []
        self.dma_census: Counter = Counter()
        self.eqn_count = 0
        self._scanned: set[int] = set()
        self._seen_sites: set = set()

    # -- violation/assumption plumbing --------------------------------

    def _add(self, rule, detail, **extra):
        key = (rule, detail)
        if key in self._seen_sites:
            return
        self._seen_sites.add(key)
        self.violations.append({"rule": rule, "detail": detail, **extra})

    def _assume(self, text):
        self.assumptions.add(text)

    def _prov(self, vals) -> str:
        labels = sorted(frozenset().union(*[v.taint for v in vals]) or {"const"})
        return ",".join(labels)

    @staticmethod
    def _kernel_name(eqn) -> str:
        """Stable kernel name of a pallas_call eqn: the explicit `name=`
        (kernels._ring_call passes the vmem.RING_FAMILIES family) via
        either the `name` param or jax 0.4.x's `name_and_src_info`."""
        params = eqn.params
        if params.get("name"):
            return str(params["name"])
        nsi = params.get("name_and_src_info")
        nm = getattr(nsi, "name", None)
        return str(nm) if nm else "pallas_kernel"

    @staticmethod
    def _site(eqn) -> str:
        """Best-effort `file:line(function)` of the traced call site —
        diagnostic text for the console report, NOT keyed into the
        manifest (line drift must not dirty the committed digest)."""
        try:
            from jax._src import source_info_util

            frame = source_info_util.user_frame(eqn.source_info)
            if frame is None:
                return ""
            fname = frame.file_name.rsplit("/", 1)[-1]
            return f" at {fname}:{frame.start_line}({frame.function_name})"
        except Exception:
            return ""

    # -- the walk -----------------------------------------------------

    def propagate(self, jaxpr, in_vals):
        from jax import core

        env: dict = {}

        def read(v):
            if isinstance(v, core.Literal):
                return self._literal(v)
            return env.get(v, Val())

        def write(var, val):
            if type(var).__name__ == "DropVar":
                return
            prev = env.get(var)
            env[var] = val if prev is None else _join(prev, val)

        for var, val in zip(jaxpr.invars, in_vals):
            env[var] = val
        for var in jaxpr.constvars:
            env[var] = Val(exact="int" in _dtype_str(var) or
                           _dtype_str(var) == "bool")
        for eqn in jaxpr.eqns:
            first = id(eqn) not in self._scanned
            vals = [read(v) for v in eqn.invars]
            outs = self._eqn(eqn, vals, first)
            if first:
                self._scanned.add(id(eqn))
                self.eqn_count += 1
            for var, val in zip(eqn.outvars, outs):
                write(var, val)
        return [read(v) for v in jaxpr.outvars]

    @staticmethod
    def _literal_value(var):
        """The concrete value of a jaxpr Literal operand, else None —
        sign-checkable constants (bit masks, clamp limits) support
        transfer rules that max-abs bounds alone cannot justify."""
        from jax import core

        if isinstance(var, core.Literal):
            try:
                import numpy as np

                return np.asarray(var.val)
            except Exception:
                return None
        return None

    def _literal(self, lit) -> Val:
        import numpy as np

        try:
            arr = np.asarray(lit.val)
            bound = float(np.max(np.abs(arr))) if arr.size else 0.0
            if bound == int(bound):
                bound = int(bound)
            exact = bool(
                np.issubdtype(arr.dtype, np.integer)
                or arr.dtype == np.bool_
                or (np.issubdtype(arr.dtype, np.floating)
                    and np.all(arr == np.floor(arr)))
            )
            return Val(_EMPTY, bound, exact, "plain")
        except Exception:
            return Val()

    def _eqn(self, eqn, vals, first):
        name = eqn.primitive.name
        params = eqn.params
        if name == "pjit":
            blessed = self.B.EXACT_FN_BOUNDS.get(params.get("name"))
            if blessed is not None:
                union = frozenset().union(*[v.taint for v in vals]) if vals else _EMPTY
                self._assume(
                    f"blessed exactness helper {params.get('name')!r}: result "
                    f"bound declared {blessed} (api.bounds.EXACT_FN_BOUNDS)"
                )
                return [
                    Val(union, blessed, True,
                        "sum" if any(v.quantity() for v in vals) else "plain")
                    for _ in eqn.outvars
                ]
        if name in _CALL_PRIMS and _CALL_PRIMS[name] in params:
            sub = params[_CALL_PRIMS[name]]
            sub_jaxpr = getattr(sub, "jaxpr", sub)
            if len(sub_jaxpr.invars) == len(vals):
                return self.propagate(sub_jaxpr, vals)
            return self._fallback(eqn, vals)
        if name == "scan":
            return self._scan(eqn, vals)
        if name == "while":
            return self._while(eqn, vals)
        if name == "cond":
            return self._cond(eqn, vals)
        if name == "pallas_call":
            return self._pallas(eqn, vals, first)
        return self._apply(eqn, vals, first)

    def _fallback(self, eqn, vals):
        from jax import core

        union = frozenset().union(*[v.taint for v in vals]) if vals else _EMPTY
        coarse = Val(union)
        for sub in core.jaxprs_in_params(eqn.params):
            sub_jaxpr = getattr(sub, "jaxpr", sub)
            self.propagate(sub_jaxpr, [coarse] * len(sub_jaxpr.invars))
        return [Val(union) for _ in eqn.outvars]

    # -- control flow -------------------------------------------------

    def _scan(self, eqn, vals):
        params = eqn.params
        sub = params["jaxpr"].jaxpr
        n_consts, n_carry = params["num_consts"], params["num_carry"]
        consts = vals[:n_consts]
        entry = vals[n_consts:n_consts + n_carry]
        xs = vals[n_consts + n_carry:]
        carry = list(entry)
        outs = None
        for _ in range(32):
            outs = self.propagate(sub, consts + carry + xs)
            new_carry = []
            changed = False
            for ent, cur, out in zip(entry, carry, outs[:n_carry]):
                nxt = self._carry_invariant(ent, cur, out, "scan")
                changed = changed or nxt.key() != cur.key()
                new_carry.append(nxt)
            if not changed:
                break
            carry = new_carry
        return carry + outs[n_carry:]

    def _while(self, eqn, vals):
        params = eqn.params
        cond_sub = params["cond_jaxpr"].jaxpr
        body_sub = params["body_jaxpr"].jaxpr
        cn, bn = params["cond_nconsts"], params["body_nconsts"]
        cond_consts = vals[:cn]
        body_consts = vals[cn:cn + bn]
        entry = vals[cn + bn:]
        carry = list(entry)
        pred = Val()
        for _ in range(32):
            pred = self.propagate(cond_sub, cond_consts + carry)[0]
            outs = self.propagate(body_sub, body_consts + carry)
            new_carry = []
            changed = False
            for ent, cur, out in zip(entry, carry, outs):
                nxt = self._carry_invariant(ent, cur, out, "while")
                changed = changed or nxt.key() != cur.key()
                new_carry.append(nxt)
            if not changed:
                break
            carry = new_carry
        return [Val(c.taint | pred.taint, c.bound, c.exact, c.kind)
                for c in carry]

    def _carry_invariant(self, ent: Val, cur: Val, out: Val, what: str) -> Val:
        """Loop-carry bound policy: a carry whose body-out bound stays
        within the entry bound keeps it (proven inductive). A QUANTITY
        carry that grows takes the declared cluster-total cap (a loop
        accumulating quantities is a sum of quantities — assumption
        recorded). Anything else that grows degrades to UNKNOWN — the
        lattice never invents a bound it can't justify."""
        taint = cur.taint | out.taint
        exact = cur.exact and out.exact
        if ent.bound is not None and out.bound is not None \
                and out.bound <= ent.bound:
            return Val(taint, ent.bound, exact, cur.kind)
        if cur.quantity() or out.quantity():
            self._assume(
                f"{what} carry ({','.join(sorted(taint)) or 'const'}) grows "
                f"past its entry bound: held at QUANTITY_SUM_MAX by the "
                f"declared aggregation invariant"
            )
            return Val(taint, self.B.QUANTITY_SUM_MAX, exact, "sum")
        return Val(taint, None, exact, "plain")

    def _cond(self, eqn, vals):
        pred, oper = vals[0], vals[1:]
        outs = None
        for branch in eqn.params["branches"]:
            b_outs = self.propagate(branch.jaxpr, oper)
            outs = b_outs if outs is None else [
                _join(a, b) for a, b in zip(outs, b_outs)
            ]
        return [Val(o.taint | pred.taint, o.bound, o.exact, o.kind)
                for o in (outs or [])]

    # -- pallas_call: KA001 + KA002 + body walk -----------------------

    def _pallas(self, eqn, vals, first):
        sub = eqn.params.get("jaxpr")
        if sub is None:
            return self._fallback(eqn, vals)
        body = getattr(sub, "jaxpr", sub)
        if first:
            self.kernels.append(self._envelope(eqn, body))
            self._check_dma(eqn, body)
        in_vals = list(vals) + [
            Val(exact="int" in _dtype_str(v) or _dtype_str(v) == "bool")
            for v in body.invars[len(vals):]
        ]
        self.propagate(body, in_vals[: len(body.invars)])
        union = frozenset().union(*[v.taint for v in vals]) if vals else _EMPTY
        # kernel outputs: the ref->output mapping is opaque here, so the
        # bound is UNKNOWN and exactness is not claimed — but a kernel
        # fed quantities emits quantities (the ring programs sum/elect
        # them), so kind survives and the downstream aggregation
        # invariant can still apply.
        kind = "sum" if any(v.quantity() for v in vals) else "plain"
        return [Val(union, None, False, kind) for _ in eqn.outvars]

    def _envelope(self, eqn, body) -> dict:
        """KA001: static worst-case VMEM footprint of one kernel body."""
        import numpy as np

        from scheduler_plugins_tpu.parallel import vmem

        params = eqn.params
        gm = params.get("grid_mapping")
        grid = tuple(getattr(gm, "grid", ()) or ())
        grid_steps = int(np.prod(grid)) if grid else 1
        num_scratch = int(getattr(gm, "num_scratch_operands", 0))
        n_inv = len(body.invars)
        name = self._kernel_name(eqn)

        vmem_bytes = 0
        sem_slots = 0
        shapes: Counter = Counter()
        refs = []
        for i, v in enumerate(body.invars):
            if _is_sem_ref(v):
                sem_slots += int(np.prod(_shape(v))) if _shape(v) else 1
                continue
            shape = _shape(v)
            try:
                itemsize = np.dtype(str(_aval(v).dtype)).itemsize
            except Exception:
                itemsize = 4
            copies = 2 if (grid_steps > 1 and i < n_inv - num_scratch) else 1
            nbytes = int(np.prod(shape)) * itemsize * copies if shape else itemsize
            vmem_bytes += nbytes
            shapes[(shape, itemsize)] += copies
            refs.append({
                "shape": list(shape),
                "itemsize": itemsize,
                "copies": copies,
                "bytes": nbytes,
            })
        # whole-payload buffer equivalents: total VMEM over the bytes of
        # the modal (payload-shaped) buffer — the (3, Hp, Lp) comm
        # scratch counts as its 3 slots, matching how
        # vmem.ring_buffer_copies sizes the envelope (ceil: partial
        # buffers still occupy a copy's worth of budget headroom)
        budget = vmem.VMEM_BUDGET_BYTES[vmem.VMEM_TARGET]
        if shapes:
            (pshape, pitem), _ = shapes.most_common(1)[0]
            pbytes = (int(np.prod(pshape)) or 1) * pitem if pshape else pitem
            payload_copies = -(-vmem_bytes // pbytes)
        else:
            payload_copies = 0
        if vmem_bytes > budget:
            self._add(
                "KA001",
                f"kernel {name!r}: worst-case VMEM footprint {vmem_bytes} B "
                f"exceeds the {vmem.VMEM_TARGET} budget {budget} B",
                kernel=name,
            )
        # the budget table and the traced body must agree per family:
        # a new output or scratch buffer added to a ring kernel without
        # updating vmem.RING_FAMILIES would silently shrink the derived
        # election threshold's safety margin
        expect = vmem.RING_FAMILIES.get(name)
        if expect is not None \
                and payload_copies != vmem.ring_buffer_copies(expect):
            self._add(
                "KA001",
                f"kernel {name!r}: traced body holds {payload_copies} "
                f"same-shape payload buffers but vmem.RING_FAMILIES "
                f"declares {vmem.ring_buffer_copies(expect)} — the "
                f"envelope table is stale",
                kernel=name,
            )
        return {
            "name": name,
            "grid": list(grid),
            "vmem_bytes": vmem_bytes,
            "budget_bytes": budget,
            "double_buffered": grid_steps > 1,
            "payload_copies": payload_copies,
            "sem_slots": sem_slots,
            "refs": refs,
        }

    # -- KA002: DMA protocol simulation -------------------------------

    def _dma_tokens(self, eqn):
        """(sem var, slot) tokens named by one dma_start/dma_wait: each
        semaphore-ref operand pairs with its immediately following index
        operand (a Literal slot in the unrolled ring; a traced index
        degrades to the wildcard slot '?')."""
        from jax import core

        toks = []
        invars = list(eqn.invars)
        for i, v in enumerate(invars):
            if isinstance(v, core.Literal) or not _is_sem_ref(v):
                continue
            slot = "?"
            if i + 1 < len(invars) and isinstance(invars[i + 1], core.Literal):
                try:
                    slot = int(invars[i + 1].val)
                except Exception:
                    slot = str(invars[i + 1].val)
            toks.append((v, slot))
        return toks

    def _token_name(self, tok, names):
        var, slot = tok
        return f"sem{names.setdefault(var, len(names))}[{slot}]"

    def _check_dma(self, eqn, body):
        """Simulate the start/wait protocol over the kernel body. `armed`
        maps (sem, slot) -> True while a copy is in flight; cond branches
        must agree on the resulting state, loop bodies must be balanced,
        and the body must end drained."""
        name = self._kernel_name(eqn)
        names: dict = {}
        starts = waits = 0

        def walk(jaxpr, armed: set) -> set:
            nonlocal starts, waits
            from jax import core

            for e in jaxpr.eqns:
                prim = e.primitive.name
                if prim == "dma_start":
                    starts += 1
                    self.dma_census[f"{name}.dma_start"] += 1
                    for tok in self._dma_tokens(e):
                        if tok in armed:
                            self._add(
                                "KA002",
                                f"kernel {name!r}: semaphore "
                                f"{self._token_name(tok, names)} re-armed "
                                "while its copy is still in flight",
                                kernel=name,
                            )
                        armed.add(tok)
                elif prim == "dma_wait":
                    waits += 1
                    self.dma_census[f"{name}.dma_wait"] += 1
                    toks = self._dma_tokens(e)
                    cleared = False
                    for tok in toks:  # first-listed semaphore preferred
                        if tok in armed:
                            armed.discard(tok)
                            cleared = True
                            break
                    if not cleared:
                        self._add(
                            "KA002",
                            f"kernel {name!r}: dma_wait on "
                            f"{[self._token_name(t, names) for t in toks]} "
                            "with no matching in-flight start "
                            "(wait-before-start)",
                            kernel=name,
                        )
                elif prim == "cond":
                    ends = []
                    for branch in e.params["branches"]:
                        ends.append(walk(branch.jaxpr, set(armed)))
                    if any(end != ends[0] for end in ends[1:]):
                        self._add(
                            "KA002",
                            f"kernel {name!r}: in-flight DMA set diverges "
                            "across cond branches",
                            kernel=name,
                        )
                    armed = set().union(*ends) if ends else armed
                elif prim in ("scan", "while"):
                    subs = []
                    if prim == "scan":
                        subs = [e.params["jaxpr"].jaxpr]
                    else:
                        subs = [e.params["body_jaxpr"].jaxpr]
                    for sub in subs:
                        end = walk(sub, set(armed))
                        if end != armed:
                            self._add(
                                "KA002",
                                f"kernel {name!r}: {prim} body leaves the "
                                "in-flight DMA set unbalanced",
                                kernel=name,
                            )
                else:
                    for sub in core.jaxprs_in_params(e.params):
                        armed = walk(getattr(sub, "jaxpr", sub), armed)
            return armed

        leftover = walk(body, set())
        for tok in sorted(
            leftover, key=lambda t: self._token_name(t, names)
        ):
            self._add(
                "KA002",
                f"kernel {name!r}: dma_start on "
                f"{self._token_name(tok, names)} never waited on "
                "(missing wait on some control path)",
                kernel=name,
            )
        if self.kernels:
            self.kernels[-1]["dma_starts"] = starts
            self.kernels[-1]["dma_waits"] = waits

    # -- KA003: per-primitive interval transfer + exactness checks ----

    def _agg(self, v: Val, n, what: str) -> Val:
        """Aggregate `n` elements of `v` (sum/cumsum/psum/scatter-add):
        naive interval when provable, the declared cluster-total cap for
        quantity operands otherwise (assumption recorded), UNKNOWN else."""
        naive = _bmul(v.bound, n)
        if naive is not None and naive < self.B.F64_EXACT_MAX:
            return Val(v.taint, naive,
                       v.exact, "sum" if v.quantity() else "plain")
        if v.quantity():
            self._assume(
                f"{what} over quantity family "
                f"({','.join(sorted(v.taint)) or 'const'}) bounded by "
                f"QUANTITY_SUM_MAX (declared aggregation invariant)"
            )
            return Val(v.taint, self.B.QUANTITY_SUM_MAX, v.exact, "sum")
        # non-quantity overflow of the 2^53 line: the naive interval is
        # still a SOUND max-abs (int64 holds it) — keep it so downstream
        # demotions/casts are judged against a number, not UNKNOWN
        return Val(v.taint, naive, v.exact, "plain")

    def _apply(self, eqn, vals, first):
        import numpy as np

        B = self.B
        name = eqn.primitive.name
        params = eqn.params
        union = frozenset().union(*[v.taint for v in vals]) if vals else _EMPTY
        out_dt = _dtype_str(eqn.outvars[0]) if eqn.outvars else ""

        def mk(bound=None, exact=False, kind="plain", taint=union):
            return Val(taint, bound, exact, kind)

        out = None
        if name in ("add", "sub"):
            a, b = vals
            if b.bound == 0:
                out = mk(a.bound, a.exact and b.exact, a.kind)
            elif a.bound == 0:
                out = mk(b.bound, a.exact and b.exact, b.kind)
            elif a.quantity() and b.quantity():
                naive = _badd(a.bound, b.bound)
                if naive is not None and naive < B.F64_EXACT_MAX:
                    out = mk(naive, a.exact and b.exact, "sum"
                             if "sum" in (a.kind, b.kind) else "elem")
                else:
                    self._assume(
                        f"{name} of quantity families "
                        f"({','.join(sorted(union)) or 'const'}) bounded by "
                        f"QUANTITY_SUM_MAX (declared aggregation invariant)"
                    )
                    out = mk(B.QUANTITY_SUM_MAX, a.exact and b.exact, "sum")
            else:
                out = mk(_badd(a.bound, b.bound), a.exact and b.exact)
        elif name == "mul":
            a, b = vals
            # multiplying by a proven 0/±1 factor (bool masks, sign
            # flips) preserves quantity kind — it's masking, not scaling
            kind = "plain"
            if b.bound is not None and b.bound <= 1 and b.exact:
                kind = a.kind
            elif a.bound is not None and a.bound <= 1 and a.exact:
                kind = b.kind
            out = mk(_bmul(a.bound, b.bound), a.exact and b.exact, kind)
        elif name in ("neg", "abs", "stop_gradient", "copy", "real"):
            v = vals[0]
            out = mk(v.bound, v.exact, v.kind)
        elif name in ("max", "min"):
            a, b = vals
            out = mk(_bmax(a.bound, b.bound), a.exact and b.exact,
                     _kind_join(a, b))
        elif name == "select_n":
            branches = vals[1:]
            bound = _bmax(*[v.bound for v in branches])
            exact = all(v.exact for v in branches)
            # 0/±1 arms (the `where(mask, q, 0)` masking idiom) are
            # kind-neutral; the live arms decide
            live = [v for v in branches if not _neutral(v)]
            kinds = {v.kind for v in live}
            kind = kinds.pop() if len(kinds) == 1 else (
                "sum" if live and all(v.quantity() for v in live)
                else "plain")
            out = mk(bound, exact, kind)
        elif name == "clamp":
            lo, x, hi = vals
            if lo.bound is not None and hi.bound is not None:
                out = mk(max(lo.bound, hi.bound), x.exact and lo.exact
                         and hi.exact, x.kind)
            else:
                out = mk(x.bound, x.exact and lo.exact and hi.exact, x.kind)
        elif name == "convert_element_type":
            out = self._convert(eqn, vals[0], union)
        elif name in ("broadcast_in_dim", "reshape", "transpose", "squeeze",
                      "expand_dims", "rev", "reduce_precision"):
            v = vals[0]
            exact = v.exact and name != "reduce_precision"
            out = mk(v.bound, exact, v.kind)
        elif name in ("slice", "dynamic_slice", "gather"):
            v = vals[0]
            out = mk(v.bound, v.exact, v.kind)
        elif name in ("dynamic_update_slice",):
            a, b = vals[0], vals[1]
            out = mk(_bmax(a.bound, b.bound), a.exact and b.exact,
                     _kind_join(a, b))
        elif name == "concatenate":
            # fold the pairwise kind join (zero-segment seeds stay
            # neutral — the exclusive-prefix idiom concatenates [0, ...])
            acc = vals[0]
            for v in vals[1:]:
                acc = Val(acc.taint | v.taint,
                          _bmax(acc.bound, v.bound),
                          acc.exact and v.exact, _kind_join(acc, v))
            out = mk(acc.bound, acc.exact, acc.kind)
        elif name == "pad":
            x, padv = vals[0], vals[1]
            out = mk(_bmax(x.bound, padv.bound), x.exact and padv.exact,
                     x.kind)
        elif name == "iota":
            dim = params.get("dimension", 0)
            shape = params.get("shape") or _shape(eqn.outvars[0])
            n = shape[dim] if shape else 0
            out = Val(_EMPTY, max(int(n) - 1, 0), True, "plain")
        elif name in ("argmin", "argmax"):
            axes = params.get("axes", ())
            shape = _shape(eqn.invars[0])
            n = int(np.prod([shape[a] for a in axes])) if shape else 1
            out = mk(max(n - 1, 0), True)
        elif name == "reduce_sum":
            axes = params.get("axes", ())
            shape = _shape(eqn.invars[0])
            n = int(np.prod([shape[a] for a in axes])) if axes else 1
            out = self._agg(vals[0], max(n, 1), "reduce_sum")
        elif name == "cumsum":
            axis = params.get("axis", 0)
            shape = _shape(eqn.invars[0])
            n = shape[axis] if shape else 1
            out = self._agg(vals[0], max(int(n), 1), "cumsum")
        elif name in ("reduce_max", "reduce_min", "cummax", "cummin"):
            v = vals[0]
            out = mk(v.bound, v.exact, v.kind)
        elif name in ("reduce_and", "reduce_or", "reduce_xor"):
            out = mk(1, True)
        elif name == "reduce_prod":
            out = mk(None, vals[0].exact)
        elif name == "dot_general":
            a, b = vals[0], vals[1]
            dims = params.get("dimension_numbers")
            k = 1
            try:
                (lc, _rc), _ = dims
                shape = _shape(eqn.invars[0])
                k = int(np.prod([shape[d] for d in lc])) if lc else 1
            except Exception:
                k = None
            out = mk(_bmul(_bmul(a.bound, b.bound), k),
                     a.exact and b.exact)
        elif name == "sort":
            out_vals = [mk(v.bound, v.exact, v.kind, taint=union)
                        for v in vals]
            return out_vals
        elif name == "rem":
            a, b = vals
            out = mk(b.bound if b.bound is not None else a.bound,
                     a.exact and b.exact, a.kind)
        elif name == "div":
            a, b = vals
            if "int" in out_dt:
                out = mk(a.bound, a.exact and b.exact, a.kind)
            else:
                out = mk(a.bound, False)
        elif name == "sign":
            out = mk(1, True)
        elif name == "floor" or name == "ceil" or name.startswith("round"):
            v = vals[0]
            exact = v.bound is not None and v.bound < B.F64_EXACT_MAX
            out = mk(_badd(v.bound, 1), exact, v.kind)
        elif name == "integer_pow":
            v = vals[0]
            y = params.get("y", 1)
            b = None
            if v.bound is not None and abs(y) < 16:
                try:
                    b = v.bound ** y if y >= 0 else None
                except OverflowError:
                    b = None
            out = mk(b, v.exact and y >= 0)
        elif name == "shift_left":
            a, s = vals
            b = _bmul(a.bound, None if s.bound is None else 2 ** min(
                int(s.bound), 63))
            out = mk(b, a.exact and s.exact, a.kind)
        elif name in ("shift_right_logical", "shift_right_arithmetic"):
            out = mk(vals[0].bound, vals[0].exact, vals[0].kind)
        elif name in ("and", "or", "xor"):
            a, b = vals
            known = [x for x in (a.bound, b.bound) if x is not None]
            bound = max(known) if known else None
            kind = _kind_join(a, b)
            if name == "and":
                # x & m with a literal NONNEGATIVE mask m lands in
                # [0, m] (two's complement) — the limb-split idiom
                # (`row >> s & (2^18 - 1)`) becomes provably int32-safe.
                # min-of-bounds alone would be UNSOUND (m = -1 is all
                # ones), so the mask side must be a literal we can sign-
                # check.
                for i, other in ((0, b), (1, a)):
                    lit = self._literal_value(eqn.invars[i])
                    if lit is not None and np.all(np.asarray(lit) >= 0):
                        m = int(np.max(np.asarray(lit))) if np.size(lit) \
                            else 0
                        bound = m if bound is None else min(bound, m)
                        kind = other.kind
            out = mk(bound, a.exact and b.exact, kind)
        elif name == "not":
            out = mk(1, True)
        elif name in ("eq", "ne", "lt", "le", "gt", "ge", "is_finite"):
            out = mk(1, True)
        elif name == "psum":
            axes = params.get("axes", ())
            n = 1
            for ax in axes:
                size = self.axis_sizes.get(ax)
                if size is None:
                    n = None
                    break
                n *= size
            if n is None:
                out = self._agg(vals[0], None, "psum")
            else:
                out = self._agg(vals[0], n, "psum")
            if len(vals) > 1:  # multi-operand psum: coarse per-output
                return [self._agg(v, n, "psum") for v in vals]
        elif name in ("pmin", "pmax", "all_gather", "ppermute",
                      "pbroadcast"):
            v = vals[0]
            out = mk(v.bound, v.exact, v.kind)
        elif name == "axis_index":
            ax = params.get("axis_name")
            size = self.axis_sizes.get(ax)
            out = Val(_EMPTY, (size - 1) if size else None, True, "plain")
        elif name.startswith("scatter"):
            oper, upd = vals[0], vals[-1]
            if name in ("scatter-add", "scatter_add"):
                upd_n = int(np.prod(_shape(eqn.invars[-1]))) or 1
                grown = self._agg(upd, upd_n, "scatter-add")
                if oper.bound == 0:
                    # segment-sum idiom: scatter quantity updates into a
                    # zeros accumulator — the result IS the aggregation
                    out = mk(grown.bound, oper.exact and upd.exact,
                             grown.kind, taint=oper.taint | grown.taint)
                elif oper.quantity() and grown.quantity():
                    out = self._agg(_join(oper, grown), 2, "scatter-add")
                else:
                    out = mk(_badd(oper.bound, grown.bound),
                             oper.exact and upd.exact,
                             _kind_join(oper, grown))
            else:
                out = mk(_bmax(oper.bound, upd.bound),
                         oper.exact and upd.exact, _kind_join(oper, upd))
        elif name in ("exp", "log", "log1p", "tanh", "logistic", "sqrt",
                      "rsqrt", "pow", "erf", "sin", "cos", "expm1",
                      "cbrt", "atan2"):
            out = mk(None, False)
        elif name == "get":
            v = vals[0]
            out = mk(v.bound, v.exact, v.kind)
        elif name in ("swap", "addupdate", "masked_swap", "masked_load",
                      "masked_store"):
            v = vals[0]
            out = mk(v.bound, v.exact, v.kind)
        else:
            return self._fallback(eqn, vals)

        if out is None:
            out = mk()
        # the KA003 f64-accumulation flag: an op that CLAIMS exactness
        # (integer operands carried in f64) must prove its result < 2^53
        if (first and name in _ACCUM_PRIMS and out_dt == "float64"
                and vals and all(v.exact for v in vals)
                and any(v.quantity() for v in vals)
                and (out.bound is None or out.bound >= B.F64_EXACT_MAX)):
            self._add(
                "KA003",
                f"float64 {name} of exact integer quantity operands not "
                f"provably < 2^53 (bound="
                f"{'unknown' if out.bound is None else int(out.bound)}; "
                f"provenance: {self._prov(vals)}){self._site(eqn)}",
                primitive=name,
            )
            out = Val(out.taint, out.bound, False, out.kind)
        return [out] + [Val(union) for _ in eqn.outvars[1:]]

    def _convert(self, eqn, v: Val, union) -> Val:
        B = self.B
        new = str(eqn.params.get("new_dtype", ""))
        first = id(eqn) not in self._scanned
        src = _dtype_str(eqn.invars[0])
        # scope: the KIND lattice decides what is a quantity — the
        # transfer rules carry kind through masking/selection/aggregation,
        # so taint (reported as provenance) does not widen the net to
        # score/index values that merely DEPEND on quantities
        quantity = v.quantity()
        if new == "float64":
            exact = v.exact and v.bound is not None \
                and v.bound < B.F64_EXACT_MAX
            if (first and quantity and v.exact and not exact
                    and src.startswith("int")):
                self._add(
                    "KA003",
                    f"int64 -> float64 cast of quantity not provably "
                    f"< 2^53 (bound="
                    f"{'unknown' if v.bound is None else int(v.bound)}; "
                    f"provenance: {self._prov([v])}){self._site(eqn)} — "
                    "route through a blessed helper "
                    "(utils.intmath.exact_f64) or declare the bound in "
                    "api.bounds",
                    primitive="convert_element_type",
                )
            return Val(union, v.bound, exact, v.kind)
        if new in ("int32", "uint32"):
            if (first and quantity and src in ("int64", "float64",
                                               "float32")
                    and (v.bound is None or v.bound >= B.I32_MAX)):
                self._add(
                    "KA003",
                    f"{src} -> {new} demotion of quantity not provably "
                    f"< 2^31 (bound="
                    f"{'unknown' if v.bound is None else int(v.bound)}; "
                    f"provenance: {self._prov([v])}){self._site(eqn)}",
                    primitive="convert_element_type",
                )
            bound = v.bound if v.bound is not None else None
            if bound is not None:
                bound = min(bound, B.I32_MAX - 1)
            return Val(union, bound, "int" in src or src == "bool", v.kind)
        if new == "float32":
            exact = v.exact and v.bound is not None and v.bound < (1 << 24)
            return Val(union, v.bound, exact, v.kind)
        if new in ("int64", "uint64"):
            return Val(union, v.bound, v.exact or "int" in src
                       or src == "bool", v.kind)
        if new == "bool":
            return Val(union, 1, True, "plain")
        return Val(union, v.bound, False, v.kind)


# ---------------------------------------------------------------------------
# program audit
# ---------------------------------------------------------------------------


def audit_fn(fn, args, roles=None, mesh=None) -> dict:
    """Trace `fn(*args)` to a closed jaxpr and run every KA rule."""
    import jax

    from scheduler_plugins_tpu.api import bounds as B
    from scheduler_plugins_tpu.parallel.mesh import ambient_mesh

    if mesh is not None:
        with ambient_mesh(mesh):
            closed = jax.make_jaxpr(fn)(*args)
        axis_sizes = dict(mesh.shape)
    else:
        closed = jax.make_jaxpr(fn)(*args)
        axis_sizes = {}
    labels = label_leaves(args, roles)
    if len(labels) != len(closed.jaxpr.invars):
        raise RuntimeError(
            f"label/invar mismatch: {len(labels)} leaves vs "
            f"{len(closed.jaxpr.invars)} invars"
        )
    auditor = KernelAuditor(axis_sizes)
    in_vals = []
    for label, var in zip(labels, closed.jaxpr.invars):
        dt = _dtype_str(var)
        bound, kind = B.leaf_bound(label, dt)
        exact = ("int" in dt or dt == "bool"
                 or (dt == "float64" and kind == "elem"))
        in_vals.append(Val(frozenset([label]), bound, exact, kind))
    auditor.propagate(closed.jaxpr, in_vals)

    rule_counts = {r: 0 for r in RULES}
    for v in auditor.violations:
        rule_counts[v["rule"]] += 1
    return {
        "rules": rule_counts,
        "violations": auditor.violations,
        "eqns": auditor.eqn_count,
        "kernels": auditor.kernels,
        "dma_census": {
            k: auditor.dma_census[k] for k in sorted(auditor.dma_census)
        },
        "assumptions": sorted(auditor.assumptions),
    }


def audit_program(name: str) -> dict:
    fn, args, mesh = PROGRAMS[name]()
    return audit_fn(fn, args, roles=KA_ROLE_OVERRIDES.get(name), mesh=mesh)


def envelope_summary() -> dict:
    """The shared VMEM envelope section of the manifest: budget table
    target, the envelope-derived election threshold, and the solver
    gate actually in force (KA001 fails when they drift apart)."""
    from scheduler_plugins_tpu.parallel import kernels, vmem

    derived = vmem.derive_max_election_elems()
    return {
        "target": vmem.VMEM_TARGET,
        "budget_bytes": vmem.VMEM_BUDGET_BYTES[vmem.VMEM_TARGET],
        "worst_ring_copies": vmem.WORST_RING_COPIES,
        "derived_max_election_elems": derived,
        "solver_gate": kernels.PALLAS_MAX_ELECTION_ELEMS,
        # PR 13 hand-picked 1 << 19; the derivation lands on the same
        # number, so replacing the guess changed its provenance, not the
        # fallback behavior (delta 0)
        "previous_hand_picked": 1 << 19,
    }


# ---------------------------------------------------------------------------
# driver (mirrors tools/jaxpr_audit.py: fail-closed --check, committed
# manifest)
# ---------------------------------------------------------------------------


def run(names, check: bool) -> int:
    import jax

    from scheduler_plugins_tpu.parallel import vmem

    prior = {}
    if MANIFEST.exists():
        prior = json.loads(MANIFEST.read_text())

    env = envelope_summary()
    failures = []
    if env["solver_gate"] != env["derived_max_election_elems"]:
        if os.environ.get("SPT_PALLAS_MAX_ELECTION_ELEMS"):
            print(
                "[kernel-audit] note: SPT_PALLAS_MAX_ELECTION_ELEMS "
                f"override in force (gate {env['solver_gate']}, derived "
                f"{env['derived_max_election_elems']})"
            )
        else:
            failures.append(
                "KA001 PALLAS_MAX_ELECTION_ELEMS "
                f"({env['solver_gate']}) != envelope-derived threshold "
                f"({env['derived_max_election_elems']}): the solver gate "
                "drifted from parallel/vmem.py"
            )

    results = {}
    worst_payload_copies = 0
    for name in names:
        print(f"[kernel-audit] {name} ...", flush=True)
        try:
            results[name] = audit_program(name)
        except Exception as exc:  # a program that cannot trace IS a failure
            failures.append(f"{name}: trace failed: {exc!r}")
            continue
        res = results[name]
        for v in res["violations"]:
            failures.append(f"{name}: {v['rule']} {v['detail']}")
        for k in res["kernels"]:
            worst_payload_copies = max(
                worst_payload_copies, k["payload_copies"]
            )
        print(
            f"[kernel-audit] {name}: {res['eqns']} eqns, "
            f"{len(res['kernels'])} kernels, "
            f"{sum(res['rules'].values())} violations, "
            f"{len(res['assumptions'])} assumptions",
            flush=True,
        )

    # the family table the threshold derivation uses must be no tighter
    # than what the traced kernels actually allocate
    if worst_payload_copies > vmem.WORST_RING_COPIES:
        failures.append(
            "KA001 traced worst-case payload copies "
            f"({worst_payload_copies}) exceed parallel/vmem.py "
            f"WORST_RING_COPIES ({vmem.WORST_RING_COPIES}): the ring "
            "family table is stale — fix RING_FAMILIES and re-derive"
        )

    manifest = {
        "jax": jax.__version__,
        "vmem": env,
        "programs": {
            n: {
                "rules": r["rules"],
                "eqns": r["eqns"],
                "kernels": [
                    {k: v for k, v in kern.items() if k != "refs"}
                    for kern in r["kernels"]
                ],
                "dma_census": r["dma_census"],
                "assumptions": r["assumptions"],
            }
            for n, r in sorted(results.items())
        },
    }

    if check and not prior:
        failures.append(
            "docs/kernel_audit.json missing: run "
            "`python tools/kernel_audit.py` and commit it"
        )
    if check and prior:
        prior_programs = prior.get("programs", {})
        missing = [n for n in names if n in PROGRAMS
                   and n not in prior_programs]
        if missing:
            failures.append(
                f"manifest missing programs {missing}: run "
                "`python tools/kernel_audit.py` and commit "
                "docs/kernel_audit.json"
            )
        for n, p in prior_programs.items():
            dirty = {r: c for r, c in p.get("rules", {}).items() if c}
            if dirty:
                failures.append(
                    f"manifest records violations for {n}: {dirty}"
                )
        if prior.get("vmem", {}).get("solver_gate") != env["solver_gate"] \
                or prior.get("vmem", {}).get("derived_max_election_elems") \
                != env["derived_max_election_elems"]:
            failures.append(
                "vmem envelope drift vs manifest "
                f"(manifest {prior.get('vmem')}, computed {env}): "
                "intended? re-run `python tools/kernel_audit.py` and "
                "commit the diff"
            )
        if prior.get("jax") == jax.__version__:
            for n, r in results.items():
                want = prior_programs.get(n, {})
                got = manifest["programs"][n]
                if want and want != got:
                    failures.append(
                        f"{n}: kernel-audit census drift vs manifest — "
                        "intended? re-run `python tools/kernel_audit.py` "
                        "and commit the manifest diff"
                    )
        else:
            print(
                f"[kernel-audit] note: manifest written under jax "
                f"{prior.get('jax')}, running {jax.__version__}; census "
                "equality not enforced, rule/coverage gates still apply"
            )

    overridden = bool(os.environ.get("SPT_PALLAS_MAX_ELECTION_ELEMS"))
    if not check and set(names) == set(PROGRAMS) and not failures \
            and not overridden:
        MANIFEST.write_text(
            json.dumps(manifest, indent=1, sort_keys=True) + "\n"
        )
        print(f"[kernel-audit] wrote {MANIFEST.relative_to(REPO)}")
    elif not check:
        reason = (
            "failures" if failures else
            "SPT_PALLAS_MAX_ELECTION_ELEMS override in force"
            if overridden else "partial program set"
        )
        print(f"[kernel-audit] {reason}: manifest NOT rewritten")

    for f in failures:
        print(f"[kernel-audit] FAIL: {f}", file=sys.stderr)
    if not failures:
        n_kernels = sum(len(r["kernels"]) for r in results.values())
        n_assume = sum(len(r["assumptions"]) for r in results.values())
        print(
            f"[kernel-audit] OK: {len(results)}/{len(names)} programs "
            f"audit clean (KA001-KA003), {n_kernels} kernel envelopes, "
            f"{n_assume} recorded assumptions, election gate "
            f"{env['solver_gate']} (derived)"
        )
    return 1 if failures else 0


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument(
        "--check",
        action="store_true",
        help="read-only: verify against the committed manifest (census "
        "equality enforced only under the manifest's jax version)",
    )
    parser.add_argument(
        "--programs",
        nargs="+",
        choices=sorted(PROGRAMS),
        default=sorted(PROGRAMS),
        help="subset of programs (default: all)",
    )
    args = parser.parse_args(argv)
    bootstrap()
    return run(args.programs, check=args.check)


if __name__ == "__main__":
    sys.exit(main())
