"""Counterfactual tuning observatory over flight-recorder corpora.

- `tuning.quality`: placement-quality objectives as tensor math
  (fragmentation, utilization imbalance, gang wait, unplaced fraction,
  drift) + the numpy twin `run_cycle` stamps on every report.
- `tuning.sweep`: K candidate weight vectors replayed through ONE
  vmapped sequential solve (zero per-candidate retraces).
- `tuning.gates`: numpy hard-constraint replay oracles (fit, queue-order
  quota, gang quorum) gating tuned-profile emission.
- `tuning.promotion`: THE one promotion-gate body (sweep a corpus, rank,
  disqualify, accept) shared by the offline tuner and the shadow lane.
- `tuning.shadow`: the online shadow lane (ROADMAP item 2) — background
  deadlined sweeps over the flight-recorder ring, gated live promotion
  through the aux channel, probation auto-rollback.

Drivers: `tools/tune.py` (corpus sweep + gated profile emission), the
serving daemon's `--tune` flag (`tuning.shadow.ShadowTuner`),
`tools/replay.py quality` (score a recorded bundle), `bench.py` (quality
columns on every JSON line; config 14 drives the tuned lane).
"""

from scheduler_plugins_tpu.tuning import gates, promotion, quality, sweep

__all__ = ["gates", "promotion", "quality", "sweep"]
