"""Counterfactual tuning observatory over flight-recorder corpora.

- `tuning.quality`: placement-quality objectives as tensor math
  (fragmentation, utilization imbalance, gang wait, unplaced fraction,
  drift) + the numpy twin `run_cycle` stamps on every report.
- `tuning.sweep`: K candidate weight vectors replayed through ONE
  vmapped sequential solve (zero per-candidate retraces).
- `tuning.gates`: numpy hard-constraint replay oracles (fit, queue-order
  quota, gang quorum) gating tuned-profile emission.

Drivers: `tools/tune.py` (corpus sweep + gated profile emission),
`tools/replay.py quality` (score a recorded bundle), `bench.py` (quality
columns on every JSON line).
"""

from scheduler_plugins_tpu.tuning import gates, quality, sweep

__all__ = ["gates", "quality", "sweep"]
