"""THE one promotion-gate body: sweep a corpus, rank, disqualify, accept.

Both tuning drivers — the offline corpus tuner (`tools/tune.py`, PR 8)
and the online shadow lane inside the serving daemon (`tuning.shadow`,
ROADMAP item 2) — decide "is this candidate weight vector allowed to
become the profile?" with exactly this code. One copy on purpose: a
candidate that would be rejected offline must be rejected online, and a
gate bug fixed here is fixed for both. The offline driver's emission
behavior is regression-locked (tests/test_shadow_tuner.py asserts the
shared identity AND the decision tables; `make tune-smoke` exercises the
end-to-end offline path).

The contract per candidate (the PR 8 rules, unchanged):

- every candidate replays through the independent numpy hard-constraint
  oracles (`tuning.gates`: fit, mask, queue-order quota, gang quorum) —
  ANY violation anywhere in the corpus disqualifies;
- ranking is the sum of sense-adjusted objective deltas vs lane 0 (the
  in-band incumbent), in each objective's own dimensionless units;
- a candidate regressing ANY objective beyond `tolerance` is
  disqualified — a tune must not buy one objective by silently selling
  another;
- acceptance additionally requires a non-incumbent winner with a
  strictly positive rank score, at least one strict improvement, zero
  violations, and zero anchor mismatches (a sequential record the
  incumbent lane cannot reproduce means the rebuild is unfaithful and
  nothing ranked on it can be trusted).

Corpus entries are `CorpusCycle`s — a thin view over either a bundle
`LoadedCycle` (offline) or an in-memory ring `CycleRecord` (online), so
the sweep/gate body never knows which driver called it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

import numpy as np

#: objectives the promotion gate ranks on, in report order (preemption/
#: nomination counts are properties of the recorded cycle's PostFilter,
#: not of a counterfactual weight vector — the sweep replays the solve,
#: not the preemption engine, so they are reported but never ranked)
RANKED_OBJECTIVES = (
    "fragmentation", "util_imbalance", "gang_wait_frac", "unplaced_frac",
    "drift",
)


@dataclass
class CorpusCycle:
    """One recorded cycle as the promotion gate consumes it.

    `prepare(scheduler)` re-prepares the (shared) replay scheduler for
    THIS cycle and re-bakes its recorded host_state — must run before
    every solve/score of the cycle (cycles of one corpus can carry
    different layouts or cluster-derived specializations). `anchor` is
    the recorded assignment when the record's own weights equal the
    sweep's lane 0 (the incumbent) — None means "no comparable anchor"
    (e.g. a ring record captured under pre-promotion weights): the
    anchor-mismatch disqualifier and the drift yardstick then fall back
    to lane 0's replayed placements, which IS the incumbent by
    construction."""

    scheduler: object
    snap: object
    meta: object
    auxes: tuple
    anchor: Optional[np.ndarray]
    wait: Optional[np.ndarray]
    mode: Optional[str]
    prepare: Callable = field(default=lambda scheduler: None)


@dataclass
class PromotionVerdict:
    """The gate's full output: per-candidate aggregates plus the one
    accepted/rejected decision both drivers act on."""

    objectives: dict  # name -> (K,) float64 corpus means
    violations: np.ndarray  # (K,) int64 hard-constraint counts
    anchor_mismatches: int
    order: np.ndarray  # (K,) candidate indices, best first
    score: np.ndarray  # (K,) rank scores (-inf = disqualified)
    improvements: dict  # name -> (K,) sense-adjusted deltas vs lane 0
    best: int
    improved: list  # objective names the winner strictly improves
    accepted: bool

    @property
    def disqualified(self) -> int:
        return int(np.sum(~np.isfinite(self.score)))


def sweep_corpus(corpus, W, mutate=None):
    """Aggregate per-candidate objective means + gate verdicts over the
    corpus. Returns (objectives {name: (K,) mean}, violations (K,) int,
    anchor_mismatches: sequential-mode cycles whose incumbent lane failed
    to reproduce the recorded placements). `mutate(A, admitted, wait)`
    post-processes each cycle's swept outputs BEFORE gating — the chaos
    harness's `tune.sweep` garbage injection point (`tuning.shadow`),
    proving the oracles disqualify corrupted sweep output before it can
    reach a promotion; production drivers pass None."""
    from scheduler_plugins_tpu.parallel.solver import profile_initial_scores
    from scheduler_plugins_tpu.tuning import gates, quality, sweep

    K = W.shape[0]
    sums = {name: np.zeros(K) for name in RANKED_OBJECTIVES}
    violations = np.zeros(K, np.int64)
    anchor_mismatches = 0
    for cc in corpus:
        cc.prepare(cc.scheduler)
        A, adm, wt = sweep.sweep_cycle(cc.scheduler, cc.snap, W,
                                       auxes=cc.auxes)
        if mutate is not None:
            A, adm, wt = mutate(A, adm, wt)
        if (
            cc.mode == "sequential" and cc.anchor is not None
            and not (A[0] == cc.anchor).all()
        ):
            anchor_mismatches += 1
        q = quality.batch_quality(cc.snap, A, wt)
        for name in ("fragmentation", "util_imbalance", "gang_wait_frac",
                     "unplaced_frac"):
            sums[name] += np.asarray(q[name], np.float64)
        # drift on the INCUMBENT profile's cycle-initial objective vs the
        # recorded sequential anchor (or, anchorless, lane 0's own
        # replayed placements) — the fixed yardstick every candidate's
        # placements are comparable on
        scores = np.asarray(
            profile_initial_scores(cc.scheduler, cc.snap, auxes=cc.auxes)[0]
        )
        ref = cc.anchor if cc.anchor is not None else A[0]
        sums["drift"] += np.array([
            quality.score_drift(scores, A[k], ref) for k in range(K)
        ])
        for k in range(K):
            violations[k] += gates.hard_violations(
                cc.snap, A[k], wt[k]
            )["total"]
    n = len(corpus)
    return (
        {name: s / n for name, s in sums.items()}, violations,
        anchor_mismatches,
    )


def rank_candidates(objectives, violations, tolerance: float,
                    rank_objectives=None, tolerances=None):
    """(order, scores, improvements): candidates ranked by summed
    sense-adjusted improvement vs lane 0; disqualified lanes
    (hard-constraint violations, or any objective regressing beyond its
    tolerance) score -inf. Deltas are ABSOLUTE in each objective's own
    dimensionless units (every ranked objective is a fraction/relative
    quantity in ~[0, 1], so absolute points are comparable and the rule
    stays well-defined when a baseline objective sits at exactly 0 —
    drift always does: the anchor IS lane 0's placements).

    `rank_objectives` (default: every objective) selects which
    objectives contribute to the rank SUM; objectives outside it remain
    pure disqualification rails. `tolerances` overrides the regression
    tolerance per objective. The offline tuner uses the defaults
    unchanged; the online shadow lane ranks on the per-cycle quality
    objectives and keeps `drift` as a rail with its own (looser)
    tolerance — over a drifting mix the incumbent's score surface is
    exactly the thing going stale, and a drift-vs-incumbent term in the
    rank sum would veto every adaptation by construction."""
    from scheduler_plugins_tpu.tuning.quality import SENSE

    K = len(violations)
    imps = {}
    for name, values in objectives.items():
        # sense-adjusted: positive = candidate better than baseline
        imps[name] = SENSE[name] * (values - values[0])
    ranked = set(imps if rank_objectives is None else rank_objectives)
    tolerances = tolerances or {}
    score = np.zeros(K)
    for name, imp in imps.items():
        if name in ranked:
            score += imp
    for k in range(K):
        if violations[k] > 0 or any(
            imp[k] < -tolerances.get(name, tolerance)
            for name, imp in imps.items()
        ):
            score[k] = -np.inf
    order = np.argsort(-score, kind="stable")
    return order, score, imps


def strict_improvements(imps, k, eps: float = 1e-9) -> list:
    return [name for name, imp in imps.items() if imp[k] > eps]


def evaluate_candidates(corpus, W, tolerance: float, mutate=None,
                        rank_objectives=None,
                        tolerances=None) -> PromotionVerdict:
    """The whole gate in one call: sweep, rank, disqualify, accept. Both
    drivers consume the returned verdict — the offline tuner emits a
    profile from it, the shadow lane stages a live promotion from it."""
    W = np.asarray(W, np.int64)
    objectives, violations, anchor_mismatches = sweep_corpus(
        corpus, W, mutate=mutate
    )
    order, score, imps = rank_candidates(
        objectives, violations, tolerance,
        rank_objectives=rank_objectives, tolerances=tolerances,
    )
    best = int(order[0])
    improved = strict_improvements(
        {name: imp for name, imp in imps.items()
         if rank_objectives is None or name in set(rank_objectives)},
        best,
    )
    accepted = bool(
        best != 0 and np.isfinite(score[best]) and score[best] > 0
        and improved and violations[best] == 0
        # a sequential record the incumbent lane cannot reproduce means
        # the rebuild is unfaithful: never promote a vector ranked on it
        and anchor_mismatches == 0
    )
    return PromotionVerdict(
        objectives=objectives, violations=violations,
        anchor_mismatches=anchor_mismatches, order=order, score=score,
        improvements=imps, best=best, improved=improved, accepted=accepted,
    )


def weights_digest(weights) -> str:
    """Short content digest of a weight vector — the active-weights
    identity stamped on /healthz, the prometheus gauge (as an int) and
    the tuner state file, so operators can tell at a glance whether two
    processes serve the same promoted profile."""
    import hashlib

    arr = np.ascontiguousarray(np.asarray(weights, np.int64))
    return hashlib.blake2b(arr.tobytes(), digest_size=6).hexdigest()
