"""Placement-quality objectives as tensor math.

The bench's headline is pods/s; this module is the quality frontier next
to it ("Priority Matters", arxiv 2511.08373; ROADMAP item 1): a solved
cycle — (snapshot, assignment, admitted, wait) — scores on a small vector
of placement-quality objectives, each a scalar float64:

- ``fragmentation``: how shattered the POST-placement free capacity is.
  Per core resource (cpu, memory): ``1 - max_node_free / total_free``
  (0 when nothing is free — a fully packed cluster is not fragmented),
  averaged over the two. 0 = all remaining headroom sits on one node
  (a gang/large pod can still land); → 1 = headroom is dust spread over
  the fleet.
- ``util_imbalance``: population standard deviation of per-node
  utilization (mean of cpu/mem used-over-allocatable) across schedulable
  nodes. 0 = perfectly balanced load.
- ``gang_wait_frac``: fraction of this cycle's placements parked in
  Permit-Wait (gang quorum unmet) — capacity held hostage by incomplete
  gangs.
- ``unplaced_frac``: fraction of the real pending batch left unplaced.
- ``drift`` (computed where an anchor exists — sweeps, batch bench
  lines): relative score-sum drift vs the sequential-anchor placements
  on the anchor profile's cycle-initial objective (the same definition as
  `parallel.solver.score_drift_vs_sequential`).
- ``preemptions`` / ``nominations`` (host counts from the `CycleReport`):
  victims deleted and nominations made by this cycle's PostFilter.

`SENSE` maps each objective to its improvement direction so ranking code
(`tools/tune.py`) never hardcodes "lower is better".

Two implementations, gated for agreement by tests/test_tuning.py:

- the JAX core (`cycle_quality`, `batch_quality`, `state_quality`) — what
  the bench lines and the vmapped counterfactual sweep use (K candidate
  lanes score in one jitted vmap);
- a numpy twin (`cycle_quality_np`) — what `framework.cycle.run_cycle`
  stamps on every `CycleReport` and exports as
  ``scheduler_placement_quality{objective}`` gauges. Numpy there on
  purpose: run_cycle executes across dozens of snapshot shapes in the
  unit suite and a per-shape jit compile for a sub-millisecond reduction
  would buy nothing but compile time (the tier-1 suite sits at its
  runtime cliff); the twin is ~30 lines of identical float64 arithmetic
  and the decision-table tests hold the two bit-close.

Multi-cycle objectives (gang admission latency in cycles) need memory
across reports — `QualityAccumulator` below.
"""

from __future__ import annotations

import numpy as np

from scheduler_plugins_tpu.api.resources import CANONICAL, CPU, MEMORY

#: resource-axis slots the capacity objectives aggregate over (requests in
#: reference units are only comparable within a resource, so objectives
#: reduce per resource first, then average)
CPU_I = CANONICAL.index(CPU)
MEM_I = CANONICAL.index(MEMORY)

#: objective -> +1 (higher is better) / -1 (lower is better)
SENSE = {
    "fragmentation": -1,
    "util_imbalance": -1,
    "packed_utilization": +1,
    "gang_wait_frac": -1,
    "unplaced_frac": -1,
    "drift": +1,
    "preemptions": -1,
    "nominations": -1,
    "gang_latency_cycles": -1,
    # rank-aware gang placement (gangs.topology; docs/GANGS.md)
    "gang_spread_cost": -1,
    "rank_cost_max": -1,
    "rank_cost_p99": -1,
    "elastic_satisfaction": +1,
}

#: the objectives `cycle_quality` / `cycle_quality_np` emit per cycle
CYCLE_OBJECTIVES = (
    "fragmentation", "util_imbalance", "packed_utilization",
    "gang_wait_frac", "unplaced_frac",
)


# ---------------------------------------------------------------------------
# JAX core
# ---------------------------------------------------------------------------


def placed_demand(req, assignment, n_nodes):
    """(N, R) demand committed by the placements: each placed pod's fit
    demand (request with the pods slot at 1) scatter-added onto its node."""
    import jax.numpy as jnp

    from scheduler_plugins_tpu.ops.fit import pod_fit_demand

    demand = pod_fit_demand(req)
    placed = assignment >= 0
    add = jnp.where(placed[:, None], demand, 0)
    return jnp.zeros((n_nodes, req.shape[1]), req.dtype).at[
        jnp.maximum(assignment, 0)
    ].add(add)


def fragmentation(free, node_mask):
    """Scalar float64 free-capacity fragmentation (see module docstring)."""
    import jax.numpy as jnp

    freef = jnp.where(node_mask[:, None], free, 0).astype(jnp.float64)
    core = freef[:, (CPU_I, MEM_I)]
    total = core.sum(axis=0)
    largest = core.max(axis=0)
    frag = jnp.where(total > 0, 1.0 - largest / jnp.maximum(total, 1.0), 0.0)
    return frag.mean()


def packed_utilization(alloc, free, node_mask):
    """Scalar float64 packing gauge (ISSUE 14): 1 − the normalized free
    capacity on nodes HOLDING ≥ 1 POD — per core resource (cpu, memory),
    sum of free over occupied schedulable nodes divided by the sum of
    allocatable over the same nodes, averaged over the two and
    subtracted from 1. A node "holds a pod" when its CANONICAL pods-slot
    usage (allocatable − free) is positive, so resident AND this-cycle
    placements both count. 0.0 when no node holds a pod (an empty
    cluster is not "perfectly packed"); → 1 as the occupied fleet fills.
    Unlike `fragmentation` (where the free dust sits) this is the direct
    consolidation gauge the packing solve mode climbs: emptying a
    lightly-loaded node removes its free from the numerator entirely."""
    import jax.numpy as jnp

    from scheduler_plugins_tpu.ops import PODS_I

    allocf = jnp.asarray(alloc).astype(jnp.float64)
    freef = jnp.asarray(free).astype(jnp.float64)
    occ = node_mask & (allocf[:, PODS_I] - freef[:, PODS_I] > 0)
    num = jnp.where(occ[:, None], freef, 0.0)[:, (CPU_I, MEM_I)].sum(axis=0)
    den = jnp.where(occ[:, None], allocf, 0.0)[:, (CPU_I, MEM_I)].sum(axis=0)
    frac = jnp.where(den > 0, num / jnp.maximum(den, 1.0), 0.0)
    return jnp.where(occ.any(), (1.0 - frac).mean(), 0.0)


def util_imbalance(alloc, free, node_mask):
    """Scalar float64 population stddev of per-node cpu/mem utilization
    over schedulable nodes."""
    import jax.numpy as jnp

    allocf = jnp.asarray(alloc).astype(jnp.float64)[:, (CPU_I, MEM_I)]
    usedf = allocf - jnp.asarray(free).astype(jnp.float64)[:, (CPU_I, MEM_I)]
    util = jnp.where(allocf > 0, usedf / jnp.maximum(allocf, 1.0), 0.0)
    node_util = util.mean(axis=1)
    n = jnp.maximum(node_mask.sum(), 1)
    mean = jnp.where(node_mask, node_util, 0.0).sum() / n
    var = jnp.where(node_mask, (node_util - mean) ** 2, 0.0).sum() / n
    return jnp.sqrt(var)


def _quality_terms(snap, assignment, wait):
    import jax.numpy as jnp

    from scheduler_plugins_tpu.ops.fit import free_capacity

    free0 = free_capacity(snap.nodes.alloc, snap.nodes.requested)
    free0 = jnp.where(snap.nodes.mask[:, None], free0, 0)
    free = free0 - placed_demand(snap.pods.req, assignment, snap.num_nodes)
    placed = (assignment >= 0) & snap.pods.mask
    n_real = jnp.maximum(snap.pods.mask.sum(), 1)
    return {
        "fragmentation": fragmentation(free, snap.nodes.mask),
        "util_imbalance": util_imbalance(
            snap.nodes.alloc, free, snap.nodes.mask
        ),
        "packed_utilization": packed_utilization(
            snap.nodes.alloc, free, snap.nodes.mask
        ),
        "gang_wait_frac": (
            jnp.where(placed, wait, False).sum().astype(jnp.float64)
            / jnp.maximum(placed.sum(), 1)
        ),
        "unplaced_frac": (
            1.0 - placed.sum().astype(jnp.float64) / n_real
        ),
    }


_CYCLE_JIT = None
_BATCH_JIT = None


def cycle_quality(snap, assignment, admitted, wait):
    """{objective: float} for one solved cycle — the jitted tensor entry
    the bench lines and `tools/replay.py quality` use. `admitted` is
    accepted for signature symmetry with the solve outputs (the
    objectives read placements and waits)."""
    import jax

    from scheduler_plugins_tpu.utils import observability as obs

    global _CYCLE_JIT
    if _CYCLE_JIT is None:
        _CYCLE_JIT = obs.compile_watch(
            jax.jit(lambda s, a, w: _quality_terms(s, a, w)),
            program="cycle_quality",
        )
    import jax.numpy as jnp

    out = _CYCLE_JIT(
        snap, jnp.asarray(assignment), jnp.asarray(wait).astype(bool)
    )
    return {k: float(v) for k, v in out.items()}


def batch_quality(snap, assignments, waits):
    """{objective: (K,) float64} for K candidate placements of ONE cycle
    in a single vmapped jit — how the counterfactual sweep scores every
    weight candidate without K dispatches."""
    import jax

    from scheduler_plugins_tpu.utils import observability as obs

    global _BATCH_JIT
    if _BATCH_JIT is None:
        _BATCH_JIT = obs.compile_watch(
            jax.jit(
                lambda s, A, W: jax.vmap(
                    lambda a, w: _quality_terms(s, a, w)
                )(A, W)
            ),
            program="batch_quality",
        )
    import jax.numpy as jnp

    out = _BATCH_JIT(
        snap, jnp.asarray(assignments), jnp.asarray(waits).astype(bool)
    )
    return {k: np.asarray(v) for k, v in out.items()}


def state_quality(alloc, used, node_mask=None):
    """{fragmentation, util_imbalance} of a CLUSTER STATE (allocatable vs
    used matrices, CANONICAL axis) — the multi-cycle benches (config 7
    serving churn, config 8 mega) score their accumulated end state with
    this instead of a single cycle's placements."""
    import jax.numpy as jnp

    alloc = jnp.asarray(alloc)
    used = jnp.asarray(used)
    if node_mask is None:
        node_mask = jnp.ones(alloc.shape[0], bool)
    free = jnp.where(node_mask[:, None], alloc - used, 0)
    return {
        "fragmentation": float(fragmentation(free, node_mask)),
        "util_imbalance": float(util_imbalance(alloc, free, node_mask)),
        "packed_utilization": float(
            packed_utilization(alloc, free, node_mask)
        ),
    }


# ---------------------------------------------------------------------------
# numpy twin (run_cycle's per-cycle stamp — no per-shape compiles)
# ---------------------------------------------------------------------------


def cycle_quality_np(snap, assignment, admitted, wait) -> dict:
    """Numpy twin of `cycle_quality` — identical float64 arithmetic on
    host arrays (tests/test_tuning.py gates the two for agreement)."""
    alloc = np.asarray(snap.nodes.alloc)
    requested = np.asarray(snap.nodes.requested)
    node_mask = np.asarray(snap.nodes.mask)
    req = np.asarray(snap.pods.req)
    pods_mask = np.asarray(snap.pods.mask)
    assignment = np.asarray(assignment)
    wait = np.asarray(wait).astype(bool)

    from scheduler_plugins_tpu.tuning.gates import pod_fit_demand_np

    free = np.where(node_mask[:, None], alloc - requested, 0)
    demand = pod_fit_demand_np(req)
    placed = (assignment >= 0) & pods_mask
    free = free.copy()
    np.add.at(free, assignment[placed], -demand[placed])

    core = np.where(node_mask[:, None], free, 0).astype(np.float64)[
        :, (CPU_I, MEM_I)
    ]
    total = core.sum(axis=0)
    largest = core.max(axis=0, initial=0.0)
    frag = np.where(total > 0, 1.0 - largest / np.maximum(total, 1.0), 0.0)

    # per-element cast of < 2^38 quantities (host-side metric, exact)
    allocf = alloc.astype(np.float64)[:, (CPU_I, MEM_I)]  # graft-lint: ignore[GL013]
    usedf = allocf - free.astype(np.float64)[:, (CPU_I, MEM_I)]
    util = np.where(allocf > 0, usedf / np.maximum(allocf, 1.0), 0.0)
    node_util = util.mean(axis=1)
    n = max(int(node_mask.sum()), 1)
    mean = float(np.where(node_mask, node_util, 0.0).sum()) / n
    var = float(np.where(node_mask, (node_util - mean) ** 2, 0.0).sum()) / n

    from scheduler_plugins_tpu.ops import PODS_I

    # packed_utilization numpy twin (same float64 arithmetic as the jax
    # core's `packed_utilization`)
    allocf2 = alloc.astype(np.float64)  # graft-lint: ignore[GL013] per-element, < 2^38
    freef2 = free.astype(np.float64)
    occ = node_mask & (allocf2[:, PODS_I] - freef2[:, PODS_I] > 0)
    num = np.where(occ[:, None], freef2, 0.0)[:, (CPU_I, MEM_I)].sum(axis=0)
    den = np.where(occ[:, None], allocf2, 0.0)[:, (CPU_I, MEM_I)].sum(axis=0)
    pfrac = np.where(den > 0, num / np.maximum(den, 1.0), 0.0)
    packed = float((1.0 - pfrac).mean()) if occ.any() else 0.0

    n_real = max(int(pods_mask.sum()), 1)
    return {
        "fragmentation": float(frag.mean()),
        "util_imbalance": float(np.sqrt(var)),
        "packed_utilization": packed,
        "gang_wait_frac": float((placed & wait).sum())
        / max(int(placed.sum()), 1),
        "unplaced_frac": 1.0 - float(placed.sum()) / n_real,
    }


def score_drift(scores, assignment, anchor) -> float:
    """Relative score-sum drift of `assignment` vs `anchor` placements on
    a (P, N) cycle-initial score matrix (same definition as
    `parallel.solver.score_drift_vs_sequential`, host-side). Out-of-range
    node indices (garbage placements — e.g. the chaos harness's corrupted
    sweep output) contribute nothing instead of crashing the scorer: the
    hard-constraint oracles are the gate that counts them, and the tuner
    must survive scoring them to reach that gate."""
    scores = np.asarray(scores)
    a = np.asarray(assignment)
    ref = np.asarray(anchor)

    def ssum(x):
        placed = (x >= 0) & (x < scores.shape[1])
        return int(scores[np.nonzero(placed)[0], x[placed]].sum())

    s_ref = ssum(ref)
    return (ssum(a) - s_ref) / max(abs(s_ref), 1)


# ---------------------------------------------------------------------------
# rank-aware gang placement objectives (gangs.topology; docs/GANGS.md)
# ---------------------------------------------------------------------------


def rank_gang_quality(rank_nodes, rank_mask, node_block, block_cost) -> dict:
    """Placement-quality objectives of a rank-gang solve — host float64
    reductions over `gangs.topology.pair_costs`:

    - ``gang_spread_cost``: mean over solved gangs of the SUM of
      inter-rank pair costs (each unordered pair once) — the aggregate
      network bill of the fleet's gang placements.
    - ``rank_cost_max``: max inter-rank pair cost across every gang — the
      single worst rank pair (the tightly-coupled MPI headline: one slow
      link paces the whole collective).
    - ``rank_cost_p99``: 99th percentile over ALL valid rank-pair costs —
      the tail the max alone can hide.

    Gangs with < 2 placed ranks contribute no pairs; with no pairs at all
    every objective is 0.0.
    """
    from scheduler_plugins_tpu.gangs.topology import pair_costs

    pc = np.asarray(
        pair_costs(rank_nodes, rank_mask, node_block, block_cost)
    )
    valid = pc >= 0
    if not valid.any():
        return {
            "gang_spread_cost": 0.0, "rank_cost_max": 0.0,
            "rank_cost_p99": 0.0,
        }
    per_gang_sum = np.sum(np.where(valid, pc, 0), axis=(1, 2)) / 2.0
    gang_has = valid.any(axis=(1, 2))
    flat = pc[valid].astype(np.float64)
    return {
        "gang_spread_cost": float(per_gang_sum[gang_has].mean()),
        "rank_cost_max": float(flat.max()),
        "rank_cost_p99": float(np.percentile(flat, 99)),
    }


def elastic_satisfaction_quality(reports_or_counts) -> float:
    """Fleet elastic-satisfaction fraction (`gangs.elastic`): accepts
    either (live_counts, desired_counts) arrays or an iterable of
    `CycleReport.rank_gangs` dicts (the LAST observation per gang wins —
    satisfaction is a state, not a flow)."""
    from scheduler_plugins_tpu.gangs.elastic import elastic_satisfaction

    if isinstance(reports_or_counts, tuple):
        return elastic_satisfaction(*reports_or_counts)
    latest: dict = {}
    for stats in reports_or_counts:
        for gang, row in stats.items():
            latest[gang] = (
                row.get("resident", 0) + row.get("placed_new", 0),
                row.get("desired", 0),
            )
    if not latest:
        return 1.0
    live = [v[0] for v in latest.values()]
    desired = [v[1] for v in latest.values()]
    return elastic_satisfaction(live, desired)


# ---------------------------------------------------------------------------
# multi-cycle: gang admission latency
# ---------------------------------------------------------------------------


class QualityAccumulator:
    """Host-side accumulator for objectives that need memory across
    cycles: gang admission latency (cycles from a gang's first pending
    appearance to its first member binding — 0 = admitted the cycle it
    arrived) and the preemption/nomination totals. Feed one
    `(cycle_no, report, gang_of)` per cycle; `gang_of` maps a pod uid to
    its gang name (or None)."""

    def __init__(self):
        self._first_pending: dict = {}
        self.latencies: dict = {}  # gang -> cycles waited
        self.preemptions = 0
        self.nominations = 0

    def observe(self, cycle_no: int, report, gang_of) -> None:
        self.nominations += len(report.preempted)
        self.preemptions += sum(
            len(victims) for _, victims in report.preempted.values()
        )
        pending = set()
        for uid in list(report.failed) + list(report.reserved):
            g = gang_of(uid)
            if g is not None:
                pending.add(g)
        admitted = set()
        for uid in report.bound:
            g = gang_of(uid)
            if g is not None:
                admitted.add(g)
        for g in pending | admitted:
            self._first_pending.setdefault(g, cycle_no)
        for g in admitted:
            if g not in self.latencies:
                self.latencies[g] = cycle_no - self._first_pending[g]

    def summary(self) -> dict:
        lat = list(self.latencies.values())
        return {
            "gang_latency_cycles": (
                float(np.mean(lat)) if lat else None
            ),
            "gangs_admitted": len(lat),
            "gangs_still_waiting": len(self._first_pending)
            - len(self.latencies),
            "preemptions": self.preemptions,
            "nominations": self.nominations,
        }


def gang_admission_latency(cycles) -> dict:
    """Gang admission latency over a recorded-corpus replay: `cycles` is
    an iterable of (gang_names, gang (P,), assignment (P,), wait (P,)) in
    cycle order. A gang is pending while a member sits in the batch, and
    admitted the first cycle a member places with quorum met (wait
    False). Returns {gang: cycles waited} for admitted gangs."""
    first: dict = {}
    admitted: dict = {}
    for cycle_no, (gang_names, gang, assignment, wait) in enumerate(cycles):
        gang = np.asarray(gang)
        assignment = np.asarray(assignment)
        wait = np.asarray(wait).astype(bool)
        for g, name in enumerate(gang_names):
            members = gang == g
            if not members.any():
                continue
            first.setdefault(name, cycle_no)
            if name not in admitted and (
                members & (assignment >= 0) & ~wait
            ).any():
                admitted[name] = cycle_no - first[name]
    return admitted
