"""Online self-tuning shadow lane with guarded rollout (ROADMAP item 2).

PR 8's counterfactual tuner closes the scoring loop OFFLINE: record a
corpus, sweep candidate weight vectors, emit a gated profile. "Learning
to Score" (arxiv 2603.10545) and the RL scheduler paper (arxiv
2601.13579) both argue the loop must close *online* — and closing it
safely is a robustness problem, not a perf one. `ShadowTuner` is that
closure, built so the live serving path can never be stalled, corrupted,
or silently regressed by its own tuner:

- **Shadow lane, off the cycle thread.** Every `sweep_every` cycles the
  tuner snapshots the last N COMPLETE flight-recorder ring records (the
  PR 5 capture at the Snapshot boundary) and replays them under K
  candidate weight vectors through the existing vmapped
  `parallel.solver.sweep_solve_fn` — on a dedicated daemon worker
  thread, against a SHADOW scheduler rebuilt from the records' own
  profile capture (`flightrec.rebuild_scheduler`), never the live one
  (tracing against the live plugins from a second thread would race the
  cycle's bind state). The in-flight job is deadlined (the PR 9
  watchdog-abandonment pattern): a hung sweep is orphaned and counted,
  and the lane degrades to "no tuning" — a tick is never stalled.
- **Promotion only through the gates.** A candidate is staged for
  promotion only when the shared promotion-gate body
  (`tuning.promotion` — the SAME code `tools/tune.py` emits offline
  profiles through) accepts it: zero hard-constraint violations across
  the whole corpus replay (numpy fit/mask/quota/gang-quorum oracles),
  no objective sold beyond tolerance, a strictly positive rank score —
  AND the same winner must repeat for `confirm_sweeps` consecutive
  sweeps (a sustained win, not one lucky corpus).
- **Rollout through the aux channel.** The swap applies at the cycle
  boundary (`framework.cycle.run_cycle(tuner=...)` calls `begin_cycle`
  before anything reads the profile) via
  `Scheduler.set_live_weights` — the weight vector is a traced argument
  of the "solve_live" program (`Plugin.bind_weight`), so promotion and
  rollback are argument changes with ZERO recompiles: the whole point
  of the aux-channel discipline.
- **Probation + auto-rollback.** Every promotion opens a probation
  window adjudicated by a PAIRED COUNTERFACTUAL PROBE: each probation
  cycle's ring record is replayed under [active, last-known-good] in
  one deadlined 2-lane sweep and the `scheduler_placement_quality`
  objectives are compared ON THE SAME SNAPSHOT — the cumulative gauges
  ride the workload's own common-mode trend, and only a paired
  same-cycle comparison isolates what the promotion changed (the PR 9
  probation-probe pattern, pointed at weights instead of backends; a
  level-vs-recent-baseline comparison is the fallback when no record
  exists). Any objective regressing beyond the `hysteresis` band —
  a large single-cycle regression immediately, a sustained one after
  `regress_cycles` consecutive cycles — or ANY watchdog fault
  (degraded flag / host-path solve / unadjudicable probe) rolls back
  to the last-known-good weights within <= `regress_cycles` (default
  2) cycles of the regression appearing. Rolled-back vectors are
  blocked from re-promotion and a cooldown window follows, so the
  controller cannot flap.
- **Self-disable.** `max_failures` consecutive sweep/promotion faults
  disable the lane entirely (state "disabled",
  `scheduler_tuner_state` = 3): a sick tuner turns itself off and live
  serving continues exactly as if `--tune` had never been passed.

Chaos sites `tune.sweep` (hang / garbage) and `tune.promote` (crash)
instrument the seams (`resilience.faults`); `make chaos-smoke` proves
every injected tuner fault leaves live placements bit-identical to a
no-tuner control. Bench config 14 ("drifting mix") is the measured
claim; `make tune-live-smoke` is the CI gate.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

import numpy as np

from scheduler_plugins_tpu.resilience import faults
from scheduler_plugins_tpu.tuning import promotion
from scheduler_plugins_tpu.utils import flightrec, observability as obs

#: the per-cycle quality objectives the probation window compares (the
#: subset of `promotion.RANKED_OBJECTIVES` that `run_cycle` stamps every
#: cycle — drift needs a replay anchor and is a sweep-time objective)
PROBATION_OBJECTIVES = (
    "fragmentation", "util_imbalance", "gang_wait_frac", "unplaced_frac",
)

#: tuner state -> `scheduler_tuner_state` gauge value
STATE_GAUGE = {"idle": 0, "probation": 1, "cooldown": 2, "disabled": 3}

#: tuner state-file format version (bump on incompatible layout change)
STATE_FORMAT = 1


def _prepare_ring_cycle(scheduler, rec, meta) -> None:
    """Re-prepare the shadow scheduler for ONE ring record and re-bake
    that record's captured host_state (the ring twin of
    `tools/tune.py._prepare_for_cycle` — must run immediately before
    every solve/score of that cycle)."""
    scheduler.prepare(meta, None)
    for plugin, prec in zip(scheduler.profile.plugins,
                            rec.manifest["plugins"]):
        hs = prec.get("host_state")
        if hs is not None:
            plugin.restore_host_state(flightrec.unpack_pytree(hs, rec.blobs))


def ring_corpus(records, scheduler, base_weights=None):
    """`promotion.CorpusCycle` list over COMPLETE in-memory ring records
    (newest last), all sharing `scheduler` (the rebuilt shadow scheduler
    — its jit caches amortize across sweeps). A record captured under
    weights other than `base_weights` (the sweep's lane-0 incumbent —
    e.g. pre-promotion cycles still in the ring) keeps its snapshot but
    drops its anchor: the incumbent lane legitimately places differently
    from what was recorded, so the anchor-mismatch disqualifier and the
    drift yardstick fall back to lane 0's own replayed placements."""
    base = (None if base_weights is None
            else tuple(int(w) for w in base_weights))
    corpus = []
    for rec in records:
        if not rec.complete or "outputs" not in rec.manifest:
            continue
        manifest = rec.manifest
        meta = flightrec.unpack_meta(manifest["meta"])
        snap = flightrec.unpack_pytree(manifest["snapshot"], rec.blobs)
        auxes = tuple(
            flightrec.unpack_pytree(p["aux"], rec.blobs)
            for p in manifest["plugins"]
        )
        out = manifest["outputs"]
        assignment = flightrec.unpack_pytree(out["assignment"], rec.blobs)
        wait_spec = out.get("wait")
        wait = (
            None if wait_spec is None
            else flightrec.unpack_pytree(wait_spec, rec.blobs)
        )
        rec_weights = tuple(
            int(p.get("weight", 1)) for p in manifest["plugins"]
        )
        anchor = (
            np.asarray(assignment)
            if base is None or rec_weights == base else None
        )
        corpus.append(promotion.CorpusCycle(
            scheduler=scheduler, snap=snap, meta=meta, auxes=auxes,
            anchor=anchor,
            wait=None if wait is None else np.asarray(wait),
            mode=out.get("mode"),
            prepare=(lambda sched, rec=rec, meta=meta:
                     _prepare_ring_cycle(sched, rec, meta)),
        ))
    return corpus


class _SweepWorker:
    """Persistent single daemon worker (the `resilience.watchdog._Worker`
    shape, non-blocking consumer side): jobs are polled, not awaited, so
    the cycle thread never blocks on the shadow lane; a job that outlives
    its deadline is ABANDONED with its worker (daemon thread — it can
    idle in a hung backend call forever without blocking process exit)."""

    def __init__(self):
        import queue

        self._jobs: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="shadow-tuner"
        )
        self._thread.start()

    def _loop(self):
        while True:
            fn, box, done = self._jobs.get()
            try:
                box["value"] = fn()
            except BaseException as exc:  # noqa: BLE001 - polled by owner
                box["error"] = exc
            finally:
                done.set()

    def submit(self, fn):
        box: dict = {}
        done = threading.Event()
        self._jobs.put((fn, box, done))
        return box, done


class ShadowTuner:
    """The guarded-rollout controller (module docstring has the design).

    Cycle-thread API (wired by `run_cycle(tuner=...)` / the daemon):

    - `begin_cycle(now_ms)` — the ONLY point weights may change: polls
      the shadow worker, applies a staged promotion or a decided
      rollback, dispatches the next sweep.
    - `observe_report(report)` — feeds the probation window from the
      cycle's quality stamp; decides rollbacks.
    - `note_fault(reason)` — immediate rollback while on probation (the
      daemon's watchdog seam; `observe_report` also reads the report's
      degraded/host-path flags).

    `sync=True` runs each sweep inline through
    `resilience.call_with_deadline` instead of the polled worker —
    deterministic for benches/tests; the deadline (and the degrade-to-
    no-tuning contract) is identical. `observe_only=True` keeps the full
    shadow lane running but never stages a promotion — the overhead
    measurement mode, and a standing proof the lane alone cannot change
    live placements."""

    def __init__(self, scheduler, recorder=None, *, candidates: int = 24,
                 corpus_cycles: int = 3, sweep_every: int = 4,
                 confirm_sweeps: int = 2, tolerance: float = 0.01,
                 drift_tolerance: float = 0.10,
                 probation_cycles: int = 6, baseline_window: int = 8,
                 baseline_min: int = 2, baseline_recent: int = 4,
                 hysteresis: float = 0.01,
                 regress_cycles: int = 2, max_failures: int = 3,
                 cooldown_cycles: int = 8, deadline_s: Optional[float] = None,
                 observe_only: bool = False, sync: bool = False,
                 seed: int = 0):
        from collections import deque

        if getattr(scheduler.profile, "solve_mode", "sequential") != (
            "sequential"
        ):
            # fail at construction, not at the first promotion: the live
            # rollout seam is the sequential parity path's traced-weight
            # argument — a packing-mode profile would accept a gated
            # promotion and then raise on every subsequent solve
            raise ValueError(
                f"online tuning requires the sequential parity path; "
                f"profile {scheduler.profile.name!r} selects solve mode "
                f"{scheduler.profile.solve_mode!r}"
            )
        self.scheduler = scheduler
        self.recorder = recorder if recorder is not None else flightrec.recorder
        self.candidates = max(2, int(candidates))
        self.corpus_cycles = max(1, int(corpus_cycles))
        self.sweep_every = max(1, int(sweep_every))
        self.confirm_sweeps = max(1, int(confirm_sweeps))
        self.tolerance = float(tolerance)
        #: drift (score-sum vs the incumbent surface) stays a
        #: disqualification RAIL but gets its own, looser tolerance and
        #: no rank-sum vote: over a drifting workload the incumbent's
        #: score surface is exactly what goes stale, and ranking on
        #: drift-vs-incumbent would veto every adaptation (see
        #: `promotion.rank_candidates`)
        self.drift_tolerance = float(drift_tolerance)
        self.probation_cycles = max(1, int(probation_cycles))
        self.baseline_min = max(1, int(baseline_min))
        self.baseline_recent = max(1, int(baseline_recent))
        self.hysteresis = float(hysteresis)
        self.regress_cycles = max(1, int(regress_cycles))
        self.max_failures = max(1, int(max_failures))
        self.cooldown_cycles = max(0, int(cooldown_cycles))
        if deadline_s is None:
            deadline_s = float(os.environ.get("SPT_TUNE_TIMEOUT_S", 30.0))
        self.deadline_s = deadline_s
        self.observe_only = bool(observe_only)
        self.sync = bool(sync)
        self.seed = int(seed)

        #: the weights currently live (== scheduler's view); promotions
        #: move it, rollbacks restore `last_known_good`
        self.active = np.asarray(
            [int(p.weight) for p in scheduler.profile.plugins], np.int64
        )
        self.last_known_good = self.active.copy()
        self.state = "idle"
        self.disabled_reason: Optional[str] = None
        self.cycle = 0
        self.promotions = 0
        self.rollbacks = 0
        self.sweeps = 0
        self.sweep_failures = 0
        self.last_rollback_reason: Optional[str] = None
        self.last_promotion_cycle: Optional[int] = None
        self.last_rollback_cycle: Optional[int] = None
        #: weight tuples rolled back on probation — never re-promoted
        self.blocked: set = set()
        self._lock = threading.Lock()
        self._window: "deque" = deque(maxlen=max(2, int(baseline_window)))
        self._baseline: Optional[dict] = None
        self._probation_elapsed = 0
        self._regress_counts: dict = {}
        self._cooldown_until = -1
        self._consecutive_failures = 0
        self._first_regress_cycle = None
        self.last_rollback_detect_cycles = None
        self._pending: Optional[dict] = None
        self._last_winner: Optional[tuple] = None
        self._winner_streak = 0
        self._sweep_seq = 0
        self._worker: Optional[_SweepWorker] = None
        self._inflight: Optional[dict] = None
        #: shadow scheduler cache (one rebuild per profile identity — its
        #: jit caches amortize the sweep program across jobs). Guarded by
        #: its own lock, NOT self._lock: the sweep worker, the deadlined
        #: counterfactual probe, and main-thread invalidation all touch
        #: it, and the rebuild trace it serializes is too slow to hold
        #: the controller lock across. Order: _lock may nest _shadow_lock,
        #: never the reverse.
        self._shadow_lock = threading.Lock()
        self._shadow_key = None
        self._shadow_sched = None
        self._export_gauges()

    # -- gauges ----------------------------------------------------------
    def _export_gauges(self) -> None:
        obs.metrics.set_gauge(obs.TUNER_STATE, STATE_GAUGE[self.state])
        digest = promotion.weights_digest(self.active)
        obs.metrics.set_gauge(
            obs.TUNER_ACTIVE_WEIGHTS, int(digest, 16)
        )

    # -- the cycle-boundary hook (weight-swap seam) ----------------------
    def begin_cycle(self, now_ms: int = 0) -> None:
        """Runs on the cycle thread BEFORE the cycle reads the profile:
        the one safe point to swap weights. Never raises — a tuner fault
        must cost tuning, not the tick."""
        with self._lock:
            self.cycle += 1
            if self.state == "disabled":
                return
            self._poll_inflight_locked()
            if self.state == "cooldown" and self.cycle >= self._cooldown_until:
                self.state = "idle"
            if (
                self._pending is not None
                and self.state in ("idle", "cooldown")
                # never start probation while a sweep job is still in
                # flight: the probation probe and the job would share
                # the shadow scheduler from two threads
                and self._inflight is None
            ):
                self._apply_pending_locked()
            self._maybe_dispatch_locked()
            self._export_gauges()

    def observe_report(self, report) -> None:
        """Runs on the cycle thread after finalize: probation evidence.
        A cycle with no solve (no quality stamp) contributes nothing —
        probation advances only on observed cycles."""
        with self._lock:
            if self.state == "disabled":
                return
            degraded = bool(getattr(report, "degraded", False)) or (
                getattr(report, "solve_path", None) == "host"
            )
            if self.state == "probation" and degraded:
                # ANY watchdog fault during probation rolls back
                # immediately: a degraded cycle's quality is evidence of
                # nothing, and new weights must never ride out an
                # incident window unobserved
                self._rollback_locked(
                    "watchdog-fault:"
                    + (getattr(report, "solve_path", None) or "degraded")
                )
                return
            quality = getattr(report, "quality", None)
            if quality is None:
                return
            q = {
                name: float(quality[name])
                for name in PROBATION_OBJECTIVES if name in quality
            }
            if not q:
                return
            if self.state != "probation":
                self._window.append(q)
                return
            self._probation_elapsed += 1
        # the counterfactual probe runs OUTSIDE the lock: it is deadlined
        # at `deadline_s` and pays the 2-lane sweep compile once per pod
        # bucket — /healthz `status()` and the SIGTERM `state_dict()`
        # must stay responsive meanwhile. All state MUTATION happens on
        # this (cycle) thread, so only readers and `note_fault` can
        # interleave; the verdict is re-checked under the lock.
        deltas = self._probation_deltas(q)
        with self._lock:
            if self.state != "probation":
                return  # note_fault rolled back while the probe ran
            if deltas is None:
                # the counterfactual probe could not run (hung, errored):
                # an UNVERIFIABLE probation cycle is a watchdog fault —
                # new weights must not ride out a window the controller
                # cannot adjudicate. A timed-out probe also leaves a
                # zombie worker holding the cached shadow scheduler —
                # drop the cache so later sweeps rebuild fresh
                with self._shadow_lock:
                    self._shadow_sched = None
                    self._shadow_key = None
                self._rollback_locked("watchdog-fault:probe-unavailable")
                return
            for name, delta in deltas.items():
                # sense-adjusted delta: negative = worse than the
                # last-known-good counterfactual (or, on the fallback
                # path, the recent pre-promotion baseline). Two-trigger
                # detector, both gated by the `hysteresis` amplitude
                # band so sub-threshold noise can never fire (the
                # no-flap contract): a LARGE single-cycle regression
                # (>= regress_cycles * hysteresis) rolls back
                # immediately; a SUSTAINED one (beyond hysteresis for
                # regress_cycles consecutive cycles) rolls back within
                # the window — so any real regression is out within
                # regress_cycles (default 2) cycles of appearing
                if delta < -self.hysteresis:
                    if self._first_regress_cycle is None:
                        self._first_regress_cycle = self.cycle
                    self._regress_counts[name] = (
                        self._regress_counts.get(name, 0) + 1
                    )
                else:
                    self._regress_counts[name] = 0
                if (
                    delta < -(self.hysteresis * self.regress_cycles)
                    or self._regress_counts[name] >= self.regress_cycles
                ):
                    self._rollback_locked(f"quality-regression:{name}")
                    return
            if self._probation_elapsed >= self.probation_cycles:
                self._confirm_locked()

    def _probation_deltas(self, q: dict) -> Optional[dict]:
        """Per-objective sense-adjusted deltas for one probation cycle,
        positive = the promoted weights are doing fine.

        Primary instrument: the PAIRED COUNTERFACTUAL PROBE — replay the
        cycle that JUST finalized (its ring record) under [active,
        last-known-good] in one 2-lane sweep and compare placement
        quality ON THE SAME SNAPSHOT. The per-cycle quality gauges are
        cumulative cluster-state reductions that ride the workload's own
        common-mode trend (a drifting mix makes them rise and fall for
        reasons no weight vector controls); a paired same-cycle
        comparison cancels the trend exactly, so the regression decision
        measures only what the promotion changed — the PR 9 probation-
        probe pattern, pointed at weights instead of backends. The probe
        is deadlined; a hung/errored probe returns None and the caller
        treats the cycle as a watchdog fault.

        Fallback (recorder has no usable record of this cycle): the
        sense-adjusted level vs the recent pre-promotion baseline."""
        from scheduler_plugins_tpu.tuning.quality import SENSE

        probe = None
        try:
            from scheduler_plugins_tpu.resilience.watchdog import (
                call_with_deadline,
            )

            probe = call_with_deadline(
                self._counterfactual_pair, self.deadline_s,
                label="tune.probe",
            )
        except Exception:  # noqa: BLE001 - adjudicated by the caller
            return None
        if probe is not None:
            q_active, q_good = probe
            return {
                name: SENSE[name] * (q_active[name] - q_good[name])
                for name in PROBATION_OBJECTIVES
                if name in q_active and name in q_good
            }
        if self._baseline is None:
            return None
        return {
            name: SENSE[name] * (value - self._baseline[name])
            for name, value in q.items()
            if name in self._baseline
        }

    def _counterfactual_pair(self):
        """({objective: float} under active, same under last-known-good)
        for the newest complete ring record — one 2-lane vmapped sweep,
        or None when no record exists (fallback path adjudicates)."""
        records = [
            rec for rec in self.recorder.records()
            if rec.complete and "outputs" in rec.manifest
        ]
        if not records:
            return None
        from scheduler_plugins_tpu.tuning import quality as Q
        from scheduler_plugins_tpu.tuning import sweep as sweep_mod

        rec = records[-1]
        # paired snapshot under the controller lock: `active` and
        # `last_known_good` must come from the SAME promotion epoch —
        # this probe runs on a deadline worker while the main thread can
        # promote/rollback between two bare attribute reads, and a torn
        # pair makes the 2-lane counterfactual compare weight vectors
        # that never coexisted (race_audit CA001)
        with self._lock:
            active = np.asarray(self.active, np.int64).copy()
            good = np.asarray(self.last_known_good, np.int64).copy()
        shadow = self._shadow_scheduler(rec)
        corpus = ring_corpus([rec], shadow, base_weights=active)
        cc = corpus[0]
        cc.prepare(cc.scheduler)
        W = np.stack([active, good])
        A, _adm, wt = sweep_mod.sweep_cycle(shadow, cc.snap, W,
                                            auxes=cc.auxes)
        q = Q.batch_quality(cc.snap, A, wt)
        q_active = {name: float(v[0]) for name, v in q.items()}
        q_good = {name: float(v[1]) for name, v in q.items()}
        return q_active, q_good

    def note_fault(self, reason: Optional[str] = None) -> None:
        """External watchdog seam: a backend fault observed outside the
        report path (the daemon's resilience layer) rolls an active
        probation back immediately."""
        with self._lock:
            if self.state == "probation":
                self._rollback_locked(f"watchdog-fault:{reason or 'fault'}")

    def inject_promotion(self, weights) -> None:
        """Harness hook (bench config 14's injected-regression phase, the
        rollback decision tables): stage `weights` for promotion at the
        next cycle boundary, BYPASSING the gates. Never used by
        production wiring — the daemon has no path to it; it exists so
        the auto-rollback machinery can be demonstrated on demand."""
        with self._lock:
            self._pending = {
                "weights": tuple(int(w) for w in weights), "forced": True,
            }

    # -- promotion / rollback (all under self._lock) ---------------------
    def _apply_pending_locked(self) -> None:
        pending, self._pending = self._pending, None
        if self.observe_only and not pending.get("forced"):
            return
        if self._baseline_snapshot() is None:
            # no pre-promotion baseline yet: without one the probation
            # window could not detect a regression — re-stage and wait
            self._pending = pending
            return
        weights = np.asarray(pending["weights"], np.int64)
        prev = self.active.copy()
        spec = None
        if faults.ACTIVE is not None:
            spec = faults.ACTIVE.fire(faults.TUNE_PROMOTE)
        try:
            if spec is not None and spec.kind == "crash":
                raise RuntimeError("injected promotion crash (tune.promote)")
            self.scheduler.set_live_weights(weights)
        except Exception as exc:
            # the promotion died mid-apply: restore the incumbent
            # defensively (set_live_weights may or may not have landed),
            # count the fault, and keep serving — live placements are
            # untouched either way
            try:
                self.scheduler.set_live_weights(prev)
            except Exception as restore_exc:  # graft-lint: ignore[GL010] — best-effort incumbent restore inside the fault handler below, which already counts/logs/disables; `prev` was valid moments ago so this cannot realistically fail
                obs.logger.warning(
                    "tuner incumbent restore failed too: %s", restore_exc
                )
            self.sweep_failures += 1
            obs.metrics.inc(obs.TUNER_SWEEP_FAILURES)
            self._consecutive_failures += 1
            obs.logger.warning("tuner promotion failed (%s): incumbent "
                               "weights kept", exc)
            self._maybe_disable_locked(f"promote-crash: {exc}")
            return
        self.active = weights
        self.promotions += 1
        obs.metrics.inc(obs.TUNER_PROMOTIONS)
        self.last_promotion_cycle = self.cycle
        self._baseline = self._baseline_snapshot()
        self._probation_elapsed = 0
        self._regress_counts = {}
        self._first_regress_cycle = None
        self.state = "probation"
        self._winner_streak = 0
        self._last_winner = None
        obs.logger.info(
            "tuner promoted weights %s (digest %s): probation for %d "
            "cycles vs baseline %s",
            [int(w) for w in weights], promotion.weights_digest(weights),
            self.probation_cycles,
            {k: round(v, 4) for k, v in (self._baseline or {}).items()},
        )

    def _baseline_snapshot(self) -> Optional[dict]:
        if len(self._window) < self.baseline_min:
            return None
        # the MOST RECENT pre-promotion cycles only: the quality gauges
        # are cumulative cluster-state reductions that TREND under a
        # drifting workload, and a baseline averaged over the whole
        # window would sit below/above the trend — falsely rolling back
        # a good promotion (or masking a bad one) on level, not effect
        recent = list(self._window)[-self.baseline_recent:]
        names = set().union(*(q.keys() for q in recent))
        return {
            name: float(np.mean([q[name] for q in recent if name in q]))
            for name in names
        }

    def _rollback_locked(self, reason: str) -> None:
        self.blocked.add(tuple(int(w) for w in self.active))
        try:
            self.scheduler.set_live_weights(self.last_known_good)
        except Exception as exc:  # pragma: no cover - defensive
            obs.logger.warning("tuner rollback set_live_weights failed: %s",
                               exc)
        self.active = np.asarray(self.last_known_good, np.int64).copy()
        self.rollbacks += 1
        obs.metrics.inc(obs.TUNER_ROLLBACKS)
        self.last_rollback_reason = reason
        self.last_rollback_cycle = self.cycle
        #: cycles from the first above-hysteresis regression observation
        #: to this rollback — the "rollback <= regress_cycles" evidence
        #: (0 for watchdog-fault rollbacks with no quality prelude)
        self.last_rollback_detect_cycles = (
            self.cycle - self._first_regress_cycle
            if self._first_regress_cycle is not None else 0
        )
        self.state = "cooldown"
        self._cooldown_until = self.cycle + self.cooldown_cycles
        self._baseline = None
        self._probation_elapsed = 0
        self._regress_counts = {}
        self._window.clear()
        self._pending = None
        self._winner_streak = 0
        self._last_winner = None
        self._export_gauges()
        obs.logger.warning(
            "tuner ROLLBACK (%s): last-known-good weights %s restored, "
            "cooldown %d cycles",
            reason, [int(w) for w in self.active], self.cooldown_cycles,
        )

    def _confirm_locked(self) -> None:
        self.last_known_good = self.active.copy()
        self.state = "idle"
        self._baseline = None
        self._probation_elapsed = 0
        self._regress_counts = {}
        # the pre-promotion window described the OLD weights' regime:
        # restart baseline accumulation under the confirmed vector
        self._window.clear()
        obs.logger.info(
            "tuner promotion CONFIRMED: weights %s are the new "
            "last-known-good", [int(w) for w in self.active],
        )

    def _maybe_disable_locked(self, reason: str) -> None:
        if self._consecutive_failures >= self.max_failures:
            self.state = "disabled"
            self.disabled_reason = reason
            self._pending = None
            self._inflight = None
            obs.logger.warning(
                "shadow tuner DISABLED after %d consecutive faults (%s): "
                "live serving continues on the incumbent weights",
                self._consecutive_failures, reason,
            )
            self._export_gauges()

    # -- the shadow sweep lane -------------------------------------------
    def _maybe_dispatch_locked(self) -> None:
        if (
            self.state not in ("idle", "cooldown")
            or self._pending is not None
            or self.cycle % self.sweep_every != 0
        ):
            return
        if self._inflight is not None:
            return
        if not self.recorder.enabled:
            return
        records = [
            rec for rec in self.recorder.records()
            if rec.complete and "outputs" in rec.manifest
        ]
        if len(records) < self.corpus_cycles:
            return
        records = records[-self.corpus_cycles:]
        base = self.active.copy()
        self._sweep_seq += 1
        # candidate generation is seeded per INCUMBENT EPOCH, not per
        # sweep: consecutive sweeps propose the same candidate set over
        # FRESH corpora, so a `confirm_sweeps` streak measures corpus
        # stability (a sustained win), never candidate-set luck
        seq = 97 * (self.promotions + self.rollbacks)
        if self.sync:
            from scheduler_plugins_tpu.resilience.watchdog import (
                BackendUnavailable,
                call_with_deadline,
            )

            try:
                verdict_w = call_with_deadline(
                    lambda: self._sweep_job(records, base, seq),
                    self.deadline_s, label="tune.sweep",
                )
                self._consume_sweep_locked(verdict_w)
            except BackendUnavailable as exc:
                self._sweep_failed_locked(str(exc))
            except Exception as exc:  # noqa: BLE001 - lane must not raise
                self._sweep_failed_locked(f"{type(exc).__name__}: {exc}")
            return
        if self._worker is None:
            self._worker = _SweepWorker()
        box, done = self._worker.submit(
            lambda: self._sweep_job(records, base, seq)
        )
        self._inflight = {
            "box": box, "done": done, "started": time.monotonic(),
        }

    def _poll_inflight_locked(self) -> None:
        job = self._inflight
        if job is None:
            return
        if job["done"].is_set():
            self._inflight = None
            if "error" in job["box"]:
                exc = job["box"]["error"]
                self._sweep_failed_locked(f"{type(exc).__name__}: {exc}")
            else:
                self._consume_sweep_locked(job["box"]["value"])
            return
        if time.monotonic() - job["started"] > self.deadline_s:
            # hung sweep: abandon the worker (it cannot be interrupted
            # inside a backend call; daemon thread, result discarded) —
            # the lane degrades to "no tuning", the tick is unaffected
            self._inflight = None
            self._worker = None
            self._sweep_failed_locked(
                f"timeout ({self.deadline_s}s) in tune.sweep"
            )

    def _sweep_failed_locked(self, reason: str) -> None:
        self.sweep_failures += 1
        obs.metrics.inc(obs.TUNER_SWEEP_FAILURES)
        self._consecutive_failures += 1
        # drop the cached shadow scheduler: an ABANDONED (timed-out) job
        # keeps running on its worker and still holds this object — the
        # next sweep/probe must rebuild a fresh one rather than race the
        # zombie's plugin host-state mutations (a shared scheduler under
        # two threads could produce feasible-but-wrong candidates that
        # PASS the gates). Costs one rebuild + retrace after a failure.
        with self._shadow_lock:
            self._shadow_sched = None
            self._shadow_key = None
        obs.logger.warning("shadow sweep failed (%s): no tuning this round",
                           reason)
        self._maybe_disable_locked(reason)

    def _consume_sweep_locked(self, result) -> None:
        self.sweeps += 1
        obs.metrics.inc(obs.TUNER_SWEEPS)
        self._consecutive_failures = 0
        verdict, W = result
        if not verdict.accepted or self.observe_only:
            self._winner_streak = 0
            self._last_winner = None
            return
        winner = None
        W = np.asarray(W)
        for k in verdict.order:
            k = int(k)
            if (
                k == 0 or not np.isfinite(verdict.score[k])
                or verdict.score[k] <= 0 or verdict.violations[k] > 0
            ):
                break  # order is best-first: nothing promotable remains
            cand = tuple(int(w) for w in W[k])
            if cand not in self.blocked:
                winner = cand
                break
        if winner is None:
            self._winner_streak = 0
            self._last_winner = None
            return
        if winner == self._last_winner:
            self._winner_streak += 1
        else:
            self._last_winner = winner
            self._winner_streak = 1
        # sustained win: the same vector must survive `confirm_sweeps`
        # independent corpus evaluations before it may touch live serving
        if self._winner_streak >= self.confirm_sweeps:
            self._pending = {"weights": winner, "forced": False}

    def _sweep_job(self, records, base, seq):
        """Runs OFF the cycle thread (or deadlined inline under `sync`):
        rebuild/reuse the shadow scheduler, sweep the ring corpus under
        the candidate matrix, gate through `tuning.promotion`. The
        TUNE_SWEEP chaos site instruments exactly this seam."""
        spec = None
        if faults.ACTIVE is not None:
            spec = faults.ACTIVE.fire(faults.TUNE_SWEEP)
        if spec is not None and spec.kind == "hang":
            time.sleep(spec.seconds)
        shadow = self._shadow_scheduler(records[0])
        # the drift yardstick is the INCUMBENT's objective: score the
        # corpus with the live weight vector, not the recorded one
        for plugin, w in zip(shadow.profile.plugins, base):
            plugin.weight = int(w)
        corpus = ring_corpus(records, shadow, base_weights=base)
        from scheduler_plugins_tpu.tuning import sweep as sweep_mod

        W = sweep_mod.candidate_weights(
            base, self.candidates, seed=self.seed + seq
        )
        mutate = None
        if spec is not None and spec.kind == "garbage":
            rng = faults.ACTIVE.rng

            def mutate(A, adm, wt):
                # a desynced sweep answers with plausible-length junk on
                # every candidate lane; the incumbent lane is kept so the
                # gate's frame of reference survives — the oracles must
                # disqualify every corrupted lane
                A = np.asarray(A).copy()
                n_nodes = 1 << 20
                A[1:] = rng.integers(
                    n_nodes, n_nodes + 1000, size=A[1:].shape
                )
                return A, adm, wt

        verdict = promotion.evaluate_candidates(
            corpus, W, self.tolerance, mutate=mutate,
            rank_objectives=PROBATION_OBJECTIVES,
            tolerances={"drift": self.drift_tolerance},
        )
        return verdict, W

    def _shadow_scheduler(self, rec):
        """Rebuild (or reuse) the shadow replay scheduler from a ring
        record's own profile capture — the live scheduler is never
        touched from the sweep thread. `_shadow_lock` serializes the
        memo AND the rebuild itself: the sweep worker and the deadlined
        counterfactual probe both land here, and two threads tracing
        through `rebuild_scheduler` at once corrupt the jit cache (the
        _EXPLAIN_LOCK lesson; race_audit CA001/CA003)."""
        manifest = rec.manifest
        key = (
            flightrec._canonical_json(manifest.get("profile_config")),
            tuple(p["class"] for p in manifest["plugins"]),
        )
        with self._shadow_lock:
            if self._shadow_key == key and self._shadow_sched is not None:
                return self._shadow_sched
            scheduler, _meta, _faithful = flightrec.rebuild_scheduler(
                manifest,
                lambda s, rec=rec: flightrec.unpack_pytree(s, rec.blobs),
            )
            # an ABANDONED probe/sweep may still reach this publish after
            # its deadline: lock-serialized and key-idempotent, so a late
            # stale publish costs at most one rebuild on the next key
            # check — it can never hand two threads one scheduler
            self._shadow_key = key  # race-audit: safe[CA005] — lock-serialized key-idempotent memo publish
            self._shadow_sched = scheduler  # race-audit: safe[CA005] — lock-serialized key-idempotent memo publish
            return scheduler

    def quiesce(self, timeout_s: float = 60.0) -> bool:
        """Wait for the in-flight shadow sweep (if any) to finish running
        — a bench/test determinism helper (the result is still consumed
        by the next `begin_cycle`); True when nothing is left running."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                job = self._inflight
            if job is None or job["done"].is_set():
                return True
            job["done"].wait(0.05)
        return False

    # -- introspection / persistence -------------------------------------
    def status(self) -> dict:
        """The /healthz tuner block."""
        with self._lock:
            return {
                "state": self.state,
                "active_weights": [int(w) for w in self.active],
                "active_digest": promotion.weights_digest(self.active),
                "last_known_good": [int(w) for w in self.last_known_good],
                "promotions": self.promotions,
                "rollbacks": self.rollbacks,
                "sweeps": self.sweeps,
                "sweep_failures": self.sweep_failures,
                "probation_elapsed": (
                    self._probation_elapsed
                    if self.state == "probation" else None
                ),
                "baseline": (
                    None if self._baseline is None
                    else {k: round(v, 6)
                          for k, v in self._baseline.items()}
                ),
                "staged": self._pending is not None,
                "last_rollback_reason": self.last_rollback_reason,
                "last_rollback_detect_cycles":
                    self.last_rollback_detect_cycles,
                "disabled_reason": self.disabled_reason,
                "observe_only": self.observe_only,
            }

    def state_dict(self) -> dict:
        """Persistable controller state (the daemon writes it next to the
        resilience checkpoint on SIGTERM; restart resumes with the
        promoted weights and the open probation window)."""
        with self._lock:
            return {
                "format": STATE_FORMAT,
                "active_weights": [int(w) for w in self.active],
                "last_known_good": [int(w) for w in self.last_known_good],
                "state": self.state,
                "probation_elapsed": self._probation_elapsed,
                "baseline": self._baseline,
                "promotions": self.promotions,
                "rollbacks": self.rollbacks,
                "blocked": [list(w) for w in sorted(self.blocked)],
                "disabled_reason": self.disabled_reason,
            }

    def restore_state(self, state: dict) -> bool:
        """Resume from a persisted `state_dict`. Returns False (and
        starts fresh) on a format/shape mismatch — a stale state file
        must never block startup."""
        if not isinstance(state, dict) or state.get("format") != STATE_FORMAT:
            return False
        L = len(self.scheduler.profile.plugins)
        active = state.get("active_weights")
        good = state.get("last_known_good")
        if (
            not isinstance(active, list) or len(active) != L
            or not isinstance(good, list) or len(good) != L
        ):
            return False
        with self._lock:
            self.scheduler.set_live_weights(active)
            self.active = np.asarray(active, np.int64)
            self.last_known_good = np.asarray(good, np.int64)
            restored = state.get("state", "idle")
            self.state = (
                restored if restored in STATE_GAUGE else "idle"
            )
            if self.state == "cooldown":
                self._cooldown_until = self.cycle + self.cooldown_cycles
            self._probation_elapsed = int(state.get("probation_elapsed", 0))
            baseline = state.get("baseline")
            self._baseline = baseline if isinstance(baseline, dict) else None
            if self.state == "probation" and self._baseline is None:
                # probation without a baseline cannot adjudicate: treat
                # the restart as a fresh confirmation window instead
                self.state = "idle"
            self.promotions = int(state.get("promotions", 0))
            self.rollbacks = int(state.get("rollbacks", 0))
            self.blocked = {
                tuple(int(x) for x in w)
                for w in state.get("blocked", []) or []
            }
            self.disabled_reason = state.get("disabled_reason")
            self._export_gauges()
        return True
