"""Hard-constraint replay oracles for counterfactual placements.

Independent numpy re-implementations of the three hard-constraint
families both solve paths enforce — resource fit, queue-order elastic
quota (Max + aggregate-Min), gang quorum Permit — replayed against a
snapshot and a candidate assignment. These mirror the differential-test
oracles (tests/test_differential.py, PR 2/7) and are the acceptance gate
for tuned-profile emission (`tools/tune.py`): a tuned weight vector is
emitted ONLY if every replay across the corpus shows zero violations.

Each oracle returns a violation COUNT (0 = clean) so the tune report can
say what broke, not just that something did.
"""

from __future__ import annotations

import numpy as np


def pod_fit_demand_np(req) -> np.ndarray:
    """Numpy twin of `ops.fit.pod_fit_demand`: the effective request with
    the pod-count slot charged 1 per pod — THE one host-side copy of the
    fit-demand rule, shared by these oracles, the quality telemetry
    (`tuning.quality.cycle_quality_np`) and the bench capacity audits, so
    a change to fit-demand semantics has exactly one numpy site to
    mirror. Deliberately a numpy re-statement, not a call into the jitted
    path — the oracles must stay independent of the solver."""
    from scheduler_plugins_tpu.ops import PODS_I

    demand = np.asarray(req).copy()
    demand[:, PODS_I] = 1
    return demand


def fit_violations(snap, assignment) -> int:
    """(node, resource) cells over allocatable after committing the
    placements (pods slot charged 1 per pod). Out-of-range node indices
    (garbage output — a desynced backend or corrupted sweep) are NOT
    this oracle's count: `mask_violations` charges them, and this one
    must survive scoring such an assignment so the gate can reject it
    instead of crashing."""
    alloc = np.asarray(snap.nodes.alloc)
    requested = np.asarray(snap.nodes.requested)
    assignment = np.asarray(assignment)
    used = requested.copy()
    demand = pod_fit_demand_np(snap.pods.req)
    placed = (assignment >= 0) & (assignment < alloc.shape[0])
    np.add.at(used, assignment[placed], demand[placed])
    return int((used > alloc).sum())


def mask_violations(snap, assignment) -> int:
    """Placements on unschedulable (masked) or padded node rows."""
    node_mask = np.asarray(snap.nodes.mask)
    assignment = np.asarray(assignment)
    placed = assignment[assignment >= 0]
    n = node_mask.shape[0]
    return int((placed >= n).sum() + (~node_mask[np.minimum(placed, n - 1)]
                                      & (placed < n)).sum())


def quota_violations(snap, assignment) -> int:
    """Placed quota-namespace pods that exceed their Max or the
    aggregate-Min pool at their own queue-order admission step (the scan
    semantics both solvers enforce; capacity_scheduling.go:208-282)."""
    if snap.quota is None:
        return 0
    req = np.asarray(snap.pods.req).astype(np.int64)
    ns = np.asarray(snap.pods.ns)
    has_q = np.asarray(snap.quota.has_quota)
    qmax = np.asarray(snap.quota.max).astype(np.int64)
    qmin = np.asarray(snap.quota.min).astype(np.int64)
    used = np.asarray(snap.quota.used).astype(np.int64).copy()
    assignment = np.asarray(assignment)
    agg_min = (qmin * has_q[:, None]).sum(axis=0)
    agg_used = (used * has_q[:, None]).sum(axis=0)
    violations = 0
    for p in range(len(assignment)):
        if assignment[p] < 0 or not has_q[ns[p]]:
            continue
        if (used[ns[p]] + req[p] > qmax[ns[p]]).any() or (
            agg_used + req[p] > agg_min
        ).any():
            violations += 1
            continue  # violating pod holds no capacity it was denied
        used[ns[p]] += req[p]
        agg_used += req[p]
    return violations


def gang_quorum_violations(snap, assignment, wait) -> int:
    """Gangs with a member BOUND (placed, not Permit-Wait) below quorum
    (assigned-before + placed-this-cycle < MinMember)."""
    if snap.gangs is None:
        return 0
    gang = np.asarray(snap.pods.gang)
    min_member = np.asarray(snap.gangs.min_member)
    assigned = np.asarray(snap.gangs.assigned)
    assignment = np.asarray(assignment)
    wait = np.asarray(wait).astype(bool)
    placed = assignment >= 0
    violations = 0
    for g in range(len(min_member)):
        members = gang == g
        bound = int((members & placed & ~wait).sum())
        total = int((members & placed).sum()) + int(assigned[g])
        if bound > 0 and total < int(min_member[g]):
            violations += 1
    return violations


def hard_violations(snap, assignment, wait) -> dict:
    """{family: count} + "total" — the one gate summary the tuner and the
    tune-smoke CI gate consume."""
    out = {
        "fit": fit_violations(snap, assignment),
        "mask": mask_violations(snap, assignment),
        "quota": quota_violations(snap, assignment),
        "gang_quorum": gang_quorum_violations(snap, assignment, wait),
    }
    out["total"] = sum(out.values())
    return out
