"""Vmapped counterfactual weight sweep over flight-recorder cycles.

The PR 5 flight recorder captures each cycle's FULL solver inputs; this
module replays a recorded cycle under K candidate plugin-weight vectors
in ONE vmapped batched solve (`parallel.solver.sweep_solve_fn`): the
candidate weights are traced arguments bound per lane through
`Plugin.bind_weight` — the aux-channel discipline applied to the one
profile knob (the score weight) the config format keeps host-side — so
all K candidates share a single compile and zero per-candidate retraces.
Candidate generation is seeded and deterministic: the identity row (the
recorded profile's own weights) always rides at index 0 as the in-band
baseline, followed by one-knob grid emphasis rows and Dirichlet
perturbations of the current weight profile ("Learning to Score",
arxiv 2603.10545, explores exactly this simplex).

`sweep_cycle` is the one-cycle engine; corpus aggregation, objective
ranking and gated profile emission live in `tools/tune.py`.
"""

from __future__ import annotations

import numpy as np

#: one-knob grid multipliers: for each plugin, emphasis (w*m) and
#: de-emphasis (max(1, w//m)) rows at these factors
GRID_FACTORS = (2, 4, 8)

#: integer budgets per plugin for Dirichlet rows: candidates live on the
#: simplex scaled to L*budget, so a ratio like 1.86:1 survives integer
#: rounding; several scales keep the distinct-candidate pool large even
#: for two-plugin profiles (weights multiply normalized scores <= 100,
#: so O(40) totals stay far inside int64)
WEIGHT_BUDGETS = (10, 20, 40)

#: Dirichlet concentration: alpha = normalized current weights * this —
#: samples cluster around the current profile instead of the uniform
#: corners (perturbation, not random search)
CONCENTRATION = 8.0


def candidate_weights(base, k: int, seed: int = 0) -> np.ndarray:
    """(K, L) int64 candidate weight matrix: row 0 = `base` (the current
    profile), then the one-knob grid, then seeded Dirichlet perturbations
    until `k` rows exist (duplicates dropped, so every lane is a distinct
    counterfactual). All weights >= 1 (the solve contracts — e.g. the
    targeted fast path — require positive weights)."""
    base = np.asarray(base, np.int64)
    L = base.shape[0]
    if (base < 1).any():
        raise ValueError("candidate sweep requires positive base weights")
    rows = [tuple(base)]
    seen = {tuple(base)}

    def add(row):
        row = tuple(int(max(w, 1)) for w in row)
        if row not in seen:
            seen.add(row)
            rows.append(row)

    for m in GRID_FACTORS:
        for i in range(L):
            up = base.copy()
            up[i] *= m
            add(up)
            down = base.copy()
            down[i] = max(1, int(down[i]) // m)
            add(down)
    rng = np.random.default_rng(seed)
    alpha = base.astype(np.float64) / base.sum() * CONCENTRATION  # graft-lint: ignore[GL013] weights <= 2^20
    guard = 0
    while len(rows) < k and guard < 64 * k:
        budget = L * WEIGHT_BUDGETS[guard % len(WEIGHT_BUDGETS)]
        guard += 1
        w = rng.dirichlet(alpha) * budget
        add(np.maximum(np.rint(w), 1).astype(np.int64))
    return np.asarray(rows[:k], np.int64)


def pad_candidates(W: np.ndarray) -> np.ndarray:
    """Pad the candidate axis to a power-of-two bucket with repeats of
    row 0, bounding jit retraces under candidate-count churn (the same
    discipline as `framework.runtime.run_explain_rows`)."""
    K = W.shape[0]
    bucket = 1 << max(int(K - 1).bit_length(), 0)
    if bucket == K:
        return W
    pad = np.broadcast_to(W[0], (bucket - K, W.shape[1]))
    return np.concatenate([W, pad], axis=0)


def sweep_cycle(scheduler, snap, W, auxes=None):
    """Replay one cycle under every row of `W` ((K, L) int64) in one
    vmapped solve. Returns (assignment (K, P), admitted (K, P), wait
    (K, P)) as host numpy, sliced back to the unpadded K. `auxes`
    force-binds recorded config arrays exactly like
    `Scheduler.solve(auxes=)` on the replay path."""
    import jax.numpy as jnp

    from scheduler_plugins_tpu.parallel.solver import sweep_solve_fn

    W = np.asarray(W, np.int64)
    K = W.shape[0]
    plugins = tuple(scheduler.profile.plugins)
    if W.shape[1] != len(plugins):
        raise ValueError(
            f"candidate width {W.shape[1]} != plugin count {len(plugins)}"
        )
    if auxes is None:
        auxes = tuple(p.aux() for p in plugins)
    fn = sweep_solve_fn(scheduler)
    out = fn(
        snap, scheduler.initial_state(snap), auxes,
        jnp.asarray(pad_candidates(W)),
    )
    assignment, admitted, wait = (np.asarray(x)[:K] for x in out)
    return assignment, admitted, wait
