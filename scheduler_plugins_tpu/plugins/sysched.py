"""SySched — syscall-aware pod spreading (Score + Normalize).

Reference: /root/reference/pkg/sysched/sysched.go:215-296. A pod's syscall set
comes from the SeccompProfile CRs its containers reference; score =
"extraneous syscall difference":

    |hostSyscalls - podSyscalls|
    + sum over existing pods p on the node of |(host ∪ pod) - p|

Lower is better (DefaultNormalizeScore reversed). Profile resolution
(sysched.go:124-210, lowered in state.snapshot._build_syscalls): container
SeccompProfile references (bare name, ns/name, or localhost path) merged
with the first SPO auto-annotation; a pod resolving NOTHING falls back to
the configured default all-syscalls CR, and only when that is absent too
does it score a huge constant on every node (the reference returns
math.MaxInt64 — clamped here to 2^53 so the normalize multiply cannot
overflow int64, which in Go silently wraps); after reverse-normalization
all nodes come out equal, so placement is unaffected.

The per-existing-pod sum uses the SyscallState decomposition (see
state.snapshot.SyscallState): pod_count * |newHost| - sum_s newHost[s]*counts.
"""

from __future__ import annotations

import jax.numpy as jnp

from scheduler_plugins_tpu.framework.plugin import Plugin
from scheduler_plugins_tpu.ops.normalize import default_normalize

NO_PROFILE_SCORE = 2**53


class SySched(Plugin):
    name = "SySched"

    def __init__(self, default_profile_namespace: str = "default",
                 default_profile_name: str = "all-syscalls"):
        # defaults.go:246-256
        self.default_profile_namespace = default_profile_namespace
        self.default_profile_name = default_profile_name

    def configure_cluster(self, cluster):
        """Install the default-profile fallback into the snapshot build: a
        pod resolving NO profile takes the configured all-syscalls CR's set
        (sysched.go:198-208); only when that CR is absent too does the pod
        score the MaxInt64-equivalent."""
        if cluster is not None:
            cluster.sysched_default_profile = (
                f"{self.default_profile_namespace}/{self.default_profile_name}"
            )

    def score(self, state, snap, p):
        if snap.syscalls is None:
            return None
        sys = snap.syscalls
        pod = sys.pod_sets[p]  # (S,)
        host = sys.host_sets  # (N, S)
        new_host = host | pod[None, :]
        # |host - pod|
        own_diff = jnp.sum(host & ~pod[None, :], axis=1).astype(jnp.int64)
        # sum_p |newHost - p| = pod_count*|newHost| - sum_s newHost[s]*counts
        new_size = jnp.sum(new_host, axis=1).astype(jnp.int64)
        overlap = jnp.sum(
            jnp.where(new_host, sys.counts, 0), axis=1
        ).astype(jnp.int64)
        others = sys.host_pod_count.astype(jnp.int64) * new_size - overlap
        total = own_diff + others
        # empty host -> 0 (sysched.go:255-259); no pod profile -> huge score
        total = jnp.where(sys.host_pod_count == 0, 0, total)
        return jnp.where(sys.has_profile[p], total, NO_PROFILE_SCORE)

    def normalize(self, scores, feasible):
        return default_normalize(scores, feasible, reverse=True)
