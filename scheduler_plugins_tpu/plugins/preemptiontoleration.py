"""PreemptionToleration — PostFilter plugin: DefaultPreemption with victim
exemption by PriorityClass toleration policy.

Reference: /root/reference/pkg/preemptiontoleration (SelectVictimsOnNode is a
near-copy of upstream DefaultPreemption except victims may be exempted:
ExemptedFromPreemption, preemption_toleration.go:124-181). The plugin itself
contributes no Filter/Score tensors — it configures the cycle's preemption
engine (framework.preemption) with toleration enabled.
"""

from __future__ import annotations

from scheduler_plugins_tpu.framework.plugin import Plugin
from scheduler_plugins_tpu.framework.preemption import (
    PreemptionEngine,
    PreemptionMode,
)
from scheduler_plugins_tpu.api import events as ev


class PreemptionToleration(Plugin):
    name = "PreemptionToleration"

    def __init__(self, min_candidate_nodes_percentage: int = None,
                 min_candidate_nodes_absolute: int = None):
        #: PreemptionTolerationArgs = upstream DefaultPreemptionArgs
        #: (/root/reference/apis/config/types.go PreemptionTolerationArgs;
        #: sampling preemption_toleration.go:306-331)
        PreemptionEngine.validate_sampling_args(  # fail fast at load time
            min_candidate_nodes_percentage, min_candidate_nodes_absolute
        )
        self.min_candidate_nodes_percentage = min_candidate_nodes_percentage
        self.min_candidate_nodes_absolute = min_candidate_nodes_absolute

    def events_to_register(self):
        # a victim's deletion admits the preemptor (upstream
        # DefaultPreemption registers Pod/Delete)
        return (ev.POD_DELETE,)

    def preemption_engine(self) -> PreemptionEngine:
        return PreemptionEngine(
            PreemptionMode.DEFAULT, toleration=True,
            min_candidate_nodes_percentage=self.min_candidate_nodes_percentage,
            min_candidate_nodes_absolute=self.min_candidate_nodes_absolute,
        )
