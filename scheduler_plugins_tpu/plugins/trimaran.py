"""Trimaran — the load-aware Score plugin family.

Reference: /root/reference/pkg/trimaran (shared Collector/handler/
resourcestats) with four Score-only plugins: TargetLoadPacking,
LoadVariationRiskBalancing, LowRiskOverCommitment, Peaks (SURVEY.md §2.7).

The metrics path maps as: load-watcher percentages land in
`MetricsState` (cluster store ingests them; the 30s collector goroutine
becomes a host-side refresh), the ScheduledPodsCache compensation becomes the
per-node `missing_cpu_millis` column, and each plugin body is one vectorized
curve from `ops.trimaran`.

Defaults (apis/config/v1/defaults.go:49-106): TLP target 40%, request
multiplier 1.5, default request 1000m; LVRB margin 1, sensitivity 1;
LROC smoothing window 5, risk-limit weight 0.5 each.
"""

from __future__ import annotations

from typing import Mapping, Optional

import jax.numpy as jnp
import numpy as np

from scheduler_plugins_tpu.framework.plugin import Plugin
from scheduler_plugins_tpu.ops import CPU_I, MEMORY_I
from scheduler_plugins_tpu.ops.normalize import peaks_normalize
from scheduler_plugins_tpu.ops.trimaran import (
    lroc_score,
    lvrb_score,
    lvrb_score_batch,
    peaks_score,
    tlp_score,
    tlp_score_batch,
)


def _validate_metric_provider(metric_provider: Optional[dict]):
    """MetricProviderSpec surface check (apis/config/types.go:73-110,
    validation_pluginargs.go ValidateTargetLoadPackingArgs) — a config this
    build cannot honor must fail at construction, not crash run_cycle."""
    if metric_provider is None:
        return None
    from scheduler_plugins_tpu.state.collector import METRIC_PROVIDER_TYPES

    mtype = metric_provider.get("type", "KubernetesMetricsServer")
    if mtype not in METRIC_PROVIDER_TYPES:
        raise ValueError(f"invalid metric provider type {mtype!r}")
    if mtype == "SignalFx":
        raise ValueError(
            f"metric provider type {mtype!r} needs an external SDK this "
            "build does not bundle; configure watcherAddress, Prometheus "
            "or KubernetesMetricsServer"
        )
    if not metric_provider.get("address"):
        raise ValueError(f"{mtype} metric provider requires an address")
    return dict(metric_provider)


class TargetLoadPacking(Plugin):
    """Best-fit bin packing around a target CPU utilisation
    (targetloadpacking.go:107-205)."""

    name = "TargetLoadPacking"

    def __init__(self, target_utilization_percent: int = 40,
                 watcher_address: Optional[str] = None,
                 metric_provider: Optional[dict] = None,
                 default_requests: Optional[dict] = None,
                 default_requests_multiplier="1.5"):
        if not 0 < target_utilization_percent <= 100:
            raise ValueError("target utilization must be in (0, 100]")
        self.target = float(target_utilization_percent)
        #: TrimaranSpec WatcherAddress (apis/config/types.go TrimaranSpec):
        #: when set, the cycle driver polls this load-watcher endpoint on
        #: the collector cadence and installs the metrics into the store
        self.watcher_address = watcher_address
        #: TrimaranSpec MetricProvider: library-mode client selection when
        #: no WatcherAddress is set (collector.go:60-73)
        self.metric_provider = _validate_metric_provider(metric_provider)
        #: DefaultRequests / DefaultRequestsMultiplier
        #: (apis/config/v1/defaults.go:76-90: 1000m cpu, "1.5"; multiplier
        #: must parse as a float >= 1, validation_pluginargs.go)
        from scheduler_plugins_tpu.api.resources import CPU as _CPU

        reqs = dict(default_requests) if default_requests else {_CPU: 1000}
        self.default_request_cpu_millis = int(reqs.get(_CPU, 1000))
        try:
            self.default_requests_multiplier = float(default_requests_multiplier)
        except (TypeError, ValueError):
            raise ValueError(
                f"invalid defaultRequestsMultiplier "
                f"{default_requests_multiplier!r}"
            ) from None
        if self.default_requests_multiplier < 1:
            raise ValueError("defaultRequestsMultiplier must be >= 1")

    def configure_cluster(self, cluster):
        """Install this plugin's pod CPU-prediction parameters: the snapshot
        builder and the missing-utilization compensation use them when
        lowering `tlp_predicted_cpu_millis`."""
        if cluster is not None:
            cluster.tlp_prediction = (
                self.default_requests_multiplier,
                self.default_request_cpu_millis,
            )

    def score(self, state, snap, p):
        if snap.metrics is None:
            return None
        return tlp_score(
            snap.metrics.cpu_tlp,
            snap.metrics.cpu_tlp_valid,
            snap.metrics.missing_cpu_millis,
            snap.nodes.capacity[:, CPU_I],
            snap.pods.predicted_cpu_millis[p],
            self.target,
        )

    def score_batch(self, state, snap):
        """Batched piecewise curve (f32 broadcast stage; +/-1 rounding vs
        the parity path at knife edges — see ops.trimaran)."""
        if snap.metrics is None:
            return None
        return tlp_score_batch(
            snap.metrics.cpu_tlp,
            snap.metrics.cpu_tlp_valid,
            snap.metrics.missing_cpu_millis,
            snap.nodes.capacity[:, CPU_I],
            snap.pods.predicted_cpu_millis,
            self.target,
        )


class LoadVariationRiskBalancing(Plugin):
    """Risk = (mu + margin*sigma^(1/sensitivity))/2 over cpu+memory
    (analysis.go:34-69)."""

    name = "LoadVariationRiskBalancing"

    def __init__(self, safe_variance_margin: float = 1.0,
                 safe_variance_sensitivity: float = 1.0,
                 watcher_address: Optional[str] = None,
                 metric_provider: Optional[dict] = None):
        if safe_variance_margin < 0 or safe_variance_sensitivity < 0:
            raise ValueError("margin/sensitivity must be non-negative")
        self.margin = safe_variance_margin
        self.sensitivity = safe_variance_sensitivity
        self.watcher_address = watcher_address
        self.metric_provider = _validate_metric_provider(metric_provider)

    def score(self, state, snap, p):
        if snap.metrics is None:
            return None
        # LVRB reads node allocatable as capacity (resourcestats.go:56-66)
        return lvrb_score(
            snap.metrics,
            snap.nodes.alloc[:, CPU_I],
            snap.nodes.alloc[:, MEMORY_I],
            snap.pods.req[p, CPU_I],
            snap.pods.req[p, MEMORY_I],
            self.margin,
            self.sensitivity,
        )

    def score_batch(self, state, snap):
        """Batched risk curve (f32 broadcast stage; +/-1 rounding vs the
        parity path at knife edges — see ops.trimaran)."""
        if snap.metrics is None:
            return None
        return lvrb_score_batch(
            snap.metrics,
            snap.nodes.alloc[:, CPU_I],
            snap.nodes.alloc[:, MEMORY_I],
            snap.pods.req[:, CPU_I],
            snap.pods.req[:, MEMORY_I],
            self.margin,
            self.sensitivity,
        )


class LowRiskOverCommitment(Plugin):
    """Weighted overcommit-potential + measured-overuse risk
    (lowriskovercommitment.go:157-256)."""

    name = "LowRiskOverCommitment"

    def __init__(
        self,
        smoothing_window_size: int = 5,
        risk_limit_weights: Optional[Mapping[str, float]] = None,
        watcher_address: Optional[str] = None,
        metric_provider: Optional[dict] = None,
    ):
        self.smoothing_window = smoothing_window_size
        self.watcher_address = watcher_address
        self.metric_provider = _validate_metric_provider(metric_provider)
        weights = dict(risk_limit_weights or {})
        self.w_cpu = weights.get("cpu", 0.5)
        self.w_mem = weights.get("memory", 0.5)

    def score(self, state, snap, p):
        if snap.metrics is None:
            return None
        raw = lroc_score(
            snap.metrics,
            snap.nodes.alloc[:, CPU_I],
            snap.nodes.alloc[:, MEMORY_I],
            snap.nodes.requested[:, CPU_I],
            snap.nodes.requested[:, MEMORY_I],
            snap.nodes.limits[:, CPU_I],
            snap.nodes.limits[:, MEMORY_I],
            snap.pods.req[p, CPU_I],
            snap.pods.req[p, MEMORY_I],
            snap.pods.limits[p, CPU_I],
            snap.pods.limits[p, MEMORY_I],
            self.smoothing_window,
            self.w_cpu,
            self.w_mem,
        )
        # best-effort pods are not scored (lowriskovercommitment.go:122-129);
        # nodes with NO metrics at all score minimum, but partial (memory-only
        # or cpu-only) metrics still rank (Score only early-outs on nil)
        best_effort = (
            (snap.pods.req[p, CPU_I] == 0)
            & (snap.pods.req[p, MEMORY_I] == 0)
            & (snap.pods.limits[p, CPU_I] == 0)
            & (snap.pods.limits[p, MEMORY_I] == 0)
        )
        no_metrics = ~(snap.metrics.cpu_valid | snap.metrics.mem_valid)
        return jnp.where(best_effort | no_metrics, 0, raw)


class Peaks(Plugin):
    """Power-aware packing: minimize the cluster power jump
    Power = K0 + K1*e^(K2*util) (peaks.go:103-196, PeaksArgs power model
    apis/config/types.go:287-307)."""

    name = "Peaks"

    def __init__(self, node_power_model: Optional[Mapping[str, tuple]] = None,
                 watcher_address: Optional[str] = None,
                 metric_provider: Optional[dict] = None):
        self.watcher_address = watcher_address
        self.metric_provider = _validate_metric_provider(metric_provider)
        #: node name -> (K0, K1, K2); missing nodes get (0, 0, 0). When the
        #: args carry no model, the NODE_POWER_MODEL env var names a JSON
        #: file {node: {"K0":..., "K1":..., "K2":...}} (peaks.go:59-74).
        self.node_power_model = dict(node_power_model or {})
        if not self.node_power_model:
            self.node_power_model = self._load_env_model()
        self._k1 = None
        self._k2 = None

    @staticmethod
    def _load_env_model() -> dict:
        import json
        import os

        path = os.environ.get("NODE_POWER_MODEL")
        if not path:
            return {}
        # the reference fails plugin creation on read AND decode errors
        # (peaks.go:59-74) — surface misconfiguration loudly either way
        try:
            with open(path) as f:
                raw = json.load(f)
            return {
                node: (
                    float(model.get("K0", 0.0)),
                    float(model.get("K1", 0.0)),
                    float(model.get("K2", 0.0)),
                )
                for node, model in raw.items()
            }
        except (OSError, ValueError, TypeError, AttributeError) as exc:
            raise ValueError(
                f"invalid NODE_POWER_MODEL file {path!r}: {exc}"
            ) from exc

    def prepare(self, meta):
        n = len(meta.node_names)
        k1 = np.zeros(max(n, 1), np.float64)
        k2 = np.zeros(max(n, 1), np.float64)
        for i, name in enumerate(meta.node_names):
            model = self.node_power_model.get(name)
            if model is not None:
                k1[i], k2[i] = float(model[1]), float(model[2])
        self._k1 = jnp.asarray(k1)
        self._k2 = jnp.asarray(k2)

    def aux(self):
        return (self._k1, self._k2)

    def score(self, state, snap, p):
        if snap.metrics is None or self._k1 is None:
            return None
        N = snap.num_nodes
        a_k1, a_k2 = self._aux
        k1 = jnp.zeros(N, jnp.float64).at[: a_k1.shape[0]].set(a_k1)
        k2 = jnp.zeros(N, jnp.float64).at[: a_k2.shape[0]].set(a_k2)
        # Peaks needs an Average/Latest CPU sample and takes the FIRST one in
        # report order (peaks.go:118-131) — cpu_valid alone is satisfied by a
        # std-only report, and cpu_avg/cpu_tlp have different selection rules
        return peaks_score(
            snap.metrics.cpu_peaks,
            snap.metrics.cpu_tlp_valid,
            snap.nodes.capacity[:, CPU_I],
            snap.pods.req[p, CPU_I],
            k1,
            k2,
        )

    def normalize(self, scores, feasible):
        # lowest power jump wins (peaks.go:152-168)
        return peaks_normalize(scores, feasible)
