"""NodeResourceTopologyMatch — NUMA-aware Filter + Score.

Reference: /root/reference/pkg/noderesourcetopology (plugin.go:79-83 extension
points; SURVEY.md §2.6). The per-node cache tier (OverReserve / Passthrough /
DiscardReserved) is host-side bookkeeping implemented in
`state.nrt_cache`; this plugin consumes whatever zone availability the
snapshot carries and contributes:

- Filter: only for nodes whose topology-manager policy is single-numa-node
  (filter.go:176-225) — container-scope handler with sequential subtraction
  or pod-scope handler, selected per node by the NRT-mirrored scope.
- Score: non-guaranteed pods always score 100 (score.go:72-75); nodes without
  NRT data score 0; strategies LeastAllocated / MostAllocated /
  BalancedAllocation / LeastNUMANodes with per-node scope handling.

All zone math is vmapped over nodes from `ops.numa` single-node kernels.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from scheduler_plugins_tpu.api.objects import (
    QOSClass,
    TopologyManagerPolicy,
    TopologyManagerScope,
)
from scheduler_plugins_tpu.framework.plugin import Plugin
from scheduler_plugins_tpu.ops import numa as numa_ops
from scheduler_plugins_tpu.ops.numa import (
    BALANCED_ALLOCATION,
    LEAST_ALLOCATED,
    LEAST_NUMA_NODES,
    MOST_ALLOCATED,
)
from scheduler_plugins_tpu.api import events as ev

STRATEGIES = (
    LEAST_ALLOCATED,
    MOST_ALLOCATED,
    BALANCED_ALLOCATION,
    LEAST_NUMA_NODES,
)


class NodeResourceTopologyMatch(Plugin):
    name = "NodeResourceTopologyMatch"

    def events_to_register(self):
        # plugin.go:141-151: Pod delete, node allocatable changes, NRT CRs
        return (ev.POD_DELETE, ev.NODE_ADD, ev.NODE_UPDATE,
                ev.NRT_ADD, ev.NRT_UPDATE)
    #: the Filter reads the carried zone availability (in-cycle pessimistic
    #: deductions) — the batched path must re-evaluate it per wave
    state_dependent_filter = True

    #: Cache.ForeignPodsDetect / ResyncMethod / InformerMode values
    #: (apis/config/types.go:124-180)
    FOREIGN_PODS_DETECT = ("All", "None", "OnlyExclusiveResources")
    RESYNC_METHODS = ("Autodetect", "All", "OnlyExclusiveResources")
    INFORMER_MODES = ("Shared", "Dedicated")

    def __init__(
        self,
        scoring_strategy: str = LEAST_ALLOCATED,
        resources: Sequence[tuple[str, int]] = (),
        cache_resync_period_seconds: Optional[int] = None,
        discard_reserved_nodes: Optional[bool] = None,
        cache: Optional[dict] = None,
    ):
        if scoring_strategy not in STRATEGIES:
            raise ValueError(f"illegal scoring strategy {scoring_strategy!r}")
        if cache_resync_period_seconds is not None and cache_resync_period_seconds < 0:
            # validation_pluginargs.go ValidateNodeResourceTopologyMatchArgs
            raise ValueError("cacheResyncPeriodSeconds must be >= 0")
        self.strategy = scoring_strategy
        self.resources = tuple(resources)
        #: cache-implementation selection (pluginhelpers.go:47-78):
        #: DiscardReservedNodes -> DiscardReserved; resync <= 0 ->
        #: Passthrough; else OverReserve driven on the resync cadence.
        #: `configure_cluster` installs the selected cache ONLY when one of
        #: these args was PASSED — a default-constructed plugin leaves
        #: manual cache wiring untouched.
        self._cache_args_given = any(
            v is not None
            for v in (cache_resync_period_seconds, discard_reserved_nodes, cache)
        )
        self.cache_resync_period_seconds = int(cache_resync_period_seconds or 0)
        self.discard_reserved_nodes = bool(discard_reserved_nodes)
        cache = dict(cache or {})
        self.cache_foreign_pods_detect = cache.get("foreignPodsDetect", "All")
        self.cache_resync_method = cache.get("resyncMethod", "Autodetect")
        self.cache_informer_mode = cache.get("informerMode", "Dedicated")
        if self.cache_foreign_pods_detect not in self.FOREIGN_PODS_DETECT:
            raise ValueError(
                f"invalid foreignPodsDetect {self.cache_foreign_pods_detect!r}"
            )
        if self.cache_resync_method not in self.RESYNC_METHODS:
            raise ValueError(
                f"invalid resyncMethod {self.cache_resync_method!r}"
            )
        if self.cache_informer_mode not in self.INFORMER_MODES:
            raise ValueError(
                f"invalid informerMode {self.cache_informer_mode!r}"
            )
        self._affine: Optional[jnp.ndarray] = None
        self._host_level: Optional[jnp.ndarray] = None
        self._weights: Optional[jnp.ndarray] = None

    def _cache_signature(self):
        return (
            self.discard_reserved_nodes,
            self.cache_resync_period_seconds,
            self.cache_foreign_pods_detect,
            self.cache_informer_mode,
            self.cache_resync_method,
        )

    def make_cache(self, scheduler_names=None):
        """Cache-tier selection exactly as initNodeTopologyInformer does it
        (pluginhelpers.go:55-66). `scheduler_names` seeds the foreign-pod
        registry (cache/foreign_pods.go's profile-name registry) so
        ownership and foreign tracking stay consistent."""
        from scheduler_plugins_tpu.state import nrt_cache as caches

        if self.discard_reserved_nodes:
            return caches.DiscardReservedCache()
        if self.cache_resync_period_seconds <= 0:
            return caches.PassthroughCache()
        cache = caches.OverReserveCache(
            foreign_pods_detect=self.cache_foreign_pods_detect,
            informer_mode=self.cache_informer_mode,
            resync_method=self.cache_resync_method,
        )
        if scheduler_names:
            cache.our_schedulers = set(scheduler_names)
        cache.resync_period_ms = self.cache_resync_period_seconds * 1000
        return cache

    def configure_cluster(self, cluster):
        if cluster is None or not self._cache_args_given:
            return
        if getattr(cluster, "_nrt_cache_config", None) == self._cache_signature():
            return
        cache = self.make_cache(
            scheduler_names=getattr(cluster, "scheduler_names", None)
        )
        for nrt in cluster.nrts.values():
            cache.update_nrt(nrt)
        if hasattr(cache, "track_pod"):
            for pod in cluster.pods.values():
                cache.track_pod(pod)
        cluster.nrt_cache = cache
        cluster._nrt_cache_config = self._cache_signature()

    def prepare_cluster(self, meta, cluster):
        """Static specialization: when every NRT shares one topology-manager
        scope (the overwhelmingly common fleet configuration), trace only
        that scope's handler instead of computing both and selecting
        (halves the per-step NUMA work in the sequential scan)."""
        self._uniform_scope = None
        if cluster is not None and cluster.nrts:
            scopes = {int(t.scope) for t in cluster.nrts.values()}
            if len(scopes) == 1:
                self._uniform_scope = scopes.pop()

    def static_key(self):
        # the uniform-scope specialization and the f32-weight guard are
        # Python-level branches baked into the trace; key the runtime's jit
        # caches on them so a config change retraces instead of reusing the
        # stale program
        return (
            "nrt_scope", getattr(self, "_uniform_scope", None),
            "w_f32_ok", self._weights_f32_ok(),
        )

    def host_state(self):
        # the scope specialization comes from the live Cluster's NRT CRs —
        # a replayed bundle has no Cluster, so record it
        return {"uniform_scope": getattr(self, "_uniform_scope", None)}

    def restore_host_state(self, state) -> None:
        scope = state.get("uniform_scope")
        self._uniform_scope = None if scope is None else int(scope)

    def _weights_f32_ok(self):
        """Whether the f32 fast path keeps the weighted zone-score sums
        exact: per-resource scores are <= 100, so sum(100 * w) over the FULL
        weight vector (defaults included) must stay below 2^24. Computed in
        `prepare` from the actual vector; conservatively False before."""
        return bool(getattr(self, "_w_f32_ok", False))

    def prepare(self, meta):
        self._uniform_scope = getattr(self, "_uniform_scope", None)
        self._affine = jnp.asarray(numa_ops.numa_affine_mask(meta.index))
        self._host_level = jnp.asarray(numa_ops.host_level_mask(meta.index))
        self._host_extended = jnp.asarray(
            np.array(["/" in name for name in meta.index.names], bool)
        )
        w = np.ones(len(meta.index), np.int64)  # default weight 1 (score.go:49-60)
        for name, weight in self.resources:
            if name in meta.index and weight >= 1:
                w[meta.index.position(name)] = weight
        self._weights = jnp.asarray(w)
        self._w_f32_ok = int(w.sum()) * numa_ops.MAX_NODE_SCORE < (1 << 24)

    def aux(self):
        return (self._affine, self._host_level, self._host_extended, self._weights)

    def _numa_avail(self, state, snap):
        """Live zone availability with in-cycle placements deducted — the
        carried equivalent of the over-reserve cache's assumed-pod deduction
        between one-at-a-time cycles (cache/overreserve.go:148-160). FLOAT
        (packed f32 or f64, see ops.numa.live_avail_init): feasibility
        compares and score divisions run without per-step int64 temporaries.
        Requests entering any comparison against this tensor go through
        `self._qty`."""
        if state is not None and state.numa_avail is not None:
            return state.numa_avail
        return numa_ops.live_avail_init(snap.numa)

    def prepare_solve(self, snap):
        if snap.numa is None:
            return None
        # loop-invariant: the whole batch's requests scaled into the
        # live-availability quantity domain once per solve, not per scan step
        return {
            "req": numa_ops.scale_qty(snap.numa, snap.pods.req),
            "creq": numa_ops.scale_qty(snap.numa, snap.pods.container_req),
        }

    def _qty_req(self, snap, p):
        """Pod p's effective request in the live-availability domain."""
        pre = getattr(self, "_presolve", None)
        if pre is not None:
            return pre["req"][p]
        return numa_ops.scale_qty(snap.numa, snap.pods.req[p])

    def _qty_creq(self, snap, p):
        """Pod p's (C, R) container requests in the live-availability domain."""
        pre = getattr(self, "_presolve", None)
        if pre is not None:
            return pre["creq"][p]
        return numa_ops.scale_qty(snap.numa, snap.pods.container_req[p])

    # -- Filter ----------------------------------------------------------
    def filter(self, state, snap, p):
        if snap.numa is None:
            return None
        numa = snap.numa
        affine, host_level, host_extended, _ = self._aux
        guaranteed = snap.pods.qos[p] == int(QOSClass.GUARANTEED)
        creq = self._qty_creq(snap, p)
        is_init = snap.pods.container_is_init[p]
        cmask = snap.pods.container_mask[p]
        req = self._qty_req(snap, p)

        available = self._numa_avail(state, snap)  # (N, Z, R) float

        def fit_one_request(r):
            """(N,) fit verdicts for a single (R,) request: one fused f64
            compare over all nodes (exact — integer values below 2^53)."""
            suitable_qty = available >= r[None, None, :]  # (N, Z, R)
            return jax.vmap(
                lambda sq, reported, zmask, alloc:
                numa_ops.feasible_zones_from_suitable(
                    sq, reported, zmask, alloc, guaranteed, r,
                    affine, host_level,
                )[1]
            )(suitable_qty, numa.reported, numa.zone_mask, snap.nodes.alloc)

        def container_fit():
            if creq.shape[0] == 1:
                # single container: no sequential subtraction to thread
                return fit_one_request(creq[0])
            return jax.vmap(
                lambda avail, reported, zmask, alloc: numa_ops.single_numa_fit(
                    avail, reported, zmask, alloc, guaranteed, creq, is_init,
                    cmask, affine, host_level,
                )
            )(available, numa.reported, numa.zone_mask, snap.nodes.alloc)

        def pod_fit():
            return fit_one_request(req)

        if self._uniform_scope == int(TopologyManagerScope.POD):
            scoped = pod_fit()
        elif self._uniform_scope == int(TopologyManagerScope.CONTAINER):
            scoped = container_fit()
        else:
            scoped = jnp.where(
                numa.scope == int(TopologyManagerScope.POD),
                pod_fit(),
                container_fit(),
            )
        # only single-numa-node policy filters (filter.go:230-241)
        applies = numa.has_nrt & (
            numa.policy == int(TopologyManagerPolicy.SINGLE_NUMA_NODE)
        )
        verdict = jnp.where(applies, scoped, True)
        # stale cache view -> Unschedulable regardless of policy
        # (filter.go:194-197)
        verdict &= numa.fresh
        # best-effort pods without extended-resource requests skip the NUMA
        # filter entirely (filter.go:180-183 IncludeNonNative)
        non_native = jnp.any((snap.pods.req[p] > 0) & host_extended)
        skip = (snap.pods.qos[p] == int(QOSClass.BEST_EFFORT)) & ~non_native
        return jnp.where(skip, True, verdict)

    # -- batched Filter/Score (the wave path's hot kernels) ---------------
    def _single_request_rows(self, snap):
        """(P, R) single-request rows in the live-quantity domain when the
        whole-batch NUMA kernels apply — uniform pod scope, or uniform
        container scope with one container slot (no sequential subtraction
        to thread). None selects the per-pod vmap fallback."""
        pre = getattr(self, "_presolve", None)
        if self._uniform_scope == int(TopologyManagerScope.POD):
            if pre is not None:
                return pre["req"]
            return numa_ops.scale_qty(snap.numa, snap.pods.req)
        if (
            self._uniform_scope == int(TopologyManagerScope.CONTAINER)
            and snap.pods.container_req.shape[1] == 1
        ):
            creq = (
                pre["creq"] if pre is not None
                else numa_ops.scale_qty(snap.numa, snap.pods.container_req)
            )
            return creq[:, 0, :]
        return None

    def _batch_single_fit(self, state, snap, sel=None):
        """(S, N) Filter verdicts for the whole batch (or the `sel` rows)
        via `ops.numa.batch_request_fit` — one fused (S, N, Z, R) pass with
        every pod-invariant tensor hoisted, replacing the per-pod vmap of
        per-node kernels on the batched path. Bit-identical to `filter`."""
        numa = snap.numa
        affine, host_level, host_extended, _ = self._aux
        rows = self._single_request_rows(snap)
        if rows is None:
            return None
        qos = snap.pods.qos
        req_raw = snap.pods.req
        if sel is not None:
            rows, qos, req_raw = rows[sel], qos[sel], req_raw[sel]
        guaranteed = qos == int(QOSClass.GUARANTEED)
        avail = self._numa_avail(state, snap)  # (N, Z, R) float
        ok = numa_ops.batch_request_fit(
            avail, numa.reported, numa.zone_mask, snap.nodes.alloc,
            guaranteed, rows, affine, host_level,
        )
        # only single-numa-node policy filters (filter.go:230-241); stale
        # cache views reject regardless (filter.go:194-197)
        applies = numa.has_nrt & (
            numa.policy == int(TopologyManagerPolicy.SINGLE_NUMA_NODE)
        )
        verdict = jnp.where(applies[None, :], ok, True) & numa.fresh[None, :]
        non_native = jnp.any((req_raw > 0) & host_extended[None, :], axis=1)
        skip = (qos == int(QOSClass.BEST_EFFORT)) & ~non_native
        return jnp.where(skip[:, None], True, verdict)

    def filter_batch(self, state, snap):
        if snap.numa is None:
            return None
        return self._batch_single_fit(state, snap)

    def filter_rows(self, state, snap, idx):
        if snap.numa is None:
            return None
        return self._batch_single_fit(state, snap, sel=idx)

    def score_batch(self, state, snap):
        """(P, N) int32 raw scores with the pod-invariant zone scales
        computed once per solve (`ops.numa.precompute_zone_scales`) —
        value-identical to the vmapped per-pod `score`, demoted to int32
        (exact: node scores are <= MAX_NODE_SCORE). LeastNUMANodes and
        mixed-scope clusters fall back to the per-pod path."""
        if snap.numa is None or self.strategy == LEAST_NUMA_NODES:
            return None
        numa = snap.numa
        scope = self._uniform_scope
        if scope not in (
            int(TopologyManagerScope.POD), int(TopologyManagerScope.CONTAINER)
        ):
            return None
        _, _, _, weights = self._aux
        available = self._numa_avail(state, snap)
        if available.dtype == jnp.float32 and not self._weights_f32_ok():
            available = available.astype(jnp.float64)
        pre = getattr(self, "_presolve", None)
        if scope == int(TopologyManagerScope.POD):
            reqs = (
                pre["req"] if pre is not None
                else numa_ops.scale_qty(snap.numa, snap.pods.req)
            )
            raw = numa_ops.batch_strategy_node_scores(
                self.strategy, reqs, available, numa.zone_mask, weights
            )
        else:
            creq = (
                pre["creq"] if pre is not None
                else numa_ops.scale_qty(snap.numa, snap.pods.container_req)
            )
            C = creq.shape[1]
            cmask = snap.pods.container_mask
            count = jnp.maximum(jnp.sum(cmask, axis=1), 1)
            scales = (
                numa_ops.precompute_zone_scales(available)
                if self.strategy in (LEAST_ALLOCATED, MOST_ALLOCATED)
                else None
            )
            # mean over containers, float, truncated (score.go:152-165) —
            # the batched form of node_container_scope's static C loop
            total = jnp.zeros((snap.num_pods, snap.num_nodes), jnp.float64)
            for c in range(C):
                s_c = numa_ops.batch_strategy_node_scores(
                    self.strategy, creq[:, c], available, numa.zone_mask,
                    weights, scales=scales,
                )
                total = total + jnp.where(
                    cmask[:, c][:, None], s_c.astype(jnp.float64), 0.0
                )
            raw = jnp.trunc(
                total / count[:, None].astype(jnp.float64)
            ).astype(jnp.int32)
        guaranteed = snap.pods.qos == int(QOSClass.GUARANTEED)
        raw = jnp.where((numa.has_nrt & numa.fresh)[None, :], raw, 0)
        return jnp.where(
            guaranteed[:, None], raw, jnp.int32(numa_ops.MAX_NODE_SCORE)
        )

    def commit(self, state, snap, p, choice):
        """Reserve: pessimistically deduct the placed pod's request from
        EVERY reported zone of the chosen node (ReserveNodeResources +
        GetCachedNRTCopy deduction semantics, cache/store.go:129-160)."""
        if snap.numa is None or state.numa_avail is None:
            return state
        N = state.numa_avail.shape[0]
        onehot = (jnp.arange(N) == choice)[:, None, None]
        reqq = self._qty_req(snap, p).astype(state.numa_avail.dtype)
        deduct = jnp.where(
            (choice >= 0) & onehot & snap.numa.reported,
            reqq[None, None, :],
            0.0,
        )
        return state.replace(numa_avail=state.numa_avail - deduct)

    def commit_batch(self, state, snap, placed, choice):
        """Batched Reserve for the wave path: the pessimistic all-reported-
        zone deduction is a sum over placed pods, so one segment-sum per
        node reproduces any sequential order of `commit`s exactly."""
        if snap.numa is None or state.numa_avail is None:
            return state
        N = state.numa_avail.shape[0]
        pre = getattr(self, "_presolve", None)
        reqq = (
            pre["req"] if pre is not None
            else numa_ops.scale_qty(snap.numa, snap.pods.req)
        ).astype(state.numa_avail.dtype)  # (P, R)
        node_demand = jnp.zeros(
            (N, reqq.shape[1]), state.numa_avail.dtype
        ).at[jnp.maximum(choice, 0)].add(
            jnp.where(placed[:, None], reqq, 0)
        )
        deduct = jnp.where(snap.numa.reported, node_demand[:, None, :], 0)
        return state.replace(numa_avail=state.numa_avail - deduct)

    def wave_capacity(self, state, snap, active):
        """(N,) pods-per-node estimate under the pessimistic zone model:
        every placement deducts from EVERY reported zone, so a node admits
        at most floor(max_z avail[z, r] / mean_request_r) pods of the
        active mix (min over requested resources). Steers waterfill
        bucketing only — admission stays exact (wave guard)."""
        if snap.numa is None:
            return None
        numa = snap.numa
        pre = getattr(self, "_presolve", None)
        reqq = (
            pre["req"] if pre is not None
            else numa_ops.scale_qty(snap.numa, snap.pods.req)
        )
        n_active = jnp.maximum(active.sum(), 1)
        mean_req = (
            jnp.sum(jnp.where(active[:, None], reqq, 0), axis=0) / n_active
        )  # (R,) float
        avail = self._numa_avail(state, snap)  # (N, Z, R)
        reported = numa.reported & numa.zone_mask[:, :, None]
        best_zone = jnp.max(
            jnp.where(reported, avail, 0.0), axis=1
        )  # (N, R)
        # a resource NO zone reports does not constrain the zone fit (the
        # exact filter's host-level bypass, feasible_zones_from_suitable) —
        # it must not zero the estimate either
        has_affinity = jnp.any(reported, axis=1)  # (N, R)
        per_r = jnp.where(
            (mean_req[None, :] > 0) & has_affinity,
            jnp.floor(best_zone / jnp.maximum(mean_req[None, :], 1e-9)),
            jnp.inf,
        )
        cap = jnp.min(per_r, axis=1)
        # clip while still FLOAT: a finite ratio above 2^31 (bytes-scale
        # zone over a tiny mean request) would make the int32 convert
        # undefined (wrap negative -> capacity 0 for the roomiest node)
        cap = jnp.where(jnp.isfinite(cap), cap, float(snap.num_pods))
        cap = jnp.clip(cap, 0.0, float(snap.num_pods)).astype(jnp.int32)
        applies = numa.has_nrt & (
            numa.policy == int(TopologyManagerPolicy.SINGLE_NUMA_NODE)
        )
        return jnp.where(applies, cap, snap.num_pods)

    def wave_guard_demand(self, snap):
        """Within-wave guard demand: the pod request in the live-availability
        quantity domain — what an earlier same-wave winner pessimistically
        deducts from every zone of the shared node."""
        if snap.numa is None:
            return None
        pre = getattr(self, "_presolve", None)
        if pre is not None:
            return pre["req"]
        return numa_ops.scale_qty(snap.numa, snap.pods.req)

    def wave_guard(self, state, snap, p, node, prefix):
        """Exact within-wave single-numa admission: re-run this pod's Filter
        verdict for `node` only, with earlier same-wave winners' demand
        (`prefix`, already in the live-quantity domain) pessimistically
        deducted from every zone — the same view a sequential scan's carry
        would have shown (filter.go:90-160 semantics on the adjusted
        availability)."""
        if snap.numa is None:
            return jnp.bool_(True)
        numa = snap.numa
        affine, host_level, host_extended, _ = self._aux
        avail = self._numa_avail(state, snap)[node]  # (Z, R) float
        avail = avail - jnp.where(
            numa.reported[node], prefix[None, :].astype(avail.dtype), 0
        )
        guaranteed = snap.pods.qos[p] == int(QOSClass.GUARANTEED)
        req = self._qty_req(snap, p)
        creq = self._qty_creq(snap, p)
        is_init = snap.pods.container_is_init[p]
        cmask = snap.pods.container_mask[p]
        node_args = (
            numa.reported[node], numa.zone_mask[node], snap.nodes.alloc[node]
        )

        def one_request(r):
            _, ok = numa_ops.feasible_zones(
                avail, *node_args, guaranteed, r, affine, host_level
            )
            return ok

        def container_fit():
            if creq.shape[0] == 1:
                return one_request(creq[0])
            return numa_ops.single_numa_fit(
                avail, *node_args, guaranteed, creq, is_init, cmask,
                affine, host_level,
            )

        if self._uniform_scope == int(TopologyManagerScope.POD):
            scoped = one_request(req)
        elif self._uniform_scope == int(TopologyManagerScope.CONTAINER):
            scoped = container_fit()
        else:
            scoped = jnp.where(
                numa.scope[node] == int(TopologyManagerScope.POD),
                one_request(req),
                container_fit(),
            )
        applies = numa.has_nrt[node] & (
            numa.policy[node] == int(TopologyManagerPolicy.SINGLE_NUMA_NODE)
        )
        verdict = jnp.where(applies, scoped, True) & numa.fresh[node]
        non_native = jnp.any((snap.pods.req[p] > 0) & host_extended)
        skip = (snap.pods.qos[p] == int(QOSClass.BEST_EFFORT)) & ~non_native
        return jnp.where(skip, True, verdict)

    # -- Score -----------------------------------------------------------
    def score(self, state, snap, p):
        if snap.numa is None:
            return None
        numa = snap.numa
        Z = numa.available.shape[1]
        guaranteed = snap.pods.qos[p] == int(QOSClass.GUARANTEED)

        if self.strategy == LEAST_NUMA_NODES:
            raw = self._least_numa_scores(state, snap, p, guaranteed)
        else:
            raw = self._strategy_scores(state, snap, p)

        # nodes without NRT or with a stale cache view score 0
        # (score.go:78-91); non-guaranteed pods always score max
        # (score.go:72-75)
        raw = jnp.where(numa.has_nrt & numa.fresh, raw, 0)
        return jnp.where(guaranteed, raw, numa_ops.MAX_NODE_SCORE)

    def _strategy_scores(self, state, snap, p):
        numa = snap.numa
        req = self._qty_req(snap, p)
        relevant = req > 0
        creq = self._qty_creq(snap, p)
        cmask = snap.pods.container_mask[p]
        C = creq.shape[0]

        _, _, _, weights = self._aux

        def node_pod_scope(avail, zmask):
            zs = numa_ops.zone_strategy_scores(
                self.strategy, req, avail, zmask, relevant, weights
            )
            return numa_ops.min_over_zones(zs, zmask)

        def node_container_scope(avail, zmask):
            # mean over containers, float, truncated (score.go:152-165)
            total = jnp.float64(0.0)
            count = jnp.maximum(jnp.sum(cmask), 1)
            for c in range(C):
                zs = numa_ops.zone_strategy_scores(
                    self.strategy, creq[c], avail, zmask,
                    creq[c] > 0, weights,
                )
                s = numa_ops.min_over_zones(zs, zmask)
                total = total + jnp.where(cmask[c], s.astype(jnp.float64), 0.0)
            return jnp.trunc(total / count).astype(jnp.int64)

        # float live availability (packed f32 / f64): exact, and feeds the
        # exact-floor divisions in zone_strategy_scores without per-step
        # int64 temporaries; oversized user weights force the f64 path
        available = self._numa_avail(state, snap)
        if available.dtype == jnp.float32 and not self._weights_f32_ok():
            available = available.astype(jnp.float64)
        if self._uniform_scope == int(TopologyManagerScope.POD):
            return jax.vmap(node_pod_scope)(available, numa.zone_mask)
        if self._uniform_scope == int(TopologyManagerScope.CONTAINER):
            return jax.vmap(node_container_scope)(available, numa.zone_mask)
        pod_scores = jax.vmap(node_pod_scope)(available, numa.zone_mask)
        cont_scores = jax.vmap(node_container_scope)(available, numa.zone_mask)
        return jnp.where(
            numa.scope == int(TopologyManagerScope.POD), pod_scores, cont_scores
        )

    def _least_numa_scores(self, state, snap, p, guaranteed):
        numa = snap.numa
        Z = numa.available.shape[1]
        masks_np, sizes_np = numa_ops.subset_masks(Z)
        masks = jnp.asarray(masks_np)
        sizes = jnp.asarray(sizes_np)
        affine = self._aux[0]
        req = self._qty_req(snap, p)
        creq = self._qty_creq(snap, p)
        is_init = snap.pods.container_is_init[p]
        cmask = snap.pods.container_mask[p]
        C = creq.shape[0]

        def node_pod(avail, reported, zmask, dists, max_numa):
            skip = numa_ops.only_non_numa(reported, zmask, req)
            count, is_min, ok, _ = numa_ops.least_numa_required(
                avail, reported, zmask, dists, guaranteed, req,
                affine, masks, sizes,
            )
            score = numa_ops.least_numa_normalize(count, is_min, max_numa)
            return jnp.where(skip, numa_ops.MAX_NODE_SCORE,
                             jnp.where(ok, score, 0))

        def node_container(avail, reported, zmask, dists, max_numa):
            worst = jnp.int32(0)
            all_min = jnp.bool_(True)
            failed = jnp.bool_(False)
            for c in range(C):
                applies = cmask[c] & ~numa_ops.only_non_numa(
                    reported, zmask, creq[c]
                )
                count, is_min, ok, chosen = numa_ops.least_numa_required(
                    avail, reported, zmask, dists, guaranteed, creq[c],
                    affine, masks, sizes,
                )
                failed |= applies & ~ok
                worst = jnp.where(applies & ok, jnp.maximum(worst, count), worst)
                all_min &= ~applies | is_min
                # subtract the full request from every chosen zone for every
                # container, init containers included (subtractFromNUMAs is
                # unconditional in the least-numa loop, least_numa.go:40-64)
                grant = jnp.where(
                    (applies & ok) & chosen[:, None] & reported,
                    creq[c][None, :],
                    0,
                )
                avail = avail - grant
            score = numa_ops.least_numa_normalize(worst, all_min, max_numa)
            return jnp.where(
                failed, 0, jnp.where(worst == 0, numa_ops.MAX_NODE_SCORE, score)
            )

        available = self._numa_avail(state, snap)
        args = (available, numa.reported, numa.zone_mask, numa.distances,
                numa.max_numa)
        if self._uniform_scope == int(TopologyManagerScope.POD):
            return jax.vmap(node_pod)(*args)
        if self._uniform_scope == int(TopologyManagerScope.CONTAINER):
            return jax.vmap(node_container)(*args)
        return jnp.where(
            numa.scope == int(TopologyManagerScope.POD),
            jax.vmap(node_pod)(*args),
            jax.vmap(node_container)(*args),
        )
