"""QOSSort — QueueSort plugin: priority desc, then QoS class
(Guaranteed > Burstable > BestEffort), then queue timestamp.

Reference: /root/reference/pkg/qos/queue_sort.go:42-84.
"""

from __future__ import annotations

from scheduler_plugins_tpu.framework.plugin import Plugin


class QOSSort(Plugin):
    name = "QOSSort"

    def queue_key(self, pod, cluster):
        # tuples sort ascending: negate priority and QoS precedence
        return (-pod.priority, -int(pod.qos_class()), pod.creation_ms,
                f"{pod.namespace}/{pod.name}")
