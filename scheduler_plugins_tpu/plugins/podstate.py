"""PodState — Score-only plugin favoring nodes that are freeing capacity.

Reference: /root/reference/pkg/podstate/pod_state.go:40-90 —
score = #terminating pods − #nominated pods per node, then the same min-max
normalization as Allocatable. Terminating/nominated counts are snapshot
columns, so the score matrix is one subtraction.
"""

from __future__ import annotations

from scheduler_plugins_tpu.framework.plugin import Plugin
from scheduler_plugins_tpu.ops.normalize import minmax_normalize


class PodState(Plugin):
    name = "PodState"

    def score(self, state, snap, p):
        return (snap.nodes.terminating - snap.nodes.nominated).astype("int64")

    def normalize(self, scores, feasible):
        return minmax_normalize(scores, feasible)
