"""CrossNodePreemption — brute-force multi-node victim search (PostFilter).

The reference ships this plugin FULLY COMMENTED OUT with its registration
disabled ("CAVEAT: don't use this in production env",
/root/reference/pkg/crossnodepreemption/cross_node_preemption.go:19-224,
cmd/scheduler/main.go registration commented). This build implements that
spec as an OPT-IN extra: enabling the plugin selects the
`PreemptionMode.CROSS_NODE` engine, which DFS-enumerates victim subsets
spanning nodes exactly like the dead code's `dfs`/`dryRunOnePass` pair and
ranks candidates by the upstream pickOneNode criteria. The pool is bounded
to the lowest-priority pods (`max_pool`) so the 2^n search stays tractable
— the one deliberate deviation from the uncapped reference spec.
"""

from __future__ import annotations

from scheduler_plugins_tpu.framework.plugin import Plugin
from scheduler_plugins_tpu.framework.preemption import (
    PreemptionEngine,
    PreemptionMode,
)
from scheduler_plugins_tpu.api import events as ev


class CrossNodePreemption(Plugin):
    name = "CrossNodePreemption"

    def __init__(self, max_pool: int = 12):
        if max_pool < 1:
            raise ValueError(f"max_pool must be >= 1, got {max_pool}")
        self.max_pool = max_pool

    def events_to_register(self):
        # a victim's deletion admits the preemptor (upstream
        # DefaultPreemption registration)
        return (ev.POD_DELETE,)

    def preemption_engine(self):
        return PreemptionEngine(
            PreemptionMode.CROSS_NODE, cross_node_max_pool=self.max_pool
        )
