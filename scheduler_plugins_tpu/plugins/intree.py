"""In-tree companion plugins: NodeAffinity, TaintToleration,
PodTopologySpread and InterPodAffinity.

These are upstream kube-scheduler plugins (k8s.io/kubernetes
pkg/scheduler/framework/plugins/{nodeaffinity,tainttoleration,
podtopologyspread,interpodaffinity}), NOT part of /root/reference — but real
profiles enable
them alongside the reference's plugins, so drop-in completeness requires
them (docs/PARITY.md "companion plugins", SURVEY.md §7 build plan item 2's
extension-point trait layer).

All matching work happens host-side at snapshot build
(`state.scheduling.build_scheduling` interns unique specs and evaluates each
against every node once); the jitted tensor methods are row gathers and
small segment sums.

- NodeAffinity: Filter = nodeSelector AND required-affinity terms; Score =
  sum of matching preferred-term weights, default-normalized (upstream
  nodeaffinity.go Score/NormalizeScore).
- TaintToleration: Filter = no untolerated NoSchedule/NoExecute taint;
  Score = count of untolerated PreferNoSchedule taints, reverse-normalized
  (upstream tainttoleration.go CountIntolerableTaintsPreferNoSchedule).
- PodTopologySpread: live per-selector NODE-level counts carried through
  the solve (`SolverState.sel_counts`); Filter enforces DoNotSchedule
  constraints (matchNum + self − globalMin <= maxSkew over the constraint
  key's domains); Score sums ScheduleAnyway match counts,
  reverse-normalized. minDomains, matchLabelKeys and nodeAffinityPolicy/
  nodeTaintsPolicy are honored: counts aggregate into domains per (pod,
  constraint) under the node-inclusion policies.
"""

from __future__ import annotations

import jax.numpy as jnp

from scheduler_plugins_tpu.framework.plugin import Plugin
from scheduler_plugins_tpu.ops.normalize import default_normalize
from scheduler_plugins_tpu.api import events as ev


class NodeAffinity(Plugin):
    name = "NodeAffinity"

    def events_to_register(self):
        return (ev.NODE_ADD, ev.NODE_UPDATE)

    def __init__(self, added_affinity=None):
        #: NodeAffinityArgs.AddedAffinity (upstream): per-profile extra
        #: REQUIRED node-selector terms (OR over terms) ANDed into every
        #: pod's node affinity — cluster operators use it to fence a
        #: profile to a node subset. Accepts NodeSelectorTerm objects or
        #: the wire shape (NodeSelectorTerm.from_wire).
        from scheduler_plugins_tpu.api.objects import NodeSelectorTerm

        self.added_affinity = [
            t if isinstance(t, NodeSelectorTerm)
            else NodeSelectorTerm.from_wire(t)
            for t in added_affinity or []
        ]
        self._added_mask = None

    def prepare_cluster(self, meta, cluster):
        if not self.added_affinity or cluster is None:
            self._added_mask = None
            return
        import numpy as np

        ok = np.ones(max(len(meta.node_names), 1), bool)
        for i, name in enumerate(meta.node_names):
            node = cluster.nodes.get(name)
            ok[i] = node is not None and any(
                t.matches(node) for t in self.added_affinity
            )
        self._added_mask = jnp.asarray(ok)

    def aux(self):
        return self._added_mask

    def filter(self, state, snap, p):
        base = None
        if snap.scheduling is not None:
            s = snap.scheduling
            base = s.node_term_ok[s.pod_node_term[p]]
        added = getattr(self, "_aux", None)
        if added is not None:
            N = snap.num_nodes
            padded = jnp.zeros(N, bool).at[: added.shape[0]].set(added)
            base = padded if base is None else base & padded
        return base

    def score(self, state, snap, p):
        if snap.scheduling is None:
            return None
        s = snap.scheduling
        return s.pref_score[s.pod_pref[p]]

    def normalize(self, scores, feasible):
        return default_normalize(scores, feasible)


class PodTopologySpread(Plugin):
    """maxSkew spreading over topology domains.

    Live counts are (TR, N) per (selector-track, NODE), carried through the
    solve and aggregated into (CT, D) domain counts per pod under the
    node-inclusion policies; the check per node is then

        matchNum(node) = dc[constraint, domain(node)]
        verdict(node)  = has_key(node)
                         & (matchNum + selfMatch - globalMin <= maxSkew)

    with globalMin the minimum count over the constraint's ELIGIBLE domains
    (0 when fewer than minDomains exist). DoNotSchedule constraints filter;
    ScheduleAnyway constraints score (summed match counts, fewer = better).
    """

    name = "PodTopologySpread"

    def events_to_register(self):
        return (ev.POD_ADD, ev.POD_UPDATE, ev.POD_DELETE, ev.NODE_ADD,
                ev.NODE_UPDATE)

    #: the filter reads the carried live counts — later placements change
    #: earlier verdicts, and domains SPAN nodes, so the batched path also
    #: re-validates placements sequentially (`validate_at`)
    state_dependent_filter = True

    def _counts(self, state, snap):
        """(TR, N) live node-level counts — materialized only when some
        eligibility row actually excludes a keyed node."""
        if state is not None and state.sel_counts is not None:
            return state.sel_counts
        return snap.scheduling.track_node_base

    def _dom_counts(self, state, snap):
        """(TR, D) live domain mirror — the O(1)-gather fast path."""
        if state is not None and state.sel_dom_counts is not None:
            return state.sel_dom_counts
        return snap.scheduling.track_base

    def _constraint_state(self, state, snap, p):
        """Per-constraint live tensors shared by filter/score/validate:
        (CT, D) eligible-node domain counts, the global minimum (minDomains
        applied), and the (CT, N) code/has lookup rows.

        Node inclusion mirrors upstream: a node's pods count toward a
        constraint's domains/minimum only when the node carries all the
        pod's constraint keys OF THE SAME CLASS (hard keys in the
        PreFilter counting, soft keys in PreScore), matches the pod's
        nodeSelector/required affinity (nodeAffinityPolicy Honor — the
        default), and tolerates its NoSchedule/NoExecute taints
        (nodeTaintsPolicy Honor; default Ignore). The masks are fully
        static, so they are host-precomputed interned rows
        (`spread_elig`); when NO row excludes a keyed node
        (`spread_needs_node_counts` False — the common case) the counting
        is provably identical to the (TR, D) domain mirror and this
        reduces to row gathers."""
        s = snap.scheduling
        code = s.topo_code[s.spread_topo[p]]  # (CT, N)
        has = s.topo_has[s.spread_topo[p]]  # (CT, N)
        if s.spread_needs_node_counts:
            counts = self._counts(state, snap)  # (TR, N)
            dcn = counts[s.spread_track[p]]  # (CT, N)
            elig = s.spread_elig[s.spread_elig_idx[p]] & (code >= 0)
            CT, N = code.shape
            D = s.domain_exists.shape[1]
            rows = jnp.broadcast_to(jnp.arange(CT)[:, None], (CT, N))
            col = jnp.maximum(code, 0)
            dc = jnp.zeros((CT, D), counts.dtype).at[rows, col].add(
                jnp.where(elig, dcn, 0)
            )
            exists = jnp.zeros((CT, D), bool).at[rows, col].max(elig)
        else:
            dc = self._dom_counts(state, snap)[s.spread_track[p]]  # (CT, D)
            exists = s.domain_exists[s.spread_topo[p]]  # (CT, D)
        big = jnp.int64(1) << 62
        # no eligible domain -> minimum stays `big` and the skew check
        # passes trivially (upstream CriticalPaths stay MaxInt32)
        minm = jnp.min(jnp.where(exists, dc, big), axis=1)  # (CT,)
        # minDomains (upstream minMatchNum): fewer eligible domains than
        # required -> the global minimum is treated as 0
        dn = jnp.sum(exists, axis=1)  # (CT,)
        md = s.spread_min_domains[p]
        minm = jnp.where((md > 0) & (dn < md), 0, minm)
        return s, dc, minm, code, has

    def filter(self, state, snap, p):
        s = snap.scheduling
        if s is None or s.spread_track is None:
            return None
        s, dc, minm, code, has = self._constraint_state(state, snap, p)
        match_at = jnp.take_along_axis(
            dc, jnp.maximum(code, 0), axis=1
        )  # (CT, N)
        selfm = s.spread_self[p][:, None].astype(jnp.int64)
        ok = match_at + selfm - minm[:, None] <= s.spread_max_skew[p][:, None]
        applies = (s.spread_mask[p] & s.spread_hard[p])[:, None]
        # a node missing the constraint's key is unschedulable for
        # DoNotSchedule constraints (upstream PreFilter node filtering)
        verdict = jnp.where(applies, has & ok, True)
        return jnp.all(verdict, axis=0)

    def score(self, state, snap, p):
        s = snap.scheduling
        if s is None or s.spread_track is None:
            return None
        s, dc, _, code, has = self._constraint_state(state, snap, p)
        match_at = jnp.take_along_axis(dc, jnp.maximum(code, 0), axis=1)
        applies = (s.spread_mask[p] & ~s.spread_hard[p])[:, None] & has
        return jnp.sum(jnp.where(applies, match_at, 0), axis=0)

    def normalize(self, scores, feasible):
        # fewer matching pods in the node's domains = better spread
        return default_normalize(scores, feasible, reverse=True)

    def validate_at(self, state, snap, p, node):
        """Hard-constraint re-check at one node against the live carry —
        used by the batched solver's post-wave demotion scan (domain
        constraints span nodes, so the same-node wave guard cannot see
        them)."""
        s = snap.scheduling
        if s is None or s.spread_track is None:
            return jnp.bool_(True)
        s, dc, minm, code, has = self._constraint_state(state, snap, p)
        code_n = code[:, node]  # (CT,)
        has_n = has[:, node]
        match_at = jnp.take_along_axis(
            dc, jnp.maximum(code_n, 0)[:, None], axis=1
        ).squeeze(1)
        selfm = s.spread_self[p].astype(jnp.int64)
        ok = match_at + selfm - minm <= s.spread_max_skew[p]
        applies = s.spread_mask[p] & s.spread_hard[p]
        return jnp.all(jnp.where(applies, has_n & ok, True))


class InterPodAffinity(Plugin):
    """Required/preferred pod (anti-)affinity over topology domains.

    All selector matching is host-precomputed into the track tables
    (state.scheduling); the live (TR, D) counts and (E, D) anti-domain
    presence bits are carried through the solve, so in-cycle placements are
    visible exactly as the reference's one-pod-per-cycle loop would see
    them. Checks per (pod, node):

    - required affinity term: node has the key AND (matching pods exist in
      the node's domain OR nobody matches cluster-wide and the pod matches
      its own term — the upstream first-pod escape).
    - required anti term (the incoming pod's own): no matching pod in the
      node's domain.
    - SYMMETRY: a node is blocked when its domain hosts a pod CARRYING a
      required anti term whose selector matches the incoming pod
      (upstream existingAntiAffinityCounts).
    - preferred terms score weight x domain match count (anti negative),
      min-max normalized.

    namespaceSelector resolves host-side against the cluster's Namespace
    objects (empty selector = all namespaces). Score is fully symmetric
    (upstream PreScore): besides the incoming pod's own preferred terms,
    every EXISTING pod's preferred (anti-)term whose selector matches the
    incoming pod adds ±weight to the existing pod's domain, and its
    REQUIRED affinity terms add `hard_pod_affinity_weight` (upstream
    HardPodAffinityWeight arg, default 1); carrier counts are carried live
    (`SolverState.sym_counts`) so in-cycle placements contribute.
    """

    name = "InterPodAffinity"
    state_dependent_filter = True

    def events_to_register(self):
        return (ev.POD_ADD, ev.POD_UPDATE, ev.POD_DELETE, ev.NODE_ADD,
                ev.NODE_UPDATE, ev.NAMESPACE_ADD, ev.NAMESPACE_UPDATE)

    def __init__(self, hard_pod_affinity_weight: int = 1,
                 ignore_preferred_terms_of_existing_pods: bool = False):
        if not 0 <= hard_pod_affinity_weight <= 100:
            raise ValueError(
                "hardPodAffinityWeight must be in [0, 100], got "
                f"{hard_pod_affinity_weight}"
            )
        self.hard_pod_affinity_weight = hard_pod_affinity_weight
        self.ignore_preferred = ignore_preferred_terms_of_existing_pods

    def static_key(self):
        return (self.hard_pod_affinity_weight, self.ignore_preferred)

    def _counts(self, state, snap):
        """(TR, D) domain-level counts — affinity has no node-inclusion
        policy, so it reads the pre-aggregated mirror (O(1) row gathers
        instead of per-pod node->domain scatters)."""
        if state is not None and state.sel_dom_counts is not None:
            return state.sel_dom_counts
        return snap.scheduling.track_base

    def _anti_domains(self, state, snap):
        if state is not None and state.anti_domains is not None:
            return state.anti_domains
        return snap.scheduling.exist_anti_base

    def filter(self, state, snap, p):
        s = snap.scheduling
        if s is None or s.aff_track is None:
            return None
        counts = self._counts(state, snap)
        N = snap.num_nodes
        verdict = jnp.ones(N, bool)

        # required affinity
        code = s.topo_code[s.aff_topo[p]]  # (AT, N)
        has = s.topo_has[s.aff_topo[p]]
        dc = counts[s.aff_track[p]]  # (AT, D)
        exists = s.domain_exists[s.aff_topo[p]]
        total = jnp.sum(jnp.where(exists, dc, 0), axis=1)  # (AT,)
        match_at = jnp.take_along_axis(dc, jnp.maximum(code, 0), axis=1)
        ok = has & (
            (match_at > 0)
            | ((total == 0) & s.aff_self[p][:, None])
        )
        verdict &= jnp.all(
            jnp.where(s.aff_mask[p][:, None], ok, True), axis=0
        )

        # the incoming pod's own required anti terms
        codeb = s.topo_code[s.anti_topo[p]]
        hasb = s.topo_has[s.anti_topo[p]]
        dcb = counts[s.anti_track[p]]  # (BT, D)
        match_b = jnp.take_along_axis(dcb, jnp.maximum(codeb, 0), axis=1)
        okb = ~hasb | (match_b == 0)
        verdict &= jnp.all(
            jnp.where(s.anti_mask[p][:, None], okb, True), axis=0
        )

        # symmetry: carriers of matching anti terms block the domain
        if s.exist_anti_sel is not None:
            domains = self._anti_domains(state, snap)  # (E, D)
            codee = s.topo_code[s.exist_anti_topo]  # (E, N)
            blocked = (
                jnp.take_along_axis(domains, jnp.maximum(codee, 0), axis=1)
                & (codee >= 0)
            )
            m = s.exist_anti_match[:, p]  # (E,)
            verdict &= ~jnp.any(m[:, None] & blocked, axis=0)
        return verdict

    def score(self, state, snap, p):
        s = snap.scheduling
        if s is None or s.waff_track is None:
            return None
        counts = self._counts(state, snap)
        code = s.topo_code[s.waff_topo[p]]  # (WT, N)
        has = s.topo_has[s.waff_topo[p]]
        dc = counts[s.waff_track[p]]  # (WT, D)
        match_at = jnp.take_along_axis(dc, jnp.maximum(code, 0), axis=1)
        contrib = jnp.where(
            s.waff_mask[p][:, None] & has,
            s.waff_weight[p][:, None] * match_at,
            0,
        )
        total = jnp.sum(contrib, axis=0)
        if s.sym_sel is not None:
            # symmetric part: existing carriers' terms matching THIS pod
            sym = (
                state.sym_counts
                if state is not None and state.sym_counts is not None
                else s.sym_base
            )  # (E2, D)
            codee = s.topo_code[s.sym_topo]  # (E2, N)
            at = jnp.take_along_axis(sym, jnp.maximum(codee, 0), axis=1)
            at = jnp.where(codee >= 0, at, 0)
            w_eff = jnp.where(
                s.sym_hard,
                self.hard_pod_affinity_weight * s.sym_weight,
                0 if self.ignore_preferred else s.sym_weight,
            )  # (E2,)
            m = s.pend_match[s.sym_sel, p]  # (E2,)
            total = total + jnp.sum(
                jnp.where(m[:, None], w_eff[:, None] * at, 0), axis=0
            )
        return total

    def normalize(self, scores, feasible):
        from scheduler_plugins_tpu.ops.normalize import minmax_normalize

        return minmax_normalize(scores, feasible)

    def validate_at(self, state, snap, p, node):
        """Single-node hard re-check against the live carry (batched-path
        demotion scan) — O(terms) gathers."""
        s = snap.scheduling
        if s is None or s.aff_track is None:
            return jnp.bool_(True)
        counts = self._counts(state, snap)
        ok = jnp.bool_(True)

        code = s.topo_code[s.aff_topo[p], node]  # (AT,)
        has = s.topo_has[s.aff_topo[p], node]
        dc = counts[s.aff_track[p]]  # (AT, D)
        exists = s.domain_exists[s.aff_topo[p]]
        total = jnp.sum(jnp.where(exists, dc, 0), axis=1)
        match_at = jnp.take_along_axis(
            dc, jnp.maximum(code, 0)[:, None], axis=1
        ).squeeze(1)
        aff_ok = has & (
            (match_at > 0) | ((total == 0) & s.aff_self[p])
        )
        ok &= jnp.all(jnp.where(s.aff_mask[p], aff_ok, True))

        codeb = s.topo_code[s.anti_topo[p], node]
        hasb = s.topo_has[s.anti_topo[p], node]
        dcb = counts[s.anti_track[p]]
        match_b = jnp.take_along_axis(
            dcb, jnp.maximum(codeb, 0)[:, None], axis=1
        ).squeeze(1)
        ok &= jnp.all(
            jnp.where(s.anti_mask[p], ~hasb | (match_b == 0), True)
        )

        if s.exist_anti_sel is not None:
            domains = self._anti_domains(state, snap)
            codee = s.topo_code[s.exist_anti_topo, node]  # (E,)
            blocked = (
                jnp.take_along_axis(
                    domains, jnp.maximum(codee, 0)[:, None], axis=1
                ).squeeze(1)
                & (codee >= 0)
            )
            ok &= ~jnp.any(s.exist_anti_match[:, p] & blocked)
        return ok


class TaintToleration(Plugin):
    name = "TaintToleration"

    def events_to_register(self):
        return (ev.NODE_ADD, ev.NODE_UPDATE)

    def filter(self, state, snap, p):
        if snap.scheduling is None:
            return None
        s = snap.scheduling
        return s.tol_ok[s.pod_tol[p]]

    def score(self, state, snap, p):
        if snap.scheduling is None:
            return None
        s = snap.scheduling
        return s.tol_prefer[s.pod_tol[p]]

    def normalize(self, scores, feasible):
        # fewer intolerable PreferNoSchedule taints wins
        return default_normalize(scores, feasible, reverse=True)
