"""In-tree companion plugins: NodeAffinity, TaintToleration,
PodTopologySpread and InterPodAffinity.

These are upstream kube-scheduler plugins (k8s.io/kubernetes
pkg/scheduler/framework/plugins/{nodeaffinity,tainttoleration,
podtopologyspread,interpodaffinity}), NOT part of /root/reference — but real
profiles enable
them alongside the reference's plugins, so drop-in completeness requires
them (docs/PARITY.md "companion plugins", SURVEY.md §7 build plan item 2's
extension-point trait layer).

All matching work happens host-side at snapshot build
(`state.scheduling.build_scheduling` interns unique specs and evaluates each
against every node once); the jitted tensor methods are row gathers and
small segment sums.

- NodeAffinity: Filter = nodeSelector AND required-affinity terms; Score =
  sum of matching preferred-term weights, default-normalized (upstream
  nodeaffinity.go Score/NormalizeScore).
- TaintToleration: Filter = no untolerated NoSchedule/NoExecute taint;
  Score = count of untolerated PreferNoSchedule taints, reverse-normalized
  (upstream tainttoleration.go CountIntolerableTaintsPreferNoSchedule).
- PodTopologySpread: live per-selector counts carried through the solve
  (`SolverState.sel_counts`); Filter enforces DoNotSchedule constraints
  (matchNum + self − globalMin <= maxSkew over the constraint key's
  domains); Score sums ScheduleAnyway match counts, reverse-normalized.
  Not modeled: minDomains, nodeAffinityPolicy/nodeTaintsPolicy refinements
  (upstream defaults approximated by counting over all ready nodes with the
  key), matchLabelKeys.
"""

from __future__ import annotations

import jax.numpy as jnp

from scheduler_plugins_tpu.framework.plugin import Plugin
from scheduler_plugins_tpu.ops.normalize import default_normalize


class NodeAffinity(Plugin):
    name = "NodeAffinity"

    def __init__(self, added_affinity=None):
        #: NodeAffinityArgs.AddedAffinity (upstream): per-profile extra
        #: REQUIRED node-selector terms (OR over terms) ANDed into every
        #: pod's node affinity — cluster operators use it to fence a
        #: profile to a node subset. Accepts NodeSelectorTerm objects or
        #: the wire shape (NodeSelectorTerm.from_wire).
        from scheduler_plugins_tpu.api.objects import NodeSelectorTerm

        self.added_affinity = [
            t if isinstance(t, NodeSelectorTerm)
            else NodeSelectorTerm.from_wire(t)
            for t in added_affinity or []
        ]
        self._added_mask = None

    def prepare_cluster(self, meta, cluster):
        if not self.added_affinity or cluster is None:
            self._added_mask = None
            return
        import numpy as np

        ok = np.ones(max(len(meta.node_names), 1), bool)
        for i, name in enumerate(meta.node_names):
            node = cluster.nodes.get(name)
            ok[i] = node is not None and any(
                t.matches(node) for t in self.added_affinity
            )
        self._added_mask = jnp.asarray(ok)

    def aux(self):
        return self._added_mask

    def filter(self, state, snap, p):
        base = None
        if snap.scheduling is not None:
            s = snap.scheduling
            base = s.node_term_ok[s.pod_node_term[p]]
        added = getattr(self, "_aux", None)
        if added is not None:
            N = snap.num_nodes
            padded = jnp.zeros(N, bool).at[: added.shape[0]].set(added)
            base = padded if base is None else base & padded
        return base

    def score(self, state, snap, p):
        if snap.scheduling is None:
            return None
        s = snap.scheduling
        return s.pref_score[s.pod_pref[p]]

    def normalize(self, scores, feasible):
        return default_normalize(scores, feasible)


class PodTopologySpread(Plugin):
    """maxSkew spreading over topology domains.

    Live counts are (TR, D) per (selector-track, domain), carried through
    the solve; every check is a handful of gathers:

        matchNum(node) = counts[track, domain(node)]
        verdict(node)  = has_key(node)
                         & (matchNum + selfMatch - min_domain <= maxSkew)

    with min_domain the minimum count over the key's existing domains
    (upstream's global minimum). DoNotSchedule constraints filter;
    ScheduleAnyway constraints score (summed match counts, fewer = better).
    """

    name = "PodTopologySpread"
    #: the filter reads the carried live counts — later placements change
    #: earlier verdicts, and domains SPAN nodes, so the batched path also
    #: re-validates placements sequentially (`validate_at`)
    state_dependent_filter = True

    def _counts(self, state, snap):
        if state is not None and state.sel_counts is not None:
            return state.sel_counts
        return snap.scheduling.track_base

    def _constraint_state(self, state, snap, p):
        """Per-constraint (CT,) tensors shared by filter/score/validate:
        live domain counts, the global per-constraint minimum, and masks."""
        s = snap.scheduling
        counts = self._counts(state, snap)  # (TR, D)
        track = s.spread_track[p]  # (CT,)
        dc = counts[track]  # (CT, D)
        exists = s.domain_exists[s.spread_topo[p]]  # (CT, D)
        big = jnp.int64(1) << 62
        minm = jnp.min(jnp.where(exists, dc, big), axis=1)  # (CT,)
        return s, dc, minm

    def filter(self, state, snap, p):
        s = snap.scheduling
        if s is None or s.spread_track is None:
            return None
        s, dc, minm = self._constraint_state(state, snap, p)
        code = s.topo_code[s.spread_topo[p]]  # (CT, N)
        has = s.topo_has[s.spread_topo[p]]  # (CT, N)
        match_at = jnp.take_along_axis(
            dc, jnp.maximum(code, 0), axis=1
        )  # (CT, N)
        selfm = s.spread_self[p][:, None].astype(jnp.int64)
        ok = match_at + selfm - minm[:, None] <= s.spread_max_skew[p][:, None]
        applies = (s.spread_mask[p] & s.spread_hard[p])[:, None]
        # a node missing the constraint's key is unschedulable for
        # DoNotSchedule constraints (upstream PreFilter node filtering)
        verdict = jnp.where(applies, has & ok, True)
        return jnp.all(verdict, axis=0)

    def score(self, state, snap, p):
        s = snap.scheduling
        if s is None or s.spread_track is None:
            return None
        s, dc, _ = self._constraint_state(state, snap, p)
        code = s.topo_code[s.spread_topo[p]]
        has = s.topo_has[s.spread_topo[p]]
        match_at = jnp.take_along_axis(dc, jnp.maximum(code, 0), axis=1)
        applies = (s.spread_mask[p] & ~s.spread_hard[p])[:, None] & has
        return jnp.sum(jnp.where(applies, match_at, 0), axis=0)

    def normalize(self, scores, feasible):
        # fewer matching pods in the node's domains = better spread
        return default_normalize(scores, feasible, reverse=True)

    def validate_at(self, state, snap, p, node):
        """Hard-constraint re-check at one node against the live carry —
        O(CT x D), used by the batched solver's post-wave demotion scan
        (domain constraints span nodes, so the same-node wave guard cannot
        see them)."""
        s = snap.scheduling
        if s is None or s.spread_track is None:
            return jnp.bool_(True)
        s, dc, minm = self._constraint_state(state, snap, p)
        code = s.topo_code[s.spread_topo[p], node]  # (CT,)
        has = s.topo_has[s.spread_topo[p], node]
        match_at = jnp.take_along_axis(
            dc, jnp.maximum(code, 0)[:, None], axis=1
        ).squeeze(1)
        selfm = s.spread_self[p].astype(jnp.int64)
        ok = match_at + selfm - minm <= s.spread_max_skew[p]
        applies = s.spread_mask[p] & s.spread_hard[p]
        return jnp.all(jnp.where(applies, has & ok, True))


class InterPodAffinity(Plugin):
    """Required/preferred pod (anti-)affinity over topology domains.

    All selector matching is host-precomputed into the track tables
    (state.scheduling); the live (TR, D) counts and (E, D) anti-domain
    presence bits are carried through the solve, so in-cycle placements are
    visible exactly as the reference's one-pod-per-cycle loop would see
    them. Checks per (pod, node):

    - required affinity term: node has the key AND (matching pods exist in
      the node's domain OR nobody matches cluster-wide and the pod matches
      its own term — the upstream first-pod escape).
    - required anti term (the incoming pod's own): no matching pod in the
      node's domain.
    - SYMMETRY: a node is blocked when its domain hosts a pod CARRYING a
      required anti term whose selector matches the incoming pod
      (upstream existingAntiAffinityCounts).
    - preferred terms score weight x domain match count (anti negative),
      min-max normalized.

    Not modeled: namespaceSelector, symmetric weighting of EXISTING pods'
    preferred terms toward the incoming pod.
    """

    name = "InterPodAffinity"
    state_dependent_filter = True

    def _counts(self, state, snap):
        if state is not None and state.sel_counts is not None:
            return state.sel_counts
        return snap.scheduling.track_base

    def _anti_domains(self, state, snap):
        if state is not None and state.anti_domains is not None:
            return state.anti_domains
        return snap.scheduling.exist_anti_base

    def filter(self, state, snap, p):
        s = snap.scheduling
        if s is None or s.aff_track is None:
            return None
        counts = self._counts(state, snap)
        N = snap.num_nodes
        verdict = jnp.ones(N, bool)

        # required affinity
        code = s.topo_code[s.aff_topo[p]]  # (AT, N)
        has = s.topo_has[s.aff_topo[p]]
        dc = counts[s.aff_track[p]]  # (AT, D)
        exists = s.domain_exists[s.aff_topo[p]]
        total = jnp.sum(jnp.where(exists, dc, 0), axis=1)  # (AT,)
        match_at = jnp.take_along_axis(dc, jnp.maximum(code, 0), axis=1)
        ok = has & (
            (match_at > 0)
            | ((total == 0) & s.aff_self[p][:, None])
        )
        verdict &= jnp.all(
            jnp.where(s.aff_mask[p][:, None], ok, True), axis=0
        )

        # the incoming pod's own required anti terms
        codeb = s.topo_code[s.anti_topo[p]]
        hasb = s.topo_has[s.anti_topo[p]]
        dcb = counts[s.anti_track[p]]
        match_b = jnp.take_along_axis(dcb, jnp.maximum(codeb, 0), axis=1)
        okb = ~hasb | (match_b == 0)
        verdict &= jnp.all(
            jnp.where(s.anti_mask[p][:, None], okb, True), axis=0
        )

        # symmetry: carriers of matching anti terms block the domain
        if s.exist_anti_sel is not None:
            domains = self._anti_domains(state, snap)  # (E, D)
            codee = s.topo_code[s.exist_anti_topo]  # (E, N)
            blocked = (
                jnp.take_along_axis(domains, jnp.maximum(codee, 0), axis=1)
                & (codee >= 0)
            )
            m = s.exist_anti_match[:, p]  # (E,)
            verdict &= ~jnp.any(m[:, None] & blocked, axis=0)
        return verdict

    def score(self, state, snap, p):
        s = snap.scheduling
        if s is None or s.waff_track is None:
            return None
        counts = self._counts(state, snap)
        code = s.topo_code[s.waff_topo[p]]  # (WT, N)
        has = s.topo_has[s.waff_topo[p]]
        dc = counts[s.waff_track[p]]  # (WT, D)
        match_at = jnp.take_along_axis(dc, jnp.maximum(code, 0), axis=1)
        contrib = jnp.where(
            s.waff_mask[p][:, None] & has,
            s.waff_weight[p][:, None] * match_at,
            0,
        )
        return jnp.sum(contrib, axis=0)

    def normalize(self, scores, feasible):
        from scheduler_plugins_tpu.ops.normalize import minmax_normalize

        return minmax_normalize(scores, feasible)

    def validate_at(self, state, snap, p, node):
        """Single-node hard re-check against the live carry (batched-path
        demotion scan) — O(terms) gathers."""
        s = snap.scheduling
        if s is None or s.aff_track is None:
            return jnp.bool_(True)
        counts = self._counts(state, snap)
        ok = jnp.bool_(True)

        code = s.topo_code[s.aff_topo[p], node]  # (AT,)
        has = s.topo_has[s.aff_topo[p], node]
        dc = counts[s.aff_track[p]]  # (AT, D)
        exists = s.domain_exists[s.aff_topo[p]]
        total = jnp.sum(jnp.where(exists, dc, 0), axis=1)
        match_at = jnp.take_along_axis(
            dc, jnp.maximum(code, 0)[:, None], axis=1
        ).squeeze(1)
        aff_ok = has & (
            (match_at > 0) | ((total == 0) & s.aff_self[p])
        )
        ok &= jnp.all(jnp.where(s.aff_mask[p], aff_ok, True))

        codeb = s.topo_code[s.anti_topo[p], node]
        hasb = s.topo_has[s.anti_topo[p], node]
        dcb = counts[s.anti_track[p]]
        match_b = jnp.take_along_axis(
            dcb, jnp.maximum(codeb, 0)[:, None], axis=1
        ).squeeze(1)
        ok &= jnp.all(
            jnp.where(s.anti_mask[p], ~hasb | (match_b == 0), True)
        )

        if s.exist_anti_sel is not None:
            domains = self._anti_domains(state, snap)
            codee = s.topo_code[s.exist_anti_topo, node]  # (E,)
            blocked = (
                jnp.take_along_axis(
                    domains, jnp.maximum(codee, 0)[:, None], axis=1
                ).squeeze(1)
                & (codee >= 0)
            )
            ok &= ~jnp.any(s.exist_anti_match[:, p] & blocked)
        return ok


class TaintToleration(Plugin):
    name = "TaintToleration"

    def filter(self, state, snap, p):
        if snap.scheduling is None:
            return None
        s = snap.scheduling
        return s.tol_ok[s.pod_tol[p]]

    def score(self, state, snap, p):
        if snap.scheduling is None:
            return None
        s = snap.scheduling
        return s.tol_prefer[s.pod_tol[p]]

    def normalize(self, scores, feasible):
        # fewer intolerable PreferNoSchedule taints wins
        return default_normalize(scores, feasible, reverse=True)
