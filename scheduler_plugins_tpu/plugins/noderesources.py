"""NodeResourcesAllocatable — Score-only plugin favoring nodes with the least
(or most) total allocatable, weighted per resource.

Reference: /root/reference/pkg/noderesources/allocatable.go:42-168,
resource_allocation.go:30-48. Score depends only on node allocatables, so the
raw vector is computed once per snapshot layout and broadcast per pod; the
min-max normalization runs over each pod's feasible set.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from scheduler_plugins_tpu.api.resources import CPU, MEMORY
from scheduler_plugins_tpu.framework.plugin import Plugin
from scheduler_plugins_tpu.ops.allocatable import (
    MODE_LEAST,
    MODE_MOST,
    allocatable_scores,
)
from scheduler_plugins_tpu.ops.normalize import minmax_normalize

#: default weights: a millicore weighs as much as 1 MiB
#: (resource_allocation.go:36)
DEFAULT_RESOURCES = ((CPU, 1 << 20), (MEMORY, 1))


class NodeResourcesAllocatable(Plugin):
    name = "NodeResourcesAllocatable"

    def __init__(
        self,
        resources: Sequence[tuple[str, int]] = DEFAULT_RESOURCES,
        mode: str = "Least",
    ):
        if mode not in ("Least", "Most"):
            raise ValueError(f"invalid mode {mode!r}")  # validation_pluginargs.go:60-75
        for _, weight in resources:
            if weight <= 0:
                raise ValueError("resource weight must be positive")
        self.resources = tuple(resources)
        self.mode_sign = MODE_LEAST if mode == "Least" else MODE_MOST
        self._weights: Optional[jnp.ndarray] = None

    def prepare(self, meta):
        w = np.zeros(len(meta.index), np.int64)
        for name, weight in self.resources:
            if name in meta.index:
                w[meta.index.position(name)] = weight
        self._weights = jnp.asarray(w)

    def aux(self):
        return self._weights

    def score(self, state, snap, p):
        return allocatable_scores(snap.nodes.alloc, self._aux, self.mode_sign)

    def static_node_scores(self, snap):
        # allocatable scores rate the NODE, never the pod
        # (resource_allocation.go:49-76) — the batched fast path applies
        return allocatable_scores(snap.nodes.alloc, self._aux, self.mode_sign)

    def normalize(self, scores, feasible):
        return minmax_normalize(scores, feasible)
