"""The plugin suite — TPU-native re-designs of every plugin the reference
ships in its scheduler binary (/root/reference/cmd/scheduler/main.go:50-67):

Coscheduling, CapacityScheduling, NodeResourcesAllocatable,
NodeResourceTopologyMatch, TargetLoadPacking, LoadVariationRiskBalancing,
LowRiskOverCommitment, Peaks, NetworkOverhead, TopologicalSort,
PreemptionToleration, SySched, PodState, QOSSort.

Plus the in-tree companion plugins real profiles combine them with
(upstream kube-scheduler, not in /root/reference): NodeAffinity,
TaintToleration, PodTopologySpread, InterPodAffinity.
"""

from scheduler_plugins_tpu.plugins.intree import (  # noqa: F401
    InterPodAffinity,
    NodeAffinity,
    PodTopologySpread,
    TaintToleration,
)

from scheduler_plugins_tpu.plugins.capacityscheduling import (  # noqa: F401
    CapacityScheduling,
)
from scheduler_plugins_tpu.plugins.coscheduling import Coscheduling  # noqa: F401
from scheduler_plugins_tpu.plugins.crossnodepreemption import (  # noqa: F401
    CrossNodePreemption,
)
from scheduler_plugins_tpu.plugins.noderesources import (  # noqa: F401
    NodeResourcesAllocatable,
)
from scheduler_plugins_tpu.plugins.noderesourcetopology import (  # noqa: F401
    NodeResourceTopologyMatch,
)
from scheduler_plugins_tpu.plugins.networkaware import (  # noqa: F401
    NetworkOverhead,
    TopologicalSort,
)
from scheduler_plugins_tpu.plugins.podstate import PodState  # noqa: F401
from scheduler_plugins_tpu.plugins.preemptiontoleration import (  # noqa: F401
    PreemptionToleration,
)
from scheduler_plugins_tpu.plugins.qos import QOSSort  # noqa: F401
from scheduler_plugins_tpu.plugins.sysched import SySched  # noqa: F401
from scheduler_plugins_tpu.plugins.trimaran import (  # noqa: F401
    LoadVariationRiskBalancing,
    LowRiskOverCommitment,
    Peaks,
    TargetLoadPacking,
)
