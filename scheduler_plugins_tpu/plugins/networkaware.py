"""Network-aware plugins: NetworkOverhead (PreFilter/Filter/Score) and
TopologicalSort (QueueSort).

Reference: /root/reference/pkg/networkaware (SURVEY.md §2.8). Pods belong to an
AppGroup CR (microservice DAG with per-dependency MaxNetworkCost); a
NetworkTopology CR carries origin->destination costs per topology key
(region/zone) per weights profile. The per-node costMap walk becomes a dense
gather over (zone, region) codes (`ops.network.dependency_tallies`):

- Filter rejects a node when violated > satisfied dependencies
  (networkoverhead.go:326-359).
- Score is the accumulated cost, normalized inverted (lowest cost wins,
  networkoverhead.go:362-420 — same transform as Peaks).
- Pods without an AppGroup or dependencies "score equally": filter passes,
  score 0 (the scoreEqually path).

TopologicalSort orders pods of the SAME AppGroup by their index in
AppGroup.Status.TopologyOrder, falling back to upstream PrioritySort
otherwise (topologicalsort.go:102-132) — an inherently pairwise comparator,
exposed via `queue_compare`.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np

from scheduler_plugins_tpu.framework.plugin import Plugin
from scheduler_plugins_tpu.ops.network import (
    class_dependency_tallies,
    dependency_tallies,
    placed_commit,
)
from scheduler_plugins_tpu.ops.normalize import peaks_normalize
from scheduler_plugins_tpu.api import events as ev

DEFAULT_WEIGHTS_NAME = "UserDefined"  # defaults.go:232-244
DEFAULT_NETWORK_TOPOLOGY_NAME = "nt-default"


class NetworkOverhead(Plugin):
    name = "NetworkOverhead"

    def events_to_register(self):
        # dependency placements/deletions and CR updates change the
        # satisfied/violated tallies (no upstream EventsToRegister — the
        # reference relies on the default rescan; these are the events its
        # Filter verdict actually depends on)
        # Pod/Update included because cluster.bind() records bindings as
        # Pod/Update — a dependency binding can flip violated>satisfied.
        return (ev.POD_ADD, ev.POD_UPDATE, ev.POD_DELETE,
                ev.APP_GROUP_ADD, ev.APP_GROUP_UPDATE,
                ev.NETWORK_TOPOLOGY_ADD, ev.NETWORK_TOPOLOGY_UPDATE)
    #: Filter tallies read the carried in-cycle placement counts — the
    #: batched path re-evaluates it per wave (counting heuristic, not a
    #: resource-safety bound, so no within-wave guard is needed)
    state_dependent_filter = True

    def __init__(
        self,
        weights_name: str = DEFAULT_WEIGHTS_NAME,
        network_topology_name: str = DEFAULT_NETWORK_TOPOLOGY_NAME,
        namespaces: tuple = (),
    ):
        self.weights_name = weights_name
        self.network_topology_name = network_topology_name
        self.namespaces = namespaces
        self._zone_cost: Optional[jnp.ndarray] = None
        self._region_cost: Optional[jnp.ndarray] = None

    def prepare_cluster(self, meta, cluster):
        """Lower the NetworkTopology CR's cost lists into dense (ZC, ZC) /
        (RC, RC) matrices on this snapshot's zone/region codes
        (networkoverhead.go:448-497 costMap extraction)."""
        ZC = max(len(meta.zones), 1)
        RC = max(len(meta.regions), 1)
        zone_cost = np.full((ZC, ZC), -1, np.int64)
        region_cost = np.full((RC, RC), -1, np.int64)
        nt = None
        if cluster is not None:
            for cand in cluster.network_topologies.values():
                if cand.name == self.network_topology_name:
                    nt = cand
                    break
        if nt is not None:
            weights = nt.weights.get(self.weights_name, {})
            for (orig, dest), cost in weights.get("zone", {}).items():
                if orig in meta.zones and dest in meta.zones:
                    zone_cost[meta.zones.index(orig), meta.zones.index(dest)] = cost
            for (orig, dest), cost in weights.get("region", {}).items():
                if orig in meta.regions and dest in meta.regions:
                    region_cost[
                        meta.regions.index(orig), meta.regions.index(dest)
                    ] = cost
        self._zone_cost = jnp.asarray(zone_cost)
        self._region_cost = jnp.asarray(region_cost)

    def aux(self):
        if self._zone_cost is None:
            return None
        return (self._zone_cost, self._region_cost)

    def host_state(self):
        # cost matrices come from the live Cluster's NetworkTopology CR;
        # replay rebuilds without a Cluster (prepare_cluster then bakes
        # all -1 matrices), so record the real ones for an exact rebuild
        if self._zone_cost is None:
            return None
        return {"zone_cost": self._zone_cost, "region_cost": self._region_cost}

    def restore_host_state(self, state) -> None:
        self._zone_cost = jnp.asarray(state["zone_cost"])
        self._region_cost = jnp.asarray(state["region_cost"])

    def _tallies(self, state, snap, p):
        net = snap.network
        placed = state.net_placed if state.net_placed is not None else net.placed_node
        zone_cost, region_cost = self._aux
        return dependency_tallies(
            net.dep_workload[p],
            net.dep_max_cost[p],
            net.dep_mask[p],
            placed,
            snap.nodes.zone,
            snap.nodes.region,
            net.zone_region,
            zone_cost,
            region_cost,
        )

    def filter(self, state, snap, p):
        if snap.network is None or self._zone_cost is None:
            return None
        satisfied, violated, _ = self._tallies(state, snap, p)
        score_equally = ~snap.network.dep_mask[p].any()
        return score_equally | (violated <= satisfied)

    def score(self, state, snap, p):
        if snap.network is None or self._zone_cost is None:
            return None
        _, _, cost = self._tallies(state, snap, p)
        score_equally = ~snap.network.dep_mask[p].any()
        return jnp.where(score_equally, 0, cost)

    # -- class-collapsed whole-batch variants ---------------------------
    # Every pod of a workload shares its AppGroup dependency row, so the
    # (D, N) tallies run once per WORKLOAD class ((W, N) work) and pods
    # gather their class row — bit-identical to the vmapped per-pod path
    # (integer tallies over identical inputs), with P/W-fold less work on
    # the batched solver's hot passes.
    def _class_tallies(self, state, snap):
        net = snap.network
        placed = (
            state.net_placed if state.net_placed is not None
            else net.placed_node
        )
        zone_cost, region_cost = self._aux
        return class_dependency_tallies(
            net.cls_dep_workload, net.cls_dep_max_cost, net.cls_dep_mask,
            placed, snap.nodes.zone, snap.nodes.region,
            net.zone_region, zone_cost, region_cost,
        )

    def batch_rows(self, state, snap):
        """Fused filter+score: the (W, N) tallies are shared, so the
        batched solver's cycle-initial pass pays for them once. The single
        source of truth for the batched verdict/score expressions —
        `filter_batch`/`score_batch` delegate here (XLA dead-code-
        eliminates whichever half a caller drops)."""
        if (snap.network is None or self._zone_cost is None
                or snap.network.cls_dep_workload is None):
            # class rows absent (e.g. a snapshot built by an export path
            # predating them): fall back to the per-pod path (ADVICE r4)
            return None
        net = snap.network
        sat, vio, cost = self._class_tallies(state, snap)  # (W, N) each
        cls = jnp.maximum(net.pod_workload, 0)
        # pods without a workload or without dependencies score equally:
        # filter passes (networkoverhead.go scoreEqually path)
        score_equally = ~net.dep_mask.any(axis=1) | (net.pod_workload < 0)
        verdict = jnp.where(
            score_equally[:, None], True, (vio <= sat)[cls]
        )
        scores = jnp.where(score_equally[:, None], 0, cost[cls])
        return verdict, scores

    def filter_batch(self, state, snap):
        rows = self.batch_rows(state, snap)
        return None if rows is None else rows[0]

    def score_batch(self, state, snap):
        rows = self.batch_rows(state, snap)
        return None if rows is None else rows[1]

    def commit(self, state, snap, p, choice):
        if snap.network is None or state.net_placed is None:
            return state
        return state.replace(
            net_placed=placed_commit(
                state.net_placed, snap.network.pod_workload[p], choice
            )
        )

    def commit_batch(self, state, snap, placed, choice):
        """Batched Reserve: placement tallies are counts, so one scatter-add
        over the wave's winners equals any sequential order of `commit`s."""
        if snap.network is None or state.net_placed is None:
            return state
        return state.replace(
            net_placed=placed_commit(
                state.net_placed,
                snap.network.pod_workload,
                jnp.where(placed, choice, -1),
            )
        )

    def normalize(self, scores, feasible):
        return peaks_normalize(scores, feasible)


class TopologicalSort(Plugin):
    """QueueSort by AppGroup topology order (topologicalsort.go:102-132)."""

    name = "TopologicalSort"

    def __init__(self, namespaces: tuple = ()):
        self.namespaces = namespaces

    def queue_compare(self, p1, p2, cluster):
        """Pairwise Less(): same AppGroup -> topology-order index; different
        or none -> upstream PrioritySort (priority desc, queue time asc)."""
        ag1, ag2 = p1.app_group(), p2.app_group()
        if ag1 and ag1 == ag2 and p1.namespace == p2.namespace and cluster is not None:
            ag = cluster.app_groups.get(f"{p1.namespace}/{ag1}")
            if ag is not None:
                o1 = ag.topology_order.get(p1.workload_selector(), 0)
                o2 = ag.topology_order.get(p2.workload_selector(), 0)
                if o1 != o2:
                    return -1 if o1 <= o2 else 1
        if p1.priority != p2.priority:
            return -1 if p1.priority > p2.priority else 1
        if p1.creation_ms != p2.creation_ms:
            return -1 if p1.creation_ms < p2.creation_ms else 1
        return -1 if p1.uid < p2.uid else 1
