"""CapacityScheduling — per-namespace elastic quota enforcement.

Reference: /root/reference/pkg/capacityscheduling (PreFilter with AddPod/
RemovePod extensions, quota-aware preemption PostFilter, Reserve/Unreserve —
capacity_scheduling.go:101-105).

TPU mapping: the EQ snapshot becomes the (Q, R) `eq_used` array carried
through the solve; PreFilter's two rejects (over own Max, aggregate over
cluster Min) are `ops.quota.quota_admit`; Reserve is `quota_commit` on the
scan carry. Quota-aware preemption is provided by the preemption engine
(plugins/preemption.py) using the same borrow rules.
"""

from __future__ import annotations

from scheduler_plugins_tpu.framework.plugin import Plugin
from scheduler_plugins_tpu.ops.quota import quota_admit, quota_commit


class CapacityScheduling(Plugin):
    name = "CapacityScheduling"

    def preemption_engine(self):
        """PostFilter = quota-aware preemption
        (capacity_scheduling.go:331-348 wraps the upstream evaluator with the
        EQ borrow rules)."""
        from scheduler_plugins_tpu.framework.preemption import (
            PreemptionEngine,
            PreemptionMode,
        )

        return PreemptionEngine(PreemptionMode.CAPACITY)

    def admit(self, state, snap, p):
        if snap.quota is None or state.eq_used is None:
            return None
        return quota_admit(
            state.eq_used,
            snap.quota.min,
            snap.quota.max,
            snap.quota.has_quota,
            snap.pods.ns[p],
            snap.pods.req[p],
        )

    def commit(self, state, snap, p, choice):
        if snap.quota is None or state.eq_used is None:
            return state
        return state.replace(
            eq_used=quota_commit(
                state.eq_used,
                snap.quota.has_quota,
                snap.pods.ns[p],
                snap.pods.req[p],
                choice >= 0,
            )
        )
