"""CapacityScheduling — per-namespace elastic quota enforcement.

Reference: /root/reference/pkg/capacityscheduling (PreFilter with AddPod/
RemovePod extensions, quota-aware preemption PostFilter, Reserve/Unreserve —
capacity_scheduling.go:101-105).

TPU mapping: the EQ snapshot becomes the (Q, R) `eq_used` array carried
through the solve; PreFilter's two rejects (over own Max, aggregate over
cluster Min) are `ops.quota.quota_admit`; Reserve is `quota_commit` on the
scan carry. Quota-aware preemption is provided by the preemption engine
(plugins/preemption.py) using the same borrow rules.
"""

from __future__ import annotations

from scheduler_plugins_tpu.framework.plugin import Plugin
from scheduler_plugins_tpu.ops.quota import quota_admit, quota_commit
from scheduler_plugins_tpu.api import events as ev


class CapacityScheduling(Plugin):
    name = "CapacityScheduling"

    def __init__(self, min_candidate_nodes_percentage: int = None,
                 min_candidate_nodes_absolute: int = None):
        #: candidate-sampling knobs of the upstream evaluator the reference
        #: wraps (preemption.NewEvaluator consumes DefaultPreemptionArgs;
        #: calculateNumCandidates preemption_toleration.go:318-331 is the
        #: shared k/k implementation) — validated at engine construction
        from scheduler_plugins_tpu.framework.preemption import (
            PreemptionEngine,
        )

        PreemptionEngine.validate_sampling_args(  # fail fast at load time
            min_candidate_nodes_percentage, min_candidate_nodes_absolute
        )
        self.min_candidate_nodes_percentage = min_candidate_nodes_percentage
        self.min_candidate_nodes_absolute = min_candidate_nodes_absolute

    def events_to_register(self):
        # freed capacity or quota growth (capacity_scheduling.go:194-203;
        # the EQ event is ActionType All)
        return (ev.POD_DELETE, ev.ELASTIC_QUOTA_ADD, ev.ELASTIC_QUOTA_UPDATE,
                ev.ELASTIC_QUOTA_DELETE)

    def preemption_engine(self):
        """PostFilter = quota-aware preemption
        (capacity_scheduling.go:331-348 wraps the upstream evaluator with the
        EQ borrow rules)."""
        from scheduler_plugins_tpu.framework.preemption import (
            PreemptionEngine,
            PreemptionMode,
        )

        return PreemptionEngine(
            PreemptionMode.CAPACITY,
            min_candidate_nodes_percentage=self.min_candidate_nodes_percentage,
            min_candidate_nodes_absolute=self.min_candidate_nodes_absolute,
        )

    def admit(self, state, snap, p):
        if snap.quota is None or state.eq_used is None:
            return None
        import jax.numpy as jnp

        quota = snap.quota
        # live nominee aggregates: a nominee that already placed in this
        # scan is usage (eq_used carry), not a nomination anymore
        placed = (
            state.placed_mask[jnp.maximum(quota.nom_batch_idx, 0)]
            & (quota.nom_batch_idx >= 0)
            if state.placed_mask is not None
            else jnp.zeros(quota.nom_req.shape[0], bool)
        )  # (M,)
        live = ~placed
        in_eq = jnp.sum(
            jnp.where(
                (quota.nom_in_eq_mask[:, p] & live)[:, None], quota.nom_req, 0
            ),
            axis=0,
        )
        total = jnp.sum(
            jnp.where(
                (quota.nom_total_mask[:, p] & live)[:, None], quota.nom_req, 0
            ),
            axis=0,
        )
        return quota_admit(
            state.eq_used,
            quota.min,
            quota.max,
            quota.has_quota,
            snap.pods.ns[p],
            snap.pods.req[p],
            in_eq,
            total,
        )

    def commit(self, state, snap, p, choice):
        if snap.quota is None or state.eq_used is None:
            return state
        return state.replace(
            eq_used=quota_commit(
                state.eq_used,
                snap.quota.has_quota,
                snap.pods.ns[p],
                snap.pods.req[p],
                choice >= 0,
            )
        )
