"""Coscheduling — gang scheduling over PodGroups.

Reference: /root/reference/pkg/coscheduling (QueueSort, PreFilter, PostFilter,
Permit, Unreserve — coscheduling.go:49-55, core engine core/core.go).

TPU mapping:
- PreFilter (backoff / membership / gated-quorum / MinResources cluster sweep)
  -> `ops.gang.gang_admit`, a masked reduction inside the jitted solve.
- Permit quorum -> segment reduction in the runtime after the scan
  (`Scheduler.solve` wait computation).
- Permit Wait/Allow/Reject timing, sibling activation, whole-gang PostFilter
  rejection and backoff are host-side wall-clock logic in
  `framework.cycle.run_cycle` — concurrency bookkeeping, not math
  (SURVEY.md §7 build order #4).

Defaults (apis/config/v1/defaults.go:29-47): PermitWaitingTimeSeconds=60,
PodGroupBackoffSeconds=0, PodGroupRejectPercentage=10.
"""

from __future__ import annotations

from scheduler_plugins_tpu.framework.plugin import Plugin
from scheduler_plugins_tpu.ops.fit import pod_fit_demand
from scheduler_plugins_tpu.ops.gang import (
    gang_admit,
    gang_commit,
    gang_inflight_commit,
)
from scheduler_plugins_tpu.api import events as ev

DEFAULT_PERMIT_WAITING_SECONDS = 60
DEFAULT_POD_GROUP_BACKOFF_SECONDS = 0
DEFAULT_REJECT_PERCENTAGE = 10


class Coscheduling(Plugin):
    name = "Coscheduling"

    def events_to_register(self):
        # a new sibling or PodGroup change can complete the quorum
        # (coscheduling.go:113-122)
        return (ev.POD_ADD, ev.POD_GROUP_ADD, ev.POD_GROUP_UPDATE)

    def __init__(
        self,
        permit_waiting_seconds: int = DEFAULT_PERMIT_WAITING_SECONDS,
        pod_group_backoff_seconds: int = DEFAULT_POD_GROUP_BACKOFF_SECONDS,
        reject_percentage: int = DEFAULT_REJECT_PERCENTAGE,
    ):
        # validation_pluginargs.go:48-58
        if permit_waiting_seconds < 0 or pod_group_backoff_seconds < 0:
            raise ValueError("timeouts must be non-negative")
        if not 0 <= reject_percentage <= 100:
            raise ValueError("reject percentage must be in [0, 100]")
        self.permit_waiting_seconds = permit_waiting_seconds
        self.pod_group_backoff_seconds = pod_group_backoff_seconds
        self.reject_percentage = reject_percentage

    # QueueSort (coscheduling.go:133-145): priority desc -> group/pod creation
    # time (failure-time override applied by the cluster store) -> name
    def queue_key(self, pod, cluster):
        created = pod.creation_ms
        tiebreak = f"{pod.namespace}/{pod.name}"
        if cluster is not None:
            pg = cluster.pod_group_of(pod)
            if pg is not None:
                created = cluster.gang_sort_time(pg)
                tiebreak = pg.full_name
        return (-pod.priority, created, tiebreak)

    def admit(self, state, snap, p):
        if snap.gangs is None:
            return None
        return gang_admit(
            snap.gangs, state.free, snap.pods.gang[p], state.gang_inflight
        )

    def commit(self, state, snap, p, choice):
        if snap.gangs is None or state.gang_scheduled is None:
            return state
        placed = choice >= 0
        gang = snap.pods.gang[p]
        state = state.replace(
            gang_scheduled=gang_commit(state.gang_scheduled, gang, placed)
        )
        if state.gang_inflight is not None:
            state = state.replace(
                gang_inflight=gang_inflight_commit(
                    state.gang_inflight,
                    gang,
                    pod_fit_demand(snap.pods.req[p]),
                    placed,
                )
            )
        return state
