"""Packing optimizer: iterative consolidation rounds over a wave placement.

The third solve mode ("Priority Matters", arxiv 2511.08373; ROADMAP
item 1): both existing solve paths — the bit-faithful sequential scan and
the wave/waterfill throughput path — are ONE-PASS greedy over queue
order, which leaves cluster utilization on the table: residual free
capacity ends up as dust spread over many partially-filled nodes
(`tuning.quality.fragmentation`), and lightly-loaded nodes stay pinned by
a handful of pods a better assignment would consolidate elsewhere.

`packing_refine` climbs that frontier: a jittable `lax.while_loop` of
reassignment rounds over the SAME int64 reference-unit quantities. Per
round:

1. **Donor election** — the emptiest still-occupied schedulable node (by
   float64 fill fraction over cpu+memory) that still holds batch pods and
   was not frozen by a failed earlier round.
2. **Bids** — each batch pod on the donor bids for every other occupied
   node: ``bid(n) = score_frac(n) + price_weight * fill(n)``, where
   `score_frac` is the profile's static node ranking min-max-normalized
   to [0, 1] (the same raw vector the targeted waterfill ranks by) and
   `fill(n)` is the node's cpu/mem fill fraction — a FRAGMENTATION PRICE
   on each node's remaining free vector: emptier targets are expensive,
   so pods prefer to densify already-full nodes (auction-style bidding
   with a static per-round price vector). A decaying temperature
   (`temperature * decay^round`) sets the minimum fill EDGE a target must
   have over the donor — early rounds take only clearly-packing moves,
   later rounds accept marginal ones.
3. **Commit** — the movers' choices run through the EXISTING sorted-
   segment queue-order admission (`ops.assign._queue_order_admission_
   choice`): a move is admitted only if the target still fits the mover's
   demand after every earlier same-round mover of that target, so
   resource fit holds BY CONSTRUCTION at every intermediate state.
   Admitted movers scatter their demand off the donor and onto the
   target; the donor is frozen when a round moves nothing.

Moves never change WHICH pods are placed — only where — so namespace
quota usage and gang quorum counts are untouched by refinement, and the
caller's `finalize_assignment` tail (queue-order quota prefix + Permit
quorum) enforces those families exactly as the wave path does. The
`tuning.gates` numpy replay oracles certify every packing solve in the
bench/CI gates (`make pack-smoke`).

Why this strictly improves the packing objectives: an emptied donor
removes its (large) free vector from the packed numerator of
`packed_utilization` — since the donor was the emptiest occupied node,
its free fraction exceeds the occupied average, so dropping it raises
packed utilization strictly; its freed capacity also consolidates into
one whole-node block, growing the largest free block `fragmentation`
measures. Targets are restricted to OCCUPIED nodes, so refinement never
spreads load onto empty nodes.

Knobs (iteration budget, price weight, temperature schedule) ride a
traced float64 aux vector (`pack_aux`), NOT closure constants — one
compile serves every budget/weight the tuner sweeps (CLAUDE.md
aux-channel discipline; the budget bounds a `lax.while_loop`, so budget 0
returns the wave placement bit-identically).

`packing_refine_np` is the bit-exact numpy twin (identical op order,
identical float64 arithmetic, lowest-index tie-breaks) — the differential
gate in tests/test_packing.py holds the two together the way
`gangs.topology.gang_solve_np` gates the gang solve.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from scheduler_plugins_tpu.ops import CPU_I, MEMORY_I, PODS_I
from scheduler_plugins_tpu.ops.assign import _queue_order_admission_choice
from scheduler_plugins_tpu.ops.fit import pod_fit_demand

#: pack_aux slots: [iterations, price_weight, temperature, decay] — one
#: traced float64 vector (see `pack_aux_vector`), so knob changes never
#: recompile. Kept as a module constant so the config surface
#: (`framework.runtime.PackingConfig`) and the solvers agree on the layout.
PACK_AUX_SLOTS = ("iterations", "price_weight", "temperature", "decay")


def pack_aux_vector(iterations, price_weight, temperature, decay):
    """The (4,) float64 traced knob vector `packing_refine` consumes."""
    return jnp.asarray(
        [float(iterations), float(price_weight), float(temperature),
         float(decay)],
        jnp.float64,
    )


def _fill_fraction(free, alloc, node_mask):
    """(N,) float64 cpu/mem fill fraction (used / allocatable, averaged
    over the two core resources); -1.0 on masked rows so they can never
    be elected donor nor priced as a target."""
    allocf = alloc[:, (CPU_I, MEMORY_I)].astype(jnp.float64)
    freef = free[:, (CPU_I, MEMORY_I)].astype(jnp.float64)
    util = jnp.where(
        allocf > 0, (allocf - freef) / jnp.maximum(allocf, 1.0), 0.0
    )
    fill = (util[:, 0] + util[:, 1]) / 2.0
    return jnp.where(node_mask, fill, -1.0)


def _score_fraction(raw_scores, node_mask):
    """(N,) float64 min-max normalization of the static node ranking to
    [0, 1] over schedulable nodes — the score term of the bid (raw int64
    scores have arbitrary scale; the price term needs a comparable
    unit)."""
    raw = raw_scores.astype(jnp.float64)
    lo = jnp.min(jnp.where(node_mask, raw, jnp.inf))
    hi = jnp.max(jnp.where(node_mask, raw, -jnp.inf))
    span = jnp.maximum(hi - lo, 1.0)
    frac = jnp.where(node_mask, (raw - lo) / span, 0.0)
    return frac


def packing_refine(raw_scores, req, pod_mask, alloc, node_mask, free0,
                   assignment0, pack_aux, mover_cap: int = 128):
    """Refine a wave placement by consolidation rounds (module docstring).

    Arguments: `raw_scores` (N,) int64 static node ranking (the targeted
    waterfill's caller contract), `req` (P, R) int64 requests, `pod_mask`
    (P,) admitted batch rows, `alloc` (N, R) allocatable, `node_mask`
    (N,) schedulable, `free0` (N, R) free AFTER the wave placement
    (consistent with `assignment0`), `assignment0` (P,) int32 the wave
    placements, `pack_aux` the (4,) traced knob vector
    (`pack_aux_vector`). `mover_cap` (static) bounds the per-round mover
    window — a donor holding more batch pods drains over several rounds.

    Returns (assignment, free, stats) with stats = {"rounds", "moves",
    "emptied"} (int32 scalars). Budget 0 returns the inputs unchanged —
    bit-identical to the wave path by construction. Not jitted itself
    (runs inside the caller's jit, like `waterfill_assign_stateful`).
    """
    P, R = req.shape
    N = free0.shape[0]
    W = min(mover_cap, P)
    demand = pod_fit_demand(req)
    n_iters = pack_aux[0]
    price_weight = pack_aux[1]
    temperature = pack_aux[2]
    decay = pack_aux[3]
    score_frac = _score_fraction(raw_scores, node_mask)
    # alloc pods-slot minus free pods-slot counts resident pods (the
    # requested base the solve free was derived from charges 1 per bound
    # pod, and every batch placement charges 1 more)
    alloc_pods = alloc[:, PODS_I]

    def occupied_of(free):
        return node_mask & (alloc_pods - free[:, PODS_I] > 0)

    def batch_count_of(assignment):
        placed = (assignment >= 0) & pod_mask
        return jnp.zeros(N + 1, jnp.int32).at[
            jnp.where(placed, assignment, N)
        ].add(1)[:N]

    def round_body(carry):
        free, assignment, frozen, it, theta, moves, done = carry
        fill = _fill_fraction(free, alloc, node_mask)
        occupied = occupied_of(free)
        eligible = occupied & ~frozen & (batch_count_of(assignment) > 0)
        any_donor = eligible.any()
        # donor = emptiest eligible node (lowest fill; ties -> lowest
        # index via argmin)
        d = jnp.argmin(jnp.where(eligible, fill, jnp.inf)).astype(jnp.int32)
        fill_d = fill[d]

        # mover window: first W batch pods on the donor, queue order
        # (rank-compaction scatter — the _straggler_window shape)
        on_donor = (assignment == d) & pod_mask & any_donor
        rank = jnp.cumsum(on_donor) - 1
        slot = jnp.where(on_donor & (rank < W), rank, W).astype(jnp.int32)
        idx = jnp.full(W + 1, P, jnp.int32).at[slot].min(
            jnp.arange(P, dtype=jnp.int32)
        )[:W]
        valid = idx < P
        dem_w = jnp.where(valid[:, None], demand[jnp.minimum(idx, P - 1)], 0)

        # bids: score + fragmentation price, over occupied fitting
        # targets with the decaying fill-edge guard (theta is carried and
        # decayed multiplicatively — a pow() here could round differently
        # between the XLA and numpy builds)
        target_ok = (
            occupied
            & (jnp.arange(N) != d)
            & (fill >= fill_d + theta)
        )
        fit = jnp.all(
            dem_w[:, None, :] <= free[None, :, :], axis=2
        )  # (W, N)
        cand = fit & target_ok[None, :] & valid[:, None]
        bid = score_frac + price_weight * fill  # (N,) static per round
        masked_bid = jnp.where(cand, bid[None, :], -jnp.inf)
        best = jnp.argmax(masked_bid, axis=1).astype(jnp.int32)
        choice = jnp.where(cand.any(axis=1), best, -1)

        # queue-order sorted-segment admission against the round-start
        # free rows (movers' own demand still sits on the donor, which is
        # never a target, so target headroom is exact)
        admitted = (choice >= 0) & _queue_order_admission_choice(
            choice, dem_w, free
        )

        safe_idx = jnp.minimum(idx, P - 1)
        placed_plus = jnp.zeros(P, jnp.int32).at[safe_idx].add(
            jnp.where(admitted, choice + 1, 0)
        )
        assignment = jnp.where(placed_plus > 0, placed_plus - 1, assignment)
        moved_dem = jnp.where(admitted[:, None], dem_w, 0)
        used_t = jnp.zeros_like(free).at[
            jnp.where(admitted, choice, N - 1)
        ].add(moved_dem)
        free = free - used_t
        free = free.at[d].add(moved_dem.sum(axis=0))
        n_moved = admitted.sum().astype(jnp.int32)
        frozen = frozen.at[d].set(
            jnp.where(any_donor, n_moved == 0, frozen[d])
        )
        return (
            free, assignment, frozen, it + 1, theta * decay,
            moves + n_moved, ~any_donor,
        )

    def cond(carry):
        _, _, _, it, _, _, done = carry
        # floor the traced budget: the numpy twin's `int(n_iters)` floors,
        # so a fractional budget (a continuous tuner proposal) must run
        # the SAME round count on both builds — `it < 1.5` would run one
        # round more here than there and break the bit-parity anchor
        return (it.astype(jnp.float64) < jnp.floor(n_iters)) & ~done

    occupied0 = occupied_of(free0)
    init = (
        free0, assignment0, jnp.zeros(N, bool), jnp.int32(0),
        temperature, jnp.int32(0), jnp.bool_(False),
    )
    free, assignment, _, rounds, _, moves, _ = jax.lax.while_loop(
        cond, round_body, init
    )
    emptied = (occupied0 & ~occupied_of(free)).sum().astype(jnp.int32)
    stats = {"rounds": rounds, "moves": moves, "emptied": emptied}
    return assignment, free, stats


# ---------------------------------------------------------------------------
# numpy twin (bit-exact: identical op order, float64 arithmetic, ties)
# ---------------------------------------------------------------------------


def _queue_order_admission_choice_np(choice, demand, free):
    """Numpy twin of `ops.assign._queue_order_admission_choice` — the
    sorted-segment queue-order admission check, identical float64 prefix
    arithmetic (cumsum minus own value, cummax rebase)."""
    P = choice.shape[0]
    N = free.shape[0]
    seg_choice = np.where(choice >= 0, choice, N)
    order = np.argsort(seg_choice.astype(np.int64) * P + np.arange(P))
    seg = seg_choice[order]
    first = np.concatenate([[True], seg[1:] != seg[:-1]])
    dem_sorted = demand[order].astype(np.float64)
    csum = np.cumsum(dem_sorted, axis=0)
    exclusive = csum - dem_sorted
    base = np.maximum.accumulate(
        np.where(first[:, None], exclusive, -1.0), axis=0
    )
    within = csum - base
    free_row = free[np.minimum(seg, N - 1)].astype(np.float64)
    ok_sorted = np.all(within <= free_row, axis=1) & (seg < N)
    out = np.zeros(P, bool)
    out[order] = ok_sorted
    return out


def packing_refine_np(raw_scores, req, pod_mask, alloc, node_mask, free0,
                      assignment0, pack_aux, mover_cap: int = 128):
    """Bit-exact numpy sequential twin of `packing_refine` (same rounds,
    same elections, same commits) — the differential anchor and the
    degraded-mode/host certification path."""
    raw_scores = np.asarray(raw_scores)
    req = np.asarray(req)
    pod_mask = np.asarray(pod_mask).astype(bool)
    alloc = np.asarray(alloc)
    node_mask = np.asarray(node_mask).astype(bool)
    free = np.asarray(free0).copy()
    assignment = np.asarray(assignment0).copy()
    pack_aux = np.asarray(pack_aux, np.float64)
    P, R = req.shape
    N = free.shape[0]
    W = min(mover_cap, P)
    demand = req.copy()
    demand[:, PODS_I] = 1
    n_iters, price_weight, temperature, decay = (
        float(pack_aux[0]), float(pack_aux[1]), float(pack_aux[2]),
        float(pack_aux[3]),
    )

    def fill_fraction(free):
        allocf = alloc[:, (CPU_I, MEMORY_I)].astype(np.float64)
        freef = free[:, (CPU_I, MEMORY_I)].astype(np.float64)
        util = np.where(
            allocf > 0, (allocf - freef) / np.maximum(allocf, 1.0), 0.0
        )
        fill = (util[:, 0] + util[:, 1]) / 2.0
        return np.where(node_mask, fill, -1.0)

    raw = raw_scores.astype(np.float64)
    lo = np.min(np.where(node_mask, raw, np.inf))
    hi = np.max(np.where(node_mask, raw, -np.inf))
    span = max(hi - lo, 1.0)
    score_frac = np.where(node_mask, (raw - lo) / span, 0.0)
    alloc_pods = alloc[:, PODS_I]

    def occupied_of(free):
        return node_mask & (alloc_pods - free[:, PODS_I] > 0)

    occupied0 = occupied_of(free)
    frozen = np.zeros(N, bool)
    moves = 0
    rounds = 0
    theta = temperature
    while rounds < int(n_iters):
        fill = fill_fraction(free)
        occupied = occupied_of(free)
        placed = (assignment >= 0) & pod_mask
        batch_count = np.zeros(N + 1, np.int32)
        np.add.at(batch_count, np.where(placed, assignment, N), 1)
        eligible = occupied & ~frozen & (batch_count[:N] > 0)
        if not eligible.any():
            rounds += 1
            break
        d = int(np.argmin(np.where(eligible, fill, np.inf)))
        fill_d = fill[d]
        on_donor = np.nonzero((assignment == d) & pod_mask)[0][:W]
        dem_w = demand[on_donor]
        target_ok = (
            occupied & (np.arange(N) != d) & (fill >= fill_d + theta)
        )
        fit = np.all(dem_w[:, None, :] <= free[None, :, :], axis=2)
        cand = fit & target_ok[None, :]
        bid = score_frac + price_weight * fill
        masked_bid = np.where(cand, bid[None, :], -np.inf)
        best = np.argmax(masked_bid, axis=1).astype(np.int32)
        choice = np.where(cand.any(axis=1), best, -1)
        admitted = (choice >= 0) & _queue_order_admission_choice_np(
            choice, dem_w, free
        )
        for j, p in enumerate(on_donor):
            if admitted[j]:
                assignment[p] = choice[j]
                free[choice[j]] -= demand[p]
                free[d] += demand[p]
                moves += 1
        if not admitted.any():
            frozen[d] = True
        theta *= decay
        rounds += 1
    emptied = int((occupied0 & ~occupied_of(free)).sum())
    return assignment, free, {
        "rounds": rounds, "moves": moves, "emptied": emptied,
    }


__all__ = [
    "PACK_AUX_SLOTS",
    "pack_aux_vector",
    "packing_refine",
    "packing_refine_np",
]
