"""ElasticQuota admission math.

Reference PreFilter rejects (/root/reference/pkg/capacityscheduling/
capacity_scheduling.go:208-282, comparators elasticquota.go:96-221):

1. `usedOverMaxWith`: own-namespace used + request exceeds Max in any
   resource (absent Max entries are unbounded — the snapshot builder encodes
   them as int64 max).
2. `aggregatedUsedOverMinWith`: sum of used over ALL ElasticQuotas + request
   exceeds the sum of Min in any resource (the cluster's guaranteed pool is
   exhausted; absent Min entries are 0).

The nominated-pod aggregates (lines 228-263) — the preemption-nomination
feedback loop — enter through the optional `nominated_in_eq` /
`nominated_total` vectors the snapshot builder precomputes per pending pod.
"""

from __future__ import annotations

import jax.numpy as jnp


def quota_admit(eq_used, eq_min, eq_max, has_quota, ns, req,
                nominated_in_eq=None, nominated_total=None):
    """Scalar admission verdict for one pod.

    eq_used/eq_min/eq_max: (Q, R); has_quota: (Q,); ns: scalar namespace code;
    req: (R,) pod effective request; nominated_in_eq/nominated_total: optional
    (R,) nominated-pod aggregates for this pod. Pods in namespaces without an
    EQ pass (capacity_scheduling.go:218-224).
    """
    in_eq = req if nominated_in_eq is None else req + nominated_in_eq
    total = req if nominated_total is None else req + nominated_total
    used_ns = eq_used[ns]
    over_max = jnp.any(used_ns + in_eq > eq_max[ns])
    agg_used = jnp.sum(jnp.where(has_quota[:, None], eq_used, 0), axis=0)
    agg_min = jnp.sum(jnp.where(has_quota[:, None], eq_min, 0), axis=0)
    over_min = jnp.any(agg_used + total > agg_min)
    return jnp.where(has_quota[ns], ~(over_max | over_min), True)


def quota_commit(eq_used, has_quota, ns, req, placed):
    """Reserve: add `req` to the namespace's usage when the pod placed
    (capacity_scheduling.go:350-368)."""
    add = jnp.where(placed & has_quota[ns], req, 0)
    return eq_used.at[ns].add(add)


def nominee_contribution(same_namespace: bool, nominee_priority: int,
                         pod_priority: int, nominee_eq_over_min: bool):
    """The single source of truth for which aggregates a nominated pod's
    request joins, for a given pending pod (capacity_scheduling.go:247-257):
    returns (counts_in_eq, counts_in_total)."""
    if same_namespace and nominee_priority >= pod_priority:
        return True, True
    if not same_namespace and not nominee_eq_over_min:
        return False, True
    return False, False
