"""NUMA-aware fitting and scoring kernels (NodeResourceTopology).

Reference: /root/reference/pkg/noderesourcetopology — the largest component
(SURVEY.md §2.6). The per-node × per-container × per-resource × per-zone Go
loops become fixed-shape boolean algebra over the (Z, R) zone tensors; all
functions here operate on ONE node's zone block and are `jax.vmap`-ed over
nodes by the plugin.

Semantics mapped bit-for-bit:
- `feasible_zones`      resourcesAvailableInAnyNUMANodes (filter.go:90-160):
  per-resource zone bitmask AND, early reject on node-level absence, QoS
  gating (isResourceSetSuitable, numaresources.go:137-142), host-level
  resource bypass (numaresources.go:105-121).
- `single_numa_fit`     container-scope handler (filter.go:39-78): init
  containers checked without subtraction (they run serially), app containers
  subtract their grant from the chosen (lowest-id) zone.
- strategy scores       least/most/balanced per zone over the requested
  resources (least_allocated.go, most_allocated.go, balanced_allocation.go);
  node score = zero-skipping min over zones (score.go:110-124); container
  scope = float mean over containers (score.go:152-165).
- `least_numa_*`        minimal-k zone-combination search with average
  inter-zone distance preference (least_numa.go:40-258).
"""

from __future__ import annotations

import itertools
from functools import lru_cache

import jax
import jax.numpy as jnp
import numpy as np

from scheduler_plugins_tpu.api.resources import (
    CPU,
    EPHEMERAL_STORAGE,
    MEMORY,
    ResourceIndex,
)
from scheduler_plugins_tpu.utils.intmath import floordiv_exact, floordiv_recip

MAX_NODE_SCORE = 100
MAX_DISTANCE = 255.0  # least_numa.go:32


# ---------------------------------------------------------------------------
# static (host-side) resource classification — numaresources.go:105-135
# ---------------------------------------------------------------------------


def numa_affine_mask(index: ResourceIndex) -> np.ndarray:
    """cpu, memory and hugepages must expose NUMA affinity."""
    out = np.zeros(len(index), bool)
    for i, name in enumerate(index.names):
        out[i] = name in (CPU, MEMORY) or name.startswith("hugepages-")
    return out


def host_level_mask(index: ResourceIndex) -> np.ndarray:
    """ephemeral-storage, storage and non-native (extended) resources may
    legitimately lack NUMA affinity."""
    out = np.zeros(len(index), bool)
    for i, name in enumerate(index.names):
        out[i] = (
            name in (EPHEMERAL_STORAGE, "storage")
            or "/" in name  # extended resources are namespaced
        )
    return out


def live_avail_init(numa):
    """Initial live zone availability for the solver carry: scaled float32
    when the snapshot's pack guard holds (values * 100 exact in f32,
    placements scale-invariant), else float64 (exact < 2^53). Quantities and
    requests must go through `scale_qty` with the same scales."""
    if numa.pack_scales is not None:
        s = jnp.asarray(numa.pack_scales, jnp.int64)
        return (numa.available // s[None, None, :]).astype(jnp.float32)
    return numa.available.astype(jnp.float64)


def scale_qty(numa, vec):
    """Request vector in the solver's NUMA quantity domain (see
    `live_avail_init`); broadcasting over the trailing resource axis."""
    if numa.pack_scales is None:
        return vec
    s = jnp.asarray(numa.pack_scales, vec.dtype)
    return (vec // s).astype(jnp.float32)


@lru_cache(maxsize=16)
def subset_masks(Z: int):
    """All non-empty zone subsets ordered by (size, lexicographic) — the
    enumeration order of combin.Combinations ascending bitmaskLen
    (least_numa.go:160-174). Returns (masks (S, Z) bool, sizes (S,) int32)."""
    masks, sizes = [], []
    for k in range(1, Z + 1):
        for combo in itertools.combinations(range(Z), k):
            row = np.zeros(Z, bool)
            row[list(combo)] = True
            masks.append(row)
            sizes.append(k)
    return np.array(masks), np.array(sizes, np.int32)


# ---------------------------------------------------------------------------
# filter
# ---------------------------------------------------------------------------


def feasible_zones_from_suitable(suitable_qty, reported, zone_mask,
                                 node_alloc, guaranteed, req, affine,
                                 host_level):
    """`feasible_zones` with the quantity check precomputed: `suitable_qty`
    is (Z, R) `live_avail >= req` — callers in the sequential scan compute it
    as one fused `avail0 >= req + deduct` compare over all nodes instead of
    materializing the live availability tensor per step."""
    relevant = req > 0  # (R,) — zero-qty requests are ignored (filter.go:100-104)
    present = node_alloc > 0
    early_reject = jnp.any(relevant & ~present)

    reported_z = reported & zone_mask[:, None]  # (Z, R)
    suitable = (~guaranteed & affine[None, :]) | suitable_qty
    per_resource = reported_z & suitable  # (Z, R)
    has_affinity = jnp.any(reported_z, axis=0)  # (R,)
    # resource constrains the bitmask unless it's irrelevant, or unreported
    # but host-level
    constrain = relevant & ~(~has_affinity & host_level)
    feasible = jnp.all(
        jnp.where(constrain[None, :], per_resource, True), axis=1
    ) & zone_mask
    ok = ~early_reject & feasible.any()
    return feasible, ok


def feasible_zones(avail, reported, zone_mask, node_alloc, guaranteed, req,
                   affine, host_level):
    """(Z,) feasible-zone mask + scalar ok for one request on one node.

    Mirrors resourcesAvailableInAnyNUMANodes: zero-qty resources ignored;
    node-level absence is an early reject; a resource reported by no zone
    passes only if host-level; non-guaranteed pods skip the quantity check
    for NUMA-affine resources.
    """
    return feasible_zones_from_suitable(
        avail >= req[None, :], reported, zone_mask, node_alloc, guaranteed,
        req, affine, host_level,
    )


def batch_request_fit(avail, reported, zone_mask, node_alloc, guaranteed,
                      reqs, affine, host_level):
    """(P, N) single-request feasibility — the whole-batch form of
    `feasible_zones(...)[1]`: one fused (P, N, Z, R) compare + boolean
    reduction instead of a per-pod vmap of per-node kernels, with every
    pod-invariant tensor (reported zones, zone masks, host-level masks,
    node-presence bits) hoisted out of the pod axis. Bit-identical to
    vmapping `feasible_zones` over nodes then pods.

    avail: (N, Z, R) float live availability; reqs: (P, R) requests in the
    same quantity domain; guaranteed: (P,) bool QoS bits.
    """
    relevant = reqs > 0  # (P, R)
    present = node_alloc > 0  # (N, R)
    early_reject = jnp.any(
        relevant[:, None, :] & ~present[None, :, :], axis=2
    )  # (P, N)
    reported_z = reported & zone_mask[:, :, None]  # (N, Z, R)
    has_affinity = jnp.any(reported_z, axis=1)  # (N, R)
    suitable = (
        (~guaranteed[:, None] & affine[None, :])[:, None, None, :]
        | (avail[None] >= reqs[:, None, None, :])
    )  # (P, N, Z, R)
    per_resource = reported_z[None] & suitable
    constrain = relevant[:, None, :] & ~(
        ~has_affinity[None] & host_level[None, None, :]
    )  # (P, N, R)
    feasible = jnp.all(
        jnp.where(constrain[:, :, None, :], per_resource, True), axis=3
    ) & zone_mask[None]  # (P, N, Z)
    return ~early_reject & feasible.any(axis=2)


def single_numa_fit(avail, reported, zone_mask, node_alloc, guaranteed,
                    creq, is_init, cmask, affine, host_level):
    """Container-scope single-numa-node Filter verdict for one node.

    creq: (C, R) per-container requests (init containers first); app
    containers subtract their grant from the chosen zone before the next
    container (filter.go:39-78).
    """
    C = creq.shape[0]
    Z = avail.shape[0]
    ok = jnp.bool_(True)
    for c in range(C):
        feasible, ok_c = feasible_zones(
            avail, reported, zone_mask, node_alloc, guaranteed, creq[c],
            affine, host_level,
        )
        applies = cmask[c]
        ok &= ~applies | ok_c
        # chosen zone: lowest feasible NUMA id (filter.go:152-157)
        zone = jnp.argmax(feasible)
        subtract = applies & ok_c & ~is_init[c]
        grant = jnp.where(
            subtract & (jnp.arange(Z) == zone)[:, None] & reported,
            creq[c][None, :],
            0,
        )
        avail = avail - grant
    return ok


# ---------------------------------------------------------------------------
# strategy scores (LeastAllocated / MostAllocated / BalancedAllocation)
# ---------------------------------------------------------------------------

LEAST_ALLOCATED = "LeastAllocated"
MOST_ALLOCATED = "MostAllocated"
BALANCED_ALLOCATION = "BalancedAllocation"
LEAST_NUMA_NODES = "LeastNUMANodes"


def _weighted_zone_score(per_resource_f, relevant, weights,
                         out_dtype=jnp.int64):
    """sum_r score_r * w_r / sum_r w_r over the requested resources, in the
    caller's float dtype (callers guarantee exactness: per-resource scores
    are <= 100, so the weighted sum stays < 2^24 for f32 / 2^53 for f64).
    The quotient is <= MAX_NODE_SCORE, so `out_dtype=jnp.int32` is always
    exact — the batched score path demotes (the `demote_scores_int32`
    pattern) to halve the (P, N, Z) traffic."""
    w = jnp.where(relevant, weights, 0).astype(per_resource_f.dtype)
    wsum = jnp.maximum(jnp.sum(w), 1.0)
    return floordiv_exact(
        jnp.sum(per_resource_f * w, axis=-1), wsum
    ).astype(out_dtype)


def precompute_zone_scales(avail):
    """Pod-invariant zone scale tensors for the Least/Most strategies:
    (capf, safe_cap, recip) in `avail`'s float dtype. The reciprocal is the
    precomputed-scale half of `floordiv_recip`; hoisting it to one per-solve
    computation (instead of per pod under the batched vmap) is what turns
    the per-element integer-division inner loop into multiplies."""
    dt = (
        avail.dtype
        if jnp.issubdtype(avail.dtype, jnp.floating)
        else jnp.float64
    )
    capf = avail.astype(dt)
    safe_cap = jnp.maximum(capf, 1)
    return capf, safe_cap, 1.0 / safe_cap


def zone_strategy_scores(strategy, req, avail, zone_mask, relevant, weights,
                         scales=None, out_dtype=jnp.int64):
    """(Z,) per-zone scores for one request on one node.

    The integer divisions of least_allocated.go:45-55 / most_allocated.go are
    computed as exact-floor float divisions in `avail`'s dtype — f32 when the
    snapshot packs (values * 100 < 2^24), else f64 (< 2^53): this sits in
    the per-pod scan's hot path, where per-element integer division is the
    dominant cost on both backends. BalancedAllocation keeps its ratio math
    in f64: the reference computes it in Go float64, and f64 division of the
    (scale-invariant) rational reproduces its rounding bit-for-bit.

    `scales`: optional precomputed `precompute_zone_scales(avail)` triple —
    callers scoring a whole batch against one availability tensor hoist it
    out of their pod loop/vmap. `out_dtype` demotes the (always <= 100)
    zone scores where the caller wants int32 tensors.
    """
    cap = avail  # zone "allocatable" = published available (pluginhelpers.go)
    dt = (
        cap.dtype
        if jnp.issubdtype(cap.dtype, jnp.floating)
        else jnp.float64
    )
    if strategy in (LEAST_ALLOCATED, MOST_ALLOCATED):
        if scales is None:
            scales = precompute_zone_scales(cap)
        capf, safe_cap, recip = scales
        reqf = req[None, :].astype(dt)
        numer = (capf - reqf) if strategy == LEAST_ALLOCATED else reqf
        # reciprocal-multiply floor division with the precomputed scale:
        # `capf` is pod-invariant, so the reciprocal is computed once per
        # solve while the division would run per (pod, node, zone,
        # resource) — the dominant op of the NUMA score pass on both
        # backends
        per = jnp.where(
            (capf == 0) | (reqf > capf),
            0.0,
            floordiv_recip(
                numer * float(MAX_NODE_SCORE), safe_cap, recip
            ),
        )
        scores = _weighted_zone_score(per, relevant, weights, out_dtype)
    elif strategy == BALANCED_ALLOCATION:
        cap = cap.astype(jnp.float64)
        # fractionOfCapacity (balanced_allocation.go:50-55): req/capacity
        # unclamped — a NEGATIVE live capacity (pessimistic in-cycle
        # deduction) yields a negative fraction that feeds the variance, it
        # is NOT the over case. Unclamped division is also scale-invariant,
        # so the packed-f32 domain reproduces it bit-for-bit after upcast.
        fraction = jnp.where(
            cap == 0,
            1.0,
            req[None, :].astype(jnp.float64) / jnp.where(cap == 0, 1.0, cap),
        )
        over = jnp.any(relevant[None, :] & (fraction > 1.0), axis=1)
        n = jnp.maximum(jnp.sum(relevant), 1)
        mean = jnp.sum(jnp.where(relevant[None, :], fraction, 0.0), axis=1) / n
        sq = jnp.sum(
            jnp.where(relevant[None, :], (fraction - mean[:, None]) ** 2, 0.0),
            axis=1,
        )
        # gonum stat.Variance is the unbiased sample variance (N-1 divisor)
        variance = jnp.where(n > 1, sq / jnp.maximum(n - 1, 1), 0.0)
        scores = jnp.where(
            over, 0, jnp.trunc((1.0 - variance) * MAX_NODE_SCORE).astype(out_dtype)
        )
    else:  # pragma: no cover
        raise ValueError(f"illegal scoring strategy {strategy}")
    return jnp.where(zone_mask, scores, 0)


def min_over_zones(scores, zone_mask):
    """Zero-skipping min (score.go:110-124): zones scoring 0 are ignored by
    the kubelet, so 0 only results when every zone scored 0. The sentinel is
    dtype-aware so the int32-demoted batched path stays int32 end to end."""
    nonzero = zone_mask & (scores != 0)
    sentinel = scores.dtype.type(jnp.iinfo(scores.dtype).max // 2)
    min_nonzero = jnp.min(jnp.where(nonzero, scores, sentinel))
    return jnp.where(nonzero.any(), min_nonzero, 0)


def batch_strategy_node_scores(strategy, reqs, avail, zone_mask, weights,
                               scales=None):
    """(P, N) zero-skip-min node scores for a batch of single (R,) requests
    — the whole-batch form of `zone_strategy_scores` + `min_over_zones`:
    the pod-invariant zone scales are hoisted and computed ONCE per solve
    (not per pod under the vmap), and the zone-score arithmetic runs
    int32-demoted (always exact — weighted zone scores are <=
    MAX_NODE_SCORE). Values are identical to the per-pod path; only the
    output dtype narrows."""
    if strategy in (LEAST_ALLOCATED, MOST_ALLOCATED):
        if scales is None:
            scales = precompute_zone_scales(avail)

        def per_pod(r):
            relevant = r > 0

            def node(avail_n, zmask_n, scales_n):
                zs = zone_strategy_scores(
                    strategy, r, avail_n, zmask_n, relevant, weights,
                    scales=scales_n, out_dtype=jnp.int32,
                )
                return min_over_zones(zs, zmask_n)

            return jax.vmap(node)(avail, zone_mask, scales)
    else:

        def per_pod(r):
            relevant = r > 0

            def node(avail_n, zmask_n):
                zs = zone_strategy_scores(
                    strategy, r, avail_n, zmask_n, relevant, weights,
                    out_dtype=jnp.int32,
                )
                return min_over_zones(zs, zmask_n)

            return jax.vmap(node)(avail, zone_mask)

    return jax.vmap(per_pod)(reqs)


# ---------------------------------------------------------------------------
# LeastNUMANodes
# ---------------------------------------------------------------------------


def _subset_distances(distances, masks, sizes):
    """(S,) average pairwise distance per subset (nodesAvgDistance,
    least_numa.go:117-139): sum of costs over the full subset product divided
    by |subset|^2. Missing costs were defaulted at snapshot build."""
    m = masks.astype(jnp.float64)  # (S, Z)
    pair_sums = jnp.einsum("sz,zy,sy->s", m, distances.astype(jnp.float64), m)
    return pair_sums / jnp.maximum(sizes.astype(jnp.float64) ** 2, 1.0)


def least_numa_required(avail, reported, zone_mask, distances, guaranteed,
                        req, affine, masks, sizes):
    """(count, is_min_avg_distance, ok, chosen_mask (Z,)) for one request.

    numaNodesRequired (least_numa.go:158-258): smallest k such that a k-zone
    combination fits; within that k, a combination achieving the minimal
    average distance over ALL k-subsets wins the bonus; otherwise the fitting
    combination with the smallest distance is chosen.
    """
    S, Z = masks.shape
    relevant = req > 0

    # validity: every zone of the subset must report every requested resource
    # (isValidCombineResources) and contain only real zones
    zone_reports_all = jnp.all(
        jnp.where(relevant[None, :], reported, True), axis=1
    )  # (Z,)
    valid = jnp.all(~masks | (zone_reports_all & zone_mask)[None, :], axis=1)

    # (S, R) summed availability via float matmul in avail's dtype — exact
    # (packed f32 keeps sums < 2^24; f64 < 2^53); int64 dot_general is
    # unsupported on TPU, and an (S, Z, R) masked-sum temporary would blow up
    # vmem under the per-(pod, node) vmap
    dt = (
        avail.dtype
        if jnp.issubdtype(avail.dtype, jnp.floating)
        else jnp.float64
    )
    avail_reported = jnp.where(reported, avail, 0).astype(dt)
    # HIGHEST precision: default TPU matmul truncates f32 operands to bf16,
    # which would break the pack guard's exactness promise
    combined = jnp.matmul(
        masks.astype(dt), avail_reported, precision=jax.lax.Precision.HIGHEST
    )
    suitable = (~guaranteed & affine[None, :]) | (
        combined >= req[None, :].astype(dt)
    )
    fits = valid & jnp.all(jnp.where(relevant[None, :], suitable, True), axis=1)

    dist = _subset_distances(distances, masks, sizes)  # (S,)
    big = jnp.float64(1e18)

    # per subset-size k: min distance over every same-size subset of REAL
    # zones (the reference enumerates combinations of the node's actual NUMA
    # cells only — padded phantom zones must not win the distance minimum)
    real_subset = jnp.all(~masks | zone_mask[None, :], axis=1)  # (S,)
    ks = sizes
    min_dist_per_k = jnp.min(
        jnp.where(
            (ks[None, :] == ks[:, None]) & real_subset[None, :],
            dist[None, :],
            big,
        ),
        axis=1,
    )  # (S,) min distance among real subsets with the same size

    # smallest fitting k
    kmin = jnp.min(jnp.where(fits, ks, jnp.int32(Z + 1)))
    ok = kmin <= Z
    in_k = fits & (ks == kmin)
    # prefer distance == min over all subsets of size kmin; among those the
    # generation order (lowest index) wins, matching the early return
    is_min = in_k & (dist == min_dist_per_k)
    pick_pool = jnp.where(is_min.any(), is_min, in_k)
    # lowest-distance fitting subset fallback, ties by generation order
    order_penalty = jnp.arange(S, dtype=jnp.float64) * 1e-9
    pick = jnp.argmin(jnp.where(pick_pool, dist + order_penalty, big))
    chosen = masks[pick] & ok
    return (
        jnp.where(ok, kmin, 0).astype(jnp.int32),
        is_min.any(),
        ok,
        chosen,
    )


def least_numa_normalize(count, is_min_distance, max_numa):
    """normalizeScore (least_numa.go:91-102)."""
    per_numa = MAX_NODE_SCORE // jnp.maximum(max_numa, 1)
    score = MAX_NODE_SCORE - count * per_numa
    return jnp.where(is_min_distance, score + per_numa // 2, score)


def only_non_numa(reported, zone_mask, req):
    """onlyNonNUMAResources: every requested resource is unreported by every
    zone (least_numa.go:262-273)."""
    relevant = req > 0
    reported_any = jnp.any(reported & zone_mask[:, None], axis=0)
    return ~jnp.any(relevant & reported_any)
