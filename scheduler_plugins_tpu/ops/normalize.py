"""Score normalization transforms shared across plugins.

Each mirrors a specific reference normalizer bit-for-bit (integer division
truncation included):
- `minmax_normalize`  — NodeResourcesAllocatable.NormalizeScore
  (/root/reference/pkg/noderesources/allocatable.go:143-168)
- `default_normalize` — upstream helper.DefaultNormalizeScore used by SySched
  and PodState (reverse=True flavors)
- `peaks_normalize`   — Peaks.NormalizeScore inversion
  (/root/reference/pkg/trimaran/peaks/peaks.go:152-168)

All operate row-wise on (..., N) score arrays with an (..., N) validity mask
(the mask plays the role of "which nodes made it into the NodeScoreList").
Entries outside the mask are returned as 0.
"""

from __future__ import annotations

import jax.numpy as jnp

from scheduler_plugins_tpu.ops import MAX_NODE_SCORE, MIN_NODE_SCORE
from scheduler_plugins_tpu.utils.intmath import masked_max, masked_min


def minmax_normalize(scores, mask):
    """((score - lowest) * 100 / oldRange) + MinNodeScore; all-MinNodeScore when
    every score is equal (allocatable.go:155-166). Division is exact Go int
    division (operands are non-negative here, so `//` matches)."""
    scores = jnp.asarray(scores)
    lo = masked_min(scores, mask, axis=-1, keepdims=True)
    hi = masked_max(scores, mask, axis=-1, keepdims=True)
    old_range = hi - lo
    new_range = MAX_NODE_SCORE - MIN_NODE_SCORE
    out = jnp.where(
        old_range == 0,
        MIN_NODE_SCORE,
        (scores - lo) * new_range // jnp.maximum(old_range, 1) + MIN_NODE_SCORE,
    )
    return jnp.where(mask, out, 0)


def default_normalize(scores, mask, reverse=False):
    """Upstream helper.DefaultNormalizeScore: scale by max to [0,100]; when the
    max is 0, scores become 0 (or all 100 when reversed)."""
    scores = jnp.asarray(scores)
    max_count = masked_max(scores, mask, axis=-1, keepdims=True)
    max_count = jnp.maximum(max_count, 0)
    scaled = scores * MAX_NODE_SCORE // jnp.maximum(max_count, 1)
    out = jnp.where(max_count == 0, 0, scaled)
    if reverse:
        out = MAX_NODE_SCORE - out
    return jnp.where(mask, out, 0)


def peaks_normalize(scores, mask):
    """Peaks inverted min-max: lowest power-jump wins (peaks.go:152-168).
    The float multiply + int64 truncation of the Go code is preserved."""
    scores = jnp.asarray(scores)
    lo = masked_min(scores, mask, axis=-1, keepdims=True)
    hi = masked_max(scores, mask, axis=-1, keepdims=True)
    all_zero = (lo == 0) & (hi == 0)
    norm = jnp.where(
        hi != lo,
        jnp.trunc(
            MAX_NODE_SCORE * (scores - lo).astype(jnp.float64)
            / jnp.maximum(hi - lo, 1).astype(jnp.float64)
        ),
        (scores - lo).astype(jnp.float64),
    ).astype(jnp.int64)
    out = jnp.where(all_zero, scores, MAX_NODE_SCORE - norm)
    return jnp.where(mask, out, 0)
