"""Pure-JAX scheduling kernels.

Each module recasts one of the reference's per-pod x per-node Go hot loops
(SURVEY.md §3 "hot loops ranked for TPU offload") as batched tensor math:

- fit.py          resource-fit Filter: (P,R) vs (N,R) -> (P,N) bool
- allocatable.py  NodeResourcesAllocatable weighted score + min-max normalize
- normalize.py    shared score-normalization transforms
- trimaran.py     load-aware score curves (TLP / LVRB / LROC / Peaks)
- numa.py         NUMA bitmask fitting + per-zone scoring strategies
- network.py      AppGroup dependency cost/violation accumulation
- gang.py         PodGroup quorum + whole-cluster capacity checks
- quota.py        ElasticQuota min/max aggregate checks
- assign.py       greedy one-pod-at-a-time placement (lax.scan)
"""

from scheduler_plugins_tpu.api.resources import (
    CANONICAL,
    CPU,
    EPHEMERAL_STORAGE,
    MEMORY,
    PODS,
)

# canonical slots on the resource axis, derived from the single source of truth
CPU_I = CANONICAL.index(CPU)
MEMORY_I = CANONICAL.index(MEMORY)
EPHEMERAL_I = CANONICAL.index(EPHEMERAL_STORAGE)
PODS_I = CANONICAL.index(PODS)

MAX_NODE_SCORE = 100
MIN_NODE_SCORE = 0
