"""NetworkOverhead dependency cost/violation accumulation.

Reference: /root/reference/pkg/networkaware/networkoverhead/networkoverhead.go
:500-638. For each already-placed pod of each dependency workload, the cost
between the candidate node and the placed pod's location depends only on
(region, zone) codes:

    same node                         -> satisfied, cost += 0  (SameHostname)
    same zone (different node)        -> satisfied (unconditionally), cost += 1
    same region, different zone       -> zone-cost map lookup:
                                         found -> satisfied/violated by
                                         MaxNetworkCost, cost += value;
                                         missing -> no count, cost += MaxCost
    different region                  -> region-cost lookup, same pattern
    placed node has no region+zone    -> violated, cost += MaxCost

The placed-pod counts are carried through the assignment scan as a (W, N)
matrix (`SolverState.net_placed`) so that members placed earlier in the same
cycle are visible to later pods — mirroring the reference's assumed-pod
snapshot updates between one-at-a-time cycles. Zone/region aggregates are
recomputed per pod with segment scatter-adds (cheap: D x ZC).
"""

from __future__ import annotations

import jax.numpy as jnp

MAX_COST = 100  # networkoverhead.go MaxCost

#: all tally contractions are integer-valued f32 counts/costs (< 2^24) and
#: feed HARD filter verdicts: force full-f32 accumulation — TPU default
#: matmul precision multiplies in bf16, which rounds any count >= 257
import jax.lax as _lax  # noqa: E402

_EXACT = _lax.Precision.HIGHEST
SAME_ZONE_COST = 1
SAME_HOST_COST = 0


def dependency_tallies(
    dep_workload,
    dep_max_cost,
    dep_mask,
    placed_node,
    node_zone,
    node_region,
    zone_region,
    zone_cost,
    region_cost,
):
    """Per-node (satisfied, violated, cost) tallies for one pod.

    dep_workload/dep_max_cost/dep_mask: (D,) dependency rows;
    placed_node: (W, N) live placed-pod counts; node_zone/node_region: (N,)
    codes (-1 unset); zone_region: (ZC,) region of each zone; zone_cost /
    region_cost: dense matrices with -1 for missing pairs.
    Returns three (N,) int64 arrays.
    """
    N = node_zone.shape[0]
    ZC = zone_cost.shape[0]
    RC = region_cost.shape[0]
    zone_cost = jnp.asarray(zone_cost).astype(jnp.int32)
    region_cost = jnp.asarray(region_cost).astype(jnp.int32)
    dep_max_cost = jnp.asarray(dep_max_cost).astype(jnp.int32)
    w = jnp.maximum(dep_workload, 0)
    # int32 internals: every tally is bounded by MAX_COST * total placed
    # pods, far inside int32; int64 doubles the memory traffic of the
    # (D, N, ZC) broadcasts on the CPU backend and is MXU-hostile on TPU
    placed = jnp.where(
        dep_mask[:, None], placed_node[w], 0
    ).astype(jnp.int32)  # (D, N)

    # aggregate placed pods by location class. One-hot matmuls, not
    # scatter-adds: XLA lowers scatter serially on CPU (the former
    # per-class `.at[:, zone].add` dominated the whole cfg5 batch pass)
    # while a (D, N) x (N, ZC) dot is a single dense contraction that
    # also rides the MXU on TPU. f32 is exact here (counts < 2^24).
    zoned = node_zone >= 0
    rnoz = (node_zone < 0) & (node_region >= 0)
    unloc = (node_zone < 0) & (node_region < 0)
    zone_onehot = (
        zoned[:, None] & (node_zone[:, None] == jnp.arange(ZC)[None, :])
    ).astype(jnp.float32)  # (N, ZC)
    rnoz_onehot = (
        rnoz[:, None] & (node_region[:, None] == jnp.arange(RC)[None, :])
    ).astype(jnp.float32)  # (N, RC)
    placed_f = placed.astype(jnp.float32)
    placed_zone = jnp.dot(
        placed_f, zone_onehot, precision=_EXACT
    ).astype(jnp.int32)  # (D, ZC)
    placed_rnoz = jnp.dot(
        placed_f, rnoz_onehot, precision=_EXACT
    ).astype(jnp.int32)  # (D, RC)
    placed_unloc = jnp.sum(jnp.where(unloc[None, :], placed, 0), axis=1)  # (D,)

    nz = jnp.maximum(node_zone, 0)
    nr = jnp.maximum(node_region, 0)
    same_zone = node_zone[:, None] == jnp.arange(ZC)[None, :]  # (N, ZC)
    same_region = node_region[:, None] == zone_region[None, :]  # (N, ZC)

    # a candidate without a zone/region label looks up with key "" in the
    # reference (networkoverhead.go:544-566) — always a miss, never row 0
    zcost_row = jnp.where((node_zone >= 0)[:, None], zone_cost[nz], -1)  # (N, ZC)
    rcost_zone = region_cost[nr][:, jnp.maximum(zone_region, 0)]  # (N, ZC)
    rcost_zone = jnp.where(
        (node_region >= 0)[:, None] & (zone_region[None, :] >= 0),
        rcost_zone,
        -1,
    )

    pair_cost = jnp.where(
        same_zone,
        SAME_ZONE_COST,
        jnp.where(
            same_region,
            jnp.where(zcost_row >= 0, zcost_row, MAX_COST),
            jnp.where(rcost_zone >= 0, rcost_zone, MAX_COST),
        ),
    )  # (N, ZC)
    pair_known = jnp.where(same_region, zcost_row >= 0, rcost_zone >= 0)
    pair_lookup = jnp.where(same_region, zcost_row, rcost_zone)

    # same-node pods are handled separately: remove them from their zone
    same_node_cnt = placed  # (D, N)
    zone_cnt = placed_zone[:, None, :] - jnp.where(
        same_zone[None, :, :], same_node_cnt[:, :, None], 0
    )
    zone_cnt = jnp.maximum(zone_cnt, 0)  # (D, N, ZC)

    # same-zone pods are unconditionally satisfied (networkoverhead.go:542-545)
    sat_pair = same_zone[None, :, :] | (
        pair_known[None, :, :] & (pair_lookup[None, :, :] <= dep_max_cost[:, None, None])
    )
    vio_pair = ~same_zone[None, :, :] & pair_known[None, :, :] & ~sat_pair

    satisfied = jnp.sum(jnp.where(sat_pair, zone_cnt, 0), axis=(0, 2))
    violated = jnp.sum(jnp.where(vio_pair, zone_cnt, 0), axis=(0, 2))
    cost = jnp.sum(zone_cnt * pair_cost[None, :, :], axis=(0, 2))

    # same-node pods: satisfied, SameHostname cost (networkoverhead.go:521-525)
    satisfied = satisfied + jnp.sum(same_node_cnt, axis=0)
    cost = cost + SAME_HOST_COST * jnp.sum(same_node_cnt, axis=0)

    # region-only placed pods. Same region: a ZONED candidate's zone lookup
    # misses (destination "" -> cost MaxCost, no count) but a ZONELESS
    # candidate compares "" == "" as the SAME zone -> satisfied, cost 1
    # (networkoverhead.go:541-545). Across regions: region-cost lookup,
    # missing for label-less candidates.
    same_r = node_region[:, None] == jnp.arange(RC)[None, :]  # (N, RC)
    rcost = jnp.where((node_region >= 0)[:, None], region_cost[nr], -1)  # (N, RC)
    both_zoneless = (node_zone < 0)[:, None] & same_r  # (N, RC)
    rn_cost = jnp.where(
        both_zoneless,
        SAME_ZONE_COST,
        jnp.where(same_r, MAX_COST, jnp.where(rcost >= 0, rcost, MAX_COST)),
    )
    rn_known = ~same_r & (rcost >= 0)
    rn_sat = both_zoneless[None, :, :] | (
        rn_known[None, :, :]
        & (
            jnp.where(rcost >= 0, rcost, MAX_COST)[None, :, :]
            <= dep_max_cost[:, None, None]
        )
    )
    rn_vio = rn_known[None, :, :] & ~rn_sat
    node_rnoz = rnoz  # (N,)
    rnoz_cnt = placed_rnoz[:, None, :] - jnp.where(
        (node_rnoz[:, None] & same_r)[None, :, :], same_node_cnt[:, :, None], 0
    )
    rnoz_cnt = jnp.maximum(rnoz_cnt, 0)
    satisfied = satisfied + jnp.sum(jnp.where(rn_sat, rnoz_cnt, 0), axis=(0, 2))
    violated = violated + jnp.sum(jnp.where(rn_vio, rnoz_cnt, 0), axis=(0, 2))
    cost = cost + jnp.sum(rnoz_cnt * rn_cost[None, :, :], axis=(0, 2))

    # unlocated placed pods: violated, MaxCost each
    unloc_cnt = jnp.maximum(
        placed_unloc[:, None] - jnp.where(unloc[None, :], same_node_cnt, 0), 0
    )  # (D, N)
    violated = violated + jnp.sum(unloc_cnt, axis=0)
    cost = cost + MAX_COST * jnp.sum(unloc_cnt, axis=0)

    return (
        satisfied.astype(jnp.int64),
        violated.astype(jnp.int64),
        cost.astype(jnp.int64),
    )


def class_dependency_tallies(
    cls_dep_workload,
    cls_dep_max_cost,
    cls_dep_mask,
    placed_node,
    node_zone,
    node_region,
    zone_region,
    zone_cost,
    region_cost,
):
    """(W, N) satisfied/violated/cost tallies for every workload class at
    once — the matmul formulation of `dependency_tallies`.

    Bit-identical to vmapping `dependency_tallies` over the class rows
    (test-gated), but restructured around the tallies' LINEARITY in the
    placed-pod counts: for a fixed candidate node n, every pair
    contribution is `weight(n, zone) * count(dep, zone)`, so the zone sums
    collapse into (W, ZC) x (ZC, N) matmuls against class-independent
    (N, ZC) weight tables, plus one (W, N, ZC) threshold pass per
    dependency slot (MaxNetworkCost compares are the only per-dep
    weights). The naive path materializes a dozen (W, D, N, ZC) broadcast
    tensors; this one touches (W, N, ZC) D times and (N, ZC) once —
    the difference between ~50ms and ~5ms per batch solve on the CPU
    backend, and MXU-shaped work instead of elementwise sprawl on TPU.

    f32 contractions are exact: every accumulated value is an integer
    bounded by MAX_COST * total placed pods (< 2^24 for any cluster this
    path sees; the chunked north-star feeds < 2^24 too).

    Reference semantics: networkoverhead.go:500-638 (same mapping as
    `dependency_tallies`, which remains the per-pod/parity formulation).
    """
    N = node_zone.shape[0]
    ZC = zone_cost.shape[0]
    RC = region_cost.shape[0]
    W, D = cls_dep_workload.shape
    zone_cost = jnp.asarray(zone_cost).astype(jnp.int32)
    region_cost = jnp.asarray(region_cost).astype(jnp.int32)
    mc = jnp.asarray(cls_dep_max_cost).astype(jnp.int32)  # (W, D)

    w = jnp.maximum(cls_dep_workload, 0)  # (W, D)
    placed = jnp.where(
        cls_dep_mask[:, :, None], placed_node[w], 0
    ).astype(jnp.float32)  # (W, D, N)
    placed_sum = jnp.sum(placed, axis=1)  # (W, N) f32

    zoned = node_zone >= 0
    rnoz = (node_zone < 0) & (node_region >= 0)
    unloc = (node_zone < 0) & (node_region < 0)
    nz = jnp.maximum(node_zone, 0)
    nr = jnp.maximum(node_region, 0)

    # location-class aggregates, one-hot matmuls (MXU-friendly)
    zone_onehot = (
        zoned[:, None] & (node_zone[:, None] == jnp.arange(ZC)[None, :])
    ).astype(jnp.float32)  # (N, ZC)
    rnoz_onehot = (
        rnoz[:, None] & (node_region[:, None] == jnp.arange(RC)[None, :])
    ).astype(jnp.float32)  # (N, RC)
    placed_zone = jnp.einsum(
        "wdn,nz->wdz", placed, zone_onehot, precision=_EXACT
    )  # (W, D, ZC)
    placed_rnoz = jnp.einsum(
        "wdn,nr->wdr", placed, rnoz_onehot, precision=_EXACT
    )  # (W, D, RC)
    placed_unloc = jnp.dot(
        placed, unloc.astype(jnp.float32), precision=_EXACT
    )  # (W, D)
    PZ = jnp.sum(placed_zone, axis=1)  # (W, ZC)
    PR = jnp.sum(placed_rnoz, axis=1)  # (W, RC)
    PU = jnp.sum(placed_unloc, axis=1)  # (W,)

    # class-independent (N, ZC) pair tables — identical to
    # dependency_tallies' definitions (incl. the ""-label corner cases)
    same_zone = node_zone[:, None] == jnp.arange(ZC)[None, :]
    same_region = node_region[:, None] == zone_region[None, :]
    zcost_row = jnp.where(zoned[:, None], zone_cost[nz], -1)
    rcost_zone = region_cost[nr][:, jnp.maximum(zone_region, 0)]
    rcost_zone = jnp.where(
        (node_region >= 0)[:, None] & (zone_region[None, :] >= 0),
        rcost_zone,
        -1,
    )
    pair_cost = jnp.where(
        same_zone,
        SAME_ZONE_COST,
        jnp.where(
            same_region,
            jnp.where(zcost_row >= 0, zcost_row, MAX_COST),
            jnp.where(rcost_zone >= 0, rcost_zone, MAX_COST),
        ),
    )
    pair_known = jnp.where(same_region, zcost_row >= 0, rcost_zone >= 0)
    pair_lookup = jnp.where(same_region, zcost_row, rcost_zone)
    kz = pair_known & ~same_zone  # (N, ZC)
    kz_f = kz.astype(jnp.float32)

    # zoned placed pods ------------------------------------------------
    # same-zone term: sum_z sz * (placed_zone - own) collapses to a gather
    # at the candidate's own zone minus its own-node contribution
    t_sz = jnp.where(
        zoned[None, :], PZ[:, nz] - placed_sum, 0.0
    )  # (W, N)
    # threshold term: sum_d sum_z kz * [lookup <= mc_d] * placed_zone_d —
    # the only per-dependency weight; one (W, N, ZC) pass per dep slot
    term_B = jnp.zeros((W, N), jnp.float32)
    for d in range(D):
        le = (
            pair_lookup[None, :, :] <= mc[:, d, None, None]
        )  # (W, N, ZC)
        term_B = term_B + jnp.sum(
            jnp.where(le, kz_f[None, :, :], 0.0)
            * placed_zone[:, d, None, :],
            axis=2,
        )
    KT = jnp.dot(PZ, kz_f.T, precision=_EXACT)  # (W, N): known-non-same-zone
    cost_z = jnp.dot(
        PZ, pair_cost.astype(jnp.float32).T, precision=_EXACT
    ) - jnp.where(
        zoned[None, :], placed_sum * SAME_ZONE_COST, 0.0
    )

    # region-only placed pods ------------------------------------------
    same_r = node_region[:, None] == jnp.arange(RC)[None, :]
    rcost = jnp.where((node_region >= 0)[:, None], region_cost[nr], -1)
    both_zoneless = (node_zone < 0)[:, None] & same_r
    rn_cost = jnp.where(
        both_zoneless,
        SAME_ZONE_COST,
        jnp.where(same_r, MAX_COST, jnp.where(rcost >= 0, rcost, MAX_COST)),
    )
    rn_known = ~same_r & (rcost >= 0)
    rn_known_f = rn_known.astype(jnp.float32)
    rcost_eff = jnp.where(rcost >= 0, rcost, MAX_COST)  # (N, RC)
    t_bz = jnp.where(
        rnoz[None, :], PR[:, nr] - placed_sum, 0.0
    )
    term_Br = jnp.zeros((W, N), jnp.float32)
    for d in range(D):
        le = rcost_eff[None, :, :] <= mc[:, d, None, None]  # (W, N, RC)
        term_Br = term_Br + jnp.sum(
            jnp.where(le, rn_known_f[None, :, :], 0.0)
            * placed_rnoz[:, d, None, :],
            axis=2,
        )
    KTr = jnp.dot(PR, rn_known_f.T, precision=_EXACT)
    cost_r = jnp.dot(
        PR, rn_cost.astype(jnp.float32).T, precision=_EXACT
    ) - jnp.where(
        rnoz[None, :], placed_sum * SAME_ZONE_COST, 0.0
    )

    # unlocated placed pods --------------------------------------------
    vu = PU[:, None] - jnp.where(unloc[None, :], placed_sum, 0.0)  # (W, N)

    satisfied = t_sz + term_B + placed_sum + t_bz + term_Br
    violated = (KT - term_B) + (KTr - term_Br) + vu
    cost = cost_z + cost_r + MAX_COST * vu
    # int32 rows (values <= MAX_COST * placed pods): downstream (P, N)
    # gathers and normalize min/max passes run at half the int64 traffic
    return (
        satisfied.astype(jnp.int32),
        violated.astype(jnp.int32),
        cost.astype(jnp.int32),
    )


def placed_commit(net_placed, workload, choice):
    """Reserve: record an in-cycle placement of `workload` on `choice`."""
    w = jnp.maximum(workload, 0)
    n = jnp.maximum(choice, 0)
    add = ((workload >= 0) & (choice >= 0)).astype(net_placed.dtype)
    return net_placed.at[w, n].add(add)
