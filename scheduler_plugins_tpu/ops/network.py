"""NetworkOverhead dependency cost/violation accumulation.

Reference: /root/reference/pkg/networkaware/networkoverhead/networkoverhead.go
:500-638. For each already-placed pod of each dependency workload, the cost
between the candidate node and the placed pod's location depends only on
(region, zone) codes:

    same node                         -> satisfied, cost += 0  (SameHostname)
    same zone (different node)        -> satisfied (unconditionally), cost += 1
    same region, different zone       -> zone-cost map lookup:
                                         found -> satisfied/violated by
                                         MaxNetworkCost, cost += value;
                                         missing -> no count, cost += MaxCost
    different region                  -> region-cost lookup, same pattern
    placed node has no region+zone    -> violated, cost += MaxCost

The placed-pod counts are carried through the assignment scan as a (W, N)
matrix (`SolverState.net_placed`) so that members placed earlier in the same
cycle are visible to later pods — mirroring the reference's assumed-pod
snapshot updates between one-at-a-time cycles. Zone/region aggregates are
recomputed per pod with segment scatter-adds (cheap: D x ZC).
"""

from __future__ import annotations

import jax.numpy as jnp

MAX_COST = 100  # networkoverhead.go MaxCost
SAME_ZONE_COST = 1
SAME_HOST_COST = 0


def dependency_tallies(
    dep_workload,
    dep_max_cost,
    dep_mask,
    placed_node,
    node_zone,
    node_region,
    zone_region,
    zone_cost,
    region_cost,
):
    """Per-node (satisfied, violated, cost) tallies for one pod.

    dep_workload/dep_max_cost/dep_mask: (D,) dependency rows;
    placed_node: (W, N) live placed-pod counts; node_zone/node_region: (N,)
    codes (-1 unset); zone_region: (ZC,) region of each zone; zone_cost /
    region_cost: dense matrices with -1 for missing pairs.
    Returns three (N,) int64 arrays.
    """
    N = node_zone.shape[0]
    ZC = zone_cost.shape[0]
    RC = region_cost.shape[0]
    w = jnp.maximum(dep_workload, 0)
    placed = jnp.where(dep_mask[:, None], placed_node[w], 0)  # (D, N)

    # aggregate placed pods by location class
    zoned = node_zone >= 0
    rnoz = (node_zone < 0) & (node_region >= 0)
    unloc = (node_zone < 0) & (node_region < 0)
    D = placed.shape[0]
    placed_zone = jnp.zeros((D, ZC), placed.dtype).at[
        :, jnp.maximum(node_zone, 0)
    ].add(jnp.where(zoned[None, :], placed, 0))
    placed_rnoz = jnp.zeros((D, RC), placed.dtype).at[
        :, jnp.maximum(node_region, 0)
    ].add(jnp.where(rnoz[None, :], placed, 0))
    placed_unloc = jnp.sum(jnp.where(unloc[None, :], placed, 0), axis=1)  # (D,)

    nz = jnp.maximum(node_zone, 0)
    nr = jnp.maximum(node_region, 0)
    same_zone = node_zone[:, None] == jnp.arange(ZC)[None, :]  # (N, ZC)
    same_region = node_region[:, None] == zone_region[None, :]  # (N, ZC)

    # a candidate without a zone/region label looks up with key "" in the
    # reference (networkoverhead.go:544-566) — always a miss, never row 0
    zcost_row = jnp.where((node_zone >= 0)[:, None], zone_cost[nz], -1)  # (N, ZC)
    rcost_zone = region_cost[nr][:, jnp.maximum(zone_region, 0)]  # (N, ZC)
    rcost_zone = jnp.where(
        (node_region >= 0)[:, None] & (zone_region[None, :] >= 0),
        rcost_zone,
        -1,
    )

    pair_cost = jnp.where(
        same_zone,
        SAME_ZONE_COST,
        jnp.where(
            same_region,
            jnp.where(zcost_row >= 0, zcost_row, MAX_COST),
            jnp.where(rcost_zone >= 0, rcost_zone, MAX_COST),
        ),
    )  # (N, ZC)
    pair_known = jnp.where(same_region, zcost_row >= 0, rcost_zone >= 0)
    pair_lookup = jnp.where(same_region, zcost_row, rcost_zone)

    # same-node pods are handled separately: remove them from their zone
    same_node_cnt = placed  # (D, N)
    zone_cnt = placed_zone[:, None, :] - jnp.where(
        same_zone[None, :, :], same_node_cnt[:, :, None], 0
    )
    zone_cnt = jnp.maximum(zone_cnt, 0)  # (D, N, ZC)

    # same-zone pods are unconditionally satisfied (networkoverhead.go:542-545)
    sat_pair = same_zone[None, :, :] | (
        pair_known[None, :, :] & (pair_lookup[None, :, :] <= dep_max_cost[:, None, None])
    )
    vio_pair = ~same_zone[None, :, :] & pair_known[None, :, :] & ~sat_pair

    satisfied = jnp.sum(jnp.where(sat_pair, zone_cnt, 0), axis=(0, 2))
    violated = jnp.sum(jnp.where(vio_pair, zone_cnt, 0), axis=(0, 2))
    cost = jnp.sum(zone_cnt * pair_cost[None, :, :], axis=(0, 2))

    # same-node pods: satisfied, SameHostname cost (networkoverhead.go:521-525)
    satisfied = satisfied + jnp.sum(same_node_cnt, axis=0)
    cost = cost + SAME_HOST_COST * jnp.sum(same_node_cnt, axis=0)

    # region-only placed pods. Same region: a ZONED candidate's zone lookup
    # misses (destination "" -> cost MaxCost, no count) but a ZONELESS
    # candidate compares "" == "" as the SAME zone -> satisfied, cost 1
    # (networkoverhead.go:541-545). Across regions: region-cost lookup,
    # missing for label-less candidates.
    same_r = node_region[:, None] == jnp.arange(RC)[None, :]  # (N, RC)
    rcost = jnp.where((node_region >= 0)[:, None], region_cost[nr], -1)  # (N, RC)
    both_zoneless = (node_zone < 0)[:, None] & same_r  # (N, RC)
    rn_cost = jnp.where(
        both_zoneless,
        SAME_ZONE_COST,
        jnp.where(same_r, MAX_COST, jnp.where(rcost >= 0, rcost, MAX_COST)),
    )
    rn_known = ~same_r & (rcost >= 0)
    rn_sat = both_zoneless[None, :, :] | (
        rn_known[None, :, :]
        & (
            jnp.where(rcost >= 0, rcost, MAX_COST)[None, :, :]
            <= dep_max_cost[:, None, None]
        )
    )
    rn_vio = rn_known[None, :, :] & ~rn_sat
    node_rnoz = rnoz  # (N,)
    rnoz_cnt = placed_rnoz[:, None, :] - jnp.where(
        (node_rnoz[:, None] & same_r)[None, :, :], same_node_cnt[:, :, None], 0
    )
    rnoz_cnt = jnp.maximum(rnoz_cnt, 0)
    satisfied = satisfied + jnp.sum(jnp.where(rn_sat, rnoz_cnt, 0), axis=(0, 2))
    violated = violated + jnp.sum(jnp.where(rn_vio, rnoz_cnt, 0), axis=(0, 2))
    cost = cost + jnp.sum(rnoz_cnt * rn_cost[None, :, :], axis=(0, 2))

    # unlocated placed pods: violated, MaxCost each
    unloc_cnt = jnp.maximum(
        placed_unloc[:, None] - jnp.where(unloc[None, :], same_node_cnt, 0), 0
    )  # (D, N)
    violated = violated + jnp.sum(unloc_cnt, axis=0)
    cost = cost + MAX_COST * jnp.sum(unloc_cnt, axis=0)

    return (
        satisfied.astype(jnp.int64),
        violated.astype(jnp.int64),
        cost.astype(jnp.int64),
    )


def placed_commit(net_placed, workload, choice):
    """Reserve: record an in-cycle placement of `workload` on `choice`."""
    w = jnp.maximum(workload, 0)
    n = jnp.maximum(choice, 0)
    add = ((workload >= 0) & (choice >= 0)).astype(net_placed.dtype)
    return net_placed.at[w, n].add(add)
