"""Resource-fit Filter as one batched comparison.

Replaces the upstream NodeResourcesFit plugin body that the reference relies on
(invoked per pod x per node by the scheduling framework; see SURVEY.md §3.2
"Filter -> (upstream NodeResourcesFit etc., per node xN) <-HOT LOOP"): a pod
fits a node iff for every resource `requested + podRequest <= allocatable`,
plus the pod-count slot where each pod counts 1.
"""

from __future__ import annotations

import jax.numpy as jnp

from scheduler_plugins_tpu.ops import PODS_I


def free_capacity(alloc, requested):
    """(N, R) leftover allocatable."""
    return alloc - requested


def pod_fit_demand(req):
    """Pod demand vector(s) for fitting: the raw effective request with the
    pod-count slot set to 1 (each pod occupies one pod slot)."""
    req = jnp.asarray(req)
    return req.at[..., PODS_I].set(1)


def fits(req, free, pod_mask=None, node_mask=None):
    """(P, R) requests vs (N, R) free capacity -> (P, N) feasibility.

    `free` must already account for assigned pods (alloc - requested).
    """
    demand = pod_fit_demand(req)
    ok = jnp.all(demand[:, None, :] <= free[None, :, :], axis=-1)
    if pod_mask is not None:
        ok &= pod_mask[:, None]
    if node_mask is not None:
        ok &= node_mask[None, :]
    return ok


def fits_one(req, free, node_mask=None):
    """(R,) single-pod request vs (N, R) free -> (N,) feasibility (scan body)."""
    demand = pod_fit_demand(req)
    ok = jnp.all(demand[None, :] <= free, axis=-1)
    if node_mask is not None:
        ok &= node_mask
    return ok
