"""PodGroup (gang) admission math.

Reference PreFilter (/root/reference/pkg/coscheduling/core/core.go:243-305):
reject a gang member when (a) the group was recently backed off, (b) fewer
siblings exist cluster-wide than MinMember, (c) too many siblings are
SchedulingGated to ever reach quorum, or (d) MinResources (with the pods slot
set to MinMember, core.go:295-297) exceeds whole-cluster free capacity
(`CheckClusterResource`, core.go:404-426).

The cluster sweep (d) subtracts each node's RAW leftover (alloc - requested,
possibly negative — no clamping, core.go:406-426) from the demand vector with
the gang's own pods added back (getNodeResource, core.go:433-467). Raw
subtraction makes the check separable per resource:

    demand_r <= sum_n free_nr + (own assigned members' demand)_r
                + (own in-cycle placements' demand)_r

The pre-cycle own-member term is `gangs.cluster_slack` (snapshot builder);
the in-cycle term is `SolverState.gang_inflight`, accumulated by the
Coscheduling commit as members place during the scan (standing in for the
reference's permittedPG memoization, core.go:286-288).
"""

from __future__ import annotations

import jax.numpy as jnp


def cluster_free_total(free):
    """(R,) whole-cluster leftover: raw per-node sums, negatives included
    (core.go:406-426 subtracts unclamped leftovers from the demand)."""
    return jnp.sum(free, axis=0)


def gang_admit(gangs, state_free, gang_id, inflight=None):
    """Scalar admission verdict for one gang-member pod.

    gangs: GangState arrays; state_free: (N, R) current free capacity;
    gang_id: scalar gang code (-1 = not in a gang -> always pass);
    inflight: optional (G, R) demand committed by this gang earlier in the
    scan (added back, since the gang's own pods don't count against it).
    """
    in_gang = gang_id >= 0
    g = jnp.maximum(gang_id, 0)
    enough_members = gangs.total_members[g] >= gangs.min_member[g]
    not_backed_off = ~gangs.backed_off[g]
    # gated siblings can never reach quorum (core.go:268-277)
    reachable = gangs.total_members[g] - gangs.gated[g] >= gangs.min_member[g]
    capacity = cluster_free_total(state_free) + gangs.cluster_slack[g]
    if inflight is not None:
        capacity = capacity + inflight[g]
    fits_cluster = jnp.all(gangs.min_resources[g] <= capacity)
    minres_ok = ~gangs.has_min_resources[g] | fits_cluster
    verdict = enough_members & not_backed_off & reachable & minres_ok
    return jnp.where(in_gang, verdict, True)


def gang_commit(gang_scheduled, gang_id, placed):
    """Count an in-cycle placement toward the gang's quorum."""
    g = jnp.maximum(gang_id, 0)
    return gang_scheduled.at[g].add(
        jnp.where(placed & (gang_id >= 0), 1, 0).astype(gang_scheduled.dtype)
    )


def gang_inflight_commit(gang_inflight, gang_id, demand, placed):
    """Fold a placed member's demand into its gang's in-cycle add-back."""
    g = jnp.maximum(gang_id, 0)
    add = jnp.where(placed & (gang_id >= 0), demand, 0)
    return gang_inflight.at[g].add(add)
