"""Placement: turn per-pod feasibility + scores into node assignments.

Three modes, all returning assignment = (P,) int32 node index (-1 =
unschedulable):

- `greedy_assign` — bit-faithful to the reference's one-pod-at-a-time cycle:
  a `lax.scan` over the pod queue where each step filters/scores against the
  *current* free capacity and commits the winner before the next pod runs
  (SURVEY.md §7 "sequential semantics"). Tie-break: lowest node index (the
  upstream framework randomizes among equals; we pin determinism instead).

- `waterfill_assign` — the TPU-throughput default: queue-ranked pods spread
  across score-ordered nodes by estimated per-node capacity per wave, with
  EXACT queue-order admission; converges in a few waves even when scores tie.

- `wave_assign` — the simpler argmax-per-pod wave variant (one node fills
  per wave under tied scores; kept for comparison and tests).

Wave placements can differ from sequential mode in tie-breaking; hard
constraints hold in all modes.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

from scheduler_plugins_tpu.ops.fit import pod_fit_demand

#: signature: (free (N,R), pod_index int32) -> (feasible (N,) bool, score (N,) int64)
StepFn = Callable

def _sorted_segments(onehot):
    """Queue-order segment layout for a wave's node choices: `order` sorts
    pods by (chosen node, queue position) with "no choice" (sentinel N)
    last; `seg` = sorted segment ids; `first` marks each segment's head."""
    P, N = onehot.shape
    choice = jnp.where(onehot.any(axis=1), jnp.argmax(onehot, axis=1), N)
    order = jnp.argsort(choice * P + jnp.arange(P))  # stable (choice, queue)
    seg = choice[order]
    first = jnp.concatenate([jnp.array([True]), seg[1:] != seg[:-1]])
    return order, seg, first


def _segment_prefix(values_sorted, first):
    """Inclusive per-segment prefix sums of NON-NEGATIVE (P, R) float values
    WITHOUT a (P, N) cumsum (int64 2-D cumsums lower to vmem-hungry
    reduce-windows on TPU and compile pathologically): 1-D cumsums over the
    sorted axis, rebased per segment with a forward-filled running maximum
    (cummax works because the exclusive cumsum is non-decreasing)."""
    csum = jnp.cumsum(values_sorted, axis=0)
    exclusive = csum - values_sorted
    base = jax.lax.cummax(jnp.where(first[:, None], exclusive, -1.0), axis=0)
    return csum - base


def _queue_order_admission(onehot, demand, free):
    """(P,) bool: pod admitted iff its node still fits after all earlier
    winners of the same wave on that node (exact sorted-segment prefix
    sums in float64 — exact below 2^53)."""
    P, N = onehot.shape
    order, seg, first = _sorted_segments(onehot)
    dem_sorted = demand[order].astype(jnp.float64)  # (P, R)
    within = _segment_prefix(dem_sorted, first)  # inclusive per-segment
    free_row = free[jnp.minimum(seg, N - 1)].astype(jnp.float64)  # (P, R)
    ok_sorted = jnp.all(within <= free_row, axis=1) & (seg < N)
    return jnp.zeros(P, bool).at[order].set(ok_sorted)


def _pick(feasible, scores):
    """argmax score among feasible nodes, lowest index on ties; -1 if none."""
    masked = jnp.where(feasible, scores, jnp.int64(-(2**62)))
    best = jnp.argmax(masked)
    return jnp.where(feasible.any(), best.astype(jnp.int32), jnp.int32(-1))


@partial(jax.jit, static_argnames=("step_fn",))
def greedy_assign(step_fn: StepFn, req, pod_mask, free0):
    """Sequential greedy placement.

    step_fn computes this pod's (feasible, scores) against current free
    capacity; the scan then commits `req` (with the pod-count slot set to 1)
    to the chosen node.
    """
    demand = pod_fit_demand(req)  # (P, R)
    P = req.shape[0]

    def body(free, p):
        feasible, scores = step_fn(free, p)
        choice = _pick(feasible & pod_mask[p], scores)
        delta = jnp.where(
            (jnp.arange(free.shape[0]) == choice)[:, None], demand[p], 0
        )
        return free + jnp.where(choice >= 0, -delta, 0), choice

    free, assignment = jax.lax.scan(body, free0, jnp.arange(P))
    return assignment, free


@partial(jax.jit, static_argnames=("batch_fn", "max_waves"))
def waterfill_assign(batch_fn, req, pod_mask, free0, max_waves: int = 4):
    """Capacity-aware wave placement: queue-ranked pods spread across
    score-ordered nodes by estimated per-node capacity, so a wave fills MANY
    nodes (plain `wave_assign` fills one node per wave when scores tie —
    e.g. the homogeneous-cluster Least-allocatable case, where the sequential
    reference semantics pack node after node).

    Per wave: rank active pods in queue order; order nodes by mean score
    (desc, index tie-break); estimate each node's capacity in pods as
    min_r floor(free_r / mean-demand_r); send pod rank k to the node whose
    cumulative-capacity bucket contains k (falling back to the pod's argmax
    when that node is infeasible for it); validate with the exact queue-order
    prefix admission and retry the rest next wave.

    Stateless front-end of `waterfill_assign_stateful` (one shared wave
    body): no plugin carry, no guards.
    """
    assignment, free, _ = waterfill_assign_stateful(
        lambda f, _state, active: batch_fn(f, active),
        lambda state, _placed, _choice: state,
        (),
        (),
        req,
        pod_mask,
        free0,
        jnp.int32(0),
        max_waves=max_waves,
    )
    return assignment, free


def waterfill_assign_stateful(
    batch_fn,
    commit_fn,
    guards,
    guard_demands,
    req,
    pod_mask,
    free0,
    state0,
    max_waves: int = 4,
    validate_fn=None,
    validate_commit_fn=None,
    capacity_fns=(),
):
    """`waterfill_assign` with a plugin-state carry for STATE-DEPENDENT
    filters (NUMA zone availability, network placement tallies): the carries
    the sequential scan threads per pod are re-evaluated per WAVE here, so
    hard plugin constraints hold against committed placements instead of the
    cycle-initial snapshot.

    - ``batch_fn(free, state, active) -> (feasible (P,N), scores (P,N))`` is
      re-invoked every wave with the carried state (per-wave re-filtering).
    - ``commit_fn(state, placed (P,) bool, choice (P,) int32) -> state``
      folds a whole wave's placements into the carry (must be
      order-independent — the framework's carries are sums).
    - ``guards`` / ``guard_demands``: per-plugin exact WITHIN-wave admission.
      Each guard is ``fn(state, p, node, prefix (R_g,)) -> bool`` evaluated
      in queue order with ``prefix`` = the exclusive per-(wave, node) sum of
      ``guard_demands[i]`` (a (P, R_g) non-negative float array) over earlier
      same-wave choosers of the same node. A pod whose guard fails retries
      next wave against the committed state. Prefixes include earlier
      choosers that were themselves rejected — conservative (never violates
      hard constraints; may defer a feasible pod to the next wave), matching
      `_queue_order_admission`'s capacity semantics.
    - ``validate_fn(state, q, choice) -> bool`` /
      ``validate_commit_fn(state, q, choice) -> state``: per-wave SEQUENTIAL
      validation for hard constraints that span nodes (topology-domain
      counting): after guard admission, the wave's winners are re-checked
      one at a time in queue order against the live carry, committing (via
      ``validate_commit_fn``) only the kept ones; a demoted pod re-enters
      the next wave against the committed state. ``commit_fn`` must then
      EXCLUDE the carries ``validate_commit_fn`` maintains. The scan body
      is a handful of gathers per pod — this is for O(1)-per-pod checks,
      not (N,)-wide filters.

    Not jitted itself: designed to run inside a caller's jit (the closures
    are trace-local). Returns (assignment, free, state).
    """
    P, R = req.shape
    demand = pod_fit_demand(req)
    N = free0.shape[0]

    def wave(free, assignment, state):
        active = (assignment == -1) & pod_mask
        feasible, scores = batch_fn(free, state, active)
        feasible &= active[:, None]
        neg_inf = jnp.iinfo(scores.dtype).min // 2
        n_active = jnp.maximum(active.sum(), 1)

        mean_score = jnp.sum(jnp.where(active[:, None], scores, 0), axis=0)
        order_n = jnp.argsort(-mean_score, stable=True)  # (N,)
        mean_demand = (
            jnp.sum(jnp.where(active[:, None], demand, 0), axis=0) // n_active
        )
        cap = jnp.min(
            jnp.where(
                mean_demand[None, :] > 0,
                free // jnp.maximum(mean_demand[None, :], 1),
                jnp.int64(P),
            ),
            axis=1,
        )
        # plugin capacity refinements (NUMA zones, ...): bucketing must not
        # send a node more pods than its tightest constraint can admit
        for cap_fn in capacity_fns:
            extra = cap_fn(state, active)
            if extra is not None:
                cap = jnp.minimum(cap, extra.astype(cap.dtype))
        cap = jnp.clip(cap, 0, P).astype(jnp.int32)
        ccap = jnp.cumsum(cap[order_n], dtype=jnp.int32)
        rank = jnp.cumsum(active, dtype=jnp.int32) - 1
        bucket = jnp.searchsorted(ccap, rank, side="right")
        target = order_n[jnp.minimum(bucket, N - 1)]
        target_ok = jnp.take_along_axis(
            feasible, target[:, None], axis=1
        ).squeeze(1)
        masked = jnp.where(feasible, scores, neg_inf)
        fallback = jnp.argmax(masked, axis=1).astype(jnp.int32)
        choice = jnp.where(
            target_ok, target.astype(jnp.int32),
            jnp.where(feasible.any(axis=1), fallback, -1),
        )
        choice = jnp.where(active, choice, -1)

        onehot = (choice[:, None] == jnp.arange(N)[None, :]) & (
            choice[:, None] >= 0
        )
        order, seg, first = _sorted_segments(onehot)
        dem_sorted = demand[order].astype(jnp.float64)
        within = _segment_prefix(dem_sorted, first)
        free_row = free[jnp.minimum(seg, N - 1)].astype(jnp.float64)
        ok_sorted = jnp.all(within <= free_row, axis=1) & (seg < N)
        node_sorted = jnp.minimum(seg, N - 1)
        for guard, gdem in zip(guards, guard_demands):
            gd_sorted = gdem[order].astype(jnp.float64)
            g_within = _segment_prefix(gd_sorted, first)
            g_excl = g_within - gd_sorted  # exclusive: earlier choosers only
            ok_sorted &= jax.vmap(
                lambda p, n, pre: guard(state, p, n, pre)
            )(order, node_sorted, g_excl)
        admitted = (choice >= 0) & jnp.zeros(P, bool).at[order].set(ok_sorted)

        if validate_fn is not None:
            # cross-node hard constraints: sequential queue-order re-check
            # of this wave's winners against the live carry; kept pods
            # commit immediately so later pods in the same wave see them
            def vstep(vstate, q):
                act = admitted[q]
                ok = act & validate_fn(vstate, q, choice[q])
                kept_choice = jnp.where(ok, choice[q], jnp.int32(-1))
                vstate = validate_commit_fn(vstate, q, kept_choice)
                return vstate, ok

            state, kept = jax.lax.scan(vstep, state, jnp.arange(P))
            admitted = kept

        new_assignment = jnp.where(admitted, choice, assignment)
        winners = onehot & admitted[:, None]
        used = jnp.stack(
            [(winners * demand[:, r][:, None]).sum(axis=0) for r in range(R)],
            axis=-1,
        )
        state = commit_fn(state, admitted, choice)
        return free - used, new_assignment, state, admitted.sum()

    def cond(loop_state):
        _, _, _, wave_idx, progressed = loop_state
        return (wave_idx < max_waves) & progressed

    def body(loop_state):
        free, assignment, state, wave_idx, _ = loop_state
        free, assignment, state, n_admitted = wave(free, assignment, state)
        return free, assignment, state, wave_idx + 1, n_admitted > 0

    free, assignment, state, _, _ = jax.lax.while_loop(
        cond,
        body,
        (free0, jnp.full(P, -1, jnp.int32), state0, jnp.int32(0),
         jnp.bool_(True)),
    )
    return assignment, free, state


@partial(jax.jit, static_argnames=("batch_fn", "max_waves"))
def wave_assign(batch_fn, req, pod_mask, free0, max_waves: int = 8):
    """Wave-parallel placement.

    batch_fn: (free (N,R), active (P,) bool) -> (feasible (P,N), scores (P,N)).
    Per wave every still-unassigned pod picks its argmax node; within a wave,
    pods that chose the same node are admitted in queue order while the node's
    capacity lasts (an exclusive running sum per node), the rest retry next
    wave.
    """
    P, R = req.shape
    demand = pod_fit_demand(req)

    def wave(carry, _):
        free, assignment = carry
        active = (assignment == -1) & pod_mask
        feasible, scores = batch_fn(free, active)
        feasible &= active[:, None]
        masked = jnp.where(feasible, scores, jnp.int64(-(2**62)))
        choice = jnp.where(
            feasible.any(axis=1), jnp.argmax(masked, axis=1).astype(jnp.int32), -1
        )
        # queue-order admission: pod p wins iff node still fits after all
        # earlier winners of the same wave on the same node (sorted-segment
        # exact prefix sums)
        onehot = (choice[:, None] == jnp.arange(free.shape[0])[None, :]) & (
            choice[:, None] >= 0
        )  # (P, N)
        admitted = (choice >= 0) & _queue_order_admission(onehot, demand, free)
        new_assignment = jnp.where(admitted, choice, assignment)
        winners = onehot & admitted[:, None]  # (P, N)
        # per-resource masked sums (int64 matmul is unsupported on TPU)
        used = jnp.stack(
            [(winners * demand[:, r][:, None]).sum(axis=0) for r in range(R)],
            axis=-1,
        )  # (N, R)
        return (free - used, new_assignment), admitted.sum()

    (free, assignment), _ = jax.lax.scan(
        wave, (free0, jnp.full(P, -1, jnp.int32)), None, length=max_waves
    )
    return assignment, free
