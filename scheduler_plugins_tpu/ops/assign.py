"""Placement: turn per-pod feasibility + scores into node assignments.

Three modes, all returning assignment = (P,) int32 node index (-1 =
unschedulable):

- `greedy_assign` — bit-faithful to the reference's one-pod-at-a-time cycle:
  a `lax.scan` over the pod queue where each step filters/scores against the
  *current* free capacity and commits the winner before the next pod runs
  (SURVEY.md §7 "sequential semantics"). Tie-break: lowest node index (the
  upstream framework randomizes among equals; we pin determinism instead).

- `waterfill_assign` — the TPU-throughput default: queue-ranked pods spread
  across score-ordered nodes by estimated per-node capacity per wave, with
  EXACT queue-order admission; converges in a few waves even when scores tie.

- `wave_assign` — the simpler argmax-per-pod wave variant (one node fills
  per wave under tied scores; kept for comparison and tests).

Wave placements can differ from sequential mode in tie-breaking; hard
constraints hold in all modes.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

from scheduler_plugins_tpu.ops.fit import pod_fit_demand

#: signature: (free (N,R), pod_index int32) -> (feasible (N,) bool, score (N,) int64)
StepFn = Callable

def _segment_prefix(values_sorted, first):
    """Inclusive per-segment prefix sums of NON-NEGATIVE (P, R) float values
    WITHOUT a (P, N) cumsum (int64 2-D cumsums lower to vmem-hungry
    reduce-windows on TPU and compile pathologically): 1-D cumsums over the
    sorted axis, rebased per segment with a forward-filled running maximum
    (cummax works because the exclusive cumsum is non-decreasing)."""
    csum = jnp.cumsum(values_sorted, axis=0)
    exclusive = csum - values_sorted
    base = jax.lax.cummax(jnp.where(first[:, None], exclusive, -1.0), axis=0)
    return csum - base


def _queue_order_admission_choice(choice, demand, free):
    """(P,) bool: pod admitted iff its chosen node still fits after all
    earlier same-wave choosers of that node (exact sorted-segment prefix
    sums in float64 — exact below 2^53). `choice` is (P,) int32 node
    indices with -1 = no choice; never materializes a (P, N) onehot."""
    P = choice.shape[0]
    N = free.shape[0]
    seg_choice = jnp.where(choice >= 0, choice, N)
    order = jnp.argsort(
        seg_choice.astype(jnp.int64) * P + jnp.arange(P)
    )  # stable (choice, queue); int64 keys — N*P can exceed int32
    seg = seg_choice[order]
    first = jnp.concatenate([jnp.array([True]), seg[1:] != seg[:-1]])
    dem_sorted = demand[order].astype(jnp.float64)  # (P, R)
    within = _segment_prefix(dem_sorted, first)  # inclusive per-segment
    free_row = free[jnp.minimum(seg, N - 1)].astype(jnp.float64)  # (P, R)
    ok_sorted = jnp.all(within <= free_row, axis=1) & (seg < N)
    return jnp.zeros(P, bool).at[order].set(ok_sorted)


def _queue_order_admission(onehot, demand, free):
    """`_queue_order_admission_choice` for callers holding a (P, N) onehot."""
    choice = jnp.where(
        onehot.any(axis=1), jnp.argmax(onehot, axis=1).astype(jnp.int32), -1
    )
    return _queue_order_admission_choice(choice, demand, free)


def _pick(feasible, scores):
    """argmax score among feasible nodes, lowest index on ties; -1 if none."""
    masked = jnp.where(feasible, scores, jnp.int64(-(2**62)))
    best = jnp.argmax(masked)
    return jnp.where(feasible.any(), best.astype(jnp.int32), jnp.int32(-1))


@partial(jax.jit, static_argnames=("step_fn",))
def greedy_assign(step_fn: StepFn, req, pod_mask, free0):
    """Sequential greedy placement.

    step_fn computes this pod's (feasible, scores) against current free
    capacity; the scan then commits `req` (with the pod-count slot set to 1)
    to the chosen node.
    """
    demand = pod_fit_demand(req)  # (P, R)
    P = req.shape[0]

    def body(free, p):
        feasible, scores = step_fn(free, p)
        choice = _pick(feasible & pod_mask[p], scores)
        delta = jnp.where(
            (jnp.arange(free.shape[0]) == choice)[:, None], demand[p], 0
        )
        return free + jnp.where(choice >= 0, -delta, 0), choice

    free, assignment = jax.lax.scan(body, free0, jnp.arange(P))
    return assignment, free


@partial(jax.jit, static_argnames=("batch_fn", "max_waves"))
def waterfill_assign(batch_fn, req, pod_mask, free0, max_waves: int = 4):
    """Capacity-aware wave placement: queue-ranked pods spread across
    score-ordered nodes by estimated per-node capacity, so a wave fills MANY
    nodes (plain `wave_assign` fills one node per wave when scores tie —
    e.g. the homogeneous-cluster Least-allocatable case, where the sequential
    reference semantics pack node after node).

    Per wave: rank active pods in queue order; order nodes by mean score
    (desc, index tie-break); estimate each node's capacity in pods as
    min_r floor(free_r / mean-demand_r); send pod rank k to the node whose
    cumulative-capacity bucket contains k (falling back to the pod's argmax
    when that node is infeasible for it); validate with the exact queue-order
    prefix admission and retry the rest next wave.

    Stateless front-end of `waterfill_assign_stateful` (one shared wave
    body): no plugin carry, no guards.
    """
    assignment, free, _ = waterfill_assign_stateful(
        lambda f, _state, active: batch_fn(f, active),
        lambda state, _placed, _choice: state,
        (),
        (),
        req,
        pod_mask,
        free0,
        jnp.int32(0),
        max_waves=max_waves,
    )
    return assignment, free


def waterfill_assign_stateful(
    batch_fn,
    commit_fn,
    guards,
    guard_demands,
    req,
    pod_mask,
    free0,
    state0,
    max_waves: int = 4,
    validate_fn=None,
    validate_commit_fn=None,
    capacity_fns=(),
    initial_batch=None,
    sub_batch_fn=None,
    straggler_cap: int = 256,
):
    """`waterfill_assign` with a plugin-state carry for STATE-DEPENDENT
    filters (NUMA zone availability, network placement tallies): the carries
    the sequential scan threads per pod are re-evaluated per WAVE here, so
    hard plugin constraints hold against committed placements instead of the
    cycle-initial snapshot.

    - ``batch_fn(free, state, active) -> (feasible (P,N), scores (P,N))`` is
      re-invoked every wave with the carried state (per-wave re-filtering).
    - ``commit_fn(state, placed (P,) bool, choice (P,) int32) -> state``
      folds a whole wave's placements into the carry (must be
      order-independent — the framework's carries are sums).
    - ``guards`` / ``guard_demands``: per-plugin exact WITHIN-wave admission.
      Each guard is ``fn(state, p, node, prefix (R_g,)) -> bool`` evaluated
      in queue order with ``prefix`` = the exclusive per-(wave, node) sum of
      ``guard_demands[i]`` (a (P, R_g) non-negative float array) over earlier
      same-wave choosers of the same node. A pod whose guard fails retries
      next wave against the committed state. Prefixes include earlier
      choosers that were themselves rejected — conservative (never violates
      hard constraints; may defer a feasible pod to the next wave), matching
      `_queue_order_admission`'s capacity semantics.
    - ``validate_fn(state, q, choice) -> bool`` /
      ``validate_commit_fn(state, q, choice) -> state``: per-wave SEQUENTIAL
      validation for hard constraints that span nodes (topology-domain
      counting): after guard admission, the wave's winners are re-checked
      one at a time in queue order against the live carry, committing (via
      ``validate_commit_fn``) only the kept ones; a demoted pod re-enters
      the next wave against the committed state. ``commit_fn`` must then
      EXCLUDE the carries ``validate_commit_fn`` maintains. The scan body
      is a handful of gathers per pod — this is for O(1)-per-pod checks,
      not (N,)-wide filters.

    ``initial_batch``: optional (feasible0 (P,N), scores0 (P,N)) — the
    cycle-initial filter/score tensors the caller already computed (the
    profile solver's per-pod pass evaluates every plugin filter against
    state0 for normalization anyway). Wave 0 then reuses them instead of
    paying ``batch_fn`` a second time on the unchanged initial state; waves
    1+ always re-evaluate against the committed carry.

    ``sub_batch_fn(free, state, idx (S,), act_sub (S,)) -> (feasible (S,N),
    scores (S,N))``: optional SPARSE straggler waves — requires
    ``initial_batch``. Waves after the dense wave 0 gather the first
    ``straggler_cap`` still-unplaced pods (queue order) and re-filter only
    those rows, so a straggler wave costs O(S·N), not O(P·N). Guard
    prefixes, queue-order admission, and the validate scan all run inside
    the subset — exact, because subset rows preserve queue order and a
    wave admits only subset pods. A sparse wave that places NOTHING
    escalates to one dense wave over all active pods (a head cohort of
    more than ``straggler_cap`` infeasible pods must not starve placeable
    pods behind it); only a stalled dense wave ends the loop early.

    Not jitted itself: designed to run inside a caller's jit (the closures
    are trace-local). Returns (assignment, free, state).
    """
    P, R = req.shape
    demand = pod_fit_demand(req)
    N = free0.shape[0]
    S = min(straggler_cap, P)
    if sub_batch_fn is not None and initial_batch is None:
        raise ValueError("sub_batch_fn requires initial_batch (dense wave 0)")

    def wave_core(free, assignment, state, idx, feasible, scores):
        """One wave over the pod rows `idx` (ascending = queue order);
        `feasible`/`scores` are the (S, N) rows for those pods. The dense
        wave passes idx = arange(P)."""
        Ssub = idx.shape[0]
        active_full = (assignment == -1) & pod_mask
        active = active_full[idx]
        dem = demand[idx]
        feasible = feasible & active[:, None]
        neg_inf = jnp.iinfo(scores.dtype).min // 2
        n_active = jnp.maximum(active.sum(), 1)

        # int64 accumulator over a possibly-int32 score matrix: exact, at
        # half the (P, N) read traffic when the caller demoted scores
        mean_score = jnp.sum(
            jnp.where(active[:, None], scores, 0), axis=0, dtype=jnp.int64
        )
        order_n = jnp.argsort(-mean_score, stable=True)  # (N,)
        mean_demand = (
            jnp.sum(jnp.where(active[:, None], dem, 0), axis=0) // n_active
        )
        cap = jnp.min(
            jnp.where(
                mean_demand[None, :] > 0,
                free // jnp.maximum(mean_demand[None, :], 1),
                jnp.int64(Ssub),
            ),
            axis=1,
        )
        # plugin capacity refinements (NUMA zones, ...): bucketing must not
        # send a node more pods than its tightest constraint can admit
        for cap_fn in capacity_fns:
            extra = cap_fn(state, active_full)
            if extra is not None:
                cap = jnp.minimum(cap, extra.astype(cap.dtype))
        cap = jnp.clip(cap, 0, Ssub).astype(jnp.int32)
        ccap = jnp.cumsum(cap[order_n], dtype=jnp.int32)
        rank = jnp.cumsum(active, dtype=jnp.int32) - 1
        bucket = jnp.searchsorted(ccap, rank, side="right")
        target = order_n[jnp.minimum(bucket, N - 1)]
        target_ok = jnp.take_along_axis(
            feasible, target[:, None], axis=1
        ).squeeze(1)
        masked = jnp.where(feasible, scores, neg_inf)
        fallback = jnp.argmax(masked, axis=1).astype(jnp.int32)
        choice = jnp.where(
            target_ok, target.astype(jnp.int32),
            jnp.where(feasible.any(axis=1), fallback, -1),
        )
        choice = jnp.where(active, choice, -1)

        # queue-order segment layout straight from `choice` — never
        # materializes the (S, N) onehot the selection math doesn't need
        seg_choice = jnp.where(choice >= 0, choice, N)
        order = jnp.argsort(
            seg_choice.astype(jnp.int64) * Ssub + jnp.arange(Ssub)
        )
        seg = seg_choice[order]
        first = jnp.concatenate([jnp.array([True]), seg[1:] != seg[:-1]])
        dem_sorted = dem[order].astype(jnp.float64)
        within = _segment_prefix(dem_sorted, first)
        free_row = free[jnp.minimum(seg, N - 1)].astype(jnp.float64)
        ok_sorted = jnp.all(within <= free_row, axis=1) & (seg < N)
        node_sorted = jnp.minimum(seg, N - 1)
        for guard, gdem in zip(guards, guard_demands):
            gd_sorted = gdem[idx][order].astype(jnp.float64)
            g_within = _segment_prefix(gd_sorted, first)
            g_excl = g_within - gd_sorted  # exclusive: earlier choosers only
            ok_sorted &= jax.vmap(
                lambda j, n, pre: guard(state, idx[j], n, pre)
            )(order, node_sorted, g_excl)
        admitted = (choice >= 0) & jnp.zeros(Ssub, bool).at[order].set(
            ok_sorted
        )

        if validate_fn is not None:
            # cross-node hard constraints: sequential queue-order re-check
            # of this wave's winners against the live carry; kept pods
            # commit immediately so later pods in the same wave see them
            # explicit int32-counter while_loop, not lax.scan: with x64 on,
            # scan lowers its xs-slicing/ys-stacking through an i64 loop
            # counter, and an i64 dynamic-slice start on these POD-SHARDED
            # rows trips older XLA spmd partitioners (s64 index vs s32
            # shard-offset compare fails the HLO verifier)
            def vstep(carry):
                vstate, kept, j = carry
                act = admitted[j]
                q = idx[j].astype(jnp.int32)
                ok = act & validate_fn(vstate, q, choice[j])
                kept_choice = jnp.where(ok, choice[j], jnp.int32(-1))
                vstate = validate_commit_fn(vstate, q, kept_choice)
                return vstate, kept.at[j].set(ok), j + 1

            state, kept, _ = jax.lax.while_loop(
                lambda c: c[2] < Ssub,
                vstep,
                (state, jnp.zeros(Ssub, bool), jnp.int32(0)),
            )
            admitted = kept

        new_assignment = assignment.at[idx].set(
            jnp.where(admitted, choice, assignment[idx])
        )
        # (N, R) usage via an (S,)-row segment sum — R * (S, N) masked
        # multiply passes collapse into one S*R-element scatter
        used = jax.ops.segment_sum(
            jnp.where(admitted[:, None], dem, 0),
            jnp.where(admitted, choice, N),
            num_segments=N + 1,
        )[:N]
        placed_full = jnp.zeros(P, bool).at[idx].set(admitted)
        choice_full = jnp.full(P, -1, jnp.int32).at[idx].set(choice)
        state = commit_fn(state, placed_full, choice_full)
        return free - used, new_assignment, state, admitted.sum()

    dense_idx = jnp.arange(P)

    def dense_wave(free, assignment, state):
        active = (assignment == -1) & pod_mask
        feasible, scores = batch_fn(free, state, active)
        return wave_core(free, assignment, state, dense_idx, feasible, scores)

    def sparse_wave(free, assignment, state):
        active = (assignment == -1) & pod_mask
        # first S active pods in queue order (stable argsort: inactive
        # rows sink with key P)
        idx = jnp.argsort(jnp.where(active, dense_idx, P))[:S]
        feasible, scores = sub_batch_fn(free, state, idx, active[idx])
        return wave_core(free, assignment, state, idx, feasible, scores)

    assignment0 = jnp.full(P, -1, jnp.int32)

    if sub_batch_fn is None:
        def cond(loop_state):
            _, assignment, _, wave_idx, progressed = loop_state
            # stop on wave budget, on a no-progress wave, or — cheaper —
            # when nothing is left to place (otherwise a fully-placed
            # batch pays one whole extra wave to discover quiescence)
            return (
                (wave_idx < max_waves)
                & progressed
                & ((assignment == -1) & pod_mask).any()
            )

        def body(loop_state):
            free, assignment, state, wave_idx, _ = loop_state
            free, assignment, state, n = dense_wave(free, assignment, state)
            return free, assignment, state, wave_idx + 1, n > 0

        if initial_batch is not None:
            feasible0, scores0 = initial_batch
            free_w, assignment_w, state_w, n0 = wave_core(
                free0, assignment0, state0, dense_idx, feasible0, scores0
            )
            init = (free_w, assignment_w, state_w, jnp.int32(1), n0 > 0)
        else:
            init = (free0, assignment0, state0, jnp.int32(0), jnp.bool_(True))
        free, assignment, state, _, _ = jax.lax.while_loop(cond, body, init)
        return assignment, free, state

    # sparse mode machine: 0 = sparse straggler wave, 1 = dense retry,
    # 2 = stop. A stalled sparse wave does NOT end the loop — a head
    # cohort of >straggler_cap infeasible pods would otherwise starve
    # placeable pods behind it — it escalates to one dense wave over ALL
    # active pods; only a stalled dense wave proves quiescence. A
    # productive wave of either kind returns to sparse.
    MODE_SPARSE, MODE_DENSE, MODE_STOP = jnp.int32(0), jnp.int32(1), jnp.int32(2)

    def cond(loop_state):
        _, assignment, _, wave_idx, mode = loop_state
        return (
            (wave_idx < max_waves)
            & (mode < MODE_STOP)
            & ((assignment == -1) & pod_mask).any()
        )

    def body(loop_state):
        free, assignment, state, wave_idx, mode = loop_state
        free, assignment, state, n = jax.lax.cond(
            mode == MODE_SPARSE,
            lambda args: sparse_wave(*args),
            lambda args: dense_wave(*args),
            (free, assignment, state),
        )
        new_mode = jnp.where(
            n > 0,
            MODE_SPARSE,
            jnp.where(mode == MODE_SPARSE, MODE_DENSE, MODE_STOP),
        )
        return free, assignment, state, wave_idx + 1, new_mode

    # wave 0 is always dense (initial_batch is required with sub_batch_fn)
    feasible0, scores0 = initial_batch
    free_w, assignment_w, state_w, n0 = wave_core(
        free0, assignment0, state0, dense_idx, feasible0, scores0
    )
    # a stalled dense wave 0 already proves quiescence
    init = (
        free_w, assignment_w, state_w, jnp.int32(1),
        jnp.where(n0 > 0, MODE_SPARSE, MODE_STOP),
    )
    free, assignment, state, _, _ = jax.lax.while_loop(cond, body, init)
    return assignment, free, state


@partial(jax.jit, static_argnames=("max_waves", "rescue_window"))
def waterfill_assign_targeted(raw_scores, req, pod_mask, free0,
                              max_waves: int = 8,
                              rescue_window: int = 512):
    """Waterfill for STATIC per-node scores (the allocatable flagship and the
    north-star scale): per wave, each active pod checks fit against ONE
    target node — the capacity-bucket choice — in O(P·R) gathers, never
    materializing the (P, N) feasibility/score matrix the generic waterfill
    recomputes every wave. At 100k pods x 10k nodes that matrix is ~4B
    int64 compares per wave; this path does ~400k.

    Caller contract: `raw_scores` must already be the desired node ranking —
    the caller's normalization must be MONOTONE in the raw score and its
    weight positive (true of minmax_normalize and the single-scoring-plugin
    fast-path gate in parallel.solver), because this path orders by the raw
    vector and never runs normalize().

    Correctness: scores are static, so the node ranking never changes.
    Queue-order per-node admission is the same exact sorted-segment prefix
    check the generic waterfill runs. A pod whose target fails (fit or
    admission) retries next wave against shrunk capacities; when the lite
    waves stop progressing, FULL waves take over: windows of up to K
    stragglers get a dense (K, N) feasibility row, feasible ones spread
    round-robin over their own feasible sets, and window pods with NO
    feasible node are retired as hopeless (sound within one solve — free
    capacity only shrinks here, so infeasible-now is infeasible-later), so
    junk pods cannot starve the window for feasible stragglers behind them.
    Completeness therefore matches `waterfill_assign` UP TO THE WAVE
    BUDGET: each phase runs at most `max_waves` waves (2*max_waves total),
    and every full wave either places a pod, retires a hopeless one, or is
    the last. Hard constraints (fit, node queue-order admission) hold
    identically in all cases.

    Mirrors the reference's scoring semantics for allocatable
    (/root/reference/pkg/noderesources/resource_allocation.go:49-76) at
    wave granularity."""
    P, R = req.shape
    N = free0.shape[0]
    demand = pod_fit_demand(req)
    order_n = jnp.argsort(-raw_scores, stable=True)  # static node ranking

    def bucket_target(free, active):
        # cumulative-demand waterfill: pod p targets the first node (score
        # order) whose CUMULATIVE free capacity covers p's inclusive
        # cumulative demand, per resource (exact under heterogeneous
        # demands, unlike a mean-demand pods-per-node estimate: a queue of
        # small pods fills the preferred nodes first instead of stampeding
        # the one big node, mirroring sequential packing order). R 1-D
        # cumsums + R searchsorteds — float64 exact below 2^53.
        charge = jnp.where(active[:, None], demand, 0).astype(jnp.float64)
        cumdem = jnp.cumsum(charge, axis=0)  # (P, R) inclusive
        cumfree = jnp.cumsum(
            jnp.clip(free[order_n], 0, None).astype(jnp.float64), axis=0
        )  # (N, R) in score order
        pos = jnp.max(
            jax.vmap(
                lambda cf, cd: jnp.searchsorted(cf, cd, side="left"),
                in_axes=(1, 1), out_axes=1,
            )(cumfree, cumdem),
            axis=1,
        )  # (P,) first node index (score order) covering the prefix
        return order_n[jnp.minimum(pos, N - 1)].astype(jnp.int32)

    def lite_choice(free, active):
        target = bucket_target(free, active)
        # O(P*R): fit against the target row only
        fit = jnp.all(demand <= free[target], axis=1)
        # lite misses prove nothing about true feasibility: no hopeless delta
        return jnp.where(active & fit, target, -1), jnp.zeros(P, bool)

    # rescue-wave window: dense feasibility is computed for at most this
    # many stragglers at a time ((K, N) work instead of (P, N); the wave
    # loop drains K per wave when more remain). Full-phase completeness
    # capacity is max_waves * K placements-or-retires — callers trading
    # window size for throughput (the north-star chunk loop passes 256,
    # halving its dominant (K, N) cumsum cost) shrink that ceiling too
    K = min(P, rescue_window)

    def full_choice(free, active):
        # dense rescue wave: straggler k takes the (k mod |feasible_k|)-th
        # best node of ITS OWN feasible set in score order. Plain argmax
        # stampedes one tied-score node (admission then drains a node's
        # worth per wave — O(stragglers/node-capacity) waves at the
        # fragmented end-game); round-robin over each pod's feasible set
        # drains the residue in O(1) dense waves. Rank 0 still gets its
        # argmax, so the common one-straggler case keeps reference scoring.
        # Compaction: only the first K stragglers (queue order) pay the
        # dense row; later ones stay active for the next wave. Window pods
        # with NO feasible node are reported hopeless so they stop
        # occupying the window (free only shrinks within a solve, so the
        # verdict cannot go stale).
        sel = jnp.argsort(jnp.where(active, jnp.arange(P), P))[:K]
        sel_active = active[sel]
        feasible = jnp.all(
            demand[sel][:, None, :] <= free[None, :, :], axis=2
        ) & sel_active[:, None]
        feas_sorted = feasible[:, order_n]  # score-desc node order
        counts = jnp.cumsum(feas_sorted.astype(jnp.int32), axis=1)
        total = counts[:, -1]
        k = jnp.where(total > 0, jnp.arange(K) % jnp.maximum(total, 1), 0)
        pos = jax.vmap(
            lambda c, kk: jnp.searchsorted(c, kk, side="right")
        )(counts, k)  # first score-ordered index with counts > k
        choice_k = jnp.where(
            sel_active & (total > 0),
            order_n[jnp.minimum(pos, N - 1)].astype(jnp.int32),
            -1,
        )
        choice = jnp.full(P, -1, jnp.int32).at[sel].set(choice_k)
        hopeless_delta = jnp.zeros(P, bool).at[sel].set(
            sel_active & (total == 0)
        )
        return choice, hopeless_delta

    def wave(free, assignment, hopeless, choice_fn):
        # O(P·R + P log P): admission runs on the (P,) choice vector via
        # sorted segments (`_queue_order_admission_choice`) and commits via
        # scatter-add — never the (P, N) onehot/winners matrices the
        # generic waterfill builds (at north-star scale those are
        # ~84M-element temporaries per wave)
        active = (assignment == -1) & pod_mask & ~hopeless
        choice, hopeless_delta = choice_fn(free, active)
        admitted = (choice >= 0) & _queue_order_admission_choice(
            choice, demand, free
        )
        new_assignment = jnp.where(admitted, choice, assignment)
        used = jnp.zeros_like(free).at[jnp.where(admitted, choice, N - 1)].add(
            jnp.where(admitted[:, None], demand, 0)
        )
        return (
            free - used,
            new_assignment,
            hopeless | hopeless_delta,
            admitted.sum() + hopeless_delta.sum(),
        )

    # two phases, EACH with its own max_waves budget (up to 2*max_waves
    # waves total): lite waves to quiescence, then full waves to
    # quiescence (full resolves any straggler the bucket heuristic
    # starves; the dense window is only paid on those late waves)
    def run(free, assignment, hopeless, choice_fn):
        def cond(ls):
            free, assignment, hopeless, wave_idx, progressed = ls
            return (
                (wave_idx < max_waves)
                & progressed
                & ((assignment == -1) & pod_mask & ~hopeless).any()
            )

        def body(ls):
            free, assignment, hopeless, wave_idx, _ = ls
            free, assignment, hopeless, n = wave(
                free, assignment, hopeless, choice_fn
            )
            return free, assignment, hopeless, wave_idx + 1, n > 0

        return jax.lax.while_loop(
            cond, body,
            (free, assignment, hopeless, jnp.int32(0), jnp.bool_(True)),
        )

    assignment0 = jnp.full(P, -1, jnp.int32)
    hopeless0 = jnp.zeros(P, bool)
    free, assignment, hopeless, _, _ = run(
        free0, assignment0, hopeless0, lite_choice
    )
    free, assignment, _, _, _ = run(free, assignment, hopeless, full_choice)
    return assignment, free


@partial(jax.jit, static_argnames=("batch_fn", "max_waves"))
def wave_assign(batch_fn, req, pod_mask, free0, max_waves: int = 8):
    """Wave-parallel placement.

    batch_fn: (free (N,R), active (P,) bool) -> (feasible (P,N), scores (P,N)).
    Per wave every still-unassigned pod picks its argmax node; within a wave,
    pods that chose the same node are admitted in queue order while the node's
    capacity lasts (an exclusive running sum per node), the rest retry next
    wave.
    """
    P, R = req.shape
    demand = pod_fit_demand(req)

    def wave(carry, _):
        free, assignment = carry
        active = (assignment == -1) & pod_mask
        feasible, scores = batch_fn(free, active)
        feasible &= active[:, None]
        masked = jnp.where(feasible, scores, jnp.int64(-(2**62)))
        choice = jnp.where(
            feasible.any(axis=1), jnp.argmax(masked, axis=1).astype(jnp.int32), -1
        )
        # queue-order admission: pod p wins iff node still fits after all
        # earlier winners of the same wave on the same node (sorted-segment
        # exact prefix sums)
        onehot = (choice[:, None] == jnp.arange(free.shape[0])[None, :]) & (
            choice[:, None] >= 0
        )  # (P, N)
        admitted = (choice >= 0) & _queue_order_admission(onehot, demand, free)
        new_assignment = jnp.where(admitted, choice, assignment)
        winners = onehot & admitted[:, None]  # (P, N)
        # per-resource masked sums (int64 matmul is unsupported on TPU)
        used = jnp.stack(
            [(winners * demand[:, r][:, None]).sum(axis=0) for r in range(R)],
            axis=-1,
        )  # (N, R)
        return (free - used, new_assignment), admitted.sum()

    (free, assignment), _ = jax.lax.scan(
        wave, (free0, jnp.full(P, -1, jnp.int32)), None, length=max_waves
    )
    return assignment, free
