"""Placement: turn per-pod feasibility + scores into node assignments.

Three modes, all returning assignment = (P,) int32 node index (-1 =
unschedulable):

- `greedy_assign` — bit-faithful to the reference's one-pod-at-a-time cycle:
  a `lax.scan` over the pod queue where each step filters/scores against the
  *current* free capacity and commits the winner before the next pod runs
  (SURVEY.md §7 "sequential semantics"). Tie-break: lowest node index (the
  upstream framework randomizes among equals; we pin determinism instead).

- `waterfill_assign` — the TPU-throughput default: queue-ranked pods spread
  across score-ordered nodes by estimated per-node capacity per wave, with
  EXACT queue-order admission; converges in a few waves even when scores tie.

- `wave_assign` — the simpler argmax-per-pod wave variant (one node fills
  per wave under tied scores; kept for comparison and tests).

Wave placements can differ from sequential mode in tie-breaking; hard
constraints hold in all modes.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

from scheduler_plugins_tpu.ops.fit import pod_fit_demand

#: signature: (free (N,R), pod_index int32) -> (feasible (N,) bool, score (N,) int64)
StepFn = Callable

def _segment_prefix(values_sorted, first):
    """Inclusive per-segment prefix sums of NON-NEGATIVE (P, R) float values
    WITHOUT a (P, N) cumsum (int64 2-D cumsums lower to vmem-hungry
    reduce-windows on TPU and compile pathologically): 1-D cumsums over the
    sorted axis, rebased per segment with a forward-filled running maximum
    (cummax works because the exclusive cumsum is non-decreasing)."""
    csum = jnp.cumsum(values_sorted, axis=0)
    exclusive = csum - values_sorted
    base = jax.lax.cummax(jnp.where(first[:, None], exclusive, -1.0), axis=0)
    return csum - base


def _cumulative_demand_positions(dem, free, order_n):
    """(W,) first score-ordered node index whose CUMULATIVE free capacity
    covers each row's inclusive cumulative demand, per resource (max over
    R) — the cumulative-demand waterfill bucketing shared by the generic
    wave core and the targeted lite waves (exact under heterogeneous
    demands, unlike a mean-demand pods-per-node estimate: a queue of small
    pods fills the preferred nodes first instead of stampeding the one big
    node, mirroring sequential packing order). `dem` must already be
    masked to the active/window rows (inactive rows charge 0). R 1-D
    float64 cumsums + R searchsorteds — exact below 2^53."""
    cumdem = jnp.cumsum(dem.astype(jnp.float64), axis=0)  # (W, R) inclusive
    cumfree = jnp.cumsum(
        jnp.clip(free[order_n], 0, None).astype(jnp.float64), axis=0
    )  # (N, R) in score order
    return jnp.max(
        jax.vmap(
            lambda cf, cd: jnp.searchsorted(cf, cd, side="left"),
            in_axes=(1, 1), out_axes=1,
        )(cumfree, cumdem),
        axis=1,
    )


def _queue_order_admission_choice(choice, demand, free):
    """(P,) bool: pod admitted iff its chosen node still fits after all
    earlier same-wave choosers of that node (exact sorted-segment prefix
    sums in float64 — exact below 2^53). `choice` is (P,) int32 node
    indices with -1 = no choice; never materializes a (P, N) onehot."""
    P = choice.shape[0]
    N = free.shape[0]
    seg_choice = jnp.where(choice >= 0, choice, N)
    order = jnp.argsort(
        seg_choice.astype(jnp.int64) * P + jnp.arange(P)
    )  # stable (choice, queue); int64 keys — N*P can exceed int32
    seg = seg_choice[order]
    first = jnp.concatenate([jnp.array([True]), seg[1:] != seg[:-1]])
    dem_sorted = demand[order].astype(jnp.float64)  # (P, R)
    within = _segment_prefix(dem_sorted, first)  # inclusive per-segment
    free_row = free[jnp.minimum(seg, N - 1)].astype(jnp.float64)  # (P, R)
    ok_sorted = jnp.all(within <= free_row, axis=1) & (seg < N)
    return jnp.zeros(P, bool).at[order].set(ok_sorted)


def _queue_order_admission(onehot, demand, free):
    """`_queue_order_admission_choice` for callers holding a (P, N) onehot."""
    choice = jnp.where(
        onehot.any(axis=1), jnp.argmax(onehot, axis=1).astype(jnp.int32), -1
    )
    return _queue_order_admission_choice(choice, demand, free)


def _pick(feasible, scores):
    """argmax score among feasible nodes, lowest index on ties; -1 if none."""
    masked = jnp.where(feasible, scores, jnp.int64(-(2**62)))
    best = jnp.argmax(masked)
    return jnp.where(feasible.any(), best.astype(jnp.int32), jnp.int32(-1))


def _straggler_window(demand, pod_mask, assignment, hopeless, W):
    """First W still-active pods in queue order: (idx (W,), valid (W,),
    dem (W, R)) — rank-compaction scatter into a W+1 buffer (slot W is
    the overflow trash slot), no P-length sort. Deliberately NOT
    `jnp.nonzero(size=)`: jax pads that via a bincount scatter whose
    out-of-bounds writes rely on drop semantics, which the SPT_SANITIZE
    checkify gate rightly flags; this form is in-bounds by construction
    at the same O(P) scatter cost. Shared by the single-device targeted
    waterfill and the shard_map sharded variant (pod-axis state is
    replicated there, so the same code runs per shard)."""
    P = pod_mask.shape[0]
    active = (assignment == -1) & pod_mask & ~hopeless
    rank = jnp.cumsum(active) - 1  # (P,) inclusive rank among active
    slot = jnp.where(active & (rank < W), rank, W).astype(jnp.int32)
    idx = jnp.full(W + 1, P, jnp.int32).at[slot].min(
        jnp.arange(P, dtype=jnp.int32)
    )[:W]
    valid = idx < P
    dem_w = jnp.where(valid[:, None], demand[jnp.minimum(idx, P - 1)], 0)
    return idx, valid, dem_w


def ring_exclusive_scan(x, axis_name, n_shards: int):
    """Exclusive prefix sum of `x` over the mesh axis `axis_name` (shard s
    receives the sum of x from shards < s) via an (S-1)-step `lax.ppermute`
    ring — O(shards) collectives of O(|x|) payload each, never a full-axis
    gather (tools/graft_lint.py GL009 forbids `all_gather` over the node
    axis: it silently degrades the ring election back to a full gather).
    After k ring steps each shard holds the value of shard (idx - k) mod S;
    summing the steps with k <= idx yields the exclusive prefix."""
    if n_shards == 1:
        return jnp.zeros_like(x)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]
    acc = jnp.zeros_like(x)
    recv = x
    for k in range(1, n_shards):
        recv = jax.lax.ppermute(recv, axis_name, perm)
        acc = acc + jnp.where(k <= idx, recv, jnp.zeros_like(recv))
    return acc


#: shard count above which the sharded wave's block-offset scans switch
#: from the one-psum slot-scatter (payload O(S·|x|), ONE barrier) to the
#: ppermute ring (payload O(|x|) per step, S-1 barriers): barriers are the
#: expensive resource on small meshes (XLA's in-process CPU collectives
#: spin-wait at every rendezvous), payload is on large ones.
PSUM_SCAN_MAX_SHARDS = 64


def block_exclusive_offsets(x, axis_name, n_shards: int):
    """(exclusive_prefix, total) of the per-shard values `x` over the mesh
    axis — the cross-shard reduction behind both wave elections (cumulative
    free-capacity bases, rescue feasible-count offsets). Reduces per-shard
    CHAMPIONS only (an (S, ...) table of block aggregates), never the node
    axis itself.

    Two exact formulations, picked by shard count:

    - S <= `PSUM_SCAN_MAX_SHARDS`: each shard scatters its value into its
      own slot of an (S, ...) zero table and ONE `lax.psum` assembles all
      block aggregates everywhere (slots are disjoint, so the sum is exact
      for any dtype); the exclusive prefix and the total then fall out of
      one local cumsum over the tiny S axis.
    - larger S: the (S-1)-step `ring_exclusive_scan` plus one psum for the
      total — O(|x|) payload per step when S·|x| tables would outgrow the
      win of fewer barriers.

    Both orderings sum blocks left-to-right, so results are bit-identical
    to each other and to the single-device cumsum decomposition whenever
    the values are exact (integers below 2^53 in float64 — the documented
    parity bound)."""
    if n_shards == 1:
        return jnp.zeros_like(x), x
    if n_shards > PSUM_SCAN_MAX_SHARDS:
        return (
            ring_exclusive_scan(x, axis_name, n_shards),
            jax.lax.psum(x, axis_name),
        )
    shard = jax.lax.axis_index(axis_name)
    slots = jnp.zeros((n_shards,) + x.shape, x.dtype).at[shard].set(x)
    blocks = jax.lax.psum(slots, axis_name)  # (S, ...) every block's value
    csum = jnp.cumsum(blocks, axis=0)
    return (csum - blocks)[shard], csum[-1]


@partial(jax.jit, static_argnames=("step_fn",))
def greedy_assign(step_fn: StepFn, req, pod_mask, free0):
    """Sequential greedy placement.

    step_fn computes this pod's (feasible, scores) against current free
    capacity; the scan then commits `req` (with the pod-count slot set to 1)
    to the chosen node.
    """
    demand = pod_fit_demand(req)  # (P, R)
    P = req.shape[0]

    def body(free, p):
        feasible, scores = step_fn(free, p)
        choice = _pick(feasible & pod_mask[p], scores)
        delta = jnp.where(
            (jnp.arange(free.shape[0]) == choice)[:, None], demand[p], 0
        )
        return free + jnp.where(choice >= 0, -delta, 0), choice

    free, assignment = jax.lax.scan(body, free0, jnp.arange(P))
    return assignment, free


@partial(jax.jit, static_argnames=("batch_fn", "max_waves"))
def waterfill_assign(batch_fn, req, pod_mask, free0, max_waves: int = 4):
    """Capacity-aware wave placement: queue-ranked pods spread across
    score-ordered nodes by estimated per-node capacity, so a wave fills MANY
    nodes (plain `wave_assign` fills one node per wave when scores tie —
    e.g. the homogeneous-cluster Least-allocatable case, where the sequential
    reference semantics pack node after node).

    Per wave: rank active pods in queue order; order nodes by mean score
    (desc, index tie-break); estimate each node's capacity in pods as
    min_r floor(free_r / mean-demand_r); send pod rank k to the node whose
    cumulative-capacity bucket contains k (falling back to the pod's argmax
    when that node is infeasible for it); validate with the exact queue-order
    prefix admission and retry the rest next wave.

    Stateless front-end of `waterfill_assign_stateful` (one shared wave
    body): no plugin carry, no guards.
    """
    assignment, free, _ = waterfill_assign_stateful(
        lambda f, _state, active: batch_fn(f, active),
        lambda state, _placed, _choice: state,
        (),
        (),
        req,
        pod_mask,
        free0,
        jnp.int32(0),
        max_waves=max_waves,
    )
    return assignment, free


def waterfill_assign_stateful(
    batch_fn,
    commit_fn,
    guards,
    guard_demands,
    req,
    pod_mask,
    free0,
    state0,
    max_waves: int = 4,
    validate_fn=None,
    validate_commit_fn=None,
    capacity_fns=(),
    initial_batch=None,
    sub_batch_fn=None,
    straggler_cap: int = 256,
    collect_stats: bool = False,
):
    """`waterfill_assign` with a plugin-state carry for STATE-DEPENDENT
    filters (NUMA zone availability, network placement tallies): the carries
    the sequential scan threads per pod are re-evaluated per WAVE here, so
    hard plugin constraints hold against committed placements instead of the
    cycle-initial snapshot.

    - ``batch_fn(free, state, active) -> (feasible (P,N), scores (P,N))`` is
      re-invoked every wave with the carried state (per-wave re-filtering).
    - ``commit_fn(state, placed (P,) bool, choice (P,) int32) -> state``
      folds a whole wave's placements into the carry (must be
      order-independent — the framework's carries are sums).
    - ``guards`` / ``guard_demands``: per-plugin exact WITHIN-wave admission.
      Each guard is ``fn(state, p, node, prefix (R_g,)) -> bool`` evaluated
      in queue order with ``prefix`` = the exclusive per-(wave, node) sum of
      ``guard_demands[i]`` (a (P, R_g) non-negative float array) over earlier
      same-wave choosers of the same node. A pod whose guard fails retries
      next wave against the committed state. Prefixes include earlier
      choosers that were themselves rejected — conservative (never violates
      hard constraints; may defer a feasible pod to the next wave), matching
      `_queue_order_admission`'s capacity semantics.
    - ``validate_fn(state, q, choice) -> bool`` /
      ``validate_commit_fn(state, q, choice) -> state``: per-wave SEQUENTIAL
      validation for hard constraints that span nodes (topology-domain
      counting): after guard admission, the wave's winners are re-checked
      one at a time in queue order against the live carry, committing (via
      ``validate_commit_fn``) only the kept ones; a demoted pod re-enters
      the next wave against the committed state. ``commit_fn`` must then
      EXCLUDE the carries ``validate_commit_fn`` maintains. The scan body
      is a handful of gathers per pod — this is for O(1)-per-pod checks,
      not (N,)-wide filters.

    ``initial_batch``: optional (feasible0 (P,N), scores0 (P,N)) — the
    cycle-initial filter/score tensors the caller already computed (the
    profile solver's per-pod pass evaluates every plugin filter against
    state0 for normalization anyway). Wave 0 then reuses them instead of
    paying ``batch_fn`` a second time on the unchanged initial state; waves
    1+ always re-evaluate against the committed carry.

    ``sub_batch_fn(free, state, idx (S,), act_sub (S,)) -> (feasible (S,N),
    scores (S,N))``: optional SPARSE straggler waves — requires
    ``initial_batch``. Waves after the dense wave 0 gather the first
    ``straggler_cap`` still-unplaced pods (queue order) and re-filter only
    those rows, so a straggler wave costs O(S·N), not O(P·N). Guard
    prefixes, queue-order admission, and the validate scan all run inside
    the subset — exact, because subset rows preserve queue order and a
    wave admits only subset pods. A sparse wave that places NOTHING
    escalates to one dense wave over all active pods (a head cohort of
    more than ``straggler_cap`` infeasible pods must not starve placeable
    pods behind it); only a stalled dense wave ends the loop early.

    ``collect_stats``: also return per-wave occupancy — a
    ``{"occupancy": (max_waves,) int32 admitted-per-wave, "waves": int32
    executed-wave-count}`` dict (wave 0 is slot 0) — so perf work can see
    whether wave count or per-wave cost moved. Adds one O(max_waves)
    scatter per wave; placements are unchanged.

    Not jitted itself: designed to run inside a caller's jit (the closures
    are trace-local). Returns (assignment, free, state), plus the stats
    dict when ``collect_stats``.
    """
    P, R = req.shape
    demand = pod_fit_demand(req)
    N = free0.shape[0]
    S = min(straggler_cap, P)
    if sub_batch_fn is not None and initial_batch is None:
        raise ValueError("sub_batch_fn requires initial_batch (dense wave 0)")

    def wave_core(free, assignment, state, idx, feasible, scores):
        """One wave over the pod rows `idx` (ascending = queue order);
        `feasible`/`scores` are the (S, N) rows for those pods. The dense
        wave passes idx = arange(P)."""
        Ssub = idx.shape[0]
        active_full = (assignment == -1) & pod_mask
        active = active_full[idx]
        dem = demand[idx]
        feasible = feasible & active[:, None]
        neg_inf = jnp.iinfo(scores.dtype).min // 2

        # int64 accumulator over a possibly-int32 score matrix: exact, at
        # half the (P, N) read traffic when the caller demoted scores
        mean_score = jnp.sum(
            jnp.where(active[:, None], scores, 0), axis=0, dtype=jnp.int64
        )
        order_n = jnp.argsort(-mean_score, stable=True)  # (N,)
        # cumulative-demand bucketing (`_cumulative_demand_positions`, the
        # targeted waterfill's exact formulation): a mean-demand
        # pods-per-node estimate misroutes heterogeneous big/small queues
        # and leaves stragglers for extra re-filtered waves
        pos = _cumulative_demand_positions(
            jnp.where(active[:, None], dem, 0), free, order_n
        )  # (S,) first score-ordered node covering the demand prefix
        # plugin capacity refinements (NUMA zones, ...): pods-per-node caps
        # the resource cumsums cannot see — bucket pod rank against the
        # cumulative cap and take the more conservative position
        rank = jnp.cumsum(active, dtype=jnp.int32) - 1
        for cap_fn in capacity_fns:
            extra = cap_fn(state, active_full)
            if extra is not None:
                cap = jnp.clip(extra.astype(jnp.int32), 0, Ssub)
                ccap = jnp.cumsum(cap[order_n], dtype=jnp.int32)
                pos = jnp.maximum(
                    pos, jnp.searchsorted(ccap, rank, side="right")
                )
        target = order_n[jnp.minimum(pos, N - 1)]
        target_ok = jnp.take_along_axis(
            feasible, target[:, None], axis=1
        ).squeeze(1)
        masked = jnp.where(feasible, scores, neg_inf)
        fallback = jnp.argmax(masked, axis=1).astype(jnp.int32)
        choice = jnp.where(
            target_ok, target.astype(jnp.int32),
            jnp.where(feasible.any(axis=1), fallback, -1),
        )
        choice = jnp.where(active, choice, -1)

        # queue-order segment layout straight from `choice` — never
        # materializes the (S, N) onehot the selection math doesn't need
        seg_choice = jnp.where(choice >= 0, choice, N)
        order = jnp.argsort(
            seg_choice.astype(jnp.int64) * Ssub + jnp.arange(Ssub)
        )
        seg = seg_choice[order]
        first = jnp.concatenate([jnp.array([True]), seg[1:] != seg[:-1]])
        dem_sorted = dem[order].astype(jnp.float64)
        within = _segment_prefix(dem_sorted, first)
        free_row = free[jnp.minimum(seg, N - 1)].astype(jnp.float64)
        ok_sorted = jnp.all(within <= free_row, axis=1) & (seg < N)
        node_sorted = jnp.minimum(seg, N - 1)
        for guard, gdem in zip(guards, guard_demands):
            gd_sorted = gdem[idx][order].astype(jnp.float64)
            g_within = _segment_prefix(gd_sorted, first)
            g_excl = g_within - gd_sorted  # exclusive: earlier choosers only
            ok_sorted &= jax.vmap(
                lambda j, n, pre: guard(state, idx[j], n, pre)
            )(order, node_sorted, g_excl)
        admitted = (choice >= 0) & jnp.zeros(Ssub, bool).at[order].set(
            ok_sorted
        )

        if validate_fn is not None:
            # cross-node hard constraints: sequential queue-order re-check
            # of this wave's winners against the live carry; kept pods
            # commit immediately so later pods in the same wave see them
            # explicit int32-counter while_loop, not lax.scan: with x64 on,
            # scan lowers its xs-slicing/ys-stacking through an i64 loop
            # counter, and an i64 dynamic-slice start on these POD-SHARDED
            # rows trips older XLA spmd partitioners (s64 index vs s32
            # shard-offset compare fails the HLO verifier)
            def vstep(carry):
                vstate, kept, j = carry
                act = admitted[j]
                q = idx[j].astype(jnp.int32)
                ok = act & validate_fn(vstate, q, choice[j])
                kept_choice = jnp.where(ok, choice[j], jnp.int32(-1))
                vstate = validate_commit_fn(vstate, q, kept_choice)
                return vstate, kept.at[j].set(ok), j + 1

            state, kept, _ = jax.lax.while_loop(
                lambda c: c[2] < Ssub,
                vstep,
                (state, jnp.zeros(Ssub, bool), jnp.int32(0)),
            )
            admitted = kept

        new_assignment = assignment.at[idx].set(
            jnp.where(admitted, choice, assignment[idx])
        )
        # (N, R) usage via an (S,)-row segment sum — R * (S, N) masked
        # multiply passes collapse into one S*R-element scatter
        used = jax.ops.segment_sum(
            jnp.where(admitted[:, None], dem, 0),
            jnp.where(admitted, choice, N),
            num_segments=N + 1,
        )[:N]
        placed_full = jnp.zeros(P, bool).at[idx].set(admitted)
        choice_full = jnp.full(P, -1, jnp.int32).at[idx].set(choice)
        state = commit_fn(state, placed_full, choice_full)
        return free - used, new_assignment, state, admitted.sum()

    dense_idx = jnp.arange(P)

    def dense_wave(free, assignment, state):
        active = (assignment == -1) & pod_mask
        feasible, scores = batch_fn(free, state, active)
        return wave_core(free, assignment, state, dense_idx, feasible, scores)

    def sparse_wave(free, assignment, state):
        active = (assignment == -1) & pod_mask
        # first S active pods in queue order (stable argsort: inactive
        # rows sink with key P)
        idx = jnp.argsort(jnp.where(active, dense_idx, P))[:S]
        feasible, scores = sub_batch_fn(free, state, idx, active[idx])
        return wave_core(free, assignment, state, idx, feasible, scores)

    assignment0 = jnp.full(P, -1, jnp.int32)
    occ0 = jnp.zeros(max_waves, jnp.int32)

    if sub_batch_fn is None:
        def cond(loop_state):
            _, assignment, _, wave_idx, progressed, _ = loop_state
            # stop on wave budget, on a no-progress wave, or — cheaper —
            # when nothing is left to place (otherwise a fully-placed
            # batch pays one whole extra wave to discover quiescence)
            return (
                (wave_idx < max_waves)
                & progressed
                & ((assignment == -1) & pod_mask).any()
            )

        def body(loop_state):
            free, assignment, state, wave_idx, _, occ = loop_state
            free, assignment, state, n = dense_wave(free, assignment, state)
            return (
                free, assignment, state, wave_idx + 1, n > 0,
                occ.at[wave_idx].set(n.astype(jnp.int32)),
            )

        if initial_batch is not None:
            feasible0, scores0 = initial_batch
            free_w, assignment_w, state_w, n0 = wave_core(
                free0, assignment0, state0, dense_idx, feasible0, scores0
            )
            init = (
                free_w, assignment_w, state_w, jnp.int32(1), n0 > 0,
                occ0.at[0].set(n0.astype(jnp.int32)),
            )
        else:
            init = (free0, assignment0, state0, jnp.int32(0),
                    jnp.bool_(True), occ0)
        free, assignment, state, waves, _, occ = jax.lax.while_loop(
            cond, body, init
        )
        if collect_stats:
            return assignment, free, state, {"occupancy": occ, "waves": waves}
        return assignment, free, state

    # sparse mode machine: 0 = sparse straggler wave, 1 = dense retry,
    # 2 = stop. A stalled sparse wave does NOT end the loop — a head
    # cohort of >straggler_cap infeasible pods would otherwise starve
    # placeable pods behind it — it escalates to one dense wave over ALL
    # active pods; only a stalled dense wave proves quiescence. A
    # productive wave of either kind returns to sparse.
    MODE_SPARSE, MODE_DENSE, MODE_STOP = jnp.int32(0), jnp.int32(1), jnp.int32(2)

    def cond(loop_state):
        _, assignment, _, wave_idx, mode, _ = loop_state
        return (
            (wave_idx < max_waves)
            & (mode < MODE_STOP)
            & ((assignment == -1) & pod_mask).any()
        )

    def body(loop_state):
        free, assignment, state, wave_idx, mode, occ = loop_state
        free, assignment, state, n = jax.lax.cond(
            mode == MODE_SPARSE,
            lambda args: sparse_wave(*args),
            lambda args: dense_wave(*args),
            (free, assignment, state),
        )
        new_mode = jnp.where(
            n > 0,
            MODE_SPARSE,
            jnp.where(mode == MODE_SPARSE, MODE_DENSE, MODE_STOP),
        )
        return (
            free, assignment, state, wave_idx + 1, new_mode,
            occ.at[wave_idx].set(n.astype(jnp.int32)),
        )

    # wave 0 is always dense (initial_batch is required with sub_batch_fn)
    feasible0, scores0 = initial_batch
    free_w, assignment_w, state_w, n0 = wave_core(
        free0, assignment0, state0, dense_idx, feasible0, scores0
    )
    # a stalled dense wave 0 already proves quiescence
    init = (
        free_w, assignment_w, state_w, jnp.int32(1),
        jnp.where(n0 > 0, MODE_SPARSE, MODE_STOP),
        occ0.at[0].set(n0.astype(jnp.int32)),
    )
    free, assignment, state, waves, _, occ = jax.lax.while_loop(
        cond, body, init
    )
    if collect_stats:
        return assignment, free, state, {"occupancy": occ, "waves": waves}
    return assignment, free, state


@partial(jax.jit,
         static_argnames=("max_waves", "rescue_window", "lite_window",
                          "collect_stats"))
def waterfill_assign_targeted(raw_scores, req, pod_mask, free0,
                              max_waves: int = 8,
                              rescue_window: int = 512,
                              lite_window: int = 1024,
                              collect_stats: bool = False):
    """Waterfill for STATIC per-node scores (the allocatable flagship and the
    north-star scale): per wave, each active pod checks fit against a
    handful of target nodes — the capacity-bucket choice plus next-fit
    probes — in O(W*R) gathers, never materializing the (P, N)
    feasibility/score matrix the generic waterfill recomputes every wave.
    At 100k pods x 10k nodes that matrix is ~4B int64 compares per wave.

    Caller contract: `raw_scores` must already be the desired node ranking —
    the caller's normalization must be MONOTONE in the raw score and its
    weight positive (true of minmax_normalize and the single-scoring-plugin
    fast-path gate in parallel.solver), because this path orders by the raw
    vector and never runs normalize().

    Wave structure (every retry wave runs on a bounded straggler WINDOW —
    the first W still-active pods in queue order via a rank-compaction
    scatter — so late waves sort/scan W elements, not P; at north-star
    scale the
    per-wave queue-order admission sort over the full 8k-pod chunk was the
    dominant fixed cost of the ~7-wave tail):

    1. one whole-queue lite wave: cumulative-demand bucket targets + next-
       fit probes, O(P·R);
    2. sparse lite waves (`lite_window` pods each) to quiescence;
    3. sparse rescue waves (`rescue_window` pods each): a dense (K, N)
       feasibility row per window pod, feasible ones spread round-robin
       over their own feasible sets, and window pods with NO feasible node
       are retired as hopeless (sound within one solve — free capacity
       only shrinks here, so infeasible-now is infeasible-later), so junk
       pods cannot starve the window for feasible stragglers behind them.

    Correctness: scores are static, so the node ranking never changes.
    Queue-order per-node admission is the same exact sorted-segment prefix
    check the generic waterfill runs — exact on a window because only
    window pods choose in that wave and window order IS queue order.
    Completeness matches `waterfill_assign` UP TO THE WAVE BUDGET: phases
    2 and 3 each run at most `max_waves` waves (2*max_waves + 1 total),
    draining at least their window per productive wave. Hard constraints
    (fit, node queue-order admission) hold identically in all cases.

    Mirrors the reference's scoring semantics for allocatable
    (/root/reference/pkg/noderesources/resource_allocation.go:49-76) at
    wave granularity."""
    P, R = req.shape
    N = free0.shape[0]
    demand = pod_fit_demand(req)
    order_n = jnp.argsort(-raw_scores, stable=True)  # static node ranking

    #: next-fit probe depth per lite wave: a pod whose bucket node cannot
    #: fit it individually (fragmentation — cumulative coverage is
    #: necessary, not sufficient) probes the next few score-ordered nodes
    #: in the SAME O(W*R) wave instead of stalling into the dense rescue
    #: phase.
    LITE_PROBES = 4

    def window_of(free, assignment, hopeless, W):
        """First W still-active pods in queue order — the shared
        `_straggler_window` rank-compaction scatter (one copy with the
        sharded waterfill, so the window rule cannot drift)."""
        return _straggler_window(demand, pod_mask, assignment, hopeless, W)

    def lite_choice(free, idx, valid, dem_w):
        # cumulative-demand waterfill over the window (the shared
        # `_cumulative_demand_positions` bucketing; dem_w rows are already
        # masked to valid window pods)
        pos = _cumulative_demand_positions(dem_w, free, order_n)
        choice = jnp.full(idx.shape[0], -1, jnp.int32)
        for probe in range(LITE_PROBES):
            cand = order_n[jnp.minimum(pos + probe, N - 1)].astype(jnp.int32)
            fit = jnp.all(dem_w <= free[cand], axis=1)
            choice = jnp.where((choice < 0) & valid & fit, cand, choice)
        # lite misses prove nothing about true feasibility: no hopeless delta
        return choice, jnp.zeros(idx.shape[0], bool)

    def rescue_choice(free, idx, valid, dem_w):
        # dense rescue wave: straggler k takes the (k mod |feasible_k|)-th
        # best node of ITS OWN feasible set in score order. Plain argmax
        # stampedes one tied-score node (admission then drains a node's
        # worth per wave — O(stragglers/node-capacity) waves at the
        # fragmented end-game); round-robin over each pod's feasible set
        # drains the residue in O(1) dense waves. Rank 0 still gets its
        # argmax, so the common one-straggler case keeps reference scoring.
        W = idx.shape[0]
        feasible = jnp.all(
            dem_w[:, None, :] <= free[None, :, :], axis=2
        ) & valid[:, None]
        feas_sorted = feasible[:, order_n]  # score-desc node order
        counts = jnp.cumsum(feas_sorted.astype(jnp.int32), axis=1)
        total = counts[:, -1]
        k = jnp.where(total > 0, jnp.arange(W) % jnp.maximum(total, 1), 0)
        pos = jax.vmap(
            lambda c, kk: jnp.searchsorted(c, kk, side="right")
        )(counts, k)  # first score-ordered index with counts > k
        choice = jnp.where(
            valid & (total > 0),
            order_n[jnp.minimum(pos, N - 1)].astype(jnp.int32),
            -1,
        )
        # window pods with NO feasible node retire as hopeless so they stop
        # occupying the window (free only shrinks within a solve, so the
        # verdict cannot go stale)
        return choice, valid & (total == 0)

    def wave(free, assignment, hopeless, W, choice_fn):
        # O(W·R + W log W): admission runs on the (W,) window choice vector
        # via sorted segments (`_queue_order_admission_choice`) — exact,
        # because only window pods choose and window order is queue order —
        # and commits via scatter-add; never the (P, N) onehot/winners
        # matrices (at north-star scale ~84M-element temporaries per wave)
        idx, valid, dem_w = window_of(free, assignment, hopeless, W)
        choice_w, hopeless_w = choice_fn(free, idx, valid, dem_w)
        admitted = (choice_w >= 0) & _queue_order_admission_choice(
            choice_w, dem_w, free
        )
        # scatter-ADD commits (not set-with-drop): adds of zero from the
        # clamped fill rows are harmless under duplication AND partition
        # cleanly when the pod axis is sharded (the SPMD partitioner
        # mishandles windowed set-scatters)
        safe_idx = jnp.minimum(idx, P - 1)
        placed_plus = jnp.zeros(P, jnp.int32).at[safe_idx].add(
            jnp.where(admitted, choice_w + 1, 0)
        )
        assignment = jnp.where(placed_plus > 0, placed_plus - 1, assignment)
        hop_add = jnp.zeros(P, jnp.int32).at[safe_idx].add(
            hopeless_w.astype(jnp.int32)
        )
        hopeless = hopeless | (hop_add > 0)
        used = jnp.zeros_like(free).at[jnp.where(admitted, choice_w, N - 1)].add(
            jnp.where(admitted[:, None], dem_w, 0)
        )
        return (
            free - used,
            assignment,
            hopeless,
            admitted.sum(),
            hopeless_w.sum(),
        )

    # `occ` records ADMITTED pods per executed wave (whole-queue wave in
    # slot 0, then lite/rescue waves in execution order); retirements count
    # as progress but not occupancy.
    def run(free, assignment, hopeless, W, choice_fn, occ, base, budget):
        def cond(ls):
            free, assignment, hopeless, wave_idx, progressed, _ = ls
            return (
                (wave_idx < budget)
                & progressed
                & ((assignment == -1) & pod_mask & ~hopeless).any()
            )

        def body(ls):
            free, assignment, hopeless, wave_idx, _, occ = ls
            free, assignment, hopeless, adm, retired = wave(
                free, assignment, hopeless, W, choice_fn
            )
            return (
                free, assignment, hopeless, wave_idx + 1,
                (adm + retired) > 0,
                occ.at[base + wave_idx].set(adm.astype(jnp.int32)),
            )

        return jax.lax.while_loop(
            cond, body,
            (free, assignment, hopeless, jnp.int32(0), jnp.bool_(True), occ),
        )

    assignment0 = jnp.full(P, -1, jnp.int32)
    hopeless0 = jnp.zeros(P, bool)
    occ0 = jnp.zeros(2 * max_waves + 1, jnp.int32)
    Wl = min(P, lite_window)
    K = min(P, rescue_window)
    # phase 1: one whole-queue lite wave
    free, assignment, hopeless, adm0, _ = wave(
        free0, assignment0, hopeless0, P, lite_choice
    )
    occ = occ0.at[0].set(adm0.astype(jnp.int32))
    # phase 2: sparse lite waves over straggler windows
    free, assignment, hopeless, w_lite, _, occ = run(
        free, assignment, hopeless, Wl, lite_choice, occ, jnp.int32(1),
        max_waves,
    )
    # phase 3: sparse rescue waves
    free, assignment, _, w_full, _, occ = run(
        free, assignment, hopeless, K, rescue_choice, occ, 1 + w_lite,
        max_waves,
    )
    if collect_stats:
        return assignment, free, {
            "occupancy": occ, "waves": 1 + w_lite + w_full
        }
    return assignment, free


@partial(jax.jit, static_argnames=("batch_fn", "max_waves"))
def wave_assign(batch_fn, req, pod_mask, free0, max_waves: int = 8):
    """Wave-parallel placement.

    batch_fn: (free (N,R), active (P,) bool) -> (feasible (P,N), scores (P,N)).
    Per wave every still-unassigned pod picks its argmax node; within a wave,
    pods that chose the same node are admitted in queue order while the node's
    capacity lasts (an exclusive running sum per node), the rest retry next
    wave.
    """
    P, R = req.shape
    demand = pod_fit_demand(req)

    def wave(carry, _):
        free, assignment = carry
        active = (assignment == -1) & pod_mask
        feasible, scores = batch_fn(free, active)
        feasible &= active[:, None]
        masked = jnp.where(feasible, scores, jnp.int64(-(2**62)))
        choice = jnp.where(
            feasible.any(axis=1), jnp.argmax(masked, axis=1).astype(jnp.int32), -1
        )
        # queue-order admission: pod p wins iff node still fits after all
        # earlier winners of the same wave on the same node (sorted-segment
        # exact prefix sums)
        onehot = (choice[:, None] == jnp.arange(free.shape[0])[None, :]) & (
            choice[:, None] >= 0
        )  # (P, N)
        admitted = (choice >= 0) & _queue_order_admission(onehot, demand, free)
        new_assignment = jnp.where(admitted, choice, assignment)
        winners = onehot & admitted[:, None]  # (P, N)
        # per-resource masked sums (int64 matmul is unsupported on TPU)
        used = jnp.stack(
            [(winners * demand[:, r][:, None]).sum(axis=0) for r in range(R)],
            axis=-1,
        )  # (N, R)
        return (free - used, new_assignment), admitted.sum()

    (free, assignment), _ = jax.lax.scan(
        wave, (free0, jnp.full(P, -1, jnp.int32)), None, length=max_waves
    )
    return assignment, free


# ---------------------------------------------------------------------------
# Sharded targeted waterfill (shard_map body): node axis sharded, per-wave
# winner election via ring collectives
# ---------------------------------------------------------------------------


def waterfill_targeted_sharded(rank_free, node_ids, req, pod_mask,
                               axis_name: str, n_shards: int,
                               n_real: int,
                               max_waves: int = 8,
                               rescue_window: int = 512,
                               lite_window: int = 1024,
                               collect_stats: bool = False,
                               use_pallas: bool = False,
                               pallas_interpret: bool = True):
    """Shard-local body of `waterfill_assign_targeted` — runs INSIDE a
    `shard_map` with the NODE axis sharded over `axis_name` (S = `n_shards`
    shards). The node axis arrives in GLOBAL SCORE-RANK ORDER (the caller
    permutes once per solve via `parallel.solver.rank_order_inputs`), so
    shard s owns the contiguous rank block [s*BS, (s+1)*BS) and the static
    ranking `order_n` of the single-device path becomes the identity: all
    wave math happens in rank space, and the winning shard maps its rank
    back to the original node index through its `node_ids` rows.

    Per-wave cross-shard traffic is O(shards) collectives of O(window)
    payload — never a gather of the node axis:

    - cumulative-free bases for the demand buckets: per-shard block totals
      combined with an (S-1)-step `ring_exclusive_scan` (`lax.ppermute`);
    - winner election: each shard proposes its local champion RANK (or N =
      "no candidate") and `lax.pmin` elects the global minimum — the
      min-rank key reproduces the single-device searchsorted/first-fit
      choice exactly, because rank order IS score order with the
      lowest-index tie-break baked in by the stable pre-sort;
    - admission/committal: the queue-order sorted-segment prefix check runs
      replicated on the (W,) window, each shard verifies the pods that
      chose ITS nodes against its local free rows, and one `lax.psum`
      ORs the per-shard verdicts; commits then scatter ONLY into the
      owning shard's resident `rank_free` block.

    Padded rank rows (node_ids -1, zero capacity) can never win an
    election: every valid pod's fit demand carries a pods-slot of 1, so a
    zero-capacity row fails both the lite fit probes and the rescue
    feasibility row (tests/test_shard_wave.py gates the edge).

    Placements are BIT-IDENTICAL to `waterfill_assign_targeted` at any
    shard count while every cumulative-capacity float64 sum stays exact
    (< 2^53 — all test/gate shapes; beyond it, block-decomposed summation
    can round bucket POSITIONS differently than the single-device cumsum:
    a targeting heuristic only — the per-node admission sums stay exact at
    any scale, so hard constraints never depend on the bound). The
    degenerate 1-shard program emits no ring steps and is bit-identical by
    construction.

    Arguments (per shard): `rank_free` (BS, R) local block of score-rank-
    ordered free capacity (the resident carry — returned updated),
    `node_ids` (BS,) original rank-row node index (-1 = padding),
    `req` (P, R) and `pod_mask` (P,) replicated. `n_real` is the PRE-
    PADDING rank count (the single-device path's N): probe clamps must
    saturate at the worst REAL node, exactly as the unsharded
    `jnp.minimum(pos + probe, N - 1)` does — clamping into the padding
    tail would silently drop overflow pods the single-device path still
    probes against rank N-1. Returns (assignment (P,) original node
    indices, replicated; rank_free (BS, R); stats dict when
    `collect_stats`).

    Under `use_pallas` (the `SPT_PALLAS=1` opt-in, ISSUE 13) every
    cross-shard exchange runs as a `parallel.kernels` Pallas ring program
    instead of a framework collective: `ring_offsets_*` replaces
    `block_exclusive_offsets`, `elect_min` the bucket-position `pmin`, and
    `fused_election` folds the min-rank champion reduction AND the
    admission-verdict resolution into ONE kernel — the winning shard
    attaches its node id and pre-wave free row to the election payload, so
    the queue-order admission check runs REPLICATED on every shard
    (`_admission_replicated`) and the packed verdict `psum` disappears. A
    rescue wave then costs 2 fused collective programs and a lite wave 3,
    versus the 3/3 framework collectives of the lax formulation.
    Placements are bit-identical either way (same elections, same f64
    admission sums — the kernels move exact-integer limbs); call sites
    whose padded payload would exceed the kernel VMEM envelope
    (`kernels.PALLAS_MAX_ELECTION_ELEMS` — the mega whole-queue wave)
    statically keep the lax collectives. `pallas_interpret` selects the
    CPU interpret twins (the CI/differential path) versus the compiled
    on-chip kernels.
    """
    P, R = req.shape
    BS = rank_free.shape[0]
    N = BS * n_shards  # padded global rank count ("no candidate" sentinel)
    demand = pod_fit_demand(req)
    shard = jax.lax.axis_index(axis_name)
    block_start = shard * BS

    LITE_PROBES = 4

    pk = None
    if use_pallas and n_shards > 1:
        from scheduler_plugins_tpu.parallel import kernels as pk  # noqa: N813

    #: payload rows of one fused election: winner node id + the winner's
    #: free-capacity row as exact base-2^18 limbs
    PAYLOAD_ROWS = 1 + (pk.N_LIMBS * R if pk is not None else 0)

    def pallas_wave(W: int) -> bool:
        """Static per-call-site gate: this window's elections ride the
        Pallas kernels only when every buffer fits the VMEM envelope —
        otherwise the wave keeps the lax collectives (bit-identical)."""
        return (
            pk is not None
            and pk.fits_election_budget(1 + PAYLOAD_ROWS, W)
            and pk.fits_election_budget(R, W)
        )

    def winner_payload(prop_rank, free_l):
        """(1 + 3R, W) int32 payload for the shard's own proposal
        `prop_rank` (global rank in MY block, or >= N): node id + 1 and
        my pre-wave free row for that rank as limbs; zeros when not
        proposing (the sentinel key ties everywhere with zero payload)."""
        local = prop_rank - block_start
        has = (local >= 0) & (local < BS) & (prop_rank < N)
        safe = jnp.clip(local, 0, BS - 1)
        nid = jnp.where(has, node_ids[safe].astype(jnp.int32) + 1, 0)
        row = jnp.where(has[:, None], free_l[safe], 0)  # (W, R) int64
        limb_rows = pk.split_limbs(row).transpose(0, 2, 1).reshape(
            pk.N_LIMBS * R, -1
        )
        return jnp.concatenate([nid[None, :], limb_rows], axis=0)

    def unpack_payload(rows):
        """(nid (W,) int32, win_row (W, R) float64) from the elected
        payload — the winner's free row recombines exactly (limb sums are
        selected, not summed, so each limb is still < 2^18)."""
        nid = rows[0]
        limbs = rows[1:].reshape(pk.N_LIMBS, R, -1).transpose(0, 2, 1)
        return nid, pk.join_limbs(limbs)

    def lite_choice(free_l, idx, valid, dem_w):
        """Cumulative-demand bucket targets + next-fit probes, elected
        across shards: per-resource global bucket position = pmin over the
        shards' local searchsorted candidates (exact — the global cumfree
        is nondecreasing, so the first covering index lives in exactly one
        shard), then the first fitting probe = min fitting rank."""
        W = idx.shape[0]
        cumfree_l = jnp.cumsum(
            jnp.clip(free_l, 0, None).astype(jnp.float64), axis=0
        )  # (BS, R) local inclusive
        if pallas_wave(W):
            base, _ = pk.ring_offsets_f64(
                cumfree_l[-1], axis_name, n_shards,
                interpret=pallas_interpret,
            )
        else:
            base, _ = block_exclusive_offsets(
                cumfree_l[-1], axis_name, n_shards
            )  # (R,)
        abs_cf = cumfree_l + base[None, :]
        cumdem = jnp.cumsum(dem_w.astype(jnp.float64), axis=0)  # (W, R)
        loc = jax.vmap(
            lambda cf, cd: jnp.searchsorted(cf, cd, side="left"),
            in_axes=(1, 1), out_axes=1,
        )(abs_cf, cumdem)  # (W, R) local positions
        cand = jnp.where(loc < BS, block_start + loc, N)
        if pallas_wave(W):
            pos = jnp.max(
                pk.elect_min(
                    cand.T.astype(jnp.int32), axis_name, n_shards,
                    interpret=pallas_interpret,
                ),
                axis=0,
            )  # (W,) global
        else:
            pos = jnp.max(jax.lax.pmin(cand, axis_name), axis=1)  # (W,)
        ranks = jnp.minimum(
            pos[None, :] + jnp.arange(LITE_PROBES)[:, None], n_real - 1
        )  # (LP, W) — saturate at the worst REAL rank, never the padding
        local = ranks - block_start
        mine = (local >= 0) & (local < BS)
        row = free_l[jnp.clip(local, 0, BS - 1)]  # (LP, W, R)
        fit_l = mine & valid[None, :] & jnp.all(
            dem_w[None, :, :] <= row, axis=2
        )
        # first fitting probe == min fitting rank (ranks nondecreasing in
        # probe order; equal only when clamped to the same node): each
        # shard proposes its min fitting OWNED rank, pmin elects — a (W,)
        # champion reduction instead of a (LP, W) verdict exchange
        prop = jnp.min(jnp.where(fit_l, ranks, N), axis=0)  # (W,) mine
        if pallas_wave(W):
            fit_rank, pay = pk.fused_election(
                prop.astype(jnp.int32), winner_payload(prop, free_l),
                axis_name, n_shards, interpret=pallas_interpret,
            )
            choice = jnp.where(
                valid & (fit_rank < N), fit_rank.astype(jnp.int32), -1
            )
            return choice, jnp.zeros(W, bool), unpack_payload(pay)
        fit_rank = jax.lax.pmin(prop, axis_name)  # (W,)
        choice = jnp.where(
            valid & (fit_rank < N), fit_rank.astype(jnp.int32), -1
        )
        # lite misses prove nothing about true feasibility: no hopeless delta
        return choice, jnp.zeros(idx.shape[0], bool), None

    def rescue_choice(free_l, idx, valid, dem_w):
        """Dense rescue wave, sharded: each shard counts its local feasible
        nodes per window pod; a ring scan turns the counts into global
        score-order offsets (rank blocks ARE score order), the shard whose
        range covers the pod's round-robin slot k proposes its k-local-th
        feasible rank, and pmin elects it (exactly one shard proposes)."""
        W = idx.shape[0]
        feasible_l = jnp.all(
            dem_w[:, None, :] <= free_l[None, :, :], axis=2
        ) & valid[:, None]  # (W, BS)
        counts_l = feasible_l.sum(axis=1, dtype=jnp.int32)  # (W,)
        if pallas_wave(W):
            base_l, total = pk.ring_offsets_i32(
                counts_l, axis_name, n_shards, interpret=pallas_interpret,
            )
        else:
            base_l, total = block_exclusive_offsets(
                counts_l, axis_name, n_shards
            )  # (W,) each — ONE collective serves both the round-robin
            # offsets and the global feasible totals
        k = jnp.where(total > 0, jnp.arange(W) % jnp.maximum(total, 1), 0)
        k_local = (k - base_l).astype(jnp.int32)
        c_l = jnp.cumsum(feasible_l.astype(jnp.int32), axis=1)  # (W, BS)
        locpos = jax.vmap(
            lambda c, kk: jnp.searchsorted(c, kk, side="right")
        )(c_l, k_local)  # first local idx with count > k_local
        mine = (k_local >= 0) & (k_local < counts_l)
        cand = jnp.where(
            mine & valid & (total > 0), block_start + locpos, N
        )
        if pallas_wave(W):
            # whenever total > 0 some shard proposes the k-th feasible
            # rank (k < total), so the elected rank is always a REAL
            # feasible node and the n_real clamp below is a no-op there —
            # the payload (proposer's node id + free row) stays consistent
            rank, pay = pk.fused_election(
                cand.astype(jnp.int32), winner_payload(cand, free_l),
                axis_name, n_shards, interpret=pallas_interpret,
            )
            choice = jnp.where(
                valid & (total > 0),
                jnp.minimum(rank, n_real - 1).astype(jnp.int32), -1,
            )
            return choice, valid & (total == 0), unpack_payload(pay)
        rank = jax.lax.pmin(cand, axis_name)  # (W,)
        choice = jnp.where(
            valid & (total > 0),
            jnp.minimum(rank, n_real - 1).astype(jnp.int32), -1,
        )
        # window pods with NO feasible node anywhere retire as hopeless
        # (free only shrinks within a solve, so the verdict cannot go stale)
        return choice, valid & (total == 0), None

    def _admission_segments(choice, dem_w):
        """The ONE copy of the queue-order admission sort/segment math
        both formulations below share — lax-vs-pallas bit-identity rests
        on these staying byte-equivalent, so neither path may inline its
        own: (order, seg, within) where `order` is the stable
        choice-then-queue-position sort, `seg` the sorted chosen ranks
        (N for unchosen), and `within` the inclusive per-segment f64
        demand prefix."""
        W = choice.shape[0]
        seg_choice = jnp.where(choice >= 0, choice, N)
        order = jnp.argsort(
            seg_choice.astype(jnp.int64) * W + jnp.arange(W)
        )
        seg = seg_choice[order]
        first = jnp.concatenate([jnp.array([True]), seg[1:] != seg[:-1]])
        within = _segment_prefix(dem_w[order].astype(jnp.float64), first)
        return order, seg, within

    def queue_admission_local(choice, dem_w, free_l):
        """`_queue_order_admission_choice` with the free rows sharded: the
        sorted-segment prefix math is replicated (choice/demand are), each
        shard checks the pods whose chosen rank lies in its block against
        its local rows. Returns the LOCAL sorted-order verdicts + the sort
        permutation — the wave ORs the verdicts across shards in the same
        psum that elects the winner node ids (each chosen rank is owned by
        exactly one shard, so a sum is an OR)."""
        order, seg, within = _admission_segments(choice, dem_w)
        local = seg - block_start
        mine = (local >= 0) & (local < BS) & (seg < N)
        free_row = free_l[jnp.clip(local, 0, BS - 1)].astype(jnp.float64)
        ok_l = mine & jnp.all(within <= free_row, axis=1)
        return ok_l, order

    def _admission_replicated(choice, dem_w, win_row):
        """`queue_admission_local` + verdict psum collapsed to REPLICATED
        math (the pallas path): the winner's pre-wave free row arrived
        with the election payload, so every shard evaluates the same
        sorted-segment prefix check against the same f64 rows — identical
        verdicts to the owner-checks-then-psum formulation, zero
        collectives."""
        Wn = choice.shape[0]
        order, seg, within = _admission_segments(choice, dem_w)
        ok_sorted = (seg < N) & jnp.all(within <= win_row[order], axis=1)
        return (choice >= 0) & jnp.zeros(Wn, bool).at[order].set(ok_sorted)

    def wave(free_l, assignment, hopeless, W, choice_fn):
        idx, valid, dem_w = _straggler_window(
            demand, pod_mask, assignment, hopeless, W
        )
        choice, hopeless_w, payload = choice_fn(free_l, idx, valid, dem_w)
        Wn = choice.shape[0]
        local = choice - block_start
        own = (choice >= 0) & (local >= 0) & (local < BS)
        if payload is not None:
            # pallas path: the fused election already delivered the
            # winner's node id and free row — admission is replicated
            # math, no further collective this wave
            nid, win_row = payload
            admitted = _admission_replicated(choice, dem_w, win_row)
        else:
            ok_l, order = queue_admission_local(choice, dem_w, free_l)
            # rank -> original node id: the owning shard contributes id+1
            # for its owned CHOICES (independent of admission, so it packs
            # into the same collective; -1 padding rows can never be
            # chosen, so id+1 >= 1 on every elected winner)
            nid_l = jnp.where(
                own,
                node_ids[jnp.clip(local, 0, BS - 1)].astype(jnp.int32) + 1,
                0,
            )
            # ONE barrier elects admission verdicts (sorted order) AND
            # winner node ids (window order): psum is elementwise, the two
            # rows just ride together
            packed = jax.lax.psum(
                jnp.stack([ok_l.astype(jnp.int32), nid_l]), axis_name
            )
            admitted = (choice >= 0) & jnp.zeros(Wn, bool).at[order].set(
                packed[0] > 0
            )
            nid = packed[1]  # (W,) node_id + 1, replicated
        ownc = admitted & own
        safe_idx = jnp.minimum(idx, P - 1)
        placed_plus = jnp.zeros(P, jnp.int32).at[safe_idx].add(
            jnp.where(admitted, nid, 0)
        )
        assignment = jnp.where(placed_plus > 0, placed_plus - 1, assignment)
        hop_add = jnp.zeros(P, jnp.int32).at[safe_idx].add(
            hopeless_w.astype(jnp.int32)
        )
        hopeless = hopeless | (hop_add > 0)
        # commit scatters ONLY into the owning shard's resident block
        used_l = jnp.zeros_like(free_l).at[
            jnp.where(ownc, jnp.clip(local, 0, BS - 1), BS - 1)
        ].add(jnp.where(ownc[:, None], dem_w, 0))
        return (
            free_l - used_l, assignment, hopeless,
            admitted.sum(), hopeless_w.sum(),
        )

    def run(free_l, assignment, hopeless, W, choice_fn, occ, base, budget):
        """Wave loop to `budget` — the loop state is replicated except the
        local free block, so every shard takes identical trips."""
        def cond(ls):
            free_l, assignment, hopeless, wave_idx, progressed, _ = ls
            return (
                (wave_idx < budget)
                & progressed
                & ((assignment == -1) & pod_mask & ~hopeless).any()
            )

        def body(ls):
            free_l, assignment, hopeless, wave_idx, _, occ = ls
            free_l, assignment, hopeless, adm, retired = wave(
                free_l, assignment, hopeless, W, choice_fn
            )
            return (
                free_l, assignment, hopeless, wave_idx + 1,
                (adm + retired) > 0,
                occ.at[base + wave_idx].set(adm.astype(jnp.int32)),
            )

        return jax.lax.while_loop(
            cond, body,
            (free_l, assignment, hopeless, jnp.int32(0), jnp.bool_(True),
             occ),
        )

    assignment0 = jnp.full(P, -1, jnp.int32)
    hopeless0 = jnp.zeros(P, bool)
    occ0 = jnp.zeros(2 * max_waves + 1, jnp.int32)
    Wl = min(P, lite_window)
    K = min(P, rescue_window)
    # phase 1: one whole-queue lite wave
    free_l, assignment, hopeless, adm0, _ = wave(
        rank_free, assignment0, hopeless0, P, lite_choice
    )
    occ = occ0.at[0].set(adm0.astype(jnp.int32))
    # phase 2: sparse lite waves over straggler windows
    free_l, assignment, hopeless, w_lite, _, occ = run(
        free_l, assignment, hopeless, Wl, lite_choice, occ, jnp.int32(1),
        max_waves,
    )
    # phase 3: sparse rescue waves
    free_l, assignment, _, w_full, _, occ = run(
        free_l, assignment, hopeless, K, rescue_choice, occ, 1 + w_lite,
        max_waves,
    )
    if collect_stats:
        return assignment, free_l, {
            "occupancy": occ, "waves": 1 + w_lite + w_full
        }
    return assignment, free_l
