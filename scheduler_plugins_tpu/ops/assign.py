"""Placement: turn per-pod feasibility + scores into node assignments.

Two modes:

- `greedy_assign` — bit-faithful to the reference's one-pod-at-a-time cycle:
  a `lax.scan` over the pod queue where each step filters/scores against the
  *current* free capacity and commits the winner before the next pod runs
  (SURVEY.md §7 "sequential semantics"). Tie-break: lowest node index (the
  upstream framework randomizes among equals; we pin determinism instead).

- `wave_assign` — the TPU-throughput mode: scores are computed for the whole
  batch at once, pods pick their argmax node, conflicts are resolved by queue
  order within the wave via a much shorter scan over *waves*. Placements can
  differ from sequential mode when a wave overcommits a node; the caller
  chooses the trade-off.

Both return assignment = (P,) int32 node index, -1 for unschedulable.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp

from scheduler_plugins_tpu.ops.fit import pod_fit_demand

#: signature: (free (N,R), pod_index int32) -> (feasible (N,) bool, score (N,) int64)
StepFn = Callable


def _pick(feasible, scores):
    """argmax score among feasible nodes, lowest index on ties; -1 if none."""
    masked = jnp.where(feasible, scores, jnp.int64(-(2**62)))
    best = jnp.argmax(masked)
    return jnp.where(feasible.any(), best.astype(jnp.int32), jnp.int32(-1))


@partial(jax.jit, static_argnames=("step_fn",))
def greedy_assign(step_fn: StepFn, req, pod_mask, free0):
    """Sequential greedy placement.

    step_fn computes this pod's (feasible, scores) against current free
    capacity; the scan then commits `req` (with the pod-count slot set to 1)
    to the chosen node.
    """
    demand = pod_fit_demand(req)  # (P, R)
    P = req.shape[0]

    def body(free, p):
        feasible, scores = step_fn(free, p)
        choice = _pick(feasible & pod_mask[p], scores)
        delta = jnp.where(
            (jnp.arange(free.shape[0]) == choice)[:, None], demand[p], 0
        )
        return free + jnp.where(choice >= 0, -delta, 0), choice

    free, assignment = jax.lax.scan(body, free0, jnp.arange(P))
    return assignment, free


@partial(jax.jit, static_argnames=("batch_fn", "max_waves"))
def wave_assign(batch_fn, req, pod_mask, free0, max_waves: int = 8):
    """Wave-parallel placement.

    batch_fn: (free (N,R), active (P,) bool) -> (feasible (P,N), scores (P,N)).
    Per wave every still-unassigned pod picks its argmax node; within a wave,
    pods that chose the same node are admitted in queue order while the node's
    capacity lasts (an exclusive running sum per node), the rest retry next
    wave.
    """
    P, R = req.shape
    demand = pod_fit_demand(req)

    def wave(carry, _):
        free, assignment = carry
        active = (assignment == -1) & pod_mask
        feasible, scores = batch_fn(free, active)
        feasible &= active[:, None]
        masked = jnp.where(feasible, scores, jnp.int64(-(2**62)))
        choice = jnp.where(
            feasible.any(axis=1), jnp.argmax(masked, axis=1).astype(jnp.int32), -1
        )
        # queue-order admission: pod p wins iff node still fits after all
        # earlier winners of the same wave on the same node. Unrolled over the
        # small static R axis to keep peak memory at (P, N), not (P, N, R).
        onehot = (choice[:, None] == jnp.arange(free.shape[0])[None, :]) & (
            choice[:, None] >= 0
        )  # (P, N)
        fits_after = jnp.ones_like(onehot)
        for r in range(R):
            prefix_r = jnp.cumsum(onehot * demand[:, r][:, None], axis=0)
            fits_after &= prefix_r <= free[None, :, r]
        admitted = (choice >= 0) & jnp.take_along_axis(
            fits_after, jnp.maximum(choice, 0)[:, None], axis=1
        ).squeeze(1)
        new_assignment = jnp.where(admitted, choice, assignment)
        winners = onehot & admitted[:, None]  # (P, N)
        # per-resource masked sums (int64 matmul is unsupported on TPU)
        used = jnp.stack(
            [(winners * demand[:, r][:, None]).sum(axis=0) for r in range(R)],
            axis=-1,
        )  # (N, R)
        return (free - used, new_assignment), admitted.sum()

    (free, assignment), _ = jax.lax.scan(
        wave, (free0, jnp.full(P, -1, jnp.int32)), None, length=max_waves
    )
    return assignment, free
