"""Trimaran load-aware score curves, vectorized over nodes.

Each function mirrors one reference plugin's math bit-for-bit (float formulas,
Go `math.Round` half-away rounding, int64 truncation):

- `tlp_score`    TargetLoadPacking piecewise-linear best-fit packing curve
  (/root/reference/pkg/trimaran/targetloadpacking/targetloadpacking.go:107-193).
- `lvrb_score`   LoadVariationRiskBalancing risk = (mu + margin*sigma^(1/s))/2
  (/root/reference/pkg/trimaran/loadvariationriskbalancing/analysis.go:34-69,
  loadvariationriskbalancing.go:94-121).
- `lroc_score`   LowRiskOverCommitment: w*riskLimit + (1-w)*riskLoad with the
  beta-distribution overuse probability
  (/root/reference/pkg/trimaran/lowriskovercommitment/lowriskovercommitment.go:157-256,
  beta.go:106-191).
- `peaks_score`  power-jump K1*(e^(K2*p) - e^(K2*x)) * 1e15
  (/root/reference/pkg/trimaran/peaks/peaks.go:103-196).

All utilisation inputs are percentages of capacity, exactly as the
load-watcher reports them (resourcestats.go:33-107).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.special import betainc

from scheduler_plugins_tpu.utils.intmath import round_half_away

MAX_SCORE = 100.0


def tlp_score(
    cpu_avg_pct,
    cpu_valid,
    missing_cpu_millis,
    node_cpu_capacity_millis,
    pod_predicted_millis,
    target_pct: float = 40.0,
):
    """(N,) TargetLoadPacking scores for one pod.

    predicted% = 100 * (measured + missing-from-cache + pod) / capacity;
    score rises linearly target->100 at the target utilisation, then falls
    steeply to 0 at 100%, 0 beyond (targetloadpacking.go:150-186). Nodes
    without metrics score the minimum (avoided).
    """
    cap = node_cpu_capacity_millis.astype(jnp.float64)
    util_millis = cpu_avg_pct / 100.0 * cap
    predicted = jnp.where(
        cap != 0,
        100.0
        * (util_millis + missing_cpu_millis + pod_predicted_millis)
        / jnp.maximum(cap, 1.0),
        0.0,
    )
    rising = round_half_away(
        (100.0 - target_pct) * predicted / target_pct + target_pct
    )
    falling = round_half_away(target_pct * (100.0 - predicted) / (100.0 - target_pct))
    score = jnp.where(
        predicted > target_pct,
        jnp.where(predicted > 100.0, 0, falling),
        rising,
    )
    return jnp.where(cpu_valid, score, 0).astype(jnp.int64)


def _root_power(sigma, sensitivity):
    """sigma^(1/sensitivity) with Go math.Pow parity: Pow special-cases
    y == 0.5 to Sqrt (and y == 1/2-integer cases reduce exactly), which can
    differ from a generic pow by 1 ulp — enough to flip an int truncation
    at a score boundary (caught by the analysis_test.go vectors). Negative
    sensitivity skips the root (analysis.go:48-50); 0 means Pow(x, +Inf)."""
    if sensitivity == 0:
        return jnp.where(sigma >= 1.0, 1.0, 0.0)
    if sensitivity < 0:
        return sigma
    exponent = 1.0 / sensitivity
    if exponent == 1.0:
        return sigma
    if exponent == 0.5:
        return jnp.sqrt(sigma)
    if exponent == 2.0:
        return sigma * sigma
    return jnp.power(sigma, exponent)


def _risk_component(avg_pct, std_pct, capacity, req, margin, sensitivity):
    """computeScore (analysis.go:41-69) in [0, 100], float64."""
    cap = capacity.astype(jnp.float64)
    used = jnp.clip(avg_pct / 100.0 * cap, 0.0, cap)
    stdev = jnp.clip(std_pct / 100.0 * cap, 0.0, cap)
    req = jnp.maximum(jnp.asarray(req, jnp.float64), 0.0)
    mu = jnp.clip((used + req) / jnp.maximum(cap, 1.0), 0.0, 1.0)
    sigma = jnp.clip(stdev / jnp.maximum(cap, 1.0), 0.0, 1.0)
    sigma = _root_power(sigma, sensitivity)
    sigma = jnp.clip(sigma * margin, 0.0, 1.0)
    risk = (mu + sigma) / 2.0
    score = (1.0 - risk) * MAX_SCORE
    return jnp.where(cap > 0, score, 0.0)


def lvrb_score(
    metrics,
    node_cpu_capacity_millis,
    node_mem_capacity_bytes,
    pod_cpu_millis,
    pod_mem_bytes,
    margin: float = 1.0,
    sensitivity: float = 1.0,
):
    """(N,) LoadVariationRiskBalancing scores: min(cpuScore, memScore) when
    both metrics exist, max of the valid one otherwise
    (loadvariationriskbalancing.go:98-121)."""
    cpu = _risk_component(
        metrics.cpu_avg, metrics.cpu_std, node_cpu_capacity_millis,
        pod_cpu_millis, margin, sensitivity,
    )
    mem = _risk_component(
        metrics.mem_avg, metrics.mem_std, node_mem_capacity_bytes,
        pod_mem_bytes, margin, sensitivity,
    )
    cpu = jnp.where(metrics.cpu_valid, cpu, 0.0)
    mem = jnp.where(metrics.mem_valid, mem, 0.0)
    both = metrics.cpu_valid & metrics.mem_valid
    total = jnp.where(both, jnp.minimum(cpu, mem), jnp.maximum(cpu, mem))
    return round_half_away(total)


# ---------------------------------------------------------------------------
# Whole-batch score curves (parallel.solver throughput path)
# ---------------------------------------------------------------------------
#
# Both TLP and LVRB depend on the pod only through a SCALAR (predicted CPU
# millis / requested cpu+mem), so each node's score is a piecewise-linear
# curve in that scalar. The batch variants precompute the per-node curve
# inputs in f64 (N,) — identical math to the per-pod path — and run the
# (P, N) broadcast stage in f32 select+FMA form: ~10 fused passes instead
# of the ~100 f64 (P, N) ops the vmapped per-pod chain lowers to. f32
# rounding at round-half-away knife edges can shift a score by +/-1 vs the
# parity path — batch-only, drift-metered (test-gated to |delta| <= 1);
# the sequential solve never uses these.


#: pod-chunk width for the (P, N) broadcast stages: the curve's ~10 f32
#: intermediates stay cache-resident per chunk instead of each making a
#: full (P, N) memory pass (the XLA CPU fuser materializes them); on TPU a
#: (128, N) step is still plenty of VPU work per loop iteration
_CURVE_CHUNK = 128


def _chunked_over_pods(curve_fn, pod_values, P):
    """Apply `curve_fn((C, ...) pod rows) -> (C, N)` over pod chunks via
    lax.map; pads axis 0 of `pod_values` (any trailing dims) to a chunk
    multiple and trims the output."""
    import jax

    C = min(_CURVE_CHUNK, P)
    padded = ((P + C - 1) // C) * C
    pad_widths = [(0, padded - P)] + [(0, 0)] * (pod_values.ndim - 1)
    xs = jnp.pad(pod_values, pad_widths).reshape(
        (-1, C) + pod_values.shape[1:]
    )
    out = jax.lax.map(curve_fn, xs)  # (P//C, C, N)
    return out.reshape(padded, -1)[:P]


def tlp_score_batch(
    cpu_avg_pct,
    cpu_valid,
    missing_cpu_millis,
    node_cpu_capacity_millis,
    pod_predicted_millis_all,
    target_pct: float = 40.0,
):
    """(P, N) TargetLoadPacking scores for the whole batch (same curve as
    `tlp_score`, targetloadpacking.go:150-186)."""
    cap = node_cpu_capacity_millis.astype(jnp.float64)
    base = (
        cpu_avg_pct / 100.0 * cap + missing_cpu_millis
    ).astype(jnp.float32)  # (N,)
    inv = (100.0 / jnp.maximum(cap, 1.0)).astype(jnp.float32)  # (N,)
    cap_zero = cap != 0

    def curve(x_chunk):
        x = x_chunk.astype(jnp.float32)[:, None]  # (C, 1)
        predicted = jnp.where(
            cap_zero[None, :], (base[None, :] + x) * inv[None, :], 0.0
        )
        rising = _round_half_away_f32(
            (100.0 - target_pct) / target_pct * predicted + target_pct
        )
        falling = _round_half_away_f32(
            target_pct / (100.0 - target_pct) * (100.0 - predicted)
        )
        score = jnp.where(
            predicted > target_pct,
            jnp.where(predicted > 100.0, 0, falling),
            rising,
        )
        return jnp.where(cpu_valid[None, :], score, 0)

    return _chunked_over_pods(
        curve, pod_predicted_millis_all, pod_predicted_millis_all.shape[0]
    )


def _round_half_away_f32(x):
    """`round_half_away` staying in f32/int32 (batch stage) — the same
    exact fractional-part compare as the f64 parity version: `x + 0.5`
    itself rounds in f32 too (the largest f32 below 0.5 plus 0.5 is 1.0),
    and `x - floor(x)` is exact in any binary float format (Sterbenz for
    x >= 1, floor == 0 below)."""
    f = jnp.floor(x)
    pos = jnp.where(x - f >= 0.5, f + 1, f)
    c = jnp.ceil(x)
    neg = jnp.where(c - x >= 0.5, c - 1, c)
    return jnp.where(x >= 0, pos, neg).astype(jnp.int32)


def _risk_curve_coeffs(avg_pct, std_pct, capacity, margin, sensitivity):
    """Per-node mu base and sigma in f64 (identical to the parity path),
    demoted to the f32 coefficients the chunked stage consumes."""
    cap = capacity.astype(jnp.float64)
    used = jnp.clip(avg_pct / 100.0 * cap, 0.0, cap)
    stdev = jnp.clip(std_pct / 100.0 * cap, 0.0, cap)
    sigma = jnp.clip(stdev / jnp.maximum(cap, 1.0), 0.0, 1.0)
    sigma = _root_power(sigma, sensitivity)
    sigma = jnp.clip(sigma * margin, 0.0, 1.0)
    inv = (1.0 / jnp.maximum(cap, 1.0)).astype(jnp.float32)
    used32 = used.astype(jnp.float32)
    half_sig = (50.0 * sigma).astype(jnp.float32)  # (N,)
    return used32, inv, half_sig, cap > 0


def lvrb_score_batch(
    metrics,
    node_cpu_capacity_millis,
    node_mem_capacity_bytes,
    pod_cpu_millis_all,
    pod_mem_bytes_all,
    margin: float = 1.0,
    sensitivity: float = 1.0,
):
    """(P, N) LoadVariationRiskBalancing scores for the whole batch
    (loadvariationriskbalancing.go:98-121)."""
    c_used, c_inv, c_sig, c_pos = _risk_curve_coeffs(
        metrics.cpu_avg, metrics.cpu_std, node_cpu_capacity_millis,
        margin, sensitivity,
    )
    m_used, m_inv, m_sig, m_pos = _risk_curve_coeffs(
        metrics.mem_avg, metrics.mem_std, node_mem_capacity_bytes,
        margin, sensitivity,
    )
    P = pod_cpu_millis_all.shape[0]
    both = metrics.cpu_valid & metrics.mem_valid
    # pack the two pod scalars as one (P, 2) input for the chunk map
    pods2 = jnp.stack(
        [jnp.maximum(pod_cpu_millis_all.astype(jnp.float32), 0.0),
         jnp.maximum(pod_mem_bytes_all.astype(jnp.float32), 0.0)],
        axis=1,
    )

    def component(req, used, inv, half_sig, pos):
        mu = jnp.clip((used[None, :] + req) * inv[None, :], 0.0, 1.0)
        score = 100.0 - 50.0 * mu - half_sig[None, :]
        return jnp.where(pos[None, :], score, 0.0)

    def curve(chunk):  # (C, 2) -> (C, N)
        cpu = component(chunk[:, 0:1], c_used, c_inv, c_sig, c_pos)
        mem = component(chunk[:, 1:2], m_used, m_inv, m_sig, m_pos)
        cpu = jnp.where(metrics.cpu_valid[None, :], cpu, 0.0)
        mem = jnp.where(metrics.mem_valid[None, :], mem, 0.0)
        total = jnp.where(
            both[None, :], jnp.minimum(cpu, mem), jnp.maximum(cpu, mem)
        )
        return _round_half_away_f32(total)

    return _chunked_over_pods(curve, pods2, P)


# ---------------------------------------------------------------------------
# LowRiskOverCommitment
# ---------------------------------------------------------------------------

MAX_VARIANCE_ALLOWANCE = 0.99  # lowriskovercommitment.go:47
_TINY = jnp.finfo(jnp.float64).tiny


def _beta_cdf(threshold, alpha, beta_p, valid):
    """DistributionFunction (beta.go:80-104): I_x(a,b) with x==0 -> 0,
    x==1 -> 1; invalid fits propagate `valid`=False."""
    x = jnp.clip(threshold, 0.0, 1.0)
    safe_a = jnp.where(valid, alpha, 1.0)
    safe_b = jnp.where(valid, beta_p, 1.0)
    cdf = betainc(safe_a, safe_b, x)
    cdf = jnp.where(x <= 0.0, 0.0, jnp.where(x >= 1.0, 1.0, cdf))
    return cdf


def compute_probability(mu, sigma, threshold):
    """ComputeProbability (beta.go:174-191): P[util <= threshold] under a
    beta distribution moment-matched to (mu, sigma).

    Returns (prob, fit_valid, alpha, beta) — fit_valid mirrors
    `fitDistribution != nil` for the conditioning step."""
    m1 = mu
    variance = sigma * sigma
    m2 = variance + mu * mu
    # MatchMoments validity (beta.go:107-117)
    fit_valid = (
        (m1 >= 0.0) & (m1 <= 1.0) & (variance >= 0.0) & (variance < m1 * (1.0 - m1))
    )
    temp = jnp.maximum(m1 * (1.0 - m1) / jnp.maximum(variance, _TINY) - 1.0, _TINY)
    alpha = m1 * temp
    beta_p = (1.0 - m1) * temp

    degenerate_one = (mu == 0.0) | ((sigma == 0.0) & (mu <= threshold))
    degenerate_zero = (sigma == 0.0) & (mu > threshold)
    fit_valid = fit_valid & ~degenerate_one & ~degenerate_zero

    cdf = _beta_cdf(threshold, alpha, beta_p, fit_valid)
    cdf = jnp.where(jnp.isnan(cdf), 1.0, cdf)  # NaN CDF -> 1 (beta.go:189)
    prob = jnp.where(
        degenerate_one,
        1.0,
        jnp.where(degenerate_zero, 0.0, jnp.where(fit_valid, cdf, 0.0)),
    )
    return prob, fit_valid, alpha, beta_p


def _risk_one_resource(
    avg_pct, std_pct, valid, capacity, node_req, node_limit,
    node_req_minus_pod, node_limit_minus_pod,
    smoothing_window, risk_limit_weight,
):
    """computeRisk (lowriskovercommitment.go:173-256) for one resource,
    vectorized over nodes. Quantities are int64 in native units."""
    cap = capacity.astype(jnp.float64)
    req = node_req.astype(jnp.float64)
    limit = node_limit.astype(jnp.float64)
    req_minus = node_req_minus_pod.astype(jnp.float64)
    limit_minus = node_limit_minus_pod.astype(jnp.float64)

    # (1) riskLimit: overcommit potential
    risk_limit = jnp.where(
        limit > cap,
        (limit - cap) / jnp.maximum(limit - req, _TINY),
        0.0,
    )

    # (2) riskLoad: measured overcommitment via beta fit
    used = jnp.clip(avg_pct / 100.0 * cap, 0.0, cap)
    stdev = jnp.clip(std_pct / 100.0 * cap, 0.0, cap)
    mu = jnp.clip(used / jnp.maximum(cap, 1.0), 0.0, 1.0)
    sigma = jnp.clip(stdev / jnp.maximum(cap, 1.0), 0.0, 1.0)
    sigma = sigma * jnp.sqrt(jnp.float64(smoothing_window))
    max_var = jnp.where((mu > 0.0) & (mu < 1.0), mu * (1.0 - mu), 0.0)
    sigma = jnp.minimum(sigma, jnp.sqrt(max_var * MAX_VARIANCE_ALLOWANCE))

    alloc_threshold = jnp.clip(req_minus / jnp.maximum(cap, 1.0), 0.0, 1.0)
    alloc_prob, fit_valid, alpha, beta_p = compute_probability(
        mu, sigma, alloc_threshold
    )
    # conditioning when limits don't overcommit (lowriskovercommitment.go:232-245)
    conditioned = (limit_minus < cap) & (req_minus <= limit_minus)
    limit_threshold = limit_minus / jnp.maximum(cap, 1.0)
    limit_prob = _beta_cdf(limit_threshold, alpha, beta_p, fit_valid)
    cond_prob = jnp.where(
        limit_threshold == 0.0,
        1.0,
        jnp.where(
            fit_valid & (limit_prob > 0.0),
            jnp.clip(alloc_prob / jnp.maximum(limit_prob, _TINY), 0.0, 1.0),
            alloc_prob,
        ),
    )
    alloc_prob = jnp.where(conditioned, cond_prob, alloc_prob)
    risk_load = jnp.where(valid, 1.0 - alloc_prob, 0.0)

    total = risk_limit_weight * risk_limit + (1.0 - risk_limit_weight) * risk_load
    return jnp.clip(total, 0.0, 1.0)


def lroc_score(
    metrics,
    node_cpu_capacity,
    node_mem_capacity,
    node_req_cpu,
    node_req_mem,
    node_limit_cpu,
    node_limit_mem,
    pod_req_cpu,
    pod_req_mem,
    pod_limit_cpu,
    pod_limit_mem,
    smoothing_window: int = 5,
    risk_limit_weight_cpu: float = 0.5,
    risk_limit_weight_mem: float = 0.5,
):
    """(N,) LowRiskOverCommitment scores: round((1 - max(riskCPU, riskMem)) * 100).

    node_req_*/node_limit_* EXCLUDE the pending pod (the minus-pod values);
    the with-pod sums are formed here, with requests capped at capacity
    (resourcestats.go:163-225)."""
    req_cpu = jnp.minimum(node_req_cpu + pod_req_cpu, node_cpu_capacity)
    req_mem = jnp.minimum(node_req_mem + pod_req_mem, node_mem_capacity)
    req_cpu_minus = jnp.minimum(node_req_cpu, node_cpu_capacity)
    req_mem_minus = jnp.minimum(node_req_mem, node_mem_capacity)
    # the pending pod's limits are clamped to >= its requests, like every
    # other pod's (SetMaxLimits in CreatePodResourcesStateData)
    limit_cpu = node_limit_cpu + jnp.maximum(pod_limit_cpu, pod_req_cpu)
    limit_mem = node_limit_mem + jnp.maximum(pod_limit_mem, pod_req_mem)

    risk_cpu = _risk_one_resource(
        metrics.cpu_avg, metrics.cpu_std, metrics.cpu_valid,
        node_cpu_capacity, req_cpu, limit_cpu, req_cpu_minus, node_limit_cpu,
        smoothing_window, risk_limit_weight_cpu,
    )
    risk_mem = _risk_one_resource(
        metrics.mem_avg, metrics.mem_std, metrics.mem_valid,
        node_mem_capacity, req_mem, limit_mem, req_mem_minus, node_limit_mem,
        smoothing_window, risk_limit_weight_mem,
    )
    rank = 1.0 - jnp.maximum(risk_cpu, risk_mem)
    return round_half_away(rank * MAX_SCORE)


def peaks_score(
    cpu_avg_pct,
    cpu_valid,
    node_cpu_capacity_millis,
    pod_cpu_millis,
    k1,
    k2,
):
    """(N,) Peaks raw scores: power jump to be minimized, scaled by 1e15 and
    truncated to int64 (peaks.go:103-146). predicted > 100% or missing
    metrics -> MinNodeScore."""
    cap = node_cpu_capacity_millis.astype(jnp.float64)
    util_millis = cpu_avg_pct / 100.0 * cap
    predicted = jnp.where(
        cap != 0, 100.0 * (util_millis + pod_cpu_millis) / jnp.maximum(cap, 1.0), 0.0
    )
    jump = k1 * (jnp.exp(k2 * predicted) - jnp.exp(k2 * cpu_avg_pct))
    score = jnp.trunc(jump * 1e15).astype(jnp.int64)
    return jnp.where(cpu_valid & (predicted <= 100.0), score, 0)
