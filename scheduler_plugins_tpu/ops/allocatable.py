"""NodeResourcesAllocatable score (Score-only plugin).

Reference behavior (/root/reference/pkg/noderesources/allocatable.go:117-168,
resource_allocation.go:49-100): per node,

    nodeScore = ( sum_r sign * allocatable_r * weight_r ) / sum_r weight_r

with sign = -1 for Least mode, +1 for Most, Go integer division (truncates
toward zero — scores are negative in Least mode), then min-max normalized to
[0, 100]. Default weights: cpu(milli) 1<<20, memory(bytes) 1
(resource_allocation.go:36). The score depends only on node allocatables, so
the whole (P, N) matrix is one broadcast row per cycle.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from scheduler_plugins_tpu.ops.normalize import minmax_normalize
from scheduler_plugins_tpu.utils.intmath import go_div

MODE_LEAST = -1
MODE_MOST = 1


def allocatable_scores(alloc, weights, mode_sign=MODE_LEAST):
    """(N, R) allocatable x (R,) weights -> (N,) raw scores (pre-normalize)."""
    alloc = jnp.asarray(alloc)
    weights = jnp.asarray(weights, dtype=jnp.int64)
    weight_sum = jnp.maximum(weights.sum(), 1)
    node_score = (mode_sign * alloc * weights[None, :]).sum(axis=-1)
    return go_div(node_score, weight_sum)


@jax.jit
def demote_scores_int32(raw):
    """Order-preserving demotion of raw int64 scores to int32 for the heavy
    (P, N) normalize (int64 is emulated u32 pairs on TPU): a dynamic right
    shift squeezes magnitudes under 2^23 so (score - lo) * 100 cannot
    overflow int32 for ANY weight configuration. Shifting may merge
    near-ties; the sequential parity path stays full int64.

    A named jit boundary ON PURPOSE (XLA inlines it — no runtime cost):
    the < 2^23 result bound is enforced by the DYNAMIC shift, which an
    interval lattice cannot see, so `tools/kernel_audit.py` KA003
    blesses the pjit call by name via `api.bounds.EXACT_FN_BOUNDS`
    (declared result bound 2^24) instead of flagging the demotion."""
    max_abs = jnp.max(jnp.abs(raw))
    bits = jnp.ceil(jnp.log2(max_abs.astype(jnp.float64) + 1.0))
    shift = jnp.maximum(bits - 23, 0).astype(jnp.int64)
    return (raw >> shift).astype(jnp.int32)


def allocatable_score_matrix(alloc, weights, mode_sign, feasible):
    """Full plugin output: (P, N) normalized scores given (P, N) feasibility.

    Normalization runs per pod over that pod's feasible nodes, mirroring the
    framework calling NormalizeScore on each pod's NodeScoreList.
    """
    raw = allocatable_scores(alloc, weights, mode_sign)  # (N,)
    per_pod = jnp.broadcast_to(raw[None, :], feasible.shape)
    return minmax_normalize(per_pod, feasible)
