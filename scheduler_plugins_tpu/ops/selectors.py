"""Framework-maintained selector/topology-domain carries.

The live per-(track, domain) pod counts (`SolverState.sel_counts`) and the
anti-affinity domain-presence bits (`SolverState.anti_domains`) are read by
BOTH PodTopologySpread and InterPodAffinity (plugins/intree.py) — so the
commit is a single built-in step of the solve (like the built-in capacity
Reserve), not a per-plugin `commit` that would double-apply when both
plugins are enabled.

Tables come from `state.scheduling.SchedulingState`:
    pend_match (S, P)  pod q matches selector group s
    track_sel/track_topo (TR,)  track -> (selector group, topology key)
    topo_code (K, N)  node -> domain code under key k (-1 = key absent)
    exist_anti_{sel,topo} (E,), exist_anti_carrier (E, P)
"""

from __future__ import annotations

import jax.numpy as jnp


def commit_tracks(state, sched, p, choice):
    """Fold pod `p`'s placement on `choice` (-1 = none) into the carries."""
    if state.sel_counts is not None and sched.track_base is not None:
        dom = sched.topo_code[sched.track_topo, choice]  # (TR,)
        inc = sched.pend_match[sched.track_sel, p] & (choice >= 0) & (dom >= 0)
        TR = state.sel_counts.shape[0]
        state = state.replace(
            sel_counts=state.sel_counts.at[
                jnp.arange(TR), jnp.maximum(dom, 0)
            ].add(inc.astype(state.sel_counts.dtype))
        )
    if state.anti_domains is not None and sched.exist_anti_sel is not None:
        dom = sched.topo_code[sched.exist_anti_topo, choice]  # (E,)
        mark = (
            sched.exist_anti_carrier[:, p] & (choice >= 0) & (dom >= 0)
        )
        E = state.anti_domains.shape[0]
        state = state.replace(
            anti_domains=state.anti_domains.at[
                jnp.arange(E), jnp.maximum(dom, 0)
            ].max(mark)
        )
    return state


