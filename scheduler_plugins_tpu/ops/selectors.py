"""Framework-maintained selector/topology-domain carries.

Four live carries, kept in lockstep by ONE built-in commit step of the
solve (like the built-in capacity Reserve — never per-plugin, which would
double-apply when multiple consumers are enabled):

- `SolverState.sel_counts` (TR, N): node-level matching-pod counts, read
  by PodTopologySpread when its node-inclusion policies exclude some
  keyed node (`spread_needs_node_counts`); otherwise not materialized.
- `SolverState.sel_dom_counts` (TR, D): the same counts per topology
  domain — read by InterPodAffinity always (no node-inclusion policy)
  and by PodTopologySpread on its fast path.
- `SolverState.anti_domains` (E, D): anti-affinity domain presence bits.
- `SolverState.sym_counts` (E2, D): symmetric-score carrier counts
  (existing pods' preferred/required affinity terms per domain).

Tables come from `state.scheduling.SchedulingState`:
    pend_match (S, P)  pod q matches selector group s
    track_sel/track_topo (TR,)  track -> (selector group, topology key)
    topo_code (K, N)  node -> domain code under key k (-1 = key absent)
    exist_anti_{sel,topo} (E,), exist_anti_carrier (E, P)
"""

from __future__ import annotations

import jax.numpy as jnp


def commit_tracks(state, sched, p, choice):
    """Fold pod `p`'s placement on `choice` (-1 = none) into the carries."""
    if sched.track_base is not None and (
        state.sel_counts is not None or state.sel_dom_counts is not None
    ):
        inc = sched.pend_match[sched.track_sel, p] & (choice >= 0)  # (TR,)
        TR = sched.track_base.shape[0]
        if state.sel_counts is not None:
            state = state.replace(
                sel_counts=state.sel_counts.at[
                    jnp.arange(TR), jnp.maximum(choice, 0)
                ].add(inc.astype(state.sel_counts.dtype))
            )
        if state.sel_dom_counts is not None:
            # domain-level mirror (key-less nodes have no domain: dom < 0
            # contributes nothing)
            dom = sched.topo_code[sched.track_topo, choice]  # (TR,)
            inc_d = inc & (dom >= 0)
            state = state.replace(
                sel_dom_counts=state.sel_dom_counts.at[
                    jnp.arange(TR), jnp.maximum(dom, 0)
                ].add(inc_d.astype(state.sel_dom_counts.dtype))
            )
    if state.sym_counts is not None and sched.sym_sel is not None:
        dom = sched.topo_code[sched.sym_topo, choice]  # (E2,)
        add = jnp.where(
            (choice >= 0) & (dom >= 0), sched.sym_carrier[:, p], 0
        )
        E2 = state.sym_counts.shape[0]
        state = state.replace(
            sym_counts=state.sym_counts.at[
                jnp.arange(E2), jnp.maximum(dom, 0)
            ].add(add.astype(state.sym_counts.dtype))
        )
    if state.anti_domains is not None and sched.exist_anti_sel is not None:
        dom = sched.topo_code[sched.exist_anti_topo, choice]  # (E,)
        mark = (
            sched.exist_anti_carrier[:, p] & (choice >= 0) & (dom >= 0)
        )
        E = state.anti_domains.shape[0]
        state = state.replace(
            anti_domains=state.anti_domains.at[
                jnp.arange(E), jnp.maximum(dom, 0)
            ].max(mark)
        )
    return state


