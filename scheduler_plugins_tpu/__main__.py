"""`python -m scheduler_plugins_tpu` — the long-lived scheduler daemon.

The analog of the reference's two binaries in one process, the way the
library composes them (VERDICT r4 item 2):

- the scheduler binary (/root/reference/cmd/scheduler/main.go:46-71):
  decode a profile, register plugins, run scheduling cycles against a live
  cluster store;
- the controller binary (/root/reference/cmd/controller/app/server.go:43-97):
  PodGroup/ElasticQuota reconcilers driven on the same cadence, plus a
  health/metrics surface.

Wiring per tick:

    apiserver (LIST+WATCH, bearer auth/ca)     [--apiserver URL]
        -> ClusterAgent reflector threads (one per watch path)
        -> FeedServer (rv-fenced event protocol over TCP; --grpc-port
           serves the same events over real gRPC/HTTP2; --native-store
           mirrors hot node columns into the C++ columnar store)
        -> Cluster store  (--scheduler-name gates the queue per profile)
    cycle loop:  [--leader-elect: only while holding the Lease]
                 run_cycle (QueueSort..Bind, collector ticks, NRT resync)
                 reconcile_pod_groups / reconcile_elastic_quotas
                 bindings POSTed back to the apiserver [--bind-back]
    health:      GET /healthz      -> liveness + cycle/bound/leader status
                 GET /metrics      -> prometheus text format (counters incl.
                                      per-plugin unschedulable attribution +
                                      cycle/plugin latency histograms)
                 GET /metrics.json -> the flat JSON counter snapshot

Without --apiserver the daemon is feed-driven: external agents (the Go/C++
sidecar shape, bridge/feed.py clients) push events to --feed-port and the
cycle loop schedules whatever arrives.

`--max-cycles N` exits after N ticks (e2e tests; leader-election standby
ticks count, so bounded runs terminate either way); default runs until
SIGTERM/SIGINT, which stops cleanly (agents are daemon threads; the lease
is released, the feed/health servers shut down, a summary line prints).
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import signal
import sys
import threading
import time
import urllib.request
from pathlib import Path

from scheduler_plugins_tpu.api.config import load_profile
from scheduler_plugins_tpu.bridge.agent import DEFAULT_WATCH_PATHS, ClusterAgent
from scheduler_plugins_tpu.bridge.feed import FeedClient, FeedServer
from scheduler_plugins_tpu.controllers.elasticquota import (
    reconcile_elastic_quotas,
)
from scheduler_plugins_tpu.controllers.podgroup import reconcile_pod_groups
from scheduler_plugins_tpu.framework import Scheduler
from scheduler_plugins_tpu.obs import ledger as podledger
from scheduler_plugins_tpu.state.cluster import Cluster
from scheduler_plugins_tpu.utils import observability as obs


def parse_args(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m scheduler_plugins_tpu",
        description="TPU-native scheduler daemon (feed server + reflector "
                    "agents + cycle loop + CRD controllers + health).",
    )
    ap.add_argument("--profile", required=True,
                    help="profile file (YAML or JSON): {plugins: [...], "
                         "pluginConfig: [{name, args}...]}")
    ap.add_argument("--feed-host", default="127.0.0.1")
    ap.add_argument("--feed-port", type=int, default=0,
                    help="TCP port for the event feed (0 = ephemeral)")
    ap.add_argument("--grpc-port", type=int, default=None,
                    help="also serve the event feed over real gRPC/HTTP2 "
                         "on this port (requires grpcio; shares the store "
                         "lock and rv fence with the TCP feed)")
    ap.add_argument("--apiserver", default=None,
                    help="kube-apiserver base URL to LIST+WATCH (optional; "
                         "without it the daemon is feed-driven only)")
    ap.add_argument("--token-file", default=None,
                    help="bearer token file for --apiserver")
    ap.add_argument("--ca-file", default=None,
                    help="CA bundle to trust for --apiserver TLS "
                         "(in-cluster: the serviceaccount ca.crt)")
    ap.add_argument("--insecure-skip-verify", action="store_true")
    ap.add_argument("--watch-paths", default=None,
                    help="comma-separated resource paths to watch "
                         "(default: the full reference informer surface)")
    ap.add_argument("--bind-back", action="store_true",
                    help="POST bindings back to --apiserver "
                         "(pods/<name>/binding, the upstream bind shape)")
    ap.add_argument("--native-store", action="store_true",
                    help="mirror hot node columns into the C++ columnar "
                         "store (bridge/snapshot_store.cc) — snapshots "
                         "read memcpy exports instead of per-cycle Python "
                         "accumulation (requires the compiled .so, "
                         "`make native`)")
    ap.add_argument("--scheduler-name", action="append", default=None,
                    help="profile name(s) this scheduler owns (repeatable; "
                         "default tpu-scheduler): only pods whose "
                         "spec.schedulerName matches are scheduled")
    ap.add_argument("--leader-elect", action="store_true",
                    help="coordination.k8s.io Lease leader election via "
                         "--apiserver: schedule only while holding the "
                         "lease (reflectors keep syncing on standby)")
    ap.add_argument("--lease-name", default="scheduler-plugins-tpu")
    ap.add_argument("--lease-namespace", default="kube-system")
    ap.add_argument("--lease-duration-s", type=float, default=15.0)
    ap.add_argument("--identity", default=None,
                    help="leader-election holder identity "
                         "(default hostname_pid)")
    ap.add_argument("--cycle-interval-s", type=float, default=1.0)
    ap.add_argument("--health-port", type=int, default=0,
                    help="HTTP health/metrics port (0 = ephemeral; "
                         "-1 disables)")
    ap.add_argument("--max-cycles", type=int, default=0,
                    help="exit after N cycles (0 = run until SIGTERM)")
    ap.add_argument("--record", type=int, default=0, metavar="N",
                    help="flight recorder: keep the last N scheduling "
                         "cycles' full solver inputs+outputs in a ring "
                         "buffer (utils.flightrec; 0 = off). Enables "
                         "GET /explain?uid=<pod-uid> on the health port "
                         "(per-plugin score table for any recorded pod)")
    ap.add_argument("--record-dir", default=None, metavar="DIR",
                    help="with --record: persist the ring as a replayable "
                         "bundle under DIR on shutdown (crash-safe "
                         "temp+rename writes; replay offline with "
                         "tools/replay.py). NOTE: bundles carry full pod "
                         "specs — handle like an apiserver dump")
    ap.add_argument("--serve", action="store_true",
                    help="resident-state serving: keep node tensors "
                         "device-resident across cycles and ingest "
                         "O(changed) deltas (serving.engine.ServeEngine) "
                         "with periodic anti-entropy verification; falls "
                         "back to full snapshots transparently when the "
                         "profile surface needs them")
    ap.add_argument("--pipeline", action="store_true",
                    help="concurrent cycle pipeline "
                         "(framework.pipeline_cycle.PipelinedCycle): "
                         "dispatch the device solve asynchronously and "
                         "run the previous cycle's finalize in the "
                         "overlap window, with binds conflict-fenced at "
                         "the next ingest boundary; with --serve the "
                         "engine upgrades to the O(changed) "
                         "StreamingServeEngine (node-delete compaction, "
                         "memoized ingest, O(assigned) anti-entropy)")
    ap.add_argument("--lanes", type=int, default=0, metavar="K",
                    help="K-lane optimistic-concurrency scheduling "
                         "(framework.laned_cycle.LanedCycle): partition "
                         "the pending queue across K solver lanes by a "
                         "deterministic key (gang members never split), "
                         "solve all lanes speculatively against the same "
                         "resident state and commit through a single "
                         "host-side conflict fence in the defined serial "
                         "order — bit-identical to the serial cycle at "
                         "every K. Profiles outside the fence-exact gate "
                         "fall back to the sequential parity solve per "
                         "cycle (counted on /healthz). Mutually "
                         "exclusive with --pipeline")
    ap.add_argument("--tune", action="store_true",
                    help="online self-tuning shadow lane "
                         "(tuning.shadow.ShadowTuner): continuously "
                         "replay the recorded flight-recorder ring under "
                         "candidate plugin-weight vectors on a background "
                         "worker (deadlined — a hung sweep degrades to "
                         "'no tuning'), promote a winner only through "
                         "the tuning.promotion gates, roll it out live "
                         "via the aux channel (zero recompiles) and "
                         "auto-roll-back on quality-gauge regression "
                         "during probation. Implies --record 8 when "
                         "--record is not set (the ring IS the sweep "
                         "corpus). With --checkpoint, the promoted "
                         "weights + probation state persist to "
                         "<checkpoint>.tuner.json on shutdown and "
                         "restart resumes with them")
    ap.add_argument("--tune-candidates", type=int, default=24,
                    help="candidate weight vectors per shadow sweep")
    ap.add_argument("--tune-sweep-every", type=int, default=8,
                    help="cycles between shadow sweep dispatches")
    ap.add_argument("--resilient", action="store_true",
                    help="solve watchdog + degraded-mode failover "
                         "(resilience.watchdog): device solves complete "
                         "through a deadlined worker thread, retry with "
                         "seeded-jitter backoff, then fail over to the "
                         "host sequential parity path and probe for "
                         "recovery (SPT_SOLVE_TIMEOUT_S tunes the "
                         "deadline)")
    ap.add_argument("--checkpoint", default=None, metavar="PATH",
                    help="with --serve: restore the resident state from "
                         "PATH at startup (if present; anti-entropy "
                         "verifies it before trusting it) and write a "
                         "final crash-safe checkpoint there on shutdown")
    ap.add_argument("--no-ledger", action="store_true",
                    help="disable the pod-lifecycle SLO ledger "
                         "(obs.ledger; on by default in the daemon). The "
                         "ledger follows each pod across cycles — queue "
                         "wait, backoff, gang wait, solve/fence/bind — "
                         "feeding the scheduler_e2e_scheduling_duration_ms "
                         "/ scheduler_pod_scheduling_sli_duration_ms "
                         "families, the /healthz sli block and "
                         "GET /pods/<uid>/timeline on the health port")
    ap.add_argument("--trace", default=None, metavar="OUT.json",
                    help="record the cycle tracer for the daemon's "
                         "lifetime and flush a Perfetto-loadable JSON to "
                         "OUT.json on shutdown (SIGTERM included)")
    return ap.parse_args(argv)


def decode_profile_file(path: str) -> dict:
    """YAML/JSON profile file -> the flat {plugins, pluginConfig} mapping
    `api.config.load_profile` takes. Accepts a KubeSchedulerConfiguration
    -style {profiles: [first]} wrapper. Shared by startup profile loading
    and the flight recorder's exact-config capture, so the recorded
    config can never diverge from the profile the daemon actually runs."""
    import yaml

    with open(path) as f:
        config = yaml.safe_load(f) or {}
    if "profiles" in config:
        config = (config.get("profiles") or [{}])[0]
    return config


def load_profile_file(path: str):
    """YAML/JSON profile file -> Profile."""
    return load_profile(decode_profile_file(path))


#: fnmatch patterns for live thread names the concurrency model covers,
#: resolved lazily from the committed auditor manifest
_THREAD_PATTERNS: list | None = None


def _known_thread_patterns() -> list:
    global _THREAD_PATTERNS
    if _THREAD_PATTERNS is None:
        # interpreter main + ThreadingHTTPServer's per-request threads
        # (stdlib-named; our own threads carry explicit names — GL012)
        pats = ["MainThread", "Thread-*"]
        manifest = (
            Path(__file__).resolve().parents[1] / "docs" / "race_audit.json"
        )
        try:
            entries = json.loads(manifest.read_text())["entries"]
            pats += [
                name for name, spec in sorted(entries.items())
                if spec.get("kind") in ("thread", "pool", "server")
            ]
        except (OSError, ValueError, KeyError):
            # installed without the repo checkout: fall back to the
            # names the code itself assigns (kept in sync by the
            # manifest-coverage test in tests/test_race_audit.py)
            pats += [
                "agent-*", "feed-server", "health-server",
                "leader-elector", "load-watcher", "shadow-tuner",
                "solve-watchdog", "spt-bind-flusher*", "wd-*",
            ]
        _THREAD_PATTERNS = pats
    return _THREAD_PATTERNS


def thread_topology() -> dict:
    """Live thread names diffed against the static concurrency model
    (tools/race_audit.py's entry table). `unknown` names are topology
    drift: a running thread the lockset analysis never audited."""
    live = sorted(t.name for t in threading.enumerate())
    pats = _known_thread_patterns()
    unknown = [
        n for n in live if not any(fnmatch.fnmatch(n, p) for p in pats)
    ]
    return {"live": live, "unknown": unknown}


class HealthServer:
    """GET /healthz (liveness + loop counters), /metrics (prometheus text
    exposition 0.0.4: counters incl. per-plugin unschedulable attribution,
    plus real `_bucket{le=...}`/`_sum`/`_count` histograms for cycle and
    per-extension-point plugin latency) and /metrics.json (the flat debug
    snapshot) — the probe/metrics surface of cmd/controller/app/server.go
    :52-58, now speaking the prometheus wire format."""

    def __init__(self, daemon, host: str, port: int):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        outer = daemon

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json_reply(self, code: int, payload: dict):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.startswith("/healthz"):
                    # lock-free: a probe must answer while a cycle (incl.
                    # first-compile) holds the feed lock; `last_pending`
                    # is the previous tick's cached count
                    payload = {
                        "ok": True,
                        "cycles": outer.cycles,
                        "bound_total": outer.bound_total,
                        "pending": outer.last_pending,
                        # latest cycle's placement-quality objectives
                        # (tuning.quality; None before the first solved
                        # cycle) — the gauge view lives on /metrics as
                        # scheduler_placement_quality{objective}
                        "quality": outer.last_quality,
                        "feed_address": list(outer.feed.address),
                        # degraded-mode serving state (resilience.watchdog
                        # / docs/ROBUSTNESS.md): degraded=True means the
                        # device backend failed past the watchdog budget
                        # and cycles serve from the host parity path
                        "degraded": (
                            outer.resilience is not None
                            and outer.resilience.degraded
                        ),
                        "degraded_reason": (
                            outer.resilience.degraded_reason
                            if outer.resilience is not None else None
                        ),
                        "parked_cycles": outer.parked_cycles,
                        # pod-lifecycle SLIs (obs.ledger): e2e scheduling
                        # latency percentiles, per-stage decomposition
                        # totals and per-priority breakdown over the
                        # retired ring; None with --no-ledger
                        "sli": (
                            podledger.LEDGER.sli_summary()
                            if podledger.LEDGER.enabled else None
                        ),
                        # live thread census vs the static concurrency
                        # model (tools/race_audit.py entry table):
                        # `unknown` = running threads the lockset
                        # analysis never modeled
                        "threads": thread_topology(),
                        # device-memory watermarks (obs.costmodel, ISSUE
                        # 20): allocator bytes-in-use/peak stamped by the
                        # last cycle; available=False on backends without
                        # allocator stats (the CPU fallback), None before
                        # the first cycle — the static counterpart is
                        # docs/cost_model.json's per-program peak_bytes
                        "memory": outer.last_memory,
                    }
                    if payload["threads"]["unknown"]:
                        obs.metrics.inc(
                            obs.THREAD_TOPOLOGY_DRIFT,
                            len(payload["threads"]["unknown"]),
                        )
                    if outer.pipeline is not None:
                        # concurrent cycle pipeline introspection:
                        # configured depth + host stages still in
                        # flight (deferred finalize / unflushed binds)
                        payload["pipeline"] = {
                            "depth": outer.pipeline.depth,
                            "inflight": outer.pipeline.inflight,
                        }
                    if outer.laned is not None:
                        # K-lane engine introspection: lane config +
                        # conflict/re-resolve/fallback totals and the
                        # latest cycle's per-lane attribution
                        payload["lanes"] = outer.laned.stats()
                    if outer.engine is not None:
                        payload["serve"] = {
                            "generation": outer.engine.generation,
                            "rebases": outer.engine.rebases,
                            "antientropy_divergences":
                                outer.engine.antientropy_divergences,
                            # resident gang/quota serving health: >0
                            # means gang rosters are falling back to
                            # O(cluster) snapshots (ISSUE 12 — should
                            # stay 0 on a compatible roster)
                            "gang_fallbacks":
                                outer.engine.gang_fallbacks,
                        }
                    if outer.tuner is not None:
                        # online self-tuning controller state (guarded
                        # rollout, docs/ROBUSTNESS.md): active weights +
                        # digest, probation progress, promotion/rollback
                        # counters, self-disable reason
                        payload["tuner"] = outer.tuner.status()
                    if outer.elector is not None:
                        payload["leader"] = outer.elector.is_leader
                        payload["holder"] = outer.elector.observed_holder
                    body = json.dumps(payload).encode()
                elif self.path.startswith("/explain"):
                    # per-plugin score table for a recorded pod (flight
                    # recorder ring; 404 when off or uid not recorded)
                    from urllib.parse import parse_qs, urlparse

                    from scheduler_plugins_tpu.utils import flightrec

                    query = parse_qs(urlparse(self.path).query)
                    uid = (query.get("uid") or [""])[0]
                    cycle = query.get("cycle")
                    try:
                        top_k = int((query.get("top") or [5])[0])
                        cycle_n = int(cycle[0]) if cycle else None
                    except ValueError as exc:
                        self._json_reply(
                            400, {"error": f"bad query parameter: {exc}"}
                        )
                        return
                    rec = flightrec.recorder.find(uid, cycle=cycle_n)
                    if not uid or rec is None:
                        detail = (
                            "flight recorder off (--record N)"
                            if not flightrec.recorder.enabled
                            else f"uid {uid!r} not in the recorded ring"
                        )
                        self._json_reply(404, {"error": detail})
                        return
                    try:
                        body = json.dumps(
                            flightrec.explain_record(rec, uid, top_k=top_k)
                        ).encode()
                    except Exception as exc:
                        self._json_reply(
                            500,
                            {"error": f"{type(exc).__name__}: {exc}"},
                        )
                        return
                elif self.path.startswith("/pods/"):
                    # GET /pods/<uid>/timeline — one pod's full lifecycle
                    # story from the pod ledger: events with (cycle, lane,
                    # seq) coordinates, the per-stage latency
                    # decomposition (sums to e2e exactly) and the meta of
                    # every cycle that observed the pod
                    from urllib.parse import unquote, urlparse

                    parts = urlparse(self.path).path.strip("/").split("/")
                    if len(parts) != 3 or parts[2] != "timeline":
                        self._json_reply(
                            404,
                            {"error": "expected /pods/<uid>/timeline"},
                        )
                        return
                    if not podledger.LEDGER.enabled:
                        self._json_reply(
                            404,
                            {"error": "pod-lifecycle ledger disabled "
                                      "(--no-ledger)"},
                        )
                        return
                    timeline = podledger.LEDGER.timeline(unquote(parts[1]))
                    if timeline is None:
                        self._json_reply(
                            404,
                            {"error": f"uid {unquote(parts[1])!r} not in "
                                      "the ledger (never pending, or "
                                      "aged out of the retired ring)"},
                        )
                        return
                    body = json.dumps(timeline).encode()
                elif self.path.startswith("/metrics.json"):
                    body = json.dumps(obs.metrics.snapshot()).encode()
                elif self.path.startswith("/metrics"):
                    body = obs.metrics.prometheus_text().encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.address = self._httpd.server_address
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="health-server",
        )
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


class Daemon:
    def __init__(self, args):
        self.args = args
        self.profile = load_profile_file(args.profile)
        self.scheduler = Scheduler(self.profile)
        if not getattr(args, "no_ledger", False):
            # pod-lifecycle SLO ledger (obs.ledger): O(changed) per cycle,
            # bounded ring — on by default in the daemon, feeding the
            # upstream-parity e2e/attempts/SLI metric families and the
            # /pods/<uid>/timeline surface
            podledger.LEDGER.start()
        if args.tune and not args.record:
            # the flight-recorder ring IS the shadow lane's sweep corpus
            args.record = 8
        if args.record:
            from scheduler_plugins_tpu.utils import flightrec

            flightrec.recorder.start(capacity=args.record)
            # the daemon knows its EXACT profile config — record that
            # instead of the best-effort attribute export
            flightrec.recorder.profile_config = decode_profile_file(
                args.profile
            )
        self.cluster = Cluster()
        if args.scheduler_name:
            self.cluster.scheduler_names = set(args.scheduler_name)
        self.engine = None
        if args.serve:
            from scheduler_plugins_tpu.serving import (
                ServeEngine,
                StreamingServeEngine,
            )

            engine_cls = (
                StreamingServeEngine if (args.pipeline or args.lanes)
                else ServeEngine
            )
            self.engine = engine_cls().attach(self.cluster)
            if args.checkpoint and os.path.exists(args.checkpoint):
                try:
                    self.engine.restore_checkpoint(args.checkpoint)
                    obs.logger.info(
                        "resident state restored from %s (generation %d; "
                        "anti-entropy verifies at the first refresh)",
                        args.checkpoint, self.engine.generation,
                    )
                except Exception as exc:
                    # a bad checkpoint must never block startup: the
                    # engine just rebuilds from the store (cold path)
                    obs.logger.warning(
                        "checkpoint restore failed (%s): rebuilding "
                        "resident state from the store", exc,
                    )
        self.resilience = None
        if args.resilient:
            from scheduler_plugins_tpu.resilience import Resilience

            self.resilience = Resilience(engine=self.engine)
        self.tuner = None
        if args.tune:
            from scheduler_plugins_tpu.tuning.shadow import ShadowTuner

            try:
                self.tuner = ShadowTuner(
                    self.scheduler,
                    candidates=args.tune_candidates,
                    sweep_every=args.tune_sweep_every,
                )
            except ValueError as exc:
                # e.g. a packing-mode profile: the rollout seam is the
                # sequential parity path — refuse at startup, clearly
                raise SystemExit(f"--tune: {exc}")
            if args.checkpoint and os.path.exists(
                self._tuner_state_path()
            ):
                try:
                    with open(self._tuner_state_path()) as f:
                        restored = self.tuner.restore_state(json.load(f))
                    if restored:
                        obs.logger.info(
                            "tuner state restored from %s: weights %s "
                            "(%s)", self._tuner_state_path(),
                            self.tuner.status()["active_weights"],
                            self.tuner.status()["state"],
                        )
                except Exception as exc:
                    # a bad state file must never block startup: the
                    # tuner just starts fresh on the profile weights
                    obs.logger.warning(
                        "tuner state restore failed (%s): starting from "
                        "the profile weights", exc,
                    )
        self.pipeline = None
        if args.pipeline:
            from scheduler_plugins_tpu.framework import PipelinedCycle

            # binds flush inline (async_bind=False): every store
            # mutation happens under the feed lock the tick holds, so
            # the flusher thread's mutations cannot race feed ingest;
            # the overlap (async solve dispatch + the previous cycle's
            # finalize in the in-flight window) is within-tick
            self.pipeline = PipelinedCycle(
                self.scheduler, self.cluster, serve=self.engine,
                resilience=self.resilience, async_bind=False,
            )
        self.laned = None
        if args.lanes:
            if args.pipeline:
                raise SystemExit(
                    "--lanes and --pipeline are mutually exclusive "
                    "(both recompose the cycle around their own "
                    "concurrency model)"
                )
            if args.resilient:
                raise SystemExit(
                    "--lanes does not compose with --resilient: the "
                    "watchdog's degraded path IS the sequential engine "
                    "— lanes would add only fence overhead to it"
                )
            from scheduler_plugins_tpu.framework import LanedCycle

            try:
                # binds flush inline (async_bind=False): every store
                # mutation happens under the feed lock the tick holds
                self.laned = LanedCycle(
                    self.scheduler, self.cluster, k=args.lanes,
                    serve=self.engine, async_bind=False,
                )
            except ValueError as exc:
                raise SystemExit(f"--lanes: {exc}")
        if args.trace:
            obs.tracer.start()
        if args.native_store:
            try:
                self.cluster.attach_native_store()
            except Exception as exc:
                raise SystemExit(
                    f"--native-store: {exc} (build it with `make native`)"
                )
        self.feed = FeedServer(
            self.cluster, host=args.feed_host, port=args.feed_port
        ).start()
        self.grpc_feed = None
        if args.grpc_port is not None:
            from scheduler_plugins_tpu.bridge.grpc_feed import GrpcFeedServer

            # same lock + rv fence: redundant TCP/gRPC agents stay coherent
            self.grpc_feed = GrpcFeedServer(
                self.cluster, host=args.feed_host, port=args.grpc_port,
                lock=self.feed.lock, rv_table=self.feed.rv_table,
            ).start()
            if not self.grpc_feed.port:
                # grpc's add_insecure_port reports a bind failure as port
                # 0 instead of raising — fail fast like any bad config
                raise SystemExit(
                    f"--grpc-port {args.grpc_port}: bind failed "
                    "(port in use?)"
                )
        self.cycles = 0
        self.ticks = 0
        self.bound_total = 0
        self.last_pending = 0
        self.last_quality = None
        self.last_memory = None  # /healthz device-memory block (ISSUE 20)
        self.parked_cycles = 0
        self._unposted: dict[str, str] = {}
        self.elector = None  # before HealthServer: /healthz reads it
        self.stop_event = threading.Event()
        self.health = None
        if args.health_port >= 0:
            self.health = HealthServer(self, args.feed_host, args.health_port)
        self.token = ""
        if args.token_file:
            with open(args.token_file) as f:
                self.token = f.read().strip()
        if args.leader_elect:
            if not args.apiserver:
                raise SystemExit("--leader-elect requires --apiserver")
            import socket as _socket

            from scheduler_plugins_tpu.bridge.leader import LeaseElector

            identity = args.identity or (
                f"{_socket.gethostname()}_{os.getpid()}"
            )
            self.elector = LeaseElector(
                args.apiserver, identity,
                name=args.lease_name, namespace=args.lease_namespace,
                lease_duration_s=args.lease_duration_s,
                renew_period_s=max(args.lease_duration_s / 3.0, 0.05),
                token=self.token, ca_file=args.ca_file,
                insecure_skip_verify=args.insecure_skip_verify,
            )
            threading.Thread(
                target=self.elector.run, args=(self.stop_event,),
                daemon=True, name="leader-elector",
            ).start()
        self._agent_threads = []
        if args.apiserver:
            paths = (
                [p.strip() for p in args.watch_paths.split(",") if p.strip()]
                if args.watch_paths else list(DEFAULT_WATCH_PATHS)
            )
            for path in paths:
                t = threading.Thread(
                    target=self._agent_loop, args=(path,), daemon=True,
                    name=f"agent-{path}",
                )
                t.start()
                self._agent_threads.append(t)

    def _tuner_state_path(self) -> str:
        """The tuner's persisted controller state rides NEXT TO the
        resilience checkpoint (same crash-safe write discipline): the
        promoted weights + probation window survive a SIGTERM restart."""
        return f"{self.args.checkpoint}.tuner.json"

    def _agent_loop(self, path: str):
        """One reflector per watch path, feeding events through the real
        TCP wire to our own feed server (the exact path an external Go/C++
        agent would use)."""
        host, port = self.feed.address
        client = FeedClient(host, port)
        agent = ClusterAgent(client.send)
        agent.list_then_watch(
            self.args.apiserver, path,
            token=self.token,
            insecure_skip_verify=self.args.insecure_skip_verify,
            ca_file=self.args.ca_file,
            max_failures=None,  # the daemon retries for its lifetime
        )

    def _ssl_context(self):
        from scheduler_plugins_tpu.utils.httptls import ssl_context

        return ssl_context(self.args.apiserver, self.args.ca_file,
                           self.args.insecure_skip_verify)

    def _post_binding(self, uid: str, node: str) -> bool:
        """POST the upstream Binding shape back to the apiserver
        (the bind goroutine's process boundary, SURVEY.md §3.2). Returns
        True when the retry-queue entry should be dropped — success, or a
        pod that no longer exists in the store (deleted since binding:
        nothing left to bind)."""
        with self.feed.locked():
            pod = self.cluster.pods.get(uid)
            if pod is None:
                return True
            ns, name = pod.namespace, pod.name
        url = (f"{self.args.apiserver.rstrip('/')}"
               f"/api/v1/namespaces/{ns}/pods/{name}/binding")
        body = json.dumps({
            "apiVersion": "v1", "kind": "Binding",
            "metadata": {"name": name, "namespace": ns},
            "target": {"apiVersion": "v1", "kind": "Node", "name": node},
        }).encode()
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "application/json"},
        )
        if self.token:
            req.add_header("Authorization", f"Bearer {self.token}")
        try:
            urllib.request.urlopen(
                req, timeout=3, context=self._ssl_context()
            ).close()
        except Exception as exc:
            obs.logger.warning("binding POST failed for %s: %s", uid, exc)
            return False
        return True

    def tick(self):
        if self.elector is not None and not self.elector.is_leader:
            # standby: reflectors keep the store warm, scheduling waits
            # (client-go leaderelection semantics — informers run, the
            # scheduling/reconcile loops gate on leadership)
            with self.feed.locked():
                self.last_pending = len(self.cluster.pending_pods())
            return None
        now_ms = int(time.time() * 1000)
        cycle_started = time.monotonic()
        try:
            engine = self.pipeline or self.laned
            if engine is not None:
                # the pipelined/laned engines compose their own stage
                # functions; the tuner's two seams wrap the whole tick
                # (weights may only change between ticks — the conflict
                # fence keeps any in-flight solve on the weights it
                # dispatched with)
                if self.tuner is not None:
                    self.tuner.begin_cycle(now_ms=now_ms)
                with self.feed.locked():
                    report = engine.tick(now_ms)
                if self.tuner is not None and report is not None:
                    self.tuner.observe_report(report)
            else:
                report = self.feed.run_cycle(
                    self.scheduler, now=now_ms, serve=self.engine,
                    resilience=self.resilience, tuner=self.tuner,
                )
        except Exception as exc:
            from scheduler_plugins_tpu.resilience import BackendUnavailable

            if not isinstance(exc, BackendUnavailable):
                raise
            # backend gone AND no host fallback for this profile: park
            # the cycle (pods stay pending, requeue backoff paces them)
            # and keep ticking — the probation probe restores the fast
            # path when the backend answers again
            obs.logger.warning("cycle parked: %s", exc.reason)
            self.parked_cycles += 1
            with self.feed.locked():
                self.last_pending = len(self.cluster.pending_pods())
            return None
        obs.metrics.observe_ms(
            "scheduler_cycle", (time.monotonic() - cycle_started) * 1000
        )
        with self.feed.locked():
            events = reconcile_pod_groups(self.cluster, now_ms=now_ms)
            events += reconcile_elastic_quotas(self.cluster)
            self.last_pending = len(self.cluster.pending_pods())
        for line in events:
            obs.logger.info("controller: %s", line)
        if report.bound or report.failed:
            obs.logger.info(
                "cycle %d: bound %d, unschedulable %d",
                self.cycles + 1, len(report.bound), len(report.failed),
            )
        if self.args.apiserver and self.args.bind_back:
            # the local store binds immediately; the apiserver POST is the
            # process boundary and can fail transiently — keep unacked
            # bindings in a retry queue until the POST lands (the local
            # pod is no longer pending, so no re-schedule would re-emit
            # it). Retries are capped per tick: during an apiserver
            # outage each attempt burns its connect timeout, and the
            # scheduling loop must keep its cadence
            self._unposted.update(report.bound)
            failures = 0
            for uid, node in list(self._unposted.items()):
                if failures >= 2:  # outage: stop burning connect timeouts
                    break
                if self._post_binding(uid, node):
                    del self._unposted[uid]
                else:
                    failures += 1
        self.cycles += 1
        self.bound_total += len(report.bound)
        if report.quality is not None:
            self.last_quality = report.quality
        # device-memory watermark gauges: one allocator-stats read per
        # cycle (no device sync, no transfer — inside the ≤ max(2%,
        # jitter-floor) observability overhead bound, gated by
        # tests/test_cost_observatory.py); null-safe on backends without
        # allocator stats and on a mid-call tunnel death
        try:
            from scheduler_plugins_tpu.obs import costmodel

            self.last_memory = costmodel.stamp_device_memory(obs.metrics)
        except Exception:
            self.last_memory = None
        return report

    def run(self):
        args = self.args

        def handle_sig(signum, frame):
            self.stop_event.set()

        signal.signal(signal.SIGTERM, handle_sig)
        signal.signal(signal.SIGINT, handle_sig)

        host, port = self.feed.address
        status = {"feed": f"{host}:{port}"}
        if self.grpc_feed is not None:
            status["grpc"] = f"{self.grpc_feed.host}:{self.grpc_feed.port}"
        if self.health:
            status["health"] = "http://%s:%d/healthz" % self.health.address
        print("daemon ready " + json.dumps(status), flush=True)

        try:
            while not self.stop_event.is_set():
                started = time.monotonic()
                self.tick()
                self.ticks += 1
                # ticks, not scheduling cycles: a bounded run must also
                # terminate when leader-election standby skips every cycle
                if args.max_cycles and self.ticks >= args.max_cycles:
                    break
                remaining = args.cycle_interval_s - (
                    time.monotonic() - started
                )
                if remaining > 0:
                    self.stop_event.wait(remaining)
        finally:
            # graceful shutdown (SIGTERM/SIGINT path): every artifact the
            # process owns is flushed through the crash-safe
            # `obs.atomic_write` discipline BEFORE the servers come down,
            # then the exit path returns rc 0 — a drained, checkpointed
            # daemon is indistinguishable from one that never ran
            if self.pipeline is not None:
                try:
                    # conflict-fence + deferred finalize of the last
                    # in-flight cycle: a drained pipeline leaves the
                    # store and the recorder exactly as the serial
                    # engine would
                    with self.feed.locked():
                        self.pipeline.close()
                except Exception as exc:
                    obs.logger.warning("pipeline flush failed: %s", exc)
            if self.laned is not None:
                try:
                    # join the lane bind flusher and shut the lane pool
                    with self.feed.locked():
                        self.laned.close()
                except Exception as exc:
                    obs.logger.warning("lane flush failed: %s", exc)
            if self.args.record and self.args.record_dir:
                from scheduler_plugins_tpu.utils import flightrec

                try:
                    summary = flightrec.recorder.save(self.args.record_dir)
                    obs.logger.info("flight recorder bundle: %s", summary)
                except Exception as exc:
                    obs.logger.warning("flight recorder save failed: %s", exc)
            if self.args.trace and obs.tracer.enabled:
                try:
                    obs.tracer.stop()
                    obs.tracer.write(self.args.trace)  # atomic_write inside
                except Exception as exc:
                    obs.logger.warning("tracer flush failed: %s", exc)
            if self.engine is not None and self.args.checkpoint:
                try:
                    if self.engine.save_checkpoint(self.args.checkpoint):
                        obs.logger.info(
                            "resilience checkpoint written: %s",
                            self.args.checkpoint,
                        )
                except Exception as exc:
                    obs.logger.warning("checkpoint write failed: %s", exc)
            if self.tuner is not None and self.args.checkpoint:
                # currently-promoted weights + probation state persist
                # with the resilience checkpoint; restart resumes them
                try:
                    obs.atomic_write(
                        self._tuner_state_path(),
                        json.dumps(self.tuner.state_dict(), sort_keys=True)
                        + "\n",
                    )
                    obs.logger.info(
                        "tuner state written: %s", self._tuner_state_path()
                    )
                except Exception as exc:
                    obs.logger.warning("tuner state write failed: %s", exc)
            if self.elector is not None:
                self.elector.release()  # ReleaseOnCancel (idempotent)
            if self.health:
                self.health.stop()
            if self.grpc_feed is not None:
                self.grpc_feed.stop()
            self.feed.stop()
            print(json.dumps({
                "daemon_exit": True,
                "cycles": self.cycles,
                "bound_total": self.bound_total,
                "parked_cycles": self.parked_cycles,
                "degraded": (
                    self.resilience is not None and self.resilience.degraded
                ),
            }), flush=True)


def main(argv=None):
    daemon = Daemon(parse_args(argv))
    daemon.run()
    return 0


if __name__ == "__main__":
    sys.exit(main())
