"""Host-side cluster object model.

Plain dataclasses standing in for the k8s API objects the reference consumes:
Pod/Node plus the CRDs it defines or depends on — PodGroup and ElasticQuota
(/root/reference/apis/scheduling/v1alpha1/types.go:35-198), NodeResourceTopology
zones (external noderesourcetopology-api), AppGroup + NetworkTopology (diktyo
APIs), and seccomp profiles (SySched). These objects live on the host; the
snapshot builder (`state.snapshot`) lowers them to dense tensors.

Derived-request semantics follow the reference exactly:
- effective request = max(sum of app containers (+ sidecars), rolling init max)
  + overhead — /root/reference/pkg/util/resource.go:51-85.
- QoS class derivation mirrors upstream `v1qos.GetPodQOS`.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Mapping, Optional

from scheduler_plugins_tpu.api.resources import (
    CPU,
    MEMORY,
    add_quantities,
    max_quantities,
)

# Label that joins a pod to its PodGroup
# (/root/reference/apis/scheduling/v1alpha1/types.go: PodGroupLabel).
POD_GROUP_LABEL = "scheduling.x-k8s.io/pod-group"
# Well-known topology labels used by the network-aware plugins.
REGION_LABEL = "topology.kubernetes.io/region"
ZONE_LABEL = "topology.kubernetes.io/zone"
# AppGroup membership labels (diktyo appgroup-api).
APP_GROUP_LABEL = "app-group.scheduling.x-k8s.io"
WORKLOAD_SELECTOR_LABEL = "app"

DEFAULT_SCHEDULER_NAME = "tpu-scheduler"


class QOSClass(enum.IntEnum):
    """Ordered so that the QOSSort queue comparator can compare numerically:
    Guaranteed > Burstable > BestEffort
    (/root/reference/pkg/qos/queue_sort.go:46-81)."""

    BEST_EFFORT = 0
    BURSTABLE = 1
    GUARANTEED = 2


if hasattr(enum, "StrEnum"):  # 3.11+
    _StrEnum = enum.StrEnum
else:  # 3.10 fallback with StrEnum's str()/format() semantics
    class _StrEnum(str, enum.Enum):
        __str__ = str.__str__
        __format__ = str.__format__


class PodPhase(_StrEnum):
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    UNKNOWN = "Unknown"


# ---------------------------------------------------------------------------
# In-tree scheduling spec fragments (upstream core/v1 types — not defined by
# the reference repo, but real profiles combine its plugins with the in-tree
# NodeAffinity / TaintToleration / PodTopologySpread / InterPodAffinity
# plugins; see docs/PARITY.md "companion plugins")
# ---------------------------------------------------------------------------


@dataclass
class Taint:
    """core/v1 Taint. Effects: NoSchedule | PreferNoSchedule | NoExecute."""

    key: str
    value: str = ""
    effect: str = "NoSchedule"


@dataclass
class Toleration:
    """core/v1 Toleration; upstream v1helper.TolerationsTolerateTaint rules:
    empty effect matches all effects; empty key with Exists matches all
    taints; operator Exists ignores value."""

    key: str = ""
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # "" | NoSchedule | PreferNoSchedule | NoExecute

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.key:
            if self.key != taint.key:
                return False
        elif self.operator != "Exists":
            return False
        if self.operator == "Exists":
            return True
        return self.value == taint.value


@dataclass
class LabelSelectorRequirement:
    """metav1.LabelSelectorRequirement (In | NotIn | Exists | DoesNotExist)."""

    key: str
    operator: str
    values: tuple = ()


@dataclass
class LabelSelector:
    """metav1.LabelSelector: AND of match_labels and match_expressions.
    NOTE: a None selector matches nothing; an empty selector matches
    everything (metav1 semantics)."""

    match_labels: Mapping[str, str] = field(default_factory=dict)
    match_expressions: list[LabelSelectorRequirement] = field(
        default_factory=list
    )

    def matches(self, labels: Mapping[str, str]) -> bool:
        for k, v in self.match_labels.items():
            if labels.get(k) != v:
                return False
        for r in self.match_expressions:
            has = r.key in labels
            if r.operator == "In":
                if not has or labels[r.key] not in r.values:
                    return False
            elif r.operator == "NotIn":
                if has and labels[r.key] in r.values:
                    return False
            elif r.operator == "Exists":
                if not has:
                    return False
            elif r.operator == "DoesNotExist":
                if has:
                    return False
            else:
                raise ValueError(f"unknown selector operator {r.operator!r}")
        return True

    def _key(self):
        return (
            tuple(sorted(self.match_labels.items())),
            tuple(
                (r.key, r.operator, tuple(r.values))
                for r in self.match_expressions
            ),
        )


@dataclass
class NodeSelectorRequirement:
    """core/v1 NodeSelectorRequirement
    (In | NotIn | Exists | DoesNotExist | Gt | Lt); NotIn/DoesNotExist match
    when the label is absent (apimachinery labels.Requirement semantics)."""

    key: str
    operator: str
    values: tuple = ()

    def matches(self, labels: Mapping[str, str]) -> bool:
        has = self.key in labels
        val = labels.get(self.key)
        if self.operator == "In":
            return has and val in self.values
        if self.operator == "NotIn":
            return not has or val not in self.values
        if self.operator == "Exists":
            return has
        if self.operator == "DoesNotExist":
            return not has
        if self.operator in ("Gt", "Lt"):
            if not has or len(self.values) != 1:
                return False
            try:
                lhs, rhs = int(val), int(self.values[0])
            except ValueError:
                return False
            return lhs > rhs if self.operator == "Gt" else lhs < rhs
        raise ValueError(f"unknown node selector operator {self.operator!r}")


@dataclass
class NodeSelectorTerm:
    """AND of match_expressions (node labels) and match_fields
    (metadata.name only, as upstream supports)."""

    match_expressions: list[NodeSelectorRequirement] = field(
        default_factory=list
    )
    match_fields: list[NodeSelectorRequirement] = field(default_factory=list)

    def matches(self, node: "Node") -> bool:
        return all(
            r.matches(node.labels) for r in self.match_expressions
        ) and all(
            r.matches({"metadata.name": node.name}) for r in self.match_fields
        )

    @classmethod
    def from_wire(cls, spec: Mapping) -> "NodeSelectorTerm":
        """The one parser for the wire shape ({"match_expressions":
        [{"key","operator","values"}], "match_fields": [...]}) — used by the
        feed protocol and config args alike (JSON-null tolerant)."""

        def req(r):
            return NodeSelectorRequirement(
                key=r["key"], operator=r["operator"],
                values=tuple(r.get("values") or ()),
            )

        return cls(
            match_expressions=[
                req(r) for r in spec.get("match_expressions") or []
            ],
            match_fields=[req(r) for r in spec.get("match_fields") or []],
        )


@dataclass
class PreferredSchedulingTerm:
    weight: int  # 1..100
    preference: NodeSelectorTerm = field(default_factory=NodeSelectorTerm)


@dataclass
class TopologySpreadConstraint:
    """core/v1 TopologySpreadConstraint (whenUnsatisfiable DoNotSchedule
    filters, ScheduleAnyway scores).

    - `min_domains` (DoNotSchedule only): when fewer eligible domains than
      this exist, the global minimum is treated as 0 (upstream
      podtopologyspread minMatchNum).
    - `match_label_keys`: label keys whose values are copied from the
      incoming pod and merged into the selector as exact-match requirements
      (keys the pod lacks are ignored, upstream semantics).
    - node_affinity_policy / node_taints_policy: which nodes count for
      domain/min computation — Honor (default for affinity) restricts to
      nodes matching the pod's nodeSelector/affinity; Ignore (default for
      taints) counts all.
    """

    max_skew: int
    topology_key: str
    when_unsatisfiable: str = "DoNotSchedule"  # | ScheduleAnyway
    label_selector: Optional[LabelSelector] = None
    min_domains: Optional[int] = None
    match_label_keys: tuple = ()
    node_affinity_policy: str = "Honor"  # | Ignore
    node_taints_policy: str = "Ignore"  # | Honor


@dataclass
class PodAffinityTerm:
    """core/v1 PodAffinityTerm: selector over pod labels, scoped to
    `namespaces` plus any namespace matching `namespace_selector` (nil
    selector adds none; EMPTY selector matches every namespace — metav1
    semantics); both empty = the incoming pod's own namespace. Co-location
    judged by `topology_key` domains."""

    topology_key: str
    label_selector: Optional[LabelSelector] = None
    namespaces: tuple = ()
    namespace_selector: Optional[LabelSelector] = None


@dataclass
class Namespace:
    """core/v1 Namespace (labels only) — the namespaceSelector target."""

    name: str
    labels: Mapping[str, str] = field(default_factory=dict)


@dataclass
class WeightedPodAffinityTerm:
    weight: int  # 1..100
    term: PodAffinityTerm


@dataclass
class Container:
    name: str = "c"
    requests: Mapping[str, int] = field(default_factory=dict)
    limits: Mapping[str, int] = field(default_factory=dict)
    #: init containers with restartPolicy=Always are sidecars
    #: (/root/reference/pkg/util/sidecar.go:25-27).
    restart_policy_always: bool = False
    #: Seccomp profile reference (namespace/name of a SeccompProfile CR) for
    #: the SySched plugin; None means unconfined.
    seccomp_profile: Optional[str] = None


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    uid: str = ""
    containers: list[Container] = field(default_factory=list)
    init_containers: list[Container] = field(default_factory=list)
    overhead: Mapping[str, int] = field(default_factory=dict)
    priority: int = 0
    labels: Mapping[str, str] = field(default_factory=dict)
    annotations: Mapping[str, str] = field(default_factory=dict)
    node_name: Optional[str] = None
    #: Node the scheduler has nominated this pod for after preemption.
    nominated_node_name: Optional[str] = None
    phase: PodPhase = PodPhase.PENDING
    scheduler_name: str = DEFAULT_SCHEDULER_NAME
    creation_ms: int = 0
    #: non-None marks a terminating pod (deletionTimestamp set).
    deletion_ms: Optional[int] = None
    scheduling_gated: bool = False
    #: PriorityClass name, consumed by PreemptionToleration policy lookup.
    priority_class_name: str = ""
    #: spec.preemptionPolicy: "Never" disqualifies the pod from preempting
    #: (capacity_scheduling.go:412-416).
    preemption_policy: Optional[str] = None
    #: spec.nodeSelector: all key=value pairs must match node labels.
    node_selector: Mapping[str, str] = field(default_factory=dict)
    #: requiredDuringSchedulingIgnoredDuringExecution node affinity: OR over
    #: terms (empty list = no constraint).
    node_affinity_required: list[NodeSelectorTerm] = field(default_factory=list)
    #: preferredDuringScheduling node affinity terms (weighted score).
    node_affinity_preferred: list[PreferredSchedulingTerm] = field(
        default_factory=list
    )
    tolerations: list[Toleration] = field(default_factory=list)
    topology_spread: list[TopologySpreadConstraint] = field(
        default_factory=list
    )
    pod_affinity_required: list[PodAffinityTerm] = field(default_factory=list)
    pod_affinity_preferred: list[WeightedPodAffinityTerm] = field(
        default_factory=list
    )
    pod_anti_affinity_required: list[PodAffinityTerm] = field(
        default_factory=list
    )
    pod_anti_affinity_preferred: list[WeightedPodAffinityTerm] = field(
        default_factory=list
    )
    #: memoized derived quantities — a pod's container spec is immutable
    #: after creation (k8s semantics), and the snapshot builder re-derives
    #: these for every pod on every cycle. init=False keeps the cache out of
    #: constructors and dataclasses.replace (a spec change must not smuggle
    #: a stale cache).
    _req_cache: Optional[dict] = field(
        default=None, init=False, repr=False, compare=False
    )
    _lim_cache: Optional[dict] = field(
        default=None, init=False, repr=False, compare=False
    )

    def __post_init__(self):
        if not self.uid:
            self.uid = f"{self.namespace}/{self.name}"

    # -- derived ---------------------------------------------------------

    def pod_group(self) -> str:
        return self.labels.get(POD_GROUP_LABEL, "")

    def app_group(self) -> str:
        return self.labels.get(APP_GROUP_LABEL, "")

    def workload_selector(self) -> str:
        return self.labels.get(WORKLOAD_SELECTOR_LABEL, "")

    @property
    def terminating(self) -> bool:
        return self.deletion_ms is not None

    def effective_request(self) -> dict[str, int]:
        """Effective pod request: per resource,
        max(sum of app containers, max over init containers) + overhead —
        exactly /root/reference/pkg/util/resource.go:45-85
        (GetPodEffectiveRequest; init containers are a plain per-resource max,
        with no sidecar special-casing).
        """
        if self._req_cache is not None:
            # fresh copy per call: callers may hold or mutate their result
            # (the NRT cache stores these long-term)
            return dict(self._req_cache)
        resources: dict[str, int] = {}
        for c in self.containers:
            resources = add_quantities(resources, c.requests)

        init_max: dict[str, int] = {}
        for ic in self.init_containers:
            init_max = max_quantities(init_max, ic.requests)
        resources = max_quantities(resources, init_max)

        self._req_cache = add_quantities(resources, self.overhead)
        return dict(self._req_cache)

    def effective_limits(self) -> dict[str, int]:
        """Trimaran-style effective limits: per resource, sum of app
        containers, then max against each init container individually, plus
        overhead (/root/reference/pkg/trimaran/resourcestats.go:121-145
        GetEffectiveResource over container limits)."""
        if self._lim_cache is not None:
            return dict(self._lim_cache)
        resources: dict[str, int] = {}
        for c in self.containers:
            resources = add_quantities(resources, c.limits)
        for ic in self.init_containers:
            resources = max_quantities(resources, ic.limits)
        self._lim_cache = add_quantities(resources, self.overhead)
        return dict(self._lim_cache)

    def tlp_predicted_cpu_millis(
        self, multiplier: float = 1.5, default_millis: int = 1000
    ) -> int:
        """TargetLoadPacking's per-pod CPU prediction: per app container,
        limit if set, else round(request * multiplier), else the default
        1000m; plus pod overhead CPU
        (/root/reference/pkg/trimaran/targetloadpacking/targetloadpacking.go:123-129,
        198-205). Init containers are not counted."""
        total = 0
        for c in self.containers:
            if c.limits.get(CPU):
                total += c.limits[CPU]
            elif c.requests.get(CPU):
                # Go math.Round; requests are non-negative by construction
                total += int(c.requests[CPU] * multiplier + 0.5)
            else:
                total += default_millis
        total += self.overhead.get(CPU, 0)
        return total

    def qos_class(self) -> QOSClass:
        """Mirror of upstream `v1qos.GetPodQOS` (cpu/memory only):
        BestEffort when no container names any cpu/memory request or limit;
        Guaranteed when every container has cpu+memory limits AND the
        aggregate request sum equals the aggregate limit sum per resource
        (absent requests are fine); Burstable otherwise.
        """
        all_containers = list(self.containers) + list(self.init_containers)
        requests: dict[str, int] = {}
        limits: dict[str, int] = {}
        guaranteed = bool(all_containers)
        for c in all_containers:
            limits_found = set()
            for res in (CPU, MEMORY):
                if c.requests.get(res, 0):
                    requests[res] = requests.get(res, 0) + c.requests[res]
                if c.limits.get(res, 0):
                    limits_found.add(res)
                    limits[res] = limits.get(res, 0) + c.limits[res]
            if limits_found != {CPU, MEMORY}:
                guaranteed = False
        if not requests and not limits:
            return QOSClass.BEST_EFFORT
        for res, req_sum in requests.items():
            if limits.get(res) != req_sum:
                guaranteed = False
        return QOSClass.GUARANTEED if guaranteed else QOSClass.BURSTABLE


@dataclass
class Node:
    name: str
    allocatable: Mapping[str, int] = field(default_factory=dict)
    capacity: Mapping[str, int] = field(default_factory=dict)
    labels: Mapping[str, str] = field(default_factory=dict)
    unschedulable: bool = False
    taints: list[Taint] = field(default_factory=list)

    def __post_init__(self):
        if not self.capacity:
            self.capacity = dict(self.allocatable)

    @property
    def region(self) -> str:
        return self.labels.get(REGION_LABEL, "")

    @property
    def zone(self) -> str:
        return self.labels.get(ZONE_LABEL, "")


# ---------------------------------------------------------------------------
# CRDs defined by the reference (apis/scheduling/v1alpha1/types.go)
# ---------------------------------------------------------------------------


class PodGroupPhase(_StrEnum):
    """PodGroup status phase machine
    (/root/reference/apis/scheduling/v1alpha1/types.go:120-150)."""

    PENDING = "Pending"
    PRE_SCHEDULING = "PreScheduling"
    SCHEDULING = "Scheduling"
    SCHEDULED = "Scheduled"
    RUNNING = "Running"
    UNKNOWN = "Unknown"
    FINISHED = "Finished"
    FAILED = "Failed"


@dataclass
class PodGroup:
    name: str
    namespace: str = "default"
    min_member: int = 1
    #: Guaranteed whole-gang resource demand; enables the cluster-capacity
    #: pre-check (/root/reference/pkg/coscheduling/core/core.go:286-305).
    min_resources: Mapping[str, int] = field(default_factory=dict)
    schedule_timeout_seconds: Optional[int] = None
    creation_ms: int = 0
    #: rank-aware workload family (docs/GANGS.md, beyond the reference's
    #: scope): members are RANKS — placed as a whole gang by the
    #: topology-block waterfill (`gangs.topology`) ahead of the per-pod
    #: solve, minimizing inter-rank network cost under the same hard
    #: constraints. min_member stays the quorum.
    rank_aware: bool = False
    #: elastic DL-job bounds (Tesserae, arxiv 2508.04953): desired replica
    #: width this gang should run at (clamped into
    #: [min_member, max_replicas]); None = rigid gang (desired == min).
    #: The gang phase's reconcile grows/shrinks members between cycles
    #: (`gangs.elastic`), shrink releasing highest-cost ranks first.
    desired_replicas: Optional[int] = None
    max_replicas: Optional[int] = None
    # status
    phase: PodGroupPhase = PodGroupPhase.PENDING
    occupied_by: str = ""
    running: int = 0
    succeeded: int = 0
    failed: int = 0
    schedule_start_ms: int = 0

    @property
    def full_name(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class ElasticQuota:
    """Per-namespace elastic quota: `min` is guaranteed, `max` is the cap
    (/root/reference/apis/scheduling/v1alpha1/types.go:35-83)."""

    name: str
    namespace: str = "default"
    min: Mapping[str, int] = field(default_factory=dict)
    max: Mapping[str, int] = field(default_factory=dict)
    # status
    used: Mapping[str, int] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# NodeResourceTopology (external noderesourcetopology-api)
# ---------------------------------------------------------------------------


class TopologyManagerPolicy(enum.IntEnum):
    """Integer codes for the kubelet topology-manager policy mirrored from NRT
    attributes (/root/reference/pkg/noderesourcetopology/nodeconfig/topologymanager.go)."""

    NONE = 0
    BEST_EFFORT = 1
    RESTRICTED = 2
    SINGLE_NUMA_NODE = 3


class TopologyManagerScope(enum.IntEnum):
    CONTAINER = 0
    POD = 1


@dataclass
class NUMAZone:
    numa_id: int
    #: available = allocatable minus used, as published by the node agent.
    available: Mapping[str, int] = field(default_factory=dict)
    #: allocatable per zone (defaults to available when agent omits it).
    allocatable: Mapping[str, int] = field(default_factory=dict)
    #: SLIT-style distance to other zones, keyed by numa_id.
    costs: Mapping[int, int] = field(default_factory=dict)

    def __post_init__(self):
        if not self.allocatable:
            self.allocatable = dict(self.available)


@dataclass
class NodeResourceTopology:
    node_name: str
    zones: list[NUMAZone] = field(default_factory=list)
    policy: TopologyManagerPolicy = TopologyManagerPolicy.NONE
    scope: TopologyManagerScope = TopologyManagerScope.CONTAINER
    max_numa_nodes: int = 8
    #: pod fingerprint stamped by the node agent, validated by the
    #: over-reserve cache resync (/root/reference/pkg/noderesourcetopology/cache/overreserve.go:276-348).
    pod_fingerprint: str = ""
    #: the agent's fingerprint method attribute (podfingerprint
    #: AttributeMethod): "" / "all" = every pod; "with-exclusive-resources"
    #: = only pods pinning cpus/devices were fingerprinted — the resync's
    #: scheduler-side computation must match (store.go:204-222).
    pod_fingerprint_method: str = ""


# ---------------------------------------------------------------------------
# Network-aware CRDs (diktyo appgroup-api / networktopology-api)
# ---------------------------------------------------------------------------


@dataclass
class AppGroupDependency:
    workload_selector: str
    max_network_cost: int = 0


@dataclass
class AppGroupWorkload:
    selector: str
    dependencies: list[AppGroupDependency] = field(default_factory=list)


@dataclass
class AppGroup:
    name: str
    namespace: str = "default"
    workloads: list[AppGroupWorkload] = field(default_factory=list)
    #: status.TopologyOrder — workload selector -> topological index, used by
    #: the TopologicalSort queue comparator
    #: (/root/reference/pkg/networkaware/topologicalsort/topologicalsort.go:102-132).
    topology_order: Mapping[str, int] = field(default_factory=dict)


@dataclass
class NetworkTopology:
    """Origin->destination costs per topology key (region/zone) per weights
    profile (/root/reference/pkg/networkaware/networkoverhead/networkoverhead.go:448-638)."""

    name: str = "nt-default"
    namespace: str = "default"
    #: weightsName -> topologyKey("region"|"zone") -> (origin, dest) -> cost
    weights: Mapping[str, Mapping[str, Mapping[tuple[str, str], int]]] = field(
        default_factory=dict
    )


# ---------------------------------------------------------------------------
# SySched / seccomp
# ---------------------------------------------------------------------------


@dataclass
class SeccompProfile:
    """Syscall allow-list referenced by pod security context / annotations
    (/root/reference/pkg/sysched/sysched.go:124-210)."""

    name: str
    namespace: str = "default"
    syscalls: frozenset[str] = frozenset()

    @property
    def full_name(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass
class PodDisruptionBudget:
    """Minimal PDB surface for preemption's violating-victim partitioning
    (/root/reference/pkg/capacityscheduling/capacity_scheduling.go:889-934):
    label selector + the API-server-computed DisruptionsAllowed budget."""

    name: str
    namespace: str = "default"
    #: match-labels selector; empty matches NOTHING (upstream semantics)
    selector: Mapping[str, str] = field(default_factory=dict)
    disruptions_allowed: int = 0
    #: pod names already being disrupted (not re-counted)
    disrupted_pods: frozenset[str] = frozenset()

    def matches(self, pod: "Pod") -> bool:
        if not self.selector or pod.namespace != self.namespace or not pod.labels:
            return False
        return all(pod.labels.get(k) == v for k, v in self.selector.items())


@dataclass
class PriorityClass:
    """PriorityClass with the preemption-toleration annotations
    (/root/reference/pkg/preemptiontoleration/policy.go)."""

    name: str
    value: int = 0
    annotations: Mapping[str, str] = field(default_factory=dict)
