"""Profile configuration: the KubeSchedulerConfiguration-equivalent surface.

The reference decodes per-plugin args from YAML through a scheme with
versioned defaulting and validation (SURVEY.md §5;
/root/reference/apis/config/types.go:28-307, v1/defaults.go:29-256,
validation/validation_pluginargs.go:48-110). Here a plain dict (parsed from
YAML/JSON upstream of this module) lowers to a `framework.Profile`:

    {
      "profileName": "tpu-scheduler",
      "plugins": ["Coscheduling", "CapacityScheduling", ...],
      "pluginConfig": [
        {"name": "Coscheduling", "args": {"permitWaitingTimeSeconds": 10}},
        ...
      ],
    }

Plugin constructors carry the reference's defaulting and validation (each
raises ValueError on invalid args, mirroring validation_pluginargs.go).
"""

from __future__ import annotations

from typing import Mapping, Optional, Sequence

from scheduler_plugins_tpu.framework.runtime import (
    PackingConfig,
    Profile,
    SOLVE_MODES,
)

#: camelCase arg name -> plugin constructor kwarg, per plugin
_ARG_MAPS: dict[str, dict[str, str]] = {
    "Coscheduling": {
        "permitWaitingTimeSeconds": "permit_waiting_seconds",
        "podGroupBackoffSeconds": "pod_group_backoff_seconds",
        "podGroupRejectPercentage": "reject_percentage",
    },
    "NodeResourcesAllocatable": {"resources": "resources", "mode": "mode"},
    "TargetLoadPacking": {
        "targetUtilization": "target_utilization_percent",
        "watcherAddress": "watcher_address",
        "metricProvider": "metric_provider",
        "defaultRequests": "default_requests",
        "defaultRequestsMultiplier": "default_requests_multiplier",
    },
    "LoadVariationRiskBalancing": {
        "safeVarianceMargin": "safe_variance_margin",
        "safeVarianceSensitivity": "safe_variance_sensitivity",
        "watcherAddress": "watcher_address",
        "metricProvider": "metric_provider",
    },
    "LowRiskOverCommitment": {
        "smoothingWindowSize": "smoothing_window_size",
        "riskLimitWeights": "risk_limit_weights",
        "watcherAddress": "watcher_address",
        "metricProvider": "metric_provider",
    },
    "Peaks": {
        "nodePowerModel": "node_power_model",
        "watcherAddress": "watcher_address",
        "metricProvider": "metric_provider",
    },
    "NodeResourceTopologyMatch": {
        "scoringStrategy": "scoring_strategy",
        "resources": "resources",
        "cacheResyncPeriodSeconds": "cache_resync_period_seconds",
        "discardReservedNodes": "discard_reserved_nodes",
        "cache": "cache",
    },
    "NetworkOverhead": {
        "weightsName": "weights_name",
        "networkTopologyName": "network_topology_name",
        "namespaces": "namespaces",
    },
    "TopologicalSort": {"namespaces": "namespaces"},
    "SySched": {
        "defaultProfileNamespace": "default_profile_namespace",
        "defaultProfileName": "default_profile_name",
    },
    "CapacityScheduling": {
        "minCandidateNodesPercentage": "min_candidate_nodes_percentage",
        "minCandidateNodesAbsolute": "min_candidate_nodes_absolute",
    },
    "PreemptionToleration": {
        "minCandidateNodesPercentage": "min_candidate_nodes_percentage",
        "minCandidateNodesAbsolute": "min_candidate_nodes_absolute",
    },
    "PodState": {},
    "QOSSort": {},
    "NodeAffinity": {"addedAffinity": "added_affinity"},
    "TaintToleration": {},
    "PodTopologySpread": {},
    "InterPodAffinity": {
        "hardPodAffinityWeight": "hard_pod_affinity_weight",
        "ignorePreferredTermsOfExistingPods":
            "ignore_preferred_terms_of_existing_pods",
    },
    "CrossNodePreemption": {"maxPool": "max_pool"},
}


#: camelCase packingConfig arg -> `framework.runtime.PackingConfig` kwarg
#: (the solve-mode analog of `_ARG_MAPS`; validation lives in the
#: PackingConfig constructor like the plugin constructors)
_PACKING_ARG_MAP = {
    "iterations": "iterations",
    "priceWeight": "price_weight",
    "temperature": "temperature",
    "decay": "decay",
    "moverCap": "mover_cap",
}


def _registry():
    from scheduler_plugins_tpu import plugins as p

    return {
        "Coscheduling": p.Coscheduling,
        "CapacityScheduling": p.CapacityScheduling,
        "NodeResourcesAllocatable": p.NodeResourcesAllocatable,
        "NodeResourceTopologyMatch": p.NodeResourceTopologyMatch,
        "TargetLoadPacking": p.TargetLoadPacking,
        "LoadVariationRiskBalancing": p.LoadVariationRiskBalancing,
        "LowRiskOverCommitment": p.LowRiskOverCommitment,
        "Peaks": p.Peaks,
        "NetworkOverhead": p.NetworkOverhead,
        "TopologicalSort": p.TopologicalSort,
        "PreemptionToleration": p.PreemptionToleration,
        "SySched": p.SySched,
        "PodState": p.PodState,
        "QOSSort": p.QOSSort,
        # in-tree companions (upstream kube-scheduler, not /root/reference):
        # real profiles enable these alongside the reference plugins
        "NodeAffinity": p.NodeAffinity,
        "TaintToleration": p.TaintToleration,
        "PodTopologySpread": p.PodTopologySpread,
        "InterPodAffinity": p.InterPodAffinity,
        "CrossNodePreemption": p.CrossNodePreemption,
    }


def available_plugins() -> tuple[str, ...]:
    """The full plugin roster (19) — the 14 plugins the reference compiles into
    its scheduler binary (/root/reference/cmd/scheduler/main.go:50-67;
    CrossNodePreemption is registration-commented-out there and implemented
    here as an opt-in spec mirror, see docs/PARITY.md) plus the in-tree
    companions (NodeAffinity,
    TaintToleration, PodTopologySpread, InterPodAffinity) that real
    profiles combine them with."""
    return tuple(sorted(_registry()))


#: plugin-specific arg exporters for constructor args NOT stored under the
#: kwarg's own attribute name (the common case IS the attribute name —
#: `profile_spec` tries that first)
_SPEC_OVERRIDES = {
    "NodeResourcesAllocatable": lambda p: {
        "resources": [list(r) for r in p.resources],
        "mode": "Least" if p.mode_sign < 0 else "Most",
    },
    "NodeResourceTopologyMatch": lambda p: {
        "scoringStrategy": p.strategy,
        "resources": [list(r) for r in p.resources],
    },
}


def _json_safe(value):
    """`value` lowered to JSON-encodable form, or None when it isn't
    (tuples become lists; objects are dropped — lossy export is flagged by
    the replay's static_key/aux cross-checks, not silently trusted)."""
    if isinstance(value, (bool, int, float, str)) or value is None:
        return value
    if isinstance(value, (tuple, list)):
        items = [_json_safe(v) for v in value]
        return items if all(
            v is not None or o is None for v, o in zip(items, value)
        ) else None
    if isinstance(value, Mapping):
        out = {}
        for k, v in value.items():
            if not isinstance(k, str):
                return None
            safe = _json_safe(v)
            if safe is None and v is not None:
                return None
            out[k] = safe
        return out
    return None


def profile_spec(profile: Profile) -> dict:
    """Best-effort inverse of `load_profile`: a {plugins, pluginConfig}
    mapping that reconstructs `profile`'s plugin roster — the flight
    recorder's profile record (`utils.flightrec`) when the caller has no
    original config file. Args are exported from the constructor-kwarg
    attributes plugins keep (plus `_SPEC_OVERRIDES` for renamed ones);
    anything non-JSON-able (e.g. NodeAffinity `addedAffinity` objects) is
    omitted — the replayer detects the loss via the recorded
    `static_key`/aux digests instead of failing the export."""
    names = []
    plugin_config = []
    for plugin in profile.plugins:
        cls = type(plugin).__name__
        names.append(cls)
        override = _SPEC_OVERRIDES.get(cls)
        args = dict(override(plugin)) if override else {}
        for camel, kwarg in _ARG_MAPS.get(cls, {}).items():
            if camel in args:
                continue
            value = _json_safe(getattr(plugin, kwarg, None))
            if value is not None:
                args[camel] = value
        if args:
            plugin_config.append({"name": cls, "args": args})
    spec = {"profileName": profile.name, "plugins": names}
    if plugin_config:
        spec["pluginConfig"] = plugin_config
    # solve-mode surface (ISSUE 14): exported only off-default so legacy
    # specs round-trip byte-identically
    if profile.solve_mode != "sequential":
        spec["solveMode"] = profile.solve_mode
        pk = profile.packing
        packing_args = {
            camel: getattr(pk, kwarg)
            for camel, kwarg in _PACKING_ARG_MAP.items()
            if getattr(pk, kwarg) != getattr(PackingConfig, kwarg)
        }
        if packing_args:
            spec["packingConfig"] = packing_args
    # score weights, aligned with the `plugins` list (the upstream
    # Plugins.Score.Enabled[].Weight knob) — what the tuning observatory
    # (tools/tune.py) emits a tuned profile through
    if any(p.weight != type(p).weight for p in profile.plugins):
        spec["weights"] = [int(p.weight) for p in profile.plugins]
    return spec


def load_profile(config: Mapping) -> Profile:
    """Lower a configuration mapping into a Profile.

    Unknown plugin names or args raise ValueError (the scheme would fail to
    decode); per-plugin validation happens in the constructors.
    """
    registry = _registry()
    args_by_plugin: dict[str, Mapping] = {}
    for entry in config.get("pluginConfig", []):
        args_by_plugin[entry["name"]] = entry.get("args", {})

    plugins = []
    for name in config.get("plugins", []):
        cls = registry.get(name)
        if cls is None:
            raise ValueError(f"unknown plugin {name!r}")
        arg_map = _ARG_MAPS.get(name, {})
        kwargs = {}
        for key, value in args_by_plugin.get(name, {}).items():
            if key not in arg_map:
                raise ValueError(f"unknown arg {key!r} for plugin {name}")
            kwargs[arg_map[key]] = value
        plugins.append(cls(**kwargs))
    weights = config.get("weights")
    if weights is not None:
        if len(weights) != len(plugins):
            raise ValueError(
                f"weights list has {len(weights)} entries for "
                f"{len(plugins)} plugins"
            )
        for plugin, w in zip(plugins, weights):
            w = int(w)
            if w < 1:
                raise ValueError(f"plugin weight must be >= 1, got {w}")
            plugin.weight = w
    solve_mode = config.get("solveMode", "sequential")
    if solve_mode not in SOLVE_MODES:
        raise ValueError(
            f"unknown solveMode {solve_mode!r}; expected one of "
            f"{SOLVE_MODES}"
        )
    packing_kwargs = {}
    for key, value in config.get("packingConfig", {}).items():
        if key not in _PACKING_ARG_MAP:
            raise ValueError(f"unknown packingConfig arg {key!r}")
        packing_kwargs[_PACKING_ARG_MAP[key]] = value
    packing = PackingConfig(**packing_kwargs)
    profile = Profile(
        plugins=plugins, name=config.get("profileName", "tpu-scheduler"),
        solve_mode=solve_mode, packing=packing,
    )
    if solve_mode == "packing":
        # the packing refinement re-places pods on any fitting node,
        # which is only sound on the targeted fast-path profile shape
        # (one pod-invariant scoring plugin, no per-(pod, node) filters)
        # — reject at config time, not first-solve time
        from scheduler_plugins_tpu.parallel.solver import fast_path_scoring

        if fast_path_scoring(profile.plugins) is None:
            raise ValueError(
                "solveMode 'packing' requires a targeted fast-path "
                "profile (exactly one pod-invariant scoring plugin with "
                "positive weight and no filter plugins)"
            )
    return profile
