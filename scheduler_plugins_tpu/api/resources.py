"""Resource vocabulary and integer quantity encoding.

The reference stores quantities as k8s `resource.Quantity` int64 values — CPU in
millicores, memory/ephemeral-storage in bytes, extended ("scalar") resources as
raw counts (see /root/reference/pkg/noderesources/resource_allocation.go:84-96
and /root/reference/pkg/capacityscheduling/elasticquota.go:189-221). We pin the
same integer units so decisions are bit-identical; the tensor layout fixes an
ordered resource axis R shared by every array in a snapshot.
"""

from __future__ import annotations

from collections.abc import Iterable, Mapping

import numpy as np

# Canonical names (match k8s v1.ResourceName strings).
CPU = "cpu"
MEMORY = "memory"
EPHEMERAL_STORAGE = "ephemeral-storage"
PODS = "pods"

#: The first four slots of every resource axis, in fixed order. Extended
#: resources (nvidia.com/gpu, hugepages-2Mi, ...) are appended per snapshot.
CANONICAL = (CPU, MEMORY, EPHEMERAL_STORAGE, PODS)

# Defaults used by the upstream "NonZeroRequested" accounting that the
# Allocatable scorer reads (upstream k/k pkg/scheduler/util/nonzero):
# pods with no cpu/mem request are charged these amounts for *scoring* only.
DEFAULT_MILLI_CPU_REQUEST = 100  # 0.1 core
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024  # 200 MiB


class ResourceIndex:
    """Ordered resource-name <-> axis-position mapping for one snapshot.

    Immutable once built. `encode` turns a {name: int} mapping into a dense
    int64 vector on the fixed axis; unknown names raise (callers must build the
    index from the union of names up front — silent drops would corrupt quota
    sums).
    """

    def __init__(self, extended: Iterable[str] = ()):
        names = list(CANONICAL)
        for name in extended:
            if name not in names:
                names.append(name)
        self._names: tuple[str, ...] = tuple(names)
        self._pos = {name: i for i, name in enumerate(self._names)}

    @classmethod
    def union(cls, *mappings: Mapping[str, int]) -> "ResourceIndex":
        """Build an index covering every resource named in `mappings`."""
        extended = []
        for m in mappings:
            for name in m:
                if name not in CANONICAL and name not in extended:
                    extended.append(name)
        return cls(extended)

    @property
    def names(self) -> tuple[str, ...]:
        return self._names

    def __len__(self) -> int:
        return len(self._names)

    def __contains__(self, name: str) -> bool:
        return name in self._pos

    def position(self, name: str) -> int:
        return self._pos[name]

    def encode(self, quantities: Mapping[str, int], default: int = 0) -> np.ndarray:
        vec = np.full(len(self._names), default, dtype=np.int64)
        for name, qty in quantities.items():
            vec[self._pos[name]] = int(qty)
        return vec

    def decode(self, vec: np.ndarray) -> dict[str, int]:
        return {name: int(vec[i]) for i, name in enumerate(self._names) if vec[i]}

    def is_extended(self, name: str) -> bool:
        return name not in CANONICAL


def add_quantities(a: Mapping[str, int], b: Mapping[str, int]) -> dict[str, int]:
    out = dict(a)
    for k, v in b.items():
        out[k] = out.get(k, 0) + v
    return out


def max_quantities(a: Mapping[str, int], b: Mapping[str, int]) -> dict[str, int]:
    out = dict(a)
    for k, v in b.items():
        out[k] = max(out.get(k, 0), v)
    return out
