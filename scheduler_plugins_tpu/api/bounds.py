"""Declared static magnitude bounds on the solver's input families
(ISSUE 18, KA003).

The repo's exactness story rests on two documented numeric facts that
until now lived only in comments (`ops/numa.py`, `ops/assign.py`,
`parallel/kernels.py`): float64 arithmetic on integers is EXACT below
2^53, and every resource-quantity aggregation the solver performs stays
below that line. `tools/kernel_audit.py` turns the second fact into a
checked one: it propagates the bounds declared HERE through the traced
programs (casts, sums, cumsums, dot_generals, scan carries) with an
interval lattice and flags any float64 accumulation of exact integer
quantities — or any int32 demotion — it cannot prove in-range.

Two kinds of declaration:

- **per-element bounds** (`LABEL_BOUNDS`): a regex over input-leaf
  provenance labels (`tools/jaxpr_audit.label_leaves` vocabulary —
  `snap.pods.req`, `state.free`, ...) → the max-abs bound of one
  element. Resource quantities are int64 in reference units (cpu
  millicores, memory bytes); `QUANTITY_ELEM_MAX` = 2^38 caps one
  element at 256 GiB / 274M cores — beyond any single node the
  reference supports. int32/bool leaves need no row (their dtype is
  the bound); int64/float leaves without a row audit as UNKNOWN and
  cannot prove anything downstream.
- **the aggregation invariant** (`QUANTITY_SUM_MAX`): sums, prefix
  sums and shard-psums of DISJOINT quantity elements stay < 2^53
  because the cluster total does — quota caps and the capacity audit
  enforce `used <= quota max <= sum(capacity)` at runtime, and
  2^53 reference units is ~9 PB / 9T millicores of cluster. When the
  naive interval product (elements x axis length) overflows 2^53 on a
  quantity aggregation, the auditor substitutes this declared cap and
  RECORDS THE ASSUMPTION in docs/kernel_audit.json — the manifest
  shows exactly which claims rest on the invariant rather than on
  arithmetic.

Blessed exactness helpers (`EXACT_FN_BOUNDS`): jitted helpers whose
exactness argument is structural, not interval-provable — base-2^18
limb recombination reconstructs the ORIGINAL < 2^53 value even though
the naive interval on `l2 * 2^36` overflows. They are audited at the
call boundary (declared result bound, assumption recorded) and are the
only sanctioned way to cast unproven int64 quantities to float64
(graft_lint GL013 enforces the source-level half of that contract).
"""

from __future__ import annotations

import re

__all__ = [
    "QUANTITY_ELEM_MAX",
    "QUANTITY_SUM_MAX",
    "F64_EXACT_MAX",
    "I32_MAX",
    "NUMA_DISTANCE_MAX",
    "NETWORK_COST_MAX",
    "LABEL_BOUNDS",
    "EXACT_FN_BOUNDS",
    "leaf_bound",
    "is_quantity_label",
]

#: float64 represents every integer strictly below 2^53 exactly
F64_EXACT_MAX = 1 << 53
#: int32 range (the demotion-safety line for KA003's second check)
I32_MAX = 1 << 31

#: one resource-quantity element (int64 reference units): 2^38 covers a
#: 256 GiB node memory row or 274M millicores — no single element the
#: reference's quantity parsing produces exceeds it
QUANTITY_ELEM_MAX = 1 << 38

#: the declared aggregation invariant: any sum of disjoint quantity
#: elements is bounded by the cluster total, kept < 2^53 by the runtime
#: quota/capacity caps (ops/assign.py, ops/numa.py document the same
#: fact per call site; kernels.py's limb scheme is sized to it)
QUANTITY_SUM_MAX = (1 << 53) - 1

#: NUMA distance matrix entries are SLIT-style small ints (<= 100;
#: ops/numa.py documents the table), declared tighter than their int32
#: dtype so distance-weighted sums stay provable
NUMA_DISTANCE_MAX = 100

#: network cost thresholds / cost-table entries: ops/network.py keeps
#: tallies in int32 and float32 dot_generals and documents "every tally
#: is bounded by MAX_COST * total placed pods, far inside int32" — that
#: argument needs the per-entry cost cap declared here
NETWORK_COST_MAX = 1 << 24

#: (label regex, max-abs bound, kind) — kind "elem" marks the leaf a
#: per-element resource quantity (eligible for the aggregation
#: invariant AND in scope for KA003's flags); kind "plain" is a bound
#: with no quantity semantics. First match wins; labels are the
#: `label_leaves` vocabulary. Keep rows FULL-label anchored — a loose
#: suffix match that silently blesses a new field defeats the audit.
LABEL_BOUNDS = (
    # -- per-element resource quantities (int64 reference units) --------
    (r"^(snap|state)\.nodes\.(alloc|capacity|requested|nonzero_requested"
     r"|limits)$", QUANTITY_ELEM_MAX, "elem"),
    (r"^snap\.pods\.(req|container_req|limits|predicted_cpu_millis)$",
     QUANTITY_ELEM_MAX, "elem"),
    (r"^snap\.quota\.(min|max|used|nom_req)$", QUANTITY_ELEM_MAX, "elem"),
    (r"^snap\.numa\.(allocatable|available)$", QUANTITY_ELEM_MAX, "elem"),
    (r"^snap\.ranks\.(rank_req|quota_max)$", QUANTITY_ELEM_MAX, "elem"),
    (r"^snap\.gangs\.(min_resources|cluster_slack)$",
     QUANTITY_ELEM_MAX, "elem"),
    # network max-cost thresholds are CONFIG cost caps compared against
    # the small zone/region cost tables — not resource quantities. The
    # bound backs ops/network.py's int32 internals and its "f32 tallies
    # are exact (counts < 2^24)" precondition.
    (r"^snap\.network\.(dep_max_cost|cls_dep_max_cost)$",
     NETWORK_COST_MAX, "plain"),
    (r"^state\.(free|eq_used|gang_inflight)$", QUANTITY_ELEM_MAX, "elem"),
    (r"^state\.side\.(gang_slack|quota_used)$", QUANTITY_ELEM_MAX, "elem"),
    (r"^state\.numa_avail$", QUANTITY_ELEM_MAX, "elem"),
    # serving delta/upsert columns (the packed int64 quantity columns of
    # serving_delta_apply / serving_side_apply)
    (r"^up\.(alloc|capacity)$", QUANTITY_ELEM_MAX, "elem"),
    (r"^d\.(requested|nonzero|limits)$", QUANTITY_ELEM_MAX, "elem"),
    (r"^sd\.(g_slack|q_used)$", QUANTITY_ELEM_MAX, "elem"),
    # ring-election payloads (the pallas kernel programs' positional
    # args): exact quantities or quantity prefix sums by contract —
    # already aggregated once, so declared at the SUM cap, kind elem
    # keeps them in KA003 scope
    (r"^elect\.", QUANTITY_SUM_MAX, "elem"),
    # -- bounded non-quantity int64 families ----------------------------
    (r"^snap\.pods\.priority$", I32_MAX - 1, "plain"),
    (r"^snap\.(pods|gangs)\.creation_ms$", 1 << 45, "plain"),
    (r"^snap\.scheduling\.(pref_score|tol_prefer|waff_weight|track_base"
     r"|spread_max_skew|spread_min_domains)$", I32_MAX - 1, "plain"),
    (r"^snap\.numa\.distances$", NUMA_DISTANCE_MAX, "plain"),
    (r"^state\.sel_dom_counts$", I32_MAX - 1, "plain"),
    # plugin weight vectors ride the aux channel as small int64 config
    # scalars (profile weights are <= 2^20 by construction — framework
    # normalizes weights to the reference's int32 plugin-weight range)
    (r"^aux\.weights$", 1 << 20, "plain"),
    (r"^aux(\.|\[)", QUANTITY_ELEM_MAX, "plain"),
    # cfg6 raw score tensor: plugin scores are weight * normalized-score
    # products, bounded well under 2^45 by the weight cap above
    (r"^score_raw$", 1 << 45, "plain"),
)

_COMPILED = tuple(
    (re.compile(pat), bound, kind) for pat, bound, kind in LABEL_BOUNDS
)

#: blessed exactness helpers: jitted-function name -> declared max-abs
#: result bound. The auditor assigns the declared bound (exact integer,
#: quantity kind) at the pjit call boundary and records the assumption;
#: graft_lint GL013 blesses the same names at the source level.
EXACT_FN_BOUNDS = {
    # base-2^18 limb recombination (parallel/kernels.py join_limbs):
    # reconstructs the original value, which is a quantity prefix sum
    # < 2^53 by the aggregation invariant; the naive interval on
    # l2 * 2^36 cannot see that
    "join_limbs": QUANTITY_SUM_MAX,
    # utils/intmath.py exact_f64: the sanctioned int64 -> float64 cast
    # for values the caller asserts are quantity-scale (< 2^53)
    "exact_f64": QUANTITY_SUM_MAX,
    # ops/allocatable.py demote_scores_int32: the order-preserving int64
    # -> int32 score demotion — its < 2^23 result magnitude is enforced
    # by a DYNAMIC right shift, structural rather than interval-provable
    "demote_scores_int32": 1 << 24,
}


def _dtype_cap(dtype: str):
    if dtype == "bool":
        return 1
    if dtype in ("int32", "uint32"):
        return I32_MAX - 1
    if dtype in ("int8", "uint8", "int16", "uint16"):
        return (1 << 16) - 1
    return None


def leaf_bound(label: str, dtype: str):
    """(max-abs bound or None, kind) for one input leaf: the tighter of
    the declared row and the dtype's own range (a declared quantity row
    on an int32 leaf keeps the int32 cap). int64/float leaves without a
    row are UNKNOWN (bound None) — nothing downstream of them can be
    proven exact."""
    declared, kind = None, "plain"
    for rx, bound, k in _COMPILED:
        if rx.match(label):
            declared, kind = bound, k
            break
    cap = _dtype_cap(dtype)
    if declared is None:
        return cap, kind
    if cap is None:
        return declared, kind
    return min(declared, cap), kind


def is_quantity_label(label: str) -> bool:
    """True when the label is a declared per-element resource quantity
    (the taint family KA003's flags are scoped to)."""
    for rx, _bound, kind in _COMPILED:
        if rx.match(label):
            return kind == "elem"
    return False
