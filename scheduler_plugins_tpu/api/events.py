"""Cluster-event kind table — THE one copy of the "Resource/Action" strings.

The reference registers cluster events per plugin via EventsToRegister
(framework.ClusterEventWithHint, e.g. coscheduling.go:113-122) and the
scheduling queue gates requeues on them. Here the same kinds flow through
three seams that previously each spelled the strings by hand:

- `state.cluster.Cluster.note_event` (the store's mutation hooks),
- `bridge.feed` (delete acks for CR kinds the store has no remover for),
- plugin `events_to_register()` registrations and the framework's
  `BUILTIN_EVENTS`.

A typo in any one of them silently broke requeue gating (the event would
never match a registration); with this table the spelling exists once.
`KIND_<RESOURCE>_<ACTION>` constants are plain strings so every existing
comparison, dict key and JSON serialization keeps working unchanged.

This module is also the delta taxonomy the serving engine consumes
(`serving.deltas`): `NODE_COLUMN_EVENTS` names exactly the kinds that can
change the resident node tensors, and `SERVE_REBASE_EVENTS` the kinds
whose effects the O(changed) scatter programs cannot express (row-order
or side-table changes) — see docs/SERVING.md for the mapping.
"""

from __future__ import annotations

# -- core objects -----------------------------------------------------------
NODE_ADD = "Node/Add"
NODE_UPDATE = "Node/Update"
NODE_DELETE = "Node/Delete"
POD_ADD = "Pod/Add"
POD_UPDATE = "Pod/Update"
POD_DELETE = "Pod/Delete"

# -- scheduler-plugins CRs --------------------------------------------------
POD_GROUP_ADD = "PodGroup/Add"
POD_GROUP_UPDATE = "PodGroup/Update"
POD_GROUP_DELETE = "PodGroup/Delete"
ELASTIC_QUOTA_ADD = "ElasticQuota/Add"
ELASTIC_QUOTA_UPDATE = "ElasticQuota/Update"
ELASTIC_QUOTA_DELETE = "ElasticQuota/Delete"
NRT_ADD = "NodeResourceTopology/Add"
NRT_UPDATE = "NodeResourceTopology/Update"
NRT_DELETE = "NodeResourceTopology/Delete"
APP_GROUP_ADD = "AppGroup/Add"
APP_GROUP_UPDATE = "AppGroup/Update"
APP_GROUP_DELETE = "AppGroup/Delete"
NETWORK_TOPOLOGY_ADD = "NetworkTopology/Add"
NETWORK_TOPOLOGY_UPDATE = "NetworkTopology/Update"
NETWORK_TOPOLOGY_DELETE = "NetworkTopology/Delete"
SECCOMP_PROFILE_ADD = "SeccompProfile/Add"
SECCOMP_PROFILE_UPDATE = "SeccompProfile/Update"
SECCOMP_PROFILE_DELETE = "SeccompProfile/Delete"

# -- companion objects ------------------------------------------------------
PRIORITY_CLASS_ADD = "PriorityClass/Add"
PRIORITY_CLASS_UPDATE = "PriorityClass/Update"
PRIORITY_CLASS_DELETE = "PriorityClass/Delete"
NAMESPACE_ADD = "Namespace/Add"
NAMESPACE_UPDATE = "Namespace/Update"
NAMESPACE_DELETE = "Namespace/Delete"
PDB_ADD = "PodDisruptionBudget/Add"
PDB_UPDATE = "PodDisruptionBudget/Update"
PDB_DELETE = "PodDisruptionBudget/Delete"

#: every kind the store can emit, grouped by resource — the registry a
#: requeue registration is validated against (an unknown kind can never
#: fire, so registering one is a bug, not a no-op)
EVENT_KINDS = frozenset({
    NODE_ADD, NODE_UPDATE, NODE_DELETE,
    POD_ADD, POD_UPDATE, POD_DELETE,
    POD_GROUP_ADD, POD_GROUP_UPDATE, POD_GROUP_DELETE,
    ELASTIC_QUOTA_ADD, ELASTIC_QUOTA_UPDATE, ELASTIC_QUOTA_DELETE,
    NRT_ADD, NRT_UPDATE, NRT_DELETE,
    APP_GROUP_ADD, APP_GROUP_UPDATE, APP_GROUP_DELETE,
    NETWORK_TOPOLOGY_ADD, NETWORK_TOPOLOGY_UPDATE, NETWORK_TOPOLOGY_DELETE,
    SECCOMP_PROFILE_ADD, SECCOMP_PROFILE_UPDATE, SECCOMP_PROFILE_DELETE,
    PRIORITY_CLASS_ADD, PRIORITY_CLASS_UPDATE, PRIORITY_CLASS_DELETE,
    NAMESPACE_ADD, NAMESPACE_UPDATE, NAMESPACE_DELETE,
    PDB_ADD, PDB_UPDATE, PDB_DELETE,
})

#: kinds whose effects land entirely in the resident NODE tensors (alloc,
#: capacity, mask, usage columns) — the serving engine expresses these as
#: O(changed) scatter deltas (serving.deltas)
NODE_COLUMN_EVENTS = frozenset({
    NODE_ADD, NODE_UPDATE, POD_ADD, POD_UPDATE, POD_DELETE,
})

#: kinds that invalidate the resident row order or an excluded side table:
#: the serving engine re-bases (full re-snapshot) when one fires — the
#: same rule `Cluster._native_rebuild` applies to the C++ columnar mirror
SERVE_REBASE_EVENTS = frozenset({NODE_DELETE})

# -- pod-lifecycle ledger transitions (observability plane) ----------------
#: the `obs.ledger` transition vocabulary — NOT store mutation kinds (they
#: never enter `EVENT_KINDS` or requeue gating) but registered here so the
#: ledger, the store hooks that feed it, and the timeline renderers spell
#: one set of strings, exactly like the mutation kinds above
LIFECYCLE_FIRST_SEEN = "PodLifecycle/FirstSeen"
LIFECYCLE_WAIT = "PodLifecycle/Wait"
LIFECYCLE_UNSCHEDULABLE = "PodLifecycle/Unschedulable"
LIFECYCLE_NOMINATED = "PodLifecycle/Nominated"
LIFECYCLE_NOMINATION_CLEARED = "PodLifecycle/NominationCleared"
LIFECYCLE_RESERVED = "PodLifecycle/Reserved"
LIFECYCLE_BOUND = "PodLifecycle/Bound"
LIFECYCLE_TERMINATING = "PodLifecycle/Terminating"
LIFECYCLE_DELETED = "PodLifecycle/Deleted"
LIFECYCLE_GATE = "PodLifecycle/Gate"

#: every transition the ledger can record — appends are validated against
#: this set (an unregistered kind is a bug in the feeding seam, not a new
#: feature)
LIFECYCLE_KINDS = frozenset({
    LIFECYCLE_FIRST_SEEN, LIFECYCLE_WAIT, LIFECYCLE_UNSCHEDULABLE,
    LIFECYCLE_NOMINATED, LIFECYCLE_NOMINATION_CLEARED, LIFECYCLE_RESERVED,
    LIFECYCLE_BOUND, LIFECYCLE_TERMINATING, LIFECYCLE_DELETED,
    LIFECYCLE_GATE,
})
assert not (LIFECYCLE_KINDS & EVENT_KINDS)

#: every kind the rank-aware gang phase can emit or gate on
#: (`gangs.phase.GangPhase`): elastic growth arrives as Pod/Add, binds as
#: Pod/Update, shrink as Pod/Delete, spec changes as PodGroup/Update —
#: all spelled HERE, so the phase introduces no literal kind strings and
#: a parked gang member requeues on exactly the kinds Coscheduling
#: already registers (plus Pod/Delete: freed capacity can complete a
#: previously capacity-rejected gang)
GANG_EVENTS = frozenset({
    POD_ADD, POD_UPDATE, POD_DELETE,
    POD_GROUP_ADD, POD_GROUP_UPDATE, POD_GROUP_DELETE,
    NODE_ADD, NODE_UPDATE,
    NETWORK_TOPOLOGY_ADD, NETWORK_TOPOLOGY_UPDATE,
})
assert GANG_EVENTS <= EVENT_KINDS
