"""Typed host-side API surface: resource units, cluster objects (pods, nodes,
PodGroups, ElasticQuotas, NodeResourceTopologies, AppGroups, NetworkTopologies)
and plugin configuration args with defaults/validation — the equivalent of the
reference's `apis/` tree (CRDs in apis/scheduling/v1alpha1, plugin args in
apis/config)."""

from scheduler_plugins_tpu.api.resources import (  # noqa: F401
    CANONICAL,
    CPU,
    EPHEMERAL_STORAGE,
    MEMORY,
    PODS,
    ResourceIndex,
)
