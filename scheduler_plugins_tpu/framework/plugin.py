"""Plugin trait layer — the tensor equivalent of the `fwk.*Plugin` interfaces.

The reference's extension points receive (pod, nodeInfo) pairs one at a time
(/root/reference/pkg/coscheduling/coscheduling.go:49-55 asserts the interface
set per plugin). Here each extension point is a masked tensor transformation
evaluated inside the jitted solve:

- `admit`       PreFilter verdict for one pod: scalar bool (reject before the
                node sweep).
- `filter`      (N,) node feasibility for one pod.
- `score`       (N,) raw int64 node scores for one pod.
- `normalize`   per-pod transform of the raw scores over feasible nodes.
- `commit`      Reserve: fold the chosen placement into the SolverState carried
                through the scan (quota usage, gang counts, NUMA deductions).
- `queue_key`   host-side QueueSort key for a Pod object (lower sorts first).

All tensor methods run under jit and must be pure; `prepare(meta)` is called
once per snapshot layout so plugins can bake resource-axis-aligned constants
(e.g. the allocatable weight vector).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from flax import struct

from scheduler_plugins_tpu.api import events as ev
from scheduler_plugins_tpu.state.snapshot import ClusterSnapshot, SnapshotMeta


@struct.dataclass
class SolverState:
    """Mutable-across-pods solver state, carried through the assignment scan.

    `free` mirrors NodeInfo leftover capacity; `eq_used` mirrors the
    ElasticQuotaInfos usage map; `gang_scheduled` counts members placed in
    this cycle (assumed, pre-bind) per gang.
    """

    free: jnp.ndarray  # (N, R) int64
    eq_used: Optional[jnp.ndarray] = None  # (Q, R) int64
    gang_scheduled: Optional[jnp.ndarray] = None  # (G,) int32
    #: (G, R) demand placed by each gang earlier in this scan — added back in
    #: the MinResources cluster check (the gang's own pods don't count
    #: against it, core.go:433-467)
    gang_inflight: Optional[jnp.ndarray] = None
    #: (W, N) live placed-pod counts per AppGroup workload — in-cycle
    #: placements must be visible to later pods' network tallies
    net_placed: Optional[jnp.ndarray] = None
    #: (N, Z, R) live NUMA zone availability with in-cycle placements
    #: pessimistically deducted from every reported zone of the chosen node
    #: (cache/store.go:129-160). Carried as FLOAT64 — exact for integer
    #: quantities below 2^53 — so the scan body's feasibility compares and
    #: score divisions run entirely in f64 with no per-step int64
    #: temporaries or conversions (integer division is the slow path on
    #: both backends)
    numa_avail: Optional[jnp.ndarray] = None
    #: (P,) which batch pods have placed so far in this scan — nominee
    #: aggregates drop a nominee the moment it places (upstream removes
    #: assumed pods from the nominated set)
    placed_mask: Optional[jnp.ndarray] = None
    #: (TR, N) live per-(track, NODE) matching-pod counts (track = unique
    #: (selector, topology key) pair): base = assigned matches, in-cycle
    #: placements added by the BUILT-IN commit
    #: (`ops.selectors.commit_tracks`). Node-level so PodTopologySpread's
    #: node-inclusion policies can mask ineligible nodes per (pod,
    #: constraint) at aggregation time.
    sel_counts: Optional[jnp.ndarray] = None
    #: (TR, D) the same counts pre-aggregated per topology DOMAIN —
    #: InterPodAffinity (no node-inclusion policy) reads this directly so
    #: its per-pod checks stay O(1) row gathers; kept in lockstep by the
    #: same built-in commit
    sel_dom_counts: Optional[jnp.ndarray] = None
    #: (E, D) live anti-affinity domain presence: True when a pod carrying
    #: existing-anti term e occupies a node in domain d; built-in commit
    anti_domains: Optional[jnp.ndarray] = None
    #: (E2, D) live symmetric-score carrier counts (existing pods'
    #: preferred/required affinity terms per domain); built-in commit
    sym_counts: Optional[jnp.ndarray] = None
    #: (G2, M) live rank -> node assignment of the rank-aware gang phase
    #: (`gangs.topology.gang_solve_body`): initialized from the resident
    #: assignment (`RankGangState.prev_assigned`, its static snapshot
    #: counterpart per `state.snapshot.CARRY_COUNTERPARTS`) and updated as
    #: gangs place during the gang scan — elastic growth anchors on the
    #: carried rows, never on a re-read of the static tensor. None outside
    #: the gang phase (the per-pod solves do not thread it).
    rank_nodes: Optional[jnp.ndarray] = None


#: cluster events that can free capacity for the framework's built-in
#: resource-fit Filter (upstream NodeResourcesFit EventsToRegister) —
#: kinds from the shared `api.events` table
BUILTIN_EVENTS = (ev.NODE_ADD, ev.NODE_UPDATE, ev.POD_DELETE)


class Plugin:
    """Base plugin: every method is optional; `None` means "not implemented
    at this extension point" and costs nothing in the fused solve."""

    name: str = "Plugin"
    #: score weight, the framework multiplies normalized scores by this
    #: (upstream plugin weights in the profile config).
    weight: int = 1
    #: True when `filter` reads the SolverState carry (its verdict depends
    #: on earlier in-cycle placements). The batched throughput path
    #: (`parallel.solver.profile_batch_solve`) re-evaluates such filters
    #: every wave against the committed carry — a plugin that sets this MUST
    #: implement `commit_batch`, and should implement the `wave_guard` pair
    #: when its filter is a hard resource constraint that same-wave
    #: placements can violate.
    state_dependent_filter: bool = False

    def prepare(self, meta: SnapshotMeta) -> None:
        """Bake per-snapshot-layout constants (resource weights, arg vectors)."""

    def aux(self):
        """Per-cycle array inputs (weight vectors, cost matrices) that must be
        TRACED into the solve rather than closure-captured — jit caches the
        traced program by shape, so closure-captured arrays would be
        constant-folded and silently go stale when config or name<->code
        layouts change between cycles. Return a pytree of arrays or None."""
        return None

    def bind_aux(self, aux) -> None:
        """Called inside the traced solve with this plugin's aux pytree (as
        tracers); tensor methods read `self._aux`. Also clears any traced
        weight override left by a sweep trace (`bind_weight`) so every
        solve body that binds aux starts from the static profile weight —
        a leaked weight tracer from an earlier sweep trace would otherwise
        poison the next program traced against this plugin object."""
        self._aux = aux
        self._weight_t = None

    def bind_weight(self, w) -> None:
        """Traced per-candidate weight override — the tuning sweep's aux
        channel for the ONE config knob the profile format keeps outside
        `aux()` (the score weight, a host int baked at trace time).
        `tuning.sweep` binds each vmapped lane's weight scalar here so K
        candidate weight vectors share one compiled program; None falls
        back to the static `weight`."""
        self._weight_t = w

    @property
    def eff_weight(self):
        """The weight the traced score fold multiplies by: the traced
        override when a sweep bound one, else the static profile int.
        Identical arithmetic either way (int64 scalar times the int64
        normalized column), so a swept lane is bit-identical to a solve
        whose static weight equals that lane's vector."""
        w = getattr(self, "_weight_t", None)
        return self.weight if w is None else w

    def prepare_solve(self, snap: ClusterSnapshot):
        """Called once inside the traced solve, BEFORE the per-pod scan:
        derive loop-invariant tensors from the snapshot (dtype conversions,
        static masks) so they are computed once instead of per scan step.
        Return a pytree (read back via `self._presolve`) or None."""
        return None

    def host_state(self):
        """Cluster-derived host state that `prepare_cluster` bakes into the
        trace and that a flight-recorder bundle cannot rebuild (bundles
        carry the snapshot tensors, not the Cluster object). The recorder
        packs this per plugin at capture time; replay restores it via
        `restore_host_state` after `prepare(meta, None)` so the rebuilt
        plugin traces the SAME specialization the recorded solve did.
        Return a pytree of arrays/scalars or None (nothing to restore)."""
        return None

    def restore_host_state(self, state) -> None:
        """Inverse of `host_state`: re-bake a recorded specialization into
        a rebuilt plugin (utils.flightrec replay/explain paths)."""

    def bind_presolve(self, ctx) -> None:
        """Called inside the traced solve with this plugin's prepare_solve
        result; tensor methods read `self._presolve`."""
        self._presolve = ctx

    def events_to_register(self) -> tuple:
        """EnqueueExtensions: cluster-event kinds ("Resource/Action") that
        may make a pod THIS plugin failed schedulable again — the host loop
        keeps failed pods out of the batch until a registered event (or the
        periodic flush) occurs. Score-only plugins never fail a pod and
        register nothing (upstream EventsToRegister)."""
        return ()

    def static_key(self):
        """Hashable fingerprint of any PYTHON-LEVEL specialization this
        plugin bakes into the trace (static branch selections that cannot be
        traced aux arrays). The runtime keys its jit caches on the tuple of
        these, so changing a specialization retraces instead of silently
        reusing a stale program."""
        return None

    # --- host-side -------------------------------------------------------
    def configure_cluster(self, cluster) -> None:
        """Called by the cycle driver BEFORE the snapshot is taken: plugins
        whose args configure host-side machinery (NRT cache selection, pod
        request-prediction defaults) install it here — the analog of the
        wiring the reference does in each plugin's New()."""

    def queue_key(self, pod, cluster):  # pragma: no cover - trivial default
        """QueueSort key component for `pod`; tuples compare lexicographically."""
        return None

    # --- jitted ----------------------------------------------------------
    def admit(self, state: SolverState, snap: ClusterSnapshot, p):
        """PreFilter: scalar bool verdict for pod index `p` (tracer)."""
        return None

    def filter(self, state: SolverState, snap: ClusterSnapshot, p):
        """Filter: (N,) bool feasibility for pod `p` against current state."""
        return None

    def score(self, state: SolverState, snap: ClusterSnapshot, p):
        """Score: (N,) int64 raw scores for pod `p`."""
        return None

    def static_node_scores(self, snap: ClusterSnapshot):
        """(N,) raw scores when this plugin's `score` is POD-INVARIANT
        against the cycle-initial state — i.e. `score(state0, snap, p)`
        returns the same vector for every p (the reference's allocatable
        scorer rates allocatable capacity, not the pod,
        resource_allocation.go:49-76). Implementing this lets the batched
        solver take the targeted-waterfill fast path (O(P·R) waves, no
        (P, N) score matrix). Must be called after `bind_aux`. Return None
        (default) when scores depend on the pod.

        CONTRACT: the fast path ranks nodes by this RAW vector and never
        calls `normalize` or applies `weight` — only implement it when
        your `normalize` is monotone non-decreasing in the raw score (e.g.
        minmax_normalize) and your configured weight is positive, so the
        raw ordering equals the normalized-weighted ordering."""
        return None

    def normalize(self, scores, feasible):
        """NormalizeScore: transform (N,) raw scores over the feasible mask."""
        return scores

    def commit(self, state: SolverState, snap: ClusterSnapshot, p, choice):
        """Reserve: fold `choice` (node index or -1) into the carried state."""
        return state

    # --- batched whole-matrix variants (parallel.solver) -----------------
    def filter_batch(self, state: SolverState, snap: ClusterSnapshot):
        """(P, N) Filter verdicts for the WHOLE batch against `state`, or
        None to fall back to vmapping `filter` over pods. Implement when
        per-pod verdicts collapse onto equivalence classes (e.g. every pod
        of an AppGroup workload shares one dependency row) so the batched
        solver does O(K·N) work + a gather instead of O(P·N·...). Must be
        bit-identical to the vmapped `filter`."""
        return None

    def score_batch(self, state: SolverState, snap: ClusterSnapshot):
        """(P, N) raw scores for the whole batch, or None to vmap `score`.
        Same class-collapse rationale and bit-identity contract as
        `filter_batch`; `normalize` still runs per pod row."""
        return None

    def filter_rows(self, state: SolverState, snap: ClusterSnapshot, idx):
        """(S, N) Filter verdicts for the `idx` pod rows only against
        `state`, or None to fall back to `filter_batch`/vmapped `filter`.
        Implement when the whole-matrix `filter_batch` is NOT class-
        collapsed (its cost scales with P): a sparse straggler wave then
        re-filters S rows at S/P of the dense cost instead of recomputing
        the full matrix and gathering. Same bit-identity contract as
        `filter` on the selected rows."""
        return None

    def batch_rows(self, state: SolverState, snap: ClusterSnapshot):
        """(filter (P, N) bool | None, scores (P, N) | None) computed in ONE
        pass, or None to fall back to `filter_batch`/`score_batch`.
        Implement when both derive from one shared intermediate (e.g. the
        network dependency tallies) so the batched solver's cycle-initial
        pass pays for it once instead of twice. Each element carries the
        same bit-identity contract as the split hooks."""
        return None

    # --- batched throughput path (parallel.solver) -----------------------
    def commit_batch(self, state: SolverState, snap: ClusterSnapshot,
                     placed, choice):
        """Batched Reserve: fold a whole wave's placements (`placed` (P,)
        bool, `choice` (P,) int32) into the carry in one shot. Must be
        order-independent — the carries this framework uses (zone
        deductions, placement tallies) are sums, so batch == any sequential
        order of per-pod `commit`s. Required iff `state_dependent_filter`."""
        return state

    def wave_guard_demand(self, snap: ClusterSnapshot):
        """(P, R') non-negative per-pod demand in this plugin's admission
        domain, or None when the plugin needs no within-wave guard."""
        return None

    def wave_guard(self, state: SolverState, snap: ClusterSnapshot, p, node,
                   prefix):
        """Exact within-wave admission: True iff pod `p` still passes this
        plugin's filter on `node` after `prefix` (R',) of earlier same-wave
        winners' demand landed there (evaluated against the wave-start
        carry). See `ops.assign.waterfill_assign_stateful`."""
        return jnp.bool_(True)

    def wave_capacity(self, state: SolverState, snap: ClusterSnapshot,
                      active):
        """(N,) per-node capacity ESTIMATE (in pods) under this plugin's
        constraints for the current wave's active set, or None. Only steers
        the waterfill's bucketing (how many queue-ranked pods are SENT to
        each node) — admission stays exact via guards/validators — but a
        tight estimate is what keeps a constrained wave from funneling pods
        onto nodes that can only accept one."""
        return None

    #: overridden (not None) when the plugin's hard filter must be
    #: re-validated pod-by-pod after the batched waterfill: the wave guard
    #: only sees same-NODE conflicts, but domain-counting constraints
    #: (topology spread, inter-pod anti-affinity) span nodes. The batched
    #: solver then runs a sequential demotion scan in queue order calling
    #: this with each placed pod's chosen node — the check is O(1) per pod
    #: (a few gathers), unlike re-running the (N,)-wide filter.
    validate_at = None

    # subclasses override as:
    # def validate_at(self, state, snap, p, node) -> bool:
    #     '''True iff pod `p` still passes this plugin's hard filter on
    #     `node` against the live carry; the scan commits the pod (via
    #     `commit`) only when every validator agrees, else demotes it.'''
