"""One full scheduling cycle: queue -> snapshot -> jitted solve -> apply.

Host-side application of the solve result reproduces the reference's Permit /
PostFilter machinery (/root/reference/pkg/coscheduling/coscheduling.go:162-274):

- assigned & quorum met        -> bind immediately (Permit Success); also
  releases previously-waiting siblings (IterateOverWaitingPods...Allow).
- assigned & quorum unmet      -> reserve (Permit Wait) with the gang deadline
  = PodGroup.ScheduleTimeoutSeconds or the plugin's PermitWaitingTimeSeconds.
- unschedulable gang member    -> PostFilter: if the gang can still reach
  quorum within the reject-percentage slack, let the rest retry; otherwise
  reject the whole gang — release reservations, record failure time (queue
  demotion), back off the group.
- expired gang deadline        -> same whole-gang rejection path.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

import os
import time
from collections import deque

from scheduler_plugins_tpu.api import events as ev_api
from scheduler_plugins_tpu.framework.preemption import GATED, encode_demand
from scheduler_plugins_tpu.framework.runtime import (
    Scheduler,
    SolveResult,
    now_ms as _now_ms,
)
from scheduler_plugins_tpu.obs import ledger as podledger
from scheduler_plugins_tpu.plugins.coscheduling import Coscheduling
from scheduler_plugins_tpu.resilience import faults
from scheduler_plugins_tpu.state.cluster import Cluster
from scheduler_plugins_tpu.utils import flightrec, observability as obs


@dataclass
class SolveResultView:
    """The (assignment, admitted, wait) triple the cycle consumes — what the
    streamed pipeline solve returns (no SolverState carry to surface).
    `failed_plugin` stays None: attribution for streamed solves is reduced
    from the cycle-initial per-plugin masks (`Scheduler.attribution_codes`)."""

    assignment: object
    admitted: object
    wait: object
    failed_plugin: object = None


@dataclass
class CycleReport:
    bound: dict[str, str] = field(default_factory=dict)  # uid -> node
    reserved: dict[str, str] = field(default_factory=dict)
    failed: list[str] = field(default_factory=list)
    #: uid -> plugin name that made the pod unschedulable (the upstream
    #: `UnschedulablePlugins` attribution): the first plugin in profile
    #: order whose PreFilter rejected it or whose Filter emptied the
    #: feasible node set; "NodeResourcesFit" for built-in fit/capacity
    #: failures. Exact against the carried state on the sequential parity
    #: path (`SolveResult.failed_plugin`), reduced from the cycle-initial
    #: per-plugin masks for batched/streamed solves.
    failed_by: dict[str, str] = field(default_factory=dict)
    #: pods parked unschedulable with no registered event since their last
    #: failure (EnqueueExtensions gating) — excluded from this cycle's batch
    skipped: list[str] = field(default_factory=list)
    rejected_gangs: list[str] = field(default_factory=list)
    expired_gangs: list[str] = field(default_factory=list)
    #: preemptor uid -> (nominated node, victim uids)
    preempted: dict[str, tuple[str, list[str]]] = field(default_factory=dict)
    #: checkify findings from this cycle's solve when the sanitizer mode is
    #: on (SPT_SANITIZE=1, utils.sanitize). Read together with
    #: `sanitize_checked`: empty errors are only "all checks passed" when
    #: checked calls actually ran — a cycle whose solve took an
    #: uninstrumented path (sequential fallback) reports 0 checked calls
    sanitize_errors: list[dict] = field(default_factory=list)
    #: number of checkify-instrumented solve invocations this cycle (None
    #: when sanitize mode is off; 0 means the solve path was uninstrumented)
    sanitize_checked: int | None = None
    #: placement-quality objectives for this cycle's solve
    #: (`tuning.quality`: fragmentation, util_imbalance, gang_wait_frac,
    #: unplaced_frac, plus the host preemption/nomination counts) — None
    #: when the cycle ran no solve. Also exported as
    #: `scheduler_placement_quality{objective}` gauges.
    quality: dict | None = None
    #: which solve served this cycle when a `resilience` state machine is
    #: attached: "device" (fast path) or "host" (degraded failover /
    #: probation miss — `resilience.hostsolve`); None without one
    solve_path: str | None = None
    #: True when the process was serving from the host parity path at
    #: the END of this cycle (`scheduler_degraded` gauge's report twin)
    degraded: bool = False
    #: per-gang outcome of the rank-aware gang phase (`gangs.phase`):
    #: gang full_name -> {admitted, placed_new, resident, desired,
    #: max_cost, sum_cost} — empty when the cycle ran without a gang
    #: phase or no rank-aware gang had pending members
    rank_gangs: dict = field(default_factory=dict)
    #: lane attribution when the cycle ran under the K-lane optimistic
    #: engine (`framework.laned_cycle.LanedCycle`): k, path
    #: ("laned"/"serial" fallback), per-lane sizes/committed/conflicts,
    #: re_resolved count and solve/fence wall ms (`LaneStats.as_dict`);
    #: None for every other engine
    lanes: dict | None = None

    def explain(self, uid: str, top_k: int = 5) -> dict:
        """The "why this node" score table for one pod of THIS cycle's
        pending batch (see `utils.flightrec.explain_solver`): top-k
        candidate nodes with per-plugin weighted normalized score columns,
        the built-in fit margin and the winner gap — the upstream
        `--v=10` score dump, per pod, on demand. Works for placed AND
        failed pods; raises KeyError for a uid outside the batch and
        RuntimeError when the cycle never reached a solve (no pending) or
        when the context has been released (only the most recent
        SPT_EXPLAIN_RETAIN cycle reports keep their snapshot — retaining
        every report must not pin every snapshot ever solved)."""
        ctx = getattr(self, "_explain_ctx", None)
        if ctx is _CTX_RELEASED:
            raise RuntimeError(
                f"explain context released: only the most recent "
                f"{_explain_retain()} cycle reports keep their snapshot "
                "(SPT_EXPLAIN_RETAIN; 0 disables explain entirely); use "
                "the flight recorder for postmortems beyond that window"
            )
        if ctx is None:
            raise RuntimeError(
                "this cycle ran no solve (empty pending batch) — nothing "
                "to explain"
            )
        scheduler, snap, meta, assignment, auxes = ctx
        return flightrec.explain_solver(
            scheduler, snap, meta, uid, top_k=top_k, assignment=assignment,
            auxes=auxes,
        )


#: sentinel on `CycleReport._explain_ctx`: distinguishes "released by the
#: retention window" from "this cycle never solved"
_CTX_RELEASED = object()

#: reports whose explain context (scheduler/snapshot/meta/assignment refs)
#: is still attached, most recent last — a full `ClusterSnapshot` hangs off
#: each ctx, so a caller retaining every report must not pin every
#: snapshot ever solved
_EXPLAIN_RING: deque = deque()


def _explain_retain() -> int:
    try:
        return int(os.environ.get("SPT_EXPLAIN_RETAIN", "8"))
    except ValueError:
        return 8


def _attach_explain_ctx(report: CycleReport, ctx: tuple) -> None:
    retain = _explain_retain()
    if retain <= 0:
        # explain disabled: pin nothing, not even this cycle's snapshot
        report._explain_ctx = _CTX_RELEASED
        return
    report._explain_ctx = ctx
    _EXPLAIN_RING.append(report)
    while len(_EXPLAIN_RING) > retain:
        _EXPLAIN_RING.popleft()._explain_ctx = _CTX_RELEASED


@dataclass
class CycleCtx:
    """Mutable state threaded through one cycle's stages.

    `run_cycle` composes the `_cycle_*` stage functions below strictly
    serially; the pipelined engine (`framework.pipeline_cycle`) composes
    the SAME functions with cycle N's device solve left in flight while
    host stages of neighboring cycles run — one copy of every stage, so
    the two engines cannot drift (the serial engine stays the parity
    anchor, gated by `tests/test_differential.py`'s pipelined-equivalence
    twin)."""

    scheduler: Scheduler
    cluster: Cluster
    now: int
    report: CycleReport
    stream_chunk: int | None = None
    serve: object = None
    resilience: object = None
    gangs: object = None
    cosched: object = None
    pending: list = field(default_factory=list)
    snap: object = None
    meta: object = None
    served: bool = False
    serve_t0: float | None = None
    rec: object = None
    result: object = None
    assignment: object = None
    admitted: object = None
    wait: object = None
    #: host transfers already forced (resilience path fences internally)
    fenced: bool = False
    #: early return taken (empty batch / gang-only cycle)
    done: bool = False
    #: tracer row for the bind/post-bind stages — the pipelined engine's
    #: async bind flusher runs them on a worker thread, and spans from
    #: two threads on one row would partially overlap (the Perfetto
    #: validity gate rejects that); the serial engine keeps "cycle"
    tid: str = "cycle"
    failed_idx: list = field(default_factory=list)
    failed_by_gang: dict = field(default_factory=dict)
    #: host copies of the snapshot columns `_observe_quality` reads —
    #: captured at the fence by the pipelined engine, whose deferred
    #: finalize runs AFTER the next refresh consumed (donated) the
    #: resident node tensors; None on the serial path (quality reads the
    #: live snapshot before any donation)
    quality_view: object = None
    #: this cycle's pod-lifecycle ledger context (`obs.ledger.LedgerCycle`)
    #: — None whenever the ledger is disabled, so every hook below guards
    #: on it and the off path costs one attribute read
    led: object = None


def _cycle_open(scheduler, cluster, now, stream_chunk=None, serve=None,
                resilience=None, gangs=None) -> CycleCtx:
    """Cycle prologue: counters, per-cycle plugin wiring, gang expiry, NRT
    resync and collector ticks — everything before the pending batch."""
    ctx = CycleCtx(
        scheduler=scheduler, cluster=cluster, now=now, report=CycleReport(),
        stream_chunk=stream_chunk, serve=serve, resilience=resilience,
        gangs=gangs,
    )
    # the ledger scope opens BEFORE gang expiry: whole-gang rejections in
    # the prologue are this cycle's decisions and must attribute to it.
    # Callers (run_cycle / PipelinedCycle.tick / LanedCycle.tick) pop the
    # scope in their finally — the stage functions only push nested ones.
    ctx.led = podledger.LEDGER.cycle_open(now)
    podledger.LEDGER.push_scope(ctx.led, 0)
    obs.metrics.inc(obs.SCHEDULING_CYCLES)
    ctx.cosched = next(
        (p for p in scheduler.profile.plugins if isinstance(p, Coscheduling)),
        None,
    )
    for plugin in scheduler.profile.plugins:
        plugin.configure_cluster(cluster)
    with obs.tracer.span("ExpireGangs", tid="cycle"):
        _expire_gangs(cluster, now, ctx.report)
    with obs.tracer.span("NRTResync", tid="cycle"):
        _resync_nrt_cache(cluster, now)
    with obs.tracer.span("Collectors", tid="cycle"):
        _refresh_metrics(scheduler, cluster, now)
    return ctx


def _cycle_pending(ctx: CycleCtx) -> None:
    """Pending batch assembly: requeue gating, queue sort, and the
    rank-gang phase. Sets `ctx.done` when the cycle ends here (no batch,
    or a gang-only cycle fully handled by the phase)."""
    scheduler, cluster, now, report = (
        ctx.scheduler, ctx.cluster, ctx.now, ctx.report,
    )
    gangs, serve = ctx.gangs, ctx.serve
    pending = cluster.pending_pods()
    with obs.tracer.span("Requeue", tid="cycle"):
        pending = _requeue_eligible(
            scheduler, cluster, pending, now, report,
            gang_phase=gangs is not None,
        )
    if gangs is None and not pending:
        ctx.done = True
        return
    pending = scheduler.sort_pending(pending, cluster)

    if gangs is not None:
        # the phase runs even on an empty batch: elastic reconcile must
        # observe desired-width changes (shrink deletes need no pending
        # pods), and growth clones it creates join THIS cycle's batch
        with obs.extension_span("GangPhase", type(gangs).__name__,
                                pending=len(pending)):
            pending = gangs.run(
                scheduler, cluster, pending, now, report, serve=serve
            )
        if not pending:
            # gang-only cycle: every pending pod was a rank-gang member
            # (bound or parked by the phase); nothing for the per-pod
            # solve, so close out the counters and return. A serving
            # engine still DRAINS (refresh with an empty batch): the
            # phase's binds must land in the resident columns and the
            # per-gang rank mirror now, not pile up in the sink until the
            # next non-gang cycle. The cycle is still RECORDED when the
            # flight recorder is live — the gang capture alone replays
            # bit-identically through the twin
            if serve is not None:
                serve.refresh(cluster, [], now_ms=now)
            rec = flightrec.recorder.begin(
                now_ms=now, profile=scheduler.profile.name
            )
            if rec is not None:
                gangs.annotate_record(rec)
                rec.commit(report)
            obs.metrics.inc(obs.PODS_BOUND, len(report.bound))
            obs.metrics.inc(obs.PODS_FAILED, len(report.failed))
            obs.metrics.inc(obs.GANG_REJECTIONS, len(report.rejected_gangs))
            ctx.done = True
            return
    ctx.pending = pending
    if ctx.led is not None:
        # the batch membership gate for per-attempt stage splitting:
        # binds/reservations of pods OUTSIDE this set (gang-phase binds
        # above, permit fan-out of earlier cycles' reservations) charge
        # their whole open interval to the resting wait-state instead
        ctx.led.batch = frozenset(p.uid for p in pending)


def _cycle_snapshot(ctx: CycleCtx) -> None:
    """Snapshot/serve-refresh assembly, plugin prepare, flight-recorder
    input capture. Runs inside the caller's `obs.flow` context."""
    scheduler, cluster, now = ctx.scheduler, ctx.cluster, ctx.now
    pending, serve, gangs = ctx.pending, ctx.serve, ctx.gangs
    with obs.tracer.span("Snapshot", tid="cycle", pending=len(pending)):
        snap = meta = None
        if serve is not None:
            refreshed = serve.refresh(cluster, pending, now_ms=now)
            if refreshed is not None:
                snap, meta = refreshed
                ctx.served = True
        if snap is None:
            snap, meta = cluster.snapshot(pending, now_ms=now)
    ctx.snap, ctx.meta = snap, meta
    scheduler.prepare(meta, cluster)
    if ctx.rec is not None:
        # inputs land in the ring BEFORE the solve: the cycle that
        # crashes the solver is exactly the one worth replaying
        with obs.tracer.span("Record", tid="cycle"):
            ctx.rec.capture_inputs(
                snap, meta, scheduler, stream_chunk=ctx.stream_chunk,
                profile_config=flightrec.recorder.profile_config,
            )
            if ctx.served:
                # serve provenance: resident generation, base digest,
                # and the packed delta stream that produced this
                # cycle's snapshot view
                serve.annotate_record(ctx.rec)
            if gangs is not None:
                # gang-phase provenance: the full RankGangState +
                # outputs, so a recorded gang cycle replays
                # bit-identically through the numpy twin
                gangs.annotate_record(ctx.rec)


def _cycle_solve_dispatch(ctx: CycleCtx) -> None:
    """Dispatch the solve. On the plain path the result tensors stay
    DEVICE arrays (async dispatch — `_cycle_solve_fence` forces the host
    transfer); the resilience path completes through the watchdog's own
    deadlined fence and returns host arrays (`ctx.fenced`)."""
    scheduler, snap = ctx.scheduler, ctx.snap
    if ctx.led is not None:
        # dispatch ENTRY, not return: the in-batch wait stage ends the
        # moment the solve starts consuming the snapshot
        ctx.led.t_solve = podledger.LEDGER._now()
    result = None
    if ctx.resilience is not None:
        # watchdog-guarded: dispatch + completion fence in a
        # worker thread with a deadline; retries, then failover
        # to the host parity path (resilience.watchdog)
        (assignment, admitted, wait, codes_np,
         ctx.report.solve_path) = ctx.resilience.solve_cycle(
            scheduler, snap, stream_chunk=ctx.stream_chunk
        )
        result = SolveResultView(
            assignment, admitted, wait, failed_plugin=codes_np
        )
        ctx.assignment, ctx.admitted, ctx.wait = assignment, admitted, wait
        ctx.fenced = True
    else:
        if ctx.stream_chunk:
            from scheduler_plugins_tpu.parallel.pipeline import (
                streamed_profile_solve,
            )

            streamed = streamed_profile_solve(
                scheduler, snap, chunk=ctx.stream_chunk
            )
            if streamed is not None:
                result = SolveResultView(*streamed)
        if result is None:
            result = scheduler.solve(snap)
    ctx.result = result


def _cycle_solve_fence(ctx: CycleCtx, quality_view: bool = False) -> None:
    """Force the host transfers (block_until_ready can return early
    through the tunneled backend — CLAUDE.md), so the caller's Solve
    span/histogram covers the device round-trip. `quality_view` also
    copies the snapshot columns the deferred quality observation reads
    (the pipelined engine's finalize runs after the resident node
    tensors were donated to the next cycle's delta apply)."""
    if ctx.led is not None and ctx.led.t_fence0 is None:
        ctx.led.t_fence0 = podledger.LEDGER._now()
    if not ctx.fenced:
        ctx.assignment = np.asarray(ctx.result.assignment)
        ctx.admitted = np.asarray(ctx.result.admitted)
        ctx.wait = np.asarray(ctx.result.wait)
        ctx.fenced = True
    if ctx.led is not None and ctx.led.t_fence1 is None:
        ctx.led.t_fence1 = podledger.LEDGER._now()
    if quality_view:
        ctx.quality_view = _quality_view(ctx.snap)


def _cycle_post_solve(ctx: CycleCtx) -> None:
    """Post-fence bookkeeping: degraded flag, flight-recorder output
    capture, explain-context retention, sanitizer drain."""
    from scheduler_plugins_tpu.utils import sanitize

    report, result = ctx.report, ctx.result
    report.degraded = (
        ctx.resilience is not None and ctx.resilience.degraded
    )
    if ctx.led is not None:
        ctx.led.degraded = report.degraded
        ctx.led.solve_path = report.solve_path
    if ctx.rec is not None:
        with obs.tracer.span("Record", tid="cycle"):
            from scheduler_plugins_tpu.parallel.solver import PackingSolveView

            codes = getattr(result, "failed_plugin", None)
            if isinstance(result, PackingSolveView):
                # packing placements replay through the sequential path
                # as EVIDENCE only (soft ordering differs by design) —
                # the mode string keeps the replayer honest about it
                rec_mode = "packing"
            elif isinstance(result, SolveResult) or codes is not None:
                # the host failover path carries the sequential parity
                # semantics (and per-pod codes), so its records replay
                # through the same path as device-sequential ones
                rec_mode = "sequential"
            else:
                rec_mode = "streamed"
            ctx.rec.capture_outputs(
                rec_mode,
                ctx.assignment, ctx.admitted, ctx.wait,
                failed_plugin=(
                    None if codes is None else np.asarray(codes)
                ),
            )
    if ctx.served:
        # serve cycles keep NO explain context: the snapshot's node
        # tensors are the resident carry, donated to the next cycle's
        # delta apply — a retained ctx would read freed device buffers.
        # Postmortems go through the flight recorder (host copies).
        report._explain_ctx = _CTX_RELEASED
    else:
        # cheap refs, not copies: lets `report.explain(uid)` rebuild the
        # per-plugin score table for any pod of this batch after the fact;
        # retention-bounded so old reports release their snapshot. The aux
        # pytrees are frozen HERE — a later cycle's prepare() rebinds the
        # shared plugins, and explaining an old report against the live
        # aux() would score cycle K's snapshot with cycle K+n's config
        _attach_explain_ctx(report, (
            ctx.scheduler, ctx.snap, ctx.meta, ctx.assignment,
            tuple(p.aux() for p in ctx.scheduler.profile.plugins),
        ))

    if sanitize.enabled():
        # surface this cycle's checkify findings on the report (the solve
        # paths above report into the sanitizer's buffer as they run);
        # checked-call count kept so "no errors" cannot be mistaken for
        # "checks ran" when the solve fell back to an uninstrumented path
        reports = sanitize.drain()
        report.sanitize_checked = len(reports)
        report.sanitize_errors = [r for r in reports if not r["ok"]]


def _cycle_bind(ctx: CycleCtx) -> None:
    """The bind stage: flush this cycle's placement decisions through the
    store mutators (bind / reserve / mark_unschedulable). Every mutation
    here carries THIS cycle's `now` — under the pipelined engine the
    flush may run while the wall clock is already inside the next cycle's
    ingest, and backoff windows must still be charged to the cycle that
    observed the snapshot. The ledger scope follows the same rule: lane 1
    on THIS thread (the pipelined engine's flusher has its own scope
    stack), attributing every store-hook event to the observing cycle."""
    podledger.LEDGER.push_scope(ctx.led, 1)
    try:
        _bind_decisions(ctx)
    finally:
        podledger.LEDGER.pop_scope(ctx.led)


def _bind_decisions(ctx: CycleCtx) -> None:
    cluster, report, now = ctx.cluster, ctx.report, ctx.now
    pending, meta = ctx.pending, ctx.meta
    assignment, admitted, wait = ctx.assignment, ctx.admitted, ctx.wait
    cosched = ctx.cosched
    with obs.tracer.span("Bind", tid=ctx.tid):
        for i, pod in enumerate(pending):
            node_idx = int(assignment[i])
            pg = cluster.pod_group_of(pod)
            if node_idx < 0 or not admitted[i]:
                report.failed.append(pod.uid)
                ctx.failed_idx.append((i, pod.uid))
                cluster.mark_unschedulable(pod.uid, now)
                if pg is not None:
                    ctx.failed_by_gang.setdefault(
                        pg.full_name, []
                    ).append(pod.uid)
                continue
            node_name = meta.node_names[node_idx]
            if wait[i]:
                cluster.reserve(pod.uid, node_name)
                report.reserved[pod.uid] = node_name
                # per-POD waiting timer from THIS pod's reservation time
                # (upstream waitingPods, coscheduling.go:227-235;
                # GetWaitTimeDuration: ScheduleTimeoutSeconds else
                # PermitWaitingTimeSeconds)
                timeout_s = (
                    pg.schedule_timeout_seconds if pg is not None else None
                )
                if timeout_s is None and cosched is not None:
                    timeout_s = cosched.permit_waiting_seconds
                cluster.pod_deadline_ms[pod.uid] = now + 1000 * (timeout_s or 0)
            else:
                cluster.bind(pod.uid, node_name, now)
                report.bound[pod.uid] = node_name

    if ctx.serve_t0 is not None:
        # serve-mode decision latency: delta ingest through host-visible
        # bind decisions (the per-decision number the sustained-churn
        # bench reports as p50/p99) — observed even on fallback cycles so
        # the histogram shows what serve traffic actually experienced
        obs.metrics.observe_ms(
            obs.SERVE_DECISION_LATENCY,
            (time.perf_counter() - ctx.serve_t0) * 1000.0,
        )

    if faults.ACTIVE is not None:
        # chaos harness only (zero overhead otherwise): simulate process
        # death AFTER bindings landed in the store — the worst-ordered
        # crash for resident serve state, since the dying sink's
        # undrained deltas are lost with the process. The report rides
        # the exception so the harness can account the real, landed binds
        spec = faults.ACTIVE.fire(faults.CRASH_POST_BIND)
        if spec is not None:
            raise faults.CrashInjected(ctx.report)


def _cycle_postbind(ctx: CycleCtx, attribution: bool = True) -> None:
    """Post-bind store machinery, fenced to the cycle that observed the
    snapshot: Permit quorum fan-out, whole-gang PostFilter rejection,
    over-reserve marks and preemption nomination set/clear. The pipelined
    engine MUST run this before the next cycle's ingest boundary — a
    nomination or backoff landing mid-overlap would otherwise be observed
    by (and attributed to) the wrong cycle. `attribution=False` lets the
    pipelined engine defer the host-only failure decode to its overlap
    window when the per-pod codes already rode the solve result."""
    podledger.LEDGER.push_scope(ctx.led, 1)
    try:
        _postbind_store(ctx, attribution)
    finally:
        podledger.LEDGER.pop_scope(ctx.led)


def _postbind_store(ctx: CycleCtx, attribution: bool) -> None:
    cluster, report, now = ctx.cluster, ctx.report, ctx.now
    cosched = ctx.cosched
    if attribution:
        _attribute_failures(
            ctx.scheduler, ctx.snap, ctx.result, ctx.failed_idx, report,
            tid=ctx.tid, led=ctx.led,
        )

    # Permit Allow fan-out: quorum reached this cycle releases waiting
    # siblings
    with obs.tracer.span("Permit", tid=ctx.tid):
        for pg in list(cluster.pod_groups.values()):
            _maybe_release_gang(cluster, pg, report, now)

    # PostFilter: whole-gang rejection (coscheduling.go:160-209)
    for gang_name in ctx.failed_by_gang:
        pg = cluster.pod_groups.get(gang_name)
        if pg is None:
            continue
        members = cluster.gang_members(pg)
        assigned = sum(
            1 for p in members
            if p.node_name is not None or p.uid in cluster.reserved
        )
        if assigned >= pg.min_member:
            continue  # quorum already met; stragglers can retry freely
        # tolerate a small quorum gap: (MinMember - assigned)/MinMember
        # <= rejectPercentage (coscheduling.go:180-185)
        reject_pct = cosched.reject_percentage if cosched else 10
        gap = (pg.min_member - assigned) / max(pg.min_member, 1)
        if gap <= reject_pct / 100:
            continue  # a subsequent pod may still complete the quorum
        _reject_gang(cluster, pg, now, report, cosched, len(members))

    _mark_overreserved_on_failures(cluster, report)
    engine = ctx.scheduler.profile.preemption
    with obs.extension_span(
        "PostFilter", type(engine).__name__ if engine else "none",
        tid="framework" if ctx.tid == "cycle" else ctx.tid,
        failed=len(report.failed),
    ):
        _run_preemption(ctx.scheduler, cluster, ctx.pending, report, now)
    obs.metrics.inc(obs.PODS_BOUND, len(report.bound))
    obs.metrics.inc(obs.PODS_FAILED, len(report.failed))
    obs.metrics.inc(obs.GANG_REJECTIONS, len(report.rejected_gangs))


def _cycle_finalize(ctx: CycleCtx, attribution: bool = False) -> None:
    """Report-only epilogue — placement-quality observation and the
    flight-recorder commit (plus the deferred failure decode under the
    pipelined engine). Touches no store state, so the pipelined engine
    runs it inside the NEXT cycle's overlap window, on the host copies
    `_cycle_solve_fence(quality_view=True)` captured."""
    if attribution:
        _attribute_failures(
            ctx.scheduler, ctx.snap, ctx.result, ctx.failed_idx, ctx.report,
            tid=ctx.tid, led=ctx.led,
        )
    _observe_quality(
        ctx.report, ctx.quality_view or ctx.snap,
        ctx.assignment, ctx.admitted, ctx.wait,
    )
    if ctx.rec is not None:
        ctx.rec.commit(ctx.report)


def _quality_view(snap):
    """Host copies of exactly the snapshot columns `cycle_quality_np`
    reads, in the same attribute shape — safe to read after the resident
    node tensors were donated to a later cycle's delta apply."""
    from types import SimpleNamespace

    return SimpleNamespace(
        nodes=SimpleNamespace(
            alloc=np.asarray(snap.nodes.alloc),
            requested=np.asarray(snap.nodes.requested),
            mask=np.asarray(snap.nodes.mask),
        ),
        pods=SimpleNamespace(
            req=np.asarray(snap.pods.req),
            mask=np.asarray(snap.pods.mask),
        ),
    )


def run_cycle(scheduler: Scheduler, cluster: Cluster, now: int | None = None,
              stream_chunk: int | None = None, serve=None,
              resilience=None, gangs=None, tuner=None) -> CycleReport:
    """One daemon cycle. `stream_chunk` opts the solve into the donated,
    double-buffered chunk pipeline (`parallel.pipeline.streamed_profile_solve`)
    when the profile qualifies for the targeted fast path — huge pending
    queues then stream through bounded chunks instead of one (P, N) solve,
    with wave-path placement semantics (hard constraints exact, soft
    tie-breaking may differ from the sequential scan). Profiles that don't
    qualify fall back to `scheduler.solve` unchanged.

    `serve` opts the SNAPSHOT stage into a resident-state serving engine
    (`serving.engine.ServeEngine`, attached to this cluster): instead of
    rebuilding and re-shipping the full cluster snapshot, the engine keeps
    the node tensors device-resident across cycles and applies O(changed)
    deltas captured from the store's mutation hooks. The solve itself is
    unchanged — the assembled snapshot feeds the same bit-faithful
    sequential parity path, so serve-mode placements are identical to a
    fresh-snapshot cycle (tests/test_serving.py). When the engine cannot
    own the state (side-table objects present, docs/SERVING.md gate), the
    cycle falls back to `cluster.snapshot` transparently. Serve cycles do
    NOT retain an explain context (the resident tensors are donated to
    the next cycle's delta apply — a retained snapshot would read freed
    buffers); the flight recorder is the postmortem surface there.

    `gangs` (a `gangs.phase.GangPhase`) opts the cycle into the
    rank-aware gang phase AHEAD of the per-pod solve: rank-aware
    PodGroups' members are lifted out of the pending batch, placed as
    whole gangs by the topology-block waterfill, and bound through the
    store — so the snapshot the per-pod path solves already carries the
    committed free/eq_used state (the CLAUDE.md carry discipline, at
    phase granularity). Quorum-failed gangs park whole (zero partial
    ranks); elastic gangs grow/shrink in the phase's reconcile first.

    `resilience` (a `resilience.watchdog.Resilience`) routes the solve
    through the solve watchdog: device dispatch + host-transfer
    completion fence in a worker thread with a deadline, seeded-jitter
    retries, failover to the host sequential parity path on an exhausted
    budget, probation probes while degraded (docs/ROBUSTNESS.md). Raises
    `resilience.BackendUnavailable` only when the backend is gone AND the
    profile has no host fallback — callers (the daemon) park the cycle.

    `tuner` (a `tuning.shadow.ShadowTuner`) hooks the guarded-rollout
    controller into the cycle at its two safe seams: `begin_cycle` BEFORE
    anything reads the profile weights (the one point a staged promotion
    or a decided rollback may swap the live weight vector — mid-cycle
    swaps could solve and record under different weights), and
    `observe_report` after finalize (the probation window's
    quality-gauge comparison feeds on the report's quality stamp)."""
    if now is None:
        now = _now_ms()
    if tuner is not None:
        # the weight-swap seam: promotions/rollbacks apply only here, at
        # the cycle boundary, never mid-cycle (docs/ROBUSTNESS.md)
        tuner.begin_cycle(now_ms=now)
    ctx = _cycle_open(
        scheduler, cluster, now, stream_chunk=stream_chunk, serve=serve,
        resilience=resilience, gangs=gangs,
    )
    try:
        _cycle_pending(ctx)
        if ctx.done:
            if tuner is not None:
                tuner.observe_report(ctx.report)
            return ctx.report

        from scheduler_plugins_tpu.utils import sanitize

        if sanitize.enabled():
            # discard reports left by solves OUTSIDE this cycle (warmups,
            # other schedulers): the post-solve drain below must attribute
            # only THIS cycle's checked calls to this report
            sanitize.drain()
        generation = getattr(cluster.nrt_cache, "generation", None)
        ctx.rec = flightrec.recorder.begin(
            now_ms=now, profile=scheduler.profile.name
        )
        ctx.serve_t0 = time.perf_counter() if serve is not None else None
        with obs.flow(
            "cycle", generation=generation, pending=len(ctx.pending)
        ):
            _cycle_snapshot(ctx)
            # the Solve span covers dispatch AND completion (the fence's
            # np.asarray host transfers force it) for the sequential path;
            # the streamed path's device-side overlap shows up as pipeline
            # rows emitted by run_chunk_pipeline itself
            with obs.extension_span(
                "Solve", scheduler.profile.name, pending=len(ctx.pending)
            ):
                _cycle_solve_dispatch(ctx)
                _cycle_solve_fence(ctx)
            _cycle_post_solve(ctx)
        _cycle_bind(ctx)
        _cycle_postbind(ctx, attribution=True)
        _cycle_finalize(ctx)
        if tuner is not None:
            tuner.observe_report(ctx.report)
        return ctx.report
    finally:
        # the lane-0 scope opened in `_cycle_open` — popped HERE (not in a
        # stage function) so early returns and raises cannot leak it, and
        # ambient events between cycles fall back to ambient attribution
        podledger.LEDGER.pop_scope(ctx.led)
        podledger.LEDGER.cycle_close(ctx.led)


def _observe_quality(report, snap, assignment, admitted, wait) -> None:
    """Stamp the cycle's placement-quality objectives on the report and
    export them as `scheduler_placement_quality{objective}` gauges
    (tuning.quality's numpy twin — per-cycle reductions on host arrays,
    no per-shape jit compiles on this always-on path; the jitted tensor
    core is what the bench lines and the counterfactual sweep use, and
    tests/test_tuning.py holds the two in agreement)."""
    from scheduler_plugins_tpu.tuning import quality as Q

    q = Q.cycle_quality_np(snap, assignment, admitted, wait)
    q["nominations"] = float(len(report.preempted))
    q["preemptions"] = float(
        sum(len(v) for _, v in report.preempted.values())
    )
    report.quality = q
    for objective, value in q.items():
        obs.metrics.set_gauge(
            obs.PLACEMENT_QUALITY, value, objective=objective
        )


def _attribute_failures(scheduler, snap, result, failed_idx, report,
                        tid="cycle", led=None):
    """Fill `CycleReport.failed_by` and the
    `scheduler_unschedulable_by_plugin_total{plugin}` counters — the
    upstream UnschedulablePlugins attribution. The sequential parity path
    carries exact per-pod codes out of the solve
    (`SolveResult.failed_plugin`, evaluated against the carried state);
    batched/streamed solves reduce the same per-plugin masks cycle-
    initially (`Scheduler.attribution_codes`). Codes <= 0 (built-in fit,
    gates, or in-cycle capacity exhaustion) decode to "NodeResourcesFit"."""
    if not failed_idx:
        return
    with obs.tracer.span("Attribution", tid=tid, failed=len(failed_idx)):
        codes = getattr(result, "failed_plugin", None)
        if codes is not None:
            # sequential parity path: (P,) in-solve codes, pod-indexed
            codes_np = np.asarray(codes)
            per_failure = [codes_np[i] for i, _ in failed_idx]
        else:
            # batched/streamed: reduce the failed rows only (S, N work)
            per_failure = scheduler.attribution_codes(
                snap, [i for i, _ in failed_idx]
            )
        names = scheduler.fail_plugin_names()
        for (_, uid), code in zip(failed_idx, per_failure):
            code = int(code)
            name = names[code] if code > 0 else names[0]
            report.failed_by[uid] = name
            obs.metrics.inc(obs.UNSCHEDULABLE_BY_PLUGIN, plugin=name)
            if led is not None:
                # blame fills IN PLACE on the observing cycle's
                # Unschedulable event: this decode may run in the NEXT
                # tick's overlap window under the pipelined engine, and
                # an appended event there would order differently
                podledger.LEDGER.set_blame(uid, led.cid, name)


def _requeue_eligible(scheduler, cluster, pending, now, report,
                      gang_phase=False):
    """EnqueueExtensions gating (upstream scheduling-queue semantics): a pod
    parked unschedulable re-enters the batch only when

    - a cluster event registered by an enabled plugin (or the built-in
      resource fit's Node/Pod events) occurred after its last failure,
    - it holds a live nomination (upstream nominated pods stay active),
    - its flush deadline passed (podMaxInUnschedulablePodsDuration), or
    - a gang sibling is eligible (upstream ActivateSiblings moves the whole
      group together),

    AND its requeue backoff window has expired: every re-queue pays the
    seeded deterministic jittered exponential backoff
    `Cluster.mark_unschedulable` computed at its last failure — upstream
    backoffQ semantics, where an event moves a pod from the
    unschedulable pool to the backoff queue but it pops into the active
    queue only once its per-pod backoff completes, so a
    permanently-unschedulable pod cannot hot-loop the queue. Nominated
    pods bypass the backoff like they bypass the event gate (they hold
    capacity; delaying their retry delays everyone behind them).

    Pods never marked unschedulable (new arrivals, retried reservations)
    always run. Reference: EventsToRegister registrations, e.g.
    coscheduling.go:113-122, capacity_scheduling.go:194-203,
    noderesourcetopology plugin.go:141-151; backoff:
    k8s.io/kubernetes pkg/scheduler/internal/queue/scheduling_queue.go
    (calculateBackoffDuration — the framework queue every reference
    plugin registers into).

    `gang_phase` registers `api.events.GANG_EVENTS` on top: a pod parked
    by the rank-gang phase (`RankGangPlacement`) has no owning plugin in
    the profile to register its events, but its schedulability changes on
    exactly those kinds (sibling add/delete frees quorum or capacity, a
    NetworkTopology update moves the cost surface)."""
    from scheduler_plugins_tpu.framework.plugin import BUILTIN_EVENTS

    if not cluster.unschedulable_since:
        return pending
    registered = set(BUILTIN_EVENTS)
    if gang_phase:
        registered.update(ev_api.GANG_EVENTS)
    for plugin in scheduler.profile.plugins:
        registered.update(plugin.events_to_register())

    led = podledger.LEDGER

    def eligible(pod):
        rec = cluster.unschedulable_since.get(pod.uid)
        if rec is None:
            return True
        seq, flush_at = rec
        if pod.nominated_node_name is not None:
            return True
        if now < cluster.pod_backoff_until_ms.get(pod.uid, 0):
            obs.metrics.inc(obs.REQUEUE_BACKOFF_SKIPS)
            if led.enabled:
                led.on_wait(pod.uid, "backoff_held")
            return False
        if now >= flush_at:
            return True
        if any(
            cluster.event_last.get(kind, 0) > seq for kind in registered
        ):
            return True
        if led.enabled:
            # backoff expired, no registered event yet: the pod is now
            # waiting on the QUEUE gate, not the backoff clock (the
            # ledger's one-transition-per-park-episode classification;
            # gang parks keep their gang_wait label — `Ledger.on_wait`)
            led.on_wait(pod.uid, "queue_wait")
        return False

    keep = [pod for pod in pending if eligible(pod)]
    kept_uids = {p.uid for p in keep}
    # gang activation: one eligible member activates its whole group
    eligible_gangs = {
        pg.full_name
        for p in keep
        if (pg := cluster.pod_group_of(p)) is not None
    }
    for pod in pending:
        if pod.uid in kept_uids:
            continue
        pg = cluster.pod_group_of(pod)
        if pg is not None and pg.full_name in eligible_gangs:
            keep.append(pod)
            kept_uids.add(pod.uid)
    for pod in pending:
        if pod.uid not in kept_uids:
            report.skipped.append(pod.uid)
    return keep


def _run_preemption(scheduler, cluster, pending, report, now):
    """PostFilter preemption: for each still-failed pod in queue order, dry
    run victim removal across all nodes, nominate the best candidate, mark
    victims terminating (the apiserver DELETE boundary in the reference)
    and record the nomination (SURVEY.md §3.3).

    Runs against a FRESH snapshot (this cycle's binds must count as node
    usage, or just-bound pods double as phantom victims) and threads the
    cycle's earlier nominations into each dry run so two preemptors cannot
    claim the same freed capacity (the upstream evaluator filters with
    nominated pods)."""
    engine = scheduler.profile.preemption
    if engine is None or not report.failed:
        return
    rejected = set(report.rejected_gangs)
    by_uid = {p.uid: p for p in pending}
    failed_pods = [by_uid[uid] for uid in report.failed if uid in by_uid]
    # post-bind state: assigned pods now include this cycle's placements
    snap, meta = cluster.snapshot(failed_pods, now_ms=now)
    # re-prepare: the preemption snapshot's resource-axis layout can differ
    # from the main cycle's (extended names are interned in first-seen
    # order), and plugin aux arrays must match THIS meta
    scheduler.prepare(meta, cluster)
    nominated_extra = np.zeros(
        (len(meta.node_names), len(meta.index)), np.int64
    )
    node_pos = {name: i for i, name in enumerate(meta.node_names)}
    # PRIOR cycles' live nominations (kept while gated) and nominations made
    # EARLIER IN THIS LOOP hold capacity in the dry runs, but only against
    # preemptors of lower-or-equal priority (upstream AddNominatedPods adds
    # nominees with priority >= the evaluated pod, same UID excluded); the
    # capacity in-flight terminations will free is credited to everyone.
    # Each preemptor's view is assembled fresh from the hold list — the
    # queue order of failed_pods is NOT priority-descending under every
    # QueueSort (TopologicalSort orders same-AppGroup pods by topology
    # index), so a one-way pointer sweep would fold low-priority holds in
    # against later higher-priority preemptors. A nomination that clears or
    # moves during this loop drops its old hold (same-UID dedup below).
    for pod in cluster.pods.values():
        if pod.terminating and pod.node_name in node_pos:
            nominated_extra[node_pos[pod.node_name]] -= encode_demand(
                meta.index, pod
            )
    holds = [
        (
            node_pos[pod.nominated_node_name],
            encode_demand(meta.index, pod),
            pod.priority,
            pod.uid,
        )
        for pod in cluster.pods.values()
        if pod.node_name is None
        and not pod.terminating
        and pod.nominated_node_name in node_pos
    ]
    for pod in failed_pods:
        pg = cluster.pod_group_of(pod)
        if pg is not None and pg.full_name in rejected:
            continue  # the whole gang was rejected; no point preempting
        obs.metrics.inc(obs.PREEMPTION_ATTEMPTS)
        # PodEligibleToPreemptOthers runs inside preempt(): while pods this
        # pod could benefit from are still terminating on its nominated
        # node, it must NOT preempt again — and the nomination is KEPT so
        # the gate can keep firing (capacity_scheduling.go:409-484).
        extra = nominated_extra.copy()
        for n_, demand_, prio_, uid_ in holds:
            if prio_ >= pod.priority and uid_ != pod.uid:
                extra[n_] += demand_
        result = engine.preempt(
            cluster, scheduler, pod, snap, meta, now,
            extra_reserved=extra,
        )
        if result is GATED:
            continue  # terminations in flight: nomination (hold) stays
        # past this point the pod's nomination either clears or moves —
        # either way its previous hold is dead (same-UID dedup also keeps a
        # re-preempting nominee from holding double)
        holds = [h for h in holds if h[3] != pod.uid]
        if result is None:
            # nomination did not help and nothing is terminating: clear it
            # so the pod re-enters PostFilter fresh (upstream clears
            # NominatedNodeName when unschedulable again)
            pod.nominated_node_name = None
            if cluster.delta_sink is not None:
                # in-place clear never passes through a Cluster mutator —
                # untrack it or the serving engine's compatibility gate
                # stays pinned False for this pod's lifetime
                cluster.delta_sink.note_nomination(pod)
            if podledger.LEDGER.enabled:
                podledger.LEDGER.on_nomination(pod.uid, None)
            continue
        obs.metrics.inc(obs.PREEMPTION_VICTIMS, len(result.victims))
        # setting the nomination NOW makes this pod visible to later
        # preemptors' live nominated aggregates (quota feedback) exactly once
        pod.nominated_node_name = result.nominated_node
        if cluster.delta_sink is not None:
            cluster.delta_sink.note_nomination(pod)
        if podledger.LEDGER.enabled:
            podledger.LEDGER.on_nomination(pod.uid, result.nominated_node)
        n = node_pos[result.nominated_node]
        demand = encode_demand(meta.index, pod)
        victim_freed = np.zeros(len(meta.index), np.int64)
        for victim_uid in result.victims:
            victim = cluster.pods.get(victim_uid)
            if victim is not None:
                # DELETE issued; kubelet terminates (keeps the native
                # mirror's terminating counts in sync too)
                cluster.mark_terminating(victim_uid, now)
                victim_freed += encode_demand(meta.index, victim)
        # the new nominee holds its demand against later lower-or-equal-
        # priority preemptors; the capacity its victims free is credited
        # to everyone
        holds.append((n, demand, pod.priority, pod.uid))
        nominated_extra[n] -= victim_freed
        report.preempted[pod.uid] = (result.nominated_node, result.victims)


def _refresh_metrics(scheduler, cluster: Cluster, now: int):
    """The collector pull loop: every distinct metrics source configured by
    a trimaran plugin — a WatcherAddress service or a MetricProvider library
    client (collector.go:60-73) — gets an async collector (cached on the
    scheduler) ticked once per cycle; see
    state.collector.AsyncLoadWatcherCollector for cadence/threading."""
    from scheduler_plugins_tpu.state.collector import (
        AsyncLoadWatcherCollector,
        make_metrics_client,
    )

    collectors = getattr(scheduler, "_collectors", None)
    for plugin in scheduler.profile.plugins:
        address = getattr(plugin, "watcher_address", None)
        provider = getattr(plugin, "metric_provider", None)
        if not address and not provider:
            continue
        key = address or tuple(sorted((provider or {}).items()))
        if collectors is None:
            collectors = scheduler._collectors = {}
        if key not in collectors:
            try:
                collectors[key] = AsyncLoadWatcherCollector(
                    make_metrics_client(address, provider)
                )
            except ValueError:
                # unusable source config: degrade to no metrics for this
                # source instead of failing every cycle (None sentinel stops
                # re-construction attempts)
                collectors[key] = None
        if collectors[key] is not None:
            collectors[key].tick(cluster, now)


def _resync_nrt_cache(cluster: Cluster, now: int = 0):
    """Drive the over-reserve cache's resync loop (the reference's background
    `wait.Forever(Resync, period)` goroutine, pluginhelpers.go:73): reconcile
    dirty nodes against their latest agent reports, on the configured
    CacheResyncPeriodSeconds cadence when the cache carries one."""
    cache = cluster.nrt_cache
    if cache is None or not hasattr(cache, "resync"):
        return
    period_ms = getattr(cache, "resync_period_ms", 0)
    if period_ms:
        last = getattr(cache, "_last_resync_ms", None)
        if last is not None and now - last < period_ms:
            return
        cache._last_resync_ms = now
    if not cache.desynced_nodes():
        return
    node_pods: dict[str, list] = {}
    relevant = getattr(cache, "pod_relevant", lambda pod: True)
    for pod in cluster.pods.values():
        # the cache's pod view goes through the informer-mode relevance
        # predicate (podprovider.go:37-93): fingerprints must be computed
        # over exactly the pods that provider would have listed
        if pod.node_name is not None and relevant(pod):
            node_pods.setdefault(pod.node_name, []).append(pod)
    cache.resync(node_pods)


def _mark_overreserved_on_failures(cluster: Cluster, report: CycleReport):
    """Filter failures on cached views may mean the deduction is stale
    (filter.go:219-223 NodeMaybeOverReserved): mark every node carrying
    assumed pods dirty so the next resync reconciles it."""
    cache = cluster.nrt_cache
    if not report.failed or cache is None:
        return
    if not hasattr(cache, "mark_maybe_overreserved") or not hasattr(cache, "assumed"):
        return
    for node, assumed in cache.assumed.items():
        if assumed:
            cache.mark_maybe_overreserved(node)


def _maybe_release_gang(cluster: Cluster, pg, report: CycleReport, now: int = 0):
    reserved = cluster.gang_reservations(pg)
    if not reserved:
        return
    bound = sum(
        1
        for p in cluster.gang_members(pg)
        if p.node_name is not None
    )
    if bound + len(reserved) >= pg.min_member:
        for uid in reserved:
            node = cluster.reserved[uid]
            cluster.bind(uid, node, now)  # clears the pod's permit timer
            report.bound[uid] = node
            report.reserved.pop(uid, None)


def _reject_gang(cluster: Cluster, pg, now: int, report: CycleReport, cosched, member_count: int):
    """Reject every waiting sibling, record failure time, back off the group
    (coscheduling.go:188-209, core.go:174-192). Backoff applies only when the
    gang has at least MinMember sibling pods (coscheduling.go:196-204) —
    an incomplete gang must retry as soon as its members appear."""
    for uid in cluster.gang_reservations(pg):
        cluster.release_reservation(uid)  # clears the pod's permit timer
        report.reserved.pop(uid, None)
        # released siblings are parked too (upstream Permit-Reject moves
        # waiting pods to the unschedulable queue) — without this the
        # gang-activation rule would re-run the whole group every cycle
        cluster.mark_unschedulable(uid, now)
    cluster.gang_last_failure_ms[pg.full_name] = now
    backoff_s = cosched.pod_group_backoff_seconds if cosched else 0
    if backoff_s > 0 and member_count >= pg.min_member:
        cluster.gang_backoff_until_ms[pg.full_name] = now + 1000 * backoff_s
    report.rejected_gangs.append(pg.full_name)


def _expire_gangs(cluster: Cluster, now: int, report: CycleReport):
    """Permit timeout: ANY waiting pod past its own deadline fires Reject
    (the upstream per-pod waitingPods timer, coscheduling.go:227-251), which
    unreserves every sibling — the earliest sibling deadline rejects the
    whole gang; staggered reservations carry staggered deadlines."""
    for uid, deadline in list(cluster.pod_deadline_ms.items()):
        if now < deadline or uid not in cluster.pod_deadline_ms:
            continue  # not due, or already cleared by a sibling's expiry
        pod = cluster.pods.get(uid)
        pg = cluster.pod_group_of(pod) if pod is not None else None
        if pg is None:
            cluster.release_reservation(uid)  # clears the timer too
            continue
        for sibling_uid in cluster.gang_reservations(pg):
            cluster.release_reservation(sibling_uid)
        cluster.gang_last_failure_ms[pg.full_name] = now
        report.expired_gangs.append(pg.full_name)
