"""Concurrent cycle pipeline: overlap ingest/solve/bind across cycles.

`framework.cycle.run_cycle` is strictly serial — ingest, snapshot, device
solve, host-transfer fence, bind, all on one thread, with the host idle
while the device solves and the device idle while the host ingests. This
module composes the SAME `_cycle_*` stage functions into a pipelined
engine (`PipelinedCycle`) that keeps the device solve of cycle N in
flight while neighboring cycles' host stages run:

    tick N:   [conflict fence]──[ingest N]──[dispatch N]╮
                                                        │ device solves N
              [finalize N-1  ← overlap window]──────────┤
              [fence N: host transfers]─────────────────╯
              [bind N → async flusher]  (tick returns; the flusher's
                                         mutations are joined by tick
                                         N+1's conflict fence)

Ordering contract (what keeps pipelined placements BIT-IDENTICAL to the
serial engine, gated by tests/test_differential.py's
TestPipelinedCycleEquivalence):

- **Conflict fence.** The bind/post-bind stage of cycle N mutates the
  store (binds, reservations, `mark_unschedulable` backoff charges,
  preemption nomination set/clear). Cycle N+1's ingest boundary — the
  pending-index read and the serve engine's sink drain — joins the
  flusher FIRST, so every one of those mutations is attributed to the
  cycle that observed the snapshot, never to the cycle currently
  ingesting. A bind that flushes after a drain boundary (possible only
  outside the tick loop, e.g. `flush()` racing an external drain) still
  reaches the resident serving state exactly: each store mutator pushes
  its DeltaSink event, and a late bind is an ordinary delta of the PR 6
  taxonomy (`scheduler_cycle_late_binds_total` counts them).
- **Overlap window.** Only report-local work runs while cycle N's solve
  is in flight: cycle N-1's failure attribution (when its per-pod codes
  already rode the solve result), quality observation (on host copies
  captured at N-1's fence — the resident node tensors were donated to
  cycle N's delta apply by then) and the flight-recorder commit. None of
  it touches the store, so overlap cannot reorder decisions.
- **Gang/preemption machinery** stays inside the tick, after the fence,
  exactly where the serial engine runs it.

The engine enables the cluster's O(changed) pending index
(`Cluster.enable_pending_index`) — the serial engine's per-cycle
O(pods) scan is the single biggest host cost at serving scale — and
pairs naturally with `serving.engine.StreamingServeEngine`'s O(changed)
node-delete compaction (docs/SCALING.md has the measured breakdown).
"""

from __future__ import annotations

import sys
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

from scheduler_plugins_tpu.framework.cycle import (
    CycleReport,
    _cycle_bind,
    _cycle_finalize,
    _cycle_open,
    _cycle_pending,
    _cycle_post_solve,
    _cycle_postbind,
    _cycle_snapshot,
    _cycle_solve_dispatch,
    _cycle_solve_fence,
)
from scheduler_plugins_tpu.framework.runtime import now_ms as _now_ms
from scheduler_plugins_tpu.obs import ledger as podledger
from scheduler_plugins_tpu.utils import flightrec, observability as obs


class CycleTimeline:
    """Host-stamp timeline of ONE pipelined cycle — every number comes
    from host-observable boundaries (dispatch returning, np.asarray
    completion fences), never from wall clocks inside jit (CLAUDE.md;
    GL008). The solve ENVELOPE (dispatch return -> fence return) is a
    conservative device window: the host cannot observe the device-side
    start/finish tighter than its own sync points (the
    `parallel.pipeline.PipelineTimeline` convention)."""

    __slots__ = (
        "cycle", "t0_s", "ingest_ms", "dispatch_ms", "overlap_ms",
        "fence_wait_ms", "bind_ms", "bind_done_s", "total_ms", "late_bind",
    )

    def __init__(self, cycle: int):
        self.cycle = cycle
        self.t0_s = 0.0
        self.ingest_ms = 0.0
        self.dispatch_ms = 0.0
        self.overlap_ms = 0.0
        self.fence_wait_ms = 0.0
        self.bind_ms = 0.0
        #: seconds-on-the-tick-clock when the bind flush completed (the
        #: per-decision latency stamp: ingest boundary -> host-visible
        #: binds); stamped by the flusher thread
        self.bind_done_s = 0.0
        self.total_ms = 0.0
        self.late_bind = False

    @property
    def pipeline_bubble_ms(self) -> float:
        """Wall time the fence idled with the device still solving and no
        overlap work left — the un-overlapped remainder."""
        return self.fence_wait_ms

    @property
    def overlap_efficiency(self) -> float:
        """Fraction of the post-dispatch solve envelope covered by useful
        host work (1.0 = the fence never waited)."""
        envelope = self.overlap_ms + self.fence_wait_ms
        if envelope <= 0:
            return 1.0
        return min(1.0, self.overlap_ms / envelope)

    def as_dict(self) -> dict:
        return {
            "cycle": self.cycle,
            "ingest_ms": round(self.ingest_ms, 3),
            "dispatch_ms": round(self.dispatch_ms, 3),
            "overlap_ms": round(self.overlap_ms, 3),
            "pipeline_bubble_ms": round(self.pipeline_bubble_ms, 3),
            "bind_ms": round(self.bind_ms, 3),
            "total_ms": round(self.total_ms, 3),
            "overlap_efficiency": round(self.overlap_efficiency, 4),
        }


class PipelinedCycle:
    """Pipelined cycle engine over one scheduler + cluster store.

    `tick(now)` runs one cycle and returns its `CycleReport`. With
    `async_bind` (the default) the report's bind/post-bind stage may
    still be flushing on the worker thread when `tick` returns — call
    `fence()` (or run the next tick, whose ingest boundary fences
    implicitly) before reading the store or the report's DECISION
    fields (bound/reserved/failed/preempted). The report's deferred
    fields — `quality`, and `failed_by` when the per-pod codes rode the
    solve result — are populated only by the NEXT tick's overlap window
    or by `flush()`, which fences AND finalizes the last in-flight
    cycle (always call it, or `close()`, at shutdown).

    Composition mirrors `run_cycle`: `serve` (a ServeEngine), `gangs`
    (a GangPhase), `resilience` (a watchdog — its deadline semantics
    need a synchronous solve, so resilient ticks fence inside the
    dispatch stage and the overlap window only covers the previous
    cycle's finalize) and `stream_chunk` all behave identically.
    """

    #: host stages in flight at once: cycle N's bind flush + cycle N+1's
    #: ingest/dispatch, with cycle N's finalize deferred into N+1's
    #: overlap window
    DEPTH = 2

    def __init__(self, scheduler, cluster, serve=None, resilience=None,
                 gangs=None, stream_chunk=None, async_bind=True,
                 timeline_keep=512):
        self.scheduler = scheduler
        self.cluster = cluster
        self.serve = serve
        self.resilience = resilience
        self.gangs = gangs
        self.stream_chunk = stream_chunk
        cluster.enable_pending_index()
        self._flusher = (
            ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="spt-bind-flusher"
            )
            if async_bind else None
        )
        self._bind_future = None
        #: (ctx, eager_attribution_done) awaiting deferred finalize
        self._pending_finalize = None
        self._cycle_id = 0
        self.timelines: deque = deque(maxlen=timeline_keep)
        self._clock = time.perf_counter

    # -- introspection (daemon /healthz) --------------------------------
    @property
    def depth(self) -> int:
        return self.DEPTH

    @property
    def inflight(self) -> int:
        """Cycles with host work still outstanding: an unflushed bind
        stage and/or a deferred finalize."""
        n = 0
        if self._bind_future is not None and not self._bind_future.done():
            n += 1
        if self._pending_finalize is not None:
            n += 1
        return n

    # -- the conflict fence ---------------------------------------------
    def fence(self) -> None:
        """Join the async bind flusher — THE conflict fence. Every store
        mutation of the previous cycle's bind/post-bind stage is visible
        after this returns (exceptions, including the chaos harness's
        CrashInjected, re-raise here)."""
        future, self._bind_future = self._bind_future, None
        if future is not None:
            future.result()

    def flush(self) -> CycleReport | None:
        """Fence outstanding binds and run the deferred finalize of the
        last completed cycle. Returns that cycle's report (now fully
        populated) or None."""
        self.fence()
        return self._finalize_prev()

    def close(self) -> None:
        self.flush()
        if self._flusher is not None:
            self._flusher.shutdown(wait=True)

    # -- the tick --------------------------------------------------------
    def tick(self, now: int | None = None) -> CycleReport:
        if now is None:
            now = _now_ms()
        # the pod-lifecycle ledger's lane-0 scope is pushed inside
        # `_cycle_open` (on THIS thread — the bind flusher pushes its own
        # lane-1 scopes); pop it on EVERY exit so ambient events between
        # ticks fall back to ambient attribution and a raise cannot leak
        # a stale scope onto the tick thread
        ctx_box: list = []
        try:
            return self._tick(now, ctx_box)
        finally:
            if ctx_box:
                podledger.LEDGER.pop_scope(ctx_box[0].led)
                podledger.LEDGER.cycle_close(ctx_box[0].led)

    def _tick(self, now: int, ctx_box: list) -> CycleReport:
        clock = self._clock
        cid = self._cycle_id
        self._cycle_id += 1
        tl = CycleTimeline(cid)
        tl.t0_s = clock()

        # ---- ingest boundary: conflict fence, then host ingest --------
        with obs.tracer.span(f"ingest cycle {cid}", tid="Cycle/ingest"):
            self.fence()
            ctx = _cycle_open(
                self.scheduler, self.cluster, now,
                stream_chunk=self.stream_chunk, serve=self.serve,
                resilience=self.resilience, gangs=self.gangs,
            )
            ctx.tid = "Cycle/bind"
            ctx_box.append(ctx)
            _cycle_pending(ctx)
            if ctx.done:
                # empty/gang-only cycle: nothing in flight to overlap —
                # finalize any deferred cycle now so reports stay ordered
                self._finalize_prev()
                tl.ingest_ms = (clock() - tl.t0_s) * 1000.0
                tl.total_ms = tl.ingest_ms
                tl.bind_done_s = clock() - tl.t0_s
                self.timelines.append(tl)
                return ctx.report

            from scheduler_plugins_tpu.utils import sanitize

            if sanitize.enabled():
                sanitize.drain()
            ctx.rec = flightrec.recorder.begin(
                now_ms=now, profile=self.scheduler.profile.name
            )
            ctx.serve_t0 = clock() if self.serve is not None else None
            generation = getattr(
                self.cluster.nrt_cache, "generation", None
            )
            ctx._flow = obs.flow(
                "cycle", generation=generation, pending=len(ctx.pending)
            )
            ctx._flow.__enter__()
            try:
                _cycle_snapshot(ctx)
            except BaseException:
                ctx._flow.__exit__(*sys.exc_info())
                raise
        tl.ingest_ms = (clock() - tl.t0_s) * 1000.0

        try:
            # ---- dispatch: the device solve goes in flight -------------
            t0 = clock()
            with obs.tracer.span(f"solve cycle {cid}", tid="Cycle/solve",
                                 pending=len(ctx.pending)):
                _cycle_solve_dispatch(ctx)
            tl.dispatch_ms = (clock() - t0) * 1000.0

            # ---- overlap window: previous cycle's report-only epilogue -
            t0 = clock()
            with obs.tracer.span(
                f"finalize cycle {cid - 1}", tid="Cycle/finalize"
            ):
                self._finalize_prev()
            tl.overlap_ms = (clock() - t0) * 1000.0

            # ---- fence: host transfers complete the in-flight solve ----
            t0 = clock()
            with obs.tracer.span(f"fence cycle {cid}", tid="Cycle/solve"):
                _cycle_solve_fence(
                    ctx, quality_view=ctx.serve is not None
                )
            tl.fence_wait_ms = (clock() - t0) * 1000.0
            _cycle_post_solve(ctx)
        except BaseException:
            ctx._flow.__exit__(*sys.exc_info())
            raise
        ctx._flow.__exit__(None, None, None)

        # ---- bind + post-bind: async flush behind the conflict fence ---
        # Failure attribution must run against THIS cycle's prepared
        # plugins when the codes did not ride the solve result (the
        # batched/streamed reduction re-reads plugin aux): eager, inside
        # the flush. The sequential path's codes are host-decodable any
        # time: deferred into the next overlap window.
        eager_attr = getattr(ctx.result, "failed_plugin", None) is None
        # sink drain generation at submit: inside the tick loop the
        # conflict fence guarantees the flush lands before the next
        # drain, so a crossing is only observable when an EXTERNAL
        # drain (a direct `engine.refresh`, a shutdown-path flush)
        # overtakes an in-flight bind — exactly the case the
        # binds-as-deltas taxonomy absorbs
        sink = (
            getattr(self.serve, "_sink", None)
            if self.serve is not None else None
        )
        drains_at_submit = sink.drains if sink is not None else None

        def bind_job():
            t0 = clock()
            with obs.tracer.span(f"bind cycle {cid}", tid="Cycle/bind"):
                _cycle_bind(ctx)
                _cycle_postbind(ctx, attribution=eager_attr)
            tl.bind_ms = (clock() - t0) * 1000.0
            tl.bind_done_s = clock() - tl.t0_s
            if sink is not None and sink.drains != drains_at_submit:
                # this flush crossed a drain boundary: its store
                # mutations reach the resident serving state as
                # ordinary DeltaSink deltas of a LATER window (the
                # conflict-fence taxonomy) — resident state stays
                # exact, the binds are just observed one window later
                tl.late_bind = True
                obs.metrics.inc(obs.CYCLE_LATE_BINDS)

        if self._flusher is not None:
            self._bind_future = self._flusher.submit(bind_job)
        else:
            bind_job()

        self._pending_finalize = (ctx, eager_attr)
        tl.total_ms = (clock() - tl.t0_s) * 1000.0
        obs.metrics.set_gauge(
            obs.CYCLE_OVERLAP_EFFICIENCY, tl.overlap_efficiency
        )
        obs.metrics.set_gauge(
            obs.CYCLE_PIPELINE_BUBBLE, tl.pipeline_bubble_ms
        )
        self.timelines.append(tl)
        return ctx.report

    def _finalize_prev(self) -> CycleReport | None:
        pending, self._pending_finalize = self._pending_finalize, None
        if pending is None:
            return None
        prev_ctx, attributed = pending
        _cycle_finalize(prev_ctx, attribution=not attributed)
        return prev_ctx.report
