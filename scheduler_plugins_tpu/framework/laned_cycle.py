"""K-lane optimistic-concurrency cycle engine: one conflict fence.

`framework.cycle.run_cycle` admits the whole pending queue through ONE
sequential solve lane; `framework.pipeline_cycle.PipelinedCycle` (PR 11)
overlaps cycles but still serializes admission through that one lane.
This module composes the SAME `_cycle_*` stage functions around the
K-lane speculative solver (`parallel.lanes.LaneSolver`): the pending
queue partitions across K lanes by a deterministic key (gang members
never split), every lane solves speculatively against the same resident
base snapshot, and a single host-side conflict fence walks the DEFINED
SERIAL ORDER (the global queue order — exactly the order `run_cycle`'s
scan commits), committing validated placements wholesale and re-solving
from the first conflict against committed state.

The concurrency model mirrors the reference's deployment shape — a
second scheduler solving optimistically against shared cluster state,
serialized by the apiserver's bind (SURVEY.md §L0, deploy/k8s.yaml) —
with the fence playing the apiserver's role, inside one process.

Ordering contract (what keeps laned placements BIT-IDENTICAL to
`run_cycle` at every K — gated by tests/test_differential.py's
TestLanedCycleEquivalence and bench config 15):

- **One solve boundary.** The laned solve replaces ONLY the
  dispatch+fence pair inside the Solve extension span. Everything
  before (requeue gating, queue sort, gang phase, serve refresh,
  prepare, flight-recorder input capture) and after (bind, Permit
  fan-out, PostFilter gang rejection, preemption, finalize) is the
  serial engine's own stage function — one copy, zero drift.
- **Fence exactness.** The fence validates per-pod step signatures
  (admit verdicts + built-in fit mask) on host int64 twins of the
  device math and re-solves the remaining suffix through the same
  step body on the first mismatch — `parallel.lanes` carries the
  induction argument, docs/SCALING.md the prose proof.
- **Serial fallback.** K == 1, profiles outside the fence-exact gate
  (armed side tables, preemption nominees, unknown admit plugins) and
  packing-mode profiles all route to `Scheduler.solve` — the parity
  path itself, so the engine NEVER trades exactness for lanes.
- **Binds land as ordinary deltas.** All K lanes share the one
  cluster store and (when serving) the one DeltaSink: the fence's
  merged decisions flow through `_cycle_bind`'s store mutators, whose
  sink events land at the next ingest boundary exactly like any other
  delta (the PR 6 taxonomy). With `async_bind` the flush runs on the
  "spt-lane-flusher" worker behind the same join-first fence as the
  pipelined engine; a flush crossing an external drain boundary is
  counted late (`scheduler_cycle_late_binds_total`) and absorbed.
"""

from __future__ import annotations

import sys
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

from scheduler_plugins_tpu.framework.cycle import (
    CycleReport,
    SolveResultView,
    _cycle_bind,
    _cycle_finalize,
    _cycle_open,
    _cycle_pending,
    _cycle_post_solve,
    _cycle_postbind,
    _cycle_snapshot,
    _cycle_solve_fence,
)
from scheduler_plugins_tpu.framework.runtime import now_ms as _now_ms
from scheduler_plugins_tpu.obs import ledger as podledger
from scheduler_plugins_tpu.utils import flightrec, observability as obs


class LanedCycle:
    """K-lane cycle engine over one scheduler + cluster store.

    `tick(now)` runs one cycle and returns its `CycleReport` with
    `report.lanes` carrying the lane attribution (k, per-lane sizes /
    committed / conflicts, re-resolve count, solve vs fence wall ms).
    With `async_bind` the bind/post-bind/finalize epilogue flushes on a
    worker thread — call `fence()` (or tick again: the ingest boundary
    fences first) before reading the store, and `flush()`/`close()` at
    shutdown, exactly the `PipelinedCycle` discipline.

    `serve`/`gangs` compose like `run_cycle`'s parameters. `resilience`
    is deliberately NOT accepted: the watchdog's deadline semantics wrap
    one synchronous solve, and its degraded host path IS the sequential
    engine — lanes would add nothing but fence overhead to it.
    """

    def __init__(self, scheduler, cluster, k: int = 4, serve=None,
                 gangs=None, partition: str = "namespace",
                 dispatch: str = "fused", async_bind: bool = False,
                 report_keep: int = 512):
        # deferred: parallel.lanes imports the framework package (the
        # step body + SolverState), so a module-level import here would
        # be circular through framework/__init__
        from scheduler_plugins_tpu.parallel.lanes import LaneSolver

        if scheduler.profile.solve_mode == "packing":
            raise ValueError(
                "LanedCycle requires the sequential parity solve "
                "(profile solve_mode 'packing' has no per-pod serial "
                "order for the conflict fence to replay)"
            )
        self.scheduler = scheduler
        self.cluster = cluster
        self.serve = serve
        self.gangs = gangs
        # the O(changed) pending index also pins the admission serials
        # the "hash" partition mode keys on (Cluster.admission_serial)
        cluster.enable_pending_index()
        self.solver = LaneSolver(
            scheduler, k=k, partition=partition, dispatch=dispatch
        )
        self._flusher = (
            ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="spt-lane-flusher"
            )
            if async_bind else None
        )
        self._bind_future = None
        self._cycle_id = 0
        #: rolling lane attributions (report.lanes dicts), most recent
        #: last — the daemon's /healthz lanes block reads the tail
        self.lane_reports: deque = deque(maxlen=report_keep)
        self.cycles = 0
        self.conflicts_total = 0
        self.re_resolved_total = 0
        self.serial_fallbacks = 0

    @property
    def k(self) -> int:
        return self.solver.k

    # -- the conflict fence (bind flusher join) --------------------------
    def fence(self) -> None:
        """Join the async bind flusher: every store mutation of the
        previous cycle's bind/post-bind stage is visible after this
        returns (exceptions re-raise here)."""
        future, self._bind_future = self._bind_future, None
        if future is not None:
            future.result()

    def flush(self) -> None:
        self.fence()

    def close(self) -> None:
        self.flush()
        if self._flusher is not None:
            self._flusher.shutdown(wait=True)
        self.solver.close()

    # -- the tick --------------------------------------------------------
    def tick(self, now: int | None = None) -> CycleReport:
        if now is None:
            now = _now_ms()
        # pod-lifecycle ledger scope discipline (the PipelinedCycle
        # pattern): `_cycle_open` pushes the lane-0 scope on this thread;
        # pop it on every exit so a raise cannot leak a stale scope
        ctx_box: list = []
        try:
            return self._tick(now, ctx_box)
        finally:
            if ctx_box:
                podledger.LEDGER.pop_scope(ctx_box[0].led)
                podledger.LEDGER.cycle_close(ctx_box[0].led)

    def _tick(self, now: int, ctx_box: list) -> CycleReport:
        cid = self._cycle_id
        self._cycle_id += 1

        # ingest boundary: join the previous flush FIRST, so every bind/
        # backoff/nomination of cycle N is visible to cycle N+1's
        # pending read and serve drain (the PipelinedCycle contract)
        self.fence()
        ctx = _cycle_open(
            self.scheduler, self.cluster, now, serve=self.serve,
            gangs=self.gangs,
        )
        if self._flusher is not None:
            # bind/post-bind spans move off the main thread: their own
            # tid keeps every Perfetto row single-threaded (the per-tid
            # validity gate)
            ctx.tid = "Lane/bind"
        ctx_box.append(ctx)
        _cycle_pending(ctx)
        if ctx.done:
            return ctx.report

        from scheduler_plugins_tpu.utils import sanitize

        if sanitize.enabled():
            sanitize.drain()
        ctx.rec = flightrec.recorder.begin(
            now_ms=now, profile=self.scheduler.profile.name
        )
        ctx.serve_t0 = (
            time.perf_counter() if self.serve is not None else None
        )
        generation = getattr(self.cluster.nrt_cache, "generation", None)
        ctx._flow = obs.flow(
            "cycle", generation=generation, pending=len(ctx.pending)
        )
        ctx._flow.__enter__()
        try:
            _cycle_snapshot(ctx)
            with obs.extension_span(
                "Solve", self.scheduler.profile.name,
                pending=len(ctx.pending), lanes=self.k,
            ):
                if ctx.led is not None:
                    # this engine dispatches its own solver (not
                    # `_cycle_solve_dispatch`), so the ledger's solve
                    # stamp lands here
                    ctx.led.t_solve = podledger.LEDGER._now()
                assignment, admitted, wait, codes, stats = (
                    self.solver.solve(
                        ctx.snap, ctx.pending, self.cluster,
                        meta=ctx.meta,
                    )
                )
                # host arrays + per-pod codes: the record replays through
                # the sequential twin (rec_mode "sequential") and failure
                # attribution decodes exactly, like the parity path
                ctx.result = SolveResultView(
                    assignment, admitted, wait, failed_plugin=codes
                )
                ctx.assignment = assignment
                ctx.admitted = admitted
                ctx.wait = wait
                ctx.fenced = True
                # already host arrays; this only captures the quality
                # view when the finalize may run after the resident
                # node tensors were donated (async epilogue)
                _cycle_solve_fence(
                    ctx, quality_view=(
                        self._flusher is not None
                        and self.serve is not None
                    ),
                )
            ctx.report.lanes = stats.as_dict()
            self.cycles += 1
            self.conflicts_total += sum(stats.conflicts or [])
            self.re_resolved_total += stats.re_resolved
            if (
                stats.path == "serial"
                and stats.serial_fallback_reason != "k=1"
            ):
                # gate rejections only: K == 1 routing through the
                # parity solve is the engine's intended degenerate
                # configuration, not a fallback
                self.serial_fallbacks += 1
            self.lane_reports.append(ctx.report.lanes)
            _cycle_post_solve(ctx)
        except BaseException:
            ctx._flow.__exit__(*sys.exc_info())
            raise
        ctx._flow.__exit__(None, None, None)

        # bind + post-bind + finalize: inline, or flushed behind the
        # join-first fence. Attribution always runs eagerly inside the
        # flush — the laned result carries per-pod codes (host ints,
        # decodable any time), and the postbind gang/preemption
        # machinery needs the failure set anyway.
        sink = (
            getattr(self.serve, "_sink", None)
            if self.serve is not None else None
        )
        drains_at_submit = sink.drains if sink is not None else None

        def bind_job():
            with obs.tracer.span(f"bind cycle {cid}", tid=ctx.tid):
                _cycle_bind(ctx)
                _cycle_postbind(ctx, attribution=True)
                _cycle_finalize(ctx)
            if sink is not None and sink.drains != drains_at_submit:
                # crossed an external drain boundary: the binds reach
                # the resident serving state as ordinary deltas of a
                # later window (the PR 6 conflict-fence taxonomy)
                obs.metrics.inc(obs.CYCLE_LATE_BINDS)

        if self._flusher is not None:
            self._bind_future = self._flusher.submit(bind_job)
        else:
            bind_job()
        return ctx.report

    # -- introspection (daemon /healthz) --------------------------------
    def stats(self) -> dict:
        """Totals + the most recent cycle's lane attribution."""
        last = self.lane_reports[-1] if self.lane_reports else None
        return {
            "k": self.k,
            "partition": self.solver.partition,
            "dispatch": self.solver.dispatch,
            "cycles": self.cycles,
            "conflicts_total": self.conflicts_total,
            "re_resolved_total": self.re_resolved_total,
            "serial_fallbacks": self.serial_fallbacks,
            "last": last,
        }
