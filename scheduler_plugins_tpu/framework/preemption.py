"""Preemption engine — the PostFilter tier.

Mirrors the upstream preemption evaluator driving plugin-specific victim
rules (SURVEY.md §3.3):

- `DEFAULT` mode: victims are lower-priority pods
  (upstream DefaultPreemption semantics).
- `CAPACITY` mode: ElasticQuota borrow rules
  (/root/reference/pkg/capacityscheduling/capacity_scheduling.go:486-677):
  a preemptor whose quota would stay over Min preys on same-namespace
  lower-priority pods; a preemptor within its guaranteed Min preys on other
  namespaces' pods whose quota is over Min; non-quota preemptors prey on
  non-quota lower-priority pods. Post-removal quota gates (own Max, aggregate
  Min) apply, and the reprieve loop re-checks them.
- Preemption toleration (/root/reference/pkg/preemptiontoleration): victims
  whose PriorityClass carries the toleration annotations are exempt when the
  preemptor's priority is below MinimumPreemptablePriority and the victim is
  inside its toleration window.

TPU mapping per SURVEY.md §7 step 7: the "remove all eligible victims,
re-filter" dry run is vectorized across all nodes at once (eligibility masks
+ per-node segment sums); the small per-node reprieve refinement stays
host-side and exact. Candidate ranking follows the upstream pickOneNode
criteria (fewest PDB violations -> min highest victim priority -> min
priority sum -> fewest victims -> lowest index).

The dry-run re-filter covers resource fit, the quota gates AND the enabled
plugins' Filter chain evaluated against the current cache state — the same
view the reference's RunFilterPluginsWithNominatedPods gives plugin filters
(removing victims from the NodeInfo does not alter e.g. the NRT cache copy
the TopologyMatch filter reads).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional

import numpy as np

from scheduler_plugins_tpu.api.objects import Pod
from scheduler_plugins_tpu.api.resources import PODS

# PriorityClass annotations (preemption_toleration_policy.go:26-28)
ANNOTATION_PREFIX = "preemption-toleration.scheduling.x-k8s.io/"
ANNOTATION_MIN_PREEMPTABLE = ANNOTATION_PREFIX + "minimum-preemptable-priority"
ANNOTATION_TOLERATION_SECONDS = ANNOTATION_PREFIX + "toleration-seconds"


def encode_demand(index, pod: "Pod"):
    """Pod demand vector with the pods slot set to 1 (the host-side analog
    of ops.fit.pod_fit_demand)."""
    vec = index.encode(pod.effective_request())
    vec[index.position(PODS)] = 1
    return vec


class PreemptionMode(enum.Enum):
    DEFAULT = "Default"
    CAPACITY = "CapacityScheduling"
    #: brute-force multi-node victim search — the reference ships this
    #: plugin fully commented out ("CAVEAT: don't use in production",
    #: cross_node_preemption.go:19-224); implemented here as an opt-in
    #: mirror of that spec
    CROSS_NODE = "CrossNodePreemption"


#: sentinel: the preemptor is currently INELIGIBLE (PodEligibleToPreemptOthers
#: said no — terminations in flight on its nominated node); distinct from
#: None ("eligible but no viable candidates") so callers keep the nomination
GATED = object()


@dataclass
class PreemptionResult:
    nominated_node: str
    victims: list[str]  # uids, most important first


class PreemptionEngine:
    #: upstream DefaultPreemptionArgs defaults (k/k defaults; the reference's
    #: PreemptionTolerationArgs aliases them, apis/config/types.go
    #: PreemptionTolerationArgs) — candidates = clamp(
    #: numNodes*pct/100, >=absolute, <=numNodes),
    #: preemption_toleration.go:306-331 calculateNumCandidates
    DEFAULT_MIN_CANDIDATE_NODES_PERCENTAGE = 10
    DEFAULT_MIN_CANDIDATE_NODES_ABSOLUTE = 100

    #: CROSS_NODE pool bound: the reference enumerates ALL 2^n victim
    #: subsets with no cap (its own caveat); we keep the exact DFS but bound
    #: the pool to the lowest-priority pods so the search stays tractable
    CROSS_NODE_MAX_POOL = 12

    def __init__(self, mode: PreemptionMode = PreemptionMode.DEFAULT,
                 toleration: bool = False,
                 cross_node_max_pool: int | None = None,
                 min_candidate_nodes_percentage: int | None = None,
                 min_candidate_nodes_absolute: int | None = None,
                 candidate_rng=None):
        self.mode = mode
        self.toleration = toleration
        if cross_node_max_pool is not None:
            self.CROSS_NODE_MAX_POOL = cross_node_max_pool
        pct, absolute = self.validate_sampling_args(
            min_candidate_nodes_percentage, min_candidate_nodes_absolute
        )
        self.min_candidate_nodes_percentage = pct
        self.min_candidate_nodes_absolute = absolute
        import random as _random

        # deterministic by default (seed 0): this repo's differential gates
        # and bench runs need snapshot -> decision reproducibility, where
        # upstream uses rand.Int31n; pass a Random for upstream-style jitter
        self._candidate_rng = candidate_rng or _random.Random(0)

    # -- candidate sampling ----------------------------------------------
    @classmethod
    def validate_sampling_args(cls, pct, absolute):
        """Upstream ValidateDefaultPreemptionArgs: pct in [0, 100],
        absolute >= 0, pair must yield a positive candidate count. Returns
        the defaulted (pct, absolute)."""
        if pct is None:
            pct = cls.DEFAULT_MIN_CANDIDATE_NODES_PERCENTAGE
        if absolute is None:
            absolute = cls.DEFAULT_MIN_CANDIDATE_NODES_ABSOLUTE
        if not 0 <= pct <= 100:
            raise ValueError(
                f"minCandidateNodesPercentage must be in [0, 100], got {pct}"
            )
        if absolute < 0:
            raise ValueError(
                f"minCandidateNodesAbsolute must be >= 0, got {absolute}"
            )
        if pct == 0 and absolute == 0:
            raise ValueError(
                "minCandidateNodesPercentage and minCandidateNodesAbsolute "
                "cannot both be zero"
            )
        return pct, absolute

    def calculate_num_candidates(self, num_nodes: int) -> int:
        """calculateNumCandidates (preemption_toleration.go:318-331) over
        the PREEMPTION-CANDIDATE pool size (upstream passes
        len(potentialNodes), not the cluster node count):
        max(n*pct/100, absolute) capped at n."""
        n = (num_nodes * self.min_candidate_nodes_percentage) // 100
        if n < self.min_candidate_nodes_absolute:
            n = self.min_candidate_nodes_absolute
        if n > num_nodes:
            n = num_nodes
        return n

    def sample_candidates(self, fits):
        """GetOffsetAndNumCandidates (preemption_toleration.go:306-309): a
        random offset INTO THE FEASIBLE POOL, then a circular scan over the
        pool. Both the offset draw and the candidate count run over the
        feasible pool, as upstream draws over potentialNodes. Returns
        (rotated_pool, num_candidates): the FULL rotation plus the cap —
        the caller counts only victim-producing candidates toward the cap,
        because upstream's dry run keeps scanning past nodes whose reprieve
        yields no victims until numCandidates candidates are gathered."""
        import numpy as np

        pool = np.nonzero(fits)[0]
        if pool.size == 0:
            return pool, 0
        want = self.calculate_num_candidates(int(pool.size))
        offset = self._candidate_rng.randrange(int(pool.size))
        return pool[(np.arange(pool.size) + offset) % pool.size], want

    # -- exemption -------------------------------------------------------
    def exempted(self, victim: Pod, preemptor: Pod, cluster, now_ms: int) -> bool:
        """ExemptedFromPreemption (preemption_toleration.go:129-181)."""
        if not self.toleration or not victim.priority_class_name:
            return False
        pc = cluster.priority_classes.get(victim.priority_class_name)
        if pc is None:
            return False
        raw = pc.annotations.get(ANNOTATION_MIN_PREEMPTABLE)
        if raw is None:
            return False
        try:
            min_preemptable = int(raw)
            # absent toleration-seconds defaults to 0: no time-based
            # toleration (preemption_toleration_policy.go:73)
            toleration_s = int(
                pc.annotations.get(ANNOTATION_TOLERATION_SECONDS, 0)
            )
        except ValueError:
            return False  # unparsable policy -> no toleration
        if preemptor.priority >= min_preemptable:
            return False
        if toleration_s < 0:
            return True  # tolerate forever
        scheduled_ms = victim.creation_ms  # scheduled-at proxy
        return scheduled_ms + toleration_s * 1000 > now_ms

    # -- preemptor eligibility -------------------------------------------
    @staticmethod
    def _quota_view(snap, meta, preemptor, nom_aggs=None):
        """Shared quota-state derivation for the eligibility checks: returns
        (ns_codes, has_q, used, more_than_min, over_min). `more_than_min`
        folds the same-ns nominee aggregate exactly like usedOverMinWith over
        nominatedPodsReqInEQWithPodReq (capacity_scheduling.go:560)."""
        quota = snap.quota
        ns_codes = {ns: i for i, ns in enumerate(meta.namespaces)}
        has_q = np.asarray(quota.has_quota)
        used = np.asarray(quota.used)
        qmin = np.asarray(quota.min)
        over_min = np.any(used > qmin, axis=1)
        more_than_min = False
        p_ns = ns_codes.get(preemptor.namespace, -1)
        if p_ns >= 0 and has_q[p_ns]:
            req = meta.index.encode(preemptor.effective_request())
            in_eq_agg = nom_aggs[0] if nom_aggs is not None else 0
            more_than_min = bool(
                np.any(used[p_ns] + req + in_eq_agg > qmin[p_ns])
            )
        return ns_codes, has_q, used, more_than_min, over_min

    def pod_eligible(self, cluster, preemptor: Pod, snap, meta,
                     nom_aggs=None, scheduler=None) -> bool:
        """PodEligibleToPreemptOthers: a pod that already preempted must not
        preempt again while pods it could benefit from are still terminating
        on its nominated node (capacity_scheduling.go:409-484; upstream
        DefaultPreemption semantics for the DEFAULT mode)."""
        if getattr(preemptor, "preemption_policy", None) == "Never":
            return False
        del scheduler  # plain-Unschedulable filters must NOT trigger the escape
        nom = preemptor.nominated_node_name
        if not nom or nom not in cluster.nodes or nom not in meta.node_names:
            return True
        nom_idx = meta.node_names.index(nom)
        # upstream escape (capacity_scheduling.go:427-430): only a nominated
        # node that became UnschedulableAndUnresolvable (cordoned/gone) frees
        # the pod to preempt elsewhere — a resolvable plugin-filter rejection
        # (e.g. NUMA on the still-occupied cache view) keeps the gate closed,
        # or one pod would collect two victim sets
        if not bool(np.asarray(snap.nodes.mask)[nom_idx]):
            return True
        on_node = [
            p for p in cluster.pods.values() if p.node_name == nom
        ]
        if self.mode == PreemptionMode.CAPACITY and snap.quota is not None:
            ns_codes, has_q, _, more_than_min, over_min = self._quota_view(
                snap, meta, preemptor, nom_aggs
            )

            def ns_has_q(ns):
                i = ns_codes.get(ns, -1)
                return i >= 0 and bool(has_q[i])

            p_ns = ns_codes.get(preemptor.namespace, -1)
            if p_ns >= 0 and has_q[p_ns]:
                for p in on_node:
                    if not p.terminating or not ns_has_q(p.namespace):
                        continue
                    if (
                        p.namespace == preemptor.namespace
                        and p.priority < preemptor.priority
                    ):
                        return False
                    if (
                        p.namespace != preemptor.namespace
                        and not more_than_min
                        and bool(over_min[ns_codes[p.namespace]])
                    ):
                        return False
            else:
                # non-quota preemptor: only non-quota terminating pods count
                for p in on_node:
                    if ns_has_q(p.namespace):
                        continue
                    if p.terminating and p.priority < preemptor.priority:
                        return False
        else:
            for p in on_node:
                if p.terminating and p.priority < preemptor.priority:
                    return False
        return True

    # -- victim eligibility ----------------------------------------------
    def _eligible(self, victims, preemptor, cluster, snap, meta, now_ms,
                  nom_aggs=None):
        """(V,) bool eligibility per mode."""
        pri = np.array([v.priority for v in victims])
        same_ns = np.array([v.namespace == preemptor.namespace for v in victims])
        lower = pri < preemptor.priority

        if self.mode == PreemptionMode.CAPACITY and snap.quota is not None:
            ns_codes, has_q, _, more_than_min, over_min = self._quota_view(
                snap, meta, preemptor, nom_aggs
            )
            v_ns = np.array(
                [ns_codes.get(v.namespace, -1) for v in victims]
            )
            v_has_q = (v_ns >= 0) & has_q[np.maximum(v_ns, 0)]
            p_ns = ns_codes.get(preemptor.namespace, -1)
            p_has_q = p_ns >= 0 and bool(has_q[p_ns])
            if p_has_q:
                if more_than_min:
                    eligible = v_has_q & same_ns & lower
                else:
                    v_over = (v_ns >= 0) & over_min[np.maximum(v_ns, 0)]
                    eligible = v_has_q & ~same_ns & v_over
            else:
                eligible = ~v_has_q & lower
        else:
            eligible = lower

        if self.toleration:
            exempt = np.array(
                [self.exempted(v, preemptor, cluster, now_ms) for v in victims]
            )
            eligible &= ~exempt
        return eligible

    @staticmethod
    def _nominated_aggregates(cluster, preemptor, snap, meta):
        """(in_eq, total) request vectors of OTHER nominated pods — live
        cluster view, so nominations made earlier in THIS cycle count exactly
        once. Classification shares `ops.quota.nominee_contribution` with the
        snapshot builder; resource names outside this snapshot's axis are
        dropped (the index is unioned over nodes/pending/assigned only)."""
        from scheduler_plugins_tpu.ops.quota import nominee_contribution

        R = len(meta.index)
        in_eq = np.zeros(R, np.int64)
        total = np.zeros(R, np.int64)
        if snap.quota is None:
            return in_eq, total
        ns_codes = {ns: i for i, ns in enumerate(meta.namespaces)}
        has_q = np.asarray(snap.quota.has_quota)
        used = np.asarray(snap.quota.used)
        qmin = np.asarray(snap.quota.min)
        over_min = np.any(used > qmin, axis=1)
        for m in cluster.pods.values():
            if (
                m.uid == preemptor.uid
                or m.nominated_node_name is None
                or m.node_name is not None
            ):
                continue
            m_ns = ns_codes.get(m.namespace, -1)
            if m_ns < 0 or not has_q[m_ns]:
                continue
            req_m = meta.index.encode(
                {
                    name: qty
                    for name, qty in m.effective_request().items()
                    if name in meta.index
                }
            )
            counts_in_eq, counts_total = nominee_contribution(
                m.namespace == preemptor.namespace, m.priority,
                preemptor.priority, bool(over_min[m_ns]),
            )
            if counts_in_eq:
                in_eq += req_m
            if counts_total:
                total += req_m
        return in_eq, total

    # -- main ------------------------------------------------------------
    def preempt(self, cluster, scheduler, preemptor: Pod, snap, meta,
                now_ms: int, extra_reserved=None):
        """Returns a PreemptionResult, None (no viable candidates — a kept
        nomination did not help), or the GATED sentinel (the preemptor must
        not preempt right now because pods it benefits from are still
        terminating on its nominated node — callers keep the nomination)."""
        if getattr(preemptor, "preemption_policy", None) == "Never":
            return None
        # the eligibility gate runs BEFORE any victim encoding: while the
        # nominated node's terminations are in flight (the steady state the
        # gate exists for), the gated path must be near-free. The nominee
        # aggregates are only consumed by quota logic, so DEFAULT mode skips
        # the O(pods) scan entirely.
        nom_aggs = (
            self._nominated_aggregates(cluster, preemptor, snap, meta)
            if self.mode == PreemptionMode.CAPACITY and snap.quota is not None
            else None
        )
        if not self.pod_eligible(
            cluster, preemptor, snap, meta, nom_aggs, scheduler
        ):
            return GATED
        if self.mode == PreemptionMode.CROSS_NODE:
            return self._preempt_cross_node(
                cluster, scheduler, preemptor, snap, meta, extra_reserved
            )

        victims_all = [
            p
            for p in cluster.pods.values()
            if p.node_name is not None and not p.terminating
        ]
        if not victims_all:
            return None
        node_pos = {name: i for i, name in enumerate(meta.node_names)}
        v_node = np.array(
            [node_pos.get(v.node_name, -1) for v in victims_all]
        )
        keep = v_node >= 0
        victims_all = [v for v, k in zip(victims_all, keep) if k]
        if not victims_all:
            return None
        v_node = v_node[keep]

        index = meta.index
        R = len(index)
        N = len(meta.node_names)
        v_req = np.zeros((len(victims_all), R), np.int64)
        for i, v in enumerate(victims_all):
            v_req[i] = index.encode(v.effective_request())
            v_req[i, index.position(PODS)] = 1
        v_pri = np.array([v.priority for v in victims_all])

        eligible = self._eligible(
            victims_all, preemptor, cluster, snap, meta, now_ms, nom_aggs
        )
        if not eligible.any():
            return None

        # batched dry run: free + sum of eligible victims' demand per node
        free = np.asarray(snap.nodes.alloc - snap.nodes.requested)[:N]
        if extra_reserved is not None:
            # earlier preemptors' nominations this cycle hold capacity
            free = free - extra_reserved[:N]
        removed = np.zeros((N, R), np.int64)
        np.add.at(removed, v_node[eligible], v_req[eligible])
        demand = encode_demand(index, preemptor)
        node_mask = np.asarray(snap.nodes.mask)[:N]
        fits = np.all(free + removed >= demand[None, :], axis=1) & node_mask
        has_victims = np.zeros(N, bool)
        has_victims[v_node[eligible]] = True
        fits &= has_victims  # nodes without victims are unresolvable

        # capacity-mode quota gates after removing all victims
        if self.mode == PreemptionMode.CAPACITY and snap.quota is not None:
            fits &= self._quota_gate(
                victims_all, v_node, v_req, eligible, preemptor, snap, meta, N
            )
        if not fits.any():
            return None

        # run the exact reprieve per candidate (sampled with the upstream
        # offset/numCandidates rules) and rank by the FINAL minimized victim
        # sets — pickOneNode criteria: fewest PDB violations -> min highest
        # victim priority -> min priority sum -> fewest victims -> lowest
        # index
        rotation, want = self.sample_candidates(fits)
        pdbs = list(getattr(cluster, "pdbs", {}).values())
        # plugin Filter chain against hypothetical POST-EVICTION states:
        # upstream removes victims from the NodeInfo before
        # RunFilterPluginsWithNominatedPods and re-runs the chain as
        # reprievePod re-adds each one (SelectVictimsOnNode), so
        # affinity/spread/network filters must not see pods about to be
        # evicted (and must notice a required-affinity target leaving).
        # The NRT cache view stays as-is — upstream's TopologyMatch reads
        # its own cache, which victim removal does not update either (see
        # Cluster.post_eviction_tables). Computed once outside the loop:
        has_filters = (
            scheduler is not None and preemptor.uid in meta.pod_names
        )
        p_idx = meta.pod_names.index(preemptor.uid) if has_filters else -1
        uids_by_node: dict[int, list] = {}
        for i in np.nonzero(eligible)[0]:
            uids_by_node.setdefault(int(v_node[i]), []).append(
                victims_all[i].uid
            )
        best = None
        produced = 0
        # memoized per evicted-set within this dry run: the reprieve re-adds
        # victims one at a time, so the all-evicted pre-check set (and many
        # intermediate sets) repeat across candidate nodes; each miss costs
        # a full post-eviction side-table rebuild (ADVICE r4)
        verdict_cache: dict[frozenset, np.ndarray] = {}
        for n in rotation:
            if produced >= want:
                break
            victim_uids = uids_by_node.get(int(n), [])
            filter_ok = None
            if has_filters:
                def filter_ok(evicted, _n=int(n)):
                    return self._filters_pass(
                        cluster, scheduler, snap, meta, p_idx, evicted, _n,
                        verdict_cache,
                    )

                if not filter_ok(frozenset(victim_uids)):
                    continue
            final, violations = self._reprieve(
                victims_all, v_node, v_req, v_pri, eligible, int(n),
                free[int(n)], demand, preemptor, snap, meta, pdbs, nom_aggs,
                filter_ok=filter_ok,
            )
            if not final:
                continue
            produced += 1
            stats = (
                violations,
                max(v.priority for v in final),
                sum(v.priority for v in final),
                len(final),
                int(n),
            )
            if best is None or stats < best[0]:
                best = (stats, int(n), final)
        if best is None:
            return None
        _, chosen, final_victims = best
        return PreemptionResult(
            nominated_node=meta.node_names[chosen],
            victims=[v.uid for v in final_victims],
        )

    def _preempt_cross_node(self, cluster, scheduler, preemptor, snap,
                            meta, extra_reserved=None):
        """Brute-force candidate search over victim SUBSETS spanning nodes —
        the commented-out reference algorithm (cross_node_preemption.go:
        144-208): collect every bound pod with lower priority, DFS all
        subsets (pick-first order), and for each subset nominate any
        victim-hosting node the preemptor now fits; the best candidate wins
        by the upstream pickOneNode criteria (fewest PDB violations, lowest
        highest-victim-priority, lowest priority sum, fewest victims).

        Plugin Filter verdicts are evaluated against the CURRENT cache
        state (the same approximation the sequential dry run documents) —
        only the resource fit varies per subset."""
        node_pos = {name: i for i, name in enumerate(meta.node_names)}
        pool = [
            v for v in cluster.pods.values()
            if v.node_name in node_pos
            and not v.terminating
            and v.priority < preemptor.priority
        ]
        if not pool:
            return None
        # bound the exponential search: lowest-priority (most preemptable)
        # pods first, stable by uid
        pool.sort(key=lambda v: (v.priority, v.uid))
        pool = pool[: self.CROSS_NODE_MAX_POOL]
        n_pool = len(pool)

        index = meta.index
        R = len(index)
        N = len(meta.node_names)
        v_node = np.array([node_pos[v.node_name] for v in pool])
        v_req = np.zeros((n_pool, R), np.int64)
        for i, v in enumerate(pool):
            v_req[i] = index.encode(v.effective_request())
            v_req[i, index.position(PODS)] = 1

        demand = encode_demand(index, preemptor)
        free = np.asarray(snap.nodes.alloc - snap.nodes.requested)[:N]
        if extra_reserved is not None:
            free = free - extra_reserved[:N]
        static_fit = np.asarray(snap.nodes.mask)[:N].copy()
        if scheduler is not None and preemptor.uid in meta.pod_names:
            p_idx = meta.pod_names.index(preemptor.uid)
            static_fit &= np.asarray(scheduler.filter_verdicts(snap, p_idx))[:N]

        pdbs = list(getattr(cluster, "pdbs", {}).values())
        best = None
        order = 0
        # DFS leaf order: the reference explores "pick pod i" before "skip
        # pod i" at every level, so leaf k of the counter (pod i at bit
        # n_pool-1-i, CLEAR bit = picked) reproduces its enumeration order
        for bits in range(1 << n_pool):
            subset = [
                i for i in range(n_pool)
                if not (bits >> (n_pool - 1 - i)) & 1
            ]
            if not subset:
                order += 1
                continue
            removed = np.zeros((N, R), np.int64)
            np.add.at(removed, v_node[subset], v_req[subset])
            hosting = np.unique(v_node[subset])
            for n in hosting:
                if not static_fit[n]:
                    continue
                if not np.all(free[n] + removed[n] >= demand):
                    continue
                victims = [pool[i] for i in subset]
                violating, _ = self.partition_pdb_violations(
                    list(enumerate(victims)), pdbs
                )
                violations = len(violating)
                stats = (
                    violations,
                    max(v.priority for v in victims),
                    sum(v.priority for v in victims),
                    len(victims),
                    int(n),
                    order,
                )
                if best is None or stats < best[0]:
                    best = (stats, int(n), victims)
            order += 1
        if best is None:
            return None
        _, chosen, final_victims = best
        return PreemptionResult(
            nominated_node=meta.node_names[chosen],
            victims=[v.uid for v in final_victims],
        )

    def _filters_pass(self, cluster, scheduler, snap, meta, p_idx,
                      evicted_uids, n, verdict_cache=None) -> bool:
        """Plugin Filter verdict for the preemptor (pending row `p_idx`) on
        candidate node `n` against the hypothetical state with
        `evicted_uids` evicted (pod-derived tables only; see
        Cluster.post_eviction_tables). The per-node (N,) verdict row is
        memoized in `verdict_cache` keyed by the evicted set — the side
        tables and the verdict row depend only on (snap, p_idx, evicted),
        and the reprieve revisits the same sets across candidate nodes."""
        key = frozenset(evicted_uids)
        if verdict_cache is not None and key in verdict_cache:
            return bool(verdict_cache[key][n])
        hyp = snap
        if (
            evicted_uids
            and (snap.scheduling is not None or snap.network is not None)
            and hasattr(cluster, "post_eviction_tables")
        ):
            hyp = cluster.post_eviction_tables(snap, meta, evicted_uids)
        row = np.asarray(scheduler.filter_verdicts(hyp, p_idx))
        if verdict_cache is not None:
            verdict_cache[key] = row
        return bool(row[n])

    def _quota_gate(self, victims, v_node, v_req, eligible, preemptor, snap,
                    meta, N):
        """(N,) post-removal gates: own used+req <= Max and aggregate
        used+req <= aggregate Min (capacity_scheduling.go:612-618)."""
        quota = snap.quota
        used = np.asarray(quota.used)
        qmin = np.asarray(quota.min)
        qmax = np.asarray(quota.max)
        has_q = np.asarray(quota.has_quota)
        ns_codes = {ns: i for i, ns in enumerate(meta.namespaces)}
        p_ns = ns_codes.get(preemptor.namespace, -1)
        if p_ns < 0 or not has_q[p_ns]:
            return np.ones(N, bool)
        req = meta.index.encode(preemptor.effective_request())
        R = used.shape[1]
        # the gates only need two per-node sums: removed usage of the
        # preemptor's namespace (own-Max) and removed usage across all quota
        # namespaces (aggregate-Min) — no dense (N, Q, R) tensor
        removed_own = np.zeros((N, R), np.int64)
        removed_total = np.zeros((N, R), np.int64)
        for i in np.nonzero(eligible)[0]:
            victim = victims[i]
            ns = ns_codes.get(victim.namespace, -1)
            if ns < 0 or not has_q[ns]:
                continue
            vec = meta.index.encode(victim.effective_request())
            removed_total[v_node[i]] += vec
            if ns == p_ns:
                removed_own[v_node[i]] += vec
        own_ok = np.all(
            used[p_ns][None, :] - removed_own + req[None, :]
            <= qmax[p_ns][None, :],
            axis=1,
        )
        agg_used = np.sum(used * has_q[:, None], axis=0)
        agg_min = np.sum(qmin * has_q[:, None], axis=0)
        agg_ok = np.all(
            agg_used[None, :] - removed_total + req[None, :]
            <= agg_min[None, :],
            axis=1,
        )
        return own_ok & agg_ok

    @staticmethod
    def partition_pdb_violations(candidates, pdbs):
        """filterPodsWithPDBViolation (capacity_scheduling.go:889-934):
        decrement each matching PDB's DisruptionsAllowed per candidate (pods
        already in DisruptedPods don't count); a candidate whose budget went
        negative is 'violating'. Returns (violating, non_violating) index
        lists, order preserved."""
        allowed = [pdb.disruptions_allowed for pdb in pdbs]
        violating, non_violating = [], []
        for i, pod in candidates:
            violated = False
            for j, pdb in enumerate(pdbs):
                if not pdb.matches(pod) or pod.name in pdb.disrupted_pods:
                    continue
                allowed[j] -= 1
                if allowed[j] < 0:
                    violated = True
            (violating if violated else non_violating).append(i)
        return violating, non_violating

    def _reprieve(self, victims, v_node, v_req, v_pri, eligible, node, free_n,
                  demand, preemptor, snap, meta, pdbs=(), nom_aggs=None,
                  filter_ok=None):
        """Add back victims most-important-first while the preemptor still
        fits and quota gates hold (capacity_scheduling.go:632-670); PDB-
        violating candidates are reprieved FIRST so they get the best chance
        of surviving, and surviving violations are counted for pickOneNode.
        `filter_ok(evicted_uids) -> bool`, when given, re-runs the plugin
        Filter chain for each tentative reprieve (upstream's reprievePod
        runs RunFilterPluginsWithNominatedPods with the pod re-added) — a
        victim whose return would re-block the preemptor stays evicted.
        Returns (final_victims, num_violating)."""
        idxs = [i for i in np.nonzero(eligible)[0] if v_node[i] == node]
        # MoreImportantPod: higher priority, then earlier start
        idxs.sort(key=lambda i: (-v_pri[i], victims[i].creation_ms))
        violating, non_violating = self.partition_pdb_violations(
            [(i, victims[i]) for i in idxs], list(pdbs)
        )
        violating_set = set(violating)
        idxs = violating + non_violating
        free_after = free_n + v_req[idxs].sum(axis=0) if idxs else free_n

        quota = snap.quota
        use_quota = self.mode == PreemptionMode.CAPACITY and quota is not None
        if use_quota:
            ns_codes = {ns: i for i, ns in enumerate(meta.namespaces)}
            has_q = np.asarray(quota.has_quota)
            used = np.asarray(quota.used).copy()
            qmin = np.asarray(quota.min)
            qmax = np.asarray(quota.max)
            p_ns = ns_codes.get(preemptor.namespace, -1)
            req = meta.index.encode(preemptor.effective_request())
            # reprievePod folds the nominated aggregates into both gates
            # (capacity_scheduling.go:646)
            nom_in_eq, nom_total = (
                nom_aggs if nom_aggs is not None
                else (np.zeros_like(req), np.zeros_like(req))
            )
            req_in_eq = req + nom_in_eq
            req_total = req + nom_total
            for i in idxs:
                ns = ns_codes.get(victims[i].namespace, -1)
                if ns >= 0 and has_q[ns]:
                    used[ns] -= meta.index.encode(victims[i].effective_request())

        final = []
        num_violating = 0
        evicted = {victims[i].uid for i in idxs}
        for i in idxs:
            candidate_free = free_after - v_req[i]
            fits = bool(np.all(candidate_free >= demand))
            if fits and filter_ok is not None:
                # re-adding this victim must not re-block the preemptor
                fits = filter_ok(frozenset(evicted - {victims[i].uid}))
            quota_ok = True
            if use_quota and fits and p_ns >= 0 and has_q[p_ns]:
                vec = meta.index.encode(victims[i].effective_request())
                ns = ns_codes.get(victims[i].namespace, -1)
                used_try = used.copy()
                if ns >= 0 and has_q[ns]:
                    used_try[ns] += vec
                own_ok = np.all(used_try[p_ns] + req_in_eq <= qmax[p_ns])
                agg = np.sum(used_try * has_q[:, None], axis=0)
                agg_ok = np.all(
                    agg + req_total <= np.sum(qmin * has_q[:, None], axis=0)
                )
                quota_ok = bool(own_ok and agg_ok)
            if fits and quota_ok:
                # reprieved: stays on the node
                free_after = candidate_free
                evicted.discard(victims[i].uid)
                if use_quota:
                    ns = ns_codes.get(victims[i].namespace, -1)
                    if ns >= 0 and has_q[ns]:
                        used[ns] += meta.index.encode(
                            victims[i].effective_request()
                        )
            else:
                final.append(victims[i])
                if i in violating_set:
                    num_violating += 1
        # keep victims sorted most-important-first (the reference re-sorts
        # after mixing the two partitions)
        final.sort(key=lambda v: (-v.priority, v.creation_ms))
        return final, num_violating
